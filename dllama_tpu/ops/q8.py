"""Fused Q80 dequantize-matmul (reference weight-ftype dispatch parity).

The reference's production matmul dispatches on the WEIGHT file type —
F32/F16/Q40/Q80 all have first-class kernels (funcs.cpp:414-455; Q80:
matmulQ80, funcs.cpp:268-285).  Round ≤3 only gave Q40 the packed fused
path; Q80-weight `.m` files dequantized to dense bf16 at load, paying 2
B/weight of HBM per decode step instead of the stored 1.0625 B/weight.
This module closes that gap the TPU way, mirroring ops/q40.py:

* ``Q8Tensor`` — int8 value plane ``(..., padded_n, d)`` + f16-bit scale
  plane ``(..., padded_n/32, d)``, input-dim-major so a (tile_n, tile_d)
  tile is contiguous per output column, same as the Q40 planes;
* a Pallas kernel that widens int8 → f32, applies the per-block scale
  (the file codec's math, quants.py:162-171), rounds the product to bf16
  for the MXU — one more round than the codec's f32 dequant, the same
  policy as the q40 classic variant — and accumulates reduction tiles in
  VMEM; q8.dequantize applies the identical round so kernel and XLA
  emulation agree bit-for-bit;
* a layer-stacked variant with the layer index as scalar prefetch, so
  the ``lax.scan`` over layers DMAs tiles straight from the stacked HBM
  buffer (no per-layer slice materialization — see q40.py:494-506);
* XLA-emulation fallback (`impl="xla"`): bit-identical dequant + dot,
  GSPMD-partitionable — the path multi-device meshes take (Q80 is not
  the production format; its mesh story is correctness, not the custom
  kernel; q40.py carries the sharded fast path).

Shares q40's padding contract (``padded_n``; padded scales are zero) and
its f16-bit scale decode (no f16 in the Mosaic dialect).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pallas_compat
from .. import quants
from .q40 import (PALLAS_MAX_ROWS, QLayerView, _f16_bits_to_f32, _pad_x,
                  _smap_mesh, _tiles, padded_n)

# Width-rule VMEM ceiling for THIS codec: the q8 kernel carries an f32
# accumulator intermediate of tn*td*4 B on top of the int8 value tile, so
# a rule legal for q40 (4 Mi elements) can blow VMEM here; 2 Mi keeps the
# worst case ~8 MB f32 + 2 MB int8 against ~16 MB VMEM (ADVICE r04 #2).
Q8_TILE_CAP = 2 * 1024 * 1024


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class Q8Tensor:
    """A Q80 tensor of logical shape ``(..., n, d)``, packed for the MXU.

    Field names match ``q40.QTensor`` so ``q40.QLayerView`` (select /
    flat_planes / sliced) works unchanged over stacked Q8 planes."""

    qpacked: jax.Array          # int8   (..., padded_n, d)
    scales: jax.Array           # uint16 (..., padded_n/32, d) — f16 bits
    logical_nd: tuple[int, int] = field(metadata=dict(static=True))

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.qpacked.shape[:-2]) + self.logical_nd

    @property
    def dtype(self):
        return jnp.bfloat16


# ---------------------------------------------------------------------------
# Packing
# ---------------------------------------------------------------------------

def alloc_value_plane(lead: tuple, np_: int, d: int) -> np.ndarray:
    """Q80 stores one int8 row per input position (q40 twin packs 2/byte)."""
    return np.zeros((*lead, np_, d), np.int8)


Tensor = Q8Tensor  # codec-generic alias (q40.Tensor = QTensor)


def pack_planes_np(qvals: np.ndarray, scales: np.ndarray
                   ) -> tuple[np.ndarray, np.ndarray, tuple[int, int]]:
    """int8 values ``(..., n, d)`` + f16 scales ``(..., n/32, d)`` →
    padded host planes (zero pad values AND scales: the padded region
    contributes exactly 0 to every dot)."""
    *lead, n, d = qvals.shape
    np_ = padded_n(n)
    q = np.asarray(qvals, np.int8)
    s = np.asarray(scales, np.float16)
    if np_ != n:
        q = np.concatenate([q, np.zeros((*lead, np_ - n, d), np.int8)], axis=-2)
        s = np.concatenate(
            [s, np.zeros((*lead, (np_ - n) // 32, d), np.float16)], axis=-2)
    return q, s, (n, d)


def quantize(w: np.ndarray) -> Q8Tensor:
    """Quantize a float array ``(..., n, d)`` along the input axis with the
    file codec's math (delta = absmax/127; round half away from zero like
    the reference's roundf — quants.round_half_away / writer.py:58-77)."""
    w = np.asarray(w, np.float32)
    *lead, n, d = w.shape
    if n % quants.BLOCK_SIZE:
        raise ValueError(f"input dim {n} not divisible by {quants.BLOCK_SIZE}")
    g = w.reshape(*lead, n // 32, 32, d)
    deltas = np.abs(g).max(axis=-2) / 127.0
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = quants.round_half_away(g * inv[..., None, :]) \
        .astype(np.int8).reshape(*lead, n, d)
    with np.errstate(over="ignore"):  # overflow becomes inf → caught below
        sc = deltas.astype(np.float16)
    if not np.isfinite(sc).all():
        raise ValueError("Q80 scale overflowed f16 — values too large to pack")
    qv, s, nd = pack_planes_np(q, sc)
    return Q8Tensor(jnp.asarray(qv), jnp.asarray(s.view(np.uint16)), nd)


def repack_file_bytes_into(raw: np.ndarray, d: int, n: int,
                           qv2: np.ndarray, sc2: np.ndarray, col: int = 0) -> None:
    """One (d, n) tensor's `.m` Q80 bytes → preallocated runtime planes
    (``qv2`` int8 (padded_n, ld), ``sc2`` f16 (padded_n/32, ld)) at output
    column ``col`` — a pure byte transpose (BlockQ80, quants.hpp:22-25);
    native single pass (csrc q80_repack) when built, numpy otherwise."""
    from ..native import have_native_q80, q80_repack_into

    nb = n // 32
    if have_native_q80():
        q80_repack_into(raw, d, n, qv2, sc2, col)
        return
    blocks = np.asarray(raw, np.uint8).reshape(d, nb, quants.Q80_BLOCK_BYTES)
    sc2[:nb, col:col + d] = (
        np.ascontiguousarray(blocks[:, :, :2]).view(np.float16).reshape(d, nb).T)
    vals = np.ascontiguousarray(blocks[:, :, 2:]).view(np.int8)  # (d, nb, 32)
    qv2[:nb * 32, col:col + d] = np.moveaxis(vals, 0, 2).reshape(nb * 32, d)


def pack_file_groups(groups: list[list[tuple[np.ndarray, int, int]]],
                     stacked: bool = True) -> Q8Tensor:
    """Layer-stacked Q8Tensor straight from `.m` file bytes (the Q80 twin
    of q40.pack_file_groups; same fused-group and inf/NaN-scale rules)."""
    n = groups[0][0][2]
    d_total = sum(g[1] for g in groups[0])
    L = len(groups)
    np_ = padded_n(n)
    qv = np.zeros((L, np_, d_total), np.int8)
    sc = np.zeros((L, np_ // 32, d_total), np.float16)
    for l, group in enumerate(groups):
        col = 0
        for raw, d, gn in group:
            if gn != n:
                raise ValueError(f"fused group mixes input dims {gn} != {n}")
            repack_file_bytes_into(raw, d, n, qv[l], sc[l], col)
            col += d
    if not np.isfinite(sc).all():
        raise ValueError(
            "Q80 scale plane contains inf/NaN f16 scales — corrupt or "
            "overflowed .m tensor (delta exceeded f16 range at conversion)")
    scu = sc.view(np.uint16)
    if not stacked:
        if L != 1:
            raise ValueError("stacked=False needs exactly one group")
        return Q8Tensor(jnp.asarray(qv[0]), jnp.asarray(scu[0]), (n, d_total))
    return Q8Tensor(jnp.asarray(qv), jnp.asarray(scu), (n, d_total))


# ---------------------------------------------------------------------------
# Dequantize (XLA path — also the numerics oracle for the kernel)
# ---------------------------------------------------------------------------

def dequantize(qt: Q8Tensor, dtype=jnp.bfloat16) -> jax.Array:
    """Padded planes → dense logical (..., n, d); one bf16 round of v·s,
    matching the kernel and the file codec."""
    qv, s = qt.qpacked, qt.scales
    *lead, np_, d = qv.shape
    n, _ = qt.logical_nd
    s32 = _f16_bits_to_f32(s)
    v = qv.astype(jnp.float32).reshape(*lead, np_ // 32, 32, d)
    w = (v * s32[..., :, None, :]).astype(dtype).reshape(*lead, np_, d)
    return w[..., :n, :]


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _q8_kernel(x_ref, qv_ref, s_ref, o_ref, acc_ref, *, nsteps):
    i = pl.program_id(1)
    vi = qv_ref[:]                                  # (tn, td) int8
    sc = s_ref[:]
    if vi.ndim == 3:                                # stacked: (1, tn, td) block
        vi, sc = vi[0], sc[0]
    tn, td = vi.shape
    nb = tn // 32
    s32 = _f16_bits_to_f32(sc)                      # (nb, td)
    # int8 → f32 via int32 (no direct small-int→float casts in Mosaic),
    # per-block scale, one bf16 round — the file codec's dequant exactly
    v32 = vi.astype(jnp.int32).astype(jnp.float32).reshape(nb, 32, td)
    w = (v32 * s32[:, None, :]).astype(jnp.bfloat16).reshape(tn, td)
    part = jnp.dot(x_ref[:], w, preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = part

    @pl.when(i > 0)
    def _():
        acc_ref[:] = acc_ref[:] + part

    @pl.when(i == nsteps - 1)
    def _():
        o_ref[:] = acc_ref[:]


def _stacked_q8_kernel(lidx_ref, x_ref, qv_ref, s_ref, o_ref, acc_ref, *, nsteps):
    del lidx_ref  # consumed by the index_maps
    _q8_kernel(x_ref, qv_ref, s_ref, o_ref, acc_ref, nsteps=nsteps)


@functools.partial(jax.jit, static_argnames=("interpret", "tiles"))
def _pallas_matmul(x: jax.Array, qv: jax.Array, s: jax.Array,
                   interpret: bool = False,
                   tiles: tuple[int, int] | None = None) -> jax.Array:
    t, n = x.shape
    d = qv.shape[-1]
    tile_n, tile_d = tiles or _tiles(n, d, cap_elems=Q8_TILE_CAP)
    grid = (pl.cdiv(d, tile_d), n // tile_n)
    return pl.pallas_call(
        functools.partial(_q8_kernel, nsteps=grid[1]),
        grid=grid,
        in_specs=[
            pl.BlockSpec((t, tile_n), lambda j, i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, tile_d), lambda j, i: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n // 32, tile_d), lambda j, i: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((t, tile_d), lambda j, i: (0, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((t, tile_d), jnp.float32)],
        compiler_params=pallas_compat.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x.astype(jnp.bfloat16), qv, s)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_matmul_stacked(x: jax.Array, qv: jax.Array, s: jax.Array,
                           layer: jax.Array, interpret: bool = False) -> jax.Array:
    """Layer-indexed Q80 matmul over stacked planes (scalar-prefetch index
    into the (L, n, d) HBM buffer — see q40._pallas_matmul_stacked)."""
    t, n = x.shape
    d = qv.shape[-1]
    tile_n, tile_d = _tiles(n, d, cap_elems=Q8_TILE_CAP)
    grid = (pl.cdiv(d, tile_d), n // tile_n)
    return pl.pallas_call(
        functools.partial(_stacked_q8_kernel, nsteps=grid[1]),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((t, tile_n), lambda j, i, l: (0, i)),
                pl.BlockSpec((1, tile_n, tile_d), lambda j, i, l: (l[0], i, j)),
                pl.BlockSpec((1, tile_n // 32, tile_d), lambda j, i, l: (l[0], i, j)),
            ],
            out_specs=pl.BlockSpec((t, tile_d), lambda j, i, l: (0, j)),
            scratch_shapes=[pltpu.VMEM((t, tile_d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((t, d), jnp.float32),
        compiler_params=pallas_compat.compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(layer.reshape(1).astype(jnp.int32), x.astype(jnp.bfloat16), qv, s)


@functools.cache
def _pallas_ok(tile_n: int, tile_d: int, t: int) -> bool:
    """Hardware probe for the Q80 kernel (random fixture — q40._pallas_ok
    rationale applies: layout bugs must not hide behind constant blocks)."""
    try:
        n = 2 * tile_n
        rng = np.random.RandomState(0)
        qt = quantize((rng.randn(n, tile_d) * 0.1).astype(np.float32))
        x = jnp.asarray(rng.randn(t, n).astype(np.float32), jnp.bfloat16)
        out = _pallas_matmul(x, qt.qpacked, qt.scales, tiles=(tile_n, tile_d))
        ref = x @ dequantize(qt, jnp.bfloat16)
        if not np.allclose(np.asarray(out), np.asarray(ref),
                           atol=1e-2 * float(np.abs(np.asarray(ref)).max())):
            raise AssertionError("q8 pallas probe result mismatch")
        return True
    except Exception as e:
        from ..obs import dispatch as obs_dispatch
        obs_dispatch.record_degrade(
            "q8", "probe_failed", warn_key=(tile_n, tile_d, t),
            tile_n=tile_n, tile_d=tile_d, t=t,
            error=f"{type(e).__name__}: {str(e)[:120]}")
        return False


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def matmul(x: jax.Array, qt: Q8Tensor | QLayerView, impl: str = "auto",
           out_dtype=None, kind: str | None = None) -> jax.Array:
    """``x @ dequantize(qt)`` with f32 accumulation (Q80 weights).

    Single-device: fused Pallas kernel (probe-guarded).  On a multi-device
    mesh or off-TPU: the GSPMD-partitionable XLA emulation (see module
    docstring) — ``kind`` is accepted for call-site symmetry with q40.mm
    but only the XLA path runs there, so it is unused.
    """
    del kind  # only the auto-sharded XLA path runs on meshes
    n, d = qt.logical_nd
    lead = x.shape[:-1]
    rows = int(np.prod(lead)) if lead else 1
    out_dtype = out_dtype or x.dtype
    is_view = isinstance(qt, QLayerView)

    if impl == "auto":
        on_tpu = jax.default_backend() == "tpu"
        np_ = (qt.qt if is_view else qt).qpacked.shape[-2]
        tile_n, tile_d = _tiles(np_, d, cap_elems=Q8_TILE_CAP)
        impl = "pallas" if (on_tpu and rows <= PALLAS_MAX_ROWS
                            and _smap_mesh() is None
                            and _pallas_ok(tile_n, tile_d,
                                           1 if rows == 1 else PALLAS_MAX_ROWS)) \
            else "xla"

    from ..obs import dispatch as obs_dispatch
    if impl in ("pallas", "pallas_interpret") and _smap_mesh() is None:
        interp = impl == "pallas_interpret"
        obs_dispatch.record_dispatch("q8", "pallas-fused", rows=rows,
                                     layout="row-major")
        if is_view:
            qv3, s3 = qt.flat_planes()
            np_ = qv3.shape[-2]
            x2 = _pad_x(x.reshape(rows, n), n, np_)
            out = _pallas_matmul_stacked(x2, qv3, s3, qt.layer, interpret=interp)
        else:
            np_ = qt.qpacked.shape[-2]
            x2 = _pad_x(x.reshape(rows, n), n, np_)
            out = _pallas_matmul(x2, qt.qpacked, qt.scales, interpret=interp)
        return out.reshape(*lead, d).astype(out_dtype)

    if impl not in ("xla", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown q8 matmul impl {impl!r} "
                         "(expected auto | xla | pallas | pallas_interpret)")
    if impl != "xla" and _smap_mesh() is not None:
        # Q80 has no shard_map kernel path: a forced-pallas request on a
        # mesh degrades to the GSPMD XLA emulation (see module docstring)
        obs_dispatch.record_degrade(
            "q8", "mesh_xla", warn_key=qt.logical_nd,
            shape=qt.logical_nd, impl=impl)
    # XLA path (meshes, CPU, probe failure)
    obs_dispatch.record_dispatch("q8", "xla-dequant", rows=rows)
    base = qt.sliced() if is_view else qt
    w = dequantize(base, dtype=jnp.bfloat16)
    return jnp.dot(x.astype(jnp.bfloat16), w,
                   preferred_element_type=jnp.float32).astype(out_dtype)
