"""Version portability for Pallas TPU compiler params.

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams`` and
grew new fields (``has_side_effects``) along the way; pinning either
spelling breaks half the installs we run on.  :func:`compiler_params`
resolves whichever class the installed jax exports and drops kwargs the
class predates, so kernels written against the new spelling still build
on older jax.

Dropped fields are harmless here by construction: every kernel in this
package consumes its pallas_call outputs, so ``has_side_effects`` (DCE
protection for output-free kernels) never changes lowering for us.
"""

from __future__ import annotations

import dataclasses

from jax.experimental.pallas import tpu as pltpu

_CLS = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
_FIELDS = {f.name for f in dataclasses.fields(_CLS)}


def compiler_params(**kw):
    """Build the installed jax's Pallas TPU compiler-params object,
    keeping only the fields this jax version knows about."""
    return _CLS(**{k: v for k, v in kw.items() if k in _FIELDS})


def prefetch_grid_spec(**kw):
    """Scalar-prefetch grid spec across jax versions.

    ``pltpu.PrefetchScalarGridSpec`` is the spelling every jax in our
    support window exports, but newer releases fold the same fields into
    the generic ``pl.GridSpec(num_scalar_prefetch=...)``; resolve
    whichever the installed jax carries (the page-table-walking fused
    attention kernel indexes its KV blocks through the prefetched
    table, so this spec is load-bearing, not an optimization hint)."""
    cls = getattr(pltpu, "PrefetchScalarGridSpec", None)
    if cls is not None:
        return cls(**kw)
    from jax.experimental import pallas as _pl
    return _pl.GridSpec(**kw)
