"""Core elementwise / normalization / RoPE ops.

TPU-native equivalents of the reference kernel layer
(/root/reference/src/funcs.cpp).  Where the reference hand-slices every op
across a spin-barrier thread pool (funcs.cpp:126-146 etc.), here each op is
a pure jnp function: XLA fuses them into the surrounding matmuls, which is
the TPU analogue of the reference's fusion-by-hand.

Numerics notes (for golden parity):
* rmsnorm epsilon placement matches funcs.cpp:95-124:
  ``1/sqrt(mean(x²) + 1e-5)`` — eps *after* the mean.
* gelu is the tanh approximation (funcs.cpp:488-497).
* RoPE has two conventions, selected per arch (transformer.cpp:227-231):
  - ``llama``: adjacent-pair rotation, angle per pair index within the head
    (LlamaRopeCommand, commands.cpp:160-199)
  - ``neox`` (the reference's "Falcon" rope, used by Grok-1/Mixtral):
    rotate-half within the head (FalconRopeCommand, commands.cpp:201-229)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

RMS_EPS = 1e-5  # funcs.cpp:120


def rmsnorm(x: jax.Array, weight: jax.Array, eps: float = RMS_EPS) -> jax.Array:
    """RMS-normalize over the last axis, then scale by ``weight``.

    Matches ``rms`` + ``rmsnorm`` (funcs.cpp:95-146): the sum-of-squares is
    accumulated in f32 regardless of the activation dtype.
    """
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    return (weight.astype(jnp.float32) * (xf * inv)).astype(x.dtype)


def silu(x: jax.Array) -> jax.Array:
    """x · σ(x) (funcs.cpp:499-507)."""
    return x * jax.nn.sigmoid(x)


def gelu_tanh(x: jax.Array) -> jax.Array:
    """tanh-approximated GELU (funcs.cpp:488-497)."""
    xf = x.astype(jnp.float32)
    y = 0.5 * xf * (1.0 + jnp.tanh(0.7978845608028654 * (xf + 0.044715 * xf * xf * xf)))
    return y.astype(x.dtype)


ACTIVATIONS = {0: gelu_tanh, 1: silu}  # TransformerHiddenAct (transformer.hpp:45-48)


def rope_angles(positions: jax.Array, head_size: int, theta: float) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for positions: shapes ``positions.shape + (head_size/2,)``.

    Frequency ``j`` is ``theta^(-2j/head_size)`` — identical for both
    conventions (commands.cpp:171-172, 216-217); only the pairing differs.
    """
    half = head_size // 2
    freqs = 1.0 / (theta ** (np.arange(0, half, dtype=np.float32) * 2.0 / head_size))
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, *, interleaved: bool) -> jax.Array:
    """Rotate ``x`` of shape (..., n_heads, head_size).

    ``cos``/``sin`` have shape (..., head_size/2) and broadcast over heads.

    interleaved=True  → llama convention: pairs (2j, 2j+1)
    interleaved=False → neox/"falcon" convention: pairs (j, j+half)
    """
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    c = cos[..., None, :]
    s = sin[..., None, :]
    if interleaved:
        x0 = xf[..., 0::2]
        x1 = xf[..., 1::2]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        out = jnp.stack([r0, r1], axis=-1).reshape(x.shape)
    else:
        half = x.shape[-1] // 2
        x0 = xf[..., :half]
        x1 = xf[..., half:]
        r0 = x0 * c - x1 * s
        r1 = x0 * s + x1 * c
        out = jnp.concatenate([r0, r1], axis=-1)
    return out.astype(orig_dtype)


def softmax_f32(x: jax.Array, axis: int = -1) -> jax.Array:
    """Max-shifted softmax in f32 (funcs.cpp:64-93)."""
    xf = x.astype(jnp.float32)
    m = jnp.max(xf, axis=axis, keepdims=True)
    e = jnp.exp(xf - m)
    return e / jnp.sum(e, axis=axis, keepdims=True)
