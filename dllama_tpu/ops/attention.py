"""Grouped-query attention with a persistent KV cache.

TPU-native replacement for the reference's per-head scalar attention loop
(/root/reference/src/llama2-tasks.cpp:54-94): instead of iterating heads ×
positions on a thread pool, the whole (batch, heads, q_len, kv_len) score
tensor is one batched einsum on the MXU, with causal/position masking done
with an iota comparison (static shapes; ``pos`` is a traced scalar so one
compiled program serves every decode step).

The KV cache layout is ``(batch, n_kv_heads, seq_len, head_size)`` — the
kv-head axis is the reference's ``KvCacheSlice`` dim (commands.cpp:94-99)
and is the axis sharded across the tensor-parallel mesh.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np

from .kernels import softmax_f32


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-(…, position) int8 quantization of a KV step window
    (B, Hkv, T, Dh) → int8 values + f32 absmax/127 scales (B, Hkv, T, 1).

    The int8 KV cache (beyond reference — transformer.cpp:280-282 holds
    f32) halves cache HBM traffic and residency vs bf16; a per-position
    scale over Dh values keeps the quantization row-local so decode's
    block reads stay self-contained."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = amax / 127.0
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.round(xf * inv).astype(jnp.int8)
    return q, scale


def dequant_kv(vals: jax.Array, scale: jax.Array) -> jax.Array:
    """int8 KV block × f32 per-position scale → bf16 (the dot operand
    dtype _online_fold wants: the cast+mul fuses into the score dot, so
    only int8 bytes cross HBM)."""
    return (vals.astype(jnp.float32) * scale).astype(jnp.bfloat16)


def update_kv_cache_at(k_cache: jax.Array, v_cache: jax.Array,
                       k_new: jax.Array, v_new: jax.Array,
                       layer: jax.Array, pos: jax.Array
                       ) -> tuple[jax.Array, jax.Array]:
    """Write one layer's step KV (B, Hkv, T, Dh) into the *stacked*
    (L, B, Hkv, S, Dh) caches at ``(layer, pos)``.

    The reference appends at ``pos`` into its per-slice cache
    (llama2-tasks.cpp:33-45 writes k/v straight into the cache row); here
    it is a dynamic_update_slice into the layer's window.  The stacked
    caches ride the layer scan as a **carry** and each layer writes only
    its (1, B, Hkv, T, Dh) window — a few KB — in place.  (Passing the
    caches through the scan as xs/ys instead makes XLA slice out and
    re-stack an entire layer slab per step, plus whole-cache defensive
    copies in the enclosing decode loop: measured ~8 ms/token of pure
    cache movement at 7B/1k, nearly the matmul cost itself.)"""
    zero = jnp.zeros((), layer.dtype)
    idx = (layer, zero, zero, pos.astype(layer.dtype), zero)
    k_cache = jax.lax.dynamic_update_slice(k_cache, k_new[None].astype(k_cache.dtype), idx)
    v_cache = jax.lax.dynamic_update_slice(v_cache, v_new[None].astype(v_cache.dtype), idx)
    return k_cache, v_cache


def update_kv_cache_rows(k_cache: jax.Array, v_cache: jax.Array,
                         k_new: jax.Array, v_new: jax.Array,
                         layer: jax.Array, pos_rows: jax.Array
                         ) -> tuple[jax.Array, jax.Array]:
    """Write one layer's step KV (B, Hkv, T, Dh) into the *stacked*
    (L, B, Hkv, S, Dh) caches at **per-row** positions (B,).

    The continuous-batching twin of :func:`update_kv_cache_at`: slot rows
    belong to different requests, so each row advances its own clock —
    a joining slot prefills at position 0 while its neighbors decode at
    position 900.  A vmap over the batch axis gives every row its own
    ``dynamic_update_slice`` start, which XLA lowers to B independent
    windowed writes into the carried cache (same in-place cost model as
    the shared-clock write).

    Callers must keep ``pos_rows[r] + T <= S`` for every row:
    dynamic_update_slice clamps out-of-range starts *backward*, which
    would silently overwrite the newest valid history (the scheduler
    retires rows at the context edge before dispatching)."""

    def row(ck, cv, kn, vn, p):
        # ck/cv: (L, Hkv, S, Dh) one row's stacked planes; kn/vn: (Hkv, T, Dh)
        zero = jnp.zeros((), jnp.int32)
        idx = (layer.astype(jnp.int32), zero, p.astype(jnp.int32), zero)
        ck = jax.lax.dynamic_update_slice(ck, kn[None].astype(ck.dtype), idx)
        cv = jax.lax.dynamic_update_slice(cv, vn[None].astype(cv.dtype), idx)
        return ck, cv

    return jax.vmap(row, in_axes=(1, 1, 0, 0, 0), out_axes=(1, 1))(
        k_cache, v_cache, k_new, v_new, pos_rows)


def _rows_ceiling_attention(q: jax.Array, k_l: jax.Array, v_l: jax.Array,
                            pos_rows: jax.Array) -> jax.Array:
    """One-shot causal GQA over one layer's K/V (B, Hkv, S, Dh) with a
    **per-row** causal ceiling: row ``r``'s query tokens occupy positions
    ``pos_rows[r]..pos_rows[r]+T-1`` and may see key positions
    ``<= pos_rows[r] + t_local`` only.  Shared by the contiguous slot
    read (:func:`slot_gqa_attention_at`) and the paged gather-view read
    (:func:`paged_gqa_attention_at`) so the two layouts cannot drift on
    masking or accumulation dtype."""
    b, hq, t, dh = q.shape
    hkv = k_l.shape[1]
    s = k_l.shape[2]
    g = hq // hkv

    # operands in cache dtype, f32 accumulation — see _online_fold for why
    qc = q.reshape(b, hkv, g, t, dh).astype(k_l.dtype)
    scores = jnp.einsum("bhgtd,bhsd->bhgts", qc, k_l,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(dh))

    s_idx = jnp.arange(s)[None, None, :]
    t_idx = pos_rows[:, None, None] + jnp.arange(t)[None, :, None]
    mask = s_idx <= t_idx  # (B, T, S) — per-row causal ceiling
    scores = jnp.where(mask[:, None, None], scores, _NEG)

    probs = softmax_f32(scores, axis=-1)
    out = jnp.einsum("bhgts,bhsd->bhgtd", probs.astype(v_l.dtype), v_l,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, hq, t, dh).astype(q.dtype)


def slot_gqa_attention_at(q: jax.Array, ck: jax.Array, cv: jax.Array,
                          layer: jax.Array, pos_rows: jax.Array) -> jax.Array:
    """One-shot causal GQA over the *stacked* caches at ``layer`` with a
    **per-row** causal ceiling (see :func:`_rows_ceiling_attention`).

    This is the attention read of the continuous-batching slot step.
    Unlike the ragged-batch path there is no key *floor*: every slot's
    request starts at cache position 0, and a freed slot is reused by
    simply resetting its position — the previous occupant's stale keys
    sit *above* the new request's ceiling, masked until each position is
    overwritten by the new occupant (write-before-visible).  Zeroing the
    row instead would be wrong twice over: it costs an O(S) write, and a
    zero key is a *real* key (it would contribute exp(0-ish) mass to the
    softmax denominator).

    Per-step traffic is O(S) like the one-shot decode path; slot serving
    targets the throughput regime (batch > 1, moderate context) where the
    weight read — amortized over B rows — dominates.
    """
    k_l = jax.lax.dynamic_index_in_dim(ck, layer, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(cv, layer, 0, keepdims=False)
    return _rows_ceiling_attention(q, k_l, v_l, pos_rows)


# ---------------------------------------------------------------------------
# Paged KV: a global page pool + per-slot block tables (PagedAttention).
#
# The pool is ``(L, n_pages, Hkv, page_size, Dh)`` — the contiguous stacked
# layout with the batch axis generalized to physical pages and the sequence
# axis shrunk to one page.  A slot's logical cache is described by one
# (max_pages,) int32 row of the page table, shared across layers: logical
# position ``p`` of slot ``r`` lives at ``pool[:, table[r, p // ps], :,
# p % ps]``.  Physical page 0 is reserved as a scratch page: table entries
# past a slot's reserved pages point at it, and every *invalid* token write
# (decode padding, tokens past ``n_valid``, burst overshoot past a retired
# row's budget) is redirected there — so shared prefix pages are immutable
# by construction and garbage lands where no mask can ever expose it.


def paged_write_indices(page_table: jax.Array, pos_rows: jax.Array,
                        n_valid: jax.Array, t: int, page_size: int
                        ) -> tuple[jax.Array, jax.Array]:
    """Physical (page, offset) index arrays, both (B, T) int32, for one
    slot step's KV writes through the page table.

    Computed ONCE per forward (outside the layer scan — every layer writes
    the same logical positions).  Invalid tokens (``t_local >= n_valid``)
    are redirected to scratch page 0; logical pages past the table width
    clamp into it, where unreserved entries already hold 0."""
    maxp = page_table.shape[1]
    tpos = pos_rows[:, None] + jnp.arange(t)[None, :]          # (B, T)
    valid = jnp.arange(t)[None, :] < n_valid[:, None]          # (B, T)
    pslot = jnp.clip(tpos // page_size, 0, maxp - 1)
    pidx = jnp.take_along_axis(page_table, pslot, axis=1)
    pidx = jnp.where(valid, pidx, 0)
    oidx = tpos % page_size
    return pidx.astype(jnp.int32), oidx.astype(jnp.int32)


def paged_update_kv_rows(pool_k: jax.Array, pool_v: jax.Array,
                         k_new: jax.Array, v_new: jax.Array,
                         layer: jax.Array, pidx: jax.Array, oidx: jax.Array
                         ) -> tuple[jax.Array, jax.Array]:
    """Write one layer's step KV (B, Hkv, T, Dh) into the paged pools
    (L, P, Hkv, ps, Dh) at per-token physical ``(page, offset)`` indices
    (B, T) from :func:`paged_write_indices`.

    One advanced-indexing scatter per pool: the (B, T) page/offset arrays
    are non-adjacent advanced indices (the Hkv slice sits between), so the
    update operand is (B, T, Hkv, Dh) — the step KV with its token axis
    moved ahead of the head axis.  Invalid tokens all target scratch page
    0; colliding scratch writes are unordered, which is fine — nothing
    reads that page unmasked."""
    kbt = k_new.transpose(0, 2, 1, 3).astype(pool_k.dtype)  # (B, T, Hkv, Dh)
    vbt = v_new.transpose(0, 2, 1, 3).astype(pool_v.dtype)
    li = layer.astype(jnp.int32)
    pool_k = pool_k.at[li, pidx, :, oidx].set(kbt)
    pool_v = pool_v.at[li, pidx, :, oidx].set(vbt)
    return pool_k, pool_v


def paged_gather_layer(pool: jax.Array, layer: jax.Array,
                       page_table: jax.Array,
                       scale_pool: jax.Array | None = None) -> jax.Array:
    """Materialize one layer's logical KV view (B, Hkv, maxp·ps, Dh) by
    gathering each slot's pages from the pool (L, P, Hkv, ps, Dh).  The
    gather is the paged twin of the contiguous layer slice: XLA fuses it
    into the score dot for the short-cache one-shot path, and the
    long-cache decode path avoids it entirely (page-walk fold).

    ``scale_pool``: the int8 pool's per-position scale planes
    (L, P, Hkv, ps, 1) — the gather stays int8-sized and the dequant
    multiply fuses into the downstream dot like the plain cast."""
    pl = jax.lax.dynamic_index_in_dim(pool, layer, 0, keepdims=False)
    view = pl[page_table]  # (B, maxp, Hkv, ps, Dh)
    b, maxp, hkv, ps, dh = view.shape
    out = view.transpose(0, 2, 1, 3, 4).reshape(b, hkv, maxp * ps, dh)
    if scale_pool is None:
        return out
    sl = jax.lax.dynamic_index_in_dim(scale_pool, layer, 0, keepdims=False)
    sview = sl[page_table]  # (B, maxp, Hkv, ps, 1)
    sc = sview.transpose(0, 2, 1, 3, 4).reshape(b, hkv, maxp * ps, 1)
    return dequant_kv(out, sc)


def paged_decode_attention(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                           layer: jax.Array, page_table: jax.Array,
                           pos_rows: jax.Array,
                           scales: tuple[jax.Array, jax.Array] | None = None
                           ) -> jax.Array:
    """Single-token decode over the paged pool that walks only live pages:
    :func:`blocked_live_fold` with the page as the block (the pool already
    stores fixed-size KV chunks — pages ARE the fold's block granularity)
    and one pool gather per step in place of the contiguous block slice.
    Per-row ceilings ride the fold's ``row_pos`` mask; rows whose table
    runs out before the longest neighbor read scratch page 0, fully
    masked.

    ``scales``: the int8 pool's (k, v) scale planes (L, P, Hkv, ps, 1) —
    each fold step gathers the value page AND its scale page and
    dequantizes after the int8-sized HBM read (the point of the
    quantized pool)."""
    b, hq, t, dh = q.shape
    hkv = pool_k.shape[2]
    ps = pool_k.shape[3]
    maxp = page_table.shape[1]
    g = hq // hkv
    qf = q.astype(jnp.float32).reshape(b, hkv, g, t, dh)

    def slice_page(pool, start, length):
        pid = jax.lax.dynamic_index_in_dim(page_table, start // ps, 1,
                                           keepdims=False)  # (B,)
        # advanced (scalar layer, (B,) page) indexing: one (B, Hkv, ps, Dh)
        # page gather per fold step — never the whole layer slab
        return pool[layer.astype(jnp.int32), pid]

    if scales is None:
        kc_arg, vc_arg = pool_k, pool_v
        sl = slice_page
    else:
        ks, vs = scales

        def sl(pair, start, length):
            vals, sc = pair
            return dequant_kv(slice_page(vals, start, length),
                              slice_page(sc, start, length))

        kc_arg, vc_arg = (pool_k, ks), (pool_v, vs)

    _, l, acc = blocked_live_fold(qf, sl, kc_arg, vc_arg,
                                  jnp.max(pos_rows), jnp.int32(0), maxp * ps,
                                  row_pos=pos_rows, block=ps)
    out = acc / jnp.maximum(l, 1e-38)[..., None]
    return out.reshape(b, hq, t, dh).astype(q.dtype)


# ---------------------------------------------------------------------------
# Fused paged-attention megakernel (one-dispatch decode, ROADMAP item 2).
#
# One Pallas program per decode step walks the page table directly via
# scalar prefetch: grid (B, max_pages), each step's KV block is DMA'd
# straight out of the pool at ``pool[layer, table[b, p]]`` — no
# materialized (B, Hkv, maxp·ps, Dh) gather, no separate dequant pass for
# int8 pools (the per-position scale plane rides as a second prefetched
# block and the cast*scale happens in-register), and the online-softmax
# accumulators live in VMEM scratch across the page walk.  Gating mirrors
# the q40 matmul ladder: ``DLLAMA_FUSED_ATTN`` auto/on/off/interp, a
# cached hardware probe guards auto, and every forced-path fallback goes
# through the warn-once degrade ledger (obs/dispatch.py).


_FUSED_ENV = "DLLAMA_FUSED_ATTN"


def fused_mode() -> str:
    """The fused paged-attention gate, read lazily so tests and the
    bench A/B can flip it per engine: ``auto`` (TPU + probe, silent CPU
    fallback), ``on`` (degrade loudly if unusable), ``off``, ``interp``
    (force the kernel in Pallas interpret mode — CPU parity tests and
    the ``-fused4`` A/B)."""
    return os.environ.get(_FUSED_ENV, "auto").strip().lower() or "auto"


def _make_fused_kernel(hq: int, hkv: int, dh: int, ps: int, maxp: int,
                       quantized: bool, out_dtype):
    """Build the fused decode kernel body for one (head/page) geometry.

    Ref order: 3 scalar-prefetch refs (layer (1,), page table (B, maxp),
    per-row positions (B,)), then the q block and the page-walk KV blocks
    (+ scale blocks when quantized), the output block, and the VMEM
    scratch accumulators (running max, denom, numerator) that persist
    across the page axis of the grid."""
    g = hq // hkv
    inv_sqrt = np.float32(1.0 / math.sqrt(dh))

    def kernel(layer_ref, ptab_ref, pos_ref, q_ref, k_ref, v_ref, *rest):
        del layer_ref, ptab_ref  # consumed by the BlockSpec index maps
        if quantized:
            ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
        else:
            o_ref, m_ref, l_ref, acc_ref = rest
        from jax.experimental import pallas as plx
        b = plx.program_id(0)
        p = plx.program_id(1)
        pos = pos_ref[b]

        @plx.when(p == 0)
        def _init():
            m_ref[...] = jnp.full(m_ref.shape, _NEG, jnp.float32)
            l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
            acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

        # pages past the row's live prefix are skipped entirely (their
        # BlockSpec index map also clamps to the last live page, so the
        # prefetch pipeline issues no new DMA for them)
        @plx.when(p <= pos // ps)
        def _fold():
            k = k_ref[0, 0]  # (Hkv, ps, Dh)
            v = v_ref[0, 0]
            if quantized:
                # in-register dequant: int8 page block × per-position
                # scale column → bf16 dot operands (dequant_kv semantics,
                # without the materialized intermediate)
                k = (k.astype(jnp.float32) * ks_ref[0, 0]).astype(jnp.bfloat16)
                v = (v.astype(jnp.float32) * vs_ref[0, 0]).astype(jnp.bfloat16)
            qb = q_ref[0].reshape(hkv, g, dh).astype(k.dtype)
            # (Hkv, G, ps): score dot batched over the kv-head axis, f32
            # accumulation like _online_fold
            scores = jax.lax.dot_general(
                qb, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) * inv_sqrt
            s_idx = p * ps + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 2)
            scores = jnp.where(s_idx <= pos, scores, _NEG)
            sc = scores.reshape(hq, ps)
            m_prev = m_ref[:, 0:1]                      # (Hq, 1)
            l_prev = l_ref[:, 0:1]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            pexp = jnp.exp(sc - m_new)                  # (Hq, ps)
            l_new = alpha * l_prev + jnp.sum(pexp, axis=1, keepdims=True)
            pv = jax.lax.dot_general(
                pexp.reshape(hkv, g, ps).astype(v.dtype), v,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)     # (Hkv, G, Dh)
            acc_ref[...] = alpha * acc_ref[...] + pv.reshape(hq, dh)
            m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
            l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

        @plx.when(p == maxp - 1)
        def _emit():
            l = jnp.maximum(l_ref[:, 0:1], 1e-38)
            o_ref[0] = (acc_ref[...] / l).astype(out_dtype)

    return kernel


def fused_paged_attention(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                          layer: jax.Array, page_table: jax.Array,
                          pos_rows: jax.Array,
                          scales: tuple[jax.Array, jax.Array] | None = None,
                          *, interpret: bool = False) -> jax.Array:
    """Single-token paged GQA as ONE kernel: page-table walk, (optional)
    in-register int8 dequant, and online-softmax fold in a single
    pallas_call.  Numerics mirror :func:`paged_decode_attention`'s fold
    (same operand dtypes, f32 accumulation, ``_NEG`` mask fill); rows
    whose table runs out read their last live page again, fully masked.
    """
    from jax.experimental import pallas as plx
    from jax.experimental.pallas import tpu as pltpu

    from . import pallas_compat

    b, hq, t, dh = q.shape
    if t != 1:
        raise ValueError("fused paged attention is decode-only (T must be 1)")
    hkv, ps = pool_k.shape[2], pool_k.shape[3]
    maxp = page_table.shape[1]
    quantized = scales is not None

    def walk_map(bi, pi, layer_r, ptab_r, pos_r):
        # dead pages revisit the row's last live page: consecutive equal
        # block indices skip the DMA, so traffic stays O(live pages)
        pp = jnp.minimum(pi, pos_r[bi] // ps)
        return (layer_r[0], ptab_r[bi, pp], 0, 0, 0)

    def row_map(bi, pi, *_):
        return (bi, 0, 0)

    kv_spec = plx.BlockSpec((1, 1, hkv, ps, dh), walk_map)
    in_specs = [plx.BlockSpec((1, hq, dh), row_map), kv_spec, kv_spec]
    operands = [q[:, :, 0, :], pool_k, pool_v]
    if quantized:
        sc_spec = plx.BlockSpec((1, 1, hkv, ps, 1), walk_map)
        in_specs += [sc_spec, sc_spec]
        operands += [scales[0], scales[1]]
    kernel = _make_fused_kernel(hq, hkv, dh, ps, maxp, quantized, q.dtype)
    out = plx.pallas_call(
        kernel,
        grid_spec=pallas_compat.prefetch_grid_spec(
            num_scalar_prefetch=3,
            grid=(b, maxp),
            in_specs=in_specs,
            out_specs=plx.BlockSpec((1, hq, dh), row_map),
            scratch_shapes=[pltpu.VMEM((hq, 128), jnp.float32),
                            pltpu.VMEM((hq, 128), jnp.float32),
                            pltpu.VMEM((hq, dh), jnp.float32)]),
        out_shape=jax.ShapeDtypeStruct((b, hq, dh), q.dtype),
        compiler_params=pallas_compat.compiler_params(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(jnp.atleast_1d(layer).astype(jnp.int32),
      page_table.astype(jnp.int32), pos_rows.astype(jnp.int32), *operands)
    return out[:, :, None, :]


@functools.cache
def _fused_ok(hkv: int, g: int, ps: int, dh: int, quantized: bool) -> bool:
    """Hardware probe: can Mosaic lower + run the fused paged kernel at
    this (head, page) geometry?  Guards the ``auto``/``on`` ladder so a
    lowering failure (tiny page lane widths, odd head dims) degrades to
    the gather+score path with a warn-once ledger entry instead of
    crashing decode.  The fixture is RANDOM (fixed seed) with ragged row
    positions, so a walk-order or mask bug fails the value check rather
    than shipping wrong numerics (same contract as q40._pallas_ok)."""
    try:
        b, maxp = 2, 3
        npages = 1 + b * maxp
        rng = np.random.RandomState(0)
        table = np.arange(1, npages).reshape(b, maxp).astype(np.int32)
        pos_rows = jnp.asarray([maxp * ps - 1, ps + ps // 2], jnp.int32)
        q = jnp.asarray(rng.randn(b, hkv * g, 1, dh) * 0.3, jnp.float32)
        if quantized:
            # quantize_kv reduces over the last axis, so it quantizes the
            # pool layout (1, P, Hkv, ps, Dh) directly → scale (…, ps, 1)
            pk, sk = quantize_kv(jnp.asarray(
                rng.randn(1, npages, hkv, ps, dh), jnp.float32))
            pv, sv = quantize_kv(jnp.asarray(
                rng.randn(1, npages, hkv, ps, dh), jnp.float32))
            ref_scales = (sk, sv)
        else:
            pk = jnp.asarray(rng.randn(1, npages, hkv, ps, dh) * 0.3,
                             jnp.bfloat16)
            pv = jnp.asarray(rng.randn(1, npages, hkv, ps, dh) * 0.3,
                             jnp.bfloat16)
            ref_scales = None
        layer = jnp.int32(0)
        tbl = jnp.asarray(table)
        out = fused_paged_attention(
            q, pk, pv, layer, tbl, pos_rows,
            scales=(sk, sv) if quantized else None)
        ksc, vsc = (ref_scales if quantized else (None, None))
        k_l = paged_gather_layer(pk, layer, tbl, scale_pool=ksc)
        v_l = paged_gather_layer(pv, layer, tbl, scale_pool=vsc)
        ref = _rows_ceiling_attention(q, k_l, v_l, pos_rows)
        tol = 1e-2 * max(float(np.abs(np.asarray(ref)).max()), 1e-3)
        if not np.allclose(np.asarray(out), np.asarray(ref), atol=tol):
            raise AssertionError("fused attention probe result mismatch")
        return True
    except Exception as e:  # Mosaic lowering/runtime failure
        from ..obs import dispatch as obs_dispatch
        obs_dispatch.record_degrade(
            "attn", "probe_failed", warn_key=(hkv, g, ps, dh, quantized),
            hkv=hkv, g=g, page_size=ps, dh=dh, quantized=quantized,
            error=f"{type(e).__name__}: {str(e)[:120]}")
        return False


def _fused_choice(t: int, hq: int, hkv: int, ps: int, dh: int,
                  quantized: bool) -> tuple[bool, bool]:
    """Resolve the fused-vs-fallback decision for one trace-time call
    site.  Returns ``(use_fused, interpret)``.  Mirrors the q40 ladder:
    ``auto`` off-TPU falls back silently (the clean-run ledger contract);
    ``on`` off-TPU and any probe failure degrade loudly (warn-once)."""
    mode = fused_mode()
    if mode == "off" or t != 1 or hq % hkv != 0:
        return False, False
    if mode == "interp":
        return True, True
    on_tpu = jax.default_backend() == "tpu"
    if mode == "on" and not on_tpu:
        from ..obs import dispatch as obs_dispatch
        obs_dispatch.record_degrade(
            "attn", "fused_needs_tpu", warn_key=jax.default_backend(),
            backend=jax.default_backend())
        return False, False
    if not on_tpu:  # auto on CPU: silent XLA fallback, same as q40
        return False, False
    return _fused_ok(hkv, hq // hkv, ps, dh, quantized), False


def paged_gqa_attention_at(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                           layer: jax.Array, page_table: jax.Array,
                           pos_rows: jax.Array,
                           scales: tuple[jax.Array, jax.Array] | None = None
                           ) -> jax.Array:
    """Causal GQA read through the page-table indirection at ``layer``,
    with the slot path's per-row causal ceiling.  Single-token decode
    prefers the fused page-walk megakernel (:func:`fused_paged_attention`
    — one dispatch, no materialized gather, in-register int8 dequant)
    when the ``DLLAMA_FUSED_ATTN`` ladder resolves to it; otherwise
    dispatch mirrors the contiguous path: long-cache single-token decode
    walks live pages (:func:`paged_decode_attention`, O(max pos)
    traffic); everything else gathers the logical view and reuses the
    one-shot slot math, so paged and contiguous reads are the same
    computation over the same logical keys.

    Every arm records its dispatch family at trace time (the PR 4
    ledger): ``paged-fused`` is one attention-family dispatch; the
    unfused one-shot arm is the materialized gather (``paged-gather``)
    plus the score/softmax pass (``attn-score``), plus a ``dequant``
    record for int8 pools whose scale multiply rides the gathered view.

    ``scales``: the int8-pool (k, v) scale planes (L, P, Hkv, ps, 1);
    every unfused arm dequantizes after the int8-sized page read."""
    from ..obs import dispatch as obs_dispatch
    t = q.shape[2]
    ps = pool_k.shape[3]
    s = page_table.shape[1] * ps
    codec = "kv_int8" if scales is not None else "kv_dense"
    use_fused, interp = _fused_choice(t, q.shape[1], pool_k.shape[2], ps,
                                      pool_k.shape[4], scales is not None)
    if use_fused:
        obs_dispatch.record_dispatch(codec, "paged-fused", t=t, s=s,
                                     page_size=ps, interpret=interp)
        return fused_paged_attention(q, pool_k, pool_v, layer, page_table,
                                     pos_rows, scales=scales,
                                     interpret=interp)
    if _use_blocked_decode(t, s):
        obs_dispatch.record_dispatch(codec, "paged-decode", t=t, s=s,
                                     page_size=ps)
        return paged_decode_attention(q, pool_k, pool_v, layer, page_table,
                                      pos_rows, scales=scales)
    obs_dispatch.record_dispatch(codec, "paged-gather", t=t, s=s,
                                 page_size=ps)
    obs_dispatch.record_dispatch(codec, "attn-score", t=t, s=s, page_size=ps)
    if scales is not None:
        obs_dispatch.record_dispatch("kv_int8", "dequant", t=t, s=s,
                                     page_size=ps)
    ks, vs = scales if scales is not None else (None, None)
    k_l = paged_gather_layer(pool_k, layer, page_table, scale_pool=ks)
    v_l = paged_gather_layer(pool_v, layer, page_table, scale_pool=vs)
    return _rows_ceiling_attention(q, k_l, v_l, pos_rows)


# Above this many score elements per kv-head group, prefill switches to the
# blocked online-softmax path: the one-shot path materializes the full
# (B, Hkv, G, T, S) f32 score tensor, which becomes the HBM wall at long
# context (VERDICT r01 weak #5).
_BLOCKED_THRESHOLD = 1 << 21
# numpy (not jnp): a module-level device constant would initialize the XLA
# backend at import time, breaking jax.distributed.initialize ordering
_NEG = np.float32(-1e30)  # finite -inf stand-in: keeps the running max


def _kv_chunk(s: int) -> int:
    for c in (1024, 512, 256, 128):
        if s % c == 0:
            return c
    return s


def _online_fold(qf, kb, vb, mask, m, l, acc, scale):
    """One flash-softmax block fold shared by the blocked prefill scan and
    the length-aware decode loop: fold block scores masked by ``mask``
    (``(T, S)`` broadcast over (B, Hkv, G), or ``(B, T, S)`` for per-row
    ragged-batch masks) into the running (max, denom, numerator).

    Dots keep the cache's dtype as operand type with f32 *accumulation*
    (bf16 in, f32 out on the MXU): widening a bf16 cache to f32 first makes
    XLA lower cast+dot+mask as one VPU loop fusion — measured ~8 GB/s
    effective on the decode score read, ~50× off the HBM rate the dot-form
    achieves."""
    scores = jnp.einsum("bhgtd,bhsd->bhgts", qf.astype(kb.dtype), kb,
                        preferred_element_type=jnp.float32) * scale
    if mask.ndim == 2:
        mask = mask[None]
    scores = jnp.where(mask[:, None, None], scores, _NEG)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = alpha * l + p.sum(axis=-1)
    acc_new = alpha[..., None] * acc + jnp.einsum(
        "bhgts,bhsd->bhgtd", p.astype(vb.dtype), vb,
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _fold_init(b, hkv, g, t, dh):
    return (jnp.full((b, hkv, g, t), _NEG),
            jnp.zeros((b, hkv, g, t), jnp.float32),
            jnp.zeros((b, hkv, g, t, dh), jnp.float32))


def blocked_gqa_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                          pos: jax.Array, q_len: int,
                          start: jax.Array | None = None) -> jax.Array:
    """Flash-style causal GQA: ``lax.scan`` over KV chunks with an online
    (running max/sum) softmax, so peak memory is O(T·chunk) instead of
    O(T·S).  Numerically equivalent to the one-shot path (same f32
    accumulation; association differs only within the rescale chain).

    ``start`` (B,) masks key positions below a per-row floor — the
    left-padding region of a ragged batch (see gqa_attention).
    """
    b, hq, t, dh = q.shape
    hkv = k_cache.shape[1]
    s = k_cache.shape[2]
    g = hq // hkv
    c = _kv_chunk(s)
    nc = s // c
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))

    qf = q.astype(jnp.float32).reshape(b, hkv, g, t, dh)
    # chunk-major scan inputs: (nc, B, Hkv, c, Dh)
    kc = k_cache.reshape(b, hkv, nc, c, dh).transpose(2, 0, 1, 3, 4)
    vc = v_cache.reshape(b, hkv, nc, c, dh).transpose(2, 0, 1, 3, 4)
    t_idx = pos + jnp.arange(t)[:, None]  # (T, 1)

    def body(carry, inp):
        kb, vb, base = inp
        s_idx = base + jnp.arange(c)[None, :]
        mask = s_idx <= t_idx  # (T, c)
        if start is not None:
            mask = mask[None] & (s_idx[None] >= start[:, None, None])  # (B, T, c)
        return _online_fold(qf, kb, vb, mask, *carry, scale), None

    bases = jnp.arange(nc) * c
    (m, l, acc), _ = jax.lax.scan(body, _fold_init(b, hkv, g, t, dh),
                                  (kc, vc, bases))
    out = acc / jnp.maximum(l, 1e-38)[..., None]
    return out.reshape(b, hq, t, dh).astype(q.dtype)


# Decode (t==1) over caches at least this long walks only the live
# prefix of the cache (length-aware while_loop) instead of reading the
# whole preallocated buffer; below it, one-shot attention is cheaper than
# the loop overhead.
_DECODE_BLOCKED_MIN_S = 4096


def _use_blocked_decode(t: int, s: int) -> bool:
    """Shared dispatch predicate for the length-aware decode path, so the
    stacked-cache, per-layer, and sequence-parallel entry points can never
    diverge on which attention algorithm serves the same shapes.
    ``_kv_chunk(s) == s`` would be one loop step over the whole cache: all
    the loop overhead, none of the O(pos) traffic win."""
    return t == 1 and s >= _DECODE_BLOCKED_MIN_S and _kv_chunk(s) < s


def blocked_live_fold(qf, slice_block, k_cache, v_cache, pos, base, c,
                      wrap=lambda x: x, row_start: jax.Array | None = None,
                      row_pos: jax.Array | None = None,
                      block: int | None = None):
    """The length-aware online-softmax core: walk only the KV blocks of a
    chunk of length ``c`` (global position offset ``base``) that cover
    live positions ≤ ``pos``, folding each into the running (max, denom,
    numerator).  Shared by :func:`decode_gqa_attention` (base 0, whole
    cache), the sequence-parallel per-shard partials (base = the shard's
    chunk start), and the paged decode walk (block = one KV page) so the
    block walk cannot drift between them.

    ``slice_block(cache, start, length)`` cuts one (B, Hkv, length, Dh)
    block; ``wrap`` marks fresh accumulators (shard_map bodies pass a
    device-varying cast).  ``row_pos`` (B,) replaces the scalar causal
    ceiling with a per-row one (T must be 1): ``pos`` then only bounds
    the walk — pass its row max — while each row masks at its own
    ceiling.  ``block`` overrides the auto-tuned chunk width when the
    storage layout fixes the granularity (paged pools walk page-sized
    blocks).  Returns raw ``(m, l, acc)`` — callers gated on a non-empty
    live region fold at least one block, so ``m`` is a real max.  The
    caller normalizes (``acc / l``) or combines partials."""
    b, hkv, g, t, dh = qf.shape
    if block is None:
        block = _kv_chunk(c)
    scale = 1.0 / jnp.sqrt(jnp.float32(dh))
    local_last = jnp.clip(pos - base, 0, c - 1)
    n_live = local_last // block + 1

    def cond(carry):
        return carry[0] < n_live

    def body(carry):
        i, m, l, acc = carry
        start = i * block
        kb = slice_block(k_cache, start, block)
        vb = slice_block(v_cache, start, block)
        s_idx = base + start + jnp.arange(block)
        if row_pos is not None:  # slot batch: per-row causal ceiling
            mask = s_idx[None, None, :] <= row_pos[:, None, None]  # (B, 1, blk)
        else:
            mask = (s_idx <= pos)[None, :]
        if row_start is not None:  # ragged batch: per-row key floor
            floor = s_idx[None, None] >= row_start[:, None, None]
            mask = (mask if mask.ndim == 3 else mask[None]) & floor
        m, l, acc = _online_fold(qf, kb, vb, mask, m, l, acc, scale)
        return i + 1, m, l, acc

    m0, l0, acc0 = _fold_init(b, hkv, g, t, dh)
    init = (jnp.int32(0), wrap(m0), wrap(l0), wrap(acc0))
    _, m, l, acc = jax.lax.while_loop(cond, body, init)
    return m, l, acc


def decode_gqa_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                         pos: jax.Array,
                         layer: jax.Array | None = None,
                         start: jax.Array | None = None,
                         scales: tuple[jax.Array, jax.Array] | None = None
                         ) -> jax.Array:
    """Single-token causal GQA that reads only blocks covering positions
    ``0..pos``.

    A static-shape einsum over the full cache costs O(S) HBM traffic per
    token no matter where in the sequence decoding stands — at 64k
    context that is ~32 GB/token for 7B shapes, dwarfing the weights.
    The reference's attention loop is O(pos) (llama2-tasks.cpp:68-92);
    this restores that bound under XLA's static shapes with a
    ``lax.while_loop`` whose trip count is ``pos//block + 1``: each step
    dynamic-slices one KV block and folds it into the online-softmax
    accumulator, so traffic is proportional to the live prefix.

    With ``layer`` the caches are the *stacked* (L, B, Hkv, S, Dh) buffers
    and each block is sliced at ``(layer, ..., start, ...)`` directly —
    slicing out the layer first would materialize the whole layer slab
    (O(S) again, e.g. 128 MB per layer-step at 16k) before the loop reads
    its first block.
    """
    b, hq, t, dh = q.shape
    seq_ax = 2 if layer is None else 3
    hkv = k_cache.shape[seq_ax - 1]
    s = k_cache.shape[seq_ax]
    g = hq // hkv
    qf = q.astype(jnp.float32).reshape(b, hkv, g, t, dh)

    def slice_block(cache, start, length):
        # last dim from the array itself: serves both (…, Dh) value blocks
        # and (…, 1) scale columns with one index recipe
        if layer is None:
            return jax.lax.dynamic_slice_in_dim(cache, start, length, axis=2)
        zero = jnp.zeros((), jnp.int32)
        blk = jax.lax.dynamic_slice(
            cache, (layer.astype(jnp.int32), zero, zero, start, zero),
            (1, b, hkv, length, cache.shape[-1]))
        return blk[0]

    if scales is None:
        kc_arg, vc_arg = k_cache, v_cache
        sl = slice_block
    else:
        # int8 cache: slice the value block AND its per-position scale
        # column, dequantize after the (int8-sized) HBM read
        ks, vs = scales

        def sl(pair, start, length):
            vals, sc = pair
            return dequant_kv(slice_block(vals, start, length),
                              slice_block(sc, start, length))

        kc_arg, vc_arg = (k_cache, ks), (v_cache, vs)

    _, l, acc = blocked_live_fold(qf, sl, kc_arg, vc_arg, pos,
                                  jnp.int32(0), s, row_start=start)
    out = acc / jnp.maximum(l, 1e-38)[..., None]
    return out.reshape(b, hq, t, dh).astype(q.dtype)


def gqa_attention_at(q: jax.Array, ck: jax.Array, cv: jax.Array,
                     layer: jax.Array, pos: jax.Array, q_len: int,
                     start: jax.Array | None = None,
                     scales: tuple[jax.Array, jax.Array] | None = None
                     ) -> jax.Array:
    """:func:`gqa_attention` over the *stacked* (L, B, Hkv, S, Dh) caches
    at ``layer``.

    The long-cache decode path slices its KV blocks straight out of the
    stacked buffer (O(pos) traffic end to end); the short-cache and
    prefill paths read the layer slice, which XLA fuses into the score
    dot rather than materializing (observed in the 7B decode xplane).

    ``scales``: the int8-cache dequant planes (Lk, Lv stacked,
    (L, B, Hkv, S, 1) f32).  The decode path dequantizes block-wise (the
    HBM read stays int8-sized — the point of the quantized cache); the
    short/prefill paths dequantize the layer slice, which XLA fuses into
    the dot like the plain cast.
    """
    t = q.shape[2]
    s = ck.shape[3]
    if _use_blocked_decode(t, s):
        return decode_gqa_attention(q, ck, cv, pos, layer=layer, start=start,
                                    scales=scales)
    k_l = jax.lax.dynamic_index_in_dim(ck, layer, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(cv, layer, 0, keepdims=False)
    if scales is not None:
        ks, vs = scales
        k_l = dequant_kv(k_l, jax.lax.dynamic_index_in_dim(ks, layer, 0,
                                                           keepdims=False))
        v_l = dequant_kv(v_l, jax.lax.dynamic_index_in_dim(vs, layer, 0,
                                                           keepdims=False))
    return gqa_attention(q, k_l, v_l, pos, q_len, start=start)


def gqa_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                  pos: jax.Array, q_len: int,
                  start: jax.Array | None = None) -> jax.Array:
    """Causal GQA over the cache.

    q:        (B, Hq, T, Dh) — already RoPE'd
    k_cache:  (B, Hkv, S, Dh) — positions ≥ pos+T are garbage and masked out
    v_cache:  (B, Hkv, S, Dh)
    pos:      scalar, index of q's first token
    returns:  (B, Hq, T, Dh)

    Scale is 1/sqrt(head_size) (llama2-tasks.cpp:67).  GQA head grouping
    ``kvMul = nHeads/nKvHeads`` (llama2-tasks.cpp:58) becomes a reshape to
    (B, Hkv, G, T, Dh) so each kv head serves G query heads in one einsum.

    Long prefills (score tensor past ``_BLOCKED_THRESHOLD`` elements per
    batch×kv-head) dispatch to :func:`blocked_gqa_attention`; decode over
    a long cache dispatches to the length-aware
    :func:`decode_gqa_attention`.

    ``start`` (B,) is the ragged-batch key floor: row ``b`` may only see
    key positions ``>= start[b]`` (its left-padding slots hold other
    prompts' alignment garbage).  The mask fill is the finite ``_NEG``,
    not -inf: a fully-masked query row (a pad position) then softmaxes to
    uniform garbage instead of NaN — its output is never read (the head
    picks the common last index; pad slots stay masked forever), and for
    live rows ``exp(_NEG - m)`` underflows to exactly 0.0, so the result
    is bit-identical to the -inf fill.
    """
    b, hq, t, dh = q.shape
    hkv = k_cache.shape[1]
    s = k_cache.shape[2]
    g = hq // hkv

    if t > 1 and g * t * s > _BLOCKED_THRESHOLD:
        return blocked_gqa_attention(q, k_cache, v_cache, pos, q_len, start=start)
    if _use_blocked_decode(t, s):
        return decode_gqa_attention(q, k_cache, v_cache, pos, start=start)

    # operands in cache dtype, f32 accumulation — see _online_fold for why
    qc = q.reshape(b, hkv, g, t, dh).astype(k_cache.dtype)
    scores = jnp.einsum("bhgtd,bhsd->bhgts", qc, k_cache,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(dh))

    # causal + validity mask: key position s_idx is visible to query t_idx
    # iff s_idx <= pos + t_idx (and, ragged, s_idx >= start[row])
    s_idx = jnp.arange(s)[None, :]
    t_idx = pos + jnp.arange(t)[:, None]
    mask = s_idx <= t_idx  # (T, S)
    if start is None:
        scores = jnp.where(mask[None, None, None], scores, _NEG)
    else:
        mask = mask[None] & (s_idx[None] >= start[:, None, None])  # (B, T, S)
        scores = jnp.where(mask[:, None, None], scores, _NEG)

    probs = softmax_f32(scores, axis=-1)
    out = jnp.einsum("bhgts,bhsd->bhgtd", probs.astype(v_cache.dtype), v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, hq, t, dh).astype(q.dtype)
