"""Sequence-parallel attention: seq-sharded KV cache + distributed softmax.

Long-context capability the reference does not have (SURVEY §5: its only
long-context lever is TP's 1/n KV shrink; seqLen is a hard per-node
ceiling, commands.hpp:12).  Here the KV cache's sequence axis is sharded
over the mesh's ``sp`` axis, so max context scales with sp × per-chip HBM.

Algorithm (flash-attention softmax decomposition across shards):
each sp shard holds KV positions ``[i·C, (i+1)·C)`` and computes, for the
(replicated) queries, its local masked scores, local running max ``m_i``,
partial denominator ``l_i = Σ exp(s−m_i)`` and partial numerator
``o_i = exp(s−m_i)·V_i``.  The global softmax is reassembled with one
``all_gather`` of the (tiny) ``m_i`` plus two ``psum``s:

    M = max_i m_i;   out = Σ_i e^{m_i−M}·o_i  /  Σ_i e^{m_i−M}·l_i

— a single ICI round regardless of sequence length, versus the
O(n_shards) steps of a rotation-based ring.  (A ppermute ring variant
makes sense for sharded-Q prefill; for decode and replicated-Q prefill
the one-round combine is strictly better.)

Prefill KV cache *updates* stay with GSPMD (``ops.attention.
update_kv_cache_at``'s plain dynamic_update_slice — the block write is
amortized over the whole prompt); the per-step decode write uses
:func:`sp_update_kv_cache_at`, whose shard_map makes the write shard-local
by construction instead of trusting GSPMD's lowering choice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import shard_map
from .attention import _use_blocked_decode, blocked_live_fold

NEG_BIG = -1e30  # stand-in for -inf that keeps exp() NaN-free on empty shards


def sp_update_kv_cache_at(k_cache: jax.Array, v_cache: jax.Array,
                          k_new: jax.Array, v_new: jax.Array,
                          layer: jax.Array, pos: jax.Array, mesh,
                          kv_spec: P = P(None, "dp", "tp", "sp", None),
                          new_spec: P = P("dp", "tp", None, None)
                          ) -> tuple[jax.Array, jax.Array]:
    """Decode-step KV write for *stacked* (L, B, Hkv, S, Dh) caches carried
    through the layer scan: writes one layer's decode-step row at
    ``(layer, pos)``, shard-local by construction (see
    ops.attention.update_kv_cache_at for why the caches are carried).

    A plain ``dynamic_update_slice`` on an sp-sharded cache leaves the
    lowering to GSPMD, which is *correct* but free to insert a
    gather/scatter per step.  Under ``shard_map`` the write is explicit:
    every shard runs the same update with the position clamped into its
    local range, and a mask keeps non-owning shards' rows unchanged — no
    communication by construction (the new row is replicated over ``sp``).

    Decode-only: exactly one token (T == 1) per call — a T-token window
    could straddle an ``sp`` shard boundary, which this single-row
    ownership logic does not implement (prefill block writes go through
    the GSPMD path in the transformer instead)."""
    if k_new.shape[2] != 1:
        raise ValueError(
            f"sp_update_kv_cache_at writes one decode step, got T={k_new.shape[2]}")
    sp = mesh.shape.get("sp", 1)
    chunk = k_cache.shape[3] // sp

    def shard_fn(kc, vc, kn, vn):
        i = jax.lax.axis_index("sp")
        local = pos - i * chunk
        owned = (local >= 0) & (local < chunk)
        idx = jnp.clip(local, 0, chunk - 1)
        zero = jnp.zeros((), layer.dtype)
        start = (layer, zero, zero, idx.astype(layer.dtype), zero)

        def write(cache, new):
            row = jax.lax.dynamic_slice(cache, start, (1,) + new.shape[:2] + (1, new.shape[-1]))
            new = jnp.where(owned, new[None, :, :, :1].astype(cache.dtype), row)
            return jax.lax.dynamic_update_slice(cache, new, start)

        return write(kc, kn), write(vc, vn)

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(kv_spec, kv_spec, new_spec, new_spec),
        out_specs=(kv_spec, kv_spec))(k_cache, v_cache, k_new, v_new)


def _varying(x):
    """Mark a freshly-created accumulator as device-varying over the mesh
    (shard_map branch/carry types must match the computed side).  Older
    jax has no varying-manual-axes typing (and no ``jax.lax.pcast``), so
    there the value is already fine as-is."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, ("dp", "sp", "tp"), to="varying")
    return x


def _empty_partials(shape, dh):
    """The (o_i, l_i, m_i) triple a fully-masked chunk produces — shared by
    the ring accumulator init and the one-round path's skip branch."""
    return (_varying(jnp.zeros(shape + (dh,), jnp.float32)),
            _varying(jnp.zeros(shape, jnp.float32)),
            _varying(jnp.full(shape, NEG_BIG, jnp.float32)))


def _local_partials(q, k, v, pos, q_len, chunk_start):
    """Per-shard partial attention.

    q: (B, Hkv, G, T, Dh) f32 — grouped queries
    k/v: (B, Hkv, C, Dh) — this shard's chunk
    Returns (o_i (B,Hkv,G,T,Dh), l_i (B,Hkv,G,T), m_i (B,Hkv,G,T)).
    """
    c = k.shape[2]
    # cache-dtype operands + f32 accumulation (see attention._online_fold)
    scores = jnp.einsum("bhgtd,bhsd->bhgts", q.astype(k.dtype), k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.float32(q.shape[-1]))

    s_idx = chunk_start + jnp.arange(c)[None, :]          # global key positions
    t_idx = pos + jnp.arange(q_len)[:, None]
    mask = s_idx <= t_idx                                  # (T, C) causal+validity
    scores = jnp.where(mask[None, None, None], scores, -jnp.inf)

    m_i = jnp.maximum(jnp.max(scores, axis=-1), NEG_BIG)   # (B,Hkv,G,T)
    p = jnp.exp(scores - m_i[..., None])                   # masked → exp(-inf)=0
    l_i = jnp.sum(p, axis=-1)
    o_i = jnp.einsum("bhgts,bhsd->bhgtd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return o_i, l_i, m_i


def _local_partials_blocked(q, k, v, pos, chunk_start):
    """Decode-step (T==1) per-shard partials that read only the KV blocks
    covering this shard's *live* positions — the within-shard analogue of
    ops.attention.decode_gqa_attention (same shared block-walk core), so
    sp long-context decode is O(live prefix) per shard instead of
    O(chunk): at 128k context over sp=8, a shard whose live region is 4k
    reads 4k positions, not its whole 16k chunk.  Produces the same
    (o_i, l_i, m_i) convention as :func:`_local_partials` (the caller
    gates on a non-empty live region, so at least one block folds and
    ``m_i`` is a real max)."""
    def slice_block(cache, start, length):
        return jax.lax.dynamic_slice_in_dim(cache, start, length, axis=2)

    # accumulators marked device-varying so the while_loop carry type
    # matches the body's shard-varying values (same trick as _empty_partials)
    m, l, acc = blocked_live_fold(q, slice_block, k, v, pos, chunk_start,
                                  k.shape[2], wrap=_varying)
    return acc, l, m


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh,
                   pos0: jax.Array | int = 0,
                   q_spec: P = P("dp", "tp", "sp", None),
                   kv_spec: P = P("dp", "tp", "sp", None)) -> jax.Array:
    """Causal GQA ring attention for a sequence-sharded *from-scratch*
    prefill.

    Blockwise ring attention (Liu & Abbeel's ring attention shape, built
    from the same flash softmax decomposition as the decode combine
    above): queries AND keys/values are sharded on the sequence axis over
    ``sp``; each of the sp steps computes local partials against the
    currently-held KV block, folds them into a running (max, denominator,
    numerator) accumulator, and rotates the KV block to the next shard
    with ``ppermute`` — XLA overlaps the rotation with the next block's
    compute on ICI; the last block is consumed without a rotation
    (sp−1 rotations total).  Blocks that are entirely in a query shard's
    future are skipped under ``lax.cond`` — they are fully causally
    masked, and skipping recovers the ~half of block-pair FLOPs a plain
    ring wastes.  Peak per-chip memory is O(T/sp), which is what lets a
    prompt longer than one chip's HBM prefill at all; the reference has
    no analogue (its seqLen is a hard per-node ceiling, commands.hpp:12).

    q: (B, Hq, T, Dh), k/v: (B, Hkv, T, Dh), all with T sharded on
    ``sp``.  ``pos0`` offsets the global RoPE-free position bookkeeping
    only; attention covers *only these q/k/v* — any cached KV prefix is
    NOT read, so callers continuing a sequence (pos0 > 0 with earlier
    cache content) must use :func:`sp_gqa_attention` instead (the engine
    gates the ring on ``pos == 0``).  Returns (B, Hq, T, Dh) sharded
    like q.
    """
    b, hq, t, dh = q.shape
    sp = mesh.shape.get("sp", 1)
    t_local = t // sp
    perm = [(i, (i + 1) % sp) for i in range(sp)]  # ring: shard i → i+1

    def shard_fn(q, k, v):
        hq_l, hkv_l = q.shape[1], k.shape[1]
        g = hq_l // hkv_l
        qf = q.astype(jnp.float32).reshape(q.shape[0], hkv_l, g, t_local, dh)
        my = jax.lax.axis_index("sp")
        q_start = pos0 + my * t_local

        def accumulate(i, out, lsum, m, kb, vb):
            # block held after i rotations originated at shard (my-i) mod sp
            owner = (my - i) % sp

            def fold(args):
                out, lsum, m = args
                o_i, l_i, m_i = _local_partials(
                    qf, kb, vb, q_start, t_local, pos0 + owner * t_local)
                m_new = jnp.maximum(m, m_i)
                s_old = jnp.exp(m - m_new)
                s_new = jnp.exp(m_i - m_new)
                return (out * s_old[..., None] + o_i * s_new[..., None],
                        lsum * s_old + l_i * s_new, m_new)

            # owner > my ⇔ every key in the block is a future position for
            # every query here ⇔ fully masked: skip the whole block
            return jax.lax.cond(owner <= my, fold, lambda a: a, (out, lsum, m))

        def step(i, carry):
            out, lsum, m, kb, vb = carry
            out, lsum, m = accumulate(i, out, lsum, m, kb, vb)
            kb = jax.lax.ppermute(kb, "sp", perm)
            vb = jax.lax.ppermute(vb, "sp", perm)
            return out, lsum, m, kb, vb

        # accumulators start as a fully-masked chunk's partials, marked
        # device-varying so the fori_loop carry type matches the body's
        o0, l0, m0 = _empty_partials((q.shape[0], hkv_l, g, t_local), dh)
        init = (o0, l0, m0, k, v)
        out, lsum, m, kb, vb = jax.lax.fori_loop(0, sp - 1, step, init)
        # final block: consume without the (discarded) sp-th rotation
        out, lsum, m = accumulate(sp - 1, out, lsum, m, kb, vb)
        out = out / jnp.maximum(lsum[..., None], 1e-38)
        return out.reshape(q.shape[0], hq_l, t_local, dh).astype(q.dtype)

    return shard_map(
        shard_fn, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec)(q, k, v)


def sp_gqa_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     pos: jax.Array, q_len: int, mesh,
                     q_spec: P = P("dp", "tp", None, None),
                     kv_spec: P = P("dp", "tp", "sp", None),
                     layer: jax.Array | None = None) -> jax.Array:
    """Causal GQA over a seq-sharded cache (drop-in for
    ops.attention.gqa_attention when the mesh has an ``sp`` axis).

    q: (B, Hq, T, Dh); k_cache/v_cache: (B, Hkv, S, Dh) with S sharded on
    ``sp``; returns (B, Hq, T, Dh) sharded like q.

    With ``layer`` the caches are the stacked (L, B, Hkv, S, Dh) buffers —
    ``kv_spec`` stays the per-layer 4-axis spec and the unsharded layer
    axis is prepended here — and the layer is sliced *inside* the shard
    body: slicing before the shard_map would materialize the full layer
    slab per layer-step, since shard_map is a fusion barrier (the same
    O(S) copy gqa_attention_at avoids on the single-chip path).
    """
    b, hq, t, dh = q.shape
    seq_ax = 2 if layer is None else 3
    hkv = k_cache.shape[seq_ax - 1]
    g = hq // hkv
    sp = mesh.shape.get("sp", 1)
    chunk = k_cache.shape[seq_ax] // sp
    if layer is not None:
        kv_spec = P(None, *kv_spec)

    def shard_fn(q, k, v):
        if layer is not None:
            k = jax.lax.dynamic_index_in_dim(k, layer, 0, keepdims=False)
            v = jax.lax.dynamic_index_in_dim(v, layer, 0, keepdims=False)
        # local shapes: q (b/dp, hq/tp, T, Dh), k/v (b/dp, hkv/tp, C, Dh)
        hq_l = q.shape[1]
        hkv_l = k.shape[1]
        qf = q.astype(jnp.float32).reshape(q.shape[0], hkv_l, hq_l // hkv_l, t, dh)
        chunk_start = jax.lax.axis_index("sp") * chunk

        def compute(_):
            # decode over a long local chunk: walk only the blocks covering
            # this shard's live positions (O(live) per shard, not O(chunk))
            if _use_blocked_decode(q_len, chunk):
                return _local_partials_blocked(qf, k, v, pos, chunk_start)
            return _local_partials(qf, k, v, pos, q_len, chunk_start)

        def empty(_):
            return _empty_partials(qf.shape[:3] + (t,), dh)

        # a shard whose whole chunk is in the queries' future is fully
        # masked: skip its scores/einsums and its KV chunk read.  Step
        # latency is unchanged (every shard still meets the collective
        # below, paced by the shards that do compute) — the saving is the
        # idle shards' HBM reads and FLOPs, not wall clock.
        o_i, l_i, m_i = jax.lax.cond(
            chunk_start <= pos + q_len - 1, compute, empty, None)

        m = jnp.max(jax.lax.all_gather(m_i, "sp"), axis=0)   # global max
        scale = jnp.exp(m_i - m)
        out = jax.lax.psum(o_i * scale[..., None], "sp")
        denom = jax.lax.psum(l_i * scale, "sp")
        out = out / jnp.maximum(denom[..., None], 1e-38)
        return out.reshape(q.shape[0], hq_l, t, dh).astype(q.dtype)

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
    )(q, k_cache, v_cache)
