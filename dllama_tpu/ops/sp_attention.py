"""Sequence-parallel attention: seq-sharded KV cache + distributed softmax.

Long-context capability the reference does not have (SURVEY §5: its only
long-context lever is TP's 1/n KV shrink; seqLen is a hard per-node
ceiling, commands.hpp:12).  Here the KV cache's sequence axis is sharded
over the mesh's ``sp`` axis, so max context scales with sp × per-chip HBM.

Algorithm (flash-attention softmax decomposition across shards):
each sp shard holds KV positions ``[i·C, (i+1)·C)`` and computes, for the
(replicated) queries, its local masked scores, local running max ``m_i``,
partial denominator ``l_i = Σ exp(s−m_i)`` and partial numerator
``o_i = exp(s−m_i)·V_i``.  The global softmax is reassembled with one
``all_gather`` of the (tiny) ``m_i`` plus two ``psum``s:

    M = max_i m_i;   out = Σ_i e^{m_i−M}·o_i  /  Σ_i e^{m_i−M}·l_i

— a single ICI round regardless of sequence length, versus the
O(n_shards) steps of a rotation-based ring.  (A ppermute ring variant
makes sense for sharded-Q prefill; for decode and replicated-Q prefill
the one-round combine is strictly better.)

The KV cache *update* stays outside this module: ``update_kv_cache`` is a
plain dynamic_update_slice that GSPMD lowers to a masked write on the
owning shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_BIG = -1e30  # stand-in for -inf that keeps exp() NaN-free on empty shards


def _local_partials(q, k, v, pos, q_len, chunk_start):
    """Per-shard partial attention.

    q: (B, Hkv, G, T, Dh) f32 — grouped queries
    k/v: (B, Hkv, C, Dh) — this shard's chunk
    Returns (o_i (B,Hkv,G,T,Dh), l_i (B,Hkv,G,T), m_i (B,Hkv,G,T)).
    """
    c = k.shape[2]
    scores = jnp.einsum("bhgtd,bhsd->bhgts", q, k.astype(jnp.float32))
    scores = scores / jnp.sqrt(jnp.float32(q.shape[-1]))

    s_idx = chunk_start + jnp.arange(c)[None, :]          # global key positions
    t_idx = pos + jnp.arange(q_len)[:, None]
    mask = s_idx <= t_idx                                  # (T, C) causal+validity
    scores = jnp.where(mask[None, None, None], scores, -jnp.inf)

    m_i = jnp.maximum(jnp.max(scores, axis=-1), NEG_BIG)   # (B,Hkv,G,T)
    p = jnp.exp(scores - m_i[..., None])                   # masked → exp(-inf)=0
    l_i = jnp.sum(p, axis=-1)
    o_i = jnp.einsum("bhgts,bhsd->bhgtd", p, v.astype(jnp.float32))
    return o_i, l_i, m_i


def sp_gqa_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     pos: jax.Array, q_len: int, mesh,
                     q_spec: P = P("dp", "tp", None, None),
                     kv_spec: P = P("dp", "tp", "sp", None)) -> jax.Array:
    """Causal GQA over a seq-sharded cache (drop-in for
    ops.attention.gqa_attention when the mesh has an ``sp`` axis).

    q: (B, Hq, T, Dh); k_cache/v_cache: (B, Hkv, S, Dh) with S sharded on
    ``sp``; returns (B, Hq, T, Dh) sharded like q.
    """
    b, hq, t, dh = q.shape
    hkv = k_cache.shape[1]
    g = hq // hkv
    sp = mesh.shape.get("sp", 1)
    chunk = k_cache.shape[2] // sp

    def shard_fn(q, k, v):
        # local shapes: q (b/dp, hq/tp, T, Dh), k/v (b/dp, hkv/tp, C, Dh)
        hq_l = q.shape[1]
        hkv_l = k.shape[1]
        qf = q.astype(jnp.float32).reshape(q.shape[0], hkv_l, hq_l // hkv_l, t, dh)
        chunk_start = jax.lax.axis_index("sp") * chunk
        o_i, l_i, m_i = _local_partials(qf, k, v, pos, q_len, chunk_start)

        m = jnp.max(jax.lax.all_gather(m_i, "sp"), axis=0)   # global max
        scale = jnp.exp(m_i - m)
        out = jax.lax.psum(o_i * scale[..., None], "sp")
        denom = jax.lax.psum(l_i * scale, "sp")
        out = out / jnp.maximum(denom[..., None], 1e-38)
        return out.reshape(q.shape[0], hq_l, t, dh).astype(q.dtype)

    return jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec),
        out_specs=q_spec,
    )(q, k_cache, v_cache)
