// Native Q40 loader transform: `.m` file blocks → runtime packed layout.
//
// The runtime stores a (d_out, n_in) Q40 weight input-dim-first as
//   qpacked u8  (padded_n/2, d)   row 16b+r = file nibble byte r of block b
//   scales  f16 (padded_n/32, d)
// (see dllama_tpu/ops/q40.py).  A file block for output row dd covering
// input positions [32b, 32b+32) is 18 bytes: f16 scale + 16 nibble bytes
// whose lo/hi split matches the runtime's (BlockQ40, quants.hpp:17-20 in
// the reference), so the whole transform is a blocked byte transpose —
// no nibble arithmetic.  The Python fallback (quants.q40_planes +
// pack_planes_np) materializes a dense int8 (d, n) plane and a full
// transpose per tensor; this single pass replaces it on the load path,
// the native runtime component the reference implements as
// Transformer::loadRoot/splitWeights (transformer.cpp:389-487).
//
// Build: make -C dllama_tpu/csrc    (produces libq40pack.so; the loader
// falls back to numpy when the library is absent).

#include <cstdint>
#include <cstring>

namespace {
constexpr int64_t kBlockBytes = 18;  // 2 f16 scale + 16 nibble bytes
constexpr int64_t kTileD = 64;       // dd tile: src tile = 64*8*18 B ≈ 9 KB
constexpr int64_t kTileB = 8;        // block tile (128 output rows)
}  // namespace

extern "C" {

// raw:     d*nb file blocks, row-major by output row dd
// qp:      (padded_nb*16, ld) uint8, written at columns [col, col+d)
// sc:      (padded_nb, ld) uint16 (f16 bits), same column window
// Rows beyond nb*16 (pack padding) are the caller's to zero-fill.
void q40_repack(const uint8_t* raw, int64_t d, int64_t nb,
                uint8_t* qp, uint16_t* sc, int64_t ld, int64_t col) {
#pragma omp parallel for schedule(static)
  for (int64_t b0 = 0; b0 < nb; b0 += kTileB) {
    const int64_t b1 = (b0 + kTileB < nb) ? b0 + kTileB : nb;
    for (int64_t d0 = 0; d0 < d; d0 += kTileD) {
      const int64_t d1 = (d0 + kTileD < d) ? d0 + kTileD : d;
      for (int64_t b = b0; b < b1; ++b) {
        uint8_t* qrow0 = qp + (b * 16) * ld + col;
        uint16_t* srow = sc + b * ld + col;
        for (int64_t dd = d0; dd < d1; ++dd) {
          const uint8_t* blk = raw + (dd * nb + b) * kBlockBytes;
          uint16_t s;
          std::memcpy(&s, blk, 2);
          srow[dd] = s;
          const uint8_t* nib = blk + 2;
          for (int64_t r = 0; r < 16; ++r) {
            qrow0[r * ld + dd] = nib[r];
          }
        }
      }
    }
  }
}

// Q80 twin (ops/q8.py layout): a file block for output row dd covering
// input positions [32b, 32b+32) is 34 bytes — f16 scale + 32 int8 values,
// stored to
//   qv int8  (padded_n, d)    row 32b+r = file value byte r of block b
//   sc f16   (padded_n/32, d)
// Same blocked byte transpose, twice the value rows per block.
void q80_repack(const uint8_t* raw, int64_t d, int64_t nb,
                int8_t* qv, uint16_t* sc, int64_t ld, int64_t col) {
  constexpr int64_t kBlockBytes80 = 34;
#pragma omp parallel for schedule(static)
  for (int64_t b0 = 0; b0 < nb; b0 += kTileB) {
    const int64_t b1 = (b0 + kTileB < nb) ? b0 + kTileB : nb;
    for (int64_t d0 = 0; d0 < d; d0 += kTileD) {
      const int64_t d1 = (d0 + kTileD < d) ? d0 + kTileD : d;
      for (int64_t b = b0; b < b1; ++b) {
        int8_t* qrow0 = qv + (b * 32) * ld + col;
        uint16_t* srow = sc + b * ld + col;
        for (int64_t dd = d0; dd < d1; ++dd) {
          const uint8_t* blk = raw + (dd * nb + b) * kBlockBytes80;
          uint16_t s;
          std::memcpy(&s, blk, 2);
          srow[dd] = s;
          const int8_t* vals = reinterpret_cast<const int8_t*>(blk + 2);
          for (int64_t r = 0; r < 32; ++r) {
            qrow0[r * ld + dd] = vals[r];
          }
        }
      }
    }
  }
}

}  // extern "C"
