// Native BPE greedy-merge engine (the tokenizer encode hot loop).
//
// Same algorithm as the Python fallback in tokenizer/bpe.py::_merge —
// lazy max-heap of candidate adjacent pairs over a doubly-linked token
// list, best score first, earliest position on ties — which reproduces
// the reference's rescan-per-merge output (tokenizer.cpp:258-287) in
// O(n log n) instead of O(n²).  A tokenizer handle owns the piece → id
// hash map (first occurrence wins, matching the reference's bsearch over
// a vocab sorted with duplicates, tokenizer.cpp:163-168).
//
// Build: make -C dllama_tpu/csrc   (libbpe.so; Python falls back when absent)

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Tok {
  std::vector<std::string> vocab;
  std::vector<float> scores;
  std::unordered_map<std::string, int32_t> index;
};

struct Cand {
  float score;
  int64_t a, b;       // linked-list slots (original positions)
  int32_t ia, ib;     // expected token ids at a/b (staleness check)
  int32_t mid;        // merged id
};

struct CandLess {  // max-heap: higher score first, then lower position
  bool operator()(const Cand& x, const Cand& y) const {
    if (x.score != y.score) return x.score < y.score;
    return x.a > y.a;
  }
};

}  // namespace

extern "C" {

void* bpe_create(const uint8_t* blob, const int64_t* offsets,
                 const float* scores, int64_t n_vocab) {
  auto* t = new Tok();
  t->vocab.reserve(n_vocab);
  t->scores.assign(scores, scores + n_vocab);
  for (int64_t i = 0; i < n_vocab; ++i) {
    t->vocab.emplace_back(reinterpret_cast<const char*>(blob + offsets[i]),
                          static_cast<size_t>(offsets[i + 1] - offsets[i]));
  }
  t->index.reserve(static_cast<size_t>(n_vocab) * 2);
  for (int64_t i = 0; i < n_vocab; ++i) {
    t->index.emplace(t->vocab[i], static_cast<int32_t>(i));  // first wins
  }
  return t;
}

void bpe_destroy(void* handle) { delete static_cast<Tok*>(handle); }

// In-place greedy merge of tokens[0..n); returns the merged length.
int64_t bpe_merge(void* handle, int32_t* tokens, int64_t n) {
  const Tok& t = *static_cast<Tok*>(handle);
  if (n < 2) return n;
  std::vector<int32_t> ids(tokens, tokens + n);
  std::vector<int64_t> nxt(n), prv(n);
  for (int64_t i = 0; i < n; ++i) {
    nxt[i] = (i + 1 < n) ? i + 1 : -1;
    prv[i] = i - 1;
  }
  std::vector<uint8_t> alive(n, 1);
  std::priority_queue<Cand, std::vector<Cand>, CandLess> heap;
  std::string key;

  auto push = [&](int64_t a, int64_t b) {
    if (a < 0 || b < 0) return;
    key.assign(t.vocab[ids[a]]);
    key += t.vocab[ids[b]];
    auto it = t.index.find(key);
    // strict > -1e10 keeps reference parity for sentinel/-inf/NaN scores
    // (its best_score starts at -1e10, tokenizer.cpp:262)
    if (it != t.index.end() && t.scores[it->second] > -1e10f) {
      heap.push(Cand{t.scores[it->second], a, b, ids[a], ids[b], it->second});
    }
  };

  for (int64_t k = 0; k + 1 < n; ++k) push(k, k + 1);
  while (!heap.empty()) {
    Cand c = heap.top();
    heap.pop();
    if (!alive[c.a] || !alive[c.b] || nxt[c.a] != c.b ||
        ids[c.a] != c.ia || ids[c.b] != c.ib) {
      continue;  // stale
    }
    ids[c.a] = c.mid;
    alive[c.b] = 0;
    nxt[c.a] = nxt[c.b];
    if (nxt[c.b] != -1) prv[nxt[c.b]] = c.a;
    push(prv[c.a], c.a);
    push(c.a, nxt[c.a]);
  }
  int64_t m = 0;
  for (int64_t k = 0; k != -1; k = nxt[k]) tokens[m++] = ids[k];
  return m;
}

}  // extern "C"
