"""OpenAI-compatible HTTP API server (`dllama-api` equivalent).

Re-implements `/root/reference/src/apps/dllama-api/dllama-api.cpp`:

* ``POST /v1/chat/completions`` — chat completion with optional SSE
  streaming (writeChatCompletionChunk, :168-185), per-request temperature /
  top_p / max_tokens / seed / stop (:351-380), usage counts (:336-345).
* ``GET /v1/models`` — stub model list (:387-393).
* **NaiveCache** (:187-232): if a new request's messages extend the cached
  conversation prefix exactly, generation resumes from the cached KV
  position instead of re-prefilling the whole history.

Single-threaded request handling like the reference's accept loop
(:418-429) — the engine owns one KV cache, so requests serialize.
Uses only the standard library (the reference vendors nlohmann/json;
Python's ``json`` plays that role).
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, HTTPServer

from ..runtime.engine import ContextOverflow, Engine
from ..runtime.stream import drain_generation
from ..tokenizer.bpe import Tokenizer
from ..tokenizer.chat import ChatItem, ChatTemplate, TokenizerChatStops
from ..tokenizer.eos import EosDetector


@dataclass
class ChatMessage:
    role: str
    content: str


@dataclass
class CacheItem:
    end_pos: int
    message: ChatMessage


class NaiveCache:
    """Longest-prefix conversation cache (dllama-api.cpp:187-232)."""

    def __init__(self):
        self.items: list[CacheItem] = []

    def clear(self):
        self.items.clear()

    def push(self, end_pos: int, message: ChatMessage):
        self.items.append(CacheItem(end_pos, message))

    def resolve_delta_prompt(self, messages: list[ChatMessage]) -> tuple[int, list[ChatMessage]]:
        """Returns (start_pos, delta_messages). On any mismatch the cache is
        cleared and the full message list is returned with start_pos 0."""
        n = len(self.items)
        if n and len(messages) > n:
            for i in range(n):
                if (self.items[i].message.role != messages[i].role or
                        self.items[i].message.content != messages[i].content):
                    break
            else:
                start = self.items[n - 1].end_pos
                return start, messages[n:]
        self.clear()
        return 0, messages


@dataclass
class InferenceParams:
    messages: list[ChatMessage] = field(default_factory=list)
    temperature: float = 0.7
    top_p: float = 0.9
    max_tokens: int = 0
    stream: bool = False
    seed: int | None = None
    stop: list[str] = field(default_factory=list)


def parse_request(body: dict, default_temp: float, default_topp: float) -> InferenceParams:
    """Request-param extraction (dllama-api.cpp:351-380).  JSON ``null``
    for an optional field means "unset" to most OpenAI clients."""
    p = InferenceParams(temperature=default_temp, top_p=default_topp)
    for m in body.get("messages", []):
        p.messages.append(ChatMessage(str(m.get("role", "")), str(m.get("content", ""))))
    if body.get("temperature") is not None:
        p.temperature = float(body["temperature"])
    if body.get("top_p") is not None:
        p.top_p = float(body["top_p"])
    if body.get("max_tokens") is not None:
        p.max_tokens = int(body["max_tokens"])
    if body.get("stream") is not None:
        p.stream = bool(body["stream"])
    if body.get("seed") is not None:
        p.seed = int(body["seed"])
    stop = body.get("stop")
    if isinstance(stop, str):
        p.stop = [stop]
    elif isinstance(stop, list):
        p.stop = [str(s) for s in stop]
    return p


class ApiState:
    """Engine + tokenizer + conversation cache shared across requests."""

    def __init__(self, engine: Engine, tokenizer: Tokenizer,
                 default_temperature: float = 0.7, default_topp: float = 0.9,
                 chunk: int = 16, model_name: str = "dllama-tpu"):
        self.engine = engine
        self.tokenizer = tokenizer
        self.default_temperature = default_temperature
        self.default_topp = default_topp
        self.chunk = chunk
        self.model_name = model_name
        self.naive_cache = NaiveCache()
        stops = TokenizerChatStops(tokenizer)
        self.base_stops = stops.stops
        eos = tokenizer.vocab[tokenizer.chat_eos_id].decode("utf-8", "replace")
        self.template = ChatTemplate(tokenizer.chat_template, eos)

    # ------------------------------------------------------------------
    def complete(self, params: InferenceParams, emit):
        """Run one chat completion; calls ``emit(delta_text)`` as text becomes
        safe to stream.  Returns (content, n_prompt_tokens, n_completion_tokens)."""
        engine, tok = self.engine, self.tokenizer

        start_pos, delta_messages = self.naive_cache.resolve_delta_prompt(params.messages)
        if start_pos == 0:
            engine.reset()
        engine.pos = start_pos

        items = [ChatItem(m.role, m.content) for m in delta_messages]
        text = self.template.generate(items, True)
        prompt_tokens = tok.encode(text, add_bos=start_pos == 0)
        prompt_end = start_pos + len(prompt_tokens)
        if prompt_end + 1 >= engine.seq_len:
            # refuse before touching the cache — a poisoned entry would make
            # every follow-up request resolve to a bogus start_pos
            raise ContextOverflow(
                f"prompt needs {prompt_end} of {engine.seq_len} context positions")

        for m in delta_messages:
            self.naive_cache.push(prompt_end, m)

        max_pos = engine.seq_len
        if params.max_tokens > 0:
            max_pos = min(prompt_end + params.max_tokens, engine.seq_len)
        budget = max_pos - start_pos

        detector = EosDetector(tok.chat_eos_id, self.base_stops + params.stop,
                               padding_left=2, padding_right=2)
        seed = params.seed if params.seed is not None else int(time.time())

        stream = engine.generate_stream(
            prompt_tokens, budget, temperature=params.temperature,
            topp=params.top_p, seed=seed, chunk=self.chunk,
            eos_ids=(tok.chat_eos_id,))
        reply, n_completion, _ = drain_generation(
            engine, tok, detector, stream, len(prompt_tokens), prompt_end, emit)
        if engine.pos >= engine.seq_len:
            self.naive_cache.clear()  # context exhausted (dllama-api.cpp:330-331)
        else:
            self.naive_cache.push(engine.pos, ChatMessage("assistant", reply))
        return reply, len(prompt_tokens), n_completion


def make_handler(state: ApiState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):
            print(f"🔷 {self.command} {self.path}")

        def _json(self, code: int, obj: dict):
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):
            if self.path == "/v1/models":
                self._json(200, {"object": "list", "data": [{
                    "id": state.model_name, "object": "model",
                    "created": int(time.time()), "owned_by": "user"}]})
            elif self.path in ("/health", "/healthz"):
                self._json(200, {"status": "ok"})
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/v1/chat/completions":
                self._json(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                params = parse_request(body, state.default_temperature, state.default_topp)
                if not params.messages:
                    self._json(400, {"error": "messages required"})
                    return
            except (TypeError, ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return

            created = int(time.time())
            cid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
            if params.stream:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()

                def emit(delta):
                    chunk = {"id": cid, "object": "chat.completion.chunk",
                             "created": created, "model": state.model_name,
                             "choices": [{"index": 0, "delta": {"content": delta},
                                          "finish_reason": None}]}
                    self.wfile.write(f"data: {json.dumps(chunk)}\n\n".encode())
                    self.wfile.flush()

                try:
                    state.complete(params, emit)
                except ContextOverflow as e:
                    # headers already sent: emit an OpenAI-shaped error
                    # object and terminate WITHOUT a normal finish chunk, so
                    # clients don't mistake the failure for an empty success.
                    # Only the context-window refusal maps to a client error;
                    # anything else is a server bug and propagates as a 500
                    # (ADVICE r01: a bare ValueError catch masked bugs).
                    err = {"error": {"message": str(e),
                                     "type": "invalid_request_error"}}
                    self.wfile.write(f"data: {json.dumps(err)}\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                    return
                final = {"id": cid, "object": "chat.completion.chunk",
                         "created": created, "model": state.model_name,
                         "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}]}
                self.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            else:
                try:
                    reply, n_prompt, n_completion = state.complete(params, lambda d: None)
                except ContextOverflow as e:
                    self._json(400, {"error": str(e)})
                    return
                self._json(200, {
                    "id": cid, "object": "chat.completion", "created": created,
                    "model": state.model_name,
                    "choices": [{"index": 0, "finish_reason": "stop",
                                 "message": {"role": "assistant", "content": reply}}],
                    "usage": {"prompt_tokens": n_prompt,
                              "completion_tokens": n_completion,
                              "total_tokens": n_prompt + n_completion}})

    return Handler


def serve(state: ApiState, host: str = "0.0.0.0", port: int = 9990):
    server = HTTPServer((host, port), make_handler(state))
    print(f"🔷 dllama-api listening on {host}:{port}")
    server.serve_forever()


def main(argv=None):
    import sys

    from ..cli import build_parser, load_stack
    argv = list(sys.argv[1:] if argv is None else argv)
    # reuse the dllama flag surface; the server has no positional mode
    args = build_parser().parse_args(["inference", *argv])
    engine, tok = load_stack(args)
    state = ApiState(engine, tok, default_temperature=args.temperature,
                     default_topp=args.topp, chunk=args.chunk)
    serve(state, port=args.port)


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
