"""OpenAI-compatible HTTP API server (`dllama-api` equivalent).

Re-implements `/root/reference/src/apps/dllama-api/dllama-api.cpp`:

* ``POST /v1/chat/completions`` — chat completion with optional SSE
  streaming (writeChatCompletionChunk, :168-185), per-request temperature /
  top_p / max_tokens / seed / stop (:351-380), usage counts (:336-345).
* ``POST /v1/completions`` — text completion; ``prompt`` may be a LIST of
  strings (and/or ``n > 1``), which decodes every prompt as its own
  distinct stream in ONE lockstep batch (Engine.generate_batch) — beyond
  reference (the reference is strictly batch=1, tasks.cpp:199-210) and
  the TPU serving-throughput lever: the decode matmuls amortize one
  weight read over all rows.  Enabled with ``--batch-slots N``.
* ``GET /v1/models`` — stub model list (:387-393).
* **NaiveCache** (:187-232): if a new request's messages extend the cached
  conversation prefix exactly, generation resumes from the cached KV
  position instead of re-prefilling the whole history.

Single-threaded request handling like the reference's accept loop
(:418-429) — each engine owns one KV cache, so requests serialize; the
accept queue IS the request queue (concurrent clients block, then get
served in order — see tests/test_api.py's concurrency test).
Uses only the standard library (the reference vendors nlohmann/json;
Python's ``json`` plays that role).
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, HTTPServer

from ..runtime.engine import ContextOverflow, Engine
from ..runtime.stream import drain_generation
from ..tokenizer.bpe import Tokenizer
from ..tokenizer.chat import ChatItem, ChatTemplate, TokenizerChatStops
from ..tokenizer.eos import EosDetector


def _decode_continuation(tok: Tokenizer, prev: int, token_ids: list[int]) -> str:
    """Decode a continuation with ``prev`` = the last prompt token — NOT
    from BOS: sentencepiece-style decode-from-BOS strips the first piece's
    leading space (bpe.py decode_piece), which is wrong for text that
    continues a prompt and diverges from the incremental/streaming
    decoders.  One copy shared by every non-streaming batch path."""
    parts = []
    for t in token_ids:
        parts.append(tok.decode_piece(prev, t))
        prev = t
    return b"".join(parts).decode("utf-8", errors="replace")


@dataclass
class ChatMessage:
    role: str
    content: str


@dataclass
class CacheItem:
    end_pos: int
    message: ChatMessage


class NaiveCache:
    """Longest-prefix conversation cache (dllama-api.cpp:187-232)."""

    def __init__(self):
        self.items: list[CacheItem] = []

    def clear(self):
        self.items.clear()

    def push(self, end_pos: int, message: ChatMessage):
        self.items.append(CacheItem(end_pos, message))

    def resolve_delta_prompt(self, messages: list[ChatMessage]) -> tuple[int, list[ChatMessage]]:
        """Returns (start_pos, delta_messages). On any mismatch the cache is
        cleared and the full message list is returned with start_pos 0."""
        n = len(self.items)
        if n and len(messages) > n:
            for i in range(n):
                if (self.items[i].message.role != messages[i].role or
                        self.items[i].message.content != messages[i].content):
                    break
            else:
                start = self.items[n - 1].end_pos
                return start, messages[n:]
        self.clear()
        return 0, messages


@dataclass
class InferenceParams:
    messages: list[ChatMessage] = field(default_factory=list)
    temperature: float = 0.7
    top_p: float = 0.9
    max_tokens: int = 0
    stream: bool = False
    seed: int | None = None
    stop: list[str] = field(default_factory=list)
    n: int = 1  # choices per request; n>1 runs on the batch engine


def parse_request(body: dict, default_temp: float, default_topp: float) -> InferenceParams:
    """Request-param extraction (dllama-api.cpp:351-380).  JSON ``null``
    for an optional field means "unset" to most OpenAI clients."""
    p = InferenceParams(temperature=default_temp, top_p=default_topp)
    for m in body.get("messages", []):
        p.messages.append(ChatMessage(str(m.get("role", "")), str(m.get("content", ""))))
    if body.get("temperature") is not None:
        p.temperature = float(body["temperature"])
    if body.get("top_p") is not None:
        p.top_p = float(body["top_p"])
    if body.get("max_tokens") is not None:
        p.max_tokens = int(body["max_tokens"])
    if body.get("stream") is not None:
        p.stream = bool(body["stream"])
    if body.get("seed") is not None:
        p.seed = int(body["seed"])
    if body.get("n") is not None:
        p.n = int(body["n"])
    stop = body.get("stop")
    if isinstance(stop, str):
        p.stop = [stop]
    elif isinstance(stop, list):
        p.stop = [str(s) for s in stop]
    return p


class ApiState:
    """Engine + tokenizer + conversation cache shared across requests.

    ``batch_engine`` (optional, ``--batch-slots``) is a second Engine with
    batch > 1 for /v1/completions list-prompt requests.  It shares the
    chat engine's *placed* weight buffers — Engine re-placement of an
    already-sharded array is a no-op — so the only extra HBM is its KV
    cache."""

    def __init__(self, engine: Engine, tokenizer: Tokenizer,
                 default_temperature: float = 0.7, default_topp: float = 0.9,
                 chunk: int = 16, model_name: str = "dllama-tpu",
                 batch_engine: Engine | None = None):
        self.engine = engine
        self.batch_engine = batch_engine
        self.tokenizer = tokenizer
        self.default_temperature = default_temperature
        self.default_topp = default_topp
        self.chunk = chunk
        self.model_name = model_name
        self.naive_cache = NaiveCache()
        stops = TokenizerChatStops(tokenizer)
        self.base_stops = stops.stops
        eos = tokenizer.vocab[tokenizer.chat_eos_id].decode("utf-8", "replace")
        self.template = ChatTemplate(tokenizer.chat_template, eos)

    # ------------------------------------------------------------------
    def complete(self, params: InferenceParams, emit):
        """Run one chat completion; calls ``emit(delta_text)`` as text becomes
        safe to stream.  Returns (content, n_prompt_tokens, n_completion_tokens)."""
        engine, tok = self.engine, self.tokenizer

        start_pos, delta_messages = self.naive_cache.resolve_delta_prompt(params.messages)
        if start_pos == 0:
            engine.reset()
        engine.pos = start_pos

        items = [ChatItem(m.role, m.content) for m in delta_messages]
        text = self.template.generate(items, True)
        prompt_tokens = tok.encode(text, add_bos=start_pos == 0)
        prompt_end = start_pos + len(prompt_tokens)
        if prompt_end + 1 >= engine.seq_len:
            # refuse before touching the cache — a poisoned entry would make
            # every follow-up request resolve to a bogus start_pos
            raise ContextOverflow(
                f"prompt needs {prompt_end} of {engine.seq_len} context positions")

        for m in delta_messages:
            self.naive_cache.push(prompt_end, m)

        max_pos = engine.seq_len
        if params.max_tokens > 0:
            max_pos = min(prompt_end + params.max_tokens, engine.seq_len)
        budget = max_pos - start_pos

        detector = EosDetector(tok.chat_eos_id, self.base_stops + params.stop,
                               padding_left=2, padding_right=2)
        seed = params.seed if params.seed is not None else int(time.time())

        stream = engine.generate_stream(
            prompt_tokens, budget, temperature=params.temperature,
            topp=params.top_p, seed=seed, chunk=self.chunk,
            eos_ids=(tok.chat_eos_id,))
        reply, n_completion, _ = drain_generation(
            engine, tok, detector, stream, len(prompt_tokens), prompt_end, emit)
        if engine.pos >= engine.seq_len:
            self.naive_cache.clear()  # context exhausted (dllama-api.cpp:330-331)
        else:
            self.naive_cache.push(engine.pos, ChatMessage("assistant", reply))
        return reply, len(prompt_tokens), n_completion

    # ------------------------------------------------------------------
    def _plan_ids(self, id_lists: list[list[int]], max_tokens: int,
                  eos_id: int) -> tuple[list[list[int]], int, int, int]:
        """THE batched-serving validation/padding/budget recipe — single
        copy shared by /v1/completions (stream and not) and chat ``n>1``.
        Pads the real rows to the engine's batch by repeating row 0 and
        raises ContextOverflow for every client-side problem, so handlers
        can 400 BEFORE committing to a response kind."""
        eng = self.batch_engine
        if eng is None:
            raise ValueError("batched serving not enabled (--batch-slots)")
        n_real = len(id_lists)
        if not (0 < n_real <= eng.batch):
            raise ContextOverflow(
                f"{n_real} prompts for {eng.batch} batch slots")
        if any(not ids for ids in id_lists):
            # a BOS-less tokenizer can encode "" to zero tokens; surface it
            # as the client-error type rather than letting the engine's
            # ValueError kill the connection with no HTTP response
            raise ContextOverflow("a prompt encoded to zero tokens")
        longest = max(len(i) for i in id_lists)
        if longest + 1 >= eng.seq_len:
            raise ContextOverflow(
                f"prompt needs {longest} of {eng.seq_len} context positions")
        padded = [list(i) for i in id_lists] \
            + [list(id_lists[0])] * (eng.batch - n_real)
        budget = eng.seq_len
        if max_tokens > 0:
            budget = min(longest + max_tokens, eng.seq_len)
        return padded, n_real, budget, eos_id

    def complete_n(self, params: InferenceParams
                   ) -> tuple[list[str], int, int]:
        """``n > 1`` chat choices: the templated prompt replicated n times
        decodes as one lockstep batch on ``batch_engine`` — n *sampled*
        alternatives per weight read (greedy rows are identical, as with
        any sampler).  Fresh conversation each time: the batch engine has
        its own cache and the NaiveCache is neither consulted nor updated
        (n distinct replies cannot extend one conversation prefix)."""
        eng, tok = self.batch_engine, self.tokenizer
        if eng is not None and params.n > eng.batch:
            # tailored message: the client sent ONE prompt with n choices,
            # not n prompts (the generic _plan_ids wording would mislead)
            raise ContextOverflow(
                f"n={params.n} exceeds the {eng.batch} batch slots; lower n "
                "or restart the server with a larger --batch-slots")
        items = [ChatItem(m.role, m.content) for m in params.messages]
        text = self.template.generate(items, True)
        prompt_tokens = tok.encode(text, add_bos=True)
        id_lists, _, budget, eos_id = self._plan_ids(
            [prompt_tokens] * params.n, params.max_tokens, tok.chat_eos_id)
        eng.reset()
        outs = eng.generate_batch(
            id_lists, budget, temperature=params.temperature,
            topp=params.top_p,
            seed=params.seed if params.seed is not None else int(time.time()),
            eos_ids=(eos_id,), chunk=self.chunk)
        choices = []
        n_completion = 0
        for r in range(params.n):
            comp = outs[r][len(prompt_tokens):]
            finish = "length"  # OpenAI truncation signal: cap, no eos
            if comp and comp[-1] == eos_id:
                comp = comp[:-1]
                finish = "stop"
            n_completion += len(comp)
            # continuation decode (prev = last prompt token), NOT
            # tok.decode: decode-from-BOS strips a leading space, which the
            # n=1 path's incremental drain keeps — the n choices must read
            # exactly like the single-choice reply
            reply = _decode_continuation(tok, prompt_tokens[-1], comp)
            for s in self.base_stops + params.stop:
                cut = reply.find(s)
                if cut != -1:
                    reply = reply[:cut]
                    finish = "stop"
            choices.append((reply, finish))
        return choices, len(prompt_tokens), n_completion

    # ------------------------------------------------------------------
    def plan_batch(self, prompts: list[str], max_tokens: int
                   ) -> tuple[list[list[int]], int, int, int]:
        """Tokenize a /v1/completions prompt list and run it through
        :meth:`_plan_ids` (the shared validation/budget recipe)."""
        tok = self.tokenizer
        if self.batch_engine is None:
            raise ValueError("batched serving not enabled (--batch-slots)")
        id_lists = [tok.encode(p, add_bos=self.batch_engine.cfg.add_bos)
                    for p in prompts]
        # plain-text completion stops at the base EOS (generate-mode
        # semantics), not the chat template's stop token
        eos_id = tok.eos_id if tok.eos_id >= 0 else tok.chat_eos_id
        return self._plan_ids(id_lists, max_tokens, eos_id)

    def complete_batch(self, prompts: list[str], *, temperature: float,
                       top_p: float, max_tokens: int, seed: int | None,
                       stop: list[str], echo: bool = False,
                       logprobs: int | None = None
                       ) -> tuple[list[dict], int, int]:
        """Run B distinct prompts as one lockstep batch on ``batch_engine``.

        Returns (choices, prompt_tokens, completion_tokens).  Prompt lists
        shorter than the engine's batch are padded by repeating the first
        prompt (pad rows' outputs are dropped); longer lists are the
        caller's 400.  ``stop`` strings truncate post-hoc — batch mode is
        offline-style serving, not token streaming, so the EosDetector's
        incremental hold-back buys nothing here.

        ``logprobs`` (int ≥ 0, OpenAI semantics) scores every returned
        completion with ONE extra teacher-forced ragged forward
        (Engine.score_batch): chosen-token log-probs, plus the top-k
        alternatives per position when > 0.
        """
        eng, tok = self.batch_engine, self.tokenizer
        id_lists, n_real, budget, eos_id = self.plan_batch(prompts, max_tokens)
        eng.reset()
        outs = eng.generate_batch(
            id_lists, budget, temperature=temperature, topp=top_p,
            seed=seed if seed is not None else int(time.time()),
            eos_ids=(eos_id,), chunk=self.chunk)
        choices = []
        comps = []
        n_prompt = n_completion = 0
        for r in range(n_real):
            ids, out = id_lists[r], outs[r]
            comp = out[len(ids):]
            # the lockstep budget is sized by the LONGEST prompt, so short
            # rows overshoot their own prompt+max_tokens — cap per row, so
            # a prompt served in a batch returns exactly the completion it
            # would get served alone
            if max_tokens > 0:
                comp = comp[:max_tokens]
            finish = "length"
            if comp and comp[-1] == eos_id:
                comp = comp[:-1]
                finish = "stop"
            comps.append(comp)
            n_prompt += len(ids)
            n_completion += len(comp)
            # continuation decode (see _decode_continuation); echo decodes
            # prompt+completion as ONE sequence so a UTF-8 codepoint split
            # across the prompt/completion boundary still reassembles
            text = tok.decode(ids + comp) if echo \
                else _decode_continuation(tok, ids[-1], comp)
            for s in stop:
                cut = text.find(s)
                if cut != -1:
                    text = text[:cut]
                    finish = "stop"
            choices.append({"text": text, "index": r,
                            "finish_reason": finish, "logprobs": None})
        if logprobs is not None and n_real:
            # even with every completion empty (e.g. EOS first): echo rows
            # still owe the prompt's logprobs, non-echo rows empty lists —
            # OpenAI shape either way, never a silent null.  The empty
            # non-echo shape needs no scoring forward, so skip it.
            if echo or any(comps):
                self._attach_logprobs(choices, id_lists, comps, n_real,
                                      int(logprobs), echo)
            else:
                for r in range(n_real):
                    choices[r]["logprobs"] = {
                        "tokens": [], "token_logprobs": [],
                        "top_logprobs": [] if int(logprobs) > 0 else None,
                        "text_offset": []}
        return choices, n_prompt, n_completion

    def _attach_logprobs(self, choices, id_lists, comps, n_real, top_k,
                         echo):
        """Fill each choice's ``logprobs`` object (OpenAI completions
        shape) from one teacher-forced scoring forward over the padded
        batch (Engine.score_batch).

        Alignment contract: ``"".join(tokens)`` equals the choice's
        ``text`` — piece strings come from an incremental UTF-8 decode (a
        codepoint split across byte-fallback tokens attributes to its
        final fragment), tokens past a stop-string truncation are
        dropped, and with ``echo`` the prompt's tokens lead the list with
        ``None`` as the first logprob (no conditional for position 0) —
        all OpenAI completions semantics."""
        import codecs
        eng, tok = self.batch_engine, self.tokenizer
        # pad rows never influence real rows (independent batch rows);
        # their sequences just need ≥2 tokens for the scorer
        seqs = [id_lists[r] + comps[r] if r < n_real else list(id_lists[r])
                for r in range(eng.batch)]
        seqs = [s if len(s) >= 2 else s + [0] for s in seqs]
        tok_lp, top_ids, top_lp = eng.score_batch(seqs, top_k=top_k)
        bucket = tok_lp.shape[1]
        for r in range(n_real):
            text = choices[r]["text"]
            if echo:
                # tok.decode renders no piece for a leading BOS — skip it
                # here too; the first displayed token then has a REAL
                # conditional (on BOS), so only a truly context-free
                # position 0 gets the OpenAI null.  Walk the REAL sequence,
                # not seqs[r], which may carry the scorer's min-length pad
                # token at the end
                skip = 1 if id_lists[r] and id_lists[r][0] == tok.bos_id else 0
                seq_tokens = (id_lists[r] + comps[r])[skip:]
                base = skip
            else:
                seq_tokens = comps[r]
                base = len(id_lists[r])  # seq index of entry 0
            off = bucket - len(seqs[r])
            # piece strings via incremental decode so their join equals
            # the text (which was decoded from joined bytes)
            dec = codecs.getincrementaldecoder("utf-8")("replace")
            prev = tok.bos_id if echo else id_lists[r][-1]
            prevs, pieces = [], []
            for t in seq_tokens:
                prevs.append(prev)
                pieces.append(dec.decode(tok.decode_piece(prev, t)))
                prev = t
            tail = dec.decode(b"", True)
            if tail and pieces:
                pieces[-1] += tail
            tokens, lps, tops, offsets_txt = [], [], [], []
            text_pos = 0
            for m, piece in enumerate(pieces):
                if text_pos + len(piece) > len(text):
                    break  # stop-string truncation: align to the text
                seq_idx = base + m
                tokens.append(piece)
                offsets_txt.append(text_pos)
                text_pos += len(piece)
                if seq_idx == 0:  # echo: position 0 has no conditional
                    lps.append(None)
                    if top_k > 0:
                        tops.append(None)
                    continue
                col = off + seq_idx - 1
                lps.append(float(tok_lp[r, col]))
                if top_k > 0:
                    # distinct ids can render to the same piece string
                    # (byte-fallback → U+FFFD): top_k is sorted descending,
                    # so setdefault keeps the higher logprob on collision
                    d: dict = {}
                    for i, l in zip(top_ids[r, col], top_lp[r, col]):
                        d.setdefault(tok.decode_piece(prevs[m], int(i))
                                     .decode("utf-8", "replace"), float(l))
                    tops.append(d)
            choices[r]["logprobs"] = {
                "tokens": tokens, "token_logprobs": lps,
                "top_logprobs": tops if top_k > 0 else None,
                "text_offset": offsets_txt}

    # ------------------------------------------------------------------
    def complete_batch_stream(self, prompts: list[str], *, temperature: float,
                              top_p: float, max_tokens: int, seed: int | None,
                              stop: list[str], emit,
                              plan: tuple | None = None) -> None:
        """Streaming complement of :meth:`complete_batch`: drives the same
        lockstep batch but calls ``emit(row_index, delta_text,
        finish_reason_or_None)`` as each row's text becomes safe to send.
        A row that finishes stops emitting while the batch keeps decoding
        for the rows still live.

        Parity details that keep stream ≡ non-stream for the same seed:
        per-row *incremental* UTF-8 decoding (a codepoint split across
        byte-fallback tokens reassembles instead of becoming U+FFFD, with
        a final flush when the row closes), and a per-row hold-back
        buffer of ``max(len(stop))-1`` characters — a stop string can
        begin anywhere inside a BPE piece and span any number of pieces,
        so the buffer scan sees exactly what complete_batch's post-hoc
        ``text.find`` sees, and no prefix of a stop is ever emitted
        early.  ``plan`` lets the HTTP handler run :meth:`plan_batch`
        (and 400) before committing to SSE headers.
        """
        import codecs
        eng, tok = self.batch_engine, self.tokenizer
        id_lists, n_real, budget, eos_id = \
            plan if plan is not None else self.plan_batch(prompts, max_tokens)
        eng.reset()
        decoders = [codecs.getincrementaldecoder("utf-8")("replace")
                    for _ in range(n_real)]
        hold = max((len(s) for s in stop), default=0)
        prev = [ids[-1] for ids in id_lists[:n_real]]
        buf = [""] * n_real   # decoded but not yet emitted
        n_comp = [0] * n_real
        cap = [max_tokens if max_tokens > 0
               else eng.seq_len - len(id_lists[r]) for r in range(n_real)]
        done = [False] * n_real

        def flush(r, closing, finish="length"):
            """Scan the row's unsent buffer for stops; emit everything
            safe.  While the row is live, the last ``hold-1`` characters
            stay buffered (a stop could still complete across the
            boundary); on close the whole buffer goes out with ``finish``
            ("length" at the cap, "stop" when eos fired)."""
            cuts = [c for c in (buf[r].find(s) for s in stop) if c != -1]
            if cuts:
                emit(r, buf[r][:min(cuts)], "stop")
                buf[r] = ""
                done[r] = True
                return
            if closing:
                done[r] = True
                emit(r, buf[r], finish)
                buf[r] = ""
            elif hold and len(buf[r]) >= hold:
                emit(r, buf[r][:len(buf[r]) - (hold - 1)], None)
                buf[r] = buf[r][len(buf[r]) - (hold - 1):]
            elif not hold and buf[r]:
                emit(r, buf[r], None)
                buf[r] = ""

        for step_vec in eng.generate_batch_stream(
                id_lists, budget, temperature=temperature, topp=top_p,
                seed=seed if seed is not None else int(time.time()),
                chunk=self.chunk):
            for r in range(n_real):
                if done[r]:
                    continue
                t = int(step_vec[r])
                n_comp[r] += 1
                if t == eos_id:
                    # eos text never enters the reply; flush and close as
                    # "stop" (a stop string firing in the buffer also ends
                    # the row as "stop" — flush handles both)
                    buf[r] += decoders[r].decode(b"", True)
                    flush(r, closing=True, finish="stop")
                    continue
                buf[r] += decoders[r].decode(tok.decode_piece(prev[r], t))
                prev[r] = t
                if n_comp[r] >= cap[r]:
                    buf[r] += decoders[r].decode(b"", True)
                    flush(r, closing=True)
                else:
                    flush(r, closing=False)
            if all(done):
                break
        for r in range(n_real):
            if not done[r]:  # budget exhausted with text still buffered
                buf[r] += decoders[r].decode(b"", True)
                flush(r, closing=True)


def make_handler(state: ApiState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *a):
            print(f"🔷 {self.command} {self.path}")

        def _json(self, code: int, obj: dict):
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _completions(self):
            """OpenAI text-completion endpoint; ``prompt`` may be a list
            and ``n`` replicates each prompt — every resulting row decodes
            as a distinct stream in one lockstep batch."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt = body.get("prompt")
                prompts = [str(p) for p in prompt] if isinstance(prompt, list) \
                    else [str(prompt or "")]
                if not any(prompts):
                    self._json(400, {"error": "prompt required"})
                    return
                n = int(body.get("n") or 1)
                if n > 1:  # n samples per prompt, row-major like OpenAI
                    prompts = [p for p in prompts for _ in range(n)]
                temperature = float(body["temperature"]) \
                    if body.get("temperature") is not None else state.default_temperature
                top_p = float(body["top_p"]) \
                    if body.get("top_p") is not None else state.default_topp
                max_tokens = int(body.get("max_tokens") or 0)
                seed = int(body["seed"]) if body.get("seed") is not None else None
                stop = body.get("stop")
                stop = [stop] if isinstance(stop, str) else \
                    [str(s) for s in stop] if isinstance(stop, list) else []
                echo = bool(body.get("echo"))
                stream = bool(body.get("stream"))
                logprobs = body.get("logprobs")
                if logprobs is not None:
                    logprobs = max(0, min(int(logprobs), 5))  # OpenAI cap
            except (TypeError, ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            if stream and logprobs is not None:
                self._json(400, {"error": "logprobs with stream is not "
                                          "supported; request them "
                                          "non-streaming"})
                return
            if state.batch_engine is None:
                self._json(400, {"error": "batched serving not enabled; "
                                          "start the server with --batch-slots N"})
                return
            if logprobs is not None and state.batch_engine.sp > 1:
                # reject BEFORE the generation forward: score_batch raises
                # on sp meshes, and the handler must answer 400, not drop
                # the connection after burning the decode
                self._json(400, {"error": "logprobs is not supported on "
                                          "sequence-parallel (--sp) servers"})
                return
            created = int(time.time())
            cid = f"cmpl-{uuid.uuid4().hex[:12]}"
            if stream:
                # validate BEFORE committing to SSE: an invalid request
                # gets the same 400 it would get without stream=true
                try:
                    plan = state.plan_batch(prompts, max_tokens)
                except ContextOverflow as e:
                    self._json(400, {"error": str(e)})
                    return
                # SSE chunks carry per-row deltas tagged by choice index —
                # every live row streams concurrently from the one
                # lockstep batch (echo is a non-streaming nicety; ignored)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()

                def emit(idx, delta, finish):
                    chunk = {"id": cid, "object": "text_completion",
                             "created": created, "model": state.model_name,
                             "choices": [{"text": delta, "index": idx,
                                          "finish_reason": finish,
                                          "logprobs": None}]}
                    self.wfile.write(f"data: {json.dumps(chunk)}\n\n".encode())
                    self.wfile.flush()

                try:
                    state.complete_batch_stream(
                        prompts, temperature=temperature, top_p=top_p,
                        max_tokens=max_tokens, seed=seed, stop=stop,
                        emit=emit, plan=plan)
                except Exception as e:
                    # mid-stream failure: an OpenAI-shaped error event so
                    # clients can tell a died stream from a short success,
                    # then [DONE] (they block on it); unexpected errors
                    # still propagate to the server log afterwards
                    err = {"error": {"message": str(e),
                                     "type": "invalid_request_error"
                                     if isinstance(e, ContextOverflow)
                                     else "server_error"}}
                    self.wfile.write(f"data: {json.dumps(err)}\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                    if not isinstance(e, ContextOverflow):
                        raise
                    return
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
                return
            try:
                choices, n_prompt, n_completion = state.complete_batch(
                    prompts, temperature=temperature, top_p=top_p,
                    max_tokens=max_tokens, seed=seed, stop=stop, echo=echo,
                    logprobs=logprobs)
            except ContextOverflow as e:
                self._json(400, {"error": str(e)})
                return
            self._json(200, {
                "id": cid,
                "object": "text_completion", "created": created,
                "model": state.model_name, "choices": choices,
                "usage": {"prompt_tokens": n_prompt,
                          "completion_tokens": n_completion,
                          "total_tokens": n_prompt + n_completion}})

        def do_GET(self):
            if self.path == "/v1/models":
                self._json(200, {"object": "list", "data": [{
                    "id": state.model_name, "object": "model",
                    "created": int(time.time()), "owned_by": "user"}]})
            elif self.path in ("/health", "/healthz"):
                self._json(200, {"status": "ok"})
            else:
                self._json(404, {"error": "not found"})

        def do_POST(self):
            if self.path == "/v1/completions":
                self._completions()
                return
            if self.path != "/v1/chat/completions":
                self._json(404, {"error": "not found"})
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                params = parse_request(body, state.default_temperature, state.default_topp)
                if not params.messages:
                    self._json(400, {"error": "messages required"})
                    return
            except (TypeError, ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return

            created = int(time.time())
            cid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
            if params.n > 1:
                if params.stream:
                    self._json(400, {"error": "stream with n>1 is not "
                                              "supported; request them "
                                              "separately"})
                    return
                if state.batch_engine is None:
                    self._json(400, {"error": "n>1 needs batched serving; "
                                              "start the server with "
                                              "--batch-slots N"})
                    return
                try:
                    n_choices, n_prompt, n_completion = state.complete_n(params)
                except ContextOverflow as e:
                    self._json(400, {"error": str(e)})
                    return
                self._json(200, {
                    "id": cid, "object": "chat.completion", "created": created,
                    "model": state.model_name,
                    "choices": [{"index": i, "finish_reason": fin,
                                 "message": {"role": "assistant", "content": r}}
                                for i, (r, fin) in enumerate(n_choices)],
                    "usage": {"prompt_tokens": n_prompt,
                              "completion_tokens": n_completion,
                              "total_tokens": n_prompt + n_completion}})
                return
            if params.stream:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()

                def emit(delta):
                    chunk = {"id": cid, "object": "chat.completion.chunk",
                             "created": created, "model": state.model_name,
                             "choices": [{"index": 0, "delta": {"content": delta},
                                          "finish_reason": None}]}
                    self.wfile.write(f"data: {json.dumps(chunk)}\n\n".encode())
                    self.wfile.flush()

                try:
                    state.complete(params, emit)
                except ContextOverflow as e:
                    # headers already sent: emit an OpenAI-shaped error
                    # object and terminate WITHOUT a normal finish chunk, so
                    # clients don't mistake the failure for an empty success.
                    # Only the context-window refusal maps to a client error;
                    # anything else is a server bug and propagates as a 500
                    # (ADVICE r01: a bare ValueError catch masked bugs).
                    err = {"error": {"message": str(e),
                                     "type": "invalid_request_error"}}
                    self.wfile.write(f"data: {json.dumps(err)}\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                    return
                final = {"id": cid, "object": "chat.completion.chunk",
                         "created": created, "model": state.model_name,
                         "choices": [{"index": 0, "delta": {}, "finish_reason": "stop"}]}
                self.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            else:
                try:
                    reply, n_prompt, n_completion = state.complete(params, lambda d: None)
                except ContextOverflow as e:
                    self._json(400, {"error": str(e)})
                    return
                self._json(200, {
                    "id": cid, "object": "chat.completion", "created": created,
                    "model": state.model_name,
                    "choices": [{"index": 0, "finish_reason": "stop",
                                 "message": {"role": "assistant", "content": reply}}],
                    "usage": {"prompt_tokens": n_prompt,
                              "completion_tokens": n_completion,
                              "total_tokens": n_prompt + n_completion}})

    return Handler


def serve(state: ApiState, host: str = "0.0.0.0", port: int = 9990):
    server = HTTPServer((host, port), make_handler(state))
    print(f"🔷 dllama-api listening on {host}:{port}")
    server.serve_forever()


def main(argv=None):
    import sys

    from ..cli import build_parser, load_stack
    argv = list(sys.argv[1:] if argv is None else argv)
    # reuse the dllama flag surface; the server has no positional mode
    args = build_parser().parse_args(["inference", *argv])
    if args.batch_slots > 0 and args.sp > 1:
        # the batch engine's ragged prefill needs the whole sequence axis
        # per shard (engine.prefill_ragged); accepting the flag would make
        # every /v1/completions request die mid-handler instead of this
        # one clear startup error — raised BEFORE the (minutes-long) model
        # load
        raise SystemExit("--batch-slots is not supported with --sp "
                         "(sequence-sharded KV cache); drop one of them")
    engine, tok = load_stack(args)
    batch_engine = None
    if args.batch_slots > 0:
        # share the chat engine's placed weights; only a new KV cache is
        # allocated (see ApiState docstring)
        batch_engine = Engine(engine.cfg, engine.params, mesh=engine.mesh,
                              batch=args.batch_slots, seq_len=args.max_seq_len,
                              kv_dtype=engine.cache.k.dtype)
        print(f"🔷 batched /v1/completions: {args.batch_slots} lockstep slots")
    state = ApiState(engine, tok, default_temperature=args.temperature,
                     default_topp=args.topp, chunk=args.chunk,
                     batch_engine=batch_engine)
    serve(state, port=args.port)


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
