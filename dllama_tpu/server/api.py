"""OpenAI-compatible HTTP API server (`dllama-api` equivalent).

Re-implements `/root/reference/src/apps/dllama-api/dllama-api.cpp`:

* ``POST /v1/chat/completions`` — chat completion with optional SSE
  streaming (writeChatCompletionChunk, :168-185), per-request temperature /
  top_p / max_tokens / seed / stop (:351-380), usage counts (:336-345).
* ``POST /v1/completions`` — text completion; ``prompt`` may be a LIST of
  strings (and/or ``n > 1``), which decodes every prompt as its own
  distinct stream in ONE lockstep batch (Engine.generate_batch) — beyond
  reference (the reference is strictly batch=1, tasks.cpp:199-210) and
  the TPU serving-throughput lever: the decode matmuls amortize one
  weight read over all rows.  Enabled with ``--batch-slots N``.
* **continuous batching** (``--batch-slots`` + runtime/scheduler.py):
  single-prompt completions and spillover chat requests join the batch
  engine at *decode-step* granularity — a request admitted mid-decode
  prefills in ``--sched-prefill-chunk`` chunks interleaved with its
  neighbors' tokens, and a finished stream frees its slot within
  ``--sched-max-wait-ms`` without stopping the batch.  Seeded sampling,
  logprobs, echo, list prompts, and ``n>1`` stay on the mutex/lockstep
  paths (see ``Handler._sched_eligible``).
* ``GET /v1/models`` — stub model list (:387-393).
* **NaiveCache** (:187-232): if a new request's messages extend the cached
  conversation prefix exactly, generation resumes from the cached KV
  position instead of re-prefilling the whole history.

**Request lifecycle & fault tolerance** (beyond reference — the
reference's accept loop is single-threaded blocking I/O, :418-429, and a
stalled client wedges the whole server): requests are handled on threads
(``ThreadingHTTPServer``) with a single **engine mutex** serializing
generation — each engine owns one KV cache, so the mutex queue IS the
request queue — plus:

* **bounded admission**: at most ``--max-pending`` requests in flight or
  queued; excess get ``429`` + ``Retry-After`` instead of an unbounded
  backlog (tail latency stays diagnosable under overload).
* **per-request deadlines**: a ``timeout``/``max_time`` body field (and
  ``--request-timeout`` server default) is enforced between decode
  chunks; an expired request returns a well-formed truncated completion
  with ``finish_reason="timeout"``.
* **socket I/O timeouts** (``--io-timeout``): a stalled client reading
  the body gets ``408``; a stalled reader mid-stream is treated as a
  disconnect.  Client disconnects cancel generation at the next chunk
  and rewind ``engine.pos`` (the runtime/stream.py invariant).
* **graceful drain**: SIGTERM/SIGINT stop accepting (new requests get
  ``503``), finish in-flight requests bounded by ``--drain-grace``, then
  exit (see :func:`serve`).
* **observability**: ``/health`` reports readiness + queue depth;
  ``/metrics`` exports counters (served, 429s, timeouts, disconnects).
* every degraded path above is deterministically testable through the
  fault registry (``runtime/faults.py``; ``DLLAMA_FAULTS`` arms a live
  server, ``tools/fault_drill.py`` drives one end to end).

Uses only the standard library (the reference vendors nlohmann/json;
Python's ``json`` plays that role).  docs/ROBUSTNESS.md has the full
semantics.
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import signal
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from ..io.integrity import ArtifactError
from ..obs import cost as obs_cost, dispatch as obs_dispatch, \
    events as obs_events, flight as obs_flight, metrics as obs_metrics, \
    trace as obs_trace
from ..obs.log import (configure as configure_logging, get_logger,
                       new_request_id, set_request_id)
from ..runtime.engine import ContextOverflow, Engine, NumericFault, StepTimeout
from ..runtime.faults import FAULTS
from ..runtime.scheduler import (PRIORITY_LEVELS, PRIORITY_NAMES,
                                 SchedulerClosed, SchedulerSaturated,
                                 SlotScheduler)
from ..runtime.snapshot import RecordStore, SnapshotMismatch
from ..runtime.stream import drain_generation
from .backoff import jittered_retry_after
from ..tokenizer.bpe import Tokenizer
from ..tokenizer.chat import ChatItem, ChatTemplate, TokenizerChatStops
from ..tokenizer.eos import EosDetector

_log = get_logger("server.api")

#: client-supplied X-Request-Id is echoed but sanitized to this alphabet
#: (it lands in logs and response headers verbatim otherwise)
_RID_RE = re.compile(r"[^A-Za-z0-9._-]")
_RID_MAX = 64


def priority_level(value) -> int | None:
    """QoS class name → scheduler level, or None for anything that is
    not a known class (callers decide between 400 and silent default)."""
    try:
        return PRIORITY_LEVELS[str(value).strip().lower()]
    except (KeyError, AttributeError):
        return None

#: request bodies above this are refused with 413 (an unbounded
#: Content-Length read is an easy memory DoS against a model server)
MAX_BODY_BYTES = 8 * 1024 * 1024

#: /admin/import bodies (DLREQ01 hand-off records) carry raw KV pages,
#: which dwarf JSON bodies — separate, much larger bound
MAX_HANDOFF_BYTES = 1 << 30


def _decode_continuation(tok: Tokenizer, prev: int, token_ids: list[int]) -> str:
    """Decode a continuation with ``prev`` = the last prompt token — NOT
    from BOS: sentencepiece-style decode-from-BOS strips the first piece's
    leading space (bpe.py decode_piece), which is wrong for text that
    continues a prompt and diverges from the incremental/streaming
    decoders.  One copy shared by every non-streaming batch path."""
    parts = []
    for t in token_ids:
        parts.append(tok.decode_piece(prev, t))
        prev = t
    return b"".join(parts).decode("utf-8", errors="replace")


@dataclass
class ChatMessage:
    role: str
    content: str


@dataclass
class CacheItem:
    end_pos: int
    message: ChatMessage


class NaiveCache:
    """Longest-prefix conversation cache (dllama-api.cpp:187-232)."""

    def __init__(self):
        self.items: list[CacheItem] = []

    def clear(self):
        self.items.clear()

    def push(self, end_pos: int, message: ChatMessage):
        self.items.append(CacheItem(end_pos, message))

    def resolve_delta_prompt(self, messages: list[ChatMessage]) -> tuple[int, list[ChatMessage]]:
        """Returns (start_pos, delta_messages). On any mismatch the cache is
        cleared and the full message list is returned with start_pos 0."""
        n = len(self.items)
        if n and len(messages) > n:
            for i in range(n):
                if (self.items[i].message.role != messages[i].role or
                        self.items[i].message.content != messages[i].content):
                    break
            else:
                start = self.items[n - 1].end_pos
                return start, messages[n:]
        self.clear()
        return 0, messages


@dataclass
class InferenceParams:
    messages: list[ChatMessage] = field(default_factory=list)
    temperature: float = 0.7
    top_p: float = 0.9
    max_tokens: int = 0
    stream: bool = False
    seed: int | None = None
    stop: list[str] = field(default_factory=list)
    n: int = 1  # choices per request; n>1 runs on the batch engine


def parse_request(body: dict, default_temp: float, default_topp: float) -> InferenceParams:
    """Request-param extraction (dllama-api.cpp:351-380).  JSON ``null``
    for an optional field means "unset" to most OpenAI clients."""
    p = InferenceParams(temperature=default_temp, top_p=default_topp)
    for m in body.get("messages", []):
        p.messages.append(ChatMessage(str(m.get("role", "")), str(m.get("content", ""))))
    if body.get("temperature") is not None:
        p.temperature = float(body["temperature"])
    if body.get("top_p") is not None:
        p.top_p = float(body["top_p"])
    if body.get("max_tokens") is not None:
        p.max_tokens = int(body["max_tokens"])
    if body.get("stream") is not None:
        p.stream = bool(body["stream"])
    if body.get("seed") is not None:
        p.seed = int(body["seed"])
    if body.get("n") is not None:
        p.n = int(body["n"])
    stop = body.get("stop")
    if isinstance(stop, str):
        p.stop = [stop]
    elif isinstance(stop, list):
        p.stop = [str(s) for s in stop]
    return p


#: serving counters this class mediates; each name is both the
#: pre-registry ``/metrics`` JSON key and the obs registry json_key
_SERVING_COUNTERS = (
    "requests_served", "requests_rejected_429", "requests_rejected_503",
    "read_timeouts_408", "deadline_timeouts", "client_disconnects",
    "server_errors")


class ServerMetrics:
    """Per-``ApiState`` *view* over the process-global obs registry.

    Bumps land in the one registry (so ``/metrics`` JSON and Prometheus
    exposition read the same numbers), while attribute reads and
    :meth:`snapshot` return deltas against a baseline captured at
    construction — several ApiStates in one test process each see only
    their own traffic, exactly like the pre-registry per-instance
    dataclass.  ``avg_request_s`` stays a per-instance EMA (it feeds this
    server's ``Retry-After`` hint); the global gauge mirrors it."""

    def __init__(self):
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._counters = {n: obs_metrics.REGISTRY.counter(n)
                          for n in _SERVING_COUNTERS}
        self._base = {n: c.value for n, c in self._counters.items()}
        self._avg_request_s = 0.0  # EMA; feeds the Retry-After hint

    def bump(self, name: str, n: int = 1) -> None:
        self._counters[name].inc(n)

    def observe_duration(self, seconds: float) -> None:
        with self._lock:
            a = self._avg_request_s
            self._avg_request_s = (seconds if a == 0.0
                                   else 0.8 * a + 0.2 * seconds)
        obs_metrics.AVG_REQUEST_S.set(self._avg_request_s)
        obs_metrics.REQUEST_DURATION.observe(seconds)

    @property
    def avg_request_s(self) -> float:
        with self._lock:
            return self._avg_request_s

    def __getattr__(self, name: str) -> int:
        # counter reads (state.metrics.requests_served == 1 in tests) are
        # deltas vs the construction baseline
        try:
            counters = object.__getattribute__(self, "_counters")
            base = object.__getattribute__(self, "_base")
        except AttributeError:
            raise AttributeError(name) from None
        if name in counters:
            return counters[name].value - base[name]
        raise AttributeError(name)

    def snapshot(self) -> dict:
        out = {"uptime_s": round(time.time() - self.started_at, 3)}
        for n, c in self._counters.items():
            out[n] = c.value - self._base[n]
        out["avg_request_s"] = round(self.avg_request_s, 6)
        return out


class _StreamTimer:
    """TTFT / inter-token latency observation for one request.

    Constructed at admission (so engine-mutex queue wait counts into
    TTFT, matching what the client experiences) and ticked after each
    delta has been *flushed to the socket* — a slow emit path (e.g. an
    injected ``server.emit_delta`` delay) therefore lands in the first
    delta's TTFT bucket, not between buckets.

    The exact observed values also feed the request's flight record
    (obs/flight.py), so ``/debug/requests/<id>`` and the TTFT/ITL
    histograms agree by construction."""

    def __init__(self, rid=None):
        self.t0 = time.monotonic()
        self.rid = rid
        self._last: float | None = None

    def tick(self) -> None:
        now = time.monotonic()
        if self._last is None:
            ttft = now - self.t0
            obs_metrics.TTFT.observe(ttft)
            obs_flight.first_token(self.rid, ttft)
        else:
            gap = now - self._last
            obs_metrics.INTER_TOKEN.observe(gap)
            obs_flight.inter_token(self.rid, gap)
        self._last = now


def _bounded(stream, state: "ApiState", deadline: float | None,
             is_aborted, flag: dict, n_prompt: int = 0):
    """Wrap an engine token stream so generation stops *between tokens*
    when the request deadline (or the server's drain deadline) passes or
    the client has gone away.  The consumer (drain_generation) then runs
    its normal end-of-stream path — held-back text flushes and
    ``engine.pos`` rewinds exactly as for a budget-exhausted stream, so
    cancellation reuses the one pos-rewind invariant instead of adding a
    second.  ``flag`` reports why the stream ended early.

    The deadline arms only after ``n_prompt`` + 1 items: the engine echoes
    the prompt before the first sampled token, and a "timed out" response
    must be a TRUNCATED completion, never an empty one — a cold server
    whose prefill compile alone eats the deadline still owes one token."""
    with contextlib.closing(stream):
        for i, item in enumerate(stream):
            yield item
            if is_aborted is not None and is_aborted():
                flag["aborted"] = True
                return
            d = state.effective_deadline(deadline)
            if d is not None and i >= n_prompt and time.monotonic() >= d:
                flag["timed_out"] = True
                return


class ApiState:
    """Engine + tokenizer + conversation cache shared across requests.

    ``batch_engine`` (optional, ``--batch-slots``) is a second Engine with
    batch > 1 for /v1/completions list-prompt requests.  It shares the
    chat engine's *placed* weight buffers — Engine re-placement of an
    already-sharded array is a no-op — so the only extra HBM is its KV
    cache.

    Request-lifecycle state (threaded server): ``engine_lock`` is THE
    engine mutex — generation for both engines serializes under it (one
    KV-cache conversation state, one device queue).  Admission is counted
    in ``try_enter``/``leave``; ``begin_drain`` flips the server into
    draining (reject new work, clamp in-flight deadlines)."""

    def __init__(self, engine: Engine, tokenizer: Tokenizer,
                 default_temperature: float = 0.7, default_topp: float = 0.9,
                 chunk: int = 16, model_name: str = "dllama-tpu",
                 batch_engine: Engine | None = None,
                 max_pending: int = 8, request_timeout: float = 0.0,
                 io_timeout: float = 15.0, drain_grace: float = 30.0,
                 snapshot_dir: str | None = None,
                 scheduler: SlotScheduler | None = None,
                 slo=None, handoff: bool = False,
                 handoff_ttl: float = 0.0):
        self.engine = engine
        self.snapshot_dir = snapshot_dir
        self.batch_engine = batch_engine
        self.scheduler = scheduler
        self.slo = slo  # obs.slo.SloEngine or None (--slo / DLLAMA_SLO)
        self.tokenizer = tokenizer
        self.default_temperature = default_temperature
        self.default_topp = default_topp
        self.chunk = chunk
        self.model_name = model_name
        self.naive_cache = NaiveCache()
        stops = TokenizerChatStops(tokenizer)
        self.base_stops = stops.stops
        eos = tokenizer.vocab[tokenizer.chat_eos_id].decode("utf-8", "replace")
        self.template = ChatTemplate(tokenizer.chat_template, eos)
        # ---- robustness layer ----
        self.max_pending = max_pending
        self.request_timeout = request_timeout
        self.io_timeout = io_timeout
        self.drain_grace = drain_grace
        self.engine_lock = threading.Lock()
        self.metrics = ServerMetrics()
        self._admit_lock = threading.Lock()
        self._pending = 0   # admitted: queued on the mutex or generating
        self._active = 0    # holding the engine mutex (0 or 1)
        self.draining = False
        self.drain_deadline: float | None = None
        # ---- per-request KV hand-off (--handoff; fleet router) ----
        # opt-in: with it on, a drain EXPORTS live slot requests as
        # DLREQ01 records (finish "handoff") for the router to re-bind on
        # a peer, instead of finishing them here within the grace window
        self.handoff = bool(handoff and scheduler is not None
                            and scheduler.pool is not None)
        # unclaimed export records expire after --handoff-ttl: a router
        # that died between the drain and the GET /admin/export/<rid>
        # pickup must not park the record (and this drain) forever
        self.handoff_records = RecordStore(
            ttl=handoff_ttl, on_expire=self._handoff_expired)

    def _handoff_expired(self, rid: str) -> None:
        obs_metrics.HANDOFF_EXPIRED.inc()
        _log.warning("handoff_record_expired", extra={"rid": rid})

    # -- admission / drain ---------------------------------------------
    def try_enter(self) -> str:
        """Admit one request: ``"ok"`` (caller MUST pair with ``leave``),
        ``"full"`` (queue at capacity → 429) or ``"draining"`` (→ 503)."""
        with self._admit_lock:
            if self.draining:
                return "draining"
            if self._pending >= self.max_pending:
                return "full"
            self._pending += 1
            return "ok"

    def leave(self, duration_s: float) -> None:
        with self._admit_lock:
            self._pending -= 1
        self.metrics.observe_duration(duration_s)

    def mark_active(self, on: bool) -> None:
        with self._admit_lock:
            self._active += 1 if on else -1

    def queue_depths(self) -> tuple[int, int]:
        """(in_flight, queued) — for /health and Retry-After."""
        with self._admit_lock:
            return self._active, max(self._pending - self._active, 0)

    def begin_drain(self, grace: float | None = None) -> None:
        """Stop admitting; clamp every in-flight deadline to now+grace."""
        with self._admit_lock:
            self.draining = True
            g = self.drain_grace if grace is None else grace
            self.drain_deadline = time.monotonic() + max(g, 0.0)
        if self.scheduler is not None:
            # slot-path requests drain too: no new submissions, every
            # in-flight and queued ticket's deadline clamps to the grace
            if self.handoff:
                # drain-with-export in one scheduler call: every live
                # slot becomes a DLREQ01 record the router fetches via
                # GET /admin/export/<rid>; the requests' handlers see
                # finish "handoff" and answer immediately, so the drain
                # completes in O(export) rather than O(longest
                # in-flight decode)
                try:
                    self.handoff_records.update(
                        self.scheduler.drain_with_export(
                            self.drain_deadline))
                except Exception as e:
                    # a failed export degrades to a plain grace-bounded
                    # drain; it must never turn SIGTERM into a crash
                    _log.error("handoff_export_failed",
                               extra={"error": repr(e)})
                    self.scheduler.begin_drain(self.drain_deadline)
            else:
                self.scheduler.begin_drain(self.drain_deadline)

    # -- engine-state snapshot (warm restart; runtime/snapshot.py) ------
    @property
    def snapshot_path(self) -> str | None:
        if not self.snapshot_dir:
            return None
        return os.path.join(self.snapshot_dir, "engine.snap")

    @property
    def sched_snapshot_path(self) -> str | None:
        if not self.snapshot_dir:
            return None
        return os.path.join(self.snapshot_dir, "scheduler.snap")

    def save_snapshot(self) -> str | None:
        """Snapshot the chat engine's state + the conversation cache to
        ``--snapshot-dir`` (called after drain, when no request holds the
        engine).  Returns the path, or None when disabled/failed — a
        snapshot failure must never turn a clean drain into a crash.

        A paged scheduler gets a sibling file: its pool KV, page tables
        and radix-tree keys (SlotScheduler.snapshot_paged), so the prefix
        cache built up before the drain survives the restart warm."""
        path = self.snapshot_path
        if path is None:
            return None
        try:
            os.makedirs(self.snapshot_dir, exist_ok=True)
            with obs_trace.span("snapshot_save", path=path):
                with self.engine_lock:
                    cache_items = [[it.end_pos, it.message.role,
                                    it.message.content]
                                   for it in self.naive_cache.items]
                    self.engine.snapshot(path,
                                         extra={"naive_cache": cache_items})
            _log.info("snapshot_saved", extra={"path": path})
        except Exception as e:
            _log.warning("snapshot_save_failed", extra={
                "path": path, "error": str(e)})
            return None
        if self.scheduler is not None and self.scheduler.pool is not None:
            try:
                self.scheduler.snapshot_paged(self.sched_snapshot_path)
                _log.info("sched_snapshot_saved",
                          extra={"path": self.sched_snapshot_path})
            except Exception as e:
                # best-effort: the prefix cache is a performance artifact,
                # losing it only costs re-prefills after restart
                _log.warning("sched_snapshot_save_failed", extra={
                    "path": self.sched_snapshot_path, "error": str(e)})
        return path

    def restore_snapshot(self) -> bool:
        """Warm-boot from ``--snapshot-dir`` when a snapshot exists.

        The snapshot is one-shot: deleted after a successful restore so a
        crash loop cannot replay ever-staler state.  A corrupt snapshot,
        a config-fingerprint mismatch, or any other failure logs its
        reason and cold-starts (the file is left behind for postmortem) —
        never a crash; a stale state file must not take the server down."""
        path = self.snapshot_path
        if path is None or not os.path.exists(path):
            return False
        try:
            with obs_trace.span("snapshot_restore", path=path):
                extra = self.engine.restore(path)
        except ArtifactError as e:
            _log.warning("snapshot_rejected_cold_start", extra={
                "path": path, "error": str(e)})
            self.engine.reset()
            return False
        except Exception as e:
            _log.warning("snapshot_restore_failed_cold_start", extra={
                "path": path, "error": str(e)})
            self.engine.reset()
            return False
        for end_pos, role, content in extra.get("naive_cache", []):
            self.naive_cache.push(int(end_pos), ChatMessage(str(role),
                                                            str(content)))
        try:
            os.remove(path)
        except OSError:
            pass
        _log.info("warm_start", extra={
            "path": path, "pos": self.engine.pos,
            "cached_messages": len(self.naive_cache.items)})
        spath = self.sched_snapshot_path
        if (self.scheduler is not None and self.scheduler.pool is not None
                and spath and os.path.exists(spath)):
            try:
                self.scheduler.restore_paged(spath)
                _log.info("sched_warm_start", extra={
                    "path": spath,
                    "prefix_nodes": len(self.scheduler.prefix_cache or ())})
            except Exception as e:
                # stale/mismatched scheduler state (geometry change,
                # superseded format): cold pool, warm everything else
                _log.warning("sched_snapshot_rejected_cold_start", extra={
                    "path": spath, "error": str(e)})
            try:
                os.remove(spath)
            except OSError:
                pass
        return True

    def retry_after_hint(self) -> int:
        """Retry-After seconds: queue depth × the EMA request duration
        (floor 1s) — an honest backpressure hint, not a constant."""
        with self._admit_lock:
            depth = self._pending
        avg = self.metrics.avg_request_s or 1.0
        return max(1, min(int(depth * avg + 0.999), 60))

    def should_shed(self, level: int) -> bool:
        """SLO-driven shedding order (docs/SERVING.md QoS): interactive
        is never shed; ``batch`` sheds as soon as ANY objective's burn
        rate on the fast window reaches 1.0 (the error budget has
        started burning — drop best-effort load before the verdict
        degrades); ``standard`` sheds only once the overall verdict is
        ``violating`` (every window burning — the replica is actually
        failing its objectives, not just wobbling)."""
        if self.slo is None or level <= PRIORITY_LEVELS["interactive"]:
            return False
        try:
            verdict = self.slo.evaluate()
        except Exception:
            return False
        if level >= PRIORITY_LEVELS["batch"]:
            windows = verdict.get("windows") or []
            if not windows:
                return False
            fast = windows[0]
            return any((o.get("burn") or {}).get(fast, 0.0) >= 1.0
                       for o in (verdict.get("objectives") or {}).values())
        return verdict.get("status") == "violating"

    # -- deadlines ------------------------------------------------------
    def request_deadline(self, body: dict) -> float | None:
        """Absolute (monotonic) deadline for a request: the body's
        ``timeout``/``max_time`` seconds, clamped by the server default
        (``--request-timeout``); None when neither applies."""
        t = body.get("timeout")
        if t is None:
            t = body.get("max_time")
        try:
            t = float(t) if t is not None else None
        except (TypeError, ValueError):
            t = None
        if t is not None and t <= 0:
            t = None
        if self.request_timeout > 0:
            t = self.request_timeout if t is None else min(t, self.request_timeout)
        return time.monotonic() + t if t is not None else None

    def effective_deadline(self, deadline: float | None) -> float | None:
        """The request deadline clamped by the drain deadline (a drain
        that starts mid-request shortens every in-flight request)."""
        dd = self.drain_deadline
        if dd is None:
            return deadline
        return dd if deadline is None else min(deadline, dd)

    def health(self) -> dict:
        """Readiness + liveness detail for ``/health`` (satellite: model
        loaded, mesh shape, backend, queue depths, uptime)."""
        eng = self.engine
        try:
            backend = eng.mesh.devices.flat[0].platform
        except Exception:
            backend = "unknown"
        in_flight, queued = self.queue_depths()
        occ = self.scheduler.occupancy() if self.scheduler is not None \
            else None
        # machine-readable capacity block (fleet satellite): everything
        # the router's least-loaded scorer needs in one probe, without
        # scraping Prometheus text.  Additive — the pre-fleet fields
        # below keep their exact shapes.
        capacity = {
            "free_slots": (occ["slots"] - occ["active"]) if occ
            else max(self.max_pending - in_flight - queued, 0),
            "free_kv_pages": occ.get("kv_pages_free") if occ else None,
            "queue_depth": queued + (occ["queued"] if occ else 0),
            "batch_efficiency":
                obs_metrics.SCHED_BATCH_EFFICIENCY.json_value(),
            "handoff": self.handoff,
            # KV tiering (runtime/kvtier.py): the router's free-KV
            # tiebreak should see effective capacity — resident free
            # pages plus pages reclaimable by spilling idle slots —
            # not just the resident free list
            "kv_pressure": occ.get("kv_pressure") if occ else None,
        }
        return {
            "status": "draining" if self.draining else "ok",
            "ready": True,  # the model loads before serve() binds the port
            "model": self.model_name,
            "backend": backend,
            "mesh": {k: int(v) for k, v in dict(eng.mesh.shape).items()},
            "seq_len": eng.seq_len,
            "batch_slots": self.batch_engine.batch if self.batch_engine else 0,
            # slot-scheduler occupancy (satellite: /health must surface it
            # alongside batch_slots so an over-n client can size retries)
            "scheduler": occ,
            "capacity": capacity,
            "in_flight": in_flight,
            "queued": queued,
            "max_pending": self.max_pending,
            "uptime_s": round(time.time() - self.metrics.started_at, 3),
            "requests_served": self.metrics.requests_served,
            # kernel-dispatch ledger (obs/dispatch.py): a process that fell
            # off its fast matmul path advertises it on every health probe —
            # a degraded pod shows up in the fleet dashboard, not just in
            # one scrollback warning at load time
            "degraded": obs_dispatch.degraded(),
            "degrade_reasons": obs_dispatch.reasons(),
            # SLO verdict (obs/slo.py): ok / at_risk / violating per
            # objective plus the burn rates behind the call — evaluated
            # live, so the health probe IS the alerting primitive
            "slo": self.slo.evaluate() if self.slo is not None else None,
            # performance economics (obs/cost.py): MFU/MBU against the
            # backend peak table, cumulative modeled work, and chip-time
            # by QoS class — cost-per-tenant as a health probe
            "perf": obs_cost.summary(),
        }

    # ------------------------------------------------------------------
    def complete(self, params: InferenceParams, emit, *,
                 deadline: float | None = None, is_aborted=None):
        """Run one chat completion; calls ``emit(delta_text)`` as text
        becomes safe to stream.  Returns ``(content, n_prompt_tokens,
        n_completion_tokens, finish_reason)`` with finish_reason ``"stop"``
        (eos/stop/budget — the pre-deadline contract), ``"timeout"``
        (deadline expired between chunks) or ``"aborted"`` (client gone;
        the caller sends nothing further).

        Cancellation safety: the deadline/abort checks live in a wrapper
        *around* the engine stream (:func:`_bounded`), so every early
        exit flows through drain_generation's single end-of-stream path —
        held-back text flushes, ``engine.pos`` rewinds to the consumed
        prefix, and the conversation cache records exactly the state the
        KV cache holds.  A disconnected client therefore never poisons
        the next request's cache resume."""
        engine, tok = self.engine, self.tokenizer
        if deadline is not None and time.monotonic() >= deadline:
            # expired while queued on the engine mutex: answer without
            # burning a prefill (the 429/Retry-After path exists so
            # clients can avoid this; some will miss anyway under load)
            return "", 0, 0, "timeout"

        start_pos, delta_messages = self.naive_cache.resolve_delta_prompt(params.messages)
        if start_pos == 0:
            engine.reset()
        engine.pos = start_pos

        items = [ChatItem(m.role, m.content) for m in delta_messages]
        text = self.template.generate(items, True)
        prompt_tokens = tok.encode(text, add_bos=start_pos == 0)
        prompt_end = start_pos + len(prompt_tokens)
        if prompt_end + 1 >= engine.seq_len:
            # refuse before touching the cache — a poisoned entry would make
            # every follow-up request resolve to a bogus start_pos
            raise ContextOverflow(
                f"prompt needs {prompt_end} of {engine.seq_len} context positions")

        for m in delta_messages:
            self.naive_cache.push(prompt_end, m)

        max_pos = engine.seq_len
        if params.max_tokens > 0:
            max_pos = min(prompt_end + params.max_tokens, engine.seq_len)
        budget = max_pos - start_pos

        detector = EosDetector(tok.chat_eos_id, self.base_stops + params.stop,
                               padding_left=2, padding_right=2)
        seed = params.seed if params.seed is not None else int(time.time())

        stream = engine.generate_stream(
            prompt_tokens, budget, temperature=params.temperature,
            topp=params.top_p, seed=seed, chunk=self.chunk,
            eos_ids=(tok.chat_eos_id,))
        flag: dict = {}
        if deadline is not None or is_aborted is not None \
                or self.drain_deadline is not None:
            stream = _bounded(stream, self, deadline, is_aborted, flag,
                              n_prompt=len(prompt_tokens))
        reply, n_completion, _ = drain_generation(
            engine, tok, detector, stream, len(prompt_tokens), prompt_end, emit)
        if engine.pos >= engine.seq_len:
            self.naive_cache.clear()  # context exhausted (dllama-api.cpp:330-331)
        else:
            # on timeout/disconnect this records the PARTIAL reply at the
            # rewound pos — cache and KV state stay consistent, which is
            # the whole invariant (a poisoned entry would corrupt resumes)
            self.naive_cache.push(engine.pos, ChatMessage("assistant", reply))
        finish = "aborted" if flag.get("aborted") \
            else "timeout" if flag.get("timed_out") else "stop"
        # coarse flight phases for the mutex path (the scheduler path
        # records per-dispatch detail instead); rid rides the contextvar
        obs_flight.phase(None, "prefill_chunk",
                         tokens=len(prompt_tokens), pos=start_pos)
        obs_flight.phase(None, "decode_burst", tokens=n_completion)
        obs_flight.retire(None, finish, produced=n_completion)
        return reply, len(prompt_tokens), n_completion, finish

    # ------------------------------------------------------------------
    def overflow_body(self, e: Exception) -> dict:
        """Error body for a batch-capacity 4xx: the message plus the
        server's slot count and live scheduler occupancy, so a client
        that sent too many prompts (or too large an ``n``) can split the
        work without a second probing request."""
        body: dict = {"error": str(e)}
        if self.batch_engine is not None:
            body["batch_slots"] = self.batch_engine.batch
        if self.scheduler is not None:
            body["scheduler"] = self.scheduler.occupancy()
        return body

    def _batch_exclusive(self):
        """One-shot batch-engine work (list-prompt lockstep, n>1 fan-out,
        logprobs scoring) resets the shared KV cache, which would corrupt
        any live slot rows — park the scheduler first."""
        if self.scheduler is not None:
            return self.scheduler.exclusive()
        return contextlib.nullcontext()

    def _plan_ids(self, id_lists: list[list[int]], max_tokens: int,
                  eos_id: int) -> tuple[list[list[int]], int, int, int]:
        """THE batched-serving validation/padding/budget recipe — single
        copy shared by /v1/completions (stream and not) and chat ``n>1``.
        Pads the real rows to the engine's batch by repeating row 0 and
        raises ContextOverflow for every client-side problem, so handlers
        can 400 BEFORE committing to a response kind."""
        eng = self.batch_engine
        if eng is None:
            raise ValueError("batched serving not enabled (--batch-slots)")
        if getattr(eng, "paged", False):
            # the paged pool has no whole-batch reset/lockstep mode
            # (engine.slot_step is the only entry); these requests must go
            # one at a time through the scheduler instead
            raise ContextOverflow(
                "prompt lists, n>1 and logprobs are not available with "
                "--kv-pages (slot scheduling only); send requests "
                "individually")
        n_real = len(id_lists)
        if not (0 < n_real <= eng.batch):
            raise ContextOverflow(
                f"{n_real} prompts for {eng.batch} batch slots")
        if any(not ids for ids in id_lists):
            # a BOS-less tokenizer can encode "" to zero tokens; surface it
            # as the client-error type rather than letting the engine's
            # ValueError kill the connection with no HTTP response
            raise ContextOverflow("a prompt encoded to zero tokens")
        longest = max(len(i) for i in id_lists)
        if longest + 1 >= eng.seq_len:
            raise ContextOverflow(
                f"prompt needs {longest} of {eng.seq_len} context positions")
        padded = [list(i) for i in id_lists] \
            + [list(id_lists[0])] * (eng.batch - n_real)
        budget = eng.seq_len
        if max_tokens > 0:
            budget = min(longest + max_tokens, eng.seq_len)
        return padded, n_real, budget, eos_id

    def _drain_batch(self, id_lists: list[list[int]], budget: int, *,
                     temperature: float, top_p: float, seed: int | None,
                     eos_id: int, deadline: float | None = None,
                     n_real: int | None = None
                     ) -> tuple[list[list[int]], list[bool]]:
        """Consume one lockstep batch generation (Engine.generate_batch
        semantics: per-row EOS/budget truncation) with a deadline check
        between device chunks — the batch twin of :func:`_bounded`.
        Returns ``(outs, timed_out_per_row)``; rows cut by the deadline
        keep whatever they had decoded.  The batch engine is one-shot
        (reset precedes every use), so early exit needs no pos rewind —
        only the generator close, which returns the speculative chunk's
        RNG tick (engine contract).

        ``n_real``: rows past it are ``_plan_ids`` padding — they decode
        on device (lockstep has no ragged exit) but are masked out of
        every host-side step: no detokenization, no EOS scan, and no say
        in the early-exit vote, so a short real batch finishes as soon as
        its REAL rows do.  The pad fraction is what the batch-efficiency
        gauge reports."""
        eng = self.batch_engine
        if n_real is None:
            n_real = len(id_lists)
        obs_metrics.SCHED_BATCH_EFFICIENCY.set(n_real / eng.batch)
        outs = [list(p) for p in id_lists]
        done = [len(o) >= budget or r >= n_real
                for r, o in enumerate(outs)]
        timed = [False] * len(outs)
        with self._batch_exclusive():
            eng.reset()
            stream = eng.generate_batch_stream(
                id_lists, budget, temperature=temperature, topp=top_p,
                seed=seed if seed is not None else int(time.time()),
                chunk=self.chunk)
            with contextlib.closing(stream):
                for row_tokens in stream:
                    for r, t in enumerate(row_tokens.tolist()):
                        if done[r]:
                            continue
                        outs[r].append(int(t))
                        if int(t) == eos_id or len(outs[r]) >= budget:
                            done[r] = True
                    if all(done):
                        break
                    d = self.effective_deadline(deadline)
                    if d is not None and time.monotonic() >= d:
                        timed = [not dn and r < n_real
                                 for r, dn in enumerate(done)]
                        break
        return outs, timed

    def complete_n(self, params: InferenceParams,
                   deadline: float | None = None
                   ) -> tuple[list[str], int, int]:
        """``n > 1`` chat choices: the templated prompt replicated n times
        decodes as one lockstep batch on ``batch_engine`` — n *sampled*
        alternatives per weight read (greedy rows are identical, as with
        any sampler).  Fresh conversation each time: the batch engine has
        its own cache and the NaiveCache is neither consulted nor updated
        (n distinct replies cannot extend one conversation prefix)."""
        eng, tok = self.batch_engine, self.tokenizer
        if eng is not None and params.n > eng.batch:
            # tailored message: the client sent ONE prompt with n choices,
            # not n prompts (the generic _plan_ids wording would mislead)
            raise ContextOverflow(
                f"n={params.n} exceeds the {eng.batch} batch slots; lower n "
                "or restart the server with a larger --batch-slots")
        items = [ChatItem(m.role, m.content) for m in params.messages]
        text = self.template.generate(items, True)
        prompt_tokens = tok.encode(text, add_bos=True)
        id_lists, _, budget, eos_id = self._plan_ids(
            [prompt_tokens] * params.n, params.max_tokens, tok.chat_eos_id)
        outs, timed = self._drain_batch(
            id_lists, budget, temperature=params.temperature,
            top_p=params.top_p, seed=params.seed, eos_id=eos_id,
            deadline=deadline, n_real=params.n)
        choices = []
        n_completion = 0
        for r in range(params.n):
            comp = outs[r][len(prompt_tokens):]
            finish = "timeout" if timed[r] else "length"
            if comp and comp[-1] == eos_id:
                comp = comp[:-1]
                finish = "stop"
            n_completion += len(comp)
            # continuation decode (prev = last prompt token), NOT
            # tok.decode: decode-from-BOS strips a leading space, which the
            # n=1 path's incremental drain keeps — the n choices must read
            # exactly like the single-choice reply
            reply = _decode_continuation(tok, prompt_tokens[-1], comp)
            for s in self.base_stops + params.stop:
                cut = reply.find(s)
                if cut != -1:
                    reply = reply[:cut]
                    finish = "stop"
            choices.append((reply, finish))
        return choices, len(prompt_tokens), n_completion

    # ------------------------------------------------------------------
    def plan_batch(self, prompts: list[str], max_tokens: int
                   ) -> tuple[list[list[int]], int, int, int]:
        """Tokenize a /v1/completions prompt list and run it through
        :meth:`_plan_ids` (the shared validation/budget recipe)."""
        tok = self.tokenizer
        if self.batch_engine is None:
            raise ValueError("batched serving not enabled (--batch-slots)")
        id_lists = [tok.encode(p, add_bos=self.batch_engine.cfg.add_bos)
                    for p in prompts]
        # plain-text completion stops at the base EOS (generate-mode
        # semantics), not the chat template's stop token
        eos_id = tok.eos_id if tok.eos_id >= 0 else tok.chat_eos_id
        return self._plan_ids(id_lists, max_tokens, eos_id)

    def complete_batch(self, prompts: list[str], *, temperature: float,
                       top_p: float, max_tokens: int, seed: int | None,
                       stop: list[str], echo: bool = False,
                       logprobs: int | None = None,
                       deadline: float | None = None
                       ) -> tuple[list[dict], int, int]:
        """Run B distinct prompts as one lockstep batch on ``batch_engine``.

        Returns (choices, prompt_tokens, completion_tokens).  Prompt lists
        shorter than the engine's batch are padded by repeating the first
        prompt (pad rows' outputs are dropped); longer lists are the
        caller's 400.  ``stop`` strings truncate post-hoc — batch mode is
        offline-style serving, not token streaming, so the EosDetector's
        incremental hold-back buys nothing here.

        ``logprobs`` (int ≥ 0, OpenAI semantics) scores every returned
        completion with ONE extra teacher-forced ragged forward
        (Engine.score_batch): chosen-token log-probs, plus the top-k
        alternatives per position when > 0.
        """
        eng, tok = self.batch_engine, self.tokenizer
        id_lists, n_real, budget, eos_id = self.plan_batch(prompts, max_tokens)
        outs, timed = self._drain_batch(
            id_lists, budget, temperature=temperature, top_p=top_p,
            seed=seed, eos_id=eos_id, deadline=deadline, n_real=n_real)
        choices = []
        comps = []
        n_prompt = n_completion = 0
        for r in range(n_real):
            ids, out = id_lists[r], outs[r]
            comp = out[len(ids):]
            # the lockstep budget is sized by the LONGEST prompt, so short
            # rows overshoot their own prompt+max_tokens — cap per row, so
            # a prompt served in a batch returns exactly the completion it
            # would get served alone
            if max_tokens > 0:
                comp = comp[:max_tokens]
            finish = "timeout" if timed[r] else "length"
            if comp and comp[-1] == eos_id:
                comp = comp[:-1]
                finish = "stop"
            comps.append(comp)
            n_prompt += len(ids)
            n_completion += len(comp)
            # continuation decode (see _decode_continuation); echo decodes
            # prompt+completion as ONE sequence so a UTF-8 codepoint split
            # across the prompt/completion boundary still reassembles
            text = tok.decode(ids + comp) if echo \
                else _decode_continuation(tok, ids[-1], comp)
            for s in stop:
                cut = text.find(s)
                if cut != -1:
                    text = text[:cut]
                    finish = "stop"
            choices.append({"text": text, "index": r,
                            "finish_reason": finish, "logprobs": None})
        if logprobs is not None and n_real:
            # even with every completion empty (e.g. EOS first): echo rows
            # still owe the prompt's logprobs, non-echo rows empty lists —
            # OpenAI shape either way, never a silent null.  The empty
            # non-echo shape needs no scoring forward, so skip it.
            if echo or any(comps):
                self._attach_logprobs(choices, id_lists, comps, n_real,
                                      int(logprobs), echo)
            else:
                for r in range(n_real):
                    choices[r]["logprobs"] = {
                        "tokens": [], "token_logprobs": [],
                        "top_logprobs": [] if int(logprobs) > 0 else None,
                        "text_offset": []}
        return choices, n_prompt, n_completion

    def _attach_logprobs(self, choices, id_lists, comps, n_real, top_k,
                         echo):
        """Fill each choice's ``logprobs`` object (OpenAI completions
        shape) from one teacher-forced scoring forward over the padded
        batch (Engine.score_batch).

        Alignment contract: ``"".join(tokens)`` equals the choice's
        ``text`` — piece strings come from an incremental UTF-8 decode (a
        codepoint split across byte-fallback tokens attributes to its
        final fragment), tokens past a stop-string truncation are
        dropped, and with ``echo`` the prompt's tokens lead the list with
        ``None`` as the first logprob (no conditional for position 0) —
        all OpenAI completions semantics."""
        import codecs
        eng, tok = self.batch_engine, self.tokenizer
        # pad rows never influence real rows (independent batch rows);
        # their sequences just need ≥2 tokens for the scorer
        seqs = [id_lists[r] + comps[r] if r < n_real else list(id_lists[r])
                for r in range(eng.batch)]
        seqs = [s if len(s) >= 2 else s + [0] for s in seqs]
        with self._batch_exclusive():
            tok_lp, top_ids, top_lp = eng.score_batch(seqs, top_k=top_k)
        bucket = tok_lp.shape[1]
        for r in range(n_real):
            text = choices[r]["text"]
            if echo:
                # tok.decode renders no piece for a leading BOS — skip it
                # here too; the first displayed token then has a REAL
                # conditional (on BOS), so only a truly context-free
                # position 0 gets the OpenAI null.  Walk the REAL sequence,
                # not seqs[r], which may carry the scorer's min-length pad
                # token at the end
                skip = 1 if id_lists[r] and id_lists[r][0] == tok.bos_id else 0
                seq_tokens = (id_lists[r] + comps[r])[skip:]
                base = skip
            else:
                seq_tokens = comps[r]
                base = len(id_lists[r])  # seq index of entry 0
            off = bucket - len(seqs[r])
            # piece strings via incremental decode so their join equals
            # the text (which was decoded from joined bytes)
            dec = codecs.getincrementaldecoder("utf-8")("replace")
            prev = tok.bos_id if echo else id_lists[r][-1]
            prevs, pieces = [], []
            for t in seq_tokens:
                prevs.append(prev)
                pieces.append(dec.decode(tok.decode_piece(prev, t)))
                prev = t
            tail = dec.decode(b"", True)
            if tail and pieces:
                pieces[-1] += tail
            tokens, lps, tops, offsets_txt = [], [], [], []
            text_pos = 0
            for m, piece in enumerate(pieces):
                if text_pos + len(piece) > len(text):
                    break  # stop-string truncation: align to the text
                seq_idx = base + m
                tokens.append(piece)
                offsets_txt.append(text_pos)
                text_pos += len(piece)
                if seq_idx == 0:  # echo: position 0 has no conditional
                    lps.append(None)
                    if top_k > 0:
                        tops.append(None)
                    continue
                col = off + seq_idx - 1
                lps.append(float(tok_lp[r, col]))
                if top_k > 0:
                    # distinct ids can render to the same piece string
                    # (byte-fallback → U+FFFD): top_k is sorted descending,
                    # so setdefault keeps the higher logprob on collision
                    d: dict = {}
                    for i, l in zip(top_ids[r, col], top_lp[r, col]):
                        d.setdefault(tok.decode_piece(prevs[m], int(i))
                                     .decode("utf-8", "replace"), float(l))
                    tops.append(d)
            choices[r]["logprobs"] = {
                "tokens": tokens, "token_logprobs": lps,
                "top_logprobs": tops if top_k > 0 else None,
                "text_offset": offsets_txt}

    # ------------------------------------------------------------------
    def complete_batch_stream(self, prompts: list[str], *, temperature: float,
                              top_p: float, max_tokens: int, seed: int | None,
                              stop: list[str], emit,
                              plan: tuple | None = None,
                              deadline: float | None = None,
                              is_aborted=None) -> None:
        """Streaming complement of :meth:`complete_batch`: drives the same
        lockstep batch but calls ``emit(row_index, delta_text,
        finish_reason_or_None)`` as each row's text becomes safe to send.
        A row that finishes stops emitting while the batch keeps decoding
        for the rows still live.

        Parity details that keep stream ≡ non-stream for the same seed:
        per-row *incremental* UTF-8 decoding (a codepoint split across
        byte-fallback tokens reassembles instead of becoming U+FFFD, with
        a final flush when the row closes), and a per-row hold-back
        buffer of ``max(len(stop))-1`` characters — a stop string can
        begin anywhere inside a BPE piece and span any number of pieces,
        so the buffer scan sees exactly what complete_batch's post-hoc
        ``text.find`` sees, and no prefix of a stop is ever emitted
        early.  ``plan`` lets the HTTP handler run :meth:`plan_batch`
        (and 400) before committing to SSE headers.
        """
        import codecs
        eng, tok = self.batch_engine, self.tokenizer
        id_lists, n_real, budget, eos_id = \
            plan if plan is not None else self.plan_batch(prompts, max_tokens)
        obs_metrics.SCHED_BATCH_EFFICIENCY.set(n_real / eng.batch)
        decoders = [codecs.getincrementaldecoder("utf-8")("replace")
                    for _ in range(n_real)]
        hold = max((len(s) for s in stop), default=0)
        prev = [ids[-1] for ids in id_lists[:n_real]]
        buf = [""] * n_real   # decoded but not yet emitted
        n_comp = [0] * n_real
        cap = [max_tokens if max_tokens > 0
               else eng.seq_len - len(id_lists[r]) for r in range(n_real)]
        done = [False] * n_real

        def flush(r, closing, finish="length"):
            """Scan the row's unsent buffer for stops; emit everything
            safe.  While the row is live, the last ``hold-1`` characters
            stay buffered (a stop could still complete across the
            boundary); on close the whole buffer goes out with ``finish``
            ("length" at the cap, "stop" when eos fired)."""
            cuts = [c for c in (buf[r].find(s) for s in stop) if c != -1]
            if cuts:
                emit(r, buf[r][:min(cuts)], "stop")
                buf[r] = ""
                done[r] = True
                return
            if closing:
                done[r] = True
                emit(r, buf[r], finish)
                buf[r] = ""
            elif hold and len(buf[r]) >= hold:
                emit(r, buf[r][:len(buf[r]) - (hold - 1)], None)
                buf[r] = buf[r][len(buf[r]) - (hold - 1):]
            elif not hold and buf[r]:
                emit(r, buf[r], None)
                buf[r] = ""

        with self._batch_exclusive():
            eng.reset()
            stream = eng.generate_batch_stream(
                id_lists, budget, temperature=temperature, topp=top_p,
                seed=seed if seed is not None else int(time.time()),
                chunk=self.chunk)
            with contextlib.closing(stream):
                for step_vec in stream:
                    for r in range(n_real):
                        if done[r]:
                            continue
                        t = int(step_vec[r])
                        n_comp[r] += 1
                        if t == eos_id:
                            # eos text never enters the reply; flush and close
                            # as "stop" (a stop string firing in the buffer
                            # also ends the row as "stop" — flush handles both)
                            buf[r] += decoders[r].decode(b"", True)
                            flush(r, closing=True, finish="stop")
                            continue
                        buf[r] += decoders[r].decode(
                            tok.decode_piece(prev[r], t))
                        prev[r] = t
                        if n_comp[r] >= cap[r]:
                            buf[r] += decoders[r].decode(b"", True)
                            flush(r, closing=True)
                        else:
                            flush(r, closing=False)
                    if all(done):
                        break
                    if is_aborted is not None and is_aborted():
                        return  # client gone: nothing left worth decoding
                    d = self.effective_deadline(deadline)
                    if d is not None and time.monotonic() >= d:
                        # deadline between chunks: close every live row as a
                        # well-formed truncated stream (OpenAI shape, the
                        # chat path's finish_reason="timeout" contract)
                        for r in range(n_real):
                            if not done[r]:
                                buf[r] += decoders[r].decode(b"", True)
                                flush(r, closing=True, finish="timeout")
                        return
        for r in range(n_real):
            if not done[r]:  # budget exhausted with text still buffered
                buf[r] += decoders[r].decode(b"", True)
                flush(r, closing=True)

    # -- continuous batching (runtime/scheduler.py) --------------------
    def sched_submit(self, prompt_tokens: list[int], max_tokens: int, *,
                     temperature: float, top_p: float, eos_id: int,
                     deadline: float | None, stop: list[str] | None = None,
                     priority: int = 1):
        """Validate and submit one request to the slot scheduler.  Split
        from :meth:`sched_drain` so streaming handlers can 400/429/503
        BEFORE committing to SSE headers.  Raises ContextOverflow /
        SchedulerClosed / SchedulerSaturated.  ``stop`` strings ride the
        ticket so a drain-time hand-off export can ship them (the
        importing replica owes the client the same stop-scan)."""
        eng = self.scheduler.engine
        if not prompt_tokens:
            raise ContextOverflow("a prompt encoded to zero tokens")
        if len(prompt_tokens) + 1 >= eng.seq_len:
            raise ContextOverflow(
                f"prompt needs {len(prompt_tokens)} of {eng.seq_len} "
                "context positions")
        max_new = eng.seq_len - len(prompt_tokens)
        if max_tokens > 0:
            max_new = min(max_new, max_tokens)
        ticket = self.scheduler.submit(
            prompt_tokens, max_new, temperature=temperature, top_p=top_p,
            eos_ids=(eos_id,), deadline=self.effective_deadline(deadline),
            priority=priority)
        ticket.stop = [str(s) for s in stop or []]
        return ticket

    def sched_drain(self, ticket, prev: int, *, stop: list[str], emit,
                    is_aborted=None) -> tuple[str, int, str]:
        """Consume one ticket's token stream: incremental UTF-8 decode
        plus the same ``max(len(stop))-1`` hold-back scan as
        :meth:`complete_batch_stream`, so slot-path stream ≡ non-stream
        for the same request.  Calls ``emit(delta, finish_or_None)`` as
        text becomes safe; returns ``(text, n_completion_tokens,
        finish)`` with finish stop/length/timeout/aborted.  A scheduler-
        side failure (StepTimeout, device fault) re-raises here, on this
        handler's thread."""
        import codecs
        tok = self.tokenizer
        dec = codecs.getincrementaldecoder("utf-8")("replace")
        hold = max((len(s) for s in stop), default=0)
        parts: list[str] = []
        buf = ""
        n_comp = 0

        def push(delta, finish):
            parts.append(delta)
            emit(delta, finish)

        stopped = False
        for t in ticket.tokens():
            if is_aborted is not None and is_aborted():
                ticket.cancel("aborted")
                break
            n_comp += 1
            buf += dec.decode(tok.decode_piece(prev, t))
            prev = t
            cuts = [c for c in (buf.find(s) for s in stop) if c != -1]
            if cuts:
                # the generation keeps running until the scheduler honors
                # the cancel; tokens past the stop are never decoded here
                ticket.cancel("stop")
                push(buf[:min(cuts)], "stop")
                stopped = True
                break
            if hold and len(buf) >= hold:
                push(buf[:len(buf) - (hold - 1)], None)
                buf = buf[len(buf) - (hold - 1):]
            elif not hold and buf:
                push(buf, None)
                buf = ""
        if stopped:
            return "".join(parts), n_comp, "stop"
        finish = ticket.finish or "aborted"
        buf += dec.decode(b"", True)
        cuts = [c for c in (buf.find(s) for s in stop) if c != -1]
        if cuts:
            buf = buf[:min(cuts)]
            finish = "stop"
        push(buf, finish)
        return "".join(parts), n_comp, finish

    def handoff_resume(self, ticket, extra: dict, emitted_chars: int,
                       emit, is_aborted=None) -> tuple[str, int, str]:
        """Drive an imported hand-off request to completion (the
        ``/admin/import`` twin of :meth:`sched_drain`).

        The exporter's completion tokens (``extra["completion"]``) are
        replayed through a fresh incremental UTF-8 decoder so the decode
        and stop-scan state land exactly where the exporter's stream
        stood; only text beyond ``emitted_chars`` — the characters the
        router already forwarded to the client — is emitted.  The client
        therefore sees one seamless stream across the replica move.
        Returns ``(full_completion_text, total_completion_tokens,
        finish)``; token totals include the replayed tokens, so usage
        accounting survives the hop."""
        import codecs
        tok = self.tokenizer
        stop = [str(s) for s in extra.get("stop") or []]
        hold = max((len(s) for s in stop), default=0)
        dec = codecs.getincrementaldecoder("utf-8")("replace")
        prompt = [int(x) for x in extra["prompt"]]
        replay = [int(x) for x in extra.get("completion") or []]
        prev = prompt[-1]
        full = ""
        cursor = max(0, int(emitted_chars))

        def feed(t):
            nonlocal full, prev
            full += dec.decode(tok.decode_piece(prev, t))
            prev = t

        def flush(limit, finish=None):
            nonlocal cursor
            delta = full[cursor:limit] if limit > cursor else ""
            if delta or finish is not None:
                emit(delta, finish)
            cursor = max(cursor, limit)

        for t in replay:
            feed(t)
        n_comp = len(replay)
        stopped = False
        for t in ticket.tokens():
            if is_aborted is not None and is_aborted():
                ticket.cancel("aborted")
                break
            n_comp += 1
            feed(t)
            # global stop-scan: a stop wholly inside the exporter's
            # already-emitted prefix cannot exist (its own hold-back scan
            # would have fired), so any cut found here is new text
            cuts = [c for c in (full.find(s) for s in stop) if c != -1]
            if cuts:
                ticket.cancel("stop")
                flush(min(cuts), "stop")
                stopped = True
                break
            flush(len(full) - (hold - 1) if hold else len(full))
        if stopped:
            return full[:cursor], n_comp, "stop"
        finish = ticket.finish or "aborted"
        full += dec.decode(b"", True)
        cuts = [c for c in (full.find(s) for s in stop) if c != -1]
        limit = len(full)
        if cuts:
            limit = min(cuts)
            finish = "stop"
        flush(limit, finish)
        return full[:limit], n_comp, finish


def make_handler(state: ApiState):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # socket read/write timeout (satellite fix: the reference-shaped
        # bug was a blocking read with no timeout wedging the server —
        # socket.cpp; here a stalled peer costs one 408/disconnect, never
        # a hung thread).  BaseRequestHandler.setup() applies it.
        timeout = state.io_timeout if state.io_timeout > 0 else None

        def log_message(self, fmt, *a):
            _log.debug("http", extra={"method": self.command,
                                      "path": self.path})

        def send_response(self, *a, **kw):
            self._began_response = True
            super().send_response(*a, **kw)

        def _begin_request(self) -> str:
            """Assign the request ID at accept time: a client-supplied
            ``X-Request-Id`` is echoed (sanitized — it lands in logs and
            response headers verbatim) else one is generated.  Set into
            the log contextvar so every record on this thread — server,
            engine, faults, snapshot — carries it."""
            client = self.headers.get("X-Request-Id") or ""
            rid = _RID_RE.sub("", client)[:_RID_MAX] or new_request_id()
            self._rid = rid
            # router→replica hops stamp X-Dllama-Hop (the router's hop
            # id) so this replica's flight record for the request links
            # back to the router-side ring (fleet correlation satellite)
            hop = self.headers.get("X-Dllama-Hop") or ""
            self._hop = _RID_RE.sub("", hop)[:_RID_MAX] or None
            # fleet trace context (X-Dllama-Trace): the router stamps
            # one id at accept and propagates it on every hop; binding
            # it to the rid here means scheduler-loop spans (recorded
            # with rid=t.rid) resolve to the same trace without any
            # call-site change, and DLREQ01 exports can carry it to the
            # replica that resumes the request.
            trace = obs_trace.sanitize_trace_id(
                self.headers.get("X-Dllama-Trace"))
            self._trace = trace
            obs_trace.trace_id_var.set(trace)
            if trace:
                obs_trace.set_trace(rid, trace)
            # QoS class from the transport header; the body field (when
            # present) overrides it in do_POST.  An unknown header value
            # is ignored — the router relays client headers verbatim and
            # a typo'd class must not fail the request.
            hdr = self.headers.get("X-Dllama-Priority")
            self._prio_hdr = priority_level(hdr) if hdr else None
            set_request_id(rid)
            return rid

        def _rid_header(self) -> None:
            rid = getattr(self, "_rid", None)
            if rid:
                self.send_header("X-Request-Id", rid)
            trace = getattr(self, "_trace", None)
            if trace:
                self.send_header("X-Dllama-Trace", trace)

        def _json(self, code: int, obj: dict, headers: dict | None = None):
            data = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self._rid_header()
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            if state.draining:
                # drain wants connection threads gone promptly, not
                # parked in keep-alive reads until the io timeout
                self.close_connection = True
            self.end_headers()
            try:
                self.wfile.write(data)
            except OSError:
                self.close_connection = True

        def _text(self, code: int, text: str, content_type: str):
            data = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self._rid_header()
            if state.draining:
                self.close_connection = True
            self.end_headers()
            try:
                self.wfile.write(data)
            except OSError:
                self.close_connection = True

        def _bytes(self, code: int, data: bytes, content_type: str):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self._rid_header()
            if state.draining:
                self.close_connection = True
            self.end_headers()
            try:
                self.wfile.write(data)
            except OSError:
                self.close_connection = True

        def _safe_write(self, data: bytes, aborted: list) -> None:
            """Stream-tail write that treats a dead client as abort, not
            as an unhandled thread exception."""
            if aborted[0]:
                return
            try:
                self.wfile.write(data)
                self.wfile.flush()
            except OSError:
                aborted[0] = True
                state.metrics.bump("client_disconnects")

        def _maybe_500(self, err: Exception) -> None:
            """Answer 500 if no response has started (a mid-stream failure
            already has its own SSE error-event path)."""
            if getattr(self, "_began_response", False):
                return
            try:
                self._json(500, {"error": {"message": str(err),
                                           "type": "server_error"}})
            except OSError:
                pass

        def _read_body(self) -> dict | None:
            """Read + parse the JSON body.  Returns None when a response
            (408/400/413) was already sent or the client vanished.  The
            ``server.read_body`` fault point stands in for a stalled
            client (a delay outlasting ``--io-timeout``, or
            ``raise:TimeoutError`` directly)."""
            try:
                FAULTS.fire("server.read_body")
                length = int(self.headers.get("Content-Length", 0) or 0)
                if length > MAX_BODY_BYTES:
                    self.close_connection = True
                    self._json(413, {"error": "request body too large"})
                    return None
                raw = self.rfile.read(length) if length > 0 else b""
                if len(raw) < length:  # peer closed mid-body
                    state.metrics.bump("client_disconnects")
                    self.close_connection = True
                    return None
            except TimeoutError:  # socket.timeout alias: stalled client
                state.metrics.bump("read_timeouts_408")
                self.close_connection = True
                self._json(408, {"error": "timed out reading request body"})
                return None
            except (TypeError, ValueError):
                self._json(400, {"error": "bad Content-Length"})
                return None
            try:
                body = json.loads(raw or b"{}")
            except json.JSONDecodeError as e:
                self._json(400, {"error": f"bad request: {e}"})
                return None
            if not isinstance(body, dict):
                self._json(400, {"error": "request body must be a JSON object"})
                return None
            return body

        def _completions(self, body: dict, deadline: float | None,
                         timer: _StreamTimer | None = None):
            """OpenAI text-completion endpoint; ``prompt`` may be a list
            and ``n`` replicates each prompt — every resulting row decodes
            as a distinct stream in one lockstep batch."""
            try:
                prompt = body.get("prompt")
                prompts = [str(p) for p in prompt] if isinstance(prompt, list) \
                    else [str(prompt or "")]
                if not any(prompts):
                    self._json(400, {"error": "prompt required"})
                    return
                n = int(body.get("n") or 1)
                if n > 1:  # n samples per prompt, row-major like OpenAI
                    prompts = [p for p in prompts for _ in range(n)]
                temperature = float(body["temperature"]) \
                    if body.get("temperature") is not None else state.default_temperature
                top_p = float(body["top_p"]) \
                    if body.get("top_p") is not None else state.default_topp
                max_tokens = int(body.get("max_tokens") or 0)
                seed = int(body["seed"]) if body.get("seed") is not None else None
                stop = body.get("stop")
                stop = [stop] if isinstance(stop, str) else \
                    [str(s) for s in stop] if isinstance(stop, list) else []
                echo = bool(body.get("echo"))
                stream = bool(body.get("stream"))
                logprobs = body.get("logprobs")
                if logprobs is not None:
                    logprobs = max(0, min(int(logprobs), 5))  # OpenAI cap
            except (TypeError, ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            if stream and logprobs is not None:
                self._json(400, {"error": "logprobs with stream is not "
                                          "supported; request them "
                                          "non-streaming"})
                return
            if state.batch_engine is None:
                self._json(400, {"error": "batched serving not enabled; "
                                          "start the server with --batch-slots N"})
                return
            if logprobs is not None and state.batch_engine.sp > 1:
                # reject BEFORE the generation forward: score_batch raises
                # on sp meshes, and the handler must answer 400, not drop
                # the connection after burning the decode
                self._json(400, {"error": "logprobs is not supported on "
                                          "sequence-parallel (--sp) servers"})
                return
            created = int(time.time())
            cid = f"cmpl-{uuid.uuid4().hex[:12]}"
            if stream:
                # validate BEFORE committing to SSE: an invalid request
                # gets the same 400 it would get without stream=true
                try:
                    plan = state.plan_batch(prompts, max_tokens)
                except ContextOverflow as e:
                    self._json(400, state.overflow_body(e))
                    return
                # SSE chunks carry per-row deltas tagged by choice index —
                # every live row streams concurrently from the one
                # lockstep batch (echo is a non-streaming nicety; ignored)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self._rid_header()
                self.end_headers()

                aborted = [False]

                def emit(idx, delta, finish):
                    # a dead client mid-stream flips `aborted`; the batch
                    # loop polls it (is_aborted) and stops decoding at the
                    # next chunk instead of generating into a broken pipe
                    if aborted[0]:
                        return
                    try:
                        e0 = time.perf_counter()
                        FAULTS.fire("server.emit_delta")
                        chunk = {"id": cid, "object": "text_completion",
                                 "created": created, "model": state.model_name,
                                 "choices": [{"text": delta, "index": idx,
                                              "finish_reason": finish,
                                              "logprobs": None}]}
                        self.wfile.write(
                            f"data: {json.dumps(chunk)}\n\n".encode())
                        self.wfile.flush()
                        obs_trace.record("emit", e0, time.perf_counter(),
                                         idx=idx)
                        if timer is not None:
                            timer.tick()
                        if finish == "timeout":
                            state.metrics.bump("deadline_timeouts")
                    except OSError:
                        aborted[0] = True
                        state.metrics.bump("client_disconnects")

                try:
                    state.complete_batch_stream(
                        prompts, temperature=temperature, top_p=top_p,
                        max_tokens=max_tokens, seed=seed, stop=stop,
                        emit=emit, plan=plan, deadline=deadline,
                        is_aborted=lambda: aborted[0])
                except Exception as e:
                    # mid-stream failure: an OpenAI-shaped error event so
                    # clients can tell a died stream from a short success,
                    # then [DONE] (they block on it); unexpected errors
                    # still propagate to the server log afterwards
                    err = {"error": {"message": str(e),
                                     "type": "invalid_request_error"
                                     if isinstance(e, ContextOverflow)
                                     else "server_error"}}
                    self._safe_write(f"data: {json.dumps(err)}\n\n".encode()
                                     + b"data: [DONE]\n\n", aborted)
                    if not isinstance(e, ContextOverflow):
                        raise
                    return
                self._safe_write(b"data: [DONE]\n\n", aborted)
                return
            try:
                choices, n_prompt, n_completion = state.complete_batch(
                    prompts, temperature=temperature, top_p=top_p,
                    max_tokens=max_tokens, seed=seed, stop=stop, echo=echo,
                    logprobs=logprobs, deadline=deadline)
            except ContextOverflow as e:
                self._json(400, state.overflow_body(e))
                return
            if any(c["finish_reason"] == "timeout" for c in choices):
                state.metrics.bump("deadline_timeouts")
            self._json(200, {
                "id": cid,
                "object": "text_completion", "created": created,
                "model": state.model_name, "choices": choices,
                "usage": {"prompt_tokens": n_prompt,
                          "completion_tokens": n_completion,
                          "total_tokens": n_prompt + n_completion}})

        def do_GET(self):
            self._begin_request()
            path, _, query = self.path.partition("?")
            if path == "/v1/models":
                self._json(200, {"object": "list", "data": [{
                    "id": state.model_name, "object": "model",
                    "created": int(time.time()), "owned_by": "user"}]})
            elif path in ("/health", "/healthz"):
                # liveness probes keep getting a 200 during drain (the
                # process IS alive); orchestrators read "status"/"ready"
                # for the readiness decision
                self._json(200, state.health())
            elif path == "/metrics":
                # one registry, two formats (obs/metrics.py): Prometheus
                # text 0.0.4 under Accept/?format negotiation, else the
                # backward-compatible JSON dict — registry globals
                # (integrity counters, histograms, schema_version) with
                # this server's per-instance serving counters on top
                q = parse_qs(query)
                accept = self.headers.get("Accept") or ""
                if (q.get("format", [""])[0] == "prometheus"
                        or "text/plain" in accept or "openmetrics" in accept):
                    self._text(200, obs_metrics.render_prometheus(),
                               "text/plain; version=0.0.4; charset=utf-8")
                else:
                    merged = obs_metrics.snapshot_json()
                    merged.update(state.metrics.snapshot())
                    self._json(200, merged)
            elif path == "/debug/trace":
                # Chrome trace_event JSON for the last N requests' spans
                # (obs/trace.py ring buffer; tools/trace_dump.py wraps
                # this).  ?since=<seq> switches to the raw incremental
                # export — sequenced spans plus a perf/wall clock sample
                # — which the router's fleet stitcher and fleet_top poll
                # instead of re-downloading the whole ring every tick.
                qs = parse_qs(query)
                if "since" in qs:
                    try:
                        since = int(qs["since"][0])
                    except ValueError:
                        since = 0
                    self._json(200, obs_trace.raw(since))
                    return
                try:
                    last = int(q[0]) if (q := qs.get("last")) else 20
                except ValueError:
                    last = 20
                self._json(200, obs_trace.trace_json(last))
            elif path == "/debug/events":
                # the pod event journal (obs/events.py): this replica's
                # own lifecycle events (preempt/resume/handoff); the
                # router/pod process serves its fleet-level journal at
                # the same path.  ?since=<seq> tails incrementally.
                qs = parse_qs(query)
                since = None
                if "since" in qs:
                    try:
                        since = int(qs["since"][0])
                    except ValueError:
                        since = 0
                self._json(200, obs_events.snapshot(since))
            elif path == "/debug/requests":
                # flight recorder (obs/flight.py): newest-first summaries
                try:
                    n = int(q[0]) if (q := parse_qs(query).get("n")) else 50
                except ValueError:
                    n = 50
                self._json(200, {"requests": obs_flight.recent(n)})
            elif path.startswith("/debug/requests/"):
                rid = path[len("/debug/requests/"):]
                rec = obs_flight.get(rid)
                if rec is None:
                    self._json(404, {"error": f"no flight record for "
                                              f"request id {rid!r}"})
                else:
                    self._json(200, rec)
            elif path.startswith("/admin/export/"):
                # drain-time hand-off pickup (fleet router): one-shot —
                # the record leaves this process with the response, so a
                # double-fetch cannot resume the same request twice
                rid = path[len("/admin/export/"):]
                rec = state.handoff_records.pop(rid, None)
                if rec is None:
                    self._json(404, {"error": f"no hand-off record for "
                                              f"request id {rid!r}"})
                else:
                    obs_metrics.HANDOFF_EXPORTS.inc()
                    _log.info("handoff_export_served", extra={
                        "bytes": len(rec)})
                    self._bytes(200, rec, "application/octet-stream")
            elif path.startswith("/admin/checkpoint/"):
                # proactive mid-stream checkpoint (fleet router crash
                # resume): a NON-destructive DLREQ01 snapshot of one
                # live slot — the request keeps decoding here.  Unlike
                # /admin/export this is repeatable; the router caches
                # the newest record and resumes from it if this replica
                # later dies ungracefully.
                rid = path[len("/admin/checkpoint/"):]
                if not state.handoff:
                    self._json(404, {"error": "hand-off is not enabled "
                                              "(--handoff)"})
                    return
                try:
                    rec = state.scheduler.checkpoint_export(rid)
                except Exception as e:  # noqa: BLE001 — a failed
                    # checkpoint must never take down the live request
                    _log.warning("checkpoint_export_failed", extra={
                        "rid": rid, "error": repr(e)})
                    rec = None
                if rec is None:
                    self._json(404, {"error": f"no live slot for "
                                              f"request id {rid!r}"})
                else:
                    _log.debug("checkpoint_export_served", extra={
                        "rid": rid, "bytes": len(rec)})
                    self._bytes(200, rec, "application/octet-stream")
            elif path == "/debug/timeline":
                # slot timeline + goodput decomposition (obs/flight.py +
                # scheduler accounting); trace_dump.py --slots renders it
                try:
                    n = int(q[0]) if (q := parse_qs(query).get("n")) \
                        else 256
                except ValueError:
                    n = 256
                self._json(200, {
                    "slots": (state.scheduler.engine.batch
                              if state.scheduler is not None else 0),
                    "steps": obs_flight.TIMELINE.snapshot(n),
                    "components_ms":
                        obs_metrics.SCHED_STEP_TIME_MS.json_value(),
                    "goodput_ratio":
                        obs_metrics.SCHED_GOODPUT_RATIO.json_value(),
                    "host_gap_ms":
                        obs_metrics.SCHED_HOST_GAP_MS.json_value(),
                })
            else:
                self._json(404, {"error": "not found"})

        def _debug_profile(self, query: str):
            """``POST /debug/profile?steps=N&top=K`` — live per-op device
            profile of the serving engine (docs/OBSERVABILITY.md).

            Holds the engine mutex, traces N single-token decode steps
            under the XLA profiler (runtime/profiling.traced_op_times) and
            answers with the top-K ops by device time plus the
            compute/collective split.  POST (not GET) because it perturbs
            the serving engine: it borrows the mutex for ~N steps and
            advances/rewinds the KV position.  Answers 503 while draining
            and a clean 503 when the xplane proto tooling is absent."""
            from ..runtime.profiling import summarize_split, top_ops, \
                traced_op_times
            if state.draining:
                self._json(503, {"error": "server is draining"},
                           headers={"Retry-After": jittered_retry_after(30)})
                return
            q = parse_qs(query)

            def qint(name, default, lo, hi):
                try:
                    v = int(q.get(name, [default])[0])
                except ValueError:
                    v = default
                return max(lo, min(hi, v))

            steps = qint("steps", 3, 1, 16)
            top = qint("top", 10, 1, 50)
            eng = state.engine
            with state.engine_lock:
                state.mark_active(True)
                try:
                    if eng.pos + steps + 1 > eng.seq_len:
                        # no room to decode: drop the conversation state
                        # (debug endpoint; same reset path as NumericFault)
                        state.naive_cache.clear()
                        eng.reset()
                    pos0 = eng.pos
                    try:
                        # warm step OUTSIDE the trace so a fresh T=1
                        # executable books compile time into the compile
                        # histogram, not into the op profile
                        eng.decode_one(1)
                        times = traced_op_times(
                            lambda: eng.decode_one(1), steps=steps)
                    finally:
                        # profiled steps are dead rows past the live
                        # prefix — same overshoot invariant as an aborted
                        # generation
                        eng.pos = pos0
                finally:
                    state.mark_active(False)
            if times is None:
                self._json(503, {
                    "error": "per-op profiling unavailable (xplane proto "
                             "tooling missing or backend produced no "
                             "trace)"})
                return
            split = summarize_split(times, steps)
            ops = [{"op": op, "ms": round(ms, 4)}
                   for op, ms in top_ops(times, top, steps)]
            _log.info("profile", extra={"steps": steps,
                                        "n_ops": len(times)})
            self._json(200, {
                "steps": steps,
                "devices": eng.mesh.size,
                "compute_ms": round(split["compute_ms"], 4),
                "collective_ms": round(split["collective_ms"], 4),
                "collective_pct": round(split["collective_pct"], 2),
                "ops": ops,
            })

        def _sched_eligible(self, body: dict) -> bool:
            """True when this request can ride the slot scheduler
            (tentpole: decode-step admission instead of the engine
            mutex).  The mutex path keeps everything the slot engine
            cannot express: multi-prompt lockstep, n>1, logprobs scoring,
            echo, and seeded sampling (slot rows share the engine's RNG
            stream, so per-request seeds are only reproducible when the
            request owns the engine — greedy requests are exact on both
            paths)."""
            if state.scheduler is None:
                return False
            try:
                if int(body.get("n") or 1) != 1:
                    return False
                temperature = float(body["temperature"]) \
                    if body.get("temperature") is not None \
                    else state.default_temperature
            except (TypeError, ValueError):
                return False  # malformed: the mutex handlers own the 400
            if body.get("seed") is not None and temperature != 0.0:
                return False
            if self.path == "/v1/completions":
                return not isinstance(body.get("prompt"), list) \
                    and body.get("logprobs") is None \
                    and not body.get("echo")
            return True

        def _submit_or_reject(self, ids, max_tokens, *, temperature,
                              top_p, eos_id, deadline, stop=None):
            """sched_submit with every refusal mapped to its HTTP answer
            (the same codes the mutex path's admission uses).  Returns
            the ticket, or None when a response was already sent."""
            try:
                return state.sched_submit(
                    ids, max_tokens, temperature=temperature, top_p=top_p,
                    eos_id=eos_id, deadline=deadline, stop=stop,
                    priority=getattr(self, "_priority", 1))
            except ContextOverflow as e:
                self._json(400, state.overflow_body(e))
            except SchedulerSaturated as e:
                state.metrics.bump("requests_rejected_429")
                self._json(429, state.overflow_body(e),
                           headers={"Retry-After": jittered_retry_after(
                               state.retry_after_hint())})
            except SchedulerClosed:
                state.metrics.bump("requests_rejected_503")
                self._json(503, {"error": "server is draining; "
                                          "no new requests accepted"},
                           headers={"Retry-After": jittered_retry_after(30)})
            return None

        def _completions_sched(self, body: dict, deadline: float | None,
                               timer: _StreamTimer | None = None):
            """Single-prompt /v1/completions over the slot scheduler:
            joins a batch slot at the next decode-step boundary instead
            of waiting for the engine mutex."""
            try:
                prompt = body.get("prompt")
                text = str(prompt or "")
                if not text:
                    self._json(400, {"error": "prompt required"})
                    return
                temperature = float(body["temperature"]) \
                    if body.get("temperature") is not None \
                    else state.default_temperature
                top_p = float(body["top_p"]) \
                    if body.get("top_p") is not None else state.default_topp
                max_tokens = int(body.get("max_tokens") or 0)
                stop = body.get("stop")
                stop = [stop] if isinstance(stop, str) else \
                    [str(s) for s in stop] if isinstance(stop, list) else []
                stream = bool(body.get("stream"))
            except (TypeError, ValueError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            tok = state.tokenizer
            ids = tok.encode(text,
                             add_bos=state.scheduler.engine.cfg.add_bos)
            eos_id = tok.eos_id if tok.eos_id >= 0 else tok.chat_eos_id
            # submit BEFORE any SSE commitment so capacity/overflow
            # refusals answer with their proper status codes
            ticket = self._submit_or_reject(
                ids, max_tokens, temperature=temperature, top_p=top_p,
                eos_id=eos_id, deadline=deadline, stop=stop)
            if ticket is None:
                return
            created = int(time.time())
            cid = f"cmpl-{uuid.uuid4().hex[:12]}"
            if stream:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self._rid_header()
                self.end_headers()
                aborted = [False]

                def emit(delta, finish):
                    if aborted[0]:
                        return
                    try:
                        e0 = time.perf_counter()
                        FAULTS.fire("server.emit_delta")
                        chunk = {"id": cid, "object": "text_completion",
                                 "created": created,
                                 "model": state.model_name,
                                 "choices": [{"text": delta, "index": 0,
                                              "finish_reason": finish,
                                              "logprobs": None}]}
                        self.wfile.write(
                            f"data: {json.dumps(chunk)}\n\n".encode())
                        self.wfile.flush()
                        obs_trace.record("emit", e0, time.perf_counter())
                        if timer is not None:
                            timer.tick()
                        if finish == "timeout":
                            state.metrics.bump("deadline_timeouts")
                    except OSError:
                        aborted[0] = True
                        state.metrics.bump("client_disconnects")

                try:
                    state.sched_drain(ticket, ids[-1], stop=stop,
                                      emit=emit,
                                      is_aborted=lambda: aborted[0])
                except Exception as e:
                    ticket.cancel("aborted")
                    err = {"error": {"message": str(e),
                                     "type": "server_error"}}
                    self._safe_write(f"data: {json.dumps(err)}\n\n".encode()
                                     + b"data: [DONE]\n\n", aborted)
                    raise
                self._safe_write(b"data: [DONE]\n\n", aborted)
                return
            emit = (lambda d, f: timer.tick()) if timer is not None \
                else (lambda d, f: None)
            try:
                reply, n_comp, finish = state.sched_drain(
                    ticket, ids[-1], stop=stop, emit=emit)
            finally:
                ticket.cancel("aborted")  # no-op unless we errored out
            if finish == "timeout":
                state.metrics.bump("deadline_timeouts")
            self._json(200, {
                "id": cid, "object": "text_completion", "created": created,
                "model": state.model_name,
                "choices": [{"text": reply, "index": 0,
                             "finish_reason": finish, "logprobs": None}],
                "usage": {"prompt_tokens": len(ids),
                          "completion_tokens": n_comp,
                          "total_tokens": len(ids) + n_comp}})

        def _chat_sched(self, body: dict, deadline: float | None,
                        timer: _StreamTimer | None = None):
            """Chat over the slot scheduler.  Without prefix reuse this
            is the spillover path (a second concurrent conversation joins
            a batch slot instead of queueing on the engine mutex) and the
            slot engine re-prefills the full templated history each turn.
            With the paged radix cache it is the PRIMARY chat path: the
            scheduler matches the templated history against the tree at
            admission, binds the already-cached prefix pages copy-free,
            and prefills only the new suffix — the NaiveCache's
            prefix-resume win, but shared across conversations and
            requiring no mutex.  The NaiveCache itself is neither
            consulted nor updated here."""
            try:
                params = parse_request(body, state.default_temperature,
                                       state.default_topp)
                if not params.messages:
                    self._json(400, {"error": "messages required"})
                    return
            except (TypeError, ValueError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return
            tok = state.tokenizer
            items = [ChatItem(m.role, m.content) for m in params.messages]
            ids = tok.encode(state.template.generate(items, True),
                             add_bos=True)
            stops = state.base_stops + params.stop
            ticket = self._submit_or_reject(
                ids, params.max_tokens, temperature=params.temperature,
                top_p=params.top_p, eos_id=tok.chat_eos_id,
                deadline=deadline, stop=stops)
            if ticket is None:
                return
            created = int(time.time())
            cid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
            if params.stream:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self._rid_header()
                self.end_headers()
                aborted = [False]

                def emit(delta, finish):
                    if aborted[0] or not delta:
                        return
                    try:
                        e0 = time.perf_counter()
                        FAULTS.fire("server.emit_delta")
                        chunk = {"id": cid,
                                 "object": "chat.completion.chunk",
                                 "created": created,
                                 "model": state.model_name,
                                 "choices": [{"index": 0,
                                              "delta": {"content": delta},
                                              "finish_reason": None}]}
                        self.wfile.write(
                            f"data: {json.dumps(chunk)}\n\n".encode())
                        self.wfile.flush()
                        obs_trace.record("emit", e0, time.perf_counter())
                        if timer is not None:
                            timer.tick()
                    except OSError:
                        aborted[0] = True
                        state.metrics.bump("client_disconnects")

                _, _, finish = state.sched_drain(
                    ticket, ids[-1], stop=stops, emit=emit,
                    is_aborted=lambda: aborted[0])
                if finish == "aborted" or aborted[0]:
                    return  # nobody is listening
                if finish == "length":
                    finish = "stop"  # the chat budget contract (complete())
                if finish == "timeout":
                    state.metrics.bump("deadline_timeouts")
                final = {"id": cid, "object": "chat.completion.chunk",
                         "created": created, "model": state.model_name,
                         "choices": [{"index": 0, "delta": {},
                                      "finish_reason": finish}]}
                self._safe_write(f"data: {json.dumps(final)}\n\n".encode()
                                 + b"data: [DONE]\n\n", aborted)
                return
            emit = (lambda d, f: timer.tick()) if timer is not None \
                else (lambda d, f: None)
            reply, n_comp, finish = state.sched_drain(
                ticket, ids[-1], stop=stops, emit=emit)
            if finish == "length":
                finish = "stop"
            if finish == "timeout":
                state.metrics.bump("deadline_timeouts")
            self._json(200, {
                "id": cid, "object": "chat.completion", "created": created,
                "model": state.model_name,
                "choices": [{"index": 0, "finish_reason": finish,
                             "message": {"role": "assistant",
                                         "content": reply}}],
                "usage": {"prompt_tokens": len(ids),
                          "completion_tokens": n_comp,
                          "total_tokens": len(ids) + n_comp}})

        def _admin_import(self, query: str):
            """``POST /admin/import?emitted_chars=N`` — re-bind a DLREQ01
            hand-off record (octet-stream body) into a free slot and
            stream the request's remaining completion back as
            text_completion-shaped SSE deltas (the router adapts the
            shape for chat/non-streaming clients).  ``emitted_chars`` is
            how many completion characters the router already forwarded
            to the client from the exporting replica; only text beyond
            it is emitted.  409 on geometry mismatch so the router can
            try another peer."""
            if not state.handoff:
                self._json(404, {"error": "hand-off is not enabled "
                                          "(--handoff)"})
                return
            q = parse_qs(query)
            try:
                emitted_chars = max(0, int(q.get("emitted_chars",
                                                 ["0"])[0]))
            except ValueError:
                emitted_chars = 0
            try:
                length = int(self.headers.get("Content-Length", 0) or 0)
            except (TypeError, ValueError):
                self._json(400, {"error": "bad Content-Length"})
                return
            if length > MAX_HANDOFF_BYTES:
                self.close_connection = True
                self._json(413, {"error": "hand-off record too large"})
                return
            if length <= 0:
                self._json(400, {"error": "hand-off record body required"})
                return
            try:
                raw = self.rfile.read(length)
            except TimeoutError:
                state.metrics.bump("read_timeouts_408")
                self.close_connection = True
                self._json(408, {"error": "timed out reading hand-off "
                                          "record"})
                return
            if len(raw) < length:
                state.metrics.bump("client_disconnects")
                self.close_connection = True
                return
            try:
                ticket, extra = state.scheduler.import_request(raw)
            except SnapshotMismatch as e:
                obs_metrics.HANDOFF_IMPORT_REJECTS.inc()
                self._json(409, {"error": str(e)})
                return
            except ArtifactError as e:
                obs_metrics.HANDOFF_IMPORT_REJECTS.inc()
                self._json(400, {"error": str(e)})
                return
            except ContextOverflow as e:
                self._json(400, state.overflow_body(e))
                return
            except SchedulerSaturated as e:
                state.metrics.bump("requests_rejected_429")
                self._json(429, state.overflow_body(e),
                           headers={"Retry-After": jittered_retry_after(
                               state.retry_after_hint())})
                return
            except SchedulerClosed:
                state.metrics.bump("requests_rejected_503")
                self._json(503, {"error": "server is draining; "
                                          "no new requests accepted"},
                           headers={"Retry-After": jittered_retry_after(30)})
                return
            obs_metrics.HANDOFF_IMPORTS.inc()
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self._rid_header()
            self.end_headers()
            aborted = [False]

            def emit(delta, finish):
                if aborted[0]:
                    return
                try:
                    chunk = {"object": "text_completion",
                             "model": state.model_name,
                             "choices": [{"text": delta, "index": 0,
                                          "finish_reason": finish,
                                          "logprobs": None}]}
                    self.wfile.write(
                        f"data: {json.dumps(chunk)}\n\n".encode())
                    self.wfile.flush()
                except OSError:
                    aborted[0] = True
                    state.metrics.bump("client_disconnects")

            state.mark_active(True)
            try:
                text, n_comp, finish = state.handoff_resume(
                    ticket, extra, emitted_chars, emit,
                    is_aborted=lambda: aborted[0])
            except Exception as e:
                ticket.cancel("aborted")
                err = {"error": {"message": str(e),
                                 "type": "server_error"}}
                self._safe_write(f"data: {json.dumps(err)}\n\n".encode()
                                 + b"data: [DONE]\n\n", aborted)
                raise
            finally:
                state.mark_active(False)
            usage = {"object": "handoff.usage",
                     "usage": {"prompt_tokens": len(extra.get("prompt")
                                                    or []),
                               "completion_tokens": n_comp,
                               "finish_reason": finish}}
            self._safe_write(f"data: {json.dumps(usage)}\n\n".encode()
                             + b"data: [DONE]\n\n", aborted)

        def do_POST(self):
            self._begin_request()
            ppath, _, pquery = self.path.partition("?")
            if ppath == "/debug/profile":
                self._debug_profile(pquery)
                return
            if ppath == "/admin/import":
                self._admin_import(pquery)
                return
            if self.path not in ("/v1/chat/completions", "/v1/completions"):
                self._json(404, {"error": "not found"})
                return
            _log.info("accept", extra={"path": self.path})
            body = self._read_body()
            if body is None:
                return
            # QoS class: body field wins over X-Dllama-Priority, default
            # standard.  A malformed body value is a 400 (the header is
            # lenient; the body is the caller's explicit contract).
            prio_body = body.get("priority")
            if prio_body is not None:
                lvl = priority_level(prio_body)
                if lvl is None:
                    self._json(400, {
                        "error": f"unknown priority class {prio_body!r}; "
                                 "expected interactive|standard|batch"})
                    return
                self._priority = lvl
            else:
                self._priority = self._prio_hdr \
                    if self._prio_hdr is not None \
                    else PRIORITY_LEVELS["standard"]
            prio_name = PRIORITY_NAMES.get(self._priority, "standard")
            # SLO-driven shedding: drop best-effort admissions while the
            # error budget burns, BEFORE this request counts against
            # capacity (interactive traffic is never shed here)
            if state.should_shed(self._priority):
                state.metrics.bump("requests_rejected_429")
                obs_metrics.ADMISSIONS_SHED.inc(prio_name)
                _log.info("reject", extra={"status": 429,
                                           "reason": "slo_shed",
                                           "priority": prio_name})
                self._json(429, {"error": "SLO error budget burning; "
                                          f"shedding {prio_name}-class "
                                          "admissions — retry later"},
                           headers={"Retry-After": jittered_retry_after(
                               state.retry_after_hint())})
                return
            verdict = state.try_enter()
            if verdict == "draining":
                state.metrics.bump("requests_rejected_503")
                _log.info("reject", extra={"status": 503,
                                           "reason": "draining"})
                self._json(503, {"error": "server is draining; "
                                          "no new requests accepted"},
                           headers={"Retry-After": jittered_retry_after(30)})
                return
            if verdict == "full":
                state.metrics.bump("requests_rejected_429")
                _log.info("reject", extra={"status": 429, "reason": "full"})
                self._json(429, {"error": f"server at capacity "
                                          f"({state.max_pending} requests "
                                          "pending); retry later"},
                           headers={"Retry-After": jittered_retry_after(
                               state.retry_after_hint())})
                return
            t0 = time.monotonic()
            tp0 = time.perf_counter()
            deadline = state.request_deadline(body)
            # stream timer starts at admission: queue wait counts into TTFT
            timer = _StreamTimer(rid=self._rid)
            # flight record opens at admission; the scheduler path merges
            # its per-dispatch detail into this same record by request ID
            # (hop = the router's ring id, for cross-fleet correlation)
            if getattr(self, "_hop", None):
                obs_flight.submit(self._rid, path=self.path, hop=self._hop,
                                  priority=prio_name)
            else:
                obs_flight.submit(self._rid, path=self.path,
                                  priority=prio_name)
            ok = False
            try:
                locked = False
                use_sched = False
                if self._sched_eligible(body):
                    if self.path == "/v1/completions":
                        use_sched = True
                    elif state.scheduler.prefix_cache is not None:
                        # paged scheduler with a radix prefix cache: chat
                        # always rides a slot — repeated system prompts and
                        # growing conversation histories match the tree and
                        # bind shared pages copy-free, which beats the
                        # mutex path's single-conversation NaiveCache (and
                        # the old spillover behavior of re-prefilling the
                        # full history on every contended request)
                        use_sched = True
                    else:
                        # chat spillover: the mutex path keeps the
                        # NaiveCache prefix-resume win while uncontended;
                        # under contention the request joins a slot
                        # instead of queueing on the mutex
                        locked = state.engine_lock.acquire(blocking=False)
                        use_sched = not locked
                if use_sched:
                    # slot path: no engine mutex — the scheduler
                    # interleaves this request with whatever else is live
                    # (its sched_admit span records the slot-queue wait)
                    state.mark_active(True)
                    try:
                        if self.path == "/v1/completions":
                            self._completions_sched(body, deadline, timer)
                        else:
                            self._chat_sched(body, deadline, timer)
                    finally:
                        state.mark_active(False)
                else:
                    # THE engine mutex: one generation at a time per KV
                    # cache; the wait here IS the admission queue
                    # try_enter bounded
                    q0 = time.perf_counter()
                    if not locked:
                        state.engine_lock.acquire()
                    q1 = time.perf_counter()
                    obs_metrics.QUEUE_WAIT.observe(q1 - q0)
                    obs_trace.record("queue_wait", q0, q1)
                    obs_flight.admit(self._rid, queued_ms=(q1 - q0) * 1e3)
                    _log.info("queue", extra={"wait_s": round(q1 - q0, 6)})
                    try:
                        state.mark_active(True)
                        try:
                            if self.path == "/v1/completions":
                                self._completions(body, deadline, timer)
                            else:
                                self._chat(body, deadline, timer)
                        finally:
                            state.mark_active(False)
                    finally:
                        state.engine_lock.release()
                state.metrics.bump("requests_served")
                ok = True
                _log.info("finish", extra={
                    "path": self.path,
                    "duration_s": round(time.monotonic() - t0, 6)})
            except (BrokenPipeError, ConnectionResetError):
                # client gone between chunks with nothing left to send;
                # generation already stopped via the abort flag
                state.metrics.bump("client_disconnects")
                self.close_connection = True
                _log.info("client_disconnect", extra={"path": self.path})
            except NumericFault as e:
                # NaN/Inf logits (--numeric-checks): the KV cache may be
                # poisoned from the step that diverged, so resume is NOT
                # safe — drop the conversation cache and position instead
                # of serving garbage continuations.  The request gets a
                # 500 (counted in numeric_faults via the engine) and the
                # server keeps serving fresh conversations.
                state.metrics.bump("server_errors")
                state.naive_cache.clear()
                state.engine.reset()
                self._maybe_500(e)
                _log.error("error", extra={"path": self.path,
                                           "kind": "NumericFault",
                                           "error": str(e)})
                raise  # surface in the server log — corruption is a page
            except Exception as e:
                state.metrics.bump("server_errors")
                self._maybe_500(e)
                _log.error("error", extra={"path": self.path,
                                           "kind": type(e).__name__,
                                           "error": str(e)})
                raise  # surface in the server log — a 500 is a bug to fix
            finally:
                state.leave(time.monotonic() - t0)
                obs_trace.record("request", tp0, time.perf_counter(),
                                 path=self.path)
                # fallback close for any path that didn't retire with a
                # specific finish (no-op when one already did)
                obs_flight.retire(self._rid, "served" if ok else "error")

        def _chat(self, body: dict, deadline: float | None,
                  timer: _StreamTimer | None = None):
            try:
                params = parse_request(body, state.default_temperature,
                                       state.default_topp)
                if not params.messages:
                    self._json(400, {"error": "messages required"})
                    return
            except (TypeError, ValueError) as e:
                self._json(400, {"error": f"bad request: {e}"})
                return

            created = int(time.time())
            cid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
            if params.n > 1:
                if params.stream:
                    self._json(400, {"error": "stream with n>1 is not "
                                              "supported; request them "
                                              "separately"})
                    return
                if state.batch_engine is None:
                    self._json(400, {"error": "n>1 needs batched serving; "
                                              "start the server with "
                                              "--batch-slots N"})
                    return
                try:
                    n_choices, n_prompt, n_completion = state.complete_n(
                        params, deadline=deadline)
                except ContextOverflow as e:
                    self._json(400, state.overflow_body(e))
                    return
                if any(fin == "timeout" for _, fin in n_choices):
                    state.metrics.bump("deadline_timeouts")
                self._json(200, {
                    "id": cid, "object": "chat.completion", "created": created,
                    "model": state.model_name,
                    "choices": [{"index": i, "finish_reason": fin,
                                 "message": {"role": "assistant", "content": r}}
                                for i, (r, fin) in enumerate(n_choices)],
                    "usage": {"prompt_tokens": n_prompt,
                              "completion_tokens": n_completion,
                              "total_tokens": n_prompt + n_completion}})
                return
            if params.stream:
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self._rid_header()
                self.end_headers()

                aborted = [False]

                def emit(delta):
                    # a dead client sets `aborted`; complete() polls it
                    # between chunks (is_aborted) and ends the stream via
                    # drain_generation's normal pos-rewind path
                    if aborted[0]:
                        return
                    try:
                        e0 = time.perf_counter()
                        FAULTS.fire("server.emit_delta")
                        chunk = {"id": cid, "object": "chat.completion.chunk",
                                 "created": created, "model": state.model_name,
                                 "choices": [{"index": 0,
                                              "delta": {"content": delta},
                                              "finish_reason": None}]}
                        self.wfile.write(
                            f"data: {json.dumps(chunk)}\n\n".encode())
                        self.wfile.flush()
                        obs_trace.record("emit", e0, time.perf_counter())
                        if timer is not None:
                            timer.tick()
                    except OSError:
                        aborted[0] = True
                        state.metrics.bump("client_disconnects")

                try:
                    _, _, _, finish = state.complete(
                        params, emit, deadline=deadline,
                        is_aborted=lambda: aborted[0])
                except ContextOverflow as e:
                    # headers already sent: emit an OpenAI-shaped error
                    # object and terminate WITHOUT a normal finish chunk, so
                    # clients don't mistake the failure for an empty success.
                    # Only the context-window refusal maps to a client error;
                    # anything else is a server bug and propagates as a 500
                    # (ADVICE r01: a bare ValueError catch masked bugs).
                    err = {"error": {"message": str(e),
                                     "type": "invalid_request_error"}}
                    self._safe_write(f"data: {json.dumps(err)}\n\n".encode()
                                     + b"data: [DONE]\n\n", aborted)
                    return
                if finish == "aborted" or aborted[0]:
                    return  # nobody is listening; engine state is rewound
                if finish == "timeout":
                    state.metrics.bump("deadline_timeouts")
                final = {"id": cid, "object": "chat.completion.chunk",
                         "created": created, "model": state.model_name,
                         "choices": [{"index": 0, "delta": {},
                                      "finish_reason": finish}]}
                self._safe_write(f"data: {json.dumps(final)}\n\n".encode()
                                 + b"data: [DONE]\n\n", aborted)
            else:
                on_delta = (lambda d: timer.tick()) if timer is not None \
                    else (lambda d: None)
                try:
                    reply, n_prompt, n_completion, finish = state.complete(
                        params, on_delta, deadline=deadline)
                except ContextOverflow as e:
                    self._json(400, {"error": str(e)})
                    return
                if finish == "timeout":
                    state.metrics.bump("deadline_timeouts")
                self._json(200, {
                    "id": cid, "object": "chat.completion", "created": created,
                    "model": state.model_name,
                    "choices": [{"index": 0, "finish_reason": finish,
                                 "message": {"role": "assistant", "content": reply}}],
                    "usage": {"prompt_tokens": n_prompt,
                              "completion_tokens": n_completion,
                              "total_tokens": n_prompt + n_completion}})

    return Handler


class ApiServer(ThreadingHTTPServer):
    """Threaded HTTP server wired for graceful drain: non-daemon handler
    threads + ``block_on_close`` make ``shutdown()`` wait for in-flight
    requests (each bounded by the drain deadline), and ``allow_reuse_address``
    lets a restart rebind the port while old sockets linger in TIME_WAIT."""
    daemon_threads = False
    block_on_close = True
    allow_reuse_address = True

    def __init__(self, addr, handler, state: ApiState):
        self.state = state
        super().__init__(addr, handler)


def serve(state: ApiState, host: str = "0.0.0.0", port: int = 9990, *,
          block: bool = True, install_signals: bool | None = None
          ) -> ApiServer:
    """Bind and serve.  Returns the server object; with ``block=False`` it
    serves on a background thread (tests drive requests and then call
    ``server.shutdown()`` themselves).

    Graceful drain (satellite + tentpole contract): SIGTERM/SIGINT flips
    the state into draining — new requests get 503, every in-flight
    deadline is clamped to now + ``--drain-grace`` — then ``shutdown()``
    runs from a helper thread (calling it from the signal frame inside
    ``serve_forever`` would deadlock on its own event).  A second signal
    hard-exits."""
    server = ApiServer((host, port), make_handler(state), state)
    if install_signals is None:
        install_signals = block and \
            threading.current_thread() is threading.main_thread()
    if install_signals:
        def _drain(signum, frame):
            if state.draining:  # second signal: operator means NOW
                os._exit(1)
            state.begin_drain()
            _log.info("draining", extra={
                "signal": signal.Signals(signum).name,
                "grace_s": round(state.drain_grace, 1)})

            def _shutdown():
                # hand-off records are PULLED: the router learns of the
                # drain from the finish_reason="handoff" stream chunks
                # and then GETs /admin/export/<rid> on a NEW connection.
                # shutdown() stops accepting new connections, so it must
                # wait (bounded by the drain deadline) until every
                # exported record has been picked up
                deadline = state.drain_deadline or time.monotonic()
                while state.handoff_records and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                if state.handoff_records:
                    _log.warning("handoff_records_unclaimed", extra={
                        "count": len(state.handoff_records)})
                server.shutdown()

            threading.Thread(target=_shutdown, daemon=True).start()
        signal.signal(signal.SIGTERM, _drain)
        signal.signal(signal.SIGINT, _drain)
    _log.info("listening", extra={"host": host, "port": port})
    if block:
        try:
            server.serve_forever()
        finally:
            server.server_close()
        # after shutdown() + server_close(): in-flight requests finished,
        # the engine is quiescent — snapshot here so the next boot is a
        # warm start (--snapshot-dir; ApiState.restore_snapshot)
        if state.draining:
            state.save_snapshot()
        _log.info("drained")
    else:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
    return server


def main(argv=None):
    import sys

    from ..cli import build_parser, load_draft_engine, load_stack
    argv = list(sys.argv[1:] if argv is None else argv)
    # reuse the dllama flag surface; the server has no positional mode
    args = build_parser().parse_args(["inference", *argv])
    configure_logging(args.log_format, args.log_level)
    obs_trace.configure(args.trace_buffer)
    obs_flight.configure(args.flight_buffer)
    obs_events.configure(getattr(args, "event_buffer", None),
                         getattr(args, "event_log", None))
    slo = None
    slo_spec = args.slo or os.environ.get("DLLAMA_SLO", "")
    if slo_spec:
        from ..obs.slo import SloEngine
        try:
            slo = SloEngine.from_spec(slo_spec)
        except ValueError as e:
            raise SystemExit(f"--slo: {e}")
        _log.info("slo_enabled", extra={
            "spec": slo.spec_display,
            "windows": [w for w, _ in slo.windows]})
    if args.spec != "off" and args.batch_slots <= 0:
        # speculation lives in the slot scheduler; failing fast beats a
        # silently ignored flag (and beats loading a draft model for
        # nothing)
        raise SystemExit("--spec needs --batch-slots (speculative "
                         "decoding runs under the slot scheduler)")
    if args.batch_slots > 0 and args.sp > 1:
        # the batch engine's ragged prefill needs the whole sequence axis
        # per shard (engine.prefill_ragged); accepting the flag would make
        # every /v1/completions request die mid-handler instead of this
        # one clear startup error — raised BEFORE the (minutes-long) model
        # load
        raise SystemExit("--batch-slots is not supported with --sp "
                         "(sequence-sharded KV cache); drop one of them")
    engine, tok = load_stack(args)
    batch_engine = None
    scheduler = None
    if args.batch_slots > 0:
        # share the chat engine's placed weights; only a new KV cache is
        # allocated (see ApiState docstring)
        kv_quant = getattr(args, "kv_quant", "off") == "int8"
        if args.kv_pages > 0 and engine.cache.quantized:
            raise SystemExit("--kv-pages needs a dense chat-engine KV "
                             "cache; drop --kv-cache-dtype q8 (use "
                             "--kv-quant int8 to quantize the paged pool)")
        if kv_quant and args.kv_pages <= 0:
            raise SystemExit("--kv-quant int8 needs a paged pool "
                             "(--kv-pages); contiguous slot rows have no "
                             "per-page scales")
        batch_engine = Engine(engine.cfg, engine.params, mesh=engine.mesh,
                              batch=args.batch_slots, seq_len=args.max_seq_len,
                              kv_dtype="q8" if kv_quant
                              else engine.cache.k.dtype,
                              step_timeout=args.step_timeout,
                              kv_pages=args.kv_pages,
                              kv_page_size=args.kv_page_size)
        _log.info("batch_serving_enabled",
                  extra={"slots": args.batch_slots,
                         "kv_pages": args.kv_pages,
                         "kv_quant": "int8" if kv_quant else "off"})
        try:
            # tentpole: continuous batching — single-stream requests join
            # the batch engine at decode-step granularity instead of
            # serializing on the engine mutex (which stays the fallback
            # path for seeded sampling, logprobs, echo, and n>1)
            spec = None
            if args.spec != "off":
                from ..runtime.spec import make_proposer
                draft_eng = (load_draft_engine(args, batch_engine)
                             if args.spec == "draft" else None)
                spec = make_proposer(args.spec, batch_engine,
                                     draft_engine=draft_eng)
            scheduler = SlotScheduler(
                batch_engine, prefill_chunk=args.sched_prefill_chunk,
                max_wait_ms=args.sched_max_wait_ms,
                max_queue=args.sched_max_queue,
                prefix_reuse=not args.no_prefix_reuse,
                overlap=not args.no_sched_overlap,
                preempt=not args.no_preempt,
                preempt_age_ms=args.preempt_age_ms,
                preempt_cap=args.preempt_cap,
                spill_dir=args.preempt_spill_dir,
                spec=spec, spec_k=args.spec_k,
                kv_reserve=getattr(args, "kv_reserve", "full"),
                spill_headroom=getattr(args, "spill_headroom", 16),
                host_pool_mb=getattr(args, "kv_host_pool_mb", 64.0))
            _log.info("slot_scheduler_enabled", extra={
                "slots": args.batch_slots,
                "prefill_chunk": args.sched_prefill_chunk,
                "max_wait_ms": args.sched_max_wait_ms,
                "paged": scheduler.paged,
                "prefix_reuse": scheduler.prefix_cache is not None,
                "overlap": scheduler.overlap,
                "preempt": scheduler.preempt and scheduler.paged,
                "kv_reserve": scheduler.kv_reserve,
                "kv_quant": "int8" if kv_quant else "off",
                "spec": args.spec, "spec_k": args.spec_k})
        except ValueError as e:
            # quantized KV / sp mesh: lockstep batch serving still works,
            # only decode-step admission is off
            _log.warning("slot_scheduler_disabled",
                         extra={"reason": str(e)})
    state = ApiState(engine, tok, default_temperature=args.temperature,
                     default_topp=args.topp, chunk=args.chunk,
                     batch_engine=batch_engine,
                     max_pending=args.max_pending,
                     request_timeout=args.request_timeout,
                     io_timeout=args.io_timeout,
                     drain_grace=args.drain_grace,
                     snapshot_dir=args.snapshot_dir,
                     scheduler=scheduler,
                     slo=slo, handoff=getattr(args, "handoff", False),
                     handoff_ttl=getattr(args, "handoff_ttl", 0.0))
    if args.snapshot_dir:
        state.restore_snapshot()
    try:
        serve(state, host=args.host, port=args.port)
    finally:
        if scheduler is not None:
            scheduler.close()
        if slo is not None:
            # end-of-run verdict next to the dispatch summary, same as the
            # CLI modes (cli._print_slo_summary)
            print(slo.summary_line())


if __name__ == "__main__":
    import sys
    main(sys.argv[1:])
