"""Retry-After jitter shared by the server and the router.

Every 429/503 the fleet emits carries a Retry-After; if all of them say
the same number, every backed-off client retries in the same instant and
stampedes the replica that was trying to recover.  Jittering the hint
±25% (uniform) desynchronizes the herd while keeping the expected
backoff unchanged.  Stdlib-only so the router process can import it
without pulling in the engine stack.
"""

from __future__ import annotations

import random

JITTER_FRAC = 0.25


def jittered_retry_after(seconds: float | int | str,
                         rng: random.Random | None = None) -> str:
    """Return a Retry-After header value: ``seconds`` with ±25% uniform
    jitter, rounded to a whole second, floored at 1 (the header is
    delta-seconds; 0 would mean "retry immediately", defeating the
    backoff)."""
    try:
        base = float(seconds)
    except (TypeError, ValueError):
        base = 1.0
    base = max(1.0, base)
    draw = (rng or random).uniform(1.0 - JITTER_FRAC, 1.0 + JITTER_FRAC)
    return str(max(1, int(round(base * draw))))
