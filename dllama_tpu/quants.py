"""Block-quantization formats (Q40 / Q80 / F16 / F32).

TPU-native re-implementation of the reference's quantization layer
(`/root/reference/src/quants.{hpp,cpp}` and `converter/writer.py:29-78`):

* ``Q40``: blocks of 32 values stored as one f16 scale + 16 bytes of packed
  4-bit nibbles (18 bytes / block, reference ``BlockQ40`` quants.hpp:17-20).
  Encoding follows the converter (writer.py:29-56): ``delta = amax/-8``,
  ``q = clamp(floor(x/delta + 8.5), 0, 15)``; value ``i`` goes into the low
  nibble of byte ``i`` and value ``i+16`` into the high nibble.
* ``Q80``: blocks of 32 values stored as one f16 scale + 32 int8
  (34 bytes / block, quants.hpp:22-25). ``delta = amax/127``,
  ``q = round(x/delta)``.

Unlike the reference, which dequantizes scalar-by-scalar with NEON/AVX2
(quants.cpp:137-268), everything here is vectorized numpy on the host and
jax/Pallas on device.  The wire/storage layout is byte-compatible with the
reference `.m` files so reference-converted models load directly.
"""

from __future__ import annotations

import numpy as np

# FloatType enum values — must match the reference (quants.hpp:6-12) because
# they are serialized into `.m` headers.
F32 = 0
F16 = 1
Q40 = 2
Q80 = 3

BLOCK_SIZE = 32  # QK40 == QK80 == 32 (quants.hpp:14-15)
Q40_BLOCK_BYTES = 2 + BLOCK_SIZE // 2  # f16 scale + 16 nibble-pairs = 18
Q80_BLOCK_BYTES = 2 + BLOCK_SIZE      # f16 scale + 32 int8 = 34

FLOAT_TYPE_NAMES = {F32: "f32", F16: "f16", Q40: "q40", Q80: "q80"}
FLOAT_TYPE_BY_NAME = {v: k for k, v in FLOAT_TYPE_NAMES.items()}


def numbers_per_batch(ftype: int) -> int:
    """Granularity of the format: how many numbers one storage block covers.

    Mirrors ``getNumbersPerBatch`` (quants.cpp:12-26).
    """
    if ftype in (F32, F16):
        return 1
    if ftype in (Q40, Q80):
        return BLOCK_SIZE
    raise ValueError(f"unknown float type {ftype}")


def batch_bytes(ftype: int, n: int, d: int = 1) -> int:
    """Bytes needed to store a ``d × n`` tensor in ``ftype``.

    Mirrors ``getBatchBytes`` (quants.cpp:28-51).  For block formats ``n``
    must be a multiple of the 32-element block size.
    """
    if ftype == F32:
        return 4 * n * d
    if ftype == F16:
        return 2 * n * d
    if ftype == Q40:
        if n % BLOCK_SIZE != 0:
            raise ValueError(f"Q40 row length {n} not divisible by {BLOCK_SIZE}")
        return (n // BLOCK_SIZE) * Q40_BLOCK_BYTES * d
    if ftype == Q80:
        if n % BLOCK_SIZE != 0:
            raise ValueError(f"Q80 row length {n} not divisible by {BLOCK_SIZE}")
        return (n // BLOCK_SIZE) * Q80_BLOCK_BYTES * d
    raise ValueError(f"unknown float type {ftype}")


# ---------------------------------------------------------------------------
# Q40
# ---------------------------------------------------------------------------

def quantize_q40(x: np.ndarray) -> np.ndarray:
    """Quantize a flat f32 array to Q40 bytes (writer.py:29-56 semantics).

    Returns a uint8 array of length ``(x.size/32) * 18``.
    """
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if x.size % BLOCK_SIZE != 0:
        raise ValueError(f"size {x.size} not divisible by {BLOCK_SIZE}")
    groups = x.reshape(-1, BLOCK_SIZE)
    gmax = groups.max(axis=1)
    gmin = groups.min(axis=1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    deltas16 = deltas.astype(np.float16)
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = groups * inv[:, None] + 8.5
    q = np.where(q < 15.0, q, 15.0)
    q = q.astype(np.uint8)  # truncation == floor for the non-negative range here
    lo = q[:, : BLOCK_SIZE // 2]
    hi = q[:, BLOCK_SIZE // 2:]
    packed = (lo & 0xF) | ((hi & 0xF) << 4)

    out = np.empty((groups.shape[0], Q40_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = deltas16.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = packed
    return out.reshape(-1)


def dequantize_q40(raw: np.ndarray, n: int) -> np.ndarray:
    """Dequantize Q40 bytes back to f32 (quants.cpp:137-184 semantics)."""
    raw = np.ascontiguousarray(raw, dtype=np.uint8)
    n_blocks = n // BLOCK_SIZE
    if n % BLOCK_SIZE != 0 or raw.size != n_blocks * Q40_BLOCK_BYTES:
        raise ValueError(f"bad Q40 buffer: {raw.size} bytes for {n} values")
    blocks = raw.reshape(n_blocks, Q40_BLOCK_BYTES)
    d = blocks[:, :2].copy().view(np.float16).astype(np.float32)  # (B, 1)
    qs = blocks[:, 2:]
    lo = (qs & 0xF).astype(np.int8) - 8
    hi = (qs >> 4).astype(np.int8) - 8
    out = np.empty((n_blocks, BLOCK_SIZE), dtype=np.float32)
    out[:, : BLOCK_SIZE // 2] = lo.astype(np.float32) * d
    out[:, BLOCK_SIZE // 2:] = hi.astype(np.float32) * d
    return out.reshape(-1)


def q40_planes(raw: np.ndarray, shape: tuple[int, int]) -> tuple[np.ndarray, np.ndarray]:
    """Split Q40 bytes for a ``(d, n)`` tensor into MXU-friendly planes.

    Returns ``(qvals, scales)`` where ``qvals`` is int8 of shape ``(d, n)``
    (nibbles unpacked, offset −8 applied) and ``scales`` is f32 of shape
    ``(d, n // 32)``.  This is the layout the Pallas fused dequant-matmul
    consumes: dense int8 for the MXU, per-block scales broadcast in VMEM.
    """
    d, n = shape
    n_blocks = n // BLOCK_SIZE
    blocks = raw.reshape(d * n_blocks, Q40_BLOCK_BYTES)
    scales = blocks[:, :2].copy().view(np.float16).astype(np.float32).reshape(d, n_blocks)
    qs = blocks[:, 2:]
    lo = (qs & 0xF).astype(np.int8) - 8
    hi = (qs >> 4).astype(np.int8) - 8
    qvals = np.concatenate([lo, hi], axis=1).reshape(d, n)
    return qvals, scales


# ---------------------------------------------------------------------------
# Q80
# ---------------------------------------------------------------------------

def round_half_away(v: np.ndarray) -> np.ndarray:
    """``roundf`` semantics — half away from zero (quants.cpp:264).

    ``np.round`` is half-to-even, which differs on exact ``.5`` products,
    so converter output could diverge byte-wise from reference-produced
    files on those (rare) ties.  The rounding runs in float64: every f32
    product is exact in f64 and ``v + 0.5`` cannot itself round across the
    tie boundary there (the f32-emulation pitfall for values one ulp
    below ``.5``)."""
    v = np.asarray(v, np.float64)
    return np.trunc(v + np.copysign(0.5, v))


def quantize_q80(x: np.ndarray) -> np.ndarray:
    """Quantize a flat f32 array to Q80 bytes (writer.py:58-77 semantics)."""
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    if x.size % BLOCK_SIZE != 0:
        raise ValueError(f"size {x.size} not divisible by {BLOCK_SIZE}")
    groups = x.reshape(-1, BLOCK_SIZE)
    absmax = np.abs(groups).max(axis=1)
    deltas = absmax / 127.0
    deltas16 = deltas.astype(np.float16)
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = round_half_away(groups * inv[:, None]).astype(np.int8)

    out = np.empty((groups.shape[0], Q80_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = deltas16.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q.view(np.uint8)
    return out.reshape(-1)


def dequantize_q80(raw: np.ndarray, n: int) -> np.ndarray:
    """Dequantize Q80 bytes back to f32 (quants.cpp:270-288 semantics)."""
    raw = np.ascontiguousarray(raw, dtype=np.uint8)
    n_blocks = n // BLOCK_SIZE
    if n % BLOCK_SIZE != 0 or raw.size != n_blocks * Q80_BLOCK_BYTES:
        raise ValueError(f"bad Q80 buffer: {raw.size} bytes for {n} values")
    blocks = raw.reshape(n_blocks, Q80_BLOCK_BYTES)
    d = blocks[:, :2].copy().view(np.float16).astype(np.float32)
    q = blocks[:, 2:].view(np.int8).astype(np.float32)
    return (q * d).reshape(-1)


# ---------------------------------------------------------------------------
# Generic tensor (de)serialization
# ---------------------------------------------------------------------------

def quantize_tensor(x: np.ndarray, ftype: int) -> bytes:
    """Serialize a tensor (row-major, flattened) into ``ftype`` bytes."""
    x = np.ascontiguousarray(x, dtype=np.float32)
    if ftype == F32:
        return x.tobytes()
    if ftype == F16:
        return x.astype(np.float16).tobytes()
    if ftype == Q40:
        return quantize_q40(x).tobytes()
    if ftype == Q80:
        return quantize_q80(x).tobytes()
    raise ValueError(f"unknown float type {ftype}")


def dequantize_tensor(raw: bytes | np.ndarray, ftype: int, n: int) -> np.ndarray:
    """Deserialize ``n`` values of ``ftype`` from raw bytes into f32."""
    buf = np.frombuffer(raw, dtype=np.uint8) if isinstance(raw, (bytes, memoryview)) else raw
    if ftype == F32:
        return buf.view(np.float32)[:n].astype(np.float32)
    if ftype == F16:
        return buf[: 2 * n].copy().view(np.float16).astype(np.float32)
    if ftype == Q40:
        return dequantize_q40(buf, n)
    if ftype == Q80:
        return dequantize_q80(buf, n)
    raise ValueError(f"unknown float type {ftype}")
