"""ctypes bindings for the native runtime components (csrc/).

The reference implements its whole runtime in C++; here the TPU compute
path is XLA's, and the host-side hot paths are native instead — currently
the Q40 load transform (csrc/q40pack.cpp), which turns `.m` file blocks
into the runtime packed layout in one parallel pass.  Everything degrades
to the numpy implementation when the shared library hasn't been built
(`make -C dllama_tpu/csrc`), so the package stays importable anywhere.
"""

from __future__ import annotations

import ctypes
import functools
import os

import numpy as np

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_LIB_PATH = os.path.join(_CSRC, "libq40pack.so")
_BPE_PATH = os.path.join(_CSRC, "libbpe.so")


def _load_lib(path: str):
    """Load one csrc shared library, or ``None`` (not built / load failure).

    When the .so is absent (it is machine-specific, never committed) a
    one-shot build is attempted — a 2 s compile that keeps fresh checkouts
    on the fast path; any failure falls back to the Python path silently."""
    if os.environ.get("DLLAMA_NO_NATIVE"):
        return None
    # rebuild when missing OR older than anything that shapes the binary —
    # .cpp sources, headers, and the Makefile itself (flag changes): a
    # stale library from before a source/flag change would silently keep
    # its old semantics forever (the hasattr symbol guard only catches
    # *missing* entry points).  The build is serialized with an flock and
    # the Makefile publishes via rename, so concurrent processes
    # (multihost tests, bench subprocesses) never dlopen a half-written
    # ELF — and fresh libraries skip the make exec entirely.
    def _stale() -> bool:
        if not os.path.exists(path):
            return True
        so_mtime = os.path.getmtime(path)
        return any((f.endswith((".cpp", ".hpp", ".h")) or f == "Makefile") and
                   os.path.getmtime(os.path.join(_CSRC, f)) > so_mtime
                   for f in os.listdir(_CSRC))

    if _stale():
        import subprocess
        try:
            import fcntl
            with open(os.path.join(_CSRC, ".build.lock"), "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                if _stale():  # another process may have built meanwhile
                    subprocess.run(["make", "-C", _CSRC], capture_output=True,
                                   timeout=60, check=False)
        except Exception:
            pass
    try:
        return ctypes.CDLL(path)
    except OSError:
        return None


@functools.cache
def _lib():
    lib = _load_lib(_LIB_PATH)
    if lib is None:
        return None
    lib.q40_repack.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.q40_repack.restype = None
    if hasattr(lib, "q80_repack"):  # absent in a pre-r04 cached .so
        lib.q80_repack.argtypes = lib.q40_repack.argtypes
        lib.q80_repack.restype = None
    return lib


def have_native() -> bool:
    return _lib() is not None


# ---------------------------------------------------------------------------
# BPE merge engine (csrc/bpe.cpp)
# ---------------------------------------------------------------------------

@functools.cache
def _bpe_lib():
    lib = _load_lib(_BPE_PATH)
    if lib is None:
        return None
    lib.bpe_create.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_void_p, ctypes.c_int64]
    lib.bpe_create.restype = ctypes.c_void_p
    lib.bpe_destroy.argtypes = [ctypes.c_void_p]
    lib.bpe_merge.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.bpe_merge.restype = ctypes.c_int64
    return lib


class _BpeHandle:
    """Owns one native tokenizer handle for a Tokenizer's lifetime."""

    def __init__(self, lib, vocab: list[bytes], scores: list[float]):
        blob = np.frombuffer(b"".join(vocab) or b"\0", np.uint8)
        offsets = np.zeros(len(vocab) + 1, np.int64)
        np.cumsum([len(v) for v in vocab], out=offsets[1:])
        self._lib = lib
        sc = np.asarray(scores, np.float32)
        # bpe_create copies everything into C++-owned storage, so no
        # host-side buffer needs to outlive this call
        self._ptr = lib.bpe_create(
            blob.ctypes.data_as(ctypes.c_void_p),
            offsets.ctypes.data_as(ctypes.c_void_p),
            sc.ctypes.data_as(ctypes.c_void_p), len(vocab))

    def merge(self, tokens: list[int]) -> list[int]:
        arr = np.asarray(tokens, np.int32)
        m = self._lib.bpe_merge(self._ptr,
                                arr.ctypes.data_as(ctypes.c_void_p), len(arr))
        return arr[:m].tolist()

    def __del__(self):
        try:
            self._lib.bpe_destroy(self._ptr)
        except Exception:
            pass


def bpe_merge(tokenizer, tokens: list[int]) -> list[int] | None:
    """Native greedy merge for ``tokenizer`` (a Tokenizer), or ``None`` when
    the library isn't available — the caller then runs the Python heap."""
    lib = _bpe_lib()
    if lib is None:
        return None
    handle = getattr(tokenizer, "_native_bpe", None)
    if handle is None:
        handle = _BpeHandle(lib, tokenizer.vocab, tokenizer.scores)
        tokenizer._native_bpe = handle
    return handle.merge(tokens)


def q40_repack_into(raw: np.ndarray, d: int, n: int,
                    qp: np.ndarray, sc: np.ndarray, col: int) -> None:
    """Repack one (d, n) Q40 tensor's file bytes into preallocated runtime
    planes at column offset ``col``.

    ``qp`` is uint8 (padded_n/2, ld), ``sc`` float16 (padded_n/32, ld);
    rows beyond n/32 blocks must be pre-zeroed by the caller (pack
    padding).  Requires C-contiguous outputs.
    """
    lib = _lib()
    if lib is None:
        raise RuntimeError("native library not built (make -C dllama_tpu/csrc)")
    nb = n // 32
    if raw.nbytes != d * nb * 18:
        raise ValueError(f"raw size {raw.nbytes} != {d * nb * 18}")
    if not (qp.flags.c_contiguous and sc.flags.c_contiguous):
        raise ValueError("output planes must be C-contiguous")
    if qp.dtype != np.uint8 or sc.dtype != np.float16:
        raise ValueError("qp must be uint8, sc float16")
    ld = qp.shape[-1]
    if sc.shape[-1] != ld or col + d > ld:
        raise ValueError(f"column window [{col}, {col + d}) exceeds ld={ld}")
    if qp.shape[0] < nb * 16 or sc.shape[0] < nb or qp.shape[0] != 16 * sc.shape[0]:
        raise ValueError(
            f"plane rows (qp {qp.shape[0]}, sc {sc.shape[0]}) too small for "
            f"{nb} blocks — the native write would run out of bounds")
    raw = np.ascontiguousarray(raw)
    lib.q40_repack(
        raw.ctypes.data_as(ctypes.c_void_p), d, nb,
        qp.ctypes.data_as(ctypes.c_void_p),
        sc.ctypes.data_as(ctypes.c_void_p), ld, col)


def have_native_q80() -> bool:
    lib = _lib()
    return lib is not None and hasattr(lib, "q80_repack")


def q80_repack_into(raw: np.ndarray, d: int, n: int,
                    qv: np.ndarray, sc: np.ndarray, col: int) -> None:
    """Repack one (d, n) Q80 tensor's file bytes into preallocated runtime
    planes at column offset ``col`` (csrc q80_repack — the Q80 twin of
    :func:`q40_repack_into`).

    ``qv`` is int8 (padded_n, ld), ``sc`` float16 (padded_n/32, ld); rows
    beyond n's blocks must be pre-zeroed by the caller (pack padding).
    """
    lib = _lib()
    if lib is None or not hasattr(lib, "q80_repack"):
        raise RuntimeError("native q80_repack unavailable "
                           "(make -C dllama_tpu/csrc)")
    nb = n // 32
    if raw.nbytes != d * nb * 34:
        raise ValueError(f"raw size {raw.nbytes} != {d * nb * 34}")
    if not (qv.flags.c_contiguous and sc.flags.c_contiguous):
        raise ValueError("output planes must be C-contiguous")
    if qv.dtype != np.int8 or sc.dtype != np.float16:
        raise ValueError("qv must be int8, sc float16")
    ld = qv.shape[-1]
    if sc.shape[-1] != ld or col + d > ld:
        raise ValueError(f"column window [{col}, {col + d}) exceeds ld={ld}")
    if qv.shape[0] < nb * 32 or sc.shape[0] < nb or qv.shape[0] != 32 * sc.shape[0]:
        raise ValueError(
            f"plane rows (qv {qv.shape[0]}, sc {sc.shape[0]}) too small for "
            f"{nb} blocks — the native write would run out of bounds")
    raw = np.ascontiguousarray(raw)
    lib.q80_repack(
        raw.ctypes.data_as(ctypes.c_void_p), d, nb,
        qv.ctypes.data_as(ctypes.c_void_p),
        sc.ctypes.data_as(ctypes.c_void_p), ld, col)
