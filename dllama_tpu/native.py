"""ctypes bindings for the native runtime components (csrc/).

The reference implements its whole runtime in C++; here the TPU compute
path is XLA's, and the host-side hot paths are native instead — currently
the Q40 load transform (csrc/q40pack.cpp), which turns `.m` file blocks
into the runtime packed layout in one parallel pass.  Everything degrades
to the numpy implementation when the shared library hasn't been built
(`make -C dllama_tpu/csrc`), so the package stays importable anywhere.
"""

from __future__ import annotations

import ctypes
import functools
import os

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "csrc", "libq40pack.so")


@functools.cache
def _lib():
    """The loaded library, or ``None`` (not built / load failure).

    When the .so is absent (it is machine-specific, never committed) a
    one-shot build is attempted — a 2 s compile that keeps fresh checkouts
    on the fast path; any failure falls back to numpy silently."""
    if os.environ.get("DLLAMA_NO_NATIVE"):
        return None
    if not os.path.exists(_LIB_PATH):
        import subprocess
        try:
            subprocess.run(["make", "-C", os.path.dirname(_LIB_PATH)],
                           capture_output=True, timeout=60, check=False)
        except Exception:
            pass
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.q40_repack.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
    lib.q40_repack.restype = None
    return lib


def have_native() -> bool:
    return _lib() is not None


def q40_repack_into(raw: np.ndarray, d: int, n: int,
                    qp: np.ndarray, sc: np.ndarray, col: int) -> None:
    """Repack one (d, n) Q40 tensor's file bytes into preallocated runtime
    planes at column offset ``col``.

    ``qp`` is uint8 (padded_n/2, ld), ``sc`` float16 (padded_n/32, ld);
    rows beyond n/32 blocks must be pre-zeroed by the caller (pack
    padding).  Requires C-contiguous outputs.
    """
    lib = _lib()
    if lib is None:
        raise RuntimeError("native library not built (make -C dllama_tpu/csrc)")
    nb = n // 32
    if raw.nbytes != d * nb * 18:
        raise ValueError(f"raw size {raw.nbytes} != {d * nb * 18}")
    if not (qp.flags.c_contiguous and sc.flags.c_contiguous):
        raise ValueError("output planes must be C-contiguous")
    if qp.dtype != np.uint8 or sc.dtype != np.float16:
        raise ValueError("qp must be uint8, sc float16")
    ld = qp.shape[-1]
    if sc.shape[-1] != ld or col + d > ld:
        raise ValueError(f"column window [{col}, {col + d}) exceeds ld={ld}")
    if qp.shape[0] < nb * 16 or sc.shape[0] < nb or qp.shape[0] != 16 * sc.shape[0]:
        raise ValueError(
            f"plane rows (qp {qp.shape[0]}, sc {sc.shape[0]}) too small for "
            f"{nb} blocks — the native write would run out of bounds")
    raw = np.ascontiguousarray(raw)
    lib.q40_repack(
        raw.ctypes.data_as(ctypes.c_void_p), d, nb,
        qp.ctypes.data_as(ctypes.c_void_p),
        sc.ctypes.data_as(ctypes.c_void_p), ld, col)
