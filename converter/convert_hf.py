"""HF safetensors → `.m` converter.

Re-implements `/root/reference/converter/convert-hf.py`: llama / mistral /
mixtral folders with ``config.json`` + ``*.safetensors`` become a `.m` file
in the canonical tensor order.  Key semantics preserved:

* q/k head permutation (convert-hf.py:12-15): HF stores RoPE in rotate-half
  layout; the `.m` format expects the interleaved-pair layout, so q and k
  rows are permuted ``(h, 2, hs/2) → (h, hs/2, 2)``.  The reference applies
  this to every arch (including Mixtral, whose runtime then rotates
  neox-style — a reference quirk preserved for file-format parity).
* dense FFN file order gate/down/up = w1/w2/w3 (convert-hf.py:77-83);
  MoE per-expert order up(w3)/gate(w1)/down(w2) (convert-hf.py:68-75).

Usage: python convert_hf.py <sourceFolderPath> <weightsFloatType> <name>
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dllama_tpu import quants  # noqa: E402
from dllama_tpu.io import mfile  # noqa: E402

ARCH_BY_MODEL_TYPE = {
    "llama": mfile.ARCH_LLAMA,
    "mistral": mfile.ARCH_LLAMA,
    "mixtral": mfile.ARCH_MIXTRAL,
}
HIDDEN_ACT = {"gelu": mfile.ACT_GELU, "silu": mfile.ACT_SILU}


def permute(t: np.ndarray, n_heads: int, n_kv_heads: int) -> np.ndarray:
    """Rotate-half → interleaved head layout (convert-hf.py:12-15)."""
    if n_heads != n_kv_heads:
        n_heads = n_kv_heads
    return (t.reshape(n_heads, 2, t.shape[0] // n_heads // 2, *t.shape[1:])
             .swapaxes(1, 2).reshape(t.shape))


def load_spec(folder: str, weights_ftype: int) -> mfile.ModelSpec:
    with open(os.path.join(folder, "config.json")) as f:
        config = json.load(f)
    arch = ARCH_BY_MODEL_TYPE.get(config["model_type"])
    if arch is None:
        raise SystemExit(f"Unsupported arch type: {config['model_type']}")
    n_experts = config.get("num_local_experts") or 0
    n_active = (config.get("num_active_local_experts")
                or config.get("num_experts_per_tok") or 0)
    return mfile.ModelSpec(
        arch=arch,
        dim=config["hidden_size"],
        hidden_dim=config["intermediate_size"],
        n_layers=config["num_hidden_layers"],
        n_heads=config["num_attention_heads"],
        n_kv_heads=config["num_key_value_heads"],
        n_experts=int(n_experts),
        n_active_experts=int(n_active),
        vocab_size=config["vocab_size"],
        seq_len=config["max_position_embeddings"],
        hidden_act=HIDDEN_ACT[config.get("hidden_act", "silu")],
        rope_theta=float(config.get("rope_theta", 10000.0)),
        weights_ftype=weights_ftype)


class SafetensorsStore:
    """Lazy multi-file tensor lookup over a model folder."""

    def __init__(self, folder: str):
        from safetensors import safe_open
        self._handles = {}
        self._index: dict[str, str] = {}
        for name in sorted(os.listdir(folder)):
            if name.endswith(".safetensors"):
                path = os.path.join(folder, name)
                h = safe_open(path, framework="np", device="cpu")
                self._handles[path] = h
                for key in h.keys():
                    self._index[key] = path
        if not self._handles:
            raise SystemExit("Not found any model file")

    def get(self, key: str) -> np.ndarray:
        path = self._index.get(key)
        if path is None:
            raise SystemExit(f"Layer {key} not found")
        t = self._handles[path].get_tensor(key)
        if t.dtype == np.uint16:  # bfloat16 stored raw
            import jax.numpy as jnp
            t = np.asarray(jnp.asarray(t.view(jnp.bfloat16), jnp.float32))
        return np.asarray(t, dtype=np.float32)


def hf_source_name(our_name: str, spec: mfile.ModelSpec) -> tuple[str, bool]:
    """Map a `.m` plan tensor name to its HF key; returns (key, permute?)."""
    if our_name == "token_embedding":
        return "model.embed_tokens.weight", False
    if our_name == "rms_final":
        return "model.norm.weight", False
    if our_name == "wcls":
        return "lm_head.weight", False
    parts = our_name.split(".")
    li = parts[1]
    leaf = parts[-1]
    base = f"model.layers.{li}"
    if leaf == "wq":
        return f"{base}.self_attn.q_proj.weight", True
    if leaf == "wk":
        return f"{base}.self_attn.k_proj.weight", True
    if leaf == "wv":
        return f"{base}.self_attn.v_proj.weight", False
    if leaf == "wo":
        return f"{base}.self_attn.o_proj.weight", False
    if leaf == "rms_att":
        return f"{base}.input_layernorm.weight", False
    if leaf == "rms_ffn":
        return f"{base}.post_attention_layernorm.weight", False
    # dense FFN: w1=gate w2=down w3=up (convert-hf.py:77-83)
    if leaf == "w1":
        return f"{base}.mlp.gate_proj.weight", False
    if leaf == "w2":
        return f"{base}.mlp.down_proj.weight", False
    if leaf == "w3":
        return f"{base}.mlp.up_proj.weight", False
    if parts[2] == "experts":
        e = parts[3]
        hf_leaf = {"up": "w3", "gate": "w1", "down": "w2"}[leaf]
        return f"{base}.block_sparse_moe.experts.{e}.{hf_leaf}.weight", False
    if leaf == "moe_router":
        return f"{base}.block_sparse_moe.gate.weight", False
    raise SystemExit(f"no HF mapping for {our_name}")


def convert(folder: str, weights_ftype: int, out_path: str) -> None:
    spec = load_spec(folder, weights_ftype)
    store = SafetensorsStore(folder)
    with mfile.MFileWriter(out_path, spec) as w:
        for item in w.plan:
            key, do_permute = hf_source_name(item.name, spec)
            t = store.get(key)
            if do_permute:
                heads = spec.n_heads if item.name.endswith("wq") else spec.n_kv_heads
                t = permute(t, spec.n_heads, heads)
            print(f"🔶 Writing tensor {key} {tuple(t.shape)} -> {item.name}")
            w.write_tensor(item.name, t.reshape(item.shape))
    print(f"✅ {out_path} created successfully")


def main(argv):
    if len(argv) < 3:
        print("Usage: python convert_hf.py <sourceFolderPath> <weightsFloatType> <name>")
        raise SystemExit(1)
    folder, ftype_name, name = argv[0], argv[1], argv[2]
    ftype = quants.FLOAT_TYPE_BY_NAME[ftype_name]
    out = f"dllama_model_{name}_{ftype_name}.m"
    print(f"Output file: {out}")
    convert(folder, ftype, out)


if __name__ == "__main__":
    main(sys.argv[1:])
