"""HF tokenizer folder → `.t` converter.

Re-implements `/root/reference/converter/convert-tokenizer-hf.py`:
* ``PreTrainedTokenizerFast`` — read ``tokenizer.json`` BPE vocab in id
  order with score ``-id`` (convert-tokenizer-hf.py:20-39).
* ``LlamaTokenizer`` — read ``tokenizer.model`` via sentencepiece, mapping
  ``▁`` to space (convert-tokenizer-hf.py:41-55); gated on the
  sentencepiece package being installed.

Non-interactive: the reference prompts for an extra chat stop string on
stdin; here it's the optional third argv.

Usage: python convert_tokenizer_hf.py <tokenizerFolderPath> <name> [chatExtraStop]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dllama_tpu.io import tfile  # noqa: E402


def open_json(path):
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def resolve_fast(dir_path: str, tokenizer_config: dict) -> tfile.TokenizerData:
    tok = open_json(os.path.join(dir_path, "tokenizer.json"))
    if tok["model"]["type"] != "BPE":
        raise SystemExit("only BPE tokenizer.json is supported")
    t = tfile.TokenizerData()
    for token, tid in tok["model"]["vocab"].items():
        if tid != len(t.vocab):
            raise SystemExit("non-contiguous vocab ids")
        t.vocab.append(token.encode("utf-8"))
        t.scores.append(-float(tid))
    for at in tok.get("added_tokens", []):
        if at["id"] != len(t.vocab):
            raise SystemExit("non-contiguous added_tokens ids")
        t.vocab.append(at["content"].encode("utf-8"))
        t.scores.append(-float(at["id"]))
        if at["content"] == tokenizer_config.get("bos_token"):
            t.bos_id = at["id"]
        if at["content"] == tokenizer_config.get("eos_token"):
            t.eos_id = at["id"]
    return t


def resolve_sentencepiece(dir_path: str) -> tfile.TokenizerData:
    try:
        from sentencepiece import SentencePieceProcessor
    except ImportError:
        raise SystemExit("sentencepiece is not installed in this environment; "
                         "use a tokenizer.json-based folder instead")
    sp = SentencePieceProcessor(model_file=os.path.join(dir_path, "tokenizer.model"))
    t = tfile.TokenizerData(bos_id=sp.bos_id(), eos_id=sp.eos_id())
    for i in range(sp.vocab_size()):
        piece = sp.id_to_piece(i).replace("▁", " ")
        t.vocab.append(piece.encode("utf-8"))
        t.scores.append(sp.get_score(i))
    return t


def convert(dir_path: str, name: str, chat_extra_stop: str | None = None,
            out_path: str | None = None) -> str:
    cfg = open_json(os.path.join(dir_path, "tokenizer_config.json"))
    cls = cfg.get("tokenizer_class")
    if cls == "PreTrainedTokenizerFast":
        t = resolve_fast(dir_path, cfg)
    elif cls == "LlamaTokenizer":
        t = resolve_sentencepiece(dir_path)
    else:
        raise SystemExit(f"Tokenizer {cls} is not supported")

    t.chat_eos_id = t.eos_id
    if "chat_template" in cfg:
        t.chat_template = cfg["chat_template"]
        t.chat_stop = chat_extra_stop
    t.max_token_length = max((len(v) for v in t.vocab), default=0)

    out = out_path or f"dllama_tokenizer_{name}.t"
    print(f"bosId: {t.bos_id}  eosId: {t.eos_id}")
    tfile.write_tfile(out, t)
    print(f"✅ Created {out}")
    return out


if __name__ == "__main__":
    if len(sys.argv) < 3:
        print("Usage: python convert_tokenizer_hf.py <tokenizerFolderPath> <name> [chatExtraStop]")
        raise SystemExit(1)
    convert(sys.argv[1], sys.argv[2], sys.argv[3] if len(sys.argv) > 3 else None)
