"""Llama-3 tiktoken tokenizer.model → `.t` converter.

Re-implements `/root/reference/converter/convert-tokenizer-llama3.py`:
the base64-per-line tiktoken vocab plus 256 hardcoded special tokens, the
llama3 chat template, and the fixed bos/eos/chat-eos ids
(convert-tokenizer-llama3.py:13-32).

Usage: python convert_tokenizer_llama3.py <tokenizerPath>
"""

from __future__ import annotations

import base64
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dllama_tpu.io import tfile  # noqa: E402

N_SPECIAL_TOKENS = 256
SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|reserved_special_token_2|>",
    "<|reserved_special_token_3|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|reserved_special_token_4|>",
    "<|eot_id|>",
] + [f"<|reserved_special_token_{i}|>" for i in range(5, N_SPECIAL_TOKENS - 5)]

BOS_ID = 128000
EOS_ID = 128001
CHAT_EOS_ID = 128009
CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    "+ message['content'] | trim + '<|eot_id|>' %}{% if loop.index0 == 0 %}"
    "{% set content = bos_token + content %}{% endif %}{{ content }}{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}"
    "{% endif %}")


def convert(model_path: str, out_path: str = "dllama_tokenizer_llama3.t") -> str:
    t = tfile.TokenizerData(bos_id=BOS_ID, eos_id=EOS_ID, chat_eos_id=CHAT_EOS_ID,
                            chat_template=CHAT_TEMPLATE)
    with open(model_path, "r") as f:
        for line in f:
            if not line.strip():
                continue
            b64, rank = line.split(" ")
            t.vocab.append(base64.b64decode(b64))
            t.scores.append(-float(rank))
    for i, token in enumerate(SPECIAL_TOKENS):
        t.vocab.append(token.encode("utf-8"))
        t.scores.append(-float(len(t.vocab) - 1))
    t.max_token_length = max(len(v) for v in t.vocab)
    tfile.write_tfile(out_path, t)
    print(f"✅ Created {out_path}")
    return out_path


if __name__ == "__main__":
    if len(sys.argv) < 2:
        print("Usage: python convert_tokenizer_llama3.py <tokenizerPath>")
        raise SystemExit(1)
    convert(sys.argv[1])
