"""Meta-checkpoint (`consolidated.*.pth`) → `.m` converter.

Re-implements `/root/reference/converter/convert-llama.py`: a Meta Llama
folder (``params.json`` + ``consolidated.NN.pth`` shards) becomes a `.m`
file.  Multi-shard tensors are concatenated on the axis determined by the
tensor kind (convert-llama.py:74-91): output-split tensors (wq/wk/wv/w1/w3/
embedding/output) on axis 0, input-split tensors (wo/w2) on axis 1, norms
taken from shard 0.  Meta checkpoints already use the interleaved RoPE
layout, so no q/k permutation is needed (unlike convert_hf.py).

Usage: python convert_llama.py <modelPath> <weightsFloatType>
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dllama_tpu import quants  # noqa: E402
from dllama_tpu.io import mfile  # noqa: E402


def load_spec(folder: str, weights_ftype: int, seq_len: int = 2048) -> mfile.ModelSpec:
    with open(os.path.join(folder, "params.json")) as f:
        params = json.load(f)
    dim = params["dim"]
    n_layers = params["n_layers"]
    n_heads = params["n_heads"]
    n_kv_heads = params.get("n_kv_heads", n_heads)
    multiple_of = params.get("multiple_of", 256)
    ffn_dim_multiplier = params.get("ffn_dim_multiplier")
    # Meta's SwiGLU sizing rule (same derivation the reference relies on the
    # checkpoint tensors for; needed here to pre-compute the plan)
    hidden = 4 * dim
    hidden = int(2 * hidden / 3)
    if ffn_dim_multiplier is not None:
        hidden = int(ffn_dim_multiplier * hidden)
    hidden = multiple_of * ((hidden + multiple_of - 1) // multiple_of)
    return mfile.ModelSpec(
        arch=mfile.ARCH_LLAMA, dim=dim, hidden_dim=hidden, n_layers=n_layers,
        n_heads=n_heads, n_kv_heads=n_kv_heads,
        vocab_size=params.get("vocab_size", 32000) if params.get("vocab_size", -1) > 0 else 32000,
        seq_len=seq_len, hidden_act=mfile.ACT_SILU,
        rope_theta=float(params.get("rope_theta", 10000.0)),
        weights_ftype=weights_ftype)


# our name -> (meta key template, concat axis or None for shard-0-only)
META_MAP = {
    "token_embedding": ("tok_embeddings.weight", 1),  # embedding is column-split
    "wq": ("layers.{l}.attention.wq.weight", 0),
    "wk": ("layers.{l}.attention.wk.weight", 0),
    "wv": ("layers.{l}.attention.wv.weight", 0),
    "wo": ("layers.{l}.attention.wo.weight", 1),
    "w1": ("layers.{l}.feed_forward.w1.weight", 0),
    "w2": ("layers.{l}.feed_forward.w2.weight", 1),
    "w3": ("layers.{l}.feed_forward.w3.weight", 0),
    "rms_att": ("layers.{l}.attention_norm.weight", None),
    "rms_ffn": ("layers.{l}.ffn_norm.weight", None),
    "rms_final": ("norm.weight", None),
    "wcls": ("output.weight", 0),
}


def convert(folder: str, weights_ftype: int, out_path: str, seq_len: int = 2048) -> None:
    import torch

    spec = load_spec(folder, weights_ftype, seq_len)
    shard_paths = sorted(p for p in os.listdir(folder) if p.startswith("consolidated."))
    if not shard_paths:
        raise SystemExit("no consolidated.*.pth shards found")
    shards = [torch.load(os.path.join(folder, p), map_location="cpu", mmap=True)
              for p in shard_paths]

    def get(name: str, layer: int | None) -> np.ndarray:
        tmpl, axis = META_MAP[name]
        key = tmpl.format(l=layer)
        parts = [s[key].to(torch.float32).numpy() for s in shards]
        if axis is None or len(parts) == 1:
            return parts[0]
        return np.concatenate(parts, axis=axis)

    with mfile.MFileWriter(out_path, spec) as w:
        for item in w.plan:
            parts = item.name.split(".")
            layer = int(parts[1]) if parts[0] == "layers" else None
            leaf = parts[-1] if layer is not None else item.name
            t = get(leaf, layer)
            print(f"🔶 Writing tensor {item.name} {tuple(t.shape)}")
            w.write_tensor(item.name, t.reshape(item.shape))
    print(f"✅ {out_path} created successfully")


def main(argv):
    if len(argv) < 2:
        print("Usage: python convert_llama.py <modelPath> <weightsFloatType>")
        raise SystemExit(1)
    folder, ftype_name = argv[0], argv[1]
    name = os.path.basename(os.path.normpath(folder)).lower()
    out = f"dllama_model_{name}_{ftype_name}.m"
    convert(folder, quants.FLOAT_TYPE_BY_NAME[ftype_name], out)


if __name__ == "__main__":
    main(sys.argv[1:])
