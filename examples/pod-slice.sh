#!/bin/sh
# Multi-host pod-slice launcher — the reference's examples/n-workers.sh
# analogue (it screen-spawns N TCP workers on one box; here every host
# joins one JAX process group and the mesh spans all chips — see
# docs/MULTIHOST.md).
#
# Run ON EVERY HOST of the slice (host 0 first; it serves coordination):
#   HOSTS=4 PROC_ID=$k COORD=host0:8476 MODEL=... TOKENIZER=... \
#     sh examples/pod-slice.sh "your prompt"
set -e
COORD="${COORD:?set COORD=host0:port (process 0's address)}"
HOSTS="${HOSTS:?set HOSTS=<number of hosts>}"
PROC_ID="${PROC_ID:?set PROC_ID=<this host's index, 0-based>}"
MODEL="${MODEL:?set MODEL=/path/to/model.m}"
TOKENIZER="${TOKENIZER:?set TOKENIZER=/path/to/tokenizer.t}"
PROMPT="${1:-Hello}"
STEPS="${STEPS:-64}"
WORKERS="${WORKERS:-}"   # e.g. tpu:16; empty = all chips in the slice

exec python -m dllama_tpu worker --program generate \
  --coordinator "$COORD" --nproc "$HOSTS" --proc-id "$PROC_ID" \
  --model "$MODEL" --tokenizer "$TOKENIZER" \
  --prompt "$PROMPT" --steps "$STEPS" --temperature 0 \
  ${WORKERS:+--workers "$WORKERS"}
