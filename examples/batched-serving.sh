#!/bin/sh
# Batched serving of DISTINCT prompts — the throughput lever the reference
# cannot offer (it is strictly batch=1 per cluster, tasks.cpp:199-210).
# Decode is weight-bandwidth-bound, so B lockstep streams amortize one
# weight read over B rows: aggregate tok/s scales ≈linearly with batch.
#
# Usage: ./batched-serving.sh model.m tokenizer.t
set -e
MODEL=$(realpath "${1:?model.m}")
TOK=$(realpath "${2:?tokenizer.t}")
cd "$(dirname "$0")/.."

# 1. Offline: one lockstep ragged batch from a prompts file.  Greedy rows
#    match the single-stream outputs token for token.
cat > /tmp/prompts.txt <<'EOF'
The capital of France is
Once upon a time
To be or not to be
EOF
python -m dllama_tpu batch --model "$MODEL" --tokenizer "$TOK" \
    --prompts-file /tmp/prompts.txt --steps 64 --temperature 0

# 2. Serving: /v1/completions accepts a LIST prompt (and n>1) and decodes
#    every row in one batch; SSE streaming tags chunks by choice index.
python -m dllama_tpu.server.api --model "$MODEL" --tokenizer "$TOK" \
    --port 9990 --batch-slots 8 &
SRV=$!
trap 'kill $SRV' EXIT
until curl -s -m 2 http://127.0.0.1:9990/health >/dev/null; do sleep 1; done

curl -s http://127.0.0.1:9990/v1/completions \
    -H 'Content-Type: application/json' \
    -d '{"prompt": ["The capital of France is", "Once upon a time"],
         "max_tokens": 32, "temperature": 0}'
echo

# n sampled alternatives of one chat prompt, one weight read:
curl -s http://127.0.0.1:9990/v1/chat/completions \
    -H 'Content-Type: application/json' \
    -d '{"messages": [{"role": "user", "content": "Write a haiku"}],
         "n": 4, "max_tokens": 48, "temperature": 0.9}'
echo
