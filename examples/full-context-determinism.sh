#!/bin/sh
# Full-KV-cache determinism smoke — the reference's examples/macbeth.sh
# analogue: fill the entire context window at temperature 0 twice and
# diff the outputs.  Point MODEL/TOKENIZER at any converted .m/.t pair.
set -e
MODEL="${MODEL:?set MODEL=/path/to/model.m}"
TOKENIZER="${TOKENIZER:?set TOKENIZER=/path/to/tokenizer.t}"
PROMPT="${PROMPT:-When shall we three meet again in thunder, lightning, or in rain?}"
STEPS="${STEPS:-0}"   # 0 = run to a full context window

run() {
  python -m dllama_tpu generate --model "$MODEL" --tokenizer "$TOKENIZER" \
    --prompt "$PROMPT" --steps "$STEPS" --temperature 0 --seed 1 \
    --workers "${WORKERS:-tpu:1}"
}

A="$(run)"
B="$(run)"
[ "$A" = "$B" ] && echo "✅ deterministic over a full context window" \
                || { echo "❌ outputs differ"; exit 1; }
