#!/usr/bin/env python3
"""Minimal client for the dllama-api OpenAI-compatible server — the
counterpart of the reference's `examples/chat-api-client.js`.

Start the server first:
    python -m dllama_tpu.server.api --model m.m --tokenizer t.t --port 9990

Then:
    python examples/chat-api-client.py [--port 9990] [--stream]
"""

import argparse
import json
import sys
import urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=9990)
    ap.add_argument("--stream", action="store_true")
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--system", default="You are an excellent math teacher.")
    ap.add_argument("--user", default="What is 1 + 2?")
    args = ap.parse_args()

    body = {
        "messages": [
            {"role": "system", "content": args.system},
            {"role": "user", "content": args.user},
        ],
        "temperature": 0.7,
        "seed": 2096,
        "max_tokens": args.max_tokens,
        "stream": args.stream,
    }
    req = urllib.request.Request(
        f"http://{args.host}:{args.port}/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})

    try:
        resp_cm = urllib.request.urlopen(req)
    except urllib.error.HTTPError as e:
        print(f"server returned {e.code}: {e.read().decode()}", file=sys.stderr)
        return
    with resp_cm as resp:
        if not args.stream:
            out = json.load(resp)
            print(json.dumps(out, indent=2))
            return
        # SSE: one `data: {...}` chunk per delta, then `data: [DONE]`
        for raw in resp:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            chunk = json.loads(payload)
            if "error" in chunk:
                print(f"\nserver error: {chunk['error']['message']}", file=sys.stderr)
                return
            delta = chunk["choices"][0]["delta"].get("content", "")
            sys.stdout.write(delta)
            sys.stdout.flush()
        print()


if __name__ == "__main__":
    main()
