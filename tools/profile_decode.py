"""Diagnostic: per-op device-time breakdown of a decode step on real HW.

Drives the same zero-weight Q40 decode chunk the bench times (bench.py),
traces it with ``jax.profiler``, and prints the top HLO ops by total device
time plus the compute/collective split — the recorded-fact bottleneck
analysis VERDICT r02 asked for (the reference's analogous attribution is
its per-task-type wall accounting, utils.cpp:189-192).

Usage: python tools/profile_decode.py [model] [--top N]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", nargs="?", default="llama2-7b")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--chunk", type=int, default=32)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _model_cfg, _zero_q40_params, maybe_blocked
    from dllama_tpu.models.transformer import init_kv_cache
    from dllama_tpu.runtime.decode_loop import decode_chunk

    print(f"backend: {jax.default_backend()} {jax.devices()}", file=sys.stderr)
    impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    cfg = _model_cfg(args.model).with_(quant_impl=impl)
    params = maybe_blocked(_zero_q40_params(cfg))  # same lever as the bench
    cache = init_kv_cache(cfg, batch=1)
    chunk = args.chunk

    fn = jax.jit(
        lambda p, c, tok, pos, k: decode_chunk(
            p, cfg, c, tok, pos, k, steps=chunk, temperature=0.0, topp=0.9),
        donate_argnums=(1,))
    tok = jnp.zeros((1,), jnp.int32)
    key = jax.random.PRNGKey(0)

    t0 = time.perf_counter()
    toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32(0), key)
    np.asarray(toks)
    print(f"compile+warmup {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    t0 = time.perf_counter()
    toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32(chunk), key)
    np.asarray(toks)
    wall_ms = (time.perf_counter() - t0) * 1000
    print(f"untraced chunk: {wall_ms:.1f} ms = {wall_ms / chunk:.2f} ms/token "
          f"({1000 * chunk / wall_ms:.1f} tok/s)")

    from dllama_tpu.runtime.profiling import traced_op_times

    state = {"cache": cache, "tok": tok, "pos": 2 * chunk}

    def traced_step():
        toks, state["cache"], state["tok"], _, _ = fn(
            params, state["cache"], state["tok"], jnp.int32(state["pos"]), key)
        state["pos"] += chunk
        np.asarray(toks)

    times = traced_op_times(traced_step, steps=1)
    if times is None:
        print("no xplane tooling/trace available", file=sys.stderr)
        return

    total = sum(times.values())
    print(f"\ndevice op time: {total:.1f} ms over {chunk} steps "
          f"= {total / chunk:.2f} ms/token")
    print(f"{'ms':>9}  {'%':>5}  op")
    for name, ms in sorted(times.items(), key=lambda kv: -kv[1])[: args.top]:
        print(f"{ms:9.2f}  {100 * ms / total:5.1f}  {name}")


if __name__ == "__main__":
    main()
