"""Hardware sweep of the fused Q40 kernel: variants × tile sizes.

Times the layer-stacked kernel (the decode hot path) on the llama2-7B
matmul shapes for each (variant, tile_n, tile_d) configuration — each in a
fresh subprocess because TILE_N governs the packed storage layout — and
prints effective HBM bandwidth + a projected decode ms/token so the
winning config can be made the default with evidence (VERDICT r02 Next #2).

Measurement happens *inside one jitted ``lax.scan``* cycling the layer
index, exactly like the decode loop runs the kernel: a host-side dispatch
loop (the first version of this tool) measures tunnel/dispatch latency,
not kernel time — same-config repeat runs varied ±30% where the scan
timing is stable to a few percent and matches the xplane per-op numbers.

Usage: python tools/sweep_q40.py            # sweep and rank
       python tools/sweep_q40.py --one folded 1024 2048   # single config
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

def shapes():
    """Representative llama2-7B matmuls (stacked over 32 layers), as
    (name, n_in, d_out, stacked_layers): wo is the narrow-output extreme
    (632 GB/s in the r3 xplane), w13 the wide-output extreme (354 GB/s),
    wqkv in between — enough to rank configs while keeping per-config
    compile time inside the subprocess timeout (remote compiles run
    30-90 s each; the previous 5-shape sweep timed out on compiles alone).
    Projections scale w13's rate onto w2 (similar width class) and wqkv's
    onto wcls."""
    return [
        ("wqkv", 4096, 12288, 32),
        ("wo", 4096, 4096, 32),
        ("w13", 4096, 22016, 32),
    ]

# (variant, tile_n, tile_d).  Wide tile_d configs probe DMA contiguity:
# a (tn/2, td) tile of a row-major (n/2, d) plane is td contiguous bytes
# per row, so td sets the HBM burst length (w13's d=22016 at td=1024 is
# 1 KB bursts on a 22 KB stride).  tile_n below 256 is illegal (the
# scales block spec needs tn/32 ≥ 8 sublanes).
CONFIGS = [
    ("classic", 1024, 1024), ("fma", 1024, 1024), ("folded", 1024, 1024),
    # exact is Mosaic-legal by construction since the r04 transposed-
    # operand rework (q40.py _q40_kernel) — measure it on hardware
    ("exact", 1024, 1024),
    ("classic", 512, 2048), ("folded", 512, 2048), ("exact", 512, 2048),
    # tile-contiguous layout probe (one sequential DMA per grid step; a
    # wide-shape win here graduates the layout into the pack path)
    ("blocked", 1024, 1024), ("blocked", 512, 2048),
    ("classic", 256, 4096), ("folded", 256, 4096),
    ("classic", 512, 4096),
    ("classic", 256, 2048),
    ("classic", 1024, 2048),
    ("classic", 512, 1024),
]


def blocked_stacked_matmul(x, qp_blk, sc_blk, layer, tn, td, dp,
                           interpret=False):
    """Layer-indexed fused matmul over TILE-CONTIGUOUS packed storage —
    thin wrapper over the production kernel (ops/q40.py
    _pallas_matmul_blocked / BlockedQTensor, docs/PERF.md lever #1b); the
    probe and the deployed path are the same code by construction."""
    from dllama_tpu.ops import q40
    del tn, td, dp  # implied by the blocked plane shapes
    return q40._pallas_matmul_blocked(x, qp_blk, sc_blk, layer,
                                      interpret=interpret)


def block_pack(qp, sc, tn, td):
    """Re-block row-major packed planes (L, n2, d) / (L, nb, d) into the
    tile-contiguous layout (production transform: q40.to_blocked).
    Returns host numpy arrays + the padded width dp."""
    import numpy as np

    from dllama_tpu.ops import q40

    bqt = q40.to_blocked(
        q40.QTensor(qp, sc, (qp.shape[1] * 2, qp.shape[2])), tn, td)
    return (np.asarray(bqt.qpacked), np.asarray(bqt.scales),
            bqt.qpacked.shape[2] * bqt.tiles[1])  # to_blocked may clamp td


def measure_one(variant: str, reps: int = 32, only: set | None = None) -> dict:
    """Time the stacked kernel on the 7B shapes (or the ``only`` subset —
    a single-shape run is ~one remote compile, cheap enough for the bench
    to probe tile configs inline)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, HERE)
    from dllama_tpu.ops import q40

    if jax.default_backend() == "cpu":
        print(json.dumps({"error": "no TPU"}))
        return {}
    rng = np.random.RandomState(0)
    out = {"variant": variant, "tile_n": q40.TILE_N, "tile_d": q40.TILE_D,
           "shapes": {}}
    total_ms = 0.0
    total_bytes = 0
    for name, n, d, L in shapes():
        if only and name not in only:
            continue
        nb = n // 32
        x = jnp.asarray(rng.randn(1, n).astype(np.float32), jnp.bfloat16)
        tn, td = q40.TILE_N, q40.TILE_D
        if variant == "blocked":
            # tile-contiguous layout probe: bytes are bytes, so random
            # blocked planes time identically to a real repack
            dp = -(-d // td) * td
            qp = jnp.asarray(rng.randint(
                0, 256, (L, (n // 2) // (tn // 2), dp // td, tn // 2, td),
                dtype=np.uint8))
            sc = jnp.asarray(rng.randint(
                0, 2 ** 14, (L, nb // (tn // 32), dp // td, tn // 32, td),
                dtype=np.uint16))
        else:
            qp = jnp.asarray(rng.randint(0, 256, (L, n // 2, d), dtype=np.uint8))
            sc = jnp.asarray((rng.rand(L, nb, d).astype(np.float16) * 0.01).view(np.uint16))

        # one compiled scan = `reps` serialized kernel calls cycling the
        # layer index (scalar-prefetch path), exactly like decode's layer
        # scan; the accumulator consumes each output so none is dead code
        @jax.jit
        def run(x, qp, sc):
            def body(acc, i):
                if variant == "blocked":
                    o = blocked_stacked_matmul(x, qp, sc, i % L, tn, td, dp)
                else:
                    o = q40._pallas_matmul_stacked(x, qp, sc, i % L,
                                                   variant=variant)
                return acc + o.sum(), None
            return jax.lax.scan(body, jnp.float32(0), jnp.arange(reps))[0]

        float(run(x, qp, sc))  # compile + warmup (host copy: on the axon
        t0 = time.perf_counter()  # tunnel block_until_ready doesn't block)
        float(run(x, qp, sc))
        ms = (time.perf_counter() - t0) * 1000 / reps
        d_eff = dp if variant == "blocked" else d  # blocked pads d to td
        nbytes = (n // 2) * d_eff + nb * d_eff * 2  # packed + f16-bit scales per layer
        gbps = nbytes / ms / 1e6
        out["shapes"][name] = {"ms": round(ms, 4), "GBps": round(gbps, 1)}
        total_ms += ms * L
        total_bytes += nbytes * L
    if not only:
        # unmeasured 7B shapes, projected at a measured peer's rate; the
        # rate class tracks *output width d* (= DMA row stride,
        # docs/PERF.md): w2 (d=4096) matches wo's class, wcls (d=32000)
        # extrapolates wqkv/w13's
        per_w = 0.5 + 2 / 32  # packed + f16-bit scale bytes per weight
        for nbytes, peer in ((int(11264 * 4096 * per_w) * 32, "wo"),
                             (int(4096 * 32000 * per_w), "w13")):
            gbps = out["shapes"][peer]["GBps"]
            total_ms += nbytes / gbps / 1e6
            total_bytes += nbytes
        out["proj_matmul_ms_per_token"] = round(total_ms, 3)
        out["proj_matmul_GBps"] = round(total_bytes / total_ms / 1e6, 1)
    print(json.dumps(out))
    return out


def main():
    # a deployed width-rule table would silently override the tiles under
    # test (every swept config would measure the rule's tiles and the sweep
    # could never contradict the current rules) — the sweep measures the
    # explicit DLLAMA_Q40_TILE_N/TILE_D ladder only
    os.environ.pop("DLLAMA_Q40_TILES_JSON", None)
    if len(sys.argv) > 1 and sys.argv[1] == "--one":
        argv = sys.argv[2:]
        only = None
        if "--shapes" in argv:
            i = argv.index("--shapes")
            only = set(argv[i + 1].split(","))
            argv = argv[:i] + argv[i + 2:]
        if len(argv) > 2:
            # tiles must be in the env before the q40 import inside
            # measure_one reads them
            os.environ["DLLAMA_Q40_TILE_N"] = argv[1]
            os.environ["DLLAMA_Q40_TILE_D"] = argv[2]
        measure_one(argv[0], only=only)
        return
    results = []
    for variant, tn, td in CONFIGS:
        env = dict(os.environ)
        env["DLLAMA_Q40_TILE_N"] = str(tn)
        env["DLLAMA_Q40_TILE_D"] = str(td)
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one", variant],
                stdout=subprocess.PIPE, env=env, cwd=HERE, timeout=420)
        except subprocess.TimeoutExpired:
            print(f"{variant} tn={tn} td={td}: TIMEOUT", file=sys.stderr)
            continue
        if r.returncode != 0:
            print(f"{variant} tn={tn} td={td}: rc={r.returncode}", file=sys.stderr)
            continue
        try:
            out = json.loads(r.stdout.decode().strip().splitlines()[-1])
        except Exception:
            print(f"{variant} tn={tn} td={td}: unparseable", file=sys.stderr)
            continue
        if "error" in out:
            print(f"{variant} tn={tn} td={td}: {out['error']}", file=sys.stderr)
            continue
        results.append(out)
        print(f"{variant:8s} tn={tn:<5d} td={td:<5d} "
              f"matmuls {out['proj_matmul_ms_per_token']:7.2f} ms/tok "
              f"@ {out['proj_matmul_GBps']:6.1f} GB/s", file=sys.stderr)
    results.sort(key=lambda r: r["proj_matmul_ms_per_token"])
    print("\n=== ranked ===", file=sys.stderr)
    for r in results[:6]:
        print(f"{r['variant']:8s} tn={r['tile_n']:<5d} td={r['tile_d']:<5d} "
              f"{r['proj_matmul_ms_per_token']:7.2f} ms/tok "
              f"{r['proj_matmul_GBps']:6.1f} GB/s", file=sys.stderr)


if __name__ == "__main__":
    main()
