#!/usr/bin/env python
"""Trace-record / trace-replay overload harness for multi-tenant QoS.

Two subcommands against a live replica (or the fleet router):

``record``
    Pull arrival history from ``GET /debug/requests`` (the flight
    recorder) and write a replayable trace: one row per request with its
    arrival offset, prompt size, token budget, and priority class.

``replay``
    Fire the trace back at the server at ``--speed N`` (N× compressed
    inter-arrival gaps), optionally re-assigning priority classes from a
    ``--mix`` distribution, and report what each class experienced:
    per-class TTFT / inter-token-latency percentiles, finish-reason
    counts (including honest ``preempted`` finishes), 429 sheds, and the
    server's preemption / shed counter deltas read from ``/metrics``.
    With ``--slo-ttft-ms`` the report carries a per-class verdict so a
    drill can assert "interactive held its budget while batch absorbed
    the overload".

Without ``--trace``, replay synthesizes an open-loop trace
(``--requests`` arrivals at ``--rate`` per second) whose arrival curve
``--shape`` picks: constant ``poisson``, a sinusoidal ``diurnal``
quiet→peak→quiet cycle, or an on/off ``burst`` square wave — the
acceptance shapes an elastic pod must ride without dropping requests.
``--slo`` gives every class its own TTFT budget and verdict, and
``--availability-p95-s`` samples ``/health`` throughout the replay and
bounds the p95 unavailability window, so "the fleet stayed up while it
reshaped" is measured, not asserted.

Usage::

    python tools/trace_replay.py record --base http://127.0.0.1:8000 \
        --out /tmp/trace.json
    python tools/trace_replay.py replay --base http://127.0.0.1:8000 \
        --trace /tmp/trace.json --speed 2 \
        --mix interactive=0.2,standard=0.3,batch=0.5 --slo-ttft-ms 2000

Stdlib-only; exit code 0 iff every configured bound held — each class
with an SLO budget met it AND the availability p95 stayed within its
bound (always 0 when nothing was configured).
"""

from __future__ import annotations

import argparse
import json
import math
import random
import sys
import threading
import time
import urllib.error
import urllib.request

PRIORITIES = ("interactive", "standard", "batch")

#: metric families whose deltas the report surfaces (JSON /metrics keys)
_COUNTER_FAMILIES = ("sched_preemptions", "admissions_shed",
                     "requests_rejected_429")


# -- trace shape ----------------------------------------------------------
def _get_json(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def record_trace(base: str, n: int = 500) -> dict:
    """Build a trace from the server's flight recorder (newest-first
    summaries are re-sorted into arrival order; offsets are relative to
    the oldest arrival)."""
    recs = _get_json(base, f"/debug/requests?n={n}").get("requests") or []
    rows = [r for r in recs if r.get("submitted_at") is not None]
    rows.sort(key=lambda r: r["submitted_at"])
    if not rows:
        return {"version": 1, "requests": []}
    t0 = rows[0]["submitted_at"]
    out = []
    for r in rows:
        out.append({
            "offset_s": round(r["submitted_at"] - t0, 6),
            "prompt_tokens": int(r.get("n_prompt") or 8),
            "max_tokens": max(1, int(r.get("produced") or 16)),
            "priority": r.get("priority") or "standard",
        })
    return {"version": 1, "recorded_from": base, "requests": out}


def synth_trace(n: int, rate: float, *, max_tokens: int = 16,
                prompt_tokens: int = 8, seed: int = 0,
                shape: str = "poisson", period: float = 20.0) -> dict:
    """Open-loop arrivals, deterministic under ``seed``.  ``shape``
    picks the arrival-rate curve (the elastic-pod acceptance shapes):

    * ``poisson`` — constant ``rate``/s (exponential gaps)
    * ``diurnal`` — sinusoidal swing between 10% and 100% of ``rate``
      over each ``period`` seconds: a compressed day, quiet → peak →
      quiet, which is the load curve that should trigger one scale-up
      and one scale-down per cycle
    * ``burst`` — square wave: full ``rate`` for the first quarter of
      each ``period``, 5% between bursts — the pathological on/off
      pattern that punishes a policy with no hysteresis
    """
    if shape not in ("poisson", "diurnal", "burst"):
        raise ValueError(f"unknown arrival shape {shape!r}; expected "
                         "poisson|diurnal|burst")
    rng = random.Random(seed)
    period = max(period, 1e-3)

    def rate_at(t: float) -> float:
        if shape == "diurnal":
            swing = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period))
            return max(rate * (0.1 + 0.9 * swing), 1e-3)
        if shape == "burst":
            return rate if (t % period) < period / 4.0 \
                else max(rate * 0.05, 1e-3)
        return rate

    t, rows = 0.0, []
    for _ in range(max(1, n)):
        rows.append({"offset_s": round(t, 6),
                     "prompt_tokens": prompt_tokens,
                     "max_tokens": max_tokens,
                     "priority": "standard"})
        t += rng.expovariate(rate_at(t)) if rate > 0 else 0.0
    return {"version": 1, "shape": shape, "requests": rows}


def parse_slo(spec: str) -> dict[str, float]:
    """``interactive=1500,standard=5000`` → per-class TTFT p95 budgets
    in milliseconds."""
    out = {}
    for part in spec.split(","):
        name, _, v = part.partition("=")
        name = name.strip().lower()
        if name not in PRIORITIES:
            raise ValueError(f"unknown priority class {name!r} in --slo; "
                             f"expected {'|'.join(PRIORITIES)}")
        out[name] = float(v)
    return out


class AvailabilitySampler(threading.Thread):
    """Polls ``GET /health`` while the replay runs and measures
    unavailability *windows* (consecutive failed samples count as one
    outage of their combined length), so the report can bound
    availability-p95 instead of asserting it."""

    def __init__(self, base: str, interval: float = 0.25):
        super().__init__(daemon=True, name="availability-sampler")
        self.base = base
        self.interval = interval
        self.samples = 0
        self.windows: list[float] = []
        self._down_since: float | None = None
        self._stop_evt = threading.Event()

    def run(self) -> None:
        while not self._stop_evt.is_set():
            try:
                h = _get_json(self.base, "/health", timeout=2.0)
                ok = bool(h.get("ready", h.get("status") == "ok"))
            except Exception:
                ok = False
            now = time.monotonic()
            self.samples += 1
            if ok and self._down_since is not None:
                self.windows.append(now - self._down_since)
                self._down_since = None
            elif not ok and self._down_since is None:
                self._down_since = now
            self._stop_evt.wait(self.interval)

    def stop(self) -> None:
        if self._down_since is not None:
            self.windows.append(time.monotonic() - self._down_since)
            self._down_since = None
        self._stop_evt.set()

    def report(self, bound_p95_s: float | None = None) -> dict:
        w = sorted(self.windows)
        rep = {
            "samples": self.samples,
            "unavailable_windows": len(w),
            "unavailable_p95_s": round(_pct(w, 0.95), 3) if w else 0.0,
            "unavailable_max_s": round(w[-1], 3) if w else 0.0,
        }
        if bound_p95_s is not None:
            rep["bound_p95_s"] = bound_p95_s
            rep["verdict"] = "pass" \
                if rep["unavailable_p95_s"] <= bound_p95_s else "fail"
        return rep


def parse_mix(spec: str) -> list[tuple[str, float]]:
    """``interactive=0.2,standard=0.3,batch=0.5`` → cumulative weights."""
    weights = []
    for part in spec.split(","):
        name, _, w = part.partition("=")
        name = name.strip().lower()
        if name not in PRIORITIES:
            raise ValueError(f"unknown priority class {name!r} in --mix; "
                             f"expected {'|'.join(PRIORITIES)}")
        weights.append((name, float(w)))
    total = sum(w for _, w in weights)
    if total <= 0:
        raise ValueError("--mix weights must sum to a positive value")
    acc, out = 0.0, []
    for name, w in weights:
        acc += w / total
        out.append((name, acc))
    return out


def _assign(mix, rng) -> str:
    x = rng.random()
    for name, cum in mix:
        if x <= cum:
            return name
    return mix[-1][0]


# -- one streamed request -------------------------------------------------
class _Result:
    __slots__ = ("priority", "status", "ttft_s", "itl", "finish", "error")

    def __init__(self, priority):
        self.priority = priority
        self.status = None          # HTTP status (int) or None on error
        self.ttft_s = None
        self.itl: list[float] = []
        self.finish = None
        self.error = None


def _one_request(base: str, row: dict, priority: str, timeout: float,
                 results: list, lock: threading.Lock) -> None:
    res = _Result(priority)
    prompt = "replay " * max(1, row.get("prompt_tokens", 8) // 2)
    body = json.dumps({"prompt": prompt.strip(),
                       "max_tokens": row.get("max_tokens", 16),
                       "stream": True,
                       "priority": priority}).encode()
    req = urllib.request.Request(
        base + "/v1/completions", body,
        headers={"Content-Type": "application/json"})
    t0 = time.monotonic()
    last = None
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            res.status = r.status
            for raw in r:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    break
                now = time.monotonic()
                if res.ttft_s is None:
                    res.ttft_s = now - t0
                elif last is not None:
                    res.itl.append(now - last)
                last = now
                try:
                    chunk = json.loads(payload)
                except ValueError:
                    continue
                for c in chunk.get("choices") or []:
                    if c.get("finish_reason"):
                        res.finish = c["finish_reason"]
                if "error" in chunk:
                    res.error = chunk["error"].get("message", "stream error")
    except urllib.error.HTTPError as e:
        res.status = e.code
        try:
            res.error = json.loads(e.read()).get("error")
        except Exception:
            res.error = f"http {e.code}"
    except OSError as e:
        res.error = str(e)
    with lock:
        results.append(res)


# -- replay + report ------------------------------------------------------
def _pct(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _counter_totals(metrics: dict) -> dict:
    """Flatten the families we care about into ``family`` /
    ``family{label}`` scalar totals (labeled families arrive as dicts)."""
    out = {}
    for fam in _COUNTER_FAMILIES:
        v = metrics.get(fam)
        if isinstance(v, dict):
            for label, n in v.items():
                out[f"{fam}{{{label}}}"] = n
            out[fam] = sum(v.values())
        elif v is not None:
            out[fam] = v
    return out


def replay_trace(base: str, trace: dict, *, speed: float = 1.0,
                 mix: str | None = None, seed: int = 0,
                 timeout: float = 240.0,
                 slo_ttft_ms: float | None = None,
                 slo_ms: dict[str, float] | None = None,
                 availability_bound_s: float | None = None,
                 sample_availability: bool = False) -> dict:
    """Replay ``trace`` against ``base`` and return the report dict
    (also the library entry point used by tests and fault drills).
    ``slo_ms`` carries per-class TTFT p95 budgets (``parse_slo``);
    ``slo_ttft_ms`` is the interactive-only legacy spelling.  With
    ``availability_bound_s`` (or ``sample_availability``) a sampler
    thread polls ``/health`` throughout and the report gains an
    ``availability`` block with an unavailability-window p95 — and a
    pass/fail verdict against the bound."""
    rows = trace.get("requests") or []
    if not rows:
        raise ValueError("trace has no requests")
    rng = random.Random(seed)
    mix_cum = parse_mix(mix) if mix else None
    before = _counter_totals(_get_json(base, "/metrics"))
    # event-journal cursor: whatever the pod journal records during the
    # replay window (respawns, hand-offs, preemptions…) lands in the
    # report — the drill's causal context next to the latency numbers
    try:
        ev_cursor = _get_json(base, "/debug/events").get("next_seq")
    except Exception:
        ev_cursor = None
    sampler = None
    if availability_bound_s is not None or sample_availability:
        sampler = AvailabilitySampler(base)
        sampler.start()

    results: list[_Result] = []
    lock = threading.Lock()
    threads = []
    t_start = time.monotonic()
    for row in rows:
        prio = _assign(mix_cum, rng) if mix_cum \
            else (row.get("priority") or "standard")
        due = t_start + row.get("offset_s", 0.0) / max(speed, 1e-9)
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=_one_request,
                             args=(base, row, prio, timeout, results, lock),
                             daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout)
    wall = time.monotonic() - t_start
    if sampler is not None:
        sampler.stop()
        sampler.join(timeout=2.0)

    after = _counter_totals(_get_json(base, "/metrics"))
    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in sorted(set(before) | set(after))
              if after.get(k, 0) != before.get(k, 0)}

    classes = {}
    for name in PRIORITIES:
        rs = [r for r in results if r.priority == name]
        if not rs:
            continue
        ttfts = sorted(r.ttft_s for r in rs if r.ttft_s is not None)
        itls = sorted(g for r in rs for g in r.itl)
        finishes: dict[str, int] = {}
        for r in rs:
            if r.finish:
                finishes[r.finish] = finishes.get(r.finish, 0) + 1
        row = {
            "sent": len(rs),
            "ok": sum(1 for r in rs if r.status == 200 and not r.error),
            "shed_429": sum(1 for r in rs if r.status == 429),
            "errors": sum(1 for r in rs
                          if r.error and r.status not in (200, 429)),
            "finish_reasons": finishes,
            "ttft_p50_ms": round(_pct(ttfts, 0.5) * 1e3, 1) if ttfts
            else None,
            "ttft_p95_ms": round(_pct(ttfts, 0.95) * 1e3, 1) if ttfts
            else None,
            "itl_p50_ms": round(_pct(itls, 0.5) * 1e3, 1) if itls else None,
            "itl_p95_ms": round(_pct(itls, 0.95) * 1e3, 1) if itls else None,
        }
        budget = (slo_ms or {}).get(name)
        if budget is None and slo_ttft_ms is not None \
                and name == "interactive":
            budget = slo_ttft_ms
        if budget is not None:
            row["slo_budget_ms"] = budget
            row["slo_verdict"] = (
                "pass" if ttfts and row["ttft_p95_ms"] <= budget
                else "fail")
        classes[name] = row

    try:
        slo = (_get_json(base, "/health").get("slo") or {}).get("status")
    except Exception:
        slo = None
    report = {"base": base, "speed": speed, "wall_s": round(wall, 3),
              "requests": len(rows), "classes": classes,
              "metric_deltas": deltas, "server_slo_status": slo}
    if ev_cursor is not None:
        try:
            snap = _get_json(base, f"/debug/events?since={ev_cursor}")
            events = snap.get("events") or []
            kinds: dict[str, int] = {}
            for ev in events:
                kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
            report["journal_events"] = {"count": len(events),
                                        "kinds": kinds, "events": events}
        except Exception:
            pass
    if sampler is not None:
        report["availability"] = sampler.report(availability_bound_s)
    return report


def report_verdicts(report: dict) -> list[str]:
    """Every pass/fail verdict the report carries — per-class SLO plus
    the availability bound — so callers gate on one list."""
    out = [c["slo_verdict"] for c in report["classes"].values()
           if "slo_verdict" in c]
    avail = report.get("availability") or {}
    if "verdict" in avail:
        out.append(avail["verdict"])
    return out


def print_report(report: dict) -> None:
    print(f"replayed {report['requests']} requests at "
          f"{report['speed']}x in {report['wall_s']}s "
          f"against {report['base']}")
    for name, c in report["classes"].items():
        verdict = f"  slo={c['slo_verdict']}" if "slo_verdict" in c else ""
        print(f"  {name:<12} sent={c['sent']:<4} ok={c['ok']:<4} "
              f"shed429={c['shed_429']:<4} "
              f"ttft p50/p95={c['ttft_p50_ms']}/{c['ttft_p95_ms']}ms "
              f"itl p50/p95={c['itl_p50_ms']}/{c['itl_p95_ms']}ms "
              f"finish={c['finish_reasons']}{verdict}")
    if report["metric_deltas"]:
        print("  server counter deltas:")
        for k, v in report["metric_deltas"].items():
            print(f"    {k:<40} +{v}")
    if report.get("server_slo_status"):
        print(f"  server SLO status: {report['server_slo_status']}")
    jev = report.get("journal_events")
    if jev and jev["count"]:
        mix = " ".join(f"{k}={v}" for k, v in sorted(jev["kinds"].items()))
        print(f"  journal events during replay: {jev['count']} ({mix})")
    avail = report.get("availability")
    if avail:
        verdict = f"  verdict={avail['verdict']}" if "verdict" in avail \
            else ""
        print(f"  availability: {avail['samples']} samples, "
              f"{avail['unavailable_windows']} outage window(s), "
              f"p95={avail['unavailable_p95_s']}s "
              f"max={avail['unavailable_max_s']}s{verdict}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("record", help="snapshot /debug/requests "
                                        "arrivals into a trace file")
    rec.add_argument("--base", required=True)
    rec.add_argument("--out", required=True)
    rec.add_argument("-n", type=int, default=500,
                     help="max flight records to pull")

    rep = sub.add_parser("replay", help="replay a trace (or a synthetic "
                                        "overload) and report per-class "
                                        "latency/shedding")
    rep.add_argument("--base", required=True)
    rep.add_argument("--trace", help="trace file from `record` "
                                     "(default: synthesize)")
    rep.add_argument("--speed", type=float, default=1.0,
                     help="replay at N× recorded speed")
    rep.add_argument("--mix", help="re-assign classes, e.g. "
                                   "interactive=0.2,standard=0.3,batch=0.5")
    rep.add_argument("--requests", type=int, default=32,
                     help="synthetic trace size (no --trace)")
    rep.add_argument("--rate", type=float, default=8.0,
                     help="synthetic arrivals per second (no --trace)")
    rep.add_argument("--shape", choices=["poisson", "diurnal", "burst"],
                     default="poisson",
                     help="synthetic arrival-rate curve (no --trace): "
                          "constant, sinusoidal quiet→peak→quiet, or "
                          "on/off square wave")
    rep.add_argument("--shape-period", type=float, default=20.0,
                     help="seconds per diurnal/burst cycle in trace "
                          "time (divide by --speed for wall time)")
    rep.add_argument("--max-tokens", type=int, default=16)
    rep.add_argument("--seed", type=int, default=0)
    rep.add_argument("--timeout", type=float, default=240.0)
    rep.add_argument("--slo-ttft-ms", type=float, default=None,
                     help="interactive TTFT p95 budget for the verdict")
    rep.add_argument("--slo", default=None,
                     help="per-class TTFT p95 budgets (ms), e.g. "
                          "interactive=1500,standard=5000 — each named "
                          "class gets its own pass/fail verdict")
    rep.add_argument("--availability-p95-s", type=float, default=None,
                     help="sample /health throughout and fail unless "
                          "the p95 unavailability window is within "
                          "this many seconds")
    rep.add_argument("--json", action="store_true",
                     help="emit the raw report dict instead of text")
    args = ap.parse_args(argv)

    if args.cmd == "record":
        trace = record_trace(args.base, args.n)
        with open(args.out, "w") as f:
            json.dump(trace, f, indent=1)
        print(f"recorded {len(trace['requests'])} arrivals -> {args.out}")
        return 0

    if args.trace:
        with open(args.trace) as f:
            trace = json.load(f)
    else:
        trace = synth_trace(args.requests, args.rate,
                            max_tokens=args.max_tokens, seed=args.seed,
                            shape=args.shape, period=args.shape_period)
    report = replay_trace(args.base, trace, speed=args.speed, mix=args.mix,
                          seed=args.seed, timeout=args.timeout,
                          slo_ttft_ms=args.slo_ttft_ms,
                          slo_ms=parse_slo(args.slo) if args.slo else None,
                          availability_bound_s=args.availability_p95_s)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print_report(report)
    return 1 if "fail" in report_verdicts(report) else 0


if __name__ == "__main__":
    raise SystemExit(main())
