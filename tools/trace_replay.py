#!/usr/bin/env python
"""Trace-record / trace-replay overload harness for multi-tenant QoS.

Two subcommands against a live replica (or the fleet router):

``record``
    Pull arrival history from ``GET /debug/requests`` (the flight
    recorder) and write a replayable trace: one row per request with its
    arrival offset, prompt size, token budget, and priority class.

``replay``
    Fire the trace back at the server at ``--speed N`` (N× compressed
    inter-arrival gaps), optionally re-assigning priority classes from a
    ``--mix`` distribution, and report what each class experienced:
    per-class TTFT / inter-token-latency percentiles, finish-reason
    counts (including honest ``preempted`` finishes), 429 sheds, and the
    server's preemption / shed counter deltas read from ``/metrics``.
    With ``--slo-ttft-ms`` the report carries a per-class verdict so a
    drill can assert "interactive held its budget while batch absorbed
    the overload".

Without ``--trace``, replay synthesizes an open-loop Poisson-ish trace
(``--requests`` arrivals at ``--rate`` per second), which is the usual
way to push a replica past capacity without first recording one.

Usage::

    python tools/trace_replay.py record --base http://127.0.0.1:8000 \
        --out /tmp/trace.json
    python tools/trace_replay.py replay --base http://127.0.0.1:8000 \
        --trace /tmp/trace.json --speed 2 \
        --mix interactive=0.2,standard=0.3,batch=0.5 --slo-ttft-ms 2000

Stdlib-only; exit code 0 iff every class with a configured SLO budget
met it (always 0 when no budget was given).
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request

PRIORITIES = ("interactive", "standard", "batch")

#: metric families whose deltas the report surfaces (JSON /metrics keys)
_COUNTER_FAMILIES = ("sched_preemptions", "admissions_shed",
                     "requests_rejected_429")


# -- trace shape ----------------------------------------------------------
def _get_json(base: str, path: str, timeout: float = 10.0):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def record_trace(base: str, n: int = 500) -> dict:
    """Build a trace from the server's flight recorder (newest-first
    summaries are re-sorted into arrival order; offsets are relative to
    the oldest arrival)."""
    recs = _get_json(base, f"/debug/requests?n={n}").get("requests") or []
    rows = [r for r in recs if r.get("submitted_at") is not None]
    rows.sort(key=lambda r: r["submitted_at"])
    if not rows:
        return {"version": 1, "requests": []}
    t0 = rows[0]["submitted_at"]
    out = []
    for r in rows:
        out.append({
            "offset_s": round(r["submitted_at"] - t0, 6),
            "prompt_tokens": int(r.get("n_prompt") or 8),
            "max_tokens": max(1, int(r.get("produced") or 16)),
            "priority": r.get("priority") or "standard",
        })
    return {"version": 1, "recorded_from": base, "requests": out}


def synth_trace(n: int, rate: float, *, max_tokens: int = 16,
                prompt_tokens: int = 8, seed: int = 0) -> dict:
    """Open-loop arrivals: exponential gaps at ``rate``/s (deterministic
    under ``seed`` so drills are reproducible)."""
    rng = random.Random(seed)
    t, rows = 0.0, []
    for _ in range(max(1, n)):
        rows.append({"offset_s": round(t, 6),
                     "prompt_tokens": prompt_tokens,
                     "max_tokens": max_tokens,
                     "priority": "standard"})
        t += rng.expovariate(rate) if rate > 0 else 0.0
    return {"version": 1, "requests": rows}


def parse_mix(spec: str) -> list[tuple[str, float]]:
    """``interactive=0.2,standard=0.3,batch=0.5`` → cumulative weights."""
    weights = []
    for part in spec.split(","):
        name, _, w = part.partition("=")
        name = name.strip().lower()
        if name not in PRIORITIES:
            raise ValueError(f"unknown priority class {name!r} in --mix; "
                             f"expected {'|'.join(PRIORITIES)}")
        weights.append((name, float(w)))
    total = sum(w for _, w in weights)
    if total <= 0:
        raise ValueError("--mix weights must sum to a positive value")
    acc, out = 0.0, []
    for name, w in weights:
        acc += w / total
        out.append((name, acc))
    return out


def _assign(mix, rng) -> str:
    x = rng.random()
    for name, cum in mix:
        if x <= cum:
            return name
    return mix[-1][0]


# -- one streamed request -------------------------------------------------
class _Result:
    __slots__ = ("priority", "status", "ttft_s", "itl", "finish", "error")

    def __init__(self, priority):
        self.priority = priority
        self.status = None          # HTTP status (int) or None on error
        self.ttft_s = None
        self.itl: list[float] = []
        self.finish = None
        self.error = None


def _one_request(base: str, row: dict, priority: str, timeout: float,
                 results: list, lock: threading.Lock) -> None:
    res = _Result(priority)
    prompt = "replay " * max(1, row.get("prompt_tokens", 8) // 2)
    body = json.dumps({"prompt": prompt.strip(),
                       "max_tokens": row.get("max_tokens", 16),
                       "stream": True,
                       "priority": priority}).encode()
    req = urllib.request.Request(
        base + "/v1/completions", body,
        headers={"Content-Type": "application/json"})
    t0 = time.monotonic()
    last = None
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            res.status = r.status
            for raw in r:
                line = raw.decode("utf-8", "replace").strip()
                if not line.startswith("data: "):
                    continue
                payload = line[len("data: "):]
                if payload == "[DONE]":
                    break
                now = time.monotonic()
                if res.ttft_s is None:
                    res.ttft_s = now - t0
                elif last is not None:
                    res.itl.append(now - last)
                last = now
                try:
                    chunk = json.loads(payload)
                except ValueError:
                    continue
                for c in chunk.get("choices") or []:
                    if c.get("finish_reason"):
                        res.finish = c["finish_reason"]
                if "error" in chunk:
                    res.error = chunk["error"].get("message", "stream error")
    except urllib.error.HTTPError as e:
        res.status = e.code
        try:
            res.error = json.loads(e.read()).get("error")
        except Exception:
            res.error = f"http {e.code}"
    except OSError as e:
        res.error = str(e)
    with lock:
        results.append(res)


# -- replay + report ------------------------------------------------------
def _pct(sorted_vals: list[float], q: float) -> float | None:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, max(0, round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def _counter_totals(metrics: dict) -> dict:
    """Flatten the families we care about into ``family`` /
    ``family{label}`` scalar totals (labeled families arrive as dicts)."""
    out = {}
    for fam in _COUNTER_FAMILIES:
        v = metrics.get(fam)
        if isinstance(v, dict):
            for label, n in v.items():
                out[f"{fam}{{{label}}}"] = n
            out[fam] = sum(v.values())
        elif v is not None:
            out[fam] = v
    return out


def replay_trace(base: str, trace: dict, *, speed: float = 1.0,
                 mix: str | None = None, seed: int = 0,
                 timeout: float = 240.0,
                 slo_ttft_ms: float | None = None) -> dict:
    """Replay ``trace`` against ``base`` and return the report dict
    (also the library entry point used by tests and fault drills)."""
    rows = trace.get("requests") or []
    if not rows:
        raise ValueError("trace has no requests")
    rng = random.Random(seed)
    mix_cum = parse_mix(mix) if mix else None
    before = _counter_totals(_get_json(base, "/metrics"))

    results: list[_Result] = []
    lock = threading.Lock()
    threads = []
    t_start = time.monotonic()
    for row in rows:
        prio = _assign(mix_cum, rng) if mix_cum \
            else (row.get("priority") or "standard")
        due = t_start + row.get("offset_s", 0.0) / max(speed, 1e-9)
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = threading.Thread(target=_one_request,
                             args=(base, row, prio, timeout, results, lock),
                             daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout)
    wall = time.monotonic() - t_start

    after = _counter_totals(_get_json(base, "/metrics"))
    deltas = {k: after.get(k, 0) - before.get(k, 0)
              for k in sorted(set(before) | set(after))
              if after.get(k, 0) != before.get(k, 0)}

    classes = {}
    for name in PRIORITIES:
        rs = [r for r in results if r.priority == name]
        if not rs:
            continue
        ttfts = sorted(r.ttft_s for r in rs if r.ttft_s is not None)
        itls = sorted(g for r in rs for g in r.itl)
        finishes: dict[str, int] = {}
        for r in rs:
            if r.finish:
                finishes[r.finish] = finishes.get(r.finish, 0) + 1
        row = {
            "sent": len(rs),
            "ok": sum(1 for r in rs if r.status == 200 and not r.error),
            "shed_429": sum(1 for r in rs if r.status == 429),
            "errors": sum(1 for r in rs
                          if r.error and r.status not in (200, 429)),
            "finish_reasons": finishes,
            "ttft_p50_ms": round(_pct(ttfts, 0.5) * 1e3, 1) if ttfts
            else None,
            "ttft_p95_ms": round(_pct(ttfts, 0.95) * 1e3, 1) if ttfts
            else None,
            "itl_p50_ms": round(_pct(itls, 0.5) * 1e3, 1) if itls else None,
            "itl_p95_ms": round(_pct(itls, 0.95) * 1e3, 1) if itls else None,
        }
        if slo_ttft_ms is not None and name == "interactive":
            row["slo_verdict"] = (
                "pass" if ttfts and row["ttft_p95_ms"] <= slo_ttft_ms
                else "fail")
        classes[name] = row

    try:
        slo = (_get_json(base, "/health").get("slo") or {}).get("status")
    except Exception:
        slo = None
    return {"base": base, "speed": speed, "wall_s": round(wall, 3),
            "requests": len(rows), "classes": classes,
            "metric_deltas": deltas, "server_slo_status": slo}


def print_report(report: dict) -> None:
    print(f"replayed {report['requests']} requests at "
          f"{report['speed']}x in {report['wall_s']}s "
          f"against {report['base']}")
    for name, c in report["classes"].items():
        verdict = f"  slo={c['slo_verdict']}" if "slo_verdict" in c else ""
        print(f"  {name:<12} sent={c['sent']:<4} ok={c['ok']:<4} "
              f"shed429={c['shed_429']:<4} "
              f"ttft p50/p95={c['ttft_p50_ms']}/{c['ttft_p95_ms']}ms "
              f"itl p50/p95={c['itl_p50_ms']}/{c['itl_p95_ms']}ms "
              f"finish={c['finish_reasons']}{verdict}")
    if report["metric_deltas"]:
        print("  server counter deltas:")
        for k, v in report["metric_deltas"].items():
            print(f"    {k:<40} +{v}")
    if report.get("server_slo_status"):
        print(f"  server SLO status: {report['server_slo_status']}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    rec = sub.add_parser("record", help="snapshot /debug/requests "
                                        "arrivals into a trace file")
    rec.add_argument("--base", required=True)
    rec.add_argument("--out", required=True)
    rec.add_argument("-n", type=int, default=500,
                     help="max flight records to pull")

    rep = sub.add_parser("replay", help="replay a trace (or a synthetic "
                                        "overload) and report per-class "
                                        "latency/shedding")
    rep.add_argument("--base", required=True)
    rep.add_argument("--trace", help="trace file from `record` "
                                     "(default: synthesize)")
    rep.add_argument("--speed", type=float, default=1.0,
                     help="replay at N× recorded speed")
    rep.add_argument("--mix", help="re-assign classes, e.g. "
                                   "interactive=0.2,standard=0.3,batch=0.5")
    rep.add_argument("--requests", type=int, default=32,
                     help="synthetic trace size (no --trace)")
    rep.add_argument("--rate", type=float, default=8.0,
                     help="synthetic arrivals per second (no --trace)")
    rep.add_argument("--max-tokens", type=int, default=16)
    rep.add_argument("--seed", type=int, default=0)
    rep.add_argument("--timeout", type=float, default=240.0)
    rep.add_argument("--slo-ttft-ms", type=float, default=None,
                     help="interactive TTFT p95 budget for the verdict")
    rep.add_argument("--json", action="store_true",
                     help="emit the raw report dict instead of text")
    args = ap.parse_args(argv)

    if args.cmd == "record":
        trace = record_trace(args.base, args.n)
        with open(args.out, "w") as f:
            json.dump(trace, f, indent=1)
        print(f"recorded {len(trace['requests'])} arrivals -> {args.out}")
        return 0

    if args.trace:
        with open(args.trace) as f:
            trace = json.load(f)
    else:
        trace = synth_trace(args.requests, args.rate,
                            max_tokens=args.max_tokens, seed=args.seed)
    report = replay_trace(args.base, trace, speed=args.speed, mix=args.mix,
                          seed=args.seed, timeout=args.timeout,
                          slo_ttft_ms=args.slo_ttft_ms)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print_report(report)
    verdicts = [c.get("slo_verdict") for c in report["classes"].values()]
    return 1 if "fail" in verdicts else 0


if __name__ == "__main__":
    raise SystemExit(main())
