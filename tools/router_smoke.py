#!/usr/bin/env python
"""One-command local fleet smoke: router + 2 tiny replicas, 8 clients.

Boots two ``dllama-api`` replicas on the tests' tiny synthetic model,
fronts them with the fleet router, fires 8 concurrent completions, and
asserts (a) zero errors and (b) balanced dispatch — every backend served
at least one request (read from the router's ``router_dispatch`` metric
family).  This is the cheapest end-to-end proof that the fleet path
works on this machine: registry probes, least-loaded dispatch, relay,
metrics.

Usage::

    python tools/router_smoke.py            # 8 requests, 2 replicas
    python tools/router_smoke.py -n 16

Exit code 0 iff the smoke passed.  CPU-only and fast-tier — wired into
tests/test_router.py under the ``router`` marker.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))  # tiny-model fixtures


def _wait_ready(proc, base: str, timeout: float = 120.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"process died:\n{proc.stdout.read() if proc.stdout else ''}")
        try:
            urllib.request.urlopen(base + "/health", timeout=1)
            return
        except OSError:
            time.sleep(0.2)
    raise RuntimeError(f"{base} did not come up")


def run_smoke(model: str, tok: str, *, n_requests: int = 8,
              n_replicas: int = 2) -> None:
    from fixtures import cpu_env, free_port
    env = cpu_env()
    replicas = []
    try:
        for _ in range(n_replicas):
            port = free_port()
            proc = subprocess.Popen(
                [sys.executable, "-m", "dllama_tpu.server.api",
                 "--model", model, "--tokenizer", tok,
                 "--port", str(port), "--temperature", "0",
                 "--max-seq-len", "64", "--batch-slots", "2",
                 "--kv-pages", "64", "--kv-page-size", "4"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            replicas.append((port, proc))
        router_port = free_port()
        router = subprocess.Popen(
            [sys.executable, "-m", "dllama_tpu.router",
             "--backends",
             ",".join(f"127.0.0.1:{p}" for p, _ in replicas),
             "--port", str(router_port), "--probe-interval", "0.5"],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        replicas.append((router_port, router))
        for port, proc in replicas:
            _wait_ready(proc, f"http://127.0.0.1:{port}")
        base = f"http://127.0.0.1:{router_port}"
        time.sleep(1.2)  # a probe round, so every backend is scored

        results: list = []

        def one(i: int) -> None:
            body = json.dumps({"prompt": f"request {i} says hello",
                               "max_tokens": 4}).encode()
            req = urllib.request.Request(
                base + "/v1/completions", body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=240) as r:
                    results.append(json.loads(r.read()))
            except Exception as e:  # noqa: BLE001 — reported below
                results.append(e)

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(n_requests)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(240)
        wall = time.monotonic() - t0

        errors = [r for r in results if not isinstance(r, dict)]
        if errors:
            raise AssertionError(f"{len(errors)}/{n_requests} requests "
                                 f"failed: {errors[:3]}")
        bad = [r for r in results
               if r["choices"][0]["finish_reason"] not in ("stop", "length")]
        if bad:
            raise AssertionError(f"unexpected finishes: {bad[:3]}")
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            metrics = json.loads(r.read())
        dispatch = metrics.get("router_dispatch") or {}
        idle = [f"127.0.0.1:{p}" for p, _ in replicas[:-1]
                if not dispatch.get(f"127.0.0.1:{p}")]
        if idle:
            raise AssertionError(
                f"dispatch was not balanced — {idle} served nothing "
                f"(router_dispatch={dispatch})")
        print(f"✅ fleet smoke: {n_requests} requests, 0 errors, "
              f"dispatch {dispatch}, {wall:.1f}s")
    finally:
        for _, proc in replicas:
            if proc.poll() is None:
                proc.kill()
            proc.wait()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-n", "--requests", type=int, default=8)
    ap.add_argument("--replicas", type=int, default=2)
    args = ap.parse_args(argv)
    import tempfile

    from fixtures import write_tiny_model, write_tiny_tokenizer
    with tempfile.TemporaryDirectory() as d:
        model, tok = os.path.join(d, "tiny.m"), os.path.join(d, "tiny.t")
        write_tiny_model(model)
        write_tiny_tokenizer(tok)
        try:
            run_smoke(model, tok, n_requests=args.requests,
                      n_replicas=args.replicas)
        except AssertionError as e:
            print(f"❌ {e}")
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
