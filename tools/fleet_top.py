#!/usr/bin/env python3
"""Live fleet dashboard for a dllama router / serve-pod front door.

Polls three public surfaces of one router/pod process (stdlib only —
no prometheus server, no grafana):

* ``GET /health``                 — registry rows: who is ejected,
                                    draining, retiring, and why
* ``GET /metrics?scope=fleet``    — the federated JSON registry: every
                                    replica's engine/scheduler/KV/SLO
                                    families keyed by address
* ``GET /debug/events?scope=fleet`` — the per-process event journals
                                    (spawn/death/respawn/hand-off/…)

and renders one screen: a per-replica table (occupancy, queue, KV
pressure, goodput, SLO burn, requests served) over a scrolling event
tail.  Uses curses when stdout is a terminal; ``--plain`` loops in
plain text; ``--once`` prints a single plain snapshot and exits (the
mode the tests drive).

Usage:
    python tools/fleet_top.py http://127.0.0.1:8080
    python tools/fleet_top.py http://127.0.0.1:8080 --once
    python tools/fleet_top.py http://127.0.0.1:8080 --plain -i 5
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from collections import deque


def fetch_json(base: str, path: str, timeout: float) -> dict | None:
    try:
        with urllib.request.urlopen(f"{base.rstrip('/')}{path}",
                                    timeout=timeout) as r:
            return json.loads(r.read().decode("utf-8"))
    except (OSError, ValueError, urllib.error.URLError):
        return None


def _num(snap: dict, key: str, default=None):
    v = snap.get(key, default)
    return v if isinstance(v, (int, float)) else default


def _max_burn(snap: dict) -> float | None:
    """Worst burn rate across objectives/windows (slo_burn_rate is a
    labeled-gauge JSON dict keyed ``objective/window``)."""
    burns = snap.get("slo_burn_rate")
    if isinstance(burns, dict) and burns:
        vals = [v for v in burns.values() if isinstance(v, (int, float))]
        return max(vals) if vals else None
    return None


def _chip_rate(snap: dict) -> float | None:
    """Chip-ms attributed per second of uptime (``class_chip_ms`` is a
    labeled-counter JSON dict keyed by QoS class) — how much actual
    chip-time this replica hands out per wall second."""
    cc = snap.get("class_chip_ms")
    up = _num(snap, "uptime_s")
    if not isinstance(cc, dict) or not up:
        return None
    vals = [v for v in cc.values() if isinstance(v, (int, float))]
    return sum(vals) / up if vals else None


def replica_rows(health: dict | None, fed: dict | None) -> list[dict]:
    """One row per replica: registry status joined with its federated
    metrics snapshot (stale snapshots render with a ``~`` marker)."""
    status: dict[str, dict] = {}
    for b in (health or {}).get("backends", []):
        addr = b.get("addr") or f"{b.get('host')}:{b.get('port')}"
        status[addr] = b
    rows = []
    for addr, entry in ((fed or {}).get("replicas") or {}).items():
        snap = entry.get("metrics") or {}
        st = status.get(addr, {})
        if not entry.get("up"):
            state = "DOWN"
        elif st.get("ejected") or entry.get("ejected"):
            state = "ejected"
        elif st.get("retiring") or entry.get("retiring"):
            state = "retiring"
        elif st.get("draining"):
            state = "draining"
        else:
            state = "up"
        rows.append({
            "addr": addr,
            "state": state,
            "stale": bool(entry.get("stale")),
            "slots": _num(snap, "sched_slots_occupied"),
            "queue": _num(snap, "sched_queue_depth"),
            "kv_used": _num(snap, "kv_pages_in_use"),
            "kv_total": _num(snap, "kv_pages_total"),
            "goodput": _num(snap, "sched_goodput_ratio"),
            "mfu": _num(snap, "mfu"),
            "chip_rate": _chip_rate(snap),
            "burn": _max_burn(snap),
            "served": _num(snap, "requests_served"),
        })
    rows.sort(key=lambda r: r["addr"])
    return rows


def _fmt(v, spec: str = "", dash: str = "-") -> str:
    if v is None:
        return dash
    return format(v, spec)


def format_rows(rows: list[dict], perf: dict | None = None) -> list[str]:
    hdr = (f"{'replica':<22} {'state':<9} {'slots':>5} {'queue':>5} "
           f"{'kv%':>6} {'goodput':>7} {'mfu':>6} {'chms/s':>7} "
           f"{'burn':>6} {'served':>8}")
    out = [hdr, "-" * len(hdr)]
    for r in rows:
        kv = None
        if r["kv_used"] is not None and r["kv_total"]:
            kv = 100.0 * r["kv_used"] / r["kv_total"]
        mark = "~" if r["stale"] else ""
        out.append(
            f"{r['addr']:<22} {mark + r['state']:<9} "
            f"{_fmt(r['slots'], '.0f'):>5} {_fmt(r['queue'], '.0f'):>5} "
            f"{_fmt(kv, '.1f'):>6} {_fmt(r['goodput'], '.3f'):>7} "
            f"{_fmt(r.get('mfu'), '.3f'):>6} "
            f"{_fmt(r.get('chip_rate'), '.1f'):>7} "
            f"{_fmt(r['burn'], '.2f'):>6} {_fmt(r['served'], '.0f'):>8}")
    footer = fleet_footer(perf)
    if footer:
        out.append(footer)
    return out


def fleet_footer(perf: dict | None) -> str | None:
    """One fleet-total line under the table: chip-time share by QoS
    class plus the fleet-mean MFU (the router's ``perf`` rollup in the
    federated JSON; older routers without it get no footer)."""
    if not perf:
        return None
    shares = perf.get("class_chip_share") or {}
    parts = [f"{cls}={shares[cls]:.0%}" for cls in sorted(shares)]
    mfu = perf.get("mfu_mean")
    if mfu is not None:
        parts.append(f"mfu~{mfu:.3f}")
    if not parts:
        return None
    return "fleet chip-time: " + " ".join(parts)


def format_event(src: str, ev: dict) -> str:
    ts = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
    extras = " ".join(f"{k}={v}" for k, v in sorted(ev.items())
                      if k not in ("ts", "seq", "kind"))
    return f"{ts} {src:<12} {ev.get('kind', '?'):<10} {extras}"


class EventTail:
    """Scrolling merge of every process's journal, deduplicated by a
    per-source ``seq`` cursor (``fleet_events`` ``since`` only covers
    the router's own journal — replica cursors live here)."""

    def __init__(self, keep: int = 200):
        self.cursors: dict[str, int] = {}
        self.lines: deque = deque(maxlen=keep)

    def _ingest(self, src: str, snap: dict | None) -> None:
        if not snap or "events" not in snap:
            return
        cur = self.cursors.get(src, -1)
        for ev in snap["events"]:
            seq = ev.get("seq", -1)
            if seq > cur:
                self.lines.append((ev.get("ts", 0.0), format_event(src, ev)))
                cur = max(cur, seq)
        self.cursors[src] = cur

    def update(self, doc: dict | None) -> None:
        if not doc:
            return
        self._ingest("router", doc.get("router"))
        for addr, snap in (doc.get("replicas") or {}).items():
            self._ingest(addr, snap)

    def tail(self, n: int) -> list[str]:
        return [line for _, line in sorted(self.lines)[-n:]]


def poll(base: str, timeout: float, tail: EventTail) -> dict:
    health = fetch_json(base, "/health", timeout)
    fed = fetch_json(base, "/metrics?scope=fleet", timeout)
    events = fetch_json(base, "/debug/events?scope=fleet", timeout)
    tail.update(events)
    return {"health": health, "fed": fed,
            "rows": replica_rows(health, fed)}


def render_plain(base: str, snap: dict, tail: EventTail,
                 events_n: int) -> str:
    health = snap["health"] or {}
    head = (f"fleet {base}  status={health.get('status', '?')}  "
            f"available={health.get('available', '?')}/"
            f"{health.get('total', '?')}  "
            f"model={health.get('model', '?')}")
    lines = [head, ""]
    lines += format_rows(snap["rows"], (snap["fed"] or {}).get("perf"))
    ev = tail.tail(events_n)
    if ev:
        lines += ["", "events:"] + [f"  {line}" for line in ev]
    return "\n".join(lines)


def run_curses(base: str, interval: float, timeout: float,
               events_n: int) -> int:
    import curses

    tail = EventTail()

    def loop(scr):
        curses.use_default_colors()
        scr.nodelay(True)
        scr.timeout(int(interval * 1000))
        while True:
            snap = poll(base, timeout, tail)
            scr.erase()
            maxy, maxx = scr.getmaxyx()
            text = render_plain(base, snap, tail,
                                max(0, maxy - len(snap["rows"]) - 6))
            for y, line in enumerate(text.splitlines()):
                if y >= maxy - 1:
                    break
                scr.addnstr(y, 0, line, maxx - 1)
            scr.refresh()
            ch = scr.getch()
            if ch in (ord("q"), 27):
                return

    curses.wrapper(loop)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", help="router/pod base URL, "
                                 "e.g. http://127.0.0.1:8080")
    ap.add_argument("-i", "--interval", type=float, default=2.0,
                    help="poll interval, seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print one plain-text snapshot and exit")
    ap.add_argument("--plain", action="store_true",
                    help="loop in plain text (no curses)")
    ap.add_argument("--events", type=int, default=12,
                    help="event-tail lines to show (default 12)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    if args.once or args.plain or not sys.stdout.isatty():
        tail = EventTail()
        while True:
            snap = poll(args.base, args.timeout, tail)
            if snap["health"] is None and snap["fed"] is None:
                print(f"fleet_top: {args.base} unreachable",
                      file=sys.stderr)
                return 1
            print(render_plain(args.base, snap, tail, args.events))
            if args.once or not (args.plain or sys.stdout.isatty()):
                return 0
            print()
            time.sleep(args.interval)
    try:
        return run_curses(args.base, args.interval, args.timeout,
                          args.events)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
