#!/usr/bin/env python
"""End-to-end fault drill against a REAL api-server process.

Starts ``python -m dllama_tpu.server.api`` on a tiny synthetic model with
a ``DLLAMA_FAULTS`` spec armed, fires real HTTP requests at it, and
asserts the endpoint-level contract for each degraded mode
(docs/ROBUSTNESS.md).  This is the out-of-process complement to
tests/test_faults.py: everything here crosses a real socket to a real
server under an injected fault, the way an operator would smoke-test a
deployment.

Usage::

    python tools/fault_drill.py                  # run every drill
    python tools/fault_drill.py deadline drain   # just these
    python tools/fault_drill.py --list

Each drill prints PASS/FAIL; exit code 0 iff all passed.  CPU-only and
tier-1-fast — the model is the tests' tiny fixture, written to a temp
dir.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # dllama_tpu (running from a checkout)
sys.path.insert(0, os.path.join(REPO, "tests"))  # the tiny-model fixtures

CHAT = "/v1/chat/completions"
BODY = {"messages": [{"role": "user", "content": "hello"}],
        "seed": 3, "max_tokens": 8}


def post(base: str, body: dict, timeout: float = 240.0):
    req = urllib.request.Request(
        base + CHAT, json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def post_to(base: str, path: str, body: dict, timeout: float = 240.0):
    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def get(base: str, path: str) -> dict:
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


class Server:
    """One api-server subprocess on the tiny fixture model."""

    def __init__(self, model: str, tokenizer: str, *, faults: str = "",
                 extra_flags: list[str] | None = None,
                 env_extra: dict | None = None, port: int | None = None):
        from fixtures import cpu_env, free_port
        # a fixed port lets the failover drill restart a replica at the
        # address the router already knows (allow_reuse_address rebinds)
        self.port = port if port is not None else free_port()
        self.base = f"http://127.0.0.1:{self.port}"
        env = cpu_env()
        if faults:
            env["DLLAMA_FAULTS"] = faults
        if env_extra:
            env.update(env_extra)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "dllama_tpu.server.api",
             "--model", model, "--tokenizer", tokenizer,
             "--port", str(self.port), "--temperature", "0",
             "--max-seq-len", "64", *(extra_flags or [])],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)

    def wait_ready(self, timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(f"server died:\n{self.proc.stdout.read()}")
            try:
                urllib.request.urlopen(self.base + "/health", timeout=1)
                return
            except OSError:
                time.sleep(0.2)
        raise RuntimeError("server did not come up")

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait()


# --- drills ---------------------------------------------------------------

def drill_deadline(model, tok):
    """Slow device steps + a 1s request deadline → finish_reason="timeout"
    within the deadline plus one chunk."""
    s = Server(model, tok, faults="engine.device_step=delay:0.4")
    try:
        s.wait_ready()
        t0 = time.monotonic()
        with post(s.base, dict(BODY, max_tokens=32, timeout=1.0)) as r:
            data = json.loads(r.read())
        elapsed = time.monotonic() - t0
        assert data["choices"][0]["finish_reason"] == "timeout", data
        assert data["usage"]["completion_tokens"] >= 1, data
        assert elapsed < 30.0, f"unbounded: {elapsed:.1f}s"  # compile + slack
        assert get(s.base, "/metrics")["deadline_timeouts"] >= 1
    finally:
        s.stop()


def drill_disconnect(model, tok):
    """Injected mid-SSE disconnect → the server logs the disconnect and the
    NEXT request over a fresh connection serves normally."""
    s = Server(model, tok, faults="server.emit_delta=disconnectx1")
    try:
        s.wait_ready()
        with post(s.base, dict(BODY, stream=True)) as r:
            raw = r.read()
        assert b"[DONE]" not in raw, "stream must abort, not terminate"
        with post(s.base, dict(BODY)) as r:
            data = json.loads(r.read())
        assert data["choices"][0]["finish_reason"] == "stop", data
        assert get(s.base, "/metrics")["client_disconnects"] >= 1
    finally:
        s.stop()


def drill_read_timeout(model, tok):
    """Stalled body read → 408 and the connection is closed."""
    s = Server(model, tok, faults="server.read_body=raise:TimeoutErrorx1")
    try:
        s.wait_ready()
        try:
            post(s.base, BODY)
            raise AssertionError("expected 408")
        except urllib.error.HTTPError as e:
            assert e.code == 408, e.code
        with post(s.base, BODY) as r:  # next request unaffected
            json.loads(r.read())
        assert get(s.base, "/metrics")["read_timeouts_408"] == 1
    finally:
        s.stop()


def drill_backpressure(model, tok):
    """--max-pending 1 + slow decode → concurrent request gets 429 with an
    honest Retry-After, and the admitted request is undisturbed."""
    s = Server(model, tok, faults="engine.device_step=delay:0.2",
               extra_flags=["--max-pending", "1"])
    try:
        s.wait_ready()
        results: dict = {}

        def slow():
            with post(s.base, dict(BODY, max_tokens=48)) as r:
                results["slow"] = json.loads(r.read())

        t = threading.Thread(target=slow)
        t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:  # wait until it is decoding
            if get(s.base, "/health")["in_flight"] >= 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("request never became active")
        try:
            post(s.base, dict(BODY, max_tokens=2))
            raise AssertionError("expected 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429, e.code
            assert int(e.headers["Retry-After"]) >= 1
        t.join(180)
        assert results["slow"]["choices"][0]["finish_reason"] == "stop"
    finally:
        s.stop()


def drill_drain(model, tok):
    """SIGTERM mid-request → in-flight request completes, process exits 0."""
    s = Server(model, tok, faults="engine.device_step=delay:0.15",
               extra_flags=["--drain-grace", "60", "--io-timeout", "5"])
    try:
        s.wait_ready()
        results: dict = {}

        def slow():
            with post(s.base, dict(BODY, max_tokens=48)) as r:
                results["slow"] = json.loads(r.read())

        t = threading.Thread(target=slow)
        t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if get(s.base, "/health")["in_flight"] >= 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("request never became active")
        s.proc.send_signal(signal.SIGTERM)
        t.join(180)
        assert results["slow"]["choices"][0]["finish_reason"] in (
            "stop", "timeout"), results
        assert s.proc.wait(timeout=120) == 0, "drain must exit cleanly"
    finally:
        s.stop()


def drill_corruption(model, tok):
    """A bit-flipped weight under --verify-weights → the server refuses to
    boot with a checksum ArtifactError; the pristine copy boots fine."""
    import shutil

    from dllama_tpu.io import integrity
    with tempfile.TemporaryDirectory() as d:
        bad = os.path.join(d, "bad.m")
        shutil.copy(model, bad)
        integrity.write_manifest(bad)
        man = integrity.load_manifest(integrity.manifest_path_for(bad))
        ent = next(iter(man["tensors"].values()))
        with open(bad, "r+b") as f:  # flip one bit inside the first tensor
            f.seek(ent["offset"] + ent["nbytes"] // 2)
            b = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([b[0] ^ 0x01]))
        s = Server(bad, tok, extra_flags=["--verify-weights"])
        try:
            rc = s.proc.wait(timeout=240)
            out = s.proc.stdout.read()
            assert rc != 0, "server must refuse a corrupt model"
            assert "checksum mismatch" in out, out[-2000:]
        finally:
            s.stop()
        # the same flags on an intact artifact serve normally
        good = os.path.join(d, "good.m")
        shutil.copy(model, good)
        integrity.write_manifest(good)
        s = Server(good, tok, extra_flags=["--verify-weights"])
        try:
            s.wait_ready()
            with post(s.base, BODY) as r:
                data = json.loads(r.read())
            assert data["choices"][0]["finish_reason"] == "stop", data
            assert get(s.base, "/metrics")["checksum_verified"] >= 1
        finally:
            s.stop()


def drill_snapshot_restart(model, tok):
    """SIGTERM with --snapshot-dir → state snapshots on drain; the next
    boot warm-starts from it (one-shot) and serves normally."""
    with tempfile.TemporaryDirectory() as d:
        snap = os.path.join(d, "engine.snap")
        s = Server(model, tok, extra_flags=["--snapshot-dir", d])
        try:
            s.wait_ready()
            with post(s.base, BODY) as r:
                json.loads(r.read())
            s.proc.send_signal(signal.SIGTERM)
            assert s.proc.wait(timeout=120) == 0, "drain must exit cleanly"
            assert os.path.exists(snap), "drain must write the snapshot"
        finally:
            s.stop()
        s = Server(model, tok, extra_flags=["--snapshot-dir", d])
        try:
            s.wait_ready()
            assert get(s.base, "/metrics")["snapshot_restores"] == 1
            assert not os.path.exists(snap), "restore must be one-shot"
            with post(s.base, BODY) as r:  # serves normally after restore
                data = json.loads(r.read())
            assert data["choices"][0]["finish_reason"] == "stop", data
        finally:
            s.stop()


def drill_latency_histogram(model, tok):
    """An injected 3s first-delta delay (server.emit_delta) must land the
    request in the right TTFT bucket of the Prometheus exposition: the
    fast buckets (le<=2.5) stay empty and the histogram sum reflects the
    delay — the end-to-end check that the TTFT timer ticks AFTER the
    emit-path flush, where a real latency fault would bite."""
    import re
    import urllib.request
    # delay only the FIRST delta: TTFT eats the 3s, inter-token stays fast
    s = Server(model, tok, faults="server.emit_delta=delay:3x1")
    try:
        s.wait_ready()
        with post(s.base, dict(BODY, stream=True)) as r:
            assert b"[DONE]" in r.read()
            rid = r.headers.get("X-Request-Id")
            assert rid, "stream response must carry X-Request-Id"
        req = urllib.request.Request(s.base + "/metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert "version=0.0.4" in r.headers.get("Content-Type", "")
            text = r.read().decode()

        def sample(name):
            m = re.search(rf"^{re.escape(name)} ([0-9.eE+-]+)$", text, re.M)
            assert m, f"missing sample {name}:\n{text[:2000]}"
            return float(m.group(1))

        # buckets are cumulative: everything at or under 2.5s must be
        # empty (the delayed delta cannot land in a fast bucket), and the
        # observed sum carries the injected 3s
        assert sample('dllama_ttft_seconds_bucket{le="2.5"}') == 0, text
        assert sample("dllama_ttft_seconds_count") == 1, text
        assert sample("dllama_ttft_seconds_sum") >= 3.0, text
        # later deltas were NOT delayed: inter-token gaps were observed
        # and none of them ate the 3s
        assert sample("dllama_inter_token_seconds_count") >= 1, text
        assert sample("dllama_inter_token_seconds_sum") < 3.0, text
    finally:
        s.stop()


def drill_slot_churn(model, tok):
    """--batch-slots 2 + a one-shot device fault → the poisoned dispatch
    500s its request, the slot is freed, and two waves of more requests
    than slots (churn over reused rows) all serve normally."""
    s = Server(model, tok,
               faults="engine.device_step=raise:RuntimeError:churnx1",
               extra_flags=["--batch-slots", "2"])
    try:
        s.wait_ready()
        h = get(s.base, "/health")
        assert h["batch_slots"] == 2, h
        assert h["scheduler"] and h["scheduler"]["slots"] == 2, h
        comp = {"prompt": "hello", "max_tokens": 6}
        # single-string /v1/completions rides the slot scheduler; the
        # first dispatch eats the injected fault
        try:
            post_to(s.base, "/v1/completions", comp)
            raise AssertionError("expected 500 from the poisoned dispatch")
        except urllib.error.HTTPError as e:
            assert e.code == 500, e.code
        # churn: two waves of 4 requests over 2 slots — every row gets
        # reused, including the one the fault just retired
        results: list = []

        def run():
            with post_to(s.base, "/v1/completions", comp) as r:
                results.append(json.loads(r.read()))

        for _ in range(2):
            ths = [threading.Thread(target=run) for _ in range(4)]
            for t in ths:
                t.start()
            for t in ths:
                t.join(240)
        assert len(results) == 8, f"only {len(results)}/8 served"
        for d in results:
            assert d["choices"][0]["finish_reason"] in ("stop", "length"), d
        occ = get(s.base, "/health")["scheduler"]
        assert occ["active"] == 0 and occ["queued"] == 0, occ
        retires = get(s.base, "/metrics")["sched_slot_retires"]
        assert any(k.endswith("/error") for k in retires), retires
        assert any(k.endswith("/length") or k.endswith("/stop")
                   for k in retires), retires
    finally:
        s.stop()


def drill_page_exhaustion(model, tok):
    """A paged KV pool sized for ~one request at a time: concurrent
    requests exhaust the pool, the overflow defers (queue) rather than
    erroring, submissions past the queue bound get 429, and retirements
    free the pages so every admitted request completes — no leak."""
    # seq_len 64 / page 4 → 16 pages/slot max; --kv-pages 16 gives 15
    # usable pages, and a max_tokens=48 request reserves ~13 of them, so
    # a second concurrent request cannot bind and waits for pages.
    # --no-prefix-reuse keeps the accounting exact (nothing retained).
    s = Server(model, tok, faults="engine.device_step=delay:0.2",
               extra_flags=["--batch-slots", "2", "--kv-pages", "16",
                            "--kv-page-size", "4", "--sched-max-queue", "1",
                            "--no-prefix-reuse"])
    try:
        s.wait_ready()
        occ = get(s.base, "/health")["scheduler"]
        assert occ["kv_pages_total"] == 15, occ
        comp = {"prompt": "hello", "max_tokens": 48}
        results: list = []

        def run():
            with post_to(s.base, "/v1/completions", comp) as r:
                results.append(json.loads(r.read()))

        t1 = threading.Thread(target=run)
        t1.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:  # wait until it holds its pages
            occ = get(s.base, "/health")["scheduler"]
            if occ["active"] >= 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("first request never became active")
        # these two cannot get pages: they defer in the queue (a free slot
        # exists — exhaustion must surface as queueing, not engine errors)
        t2 = threading.Thread(target=run)
        t2.start()
        t3 = threading.Thread(target=run)
        t3.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if get(s.base, "/metrics").get("kv_pool_exhausted", 0) >= 1 \
                    and get(s.base, "/health")["scheduler"]["queued"] >= 2:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("pool exhaustion was never recorded")
        # the queue is now at its bound: the next submission is refused
        # with the same 429 + Retry-After contract as mutex backpressure
        try:
            post_to(s.base, "/v1/completions", dict(comp, max_tokens=2))
            raise AssertionError("expected 429 past the queue bound")
        except urllib.error.HTTPError as e:
            assert e.code == 429, e.code
            assert int(e.headers["Retry-After"]) >= 1
        # retirement frees pages: every deferred request binds and serves
        for t in (t1, t2, t3):
            t.join(300)
        assert len(results) == 3, f"only {len(results)}/3 served"
        for d in results:
            assert d["choices"][0]["finish_reason"] in ("stop", "length"), d
        occ = get(s.base, "/health")["scheduler"]
        assert occ["active"] == 0 and occ["queued"] == 0, occ
        assert occ["kv_pages_free"] == 15, f"page leak: {occ}"
    finally:
        s.stop()


def drill_page_pressure(model, tok):
    """KV tiering under over-commit: a pool sized at ~40% of the
    workload's full-reservation demand.  Under --kv-reserve full a page
    hog starves small requests — they sit queued against a FREE slot
    until the queue bound refuses the next one (429).  Under optimistic
    the same pool seats them immediately (pages reclaimed by spilling
    the hog to host RAM and paging it back in), zero 429s, and every
    completion stays byte-identical to its uncontended solo run."""
    # page 4, 2 slots, 15 usable pages (--kv-pages 16).  The hog ("hello"
    # = 2 tokens under the tiny tokenizer) fully reserves ceil((2 + 50)/4)
    # = 13 pages; each small (2-3 tokens + 12 new) needs 4.  Under full,
    # a small can never bind beside the hog (free = 2 < 4); under
    # optimistic it binds ceil((2 + headroom 4)/4) = 2 pages and grows,
    # spilling the hog.  --no-prefix-reuse keeps the page audit exact.
    hog = {"prompt": "hello", "max_tokens": 50}
    smalls = [{"prompt": p, "max_tokens": 12}
              for p in ("hi", "hello hi", "hi hello")]
    flags = ["--batch-slots", "2", "--kv-pages", "16",
             "--kv-page-size", "4", "--sched-max-queue", "1",
             "--no-prefix-reuse"]

    def runner(base, results, errors, key, body):
        def one():
            try:
                with post_to(base, "/v1/completions", body) as r:
                    results[key] = json.loads(r.read())["choices"][0]
            except urllib.error.HTTPError as e:
                errors[key] = e.code
        t = threading.Thread(target=one)
        t.start()
        return t

    def wait_occ(base, pred, what, timeout=120):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            occ = get(base, "/health")["scheduler"]
            if pred(occ):
                return occ
            time.sleep(0.05)
        raise AssertionError(f"timed out waiting for {what}: {occ}")

    # -- phase 1: full reservation starves smalls behind the hog -------
    s = Server(model, tok, faults="engine.device_step=delay:0.3",
               extra_flags=flags)
    try:
        s.wait_ready()
        results: dict = {}
        errors: dict = {}
        ts = [runner(s.base, results, errors, "hog", hog)]
        wait_occ(s.base, lambda o: o["active"] >= 1, "hog active")
        # small1: a slot is FREE, but the hog holds 13 of 15 pages —
        # full reservation cannot bind 6, so it queues (and its queued
        # presence clamps the hog's decode bursts: the hog crawls)
        ts.append(runner(s.base, results, errors, "s0", smalls[0]))
        wait_occ(s.base, lambda o: o["queued"] >= 1 and o["active"] == 1,
                 "small starved against a free slot")
        # the queue is visible at submit; the exhausted counter only
        # ticks when the scheduler next ATTEMPTS the bind — poll for it
        deadline = time.monotonic() + 60
        while get(s.base, "/metrics").get("kv_pool_exhausted", 0) < 1:
            assert time.monotonic() < deadline, \
                "kv_pool_exhausted never incremented for the starved small"
            time.sleep(0.1)
        ts.append(runner(s.base, results, errors, "s1", smalls[1]))
        wait_occ(s.base, lambda o: o["queued"] >= 2, "second small queued")
        # queue now at max-queue + free = 2: the next submission is
        # refused — full reservation turned a memory shortfall into 429s
        try:
            post_to(s.base, "/v1/completions", dict(smalls[2]))
            raise AssertionError("expected 429 past the queue bound")
        except urllib.error.HTTPError as e:
            assert e.code == 429, e.code
        for t in ts:
            t.join(300)
        assert not errors, f"admitted requests must finish: {errors}"
    finally:
        s.stop()
    # -- phase 2: optimistic + spill serves the same load, zero 429s ---
    s = Server(model, tok, faults="engine.device_step=delay:0.3",
               extra_flags=flags + ["--kv-reserve", "optimistic",
                                    "--spill-headroom", "4",
                                    "--kv-host-pool-mb", "8"])
    try:
        s.wait_ready()
        kvp = get(s.base, "/health")["capacity"]["kv_pressure"]
        assert kvp["reserve"] == "optimistic", kvp
        total = get(s.base, "/health")["scheduler"]["kv_pages_total"]
        # solo greedy references (zero contention): the tiering path
        # must reproduce these byte-for-byte
        refs = {}
        for key, body in [("hog", hog)] + list(zip(
                ("s0", "s1", "s2"), smalls)):
            with post_to(s.base, "/v1/completions", body) as r:
                refs[key] = json.loads(r.read())["choices"][0]["text"]
        results, errors = {}, {}
        ts = [runner(s.base, results, errors, "hog", hog)]
        wait_occ(s.base, lambda o: o["active"] >= 1, "hog active")
        ts.append(runner(s.base, results, errors, "s0", smalls[0]))
        # THE tiering proof: the small gets a SLOT (impossible under
        # full — phase 1 left it queued against the same pool)
        wait_occ(s.base, lambda o: o["active"] >= 2 or o["queued"] == 0,
                 "small seated beside the hog")
        ts.append(runner(s.base, results, errors, "s1", smalls[1]))
        wait_occ(s.base, lambda o: o["queued"] == 0,
                 "queue drained before third small")
        ts.append(runner(s.base, results, errors, "s2", smalls[2]))
        for t in ts:
            t.join(300)
        assert not errors, f"optimistic must not refuse: {errors}"
        assert len(results) == 4, f"only {len(results)}/4 served"
        for key, c in sorted(results.items()):
            assert c["finish_reason"] in ("stop", "length"), c
            assert c["text"] == refs[key], \
                f"tiering drift on {key}:\n {c['text']!r}\n" \
                f" != {refs[key]!r}"
        m = get(s.base, "/metrics")
        assert m.get("kv_pages_spilled", 0) >= 1, \
            f"spill never engaged: {m.get('kv_pages_spilled')}"
        assert m.get("kv_pages_paged_in", 0) >= 1, m
        # drained: every page back on the free list, host pool empty
        occ = get(s.base, "/health")["scheduler"]
        assert occ["active"] == 0 and occ["queued"] == 0, occ
        assert occ["kv_pages_free"] == total, f"page leak: {occ}"
        assert occ["kv_pressure"]["host_pool_bytes"] == 0, occ
        assert occ["kv_pressure"]["spilled_slots"] == 0, occ
    finally:
        s.stop()


def drill_priority_preempt(model, tok):
    """Saturate every slot with batch-class decodes, then land an
    interactive burst: the scheduler must admit it by preempting a batch
    slot (DLREQ01 park), the preempted request must resume and finish
    byte-identical to its uncontended solo run (no re-prefill drift),
    and the pool must end with zero leaked pages."""
    # 2 slots, 31 usable pages (a 40-token batch decode holds ~12, so two
    # fit but a third request finds no free slot); the per-step delay
    # keeps the batch decodes on device long enough to be preempted.
    # --no-prefix-reuse keeps the end-state page audit exact.
    s = Server(model, tok, faults="engine.device_step=delay:0.15",
               extra_flags=["--batch-slots", "2", "--kv-pages", "32",
                            "--kv-page-size", "4", "--no-prefix-reuse"])
    try:
        s.wait_ready()
        total = get(s.base, "/health")["scheduler"]["kv_pages_total"]
        batch_bodies = [
            {"prompt": "Once upon a time", "max_tokens": 40,
             "priority": "batch"},
            {"prompt": "The quick brown fox", "max_tokens": 40,
             "priority": "batch"}]
        # solo greedy references, served with zero contention: the oracle
        # a preempted-and-resumed request must match byte for byte
        refs = []
        for body in batch_bodies:
            with post_to(s.base, "/v1/completions", body) as r:
                refs.append(json.loads(r.read())["choices"][0]["text"])

        results: dict = {}

        def run(key, body):
            with post_to(s.base, "/v1/completions", body) as r:
                results[key] = json.loads(r.read())["choices"][0]

        bts = [threading.Thread(target=run, args=(f"batch{i}", body))
               for i, body in enumerate(batch_bodies)]
        for t in bts:
            t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:  # both slots decoding batch
            if get(s.base, "/health")["scheduler"]["active"] >= 2:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("batch requests never filled the slots")
        # the interactive burst: no free slot → must preempt, not queue
        it = threading.Thread(target=run, args=(
            "inter", {"prompt": "hi", "max_tokens": 8,
                      "priority": "interactive"}))
        it.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            pre = get(s.base, "/metrics").get("sched_preemptions") or {}
            if sum(pre.values()) >= 1:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("interactive never triggered a preemption")
        assert pre.get("no_free_slot", 0) >= 1, pre
        it.join(240)
        for t in bts:
            t.join(300)
        assert results["inter"]["finish_reason"] in ("stop", "length"), \
            results["inter"]
        # the preempted batch request resumed from its parked DLREQ01
        # record: same bytes as the solo oracle, honest finish_reason
        for i in range(2):
            c = results[f"batch{i}"]
            assert c["finish_reason"] in ("stop", "length"), c
            assert c["text"] == refs[i], \
                f"resume drift on batch{i}:\n {c['text']!r}\n != {refs[i]!r}"
        # the flight recorder kept the preemption story
        recs = get(s.base, "/debug/requests?n=20")["requests"]
        preempted = [r for r in recs if (r.get("preempt_count") or 0) >= 1]
        assert preempted and preempted[0]["priority"] == "batch", recs
        occ = get(s.base, "/health")["scheduler"]
        assert occ["active"] == 0 and occ["queued"] == 0, occ
        assert occ["parked"] == 0, occ
        assert occ["kv_pages_free"] == total, f"page leak: {occ}"
    finally:
        s.stop()


def drill_slo_burn(model, tok):
    """An injected per-dispatch delay burns the ITL error budget: /health
    flips to violating with slo_violations_total >= 1, then recovers to
    ok after the (self-clearing) fault stops firing and the bad
    observations age out of both burn windows."""
    # --chunk 1 puts every decode token on its own delayed dispatch
    # (decode bursts would cluster the delay at burst boundaries); the
    # 0.3s delay beats the 0.25s bucket the 120ms target resolves to,
    # and x25 self-clears after roughly one request's worth of steps
    s = Server(model, tok, faults="engine.device_step=delay:0.3x25",
               extra_flags=["--slo", "itl_p99=120ms", "--chunk", "1"],
               env_extra={"DLLAMA_SLO_WINDOWS": "3s,10s"})
    try:
        s.wait_ready()
        h = get(s.base, "/health")
        assert h["slo"] is not None, "SLO engine must be armed"
        with post(s.base, dict(BODY, stream=True)) as r:
            assert b"[DONE]" in r.read()
        slo = get(s.base, "/health")["slo"]
        obj = slo["objectives"]["itl_p99"]
        assert slo["status"] == "violating", slo
        assert obj["verdict"] == "violating", slo
        assert all(b >= 1.0 for b in obj["burn"].values()), slo
        viol = get(s.base, "/metrics")["slo_violations"]
        assert viol.get("itl_p99", 0) >= 1, viol
        # recovery: the fault budget is exhausted; a clean request and
        # ageing windows (3s/10s) must walk the verdict back to ok
        with post(s.base, dict(BODY, stream=True)) as r:
            assert b"[DONE]" in r.read()
        deadline = time.monotonic() + 40
        while time.monotonic() < deadline:
            slo = get(s.base, "/health")["slo"]
            if slo["status"] == "ok":
                break
            time.sleep(0.5)
        else:
            raise AssertionError(f"never recovered: {slo}")
        # the violation count is a transition counter, not a scrape
        # counter: recovery must not have inflated it
        viol2 = get(s.base, "/metrics")["slo_violations"]
        assert viol2.get("itl_p99", 0) == viol.get("itl_p99"), viol2
    finally:
        s.stop()


def drill_overlap_stall(model, tok):
    """A slow host fanout (sched.host_fanout delay fault) stalls the
    scheduler thread after every dispatch.  With the two-deep pipeline
    (default) the next burst is already in flight during the stall, so
    the stall is hidden host time; with --no-sched-overlap it is exposed
    host_gap between dispatches.  The drill runs the identical greedy
    workload against both servers and asserts (a) byte-identical
    completion text — the pipeline never reorders or crosses tokens —
    and (b) a higher dispatch goodput ratio busy/(busy + host_gap) from
    the sched_step_time_ms components.  Idle (parked, no work) and pad
    (admission skew between the two client threads — a thread-timing
    race, not dispatch behavior) are excluded from the ratio: the
    injected stall is precisely the exposed-vs-hidden difference."""
    def run_workload(extra_flags):
        s = Server(model, tok, faults="sched.host_fanout=delay:0.05",
                   extra_flags=["--batch-slots", "2", *extra_flags])
        try:
            s.wait_ready()
            texts = [None, None]

            def run(i):
                with post_to(s.base, "/v1/completions",
                             {"prompt": "Once upon a time",
                              "max_tokens": 24}) as r:
                    texts[i] = json.loads(r.read())["choices"][0]["text"]

            ths = [threading.Thread(target=run, args=(i,)) for i in (0, 1)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            snap = get(s.base, "/metrics")
            comp = snap["sched_step_time_ms"]
            busy = comp.get("prefill", 0.0) + comp.get("decode", 0.0)
            exposed = comp.get("host_gap", 0.0)
            return (texts, busy / (busy + exposed) if busy else 0.0,
                    exposed, snap["sched_host_gap_hidden_ms"],
                    snap["sched_overlap_ratio"])
        finally:
            s.stop()

    texts_on, goodput_on, exp_on, hidden_on, ratio_on = run_workload([])
    texts_off, goodput_off, exp_off, hidden_off, ratio_off = run_workload(
        ["--no-sched-overlap"])
    assert all(texts_on) and texts_on == texts_off, \
        (texts_on, texts_off)  # no token reordering, greedy byte parity
    assert ratio_on > 0 and hidden_on > 0, (ratio_on, hidden_on)
    assert ratio_off == 0 and hidden_off == 0, (ratio_off, hidden_off)
    # the pipeline keeps the device fed through the stall: the stall ms
    # move from exposed host_gap into hidden time under the in-flight
    # dispatch, so the goodput ratio must come out ahead
    assert exp_off > exp_on + 50.0, (exp_off, exp_on)
    assert goodput_on > goodput_off, (goodput_on, goodput_off)


def drill_spec_reject_storm(model, tok):
    """An adversarial proposer (spec.propose=corrupt fault) swaps every
    draft for tokens chosen to never match the model's argmax — the
    speculative decoder's worst case.  The contract under the storm:
    completion text stays byte-identical to --spec off (rejected drafts
    are never emitted), the accept ratio collapses instead of erroring,
    throughput stays in the same regime as speculation off (each verify
    window still yields its one bonus token, so dispatch count does not
    grow), and the paged pool shows no KV page leak after retirement."""
    # paged pool (seq_len 64 / page 4, 2 slots) so the leak check is the
    # page-pool accounting itself; --no-prefix-reuse keeps it exact
    flags = ["--batch-slots", "2", "--kv-pages", "64", "--kv-page-size", "4",
             "--no-prefix-reuse"]

    def run_workload(spec_flags, faults=""):
        s = Server(model, tok, faults=faults,
                   extra_flags=flags + spec_flags)
        try:
            s.wait_ready()
            texts = [None, None]

            def run(i):
                with post_to(s.base, "/v1/completions",
                             {"prompt": "Once upon a time",
                              "max_tokens": 24}) as r:
                    texts[i] = json.loads(r.read())["choices"][0]["text"]

            t0 = time.monotonic()
            ths = [threading.Thread(target=run, args=(i,)) for i in (0, 1)]
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            elapsed = time.monotonic() - t0
            snap = get(s.base, "/metrics")
            occ = get(s.base, "/health")["scheduler"]
            return texts, elapsed, snap, occ
        finally:
            s.stop()

    texts_off, el_off, _, _ = run_workload(["--spec", "off"])
    texts_storm, el_storm, snap, occ = run_workload(
        ["--spec", "pld", "--spec-k", "4"],
        faults="spec.propose=corrupt")
    # byte parity: the storm's drafts all rejected, the emitted stream is
    # still the model's own greedy argmax
    assert all(texts_off) and texts_storm == texts_off, \
        (texts_storm, texts_off)
    # the storm actually stormed: drafts were forced and near-none stuck
    proposed = snap.get("sched_spec_proposed", 0)
    assert proposed > 0, "corrupt fault never forced a proposal"
    ratio = snap.get("sched_spec_accept_ratio", 0.0)
    assert ratio <= 0.2, f"adversarial drafts were accepted: {ratio}"
    # graceful degradation: every verify window still yields its bonus
    # token, so the dispatch count (and with it the wall) stays in the
    # spec-off regime rather than collapsing; the additive slack absorbs
    # the one-off verify-kernel compile the spec run pays
    assert el_storm <= el_off * 1.75 + 20.0, (el_storm, el_off)
    # no KV page leak: rejected-draft KV lives above the causal ceiling
    # inside each request's own reservation, never in extra pages
    assert occ["active"] == 0 and occ["queued"] == 0, occ
    assert occ["kv_pages_free"] == occ["kv_pages_total"], \
        f"page leak: {occ}"


class Router:
    """The fleet router subprocess (python -m dllama_tpu.router) — no
    model load, so it is up in well under a second."""

    def __init__(self, backends: list[int], **flags):
        from fixtures import free_port
        self.port = free_port()
        self.base = f"http://127.0.0.1:{self.port}"
        argv = [sys.executable, "-m", "dllama_tpu.router",
                "--backends", ",".join(f"127.0.0.1:{p}" for p in backends),
                "--port", str(self.port)]
        for k, v in flags.items():
            argv += [f"--{k.replace('_', '-')}", str(v)]
        self.proc = subprocess.Popen(argv, cwd=REPO, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)

    def wait_ready(self, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(f"router died:\n{self.proc.stdout.read()}")
            try:
                urllib.request.urlopen(self.base + "/health", timeout=1)
                return
            except OSError:
                time.sleep(0.1)
        raise RuntimeError("router did not come up")

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        self.proc.wait()


def drill_replica_failover(model, tok):
    """SIGKILL one of two replicas behind the router mid-decode: the
    in-flight stream finishes with finish_reason="replica_lost", fresh
    not-yet-streamed requests retry onto the survivor with zero errors,
    the dead backend ejects, and it re-admits after a restart."""
    flags = ["--batch-slots", "2", "--kv-pages", "64", "--kv-page-size",
             "4", "--io-timeout", "30"]
    a = Server(model, tok, faults="engine.device_step=delay:0.25",
               extra_flags=flags)
    b = Server(model, tok, faults="engine.device_step=delay:0.25",
               extra_flags=flags)
    router = None
    restarted = None
    try:
        a.wait_ready()
        b.wait_ready()
        router = Router([a.port, b.port], probe_interval=0.5,
                        eject_after=2, readmit_after=2, router_retries=3)
        router.wait_ready()
        time.sleep(1.2)  # one probe round so both backends are scored

        stream_result: dict = {}

        def run_stream():
            req = urllib.request.Request(
                router.base + "/v1/completions",
                json.dumps({"prompt": "Once upon a time",
                            "max_tokens": 48, "stream": True}).encode(),
                headers={"Content-Type": "application/json"})
            text, finish = "", None
            with urllib.request.urlopen(req, timeout=240) as r:
                for line in r:
                    line = line.strip()
                    if not line.startswith(b"data: "):
                        continue
                    payload = line[len(b"data: "):]
                    if payload == b"[DONE]":
                        break
                    evt = json.loads(payload)
                    c = evt["choices"][0]
                    text += c.get("text") or ""
                    stream_result["chars"] = len(text)
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
            stream_result.update(text=text, finish=finish)

        st = threading.Thread(target=run_stream)
        st.start()
        # wait for content to reach the CLIENT (a kill before first byte
        # would be retried invisibly — correct, but not this drill), then
        # find the replica actually decoding the stream
        victim = survivor = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if stream_result.get("chars", 0) < 1:
                time.sleep(0.05)
                continue
            for srv, other in ((a, b), (b, a)):
                try:
                    h = get(srv.base, "/health")
                except OSError:
                    continue
                if (h.get("scheduler") or {}).get("active", 0) >= 1:
                    victim, survivor = srv, other
                    break
            if victim is not None:
                break
            time.sleep(0.05)
        assert victim is not None, "stream never became active"
        victim.proc.kill()  # SIGKILL: no drain, no hand-off — a crash

        # queued (not-yet-streamed) requests must retry cleanly: some of
        # these dispatch to the dead replica before the probes eject it
        results: list = []

        def run_quick():
            try:
                with post_to(router.base, "/v1/completions",
                             {"prompt": "hi", "max_tokens": 2},
                             timeout=240) as r:
                    results.append(json.loads(r.read()))
            except Exception as e:  # noqa: BLE001 — the assert reports it
                results.append(e)

        qs = [threading.Thread(target=run_quick) for _ in range(4)]
        for t in qs:
            t.start()
        for t in qs:
            t.join(240)
        st.join(240)
        errors = [r for r in results if not isinstance(r, dict)]
        assert not errors, f"queued requests must not error: {errors}"
        bad = [r for r in results
               if r["choices"][0]["finish_reason"] not in ("stop", "length")]
        assert not bad, bad
        assert stream_result.get("finish") == "replica_lost", stream_result
        m = get(router.base, "/metrics")
        vkey = f"127.0.0.1:{victim.port}"
        assert m.get("router_ejections", {}).get(vkey, 0) >= 1, m
        assert m.get("router_replica_lost", 0) >= 1, m

        # restart the victim at the same address → hysteretic re-admission
        restarted = Server(model, tok,
                           faults="engine.device_step=delay:0.25",
                           extra_flags=flags, port=victim.port)
        restarted.wait_ready()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            rows = {r["addr"]: r for r in
                    get(router.base, "/health")["backends"]}
            if not rows[vkey]["ejected"]:
                break
            time.sleep(0.25)
        else:
            raise AssertionError("restarted replica never re-admitted")
        assert get(router.base, "/metrics") \
            .get("router_readmits", {}).get(vkey, 0) >= 1
    finally:
        if router is not None:
            router.stop()
        if restarted is not None:
            restarted.stop()
        a.stop()
        b.stop()


def drill_crash_resume(model, tok):
    """SIGKILL a replica mid-greedy-stream behind a resume-enabled
    router: the client's stream keeps going on the survivor and the
    total text is byte-identical to an uncontended solo run — finish
    reason stop/length, never replica_lost.  Afterwards the survivor
    shows zero leaked KV pages and the restarted victim re-admits
    (the same respawn-at-same-port recovery ``serve-pod --supervise``
    automates)."""
    flags = ["--batch-slots", "2", "--kv-pages", "64", "--kv-page-size",
             "4", "--io-timeout", "30", "--handoff", "--no-prefix-reuse"]
    body = {"prompt": "Once upon a time", "max_tokens": 40,
            "temperature": 0, "stream": True}
    a = Server(model, tok, faults="engine.device_step=delay:0.15",
               extra_flags=flags)
    b = Server(model, tok, faults="engine.device_step=delay:0.15",
               extra_flags=flags)
    router = None
    restarted = None
    try:
        a.wait_ready()
        b.wait_ready()
        router = Router([a.port, b.port], probe_interval=0.5,
                        eject_after=2, readmit_after=2, router_retries=3,
                        checkpoint_interval=1)
        router.wait_ready()
        time.sleep(1.2)  # one probe round so both backends are scored

        def run_stream(out: dict, req_body: dict = body):
            req = urllib.request.Request(
                router.base + "/v1/completions",
                json.dumps(req_body).encode(),
                headers={"Content-Type": "application/json"})
            text, finish = "", None
            with urllib.request.urlopen(req, timeout=240) as r:
                for line in r:
                    line = line.strip()
                    if not line.startswith(b"data: "):
                        continue
                    payload = line[len(b"data: "):]
                    if payload == b"[DONE]":
                        break
                    evt = json.loads(payload)
                    c = evt["choices"][0]
                    text += c.get("text") or ""
                    out["chars"] = len(text)
                    if c.get("finish_reason"):
                        finish = c["finish_reason"]
            out.update(text=text, finish=finish)

        # solo greedy oracle, no kill: the byte-parity reference
        oracle: dict = {}
        run_stream(oracle)
        assert oracle["finish"] in ("stop", "length"), oracle

        victim_run: dict = {}
        st = threading.Thread(target=run_stream, args=(victim_run,))
        st.start()
        # wait for content at the CLIENT, then find the decoding replica
        victim = survivor = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if victim_run.get("chars", 0) < 1:
                time.sleep(0.05)
                continue
            for srv, other in ((a, b), (b, a)):
                try:
                    h = get(srv.base, "/health")
                except OSError:
                    continue
                if (h.get("scheduler") or {}).get("active", 0) >= 1:
                    victim, survivor = srv, other
                    break
            if victim is not None:
                break
            time.sleep(0.05)
        assert victim is not None, "stream never became active"
        victim.proc.kill()  # SIGKILL: no drain, no hand-off — a crash
        st.join(240)
        # the resume contract: the client never saw the crash
        assert victim_run.get("finish") in ("stop", "length"), victim_run
        assert victim_run["text"] == oracle["text"], \
            f"resume drift:\n {victim_run['text']!r}\n != {oracle['text']!r}"
        # the outcome counter lands just AFTER the client's [DONE] (the
        # handler closes the peer connection first) — poll briefly
        resumes: dict = {}
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            resumes = get(router.base, "/metrics") \
                .get("router_resumes") or {}
            if sum(resumes.values()) >= 1:
                break
            time.sleep(0.2)
        assert sum(resumes.values()) >= 1, resumes
        assert set(resumes) <= {"checkpoint", "rerun"}, resumes
        # zero leaked KV pages on the survivor
        occ = get(survivor.base, "/health")["scheduler"]
        assert occ["active"] == 0 and occ["queued"] == 0, occ
        assert occ["kv_pages_free"] == occ["kv_pages_total"], \
            f"page leak: {occ}"
        # respawn at the same port → hysteretic re-admission
        restarted = Server(model, tok,
                           faults="engine.device_step=delay:0.15",
                           extra_flags=flags, port=victim.port)
        restarted.wait_ready()
        vkey = f"127.0.0.1:{victim.port}"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            rows = {r["addr"]: r for r in
                    get(router.base, "/health")["backends"]}
            if not rows[vkey]["ejected"]:
                break
            time.sleep(0.25)
        else:
            raise AssertionError("restarted replica never re-admitted")

        # non-greedy: no byte-parity guarantee exists, so even on this
        # resume-enabled router a mid-stream crash keeps the honest
        # finish_reason="replica_lost" — never a silently resampled tail
        sampled_run: dict = {}
        # no seed: seeded sampling rides the mutex path, which --kv-pages
        # replicas refuse — plain temperature>0 stays on the scheduler
        st = threading.Thread(target=run_stream, args=(
            sampled_run, dict(body, temperature=0.8)))
        st.start()
        victim2 = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if sampled_run.get("chars", 0) < 1:
                time.sleep(0.05)
                continue
            for srv in (survivor, restarted):
                try:
                    h = get(srv.base, "/health")
                except OSError:
                    continue
                if (h.get("scheduler") or {}).get("active", 0) >= 1:
                    victim2 = srv
                    break
            if victim2 is not None:
                break
            time.sleep(0.05)
        assert victim2 is not None, "sampled stream never became active"
        victim2.proc.kill()
        st.join(240)
        assert sampled_run.get("finish") == "replica_lost", sampled_run
        m = get(router.base, "/metrics")
        assert m.get("router_replica_lost", 0) >= 1, m
        # the sampled loss must not have minted any resume outcome
        assert set(m.get("router_resumes") or {}) <= \
            {"checkpoint", "rerun"}, m
    finally:
        if router is not None:
            router.stop()
        if restarted is not None:
            restarted.stop()
        a.stop()
        b.stop()


DRILLS = {
    "deadline": drill_deadline,
    "disconnect": drill_disconnect,
    "read_timeout": drill_read_timeout,
    "backpressure": drill_backpressure,
    "drain": drill_drain,
    "corruption": drill_corruption,
    "snapshot_restart": drill_snapshot_restart,
    "latency_histogram": drill_latency_histogram,
    "slot_churn": drill_slot_churn,
    "page_exhaustion": drill_page_exhaustion,
    "page_pressure": drill_page_pressure,
    "priority_preempt": drill_priority_preempt,
    "slo_burn": drill_slo_burn,
    "overlap_stall": drill_overlap_stall,
    "spec_reject_storm": drill_spec_reject_storm,
    "replica_failover": drill_replica_failover,
    "crash_resume": drill_crash_resume,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("drills", nargs="*",
                    help=f"subset to run (default: all of "
                         f"{', '.join(DRILLS)})")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)
    unknown = [d for d in args.drills if d not in DRILLS]
    if unknown:
        ap.error(f"unknown drill(s): {', '.join(unknown)} "
                 f"(choose from {', '.join(DRILLS)})")
    if args.list:
        for name, fn in DRILLS.items():
            print(f"{name:14s} {fn.__doc__.splitlines()[0]}")
        return 0
    from fixtures import write_tiny_model, write_tiny_tokenizer
    names = args.drills or list(DRILLS)
    failed = []
    with tempfile.TemporaryDirectory() as d:
        model, tok = os.path.join(d, "tiny.m"), os.path.join(d, "tiny.t")
        write_tiny_model(model)
        write_tiny_tokenizer(tok)
        for name in names:
            t0 = time.monotonic()
            try:
                DRILLS[name](model, tok)
                print(f"✅ {name} ({time.monotonic() - t0:.1f}s)")
            except Exception as e:
                failed.append(name)
                print(f"❌ {name}: {e}")
    if failed:
        print(f"{len(failed)}/{len(names)} drills failed: {', '.join(failed)}")
        return 1
    print(f"all {len(names)} drills passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
