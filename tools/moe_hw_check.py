"""Run the packed-MoE decode path on real hardware once (VERDICT r02 Next
#5): a Mixtral-shaped config through decode_chunk, proving the QLayerView
scalar-prefetch expert select (ops/q40.py) lowers under Mosaic — before
this, that path had only ever run in interpret mode on CPU.

Usage: python tools/moe_hw_check.py [--layers 2] [--steps 8]
Prints one line: `moe hw check: OK <ms/token>` or the failure.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--full", action="store_true",
                    help="mixtral-8x7b full shapes (needs ~12 GB HBM) "
                         "instead of a narrow stand-in")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bench import _zero_q40_params
    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.transformer import init_kv_cache
    from dllama_tpu.runtime.decode_loop import decode_chunk

    print(f"backend: {jax.default_backend()} {jax.devices()}", file=sys.stderr)
    on_tpu = jax.default_backend() == "tpu"
    if args.full:
        dim, hidden, heads, kv = 4096, 14336, 32, 8
    else:
        dim, hidden, heads, kv = 1024, 3584, 16, 4
    cfg = tiny_config(dim=dim, hidden_dim=hidden, n_layers=args.layers,
                      n_heads=heads, n_kv_heads=kv, vocab_size=32000,
                      seq_len=256, n_experts=8, n_active_experts=2,
                      dtype=jnp.bfloat16,
                      ).with_(quant_impl="pallas" if on_tpu else "pallas_interpret")

    params = _zero_q40_params(cfg)
    cache = init_kv_cache(cfg, batch=1)

    fn = jax.jit(
        lambda p, c, tok, pos, key: decode_chunk(
            p, cfg, c, tok, pos, key, steps=args.steps, temperature=0.0, topp=0.9),
        donate_argnums=(1,))
    tok = jnp.zeros((1,), jnp.int32)
    key = jax.random.PRNGKey(0)
    t0 = time.perf_counter()
    toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32(0), key)
    np.asarray(toks)
    print(f"compile+run: {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    t0 = time.perf_counter()
    toks, cache, tok, _, _ = fn(params, cache, tok, jnp.int32(args.steps), key)
    arr = np.asarray(toks)
    ms = (time.perf_counter() - t0) * 1000 / args.steps
    assert np.all(np.isfinite(arr)), "non-finite tokens"
    print(f"moe hw check: OK {ms:.2f} ms/token "
          f"({args.layers}L dim={dim} E=8 top2, {cfg.quant_impl})")


if __name__ == "__main__":
    main()
