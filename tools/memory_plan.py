"""HBM memory planner: will a model fit a mesh, and what is the smallest
mesh that fits?

Computes per-chip bytes for weights (packed Q40 or dense) and the KV
cache under the framework's sharding rules (docs/MEMORY.md; the
reference's RowMatmulSlice/ColMatmulSlice/KvCacheSlice semantics,
commands.cpp:8-105: matmul weights and kv heads shard 1/tp, norms /
embedding / routers replicate, the cache's sequence axis shards 1/sp,
batch 1/dp, experts 1/ep), and searches the (tp, sp) grid for the
smallest mesh that fits a per-chip budget — the planning the reference
leaves to trial-and-error OOM (its only guidance is 'This version does
not support more nodes than the number of KV heads',
transformer.cpp:88-91).

Usage:
    python tools/memory_plan.py llama3-8b --seq 8192 --tp 8
    python tools/memory_plan.py grok-314b --seq 8192 --fit
    python tools/memory_plan.py /path/to/model.m --seq 4096 --fit
"""

from __future__ import annotations

import argparse
import os
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

Q40_BYTES_PER_WEIGHT = 0.5 + 2 / 32   # nibble + f16-bit scale = 0.5625
V5E_HBM = 16e9
# runtime allowance: XLA scratch, the donated-cache double buffer during
# relayout, activation workspaces (decode activations are ~MB-scale)
OVERHEAD = 0.5e9

# (dim, hidden, layers, heads, kv_heads, vocab, experts, active, seq_max)
PRESETS = {
    "tinyllama-1.1b": (2048, 5632, 22, 32, 4, 32000, 0, 0, 2048),
    "llama2-7b": (4096, 11008, 32, 32, 32, 32000, 0, 0, 4096),
    "llama2-13b": (5120, 13824, 40, 40, 40, 32000, 0, 0, 4096),
    "llama2-70b": (8192, 28672, 80, 64, 8, 32000, 0, 0, 4096),
    "llama3-8b": (4096, 14336, 32, 32, 8, 128256, 0, 0, 8192),
    "mixtral-8x7b": (4096, 14336, 32, 32, 8, 32000, 8, 2, 32768),
    "grok-314b": (6144, 32768, 64, 48, 8, 131072, 8, 2, 8192),
}


def _cfg(name_or_path: str):
    from dllama_tpu.models.config import tiny_config

    if os.path.exists(name_or_path):
        from dllama_tpu.io import mfile
        from dllama_tpu.models.config import ModelConfig
        return ModelConfig.from_spec(mfile.read_spec(name_or_path))
    if name_or_path not in PRESETS:
        raise SystemExit(f"unknown model {name_or_path!r}; presets: "
                         f"{', '.join(PRESETS)} (or a .m path)")
    d, f, l, h, hkv, v, e, a, s = PRESETS[name_or_path]
    return tiny_config(dim=d, hidden_dim=f, n_layers=l, n_heads=h,
                       n_kv_heads=hkv, vocab_size=v, n_experts=e,
                       n_active_experts=a, seq_len=s)


def plan(cfg, tp=1, sp=1, dp=1, ep=1, seq_len=None, batch=1,
         kv_bytes=2, quant=True) -> dict:
    """Per-chip byte breakdown for cfg on a tp×sp×dp×ep mesh.

    Besides residency, the plan reports ``decode_read_per_step``: the
    weight bytes one decode step streams from HBM across the WHOLE mesh —
    dense weights once, plus only the ``n_active_experts`` routed experts'
    FFN bytes for MoE (non-owner ep shards read nothing: the lax.cond
    skip in q40._sharded_matmul_ep).  Dividing by aggregate HBM bandwidth
    gives the bandwidth-bound ms/token floor."""
    from dllama_tpu.models.params import param_shapes

    if cfg.n_kv_heads % tp:
        raise ValueError(
            f"tp={tp} does not divide nKvHeads={cfg.n_kv_heads} — the mesh "
            "cannot be realized (nSlices ≤ nKvHeads, transformer.cpp:88-91)")
    if cfg.is_moe and cfg.n_experts % ep:
        raise ValueError(f"ep={ep} does not divide nExperts={cfg.n_experts}")
    s = seq_len or cfg.seq_len
    if s % sp:
        raise ValueError(f"sp={sp} does not divide seq_len={s}")
    shapes = param_shapes(cfg)
    w_sharded = 0   # matmul weights: shard 1/tp (and experts 1/ep)
    w_repl = 0      # embedding/norms/router: replicated, bf16(2B)/f32(4B)
    decode_read = 0  # weight bytes one decode step reads, whole mesh
    for k, shp in shapes.items():
        n = 1
        for x in shp:
            n *= x
        if k in ("embedding",):
            w_repl += n * 2
            decode_read += cfg.dim * 2  # one row gathered per token
        elif k.startswith("rms"):
            w_repl += n * 4
            decode_read += n * 4
        elif k == "router":
            w_repl += n * 2
            decode_read += n * 2
        else:
            per_w = Q40_BYTES_PER_WEIGHT if quant else 2
            is_expert = k in ("up", "gate", "down")
            div = tp * (ep if is_expert else 1)
            if quant:
                # packed planes pad the input axis to the kernel's block
                # granularity (q40.padded_n; up to +9% on odd hidden dims,
                # e.g. TinyLlama's 5632→6144) — estimate what HBM actually
                # holds, not the logical element count (ADVICE r03)
                from dllama_tpu.ops.q40 import blocked_tiles_env, padded_n
                *lead, nin, dout = shp
                n = 1
                for x in lead:
                    n *= x
                if os.environ.get("DLLAMA_Q40_LAYOUT", "") == "blocked" \
                        and not is_expert:
                    # tile-contiguous storage also pads the OUTPUT axis to
                    # a tile_d multiple (q40.to_blocked; ~1% on the 7B
                    # shapes at the 2048 default)
                    # mirror to_blocked's clamp: planes narrower than the
                    # tile pad only to a 128 multiple
                    td = min(blocked_tiles_env()[1], -(-dout // 128) * 128)
                    dout = -(-dout // td) * td
                n *= padded_n(nin) * dout
            w_sharded += n * per_w / div
            if is_expert:
                # only the routed experts' tiles are streamed, each read
                # exactly once on its owner shard (the ep lax.cond skip)
                decode_read += n * per_w * cfg.n_active_experts / cfg.n_experts
            else:
                decode_read += n * per_w
    cache = 2 * cfg.n_layers * batch * cfg.n_kv_heads * s * cfg.head_size * kv_bytes
    cache /= tp * sp * max(dp, 1)  # kv heads /tp, seq /sp, batch /dp
    per_chip = w_sharded + w_repl + cache + OVERHEAD
    return {
        "weights_sharded": w_sharded, "weights_replicated": w_repl,
        "kv_cache": cache, "overhead": OVERHEAD, "per_chip": per_chip,
        "decode_read_per_step": decode_read,
        "fits_v5e": per_chip <= V5E_HBM,
    }


def find_fit(cfg, seq_len=None, budget=V5E_HBM, max_devices=256,
             batch=1, kv_bytes=2, quant=True) -> tuple | None:
    """Smallest (tp, sp, ep) whose per-chip footprint fits ``budget``.

    tp obeys the reference's nSlices ≤ nKvHeads constraint
    (transformer.cpp:88-91) and must divide the kv-head count; sp must
    divide the sequence length; ep (MoE only) must divide the expert
    count.  Returns (tp, sp, ep, plan) or None."""
    s = seq_len or cfg.seq_len
    tps = [t for t in range(1, cfg.n_kv_heads + 1) if cfg.n_kv_heads % t == 0]
    eps = ([e for e in range(1, cfg.n_experts + 1) if cfg.n_experts % e == 0]
           if cfg.is_moe else [1])
    best = None
    for tp in tps:
        for ep in eps:
            for sp in (1, 2, 4, 8, 16, 32):
                n = tp * sp * ep
                if s % sp or n > max_devices:
                    continue
                if best is not None and n >= best[0] * best[1] * best[2]:
                    continue
                p = plan(cfg, tp=tp, sp=sp, ep=ep, seq_len=s, batch=batch,
                         kv_bytes=kv_bytes, quant=quant)
                if p["per_chip"] <= budget:
                    best = (tp, sp, ep, p)
                    break  # larger sp only helps cache; this (tp, ep) fits
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", help="preset name or .m path")
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--ep", type=int, default=1)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--kv-dtype-bytes", type=float, default=2,
                    help="bytes per cache element: 4 f32, 2 bf16 (default), "
                         "1.03 for the int8 cache (--kv-cache-dtype q8: "
                         "1 B values + 4 B/Dh scales)")
    ap.add_argument("--dense", action="store_true",
                    help="dense bf16 weights instead of packed Q40")
    ap.add_argument("--fit", action="store_true",
                    help="search the smallest (tp, sp) that fits one v5e chip budget")
    args = ap.parse_args()

    cfg = _cfg(args.model)
    s = args.seq or cfg.seq_len
    p = plan(cfg, tp=args.tp, sp=args.sp, dp=args.dp, ep=args.ep,
             seq_len=s, batch=args.batch, kv_bytes=args.kv_dtype_bytes,
             quant=not args.dense)
    print(f"model {args.model}  seq {s}  mesh tp={args.tp} sp={args.sp} "
          f"dp={args.dp} ep={args.ep}")
    for k in ("weights_sharded", "weights_replicated", "kv_cache", "overhead"):
        print(f"  {k:20s} {p[k] / 1e9:8.2f} GB/chip")
    print(f"  {'per_chip':20s} {p['per_chip'] / 1e9:8.2f} GB/chip "
          f"{'✓ fits' if p['fits_v5e'] else '✗ exceeds'} 16 GB v5e")
    if args.fit:
        best = find_fit(cfg, seq_len=s, batch=args.batch,
                        kv_bytes=args.kv_dtype_bytes, quant=not args.dense)
        if best is None:
            print("  no (tp ≤ nKvHeads, sp ≤ 32, ep ≤ nExperts) mesh "
                  "fits a 16 GB chip")
        else:
            tp, sp, ep, bp = best
            print(f"  smallest fitting mesh: tp={tp} sp={sp} ep={ep} "
                  f"({tp * sp * ep} chips, {bp['per_chip'] / 1e9:.2f} GB/chip)")


if __name__ == "__main__":
    main()
