#!/usr/bin/env python3
"""Dump a running dllama-api server's span ring as a Chrome trace file.

Fetches ``GET /debug/trace?last=N`` (dllama_tpu/obs/trace.py) and writes
the Chrome ``trace_event`` JSON to a file loadable in ``chrome://tracing``
or https://ui.perfetto.dev — the cheap first-line latency attribution for
a live server (queue_wait / prefill / decode_chunk / emit / request spans
per request ID), no restart and no ``--profile-split`` XLA tracer needed.

With ``--slots`` it also fetches ``GET /debug/timeline`` (the scheduler's
per-dispatch slot timeline, obs/flight.py) and appends one named Perfetto
track per scheduler slot (pid 2): every dispatch becomes one event per
slot, named by that slot's phase (``prefill``/``decode``/``pad``), so the
goodput decomposition is visible as colored bars next to the request
spans — both use the same ``perf_counter`` clock.

With ``--fleet`` the base URL is a *router* (or serve-pod front door)
and the dump comes from ``GET /debug/trace?scope=fleet``: the router
stitches every replica's span ring plus its own into one wall-clock-
aligned Perfetto timeline — one named process track per replica, pod
journal entries (spawn/death/respawn/hand-off/resume…) as instant
markers — so a request that migrated across replicas shows up as one
trace id spanning multiple tracks. ``--trace ID`` filters to one
request's trace across the whole fleet.

Usage:
    python tools/trace_dump.py http://127.0.0.1:9090 [-o trace.json] [-n 20]
    python tools/trace_dump.py http://127.0.0.1:9090 --slots
    python tools/trace_dump.py http://127.0.0.1:8080 --fleet [--trace ID]
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from collections import Counter


def fetch_trace(base: str, last: int, timeout: float = 10.0) -> dict:
    url = f"{base.rstrip('/')}/debug/trace?last={last}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def fetch_timeline(base: str, n: int = 256, timeout: float = 10.0) -> dict:
    url = f"{base.rstrip('/')}/debug/timeline?n={n}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def fetch_fleet(base: str, trace: str | None,
                timeout: float = 10.0) -> dict:
    url = f"{base.rstrip('/')}/debug/trace?scope=fleet"
    if trace:
        url += f"&trace={trace}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def summarize_fleet(doc: dict) -> str:
    """Per-replica span/up table plus the distinct trace ids that span
    more than one process — the migrated requests worth opening."""
    fleet = doc.get("fleet", {})
    spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    marks = [e for e in doc.get("traceEvents", []) if e.get("ph") == "i"]
    lines = [f"{len(spans)} spans + {len(marks)} journal markers "
             f"from {len(fleet)} process(es):"]
    for name, info in sorted(fleet.items()):
        up = "up" if info.get("up") else "DOWN"
        lines.append(f"  {name:<22} {up:<5} {info.get('spans', 0):>5} spans")
    # trace ids seen on more than one pid = cross-replica requests
    procs: dict = {}
    for e in spans:
        tid = e.get("args", {}).get("trace_id")
        if tid:
            procs.setdefault(tid, set()).add(e.get("pid"))
    crossed = sorted(t for t, p in procs.items() if len(p) > 1)
    if crossed:
        lines.append(f"  {len(crossed)} trace(s) span multiple replicas:")
        for t in crossed[:8]:
            lines.append(f"    {t}")
    return "\n".join(lines)


def slot_events(doc: dict) -> list[dict]:
    """Chrome ``trace_event`` array for the slot timeline: pid 2, one
    named thread per scheduler slot, one X event per (dispatch, slot)
    named by the slot's phase in that dispatch."""
    steps = doc.get("steps", [])
    nslots = doc.get("slots", 0) or max(
        (len(e.get("slots", [])) for e in steps), default=0)
    events = [{"name": "process_name", "ph": "M", "pid": 2, "tid": 0,
               "args": {"name": "slot timeline"}}]
    for s in range(nslots):
        events.append({"name": "thread_name", "ph": "M", "pid": 2,
                       "tid": s, "args": {"name": f"slot {s}"}})
    for e in steps:
        ts = round(e["ts"] * 1e6, 3)
        dur = round(e["wall_ms"] * 1e3, 3)
        for slot in e.get("slots", []):
            args = {"tokens": slot.get("tokens", 0),
                    "steps": e.get("steps"), "t_width": e.get("t_width")}
            if slot.get("request_id"):
                args["request_id"] = slot["request_id"]
            if e.get("error"):
                args["error"] = True
            events.append({"name": slot.get("phase", "?"), "cat": "sched",
                           "ph": "X", "ts": ts, "dur": dur,
                           "pid": 2, "tid": slot.get("slot", 0),
                           "args": args})
    return events


def summarize(doc: dict) -> str:
    """Per-span-name count + total ms, so the terminal shows where the
    time went before anyone opens Perfetto."""
    spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    counts = Counter(e["name"] for e in spans)
    total_ms: Counter = Counter()
    for e in spans:
        total_ms[e["name"]] += e.get("dur", 0.0) / 1000.0
    rids = {e["args"]["request_id"] for e in spans
            if e.get("args", {}).get("request_id")}
    lines = [f"{len(spans)} spans across {len(rids)} request(s):"]
    for name, n in counts.most_common():
        lines.append(f"  {name:<16} x{n:<5} {total_ms[name]:9.1f} ms total")
    # QoS story: admissions per priority class, plus the preempt/resume
    # pairs with time spent parked (sched_preempt / sched_resume spans
    # carry priority, reason and parked_ms in their args)
    admits = Counter(e["args"].get("priority") or "?"
                     for e in spans if e["name"] == "sched_admit")
    preempts = Counter(e["args"].get("reason") or "?"
                       for e in spans if e["name"] == "sched_preempt")
    parked_ms = sum(e["args"].get("parked_ms") or 0.0
                    for e in spans if e["name"] == "sched_resume")
    if admits:
        mix = " ".join(f"{k}={v}" for k, v in admits.most_common())
        lines.append(f"  admits by class: {mix}")
    if preempts:
        why = " ".join(f"{k}={v}" for k, v in preempts.most_common())
        lines.append(f"  preemptions: {why}; "
                     f"{parked_ms:.0f} ms total parked")
    # speculative decoding story: sched_verify spans carry per-dispatch
    # proposed/accepted draft counts (--spec; runtime/spec.py)
    verifies = [e for e in spans if e["name"] == "sched_verify"]
    proposed = sum(e["args"].get("proposed") or 0 for e in verifies)
    accepted = sum(e["args"].get("accepted") or 0 for e in verifies)
    if proposed:
        lines.append(f"  speculation: {accepted}/{proposed} drafts "
                     f"accepted ({accepted / proposed:.2f}) over "
                     f"{len(verifies)} verify dispatches")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", help="server base URL, e.g. http://127.0.0.1:9090")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="output file (default trace.json)")
    ap.add_argument("-n", "--last", type=int, default=20,
                    help="number of most-recent requests to include")
    ap.add_argument("--slots", action="store_true",
                    help="also fetch /debug/timeline and add one Perfetto "
                         "track per scheduler slot (phase-named events)")
    ap.add_argument("--timeline-n", type=int, default=256,
                    help="with --slots: number of most-recent dispatches")
    ap.add_argument("--fleet", action="store_true",
                    help="base is a router/pod: fetch the stitched "
                         "fleet-wide trace (/debug/trace?scope=fleet)")
    ap.add_argument("--trace", default=None,
                    help="with --fleet: filter to one trace id")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)

    if args.fleet:
        try:
            doc = fetch_fleet(args.base, args.trace, args.timeout)
        except Exception as e:
            print(f"trace_dump: fleet fetch failed: {e}", file=sys.stderr)
            return 1
        with open(args.out, "w") as f:
            json.dump(doc, f)
        print(f"wrote {args.out} — load it in chrome://tracing or "
              f"https://ui.perfetto.dev")
        print(summarize_fleet(doc))
        return 0

    try:
        doc = fetch_trace(args.base, args.last, args.timeout)
    except Exception as e:
        print(f"trace_dump: fetch failed: {e}", file=sys.stderr)
        return 1
    if not doc.get("traceEvents"):
        print("trace_dump: no spans recorded yet (serve a request first)",
              file=sys.stderr)
    if args.slots:
        try:
            tl = fetch_timeline(args.base, args.timeline_n, args.timeout)
        except Exception as e:
            print(f"trace_dump: timeline fetch failed: {e}", file=sys.stderr)
            return 1
        doc["traceEvents"] = doc.get("traceEvents", []) + slot_events(tl)
        gp = tl.get("goodput_ratio")
        comp = tl.get("components_ms") or {}
        if comp:
            split = " ".join(f"{k}={v:.0f}ms"
                             for k, v in sorted(comp.items()))
            print(f"goodput {gp:.3f} over {len(tl.get('steps', []))} "
                  f"dispatches: {split}")
    with open(args.out, "w") as f:
        json.dump(doc, f)
    print(f"wrote {args.out} — load it in chrome://tracing or "
          f"https://ui.perfetto.dev")
    print(summarize(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
