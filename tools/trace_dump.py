#!/usr/bin/env python3
"""Dump a running dllama-api server's span ring as a Chrome trace file.

Fetches ``GET /debug/trace?last=N`` (dllama_tpu/obs/trace.py) and writes
the Chrome ``trace_event`` JSON to a file loadable in ``chrome://tracing``
or https://ui.perfetto.dev — the cheap first-line latency attribution for
a live server (queue_wait / prefill / decode_chunk / emit / request spans
per request ID), no restart and no ``--profile-split`` XLA tracer needed.

Usage:
    python tools/trace_dump.py http://127.0.0.1:9090 [-o trace.json] [-n 20]
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from collections import Counter


def fetch_trace(base: str, last: int, timeout: float = 10.0) -> dict:
    url = f"{base.rstrip('/')}/debug/trace?last={last}"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def summarize(doc: dict) -> str:
    """Per-span-name count + total ms, so the terminal shows where the
    time went before anyone opens Perfetto."""
    spans = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    counts = Counter(e["name"] for e in spans)
    total_ms: Counter = Counter()
    for e in spans:
        total_ms[e["name"]] += e.get("dur", 0.0) / 1000.0
    rids = {e["args"]["request_id"] for e in spans
            if e.get("args", {}).get("request_id")}
    lines = [f"{len(spans)} spans across {len(rids)} request(s):"]
    for name, n in counts.most_common():
        lines.append(f"  {name:<16} x{n:<5} {total_ms[name]:9.1f} ms total")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", help="server base URL, e.g. http://127.0.0.1:9090")
    ap.add_argument("-o", "--out", default="trace.json",
                    help="output file (default trace.json)")
    ap.add_argument("-n", "--last", type=int, default=20,
                    help="number of most-recent requests to include")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)

    try:
        doc = fetch_trace(args.base, args.last, args.timeout)
    except Exception as e:
        print(f"trace_dump: fetch failed: {e}", file=sys.stderr)
        return 1
    if not doc.get("traceEvents"):
        print("trace_dump: no spans recorded yet (serve a request first)",
              file=sys.stderr)
    with open(args.out, "w") as f:
        json.dump(doc, f)
    print(f"wrote {args.out} — load it in chrome://tracing or "
          f"https://ui.perfetto.dev")
    print(summarize(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
