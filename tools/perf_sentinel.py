#!/usr/bin/env python3
"""Performance-regression sentinel over banked bench evidence.

Loads any two performance snapshots — in any mix of the three formats
the repo already produces — normalizes them into one flat
``{metric: value}`` schema, and renders a direction-aware verdict table:

* ``BENCH_r*.json``      — a banked round (the driver's wrapper with its
                           ``parsed`` result, a raw result line, or the
                           result embedded in ``tail``)
* ``BENCH_metrics.jsonl`` — per-stage registry snapshots
                           (``_bank_stage_metrics``), keyed ``stage:metric``
* ``http://...``          — a live ``/metrics`` or ``/metrics?scope=fleet``
                           scrape

A metric regresses when it moved in its *bad* direction by more than
``--threshold`` (relative).  tok/s down 20% is a regression; latency-ms
down 20% is an improvement; metrics whose direction is unknown are shown
but never gate.  Exit code: 0 clean, 1 regression, 2 load/usage error.

Usage:
    python tools/perf_sentinel.py BENCH_r05.json BENCH_r06.json
    python tools/perf_sentinel.py old_metrics.jsonl BENCH_metrics.jsonl
    python tools/perf_sentinel.py BENCH_r06.json http://127.0.0.1:8080/metrics?scope=fleet
    python tools/perf_sentinel.py --self-check

``bench.py`` calls :func:`compare` as a library at the end of every run
(previous banked round vs the fresh result) and records the verdict in
the result's ``extras`` — evidence, never a gate there.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

#: relative move in the bad direction beyond which a metric regresses
DEFAULT_THRESHOLD = 0.10

# direction classification by key substring, first match wins (checked
# against the lowercased final key segment).  "lower" = smaller is
# better (latency, waste); "higher" = bigger is better (throughput,
# utilization, acceptance).
_LOWER_HINTS = ("_ms", "ms_", "host_gap", "gap_share", "share", "spill",
                "queued", "burn", "wait", "latency", "ttft", "itl",
                "recompile", "degrade", "errors", "preempt",
                "dispatches_per_step")
_HIGHER_HINTS = ("toks", "tok_s", "speedup", "goodput", "mfu", "mbu",
                 "accept", "ratio", "throughput", "served", "reused",
                 "hit", "value")


def direction_of(key: str) -> str:
    """'higher' / 'lower' / 'unknown' — which way is good for ``key``."""
    leaf = key.rsplit(":", 1)[-1].lower()
    for hint in _LOWER_HINTS:
        if hint in leaf:
            return "lower"
    for hint in _HIGHER_HINTS:
        if hint in leaf:
            return "higher"
    return "unknown"


# --- normalizers (each returns a flat {key: float}) -----------------------

def normalize_result(doc: dict) -> dict:
    """One bench result line ({"metric", "value", "unit", "extras"}):
    the headline rides as ``value`` (unit-checked), extras ride by key."""
    out = {}
    v = doc.get("value")
    if isinstance(v, (int, float)) and "tok" in str(doc.get("unit", "")):
        out["value"] = float(v)
    for k, x in (doc.get("extras") or {}).items():
        if isinstance(x, (int, float)) and not isinstance(x, bool):
            out[str(k)] = float(x)
    return out


def _registry_scalars(snap: dict, prefix: str = "") -> dict:
    """The comparable scalars of one registry snapshot: plain-number
    gauges/counters plus histogram averages (``<name>_avg``).  Label
    dicts are skipped — their keysets churn across runs."""
    skip = {"schema_version", "uptime_s", "ts", "bench_run_id", "git_sha"}
    out = {}
    for k, v in (snap or {}).items():
        if k in skip:
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[prefix + k] = float(v)
        elif isinstance(v, dict) and "avg" in v and "count" in v:
            if v["count"]:
                out[prefix + k + "_avg"] = float(v["avg"])
    return out


def normalize_stage_lines(lines) -> dict:
    """BENCH_metrics.jsonl → ``{"<stage>:<metric>": value}``; a stage
    appearing twice keeps its last snapshot (rerun wins)."""
    out = {}
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        stage = row.get("stage", "?")
        scalars = _registry_scalars(row.get("metrics") or {},
                                    prefix=f"{stage}:")
        # last write wins per stage: drop that stage's previous keys
        out = {k: v for k, v in out.items()
               if not k.startswith(f"{stage}:")}
        out.update(scalars)
    return out


def normalize_fleet(doc: dict) -> dict:
    """A ``/metrics?scope=fleet`` document: the router's perf rollup
    plus every up replica's registry scalars keyed by address."""
    out = {}
    for k, v in (doc.get("perf") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[f"fleet:{k}"] = float(v)
    for addr, entry in (doc.get("replicas") or {}).items():
        if entry.get("up") or entry.get("metrics"):
            out.update(_registry_scalars(entry.get("metrics") or {},
                                         prefix=f"{addr}:"))
    return out


def normalize(doc) -> dict:
    """Dispatch on document shape (one already-parsed JSON value)."""
    if isinstance(doc, list):
        return normalize_stage_lines(json.dumps(r) for r in doc)
    if not isinstance(doc, dict):
        raise ValueError("unrecognized snapshot shape")
    if "replicas" in doc:
        return normalize_fleet(doc)
    if "metric" in doc and "value" in doc:
        return normalize_result(doc)
    if "schema_version" in doc:
        return _registry_scalars(doc)
    # driver wrapper around a bench round: prefer the parsed result,
    # else fish the last result-looking JSON line out of the tail
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        return normalize_result(doc["parsed"])
    if "tail" in doc:
        for line in reversed(str(doc["tail"]).splitlines()):
            line = line.strip()
            i = line.find('{"metric"')
            if i < 0:
                continue
            try:
                return normalize_result(json.loads(line[i:]))
            except ValueError:
                continue
    raise ValueError("unrecognized snapshot shape")


def load_any(src: str) -> dict:
    """Normalize a path or URL into the flat schema."""
    if src.startswith(("http://", "https://")):
        with urllib.request.urlopen(src, timeout=10) as r:
            return normalize(json.loads(r.read().decode("utf-8")))
    with open(src) as f:
        text = f.read()
    if src.endswith(".jsonl"):
        return normalize_stage_lines(text.splitlines())
    doc = json.loads(text)
    return normalize(doc)


# --- comparison -----------------------------------------------------------

def compare(base: dict, cur: dict,
            threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Pairwise verdict over the metrics both snapshots report."""
    rows = []
    for key in sorted(set(base) & set(cur)):
        b, c = base[key], cur[key]
        d = direction_of(key)
        if b == 0:
            delta = 0.0 if c == 0 else None
        else:
            delta = (c - b) / abs(b)
        status = "n/a"
        if delta is not None and d != "unknown":
            bad = -delta if d == "higher" else delta
            if bad > threshold:
                status = "regression"
            elif bad < -threshold:
                status = "improvement"
            else:
                status = "ok"
        elif delta is not None:
            status = "info"
        rows.append({"metric": key, "base": b, "cur": c,
                     "delta_pct": round(delta * 100, 2)
                     if delta is not None else None,
                     "direction": d, "status": status})
    regressions = [r["metric"] for r in rows if r["status"] == "regression"]
    return {"verdict": "regression" if regressions else "ok",
            "threshold": threshold, "compared": len(rows),
            "regressions": regressions, "metrics": rows}


def render_table(report: dict) -> str:
    lines = [f"{'metric':<48} {'base':>12} {'cur':>12} "
             f"{'delta':>8} {'dir':<7} status",
             "-" * 96]
    for r in report["metrics"]:
        delta = f"{r['delta_pct']:+.1f}%" if r["delta_pct"] is not None \
            else "-"
        lines.append(f"{r['metric']:<48.48} {r['base']:>12.4g} "
                     f"{r['cur']:>12.4g} {delta:>8} "
                     f"{r['direction']:<7} {r['status']}")
    lines.append(f"verdict: {report['verdict'].upper()} "
                 f"({len(report['regressions'])} regression(s) over "
                 f"{report['compared']} comparable metric(s), "
                 f"threshold {report['threshold']:.0%})")
    return "\n".join(lines)


# --- self-check -----------------------------------------------------------

def self_check() -> int:
    """Canned-fixture verdicts: the schema normalizers and the
    direction-aware comparison, no filesystem or network."""
    base = normalize_result({
        "metric": "tiny decode tok/s", "value": 100.0, "unit": "tok/s",
        "extras": {"sched4_agg_toks": 50.0, "host_gap_share": 0.10}})
    slower = normalize_result({
        "metric": "tiny decode tok/s", "value": 80.0, "unit": "tok/s",
        "extras": {"sched4_agg_toks": 50.0, "host_gap_share": 0.10}})
    checks = [
        ("result schema", set(base) ==
         {"value", "sched4_agg_toks", "host_gap_share"}),
        ("20% tok/s drop regresses",
         compare(base, slower)["verdict"] == "regression"),
        ("equal pair is clean",
         compare(base, dict(base))["verdict"] == "ok"),
        ("latency drop is improvement",
         compare({"ttft_seconds_avg": 0.2}, {"ttft_seconds_avg": 0.1})
         ["verdict"] == "ok"),
        ("latency jump regresses",
         compare({"ttft_seconds_avg": 0.1}, {"ttft_seconds_avg": 0.2})
         ["verdict"] == "regression"),
        ("dispatch-count drop is improvement",
         compare({"cpu_fused4_dispatches_per_step": 4.0},
                 {"cpu_fused4_dispatches_per_step": 2.0})
         ["verdict"] == "ok"),
        ("dispatch-count jump regresses",
         compare({"cpu_fused4_dispatches_per_step": 2.0},
                 {"cpu_fused4_dispatches_per_step": 4.0})
         ["verdict"] == "regression"),
    ]
    stage = normalize_stage_lines([json.dumps(
        {"stage": "cpu-tiny-sched4", "ts": 1.0,
         "metrics": {"schema_version": 2, "sched_goodput_ratio": 0.9,
                     "mfu": 0.2,
                     "ttft_seconds": {"count": 3, "sum": 0.3, "avg": 0.1,
                                      "buckets": {}}}})])
    checks.append(("jsonl schema", stage == {
        "cpu-tiny-sched4:sched_goodput_ratio": 0.9,
        "cpu-tiny-sched4:mfu": 0.2,
        "cpu-tiny-sched4:ttft_seconds_avg": 0.1}))
    fleet = normalize_fleet({
        "perf": {"mfu_mean": 0.25, "mbu_mean": None},
        "replicas": {"127.0.0.1:1": {"up": True, "metrics": {
            "schema_version": 2, "requests_served": 7}}}})
    checks.append(("fleet schema", fleet == {
        "fleet:mfu_mean": 0.25, "127.0.0.1:1:requests_served": 7.0}))
    ok = True
    for name, passed in checks:
        print(f"self-check: {name}: {'ok' if passed else 'FAIL'}")
        ok = ok and passed
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", nargs="?",
                    help="baseline snapshot (path or URL)")
    ap.add_argument("current", nargs="?",
                    help="current snapshot (path or URL)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="relative bad-direction move that regresses "
                         "(default 0.10)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report")
    ap.add_argument("--self-check", action="store_true",
                    help="run the canned-fixture schema/verdict checks")
    args = ap.parse_args(argv)

    if args.self_check:
        return self_check()
    if not args.base or not args.current:
        ap.error("need BASE and CURRENT snapshots (or --self-check)")
    try:
        base = load_any(args.base)
        cur = load_any(args.current)
    except Exception as e:
        print(f"perf_sentinel: load failed: {e}", file=sys.stderr)
        return 2
    report = compare(base, cur, threshold=args.threshold)
    if not report["compared"]:
        print("perf_sentinel: no comparable metrics between the two "
              "snapshots", file=sys.stderr)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_table(report))
    return 1 if report["verdict"] == "regression" else 0


if __name__ == "__main__":
    raise SystemExit(main())
