#!/bin/bash
# Poll the axon relay; when it comes back, immediately capture hardware
# evidence: full bench (headline + evidence stages) then the kernel sweep.
# Logs to /tmp/tunnel_watch.log; bench JSON to /tmp/BENCH_recovered.json.
cd "$(dirname "$0")/.."
log=/tmp/tunnel_watch.log
echo "$(date -u +%H:%M:%S) watcher start" >> "$log"
while true; do
    code=$(curl -s -m 5 -o /dev/null -w "%{http_code}" http://127.0.0.1:8093/healthz)
    if [ "$code" != "000" ]; then
        echo "$(date -u +%H:%M:%S) relay answered ($code) — probing jax" >> "$log"
        if timeout 120 python -c "import jax; assert jax.default_backend() != 'cpu', 'cpu'; print(jax.devices())" >> "$log" 2>&1; then
            echo "$(date -u +%H:%M:%S) TPU back — running bench" >> "$log"
            BENCH_BUDGET_S=1500 timeout 1600 python bench.py \
                > /tmp/BENCH_recovered.json 2>> "$log"
            echo "$(date -u +%H:%M:%S) bench rc=$? — running sweep" >> "$log"
            timeout 1500 python tools/sweep_q40.py >> "$log" 2>&1
            echo "$(date -u +%H:%M:%S) sweep rc=$? — watcher done" >> "$log"
            exit 0
        fi
        echo "$(date -u +%H:%M:%S) relay up but jax probe failed" >> "$log"
    fi
    sleep 300
done
