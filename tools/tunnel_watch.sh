#!/bin/bash
# All-session relay watcher (VERDICT r04 Next #1): poll the axon relay for
# the WHOLE build session and, at the first sign of life, capture hardware
# evidence and commit it — the full bench (headline llama2-7b + llama3-8b +
# tile auto-tune + long-context extras) to BENCH_insession.json, then the
# kernel sweep table to tools/sweep_results.txt.  Keeps watching after a
# capture: later windows refresh a degraded result or add the sweep.
# r02 proved the tunnel can be up mid-session while dead at round end, and
# r03+r04 produced zero hardware data by only benching at round end.
#
# Liveness marker: /tmp/RELAY_UP exists while the relay answers.
# Log: /tmp/tunnel_watch.log.
cd "$(dirname "$0")/.."
log=/tmp/tunnel_watch.log
echo "$(date -u +%H:%M:%S) watcher start (pid $$)" >> "$log"

bench_ok=0
sweep_ok=0

commit_paths() {  # commit_paths <msg> <path>... — retry around index.lock
    local msg="$1"; shift
    for i in 1 2 3 4 5; do
        git add -- "$@" >> "$log" 2>&1
        if git commit -m "$msg" -- "$@" >> "$log" 2>&1; then return 0; fi
        sleep 7
    done
    return 1
}

while true; do
    code=$(curl -s -m 5 -o /dev/null -w "%{http_code}" http://127.0.0.1:8093/healthz)
    if [ "$code" = "000" ]; then
        rm -f /tmp/RELAY_UP
        sleep 60
        continue
    fi
    touch /tmp/RELAY_UP
    if [ "$bench_ok" = 1 ] && [ "$sweep_ok" = 1 ]; then
        sleep 120   # everything captured; just maintain the marker
        continue
    fi
    echo "$(date -u +%H:%M:%S) relay answered ($code) — probing jax" >> "$log"
    if ! timeout 180 python -c "import jax; assert jax.default_backend() != 'cpu', 'cpu'; print(jax.devices())" >> "$log" 2>&1; then
        echo "$(date -u +%H:%M:%S) relay up but jax probe failed" >> "$log"
        sleep 60
        continue
    fi
    if [ "$bench_ok" = 0 ]; then
        echo "$(date -u +%H:%M:%S) TPU live — running bench" >> "$log"
        BENCH_BUDGET_S=1500 timeout 1600 python bench.py \
            > /tmp/BENCH_insession.json 2>> "$log"
        rc=$?
        echo "$(date -u +%H:%M:%S) bench rc=$rc: $(cat /tmp/BENCH_insession.json)" >> "$log"
        # hardware evidence = a parseable line whose metric is not the
        # DEGRADED cpu fallback and whose value is non-zero
        if python - <<'EOF'
import json, sys
try:
    r = json.loads(open("/tmp/BENCH_insession.json").read().strip().splitlines()[-1])
except Exception:
    sys.exit(1)
sys.exit(0 if r.get("value", 0) > 0 and "DEGRADED" not in r.get("metric", "")
         and "interrupted" not in r.get("metric", "") else 1)
EOF
        then
            # capture succeeded regardless of git state: never re-burn a
            # 1500 s TPU bench because the build session held index.lock
            bench_ok=1
            cp /tmp/BENCH_insession.json BENCH_insession.json
            bench_committed=0
            commit_paths "In-session TPU bench capture (relay window)" BENCH_insession.json \
                && bench_committed=1
            echo "$(date -u +%H:%M:%S) bench artifact committed=$bench_committed" >> "$log"
        else
            echo "$(date -u +%H:%M:%S) bench produced no hardware number" >> "$log"
        fi
    fi
    if [ "$bench_ok" = 1 ] && [ "${bench_committed:-1}" = 0 ]; then
        commit_paths "In-session TPU bench capture (relay window)" BENCH_insession.json \
            && bench_committed=1
    fi
    if [ "$bench_ok" = 1 ] && [ "$sweep_ok" = 0 ]; then
        echo "$(date -u +%H:%M:%S) running kernel sweep" >> "$log"
        timeout 2400 python tools/sweep_q40.py > /tmp/sweep_results.txt 2>> "$log"
        rc=$?
        echo "$(date -u +%H:%M:%S) sweep rc=$rc" >> "$log"
        if [ "$rc" = 0 ] && [ -s /tmp/sweep_results.txt ]; then
            sweep_ok=1
            cp /tmp/sweep_results.txt tools/sweep_results.txt
            sweep_committed=0
            commit_paths "In-session kernel sweep results (relay window)" tools/sweep_results.txt \
                && sweep_committed=1
        fi
    fi
    if [ "$sweep_ok" = 1 ] && [ "${sweep_committed:-1}" = 0 ]; then
        commit_paths "In-session kernel sweep results (relay window)" tools/sweep_results.txt \
            && sweep_committed=1
    fi
    sleep 60
done
