#!/bin/bash
# All-session relay watcher (VERDICT r04 Next #1): poll the axon relay for
# the WHOLE build session and, at each sign of life, run the incremental
# gap-filler (tools/hw_capture.py) — it inspects BENCH_insession.json and
# tools/sweep_results.txt, runs only the missing hardware stages, and
# commits every artifact the moment it lands.  r02-r05 all showed the same
# tunnel pattern: ~30 min windows of life separated by hours of nothing,
# sometimes ending in a wedged chip claim — so capture must be incremental
# and idempotent, never a monolithic bench that loses everything when the
# window closes.
#
# Liveness marker: /tmp/RELAY_UP exists while the relay answers.
# Log: /tmp/tunnel_watch.log.
cd "$(dirname "$0")/.."
log=/tmp/tunnel_watch.log
# same relay address derivation as bench.py/hw_capture.py — the gate and
# the capture must watch the same endpoint
RELAY_PORT="${BENCH_RELAY_PORT:-8093}"
RELAY_HOST="${PALLAS_AXON_POOL_IPS%%,*}"
RELAY_HOST="${RELAY_HOST:-127.0.0.1}"
echo "$(date -u +%H:%M:%S) watcher start (pid $$, relay $RELAY_HOST:$RELAY_PORT)" >> "$log"

while true; do
    if ! timeout 6 bash -c "exec 3<>/dev/tcp/$RELAY_HOST/$RELAY_PORT" 2>/dev/null; then
        rm -f /tmp/RELAY_UP
        sleep 60
        continue
    fi
    touch /tmp/RELAY_UP
    echo "$(date -u +%H:%M:%S) relay answering — running hw_capture" >> "$log"
    # SIGTERM on timeout: hw_capture's handler kills its in-flight bench
    # child so the chip claim is never orphaned; 9000 s covers the worst-
    # case full-stage window (llama3-8b 900 + probes + extras)
    timeout 9000 python tools/hw_capture.py >> "$log" 2>&1
    rc=$?
    echo "$(date -u +%H:%M:%S) hw_capture rc=$rc" >> "$log"
    if [ "$rc" = 0 ] || [ "$rc" = 4 ]; then
        sleep 300   # all landed, or wedged claim cooling off
    else
        sleep 60    # stages remain (relay flicker / probe fail): fast poll
    fi
done
