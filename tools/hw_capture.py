#!/usr/bin/env python
"""Incremental TPU-evidence capture for a flaky relay window.

Invoked by tools/tunnel_watch.sh whenever the axon relay answers.  Reads
what evidence already exists (``BENCH_insession.json`` headline+extras,
``tools/sweep_results.txt`` kernel probes), runs ONLY the missing stages
in priority order, merges each result into the artifact the moment it
lands, and git-commits it — so a tunnel that dies mid-window (the r02-r05
norm: ~30 min of life, then nothing for hours) never re-burns or loses a
measurement.

Priority order (each stage gated on the relay still answering, with a
wedge probe after any timeout — the r05 window showed a killed child can
leave the chip's exclusive claim stuck, hanging every later client):

  1. llama2-7b headline (only if the artifact is missing/degraded)
  2. llama3-8b          — the BASELINE.json north-star, never yet measured
  3. chunk probes       — decode chunk 64/128 amortize the ~75 ms/chunk
                          tunnel dispatch overhead measured in r05
  4. tile probes (w13)  — docs/PERF.md lever #1 (tile_d = HBM burst len)
  5. variant probes     — folded/exact/fma vs classic on w13+wo
  6. combined re-run    — headline with every winning lever; promoted only
                          if it beats the recorded number end-to-end
  7. extras             — batch=8 aggregate, 16k long-context, int8-KV 16k
  8. moe hw check, xplane profile (diagnostics; profile LAST — it can
                          wedge the tunnel claim)

Idempotent: run it as many times as the relay flickers; done stages are
skipped by inspecting the artifacts.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(HERE, "BENCH_insession.json")
SWEEP = os.path.join(HERE, "tools", "sweep_results.txt")
BENCH = os.path.join(HERE, "bench.py")

sys.path.insert(0, HERE)
from bench import _with_compile_cache, current_round  # noqa: E402  (shared recipes)

# the in-flight child, killed from the SIGTERM handler: if the watcher's
# outer timeout tears THIS process down mid-attempt, the bench child must
# not survive holding the chip's exclusive claim (it would wedge every
# later capture — the r05 failure mode, self-inflicted)
_child: subprocess.Popen | None = None


def _on_term(signum, frame):
    if _child is not None and _child.poll() is None:
        _child.kill()
    raise SystemExit(7)


signal.signal(signal.SIGTERM, _on_term)

RELAY_PORT = int(os.environ.get("BENCH_RELAY_PORT", "8093"))
RELAY_HOST = (os.environ.get("PALLAS_AXON_POOL_IPS", "").split(",")[0].strip()
              or "127.0.0.1")

TILE_CONFIGS = [(1024, 1024), (512, 2048), (256, 4096), (512, 4096),
                (1024, 2048)]
VARIANTS = ["folded", "fma", "exact"]


def log(msg: str) -> None:
    print(f"hwcap {time.strftime('%H:%M:%S')}: {msg}", file=sys.stderr,
          flush=True)


def relay_up(timeout: float = 3.0) -> bool:
    try:
        with socket.create_connection((RELAY_HOST, RELAY_PORT), timeout):
            return True
    except OSError:
        return False


def child_env(extra: dict | None = None) -> dict:
    env = _with_compile_cache(dict(os.environ))
    env["PYTHONPATH"] = HERE + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra or {})
    return env


def _run(cmd: list, timeout_s: float, env: dict):
    """subprocess.run equivalent that tracks the child for the SIGTERM
    handler (the watcher's outer timeout must never orphan a bench child
    on the chip)."""
    global _child
    _child = subprocess.Popen(cmd, stdout=subprocess.PIPE, env=env, cwd=HERE)
    try:
        stdout, _ = _child.communicate(timeout=timeout_s)
        return _child.returncode, stdout
    except subprocess.TimeoutExpired:
        _child.kill()
        _child.communicate()
        raise
    finally:
        _child = None


def attempt(name: str, timeout_s: float, env_extra: dict | None = None):
    """One bench.py --attempt child; stderr streams through to our log."""
    log(f"attempt {name} (timeout {timeout_s:.0f}s)")
    t0 = time.time()
    try:
        rc, stdout = _run([sys.executable, BENCH, "--attempt", name],
                          timeout_s, child_env(env_extra))
    except subprocess.TimeoutExpired:
        log(f"{name} timed out after {time.time() - t0:.0f}s")
        return None
    if rc != 0:
        log(f"{name} exited rc={rc}")
        return None
    try:
        out = json.loads(stdout.decode().strip().splitlines()[-1])
    except Exception:
        log(f"{name} produced no parseable line")
        return None
    if out.get("backend") == "cpu":
        # the tunnel dropped between the window probe and this child: its
        # jax silently fell back to the host CPU — NOT hardware evidence
        log(f"{name} ran on the CPU backend (tunnel gone); discarding")
        return None
    log(f"{name} ok in {time.time() - t0:.0f}s: {json.dumps(out)}")
    return out


def probe(timeout_s: float = 120) -> bool:
    out = attempt("probe", timeout_s)
    return bool(out) and out.get("platform") != "cpu"


def wedged() -> bool:
    """After a timeout: can a fresh client still claim the chip?"""
    if not relay_up():
        log("relay died")
        return True
    if not probe(90):
        log("chip claim hangs — tunnel wedged, abandoning this window")
        return True
    return False


def load_art() -> dict:
    try:
        with open(ART) as f:
            return json.loads(f.read().strip())
    except Exception:
        return {}


def save_art(art: dict) -> None:
    # captured_unix + round feed bench.py's round-end freshness gate: a
    # committed artifact from a PREVIOUS round must not be replayed as
    # current hardware evidence (the round stamp is exact; the timestamp
    # is the fallback when either side lacks one)
    art["captured_unix"] = time.time()
    rnd = current_round()
    if rnd is not None:
        art["round"] = rnd
    with open(ART, "w") as f:
        f.write(json.dumps(art) + "\n")


def commit(msg: str, *paths: str) -> bool:
    """Commit artifacts, retrying around a build session's index.lock."""
    for _ in range(5):
        subprocess.run(["git", "add", "--"] + list(paths), cwd=HERE,
                       stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        r = subprocess.run(["git", "commit", "-m", msg, "--"] + list(paths),
                           cwd=HERE, stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL)
        if r.returncode == 0:
            log(f"committed: {msg}")
            return True
        time.sleep(7)
    log(f"could not commit ({msg}); artifact saved on disk")
    return False


def sweep_done() -> set:
    done = set()
    try:
        with open(SWEEP) as f:
            for line in f:
                try:
                    o = json.loads(line)
                    done.add((o["variant"], o["tile_n"], o["tile_d"],
                              tuple(sorted(o.get("shapes", {})))))
                except Exception:
                    continue
    except OSError:
        pass
    return done


def sweep_probe(variant: str, tn: int, td: int, shapes: str,
                timeout_s: float = 300):
    """One tools/sweep_q40.py --one run; appends its JSON to SWEEP."""
    log(f"sweep probe {variant} tn={tn} td={td} shapes={shapes}")
    try:
        rc, stdout = _run(
            [sys.executable, os.path.join(HERE, "tools", "sweep_q40.py"),
             "--one", variant, str(tn), str(td), "--shapes", shapes],
            timeout_s, child_env())
    except subprocess.TimeoutExpired:
        log("sweep probe timed out")
        return None
    if rc != 0 or not stdout:
        log(f"sweep probe rc={rc}")
        return None
    try:
        out = json.loads(stdout.decode().strip().splitlines()[-1])
    except Exception:
        return None
    if "error" in out or not out.get("shapes"):
        log(f"sweep probe: {out}")
        return None
    with open(SWEEP, "a") as f:
        f.write(json.dumps(out) + "\n")
    log(f"sweep probe: {json.dumps(out['shapes'])}")
    return out


def main() -> int:
    if not relay_up():
        log("relay not listening")
        return 2
    if not probe():
        log("backend probe failed")
        return 3

    art = load_art()
    extras = art.get("extras") or {}
    hw = bool(art) and art.get("value", 0) > 0 \
        and "DEGRADED" not in art.get("metric", "")

    def merge_commit(msg):
        art["extras"] = extras
        save_art(art)
        commit(msg, ART)

    # --- 1. headline --------------------------------------------------
    if not hw:
        out = attempt("llama2-7b", 900)
        if out and "llama2-7b" in out.get("metric", ""):
            art = {k: out.get(k) for k in
                   ("metric", "value", "unit", "vs_baseline")}
            hw = True
            merge_commit("In-session TPU bench capture (headline)")
        elif wedged():
            return 4
        else:
            return 5  # no headline and no wedge: give the relay a rest
    baseline_toks = art["value"]

    # --- 2. north-star ------------------------------------------------
    if "llama3-8b_toks" not in extras:
        out = attempt("llama3-8b", 900)
        if out:
            extras["llama3-8b_toks"] = out["value"]
            merge_commit("In-session TPU capture: llama3-8b north-star")
        elif wedged():
            return 4

    # --- 3. chunk probes ----------------------------------------------
    for c in (64, 128):
        key = f"llama2-7b_c{c}_toks"
        if key in extras:
            continue
        if not relay_up():
            return 6  # stages remain; watcher keeps the fast 60 s poll
        out = attempt(f"llama2-7b-c{c}", 300)
        if out:
            extras[key] = out["value"]
            if out["value"] > art["value"]:
                extras.setdefault("llama2-7b_chunk32_toks", baseline_toks)
                art.update({k: out.get(k) for k in
                            ("metric", "value", "unit", "vs_baseline")})
            merge_commit(f"In-session TPU capture: chunk={c} decode probe")
        elif wedged():
            return 4

    # --- 4./5. kernel probes ------------------------------------------
    done = sweep_done()
    probes = [("classic", tn, td, "w13") for tn, td in TILE_CONFIGS] + \
             [(v, 1024, 1024, "w13,wo") for v in VARIANTS] + \
             [("blocked", 1024, 1024, "w13,wo"),   # tile-contiguous layout
              ("blocked", 512, 2048, "w13")]       # (PERF.md lever #1b)
    ran_probe = False
    for variant, tn, td, shapes in probes:
        if (variant, tn, td, tuple(sorted(shapes.split(",")))) in done:
            continue
        if not relay_up():
            return 6  # stages remain; watcher keeps the fast 60 s poll
        out = sweep_probe(variant, tn, td, shapes)
        ran_probe = True
        if out is None and wedged():
            commit("In-session kernel probe results (partial)", SWEEP)
            return 4
    if ran_probe and os.path.exists(SWEEP):
        commit("In-session kernel probe results", SWEEP)

    # --- 6. combined re-run -------------------------------------------
    if "combined_rerun_toks" not in extras and os.path.exists(SWEEP):
        rows = []
        with open(SWEEP) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except Exception:
                    continue
        w13 = {(o["variant"], o["tile_n"], o["tile_d"]):
               o["shapes"]["w13"]["ms"] for o in rows if "w13" in o["shapes"]}
        base_ms = w13.get(("classic", 1024, 1024))
        env = {}
        tags = []
        if base_ms:
            best = min(w13, key=w13.get)
            if w13[best] < 0.95 * base_ms:
                if best[0] == "blocked":
                    # the tile-contiguous layout is deployable end to end
                    # (ops/q40.py BlockedQTensor, DLLAMA_Q40_LAYOUT)
                    env["DLLAMA_Q40_LAYOUT"] = "blocked"
                    env["DLLAMA_Q40_BLOCK_TILES"] = f"{best[1]},{best[2]}"
                    tags.append(f"blocked tiles {best[1]},{best[2]}")
                elif best[0] == "classic" and best[1:] != (1024, 1024):
                    rule = json.dumps([[8192, best[1], best[2]]])
                    env["DLLAMA_Q40_TILES_JSON"] = rule
                    tags.append(f"tiles {rule}")
                elif best[0] != "classic":
                    env["DLLAMA_Q40_VARIANT"] = best[0]
                    tags.append(f"variant {best[0]}")
        best_c = max((c for c in (64, 128)
                      if extras.get(f"llama2-7b_c{c}_toks", 0) > baseline_toks),
                     key=lambda c: extras[f"llama2-7b_c{c}_toks"], default=None)
        name = f"llama2-7b-c{best_c}" if best_c else "llama2-7b"
        if env and relay_up():
            out = attempt(name, 420, env_extra=env)
            if out:
                extras["combined_rerun_toks"] = out["value"]
                if out["value"] > art["value"]:
                    out["metric"] += " [" + ", ".join(tags) + "]"
                    extras.setdefault("llama2-7b_default_toks", baseline_toks)
                    for t in tags:
                        if t.startswith("blocked"):
                            extras["blocked_tiles"] = env["DLLAMA_Q40_BLOCK_TILES"]
                        elif t.startswith("tiles"):
                            extras["tile_rule"] = env["DLLAMA_Q40_TILES_JSON"]
                        else:
                            extras["kernel_variant"] = env["DLLAMA_Q40_VARIANT"]
                    art.update({k: out.get(k) for k in
                                ("metric", "value", "unit", "vs_baseline")})
                merge_commit("In-session TPU capture: combined-lever re-run")
            elif wedged():
                return 4

    # --- 7. extras ----------------------------------------------------
    for name, key, msg, stage_timeout in (
            ("llama2-7b-b8", "llama2-7b_batch8_agg_toks",
             "batch=8 aggregate", 360),
            ("llama2-7b-long", "llama2-7b_16k_toks", "16k long-context", 360),
            ("llama2-7b-long-q8kv", "llama2-7b_16k_q8kv_toks",
             "int8-KV 16k long-context", 360),
            ("llama2-7b-prefill", "llama2-7b_prefill_toks",
             "prefill throughput", 300),
            # 13B compiles every 40-layer kernel shape fresh over the
            # tunnel — give it the same headroom bench.py budgets (600+)
            ("llama2-13b", "llama2-13b_toks", "13B decode (reference row "
             "README.md:127)", 900),
            ("llama2-7b-q8w", "llama2-7b_q80w_toks",
             "Q80-weights decode (first hardware number for the fused "
             "Q80 kernel)", 600)):
        if key in extras:
            continue
        if not relay_up():
            return 6  # stages remain; watcher keeps the fast 60 s poll
        out = attempt(name, stage_timeout)
        if out:
            extras[key] = out["value"]
            merge_commit(f"In-session TPU capture: {msg}")
        elif wedged():
            return 4

    # --- 8. diagnostics (profile LAST: it can wedge the claim) --------
    if "moe_hw_ok" not in extras and relay_up():
        try:
            rc, stdout = _run(
                [sys.executable, os.path.join(HERE, "tools", "moe_hw_check.py"),
                 "--layers", "2", "--steps", "8"],
                300, child_env())
            tail = stdout.decode().strip().splitlines()[-1] if stdout else ""
            log(f"moe hw check rc={rc}: {tail}")
            if rc == 0:
                extras["moe_hw_ok"] = 1
                merge_commit("In-session TPU capture: packed-MoE hw check")
        except subprocess.TimeoutExpired:
            log("moe hw check timed out")
            if wedged():
                return 4
    if relay_up():
        attempt("llama2-7b-profile", 300)
    log("window complete: all stages landed or attempted")
    return 0


if __name__ == "__main__":
    sys.exit(main())
