#!/usr/bin/env python
"""Write or verify checksum manifests for dllama artifacts.

A manifest is a JSON sidecar (``<artifact>.sum``) carrying a crc32 per
tensor byte-range (for ``.m`` model files) or a whole-file digest (for
``.t`` tokenizers and anything else), plus a header digest and the file
size — see dllama_tpu/io/integrity.py for the format.  With a manifest
present, ``MFile`` always verifies the header at open and verifies each
tensor on first read under ``--verify-weights``; ``read_tfile`` verifies
the whole file.

Usage::

    python tools/checksum_model.py write  model.m [tokenizer.t ...]
    python tools/checksum_model.py verify model.m [tokenizer.t ...]
    python tools/checksum_model.py write  legacy.m --weights-float-type q40

``write`` computes digests and writes the sidecar atomically.  ``verify``
re-reads every manifested region and exits non-zero on the first
mismatch, printing the ArtifactError (file, field, byte offset,
expected-vs-got crc32).  ``--weights-float-type`` is only needed for
legacy ``.m`` files whose header predates the weights-float-type key.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)  # dllama_tpu (running from a checkout)

from dllama_tpu.io import integrity  # noqa: E402
from dllama_tpu.io.integrity import ArtifactError  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=("write", "verify"))
    ap.add_argument("artifacts", nargs="+",
                    help="model (.m) / tokenizer (.t) files")
    ap.add_argument("--weights-float-type", default=None,
                    help="weight float type for legacy .m headers that "
                         "omit it (e.g. q40, q80, f32)")
    args = ap.parse_args(argv)

    wft = None
    if args.weights_float_type:
        from dllama_tpu.models import quants
        wft = quants.FLOAT_TYPE_BY_NAME[args.weights_float_type]

    rc = 0
    for path in args.artifacts:
        if not os.path.exists(path):
            print(f"❌ {path}: no such file")
            rc = 1
            continue
        try:
            if args.command == "write":
                mp = integrity.write_manifest(path, weights_ftype=wft)
                man = integrity.load_manifest(mp)
                n = 1 + len(man["tensors"])
                print(f"✅ {path}: wrote {mp} ({n} region(s), "
                      f"{man['file_size']} bytes covered)")
            else:
                n = integrity.verify_file(path)
                print(f"✅ {path}: {n} region(s) verified")
        except ArtifactError as e:
            print(f"❌ {e}")
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
