#!/usr/bin/env python
"""Chaos soak: a supervised serve-pod fleet under live process murder.

Boots ``dllama serve-pod --supervise`` (replica child processes under
the pod supervisor, fleet router on one public port) on the tests' tiny
CPU model, drives a trace-replay workload plus dedicated greedy parity
streams at it, and meanwhile SIGKILLs / SIGSTOPs replica children on a
schedule.  The soak PASSES only if the whole crash-tolerance story held
(docs/ROBUSTNESS.md):

* **zero wrong bytes** — every greedy parity stream's text is
  byte-identical to the pre-chaos solo oracle, finish stop/length
  (transparent mid-stream resume, never silent truncation);
* **honest finish reasons** — the replay mix (sampled, not resumable)
  sees only stop/length/replica_lost/preempted, zero transport errors;
* **bounded unavailability** — the router's fleet aggregate never goes
  dark longer than the recovery bound (p95 and max window asserted);
* **zero leaked KV pages** — every replica's paged pool drains back to
  its full size once the workload quiesces;
* **capacity restored** — the supervisor respawned every victim
  (``dllama_pod_respawns_total`` grew) and the registry re-admitted
  them: fleet ``available`` is back to ``--dp``;
* **honest narration** — the pod event journal (``/debug/events``)
  recorded the whole chain murder→respawn→readmit (and the reshape
  start→done phases in ``--reshape`` mode) in causal ``seq`` order.

Usage::

    python tools/chaos_drill.py             # full soak (several minutes)
    python tools/chaos_drill.py --quick     # single-kill smoke (~2 min)

Exit code 0 iff every assertion held.  CPU-only, stdlib-only, no
accelerator needed — the point is the process/protocol machinery, not
the math.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))   # tiny-model fixtures
sys.path.insert(0, os.path.join(REPO, "tools"))   # trace_replay library

GREEDY_BODY = {"prompt": "Once upon a time", "max_tokens": 32,
               "temperature": 0, "stream": True,
               # interactive: the parity probes measure crash tolerance,
               # not overload policy — the replay mix saturates the fleet
               # and a shed (429) retry after a crash would end an
               # admitted stream with an honest replica_lost
               "priority": "interactive"}


def get(base: str, path: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def journal_cursor(base: str) -> int:
    """Current end of the pod's event journal (``/debug/events``)."""
    return int(get(base, "/debug/events").get("next_seq", 0))


def journal_since(base: str, since: int) -> list[dict]:
    return get(base, f"/debug/events?since={since}").get("events") or []


def check_murder_causality(events: list[dict], killed: int,
                           check) -> None:
    """The pod journal must tell the whole murder story in causal
    (monotonic ``seq``) order: every recorded death is followed by a
    respawn of the same replica, and every router ejection by a
    readmit — the observable chain behind "capacity restored"."""
    deaths = [e for e in events if e["kind"] == "death"]
    respawns = [e for e in events if e["kind"] == "respawn"]
    ejects = [e for e in events if e["kind"] == "eject"]
    readmits = [e for e in events if e["kind"] == "readmit"]
    check(len(deaths) >= killed,
          f"journal recorded every murder "
          f"(death x{len(deaths)}, killed {killed})")
    orphans = [d for d in deaths
               if not any(r["seq"] > d["seq"]
                          and r.get("replica") == d.get("replica")
                          for r in respawns)]
    check(not orphans,
          f"every death followed by a same-replica respawn in seq order"
          + (f" (orphans: {orphans[:2]})" if orphans else ""))
    check(len(ejects) >= 1,
          f"router ejected at least one murdered replica "
          f"(eject x{len(ejects)})")
    unforgiven = [e for e in ejects
                  if not any(r["seq"] > e["seq"]
                             and r.get("replica") == e.get("replica")
                             for r in readmits)]
    check(not unforgiven,
          f"every eject followed by a same-replica readmit in seq order"
          + (f" (unforgiven: {unforgiven[:2]})" if unforgiven else ""))


def stream_once(base: str, body: dict, out: dict | None = None,
                timeout: float = 240.0) -> tuple[str, str | None]:
    """One streamed completion; returns (text, finish_reason).  ``out``
    (optional) is live-updated with ``chars`` so a chaos thread can wait
    for the stream to be mid-flight before killing its replica."""
    req = urllib.request.Request(
        base + "/v1/completions", json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    text, finish = "", None
    with urllib.request.urlopen(req, timeout=timeout) as r:
        for line in r:
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                break
            evt = json.loads(payload)
            c = evt["choices"][0]
            text += c.get("text") or ""
            if out is not None:
                out["chars"] = len(text)
            if c.get("finish_reason"):
                finish = c["finish_reason"]
    return text, finish


# -- /proc spelunking (Linux): find the pod's replica children ----------

def children_of(pid: int) -> list[int]:
    kids = []
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        try:
            with open(f"/proc/{d}/stat") as f:
                data = f.read()
            ppid = int(data.rpartition(")")[2].split()[1])
        except (OSError, ValueError, IndexError):
            continue
        if ppid == pid:
            kids.append(int(d))
    return kids


def child_by_port(pod_pid: int, port: int) -> int | None:
    """The replica child serving ``--port <port>`` (from its cmdline)."""
    want = str(port).encode()
    for kid in children_of(pod_pid):
        try:
            with open(f"/proc/{kid}/cmdline", "rb") as f:
                args = f.read().split(b"\0")
        except OSError:
            continue
        for i, a in enumerate(args[:-1]):
            if a == b"--port" and args[i + 1] == want:
                return kid
    return None


class Pod:
    """One ``serve-pod --supervise`` process (router + supervisor +
    replica children) on the tiny fixture model."""

    def __init__(self, model: str, tok: str, *, dp: int = 2,
                 snapshot_dir: str | None = None, faults: str = "",
                 extra: list[str] | None = None):
        from fixtures import cpu_env, free_port
        self.dp = dp
        self.port = free_port()
        self.base = f"http://127.0.0.1:{self.port}"
        env = cpu_env()
        if faults:
            # inherited by the replica children — the supervisor parent
            # never hits engine fault points itself
            env["DLLAMA_FAULTS"] = faults
        argv = [sys.executable, "-m", "dllama_tpu", "serve-pod",
                "--supervise", "--dp", str(dp),
                "--model", model, "--tokenizer", tok,
                "--port", str(self.port),
                "--temperature", "0", "--max-seq-len", "64",
                "--batch-slots", "2", "--kv-pages", "64",
                "--kv-page-size", "4", "--no-prefix-reuse",
                "--handoff",
                "--probe-interval", "0.5", "--eject-after", "2",
                "--readmit-after", "2", "--router-retries", "3",
                "--checkpoint-interval", "1",
                "--stall-timeout", "10",
                # generous crash-loop budget: the drill's own murders
                # must not quarantine anyone
                "--respawn-max", "20", "--respawn-window", "60"]
        if snapshot_dir:
            argv += ["--snapshot-dir", snapshot_dir]
        if extra:
            argv += extra
        self.proc = subprocess.Popen(argv, cwd=REPO, env=env,
                                     stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)

    def wait_ready(self, timeout: float = 300.0) -> None:
        """Up = every replica admitted (children each load the model)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"pod died:\n{self.proc.stdout.read()[-4000:]}")
            try:
                if get(self.base, "/health", 2)["available"] >= self.dp:
                    return
            except OSError:
                pass
            time.sleep(0.5)
        raise RuntimeError("pod fleet never became fully available")

    def backend_ports(self) -> list[int]:
        rows = get(self.base, "/health")["backends"]
        return [int(r["addr"].rpartition(":")[2]) for r in rows]

    def kill_replica(self, port: int, sig: int) -> bool:
        kid = child_by_port(self.proc.pid, port)
        if kid is None:
            return False
        os.kill(kid, sig)
        return True

    def active_port(self) -> int | None:
        """Port of a replica currently decoding a scheduler request."""
        for p in self.backend_ports():
            try:
                h = get(f"http://127.0.0.1:{p}", "/health", 2)
            except OSError:
                continue
            if (h.get("scheduler") or {}).get("active", 0) >= 1:
                return p
        return None

    def stop(self) -> None:
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGTERM)
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.proc.wait()


class AvailabilitySampler:
    """Samples the router's fleet aggregate; reports unavailability
    windows (consecutive samples with no dispatchable backend)."""

    def __init__(self, base: str, period: float = 0.25):
        self.base = base
        self.period = period
        self.samples: list[tuple[float, bool]] = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            try:
                ok = get(self.base, "/health", 2)["available"] >= 1
            except OSError:
                ok = False
            self.samples.append((time.monotonic(), ok))

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def windows(self) -> list[float]:
        """Durations (s) of each contiguous unavailable run."""
        out, start = [], None
        for t, ok in self.samples:
            if not ok and start is None:
                start = t
            elif ok and start is not None:
                out.append(t - start)
                start = None
        if start is not None and self.samples:
            out.append(self.samples[-1][0] - start)
        return out


def _pct(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, max(0, round(q * (len(s) - 1))))]


def run_drill(*, quick: bool) -> int:
    from fixtures import write_tiny_model, write_tiny_tokenizer
    from trace_replay import replay_trace, synth_trace

    kills = 1 if quick else 4
    n_req = 16 if quick else 64
    rate = 4.0 if quick else 6.0
    n_parity = 2 if quick else 6
    failures: list[str] = []

    def check(cond: bool, msg: str) -> None:
        mark = "✅" if cond else "❌"
        print(f"{mark} {msg}")
        if not cond:
            failures.append(msg)

    with tempfile.TemporaryDirectory() as d:
        model, tok = os.path.join(d, "tiny.m"), os.path.join(d, "tiny.t")
        write_tiny_model(model)
        write_tiny_tokenizer(tok)
        pod = Pod(model, tok, dp=2,
                  snapshot_dir=os.path.join(d, "snap"),
                  # stretch decode so kills land mid-stream
                  faults="engine.device_step=delay:0.05")
        try:
            t0 = time.monotonic()
            pod.wait_ready()
            print(f"fleet up in {time.monotonic() - t0:.0f}s "
                  f"(router {pod.base}, replicas {pod.backend_ports()})")

            # solo greedy oracle, zero chaos: the byte-parity reference
            oracle, fin = stream_once(pod.base, GREEDY_BODY)
            assert fin in ("stop", "length") and oracle, (fin, oracle)

            ev0 = journal_cursor(pod.base)

            sampler = AvailabilitySampler(pod.base)
            sampler.start()

            replay_out: dict = {}

            def replay():
                replay_out["report"] = replay_trace(
                    pod.base, synth_trace(n_req, rate, max_tokens=12),
                    mix="interactive=0.3,standard=0.4,batch=0.3",
                    timeout=240.0)

            parity: list[tuple[str, str | None] | Exception] = []
            chaos_done = threading.Event()

            def parity_loop():
                # keep greedy traffic flowing until the last murder has
                # landed (the kill loop targets whichever replica is
                # decoding — without live streams it would starve), then
                # top up to at least n_parity streams
                while not (chaos_done.is_set()
                           and len(parity) >= n_parity):
                    if len(parity) >= n_parity * 8:  # runaway guard
                        break
                    try:
                        parity.append(stream_once(
                            pod.base, GREEDY_BODY, live))
                    except Exception as e:  # noqa: BLE001 — asserted below
                        parity.append(e)

            live: dict = {}
            rt = threading.Thread(target=replay, daemon=True)
            pt = threading.Thread(target=parity_loop, daemon=True)
            rt.start()
            pt.start()

            # chaos: murder the replica that is actually decoding,
            # alternating outright death (SIGKILL) and a wedge (SIGSTOP
            # — the supervisor's hang detector must SIGKILL + respawn)
            killed = 0
            deadline = time.monotonic() + (120 if quick else 300)
            while killed < kills and time.monotonic() < deadline:
                # murder only at full strength: the resume contract needs
                # a healthy peer, so back-to-back murders must not overlap
                # a victim still respawning/re-admitting
                try:
                    if get(pod.base, "/health", 2)["available"] < pod.dp:
                        time.sleep(0.5)
                        continue
                except OSError:
                    time.sleep(0.5)
                    continue
                port = pod.active_port()
                if port is None:
                    time.sleep(0.2)
                    continue
                sig = signal.SIGKILL if killed % 2 == 0 \
                    else signal.SIGSTOP
                if pod.kill_replica(port, sig):
                    killed += 1
                    print(f"💀 sent {signal.Signals(sig).name} to "
                          f"replica :{port} ({killed}/{kills})")
                    time.sleep(3.0 if quick else 8.0)  # let it recover
            chaos_done.set()
            rt.join(300)
            pt.join(300)
            sampler.stop()

            check(killed == kills,
                  f"chaos injected: {killed}/{kills} replica murders")

            # zero wrong bytes on greedy streams
            bad = [p for p in parity
                   if isinstance(p, Exception)
                   or p[1] not in ("stop", "length") or p[0] != oracle]
            check(not bad,
                  f"greedy byte parity: {len(parity) - len(bad)}/"
                  f"{len(parity)} streams identical to oracle"
                  + (f" (bad: {bad[:2]})" if bad else ""))

            # honest finish reasons + zero transport errors on the mix
            rep = replay_out.get("report") or {}
            classes = rep.get("classes") or {}
            errs = sum(c["errors"] for c in classes.values())
            finishes = set()
            for c in classes.values():
                finishes |= set(c["finish_reasons"])
            check(classes != {} and errs == 0,
                  f"replay mix: 0 transport errors "
                  f"({sum(c['sent'] for c in classes.values())} sent, "
                  f"{sum(c['shed_429'] for c in classes.values())} shed)")
            # "preempted" is honest too: the QoS layer parks batch work
            # under interactive pressure and finishes it truthfully when
            # the parked area overflows (docs/SERVING.md QoS)
            check(finishes <= {"stop", "length", "replica_lost",
                              "preempted"},
                  f"honest finish reasons only: {sorted(finishes)}")

            # bounded unavailability
            wins = sampler.windows()
            p95 = _pct(wins, 0.95)
            check(p95 <= 15.0 and max(wins, default=0.0) <= 45.0,
                  f"unavailability bounded: p95={p95:.1f}s "
                  f"max={max(wins, default=0.0):.1f}s "
                  f"({len(wins)} windows)")

            # capacity restored: every victim respawned + re-admitted
            deadline = time.monotonic() + 180
            avail = 0
            while time.monotonic() < deadline:
                avail = get(pod.base, "/health")["available"]
                if avail >= pod.dp:
                    break
                time.sleep(1.0)
            check(avail >= pod.dp,
                  f"fleet capacity restored: {avail}/{pod.dp} available")
            m = get(pod.base, "/metrics")
            respawns = sum((m.get("pod_respawns") or {}).values())
            check(respawns >= killed,
                  f"supervisor respawned every victim "
                  f"(pod_respawns={respawns})")
            print(f"   resumes={m.get('router_resumes')} "
                  f"stalls={m.get('router_stalls', 0)} "
                  f"replica_lost={m.get('router_replica_lost', 0)} "
                  f"retries={m.get('router_retries', 0)}")

            # the event journal narrates the whole chain in seq order
            check_murder_causality(journal_since(pod.base, ev0),
                                   killed, check)

            # zero leaked KV pages once quiesced
            leaks = []
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                leaks = []
                for p in pod.backend_ports():
                    try:
                        occ = get(f"http://127.0.0.1:{p}",
                                  "/health", 2).get("scheduler") or {}
                    except OSError:
                        leaks.append((p, "unreachable"))
                        continue
                    if occ.get("active") or occ.get("queued") \
                            or occ.get("parked") \
                            or occ.get("kv_pages_free") \
                            != occ.get("kv_pages_total"):
                        leaks.append((p, occ))
                if not leaks:
                    break
                time.sleep(1.0)
            check(not leaks,
                  "zero leaked KV pages"
                  + (f" (leaks: {leaks[:2]})" if leaks else ""))
        finally:
            pod.stop()

    if failures:
        print(f"\n{len(failures)} chaos assertion(s) FAILED")
        return 1
    print("\nchaos drill passed")
    return 0


def post(base: str, path: str, timeout: float = 10.0) -> dict:
    req = urllib.request.Request(base + path, data=b"", method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _reshape_converged(base: str, tp_want: int, n_want: int) -> bool:
    """The pod finished a reshape when the fleet block, the registry,
    and every backend's OWN mesh all agree on the new shape."""
    try:
        h = get(base, "/health", 2)
    except OSError:
        return False
    fl = h.get("fleet") or {}
    reps = fl.get("replicas") or []
    if fl.get("tp") != tp_want or fl.get("busy") is not None:
        return False
    if len(reps) != n_want or any(
            r["tp"] != tp_want or r["retiring"] for r in reps):
        return False
    if h.get("available", 0) < n_want:
        return False
    for row in h.get("backends") or []:
        p = int(row["addr"].rpartition(":")[2])
        try:
            mesh = get(f"http://127.0.0.1:{p}", "/health", 2).get(
                "mesh") or {}
        except OSError:
            return False
        if mesh.get("tp") != tp_want:
            return False
    return True


def run_reshape_drill(*, quick: bool) -> int:
    """Elastic-pod chaos: live 2×tp=1 → tp=2 reshape with a SIGKILL
    landing mid-migration (and the reverse reshape in full mode).
    Asserts the reshape converges, migrated greedy streams stay
    byte-identical to the solo oracle (PR 14 resume ladder), and no KV
    pages leak."""
    from fixtures import write_tiny_model, write_tiny_tokenizer

    n_parity = 2 if quick else 4
    failures: list[str] = []

    def check(cond: bool, msg: str) -> None:
        mark = "✅" if cond else "❌"
        print(f"{mark} {msg}")
        if not cond:
            failures.append(msg)

    with tempfile.TemporaryDirectory() as d:
        model, tok = os.path.join(d, "tiny.m"), os.path.join(d, "tiny.t")
        write_tiny_model(model)
        write_tiny_tokenizer(tok)
        pod = Pod(model, tok, dp=2,
                  snapshot_dir=os.path.join(d, "snap"),
                  # stretch decode so the SIGKILL lands mid-stream
                  faults="engine.device_step=delay:0.05",
                  # elastic with the policy neutered (impossible
                  # thresholds): only the drill's /admin commands act,
                  # so the reshape window is deterministic
                  extra=["--elastic", "--pod-devices", "4",
                         "--min-replicas", "1", "--max-replicas", "4",
                         "--elastic-interval", "0.2",
                         "--scale-up-util", "2", "--scale-down-util",
                         "-1", "--scale-up-queue", "1000000",
                         "--reshape-kv-low", "-1",
                         "--drain-grace", "60"])
        try:
            t0 = time.monotonic()
            pod.wait_ready()
            print(f"fleet up in {time.monotonic() - t0:.0f}s "
                  f"(router {pod.base}, replicas {pod.backend_ports()})")

            # solo greedy oracle before any chaos (tp=1 replicas; the
            # tp-serving tier proves greedy parity across tp degrees)
            oracle, fin = stream_once(pod.base, GREEDY_BODY)
            assert fin in ("stop", "length") and oracle, (fin, oracle)

            ev0 = journal_cursor(pod.base)

            sampler = AvailabilitySampler(pod.base)
            sampler.start()

            parity: list[tuple[str, str | None] | Exception] = []
            chaos_done = threading.Event()
            live: dict = {}

            def parity_loop():
                while not (chaos_done.is_set()
                           and len(parity) >= n_parity):
                    if len(parity) >= n_parity * 10:  # runaway guard
                        break
                    try:
                        parity.append(stream_once(
                            pod.base, GREEDY_BODY, live))
                    except Exception as e:  # noqa: BLE001 — asserted
                        parity.append(e)

            pt = threading.Thread(target=parity_loop, daemon=True)
            pt.start()

            # wait for at least one in-flight stream, then reshape
            deadline = time.monotonic() + 60
            while not live.get("chars") and time.monotonic() < deadline:
                time.sleep(0.1)
            print("🔁 POST /admin/reshape?tp=2 (in-flight streams live)")
            out = post(pod.base, "/admin/reshape?tp=2")
            check(out.get("accepted") is True,
                  f"reshape command accepted: {out}")

            # SIGKILL a decoding replica while the reshape is running
            time.sleep(1.0)
            killed = False
            deadline = time.monotonic() + 30
            while not killed and time.monotonic() < deadline:
                port = pod.active_port()
                if port is None:
                    time.sleep(0.2)
                    continue
                killed = pod.kill_replica(port, signal.SIGKILL)
            check(killed, "SIGKILL landed on a decoding replica "
                          "mid-reshape")

            # convergence: everything agrees the fleet is 2×tp=2
            deadline = time.monotonic() + (240 if quick else 420)
            while time.monotonic() < deadline:
                if _reshape_converged(pod.base, 2, 2):
                    break
                time.sleep(1.0)
            check(_reshape_converged(pod.base, 2, 2),
                  "reshape converged to 2×tp=2 despite the SIGKILL")

            if not quick:
                print("🔁 POST /admin/reshape?tp=1 (reverse)")
                post(pod.base, "/admin/reshape?tp=1")
                deadline = time.monotonic() + 420
                while time.monotonic() < deadline:
                    if _reshape_converged(pod.base, 1, 4):
                        break
                    time.sleep(1.0)
                check(_reshape_converged(pod.base, 1, 4),
                      "reverse reshape converged to 4×tp=1")

            chaos_done.set()
            pt.join(300)
            sampler.stop()

            # zero wrong bytes on the migrated greedy streams
            bad = [p for p in parity
                   if isinstance(p, Exception)
                   or p[1] not in ("stop", "length") or p[0] != oracle]
            check(not bad,
                  f"greedy byte parity through reshape: "
                  f"{len(parity) - len(bad)}/{len(parity)} streams "
                  f"identical to oracle"
                  + (f" (bad: {bad[:2]})" if bad else ""))

            # bounded unavailability through reshape + murder
            wins = sampler.windows()
            p95 = _pct(wins, 0.95)
            check(p95 <= 15.0 and max(wins, default=0.0) <= 45.0,
                  f"unavailability bounded: p95={p95:.1f}s "
                  f"max={max(wins, default=0.0):.1f}s "
                  f"({len(wins)} windows)")

            m = get(pod.base, "/metrics")
            events = m.get("pod_scale_events") or {}
            check(any(k.startswith("reshape") for k in events),
                  f"reshape recorded in pod_scale_events: {events}")

            # the journal narrates the reshape phases + the murder in
            # causal seq order: start before done, the death inside or
            # after the window it interrupted
            jev = journal_since(pod.base, ev0)
            starts = [e for e in jev if e["kind"] == "reshape"
                      and e.get("phase") == "start"]
            dones = [e for e in jev if e["kind"] == "reshape"
                     and e.get("phase") == "done"]
            deaths = [e for e in jev if e["kind"] == "death"]
            check(bool(starts) and bool(dones)
                  and starts[0]["seq"] < dones[-1]["seq"],
                  f"journal: reshape start→done in seq order "
                  f"(starts x{len(starts)}, dones x{len(dones)})")
            check(bool(deaths)
                  and any(d["seq"] > starts[0]["seq"] for d in deaths),
                  f"journal: mid-reshape murder recorded "
                  f"(death x{len(deaths)} after reshape start)")

            # zero leaked KV pages on the surviving (new-shape) fleet
            leaks = []
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                leaks = []
                for p in pod.backend_ports():
                    try:
                        occ = get(f"http://127.0.0.1:{p}",
                                  "/health", 2).get("scheduler") or {}
                    except OSError:
                        leaks.append((p, "unreachable"))
                        continue
                    if occ.get("active") or occ.get("queued") \
                            or occ.get("parked") \
                            or occ.get("kv_pages_free") \
                            != occ.get("kv_pages_total"):
                        leaks.append((p, occ))
                if not leaks:
                    break
                time.sleep(1.0)
            check(not leaks,
                  "zero leaked KV pages after reshape"
                  + (f" (leaks: {leaks[:2]})" if leaks else ""))
        finally:
            pod.stop()

    if failures:
        print(f"\n{len(failures)} reshape-chaos assertion(s) FAILED")
        return 1
    print("\nreshape chaos drill passed")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--quick", action="store_true",
                    help="single-kill smoke instead of the full soak")
    ap.add_argument("--reshape", action="store_true",
                    help="elastic-pod variant: SIGKILL a replica "
                         "DURING a live tp reshape and assert "
                         "convergence + byte parity + zero KV leaks")
    args = ap.parse_args(argv)
    if args.reshape:
        return run_reshape_drill(quick=args.quick)
    return run_drill(quick=args.quick)


if __name__ == "__main__":
    raise SystemExit(main())
