"""Model-zoo launcher.

Re-implements `/root/reference/launch.py`: downloads a converted `.m`/`.t`
pair from the model zoo and writes a ready-to-run script.  Same model list
(launch.py:6-22); the generated run command targets the TPU mesh
(``--workers tpu:N``) instead of spawning TCP workers.

Note: this build environment has zero network egress — downloads will fail
here, but the tool is part of the capability surface and works wherever the
zoo is reachable.

Usage: python launch.py <model-name> [--tp N]
"""

from __future__ import annotations

import os
import sys
import urllib.request

# [model-url, tokenizer-url, weights-float-type, buffer-float-type, model-type]
MODELS = {
    "tinyllama_1_1b_3t_q40": [
        "https://huggingface.co/b4rtaz/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_model_tinylama_1.1b_3t_q40.m?download=true",
        "https://huggingface.co/b4rtaz/TinyLlama-1.1B-3T-Distributed-Llama/resolve/main/dllama_tokenizer_tinylama_1.1b_3t.t?download=true",
        "q40", "q80", "base",
    ],
    "llama3_8b_q40": [
        "https://huggingface.co/b4rtaz/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_model_meta-llama-3-8b_q40.m?download=true",
        "https://huggingface.co/b4rtaz/Llama-3-8B-Q40-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
        "q40", "q80", "base",
    ],
    "llama3_8b_instruct_q40": [
        "https://huggingface.co/b4rtaz/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_model_lama3_instruct_q40.m?download=true",
        "https://huggingface.co/b4rtaz/Llama-3-8B-Q40-Instruct-Distributed-Llama/resolve/main/dllama_tokenizer_llama3.t?download=true",
        "q40", "q80", "chat",
    ],
}


def download_file(url: str, path: str) -> None:
    if os.path.isfile(path):
        print(f"📄 {os.path.basename(path)} already exists, skipping")
        return
    print(f"📄 {url}")
    with urllib.request.urlopen(url) as r, open(path, "wb") as f:
        while True:
            chunk = r.read(1 << 16)
            if not chunk:
                break
            f.write(chunk)
            size = f.tell() // 1024
            sys.stdout.write(f"\rDownloaded {size} kB")
    sys.stdout.write(" ✅\n")


def launch(name: str, tp: int = 1) -> None:
    if name not in MODELS:
        raise SystemExit(f"unknown model {name}; available: {', '.join(MODELS)}")
    model = MODELS[name]
    dir_path = os.path.join("models", name)
    os.makedirs(dir_path, exist_ok=True)
    model_path = os.path.join(dir_path, f"dllama_model_{name}.m")
    tok_path = os.path.join(dir_path, f"dllama_tokenizer_{name}.t")
    download_file(model[0], model_path)
    download_file(model[1], tok_path)

    mode = "chat" if model[4] == "chat" else "inference"
    command = (f"python -m dllama_tpu {mode} --model {model_path} "
               f"--tokenizer {tok_path} --buffer-float-type bf16 "
               f"--workers tpu:{tp}")
    run_path = f"run_{name}.sh"
    with open(run_path, "w") as f:
        f.write(f"#!/bin/sh\n\n{command}\n")
    os.chmod(run_path, 0o755)
    print(f"🚀 Created {run_path}:\n   {command}")


if __name__ == "__main__":
    if len(sys.argv) < 2 or sys.argv[1] in ("-h", "--help"):
        print(__doc__)
        print("Available models:\n  " + "\n  ".join(MODELS))
        raise SystemExit(0 if len(sys.argv) > 1 else 1)
    tp_arg = 1
    if "--tp" in sys.argv:
        tp_arg = int(sys.argv[sys.argv.index("--tp") + 1])
    launch(sys.argv[1], tp_arg)
