"""HBM memory planner (tools/memory_plan.py): byte math + fit search."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from tools.memory_plan import PRESETS, _cfg, find_fit, plan  # noqa: E402


def test_llama2_7b_single_chip_fits():
    cfg = _cfg("llama2-7b")
    p = plan(cfg)
    # ~6.74 G matmul weights × 0.5625 B ≈ 3.7 GB packed
    assert 3.3e9 < p["weights_sharded"] < 4.2e9
    assert p["fits_v5e"]


def test_tp_shards_weights_and_cache():
    cfg = _cfg("llama2-7b")
    p1, p8 = plan(cfg, tp=1), plan(cfg, tp=8)
    assert abs(p8["weights_sharded"] - p1["weights_sharded"] / 8) < 1e6
    assert abs(p8["kv_cache"] - p1["kv_cache"] / 8) < 1e6
    assert p8["weights_replicated"] == p1["weights_replicated"]


def test_sp_shards_cache_only():
    cfg = _cfg("llama3-8b")
    p1, p4 = plan(cfg, sp=1), plan(cfg, sp=4)
    assert abs(p4["kv_cache"] - p1["kv_cache"] / 4) < 1e6
    assert p4["weights_sharded"] == p1["weights_sharded"]


def test_grok_needs_multihost_scale():
    """docs/MEMORY.md's conclusion, as executable math: Grok-1-314B cannot
    fit 8 chips; the smallest fitting mesh is a 16-chip (multi-host on
    v5e-8 hardware) tp×ep layout."""
    cfg = _cfg("grok-314b")
    assert not plan(cfg, tp=8)["fits_v5e"]
    best = find_fit(cfg)
    assert best is not None
    tp, sp, ep, p = best
    assert tp * sp * ep == 16
    assert p["fits_v5e"]


def test_ep_shards_expert_weights():
    cfg = _cfg("mixtral-8x7b")
    p1, p8 = plan(cfg, ep=1), plan(cfg, ep=8)
    # experts dominate mixtral: /8 on experts cuts sharded bytes ~7.7x
    assert p8["weights_sharded"] < p1["weights_sharded"] / 6


def test_cli_runs():
    for model in ("llama2-7b", "grok-314b"):
        r = subprocess.run(
            [sys.executable, "tools/memory_plan.py", model, "--fit"],
            capture_output=True, text=True, timeout=120,
            cwd=REPO)
        assert r.returncode == 0, r.stderr
        assert "per_chip" in r.stdout and "mesh" in r.stdout


def test_presets_all_resolve():
    for name in PRESETS:
        cfg = _cfg(name)
        assert plan(cfg)["per_chip"] > 0


def test_unrealizable_mesh_rejected():
    import pytest
    cfg = _cfg("llama3-8b")  # 8 kv heads
    with pytest.raises(ValueError, match="nKvHeads"):
        plan(cfg, tp=32)
