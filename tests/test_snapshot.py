"""Engine snapshot/restore tests.

The contract (docs/ROBUSTNESS.md): after ``Engine.restore()`` the
continued decode stream is *token-identical* to an engine that never
restarted — KV cache, position clock, sampler RNG stream, and ragged
offsets all come back exactly.  And the failure half: a corrupt,
truncated, or differently-configured snapshot raises
:class:`ArtifactError`/:class:`SnapshotMismatch` — the server's boot
path turns that into a logged cold start, never a crash.
"""

import numpy as np
import pytest
import jax

from dllama_tpu.io.integrity import ArtifactError, counters, reset_counters
from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime import snapshot as snapfmt
from dllama_tpu.runtime.engine import Engine, NumericFault
from dllama_tpu.runtime.snapshot import SnapshotMismatch

pytestmark = pytest.mark.integrity

CFG = tiny_config(seq_len=64)


def make_engine(cfg=CFG, seed=4, **kw):
    return Engine(cfg, init_params(cfg, seed=seed),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]), **kw)


def turn(eng, prompt, seed, n=10):
    """One sampled chat turn; seed=None continues the RNG stream."""
    return [t for t, _ in eng.generate_stream(
        prompt, n, temperature=0.8, seed=seed, chunk=4)]


def test_roundtrip_token_identical(tmp_path):
    """Restore → continued decode matches the uninterrupted engine token
    for token, including the cross-turn RNG stream (seed=None)."""
    path = str(tmp_path / "engine.snap")
    e1 = make_engine()
    turn(e1, [3, 4, 1], seed=7)
    e1.snapshot(path, extra={"note": "turn-1"})
    pos_at_snapshot = e1.pos
    uninterrupted = turn(e1, [8, 2], seed=None)

    e2 = make_engine()  # same params, fresh state
    extra = e2.restore(path)
    assert extra["note"] == "turn-1"
    assert e2.pos == pos_at_snapshot
    restored = turn(e2, [8, 2], seed=None)
    assert restored == uninterrupted


def test_roundtrip_quantized_cache(tmp_path):
    """A q8 KV cache snapshots all four arrays (values + scales) and
    restores token-identically."""
    path = str(tmp_path / "q8.snap")
    e1 = make_engine(kv_dtype="q8")
    assert e1.cache.quantized
    turn(e1, [5, 9, 2], seed=3)
    e1.snapshot(path)
    scales_at_snapshot = np.asarray(e1.cache.k_scale).copy()
    uninterrupted = turn(e1, [7], seed=None)
    e2 = make_engine(kv_dtype="q8")
    e2.restore(path)
    np.testing.assert_array_equal(np.asarray(e2.cache.k_scale),
                                  scales_at_snapshot)
    assert turn(e2, [7], seed=None) == uninterrupted


def test_fingerprint_mismatch_cold_start(tmp_path):
    """A snapshot from a differently-shaped engine is refused with
    SnapshotMismatch (an ArtifactError → the server cold-starts)."""
    path = str(tmp_path / "engine.snap")
    e1 = make_engine(cfg=tiny_config(seq_len=32))
    turn(e1, [3], seed=1)
    e1.snapshot(path)
    e2 = make_engine(cfg=tiny_config(seq_len=64))
    with pytest.raises(SnapshotMismatch, match="differently-configured"):
        e2.restore(path)
    assert isinstance(SnapshotMismatch(path, "x", "y"), ArtifactError)
    assert e2.pos == 0  # engine untouched by the refused restore


def test_quantized_vs_dense_layout_mismatch(tmp_path):
    """Cache layout is part of the fingerprint: a dense snapshot cannot
    restore into a q8 engine."""
    path = str(tmp_path / "dense.snap")
    e1 = make_engine()
    e1.snapshot(path)
    with pytest.raises(SnapshotMismatch):
        make_engine(kv_dtype="q8").restore(path)


def test_corrupt_snapshot_rejected(tmp_path):
    """Any single-byte flip fails the load's crc32 (covers meta AND
    payload) with an ArtifactError naming the field."""
    path = str(tmp_path / "engine.snap")
    e = make_engine()
    turn(e, [3, 4], seed=2)
    e.snapshot(path)
    data = bytearray(open(path, "rb").read())
    rng = np.random.RandomState(9)
    for off in sorted({0, 9, len(data) - 1} |
                      {int(o) for o in rng.randint(len(data), size=12)}):
        flipped = bytearray(data)
        flipped[off] ^= 0x10
        bad = str(tmp_path / "bad.snap")
        with open(bad, "wb") as f:
            f.write(flipped)
        with pytest.raises(ArtifactError):
            make_engine().restore(bad)


def test_truncated_snapshot_rejected(tmp_path):
    path = str(tmp_path / "engine.snap")
    e = make_engine()
    e.snapshot(path)
    data = open(path, "rb").read()
    for keep in (0, 7, 13, len(data) // 2, len(data) - 1):
        bad = str(tmp_path / "trunc.snap")
        with open(bad, "wb") as f:
            f.write(data[:keep])
        with pytest.raises(ArtifactError):
            make_engine().restore(bad)


def test_pos_out_of_range_rejected(tmp_path):
    """A forged-but-checksummed snapshot with pos past the context window
    is refused (defense against a stale snapshot from a longer run)."""
    e = make_engine()
    arrays = {n: np.asarray(a) for n, a in e._cache_arrays().items()}
    arrays["rng_key"] = np.asarray(e._key)
    path = str(tmp_path / "forged.snap")
    snapfmt.save(path, fingerprint=e.config_fingerprint(),
                 pos=e.seq_len + 1, chunk_counter=0, arrays=arrays)
    with pytest.raises(SnapshotMismatch, match="position"):
        e.restore(path)


def test_missing_cache_array_rejected(tmp_path):
    e = make_engine()
    path = str(tmp_path / "partial.snap")
    snapfmt.save(path, fingerprint=e.config_fingerprint(), pos=0,
                 chunk_counter=0,
                 arrays={"cache.k": np.asarray(e.cache.k),
                         "rng_key": np.asarray(e._key)})
    with pytest.raises(SnapshotMismatch, match="cache.v"):
        e.restore(path)


def test_restore_counter_exported(tmp_path):
    reset_counters()
    e = make_engine()
    path = str(tmp_path / "engine.snap")
    e.snapshot(path)
    make_engine().restore(path)
    assert counters()["snapshot_restores"] == 1


def test_numeric_guard_raises_on_injected_nan():
    """--numeric-checks: the engine.numeric=nan fault poisons the host
    logits and the guard raises NumericFault naming step and pos —
    instead of sampling garbage tokens from NaN logits."""
    from dllama_tpu.runtime.faults import injected
    reset_counters()
    e = make_engine(numeric_checks=True)
    with injected("engine.numeric=nanx1"):
        with pytest.raises(NumericFault, match="pos=") as ei:
            e.prefill([3, 4, 1])
        assert ei.value.step == "prefill"
    assert counters()["numeric_faults"] == 1
    e.reset()
    toks = turn(e, [3, 4, 1], seed=7)  # disarmed: decodes normally
    assert len(toks) == 10


def test_numeric_guard_off_by_default():
    from dllama_tpu.runtime.faults import injected
    e = make_engine()
    assert not e.numeric_checks
    with injected("engine.numeric=nanx1"):
        e.prefill([3])  # unchecked: the fault point is never consulted


def test_server_restore_snapshot_cold_start_paths(tmp_path):
    """ApiState.restore_snapshot: warm start on a good snapshot (one-shot
    file), logged cold start — not a crash — on a corrupt one."""
    import os

    from fixtures import write_tiny_tokenizer

    from dllama_tpu.server.api import ApiState
    from dllama_tpu.tokenizer.bpe import Tokenizer

    tok = Tokenizer(write_tiny_tokenizer(str(tmp_path / "tok.t")))
    cfg = tiny_config(seq_len=64, vocab_size=300)
    snap_dir = str(tmp_path / "snaps")

    eng = make_engine(cfg=cfg)
    state = ApiState(eng, tok, snapshot_dir=snap_dir)
    assert state.restore_snapshot() is False  # nothing to restore yet
    turn(eng, [3, 4], seed=5)
    assert state.save_snapshot() == state.snapshot_path

    eng2 = make_engine(cfg=cfg)
    state2 = ApiState(eng2, tok, snapshot_dir=snap_dir)
    assert state2.restore_snapshot() is True
    assert eng2.pos == eng.pos
    assert not os.path.exists(state2.snapshot_path)  # one-shot

    # corrupt snapshot → cold start, file kept for postmortem
    state.save_snapshot()
    with open(state.snapshot_path, "r+b") as f:
        f.seek(20)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    eng3 = make_engine(cfg=cfg)
    state3 = ApiState(eng3, tok, snapshot_dir=snap_dir)
    assert state3.restore_snapshot() is False
    assert eng3.pos == 0
    assert os.path.exists(state3.snapshot_path)


# -- DLSNAP02: paged-KV state ----------------------------------------------

def test_legacy_dlsnap01_magic_rejected(tmp_path):
    """A DLSNAP01-era file is refused with a 'superseded format' error
    (an ArtifactError, so the server's restore path cold-starts exactly
    like the corrupt-file case — with a reason that says why)."""
    path = str(tmp_path / "engine.snap")
    e = make_engine()
    e.snapshot(path)
    data = bytearray(open(path, "rb").read())
    assert data[:8] == b"DLSNAP02"
    data[:8] = b"DLSNAP01"  # the header crc covers meta+payload, not magic
    old = str(tmp_path / "old.snap")
    with open(old, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(ArtifactError, match="superseded"):
        snapfmt.load(old)
    with pytest.raises(ArtifactError, match="superseded"):
        make_engine().restore(old)


def make_paged_stack(kv_pages=17, page=8, batch=2, prefix_reuse=True):
    from dllama_tpu.runtime.scheduler import SlotScheduler
    eng = make_engine(batch=batch, kv_pages=kv_pages, kv_page_size=page)
    return eng, SlotScheduler(eng, prefill_chunk=4,
                              prefix_reuse=prefix_reuse)


def test_paged_scheduler_snapshot_roundtrip(tmp_path):
    """snapshot_paged persists the pool KV, page tables, and the radix
    tree's token keys; restore_paged rebuilds them so a prompt that
    matched the tree before the restart still matches after it — and the
    reused decode is byte-identical to the pre-restart one."""
    from dllama_tpu.obs import metrics as obs_metrics
    path = str(tmp_path / "sched.snap")
    prompt = list(range(1, 18))  # two full 8-token blocks + a suffix
    eng1, sched1 = make_paged_stack()
    try:
        t = sched1.submit(prompt, 8, temperature=0.0)
        ref = list(t.tokens())
        assert len(sched1.prefix_cache) == 2
        sched1.snapshot_paged(path, extra={"note": "pre-restart"})
    finally:
        sched1.close()

    eng2, sched2 = make_paged_stack()
    try:
        extra = sched2.restore_paged(path)
        assert extra["note"] == "pre-restart"
        assert len(sched2.prefix_cache) == 2
        assert sched2.pool.in_use == 2
        sched2.pool.check()
        reused0 = obs_metrics.PREFIX_TOKENS_REUSED.value
        t = sched2.submit(prompt, 8, temperature=0.0)
        out = list(t.tokens())
        # the restored tree (and restored pool KV) served the prefix
        assert obs_metrics.PREFIX_TOKENS_REUSED.value - reused0 == 16
        assert out == ref
    finally:
        sched2.close()


def test_paged_pool_geometry_mismatch(tmp_path):
    """Pool geometry rides the config fingerprint: a snapshot from a
    different page count or size is refused with SnapshotMismatch and the
    scheduler cold-starts untouched."""
    path = str(tmp_path / "sched.snap")
    eng1, sched1 = make_paged_stack(kv_pages=17)
    try:
        sched1.snapshot_paged(path)
    finally:
        sched1.close()
    for kw in ({"kv_pages": 9}, {"kv_pages": 34, "page": 4}):
        eng2, sched2 = make_paged_stack(**kw)
        try:
            with pytest.raises(SnapshotMismatch):
                sched2.restore_paged(path)
        finally:
            sched2.close()
