"""Overlapped dispatch pipeline tests (runtime/scheduler.py two-deep
pipeline + runtime/engine.py ``slot_step_async`` / ``feed_dev``).

The tentpole contracts, each pinned here on CPU with a tiny model:

* **device feedback parity** — an async dispatch chain fed by the
  previous dispatch's on-device last-token row (``feed_dev``, no
  device→host→device round trip) is byte-identical to the synchronous
  host-feedback chain, and the ``fresh`` compile bit reports executable
  reuse honestly;
* **overlap on/off byte parity** — greedy output under ragged staggered
  traffic is identical with the pipeline on and off, including EOS
  stops and cancels (partial output is a prefix of the solo run);
* **flush correctness** — a hand-off export fired mid-pipeline lands
  and discards the in-flight pipelined dispatch before any DLREQ01
  snapshot is taken (zero in-flight observed), and the exported request
  resumes byte-identically on a peer;
* **honest accounting** — host gap hidden behind device compute is
  reported as hidden (timeline ``hidden_host_ms`` + the hidden-gap
  counter), never silently dropped; discarded dispatches are marked and
  counted; the goodput components still telescope (the existing
  test_scheduler.py sum-to-wall test runs with overlap on by default);
* **EMA compile poisoning** — a fresh-compile dispatch's trace+compile
  wall never moves the burst-size EMA;
* **parked wakeups** — an idle scheduler wakes from its parked wait a
  handful of times per second (deadline-derived timeout, 0.5s cap),
  not the old fixed-0.1s poll's ~10/s, while queued-deadline expiry
  stays accurate.
"""

import threading
import time

import numpy as np
import pytest

import jax

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.obs import flight as obs_flight, metrics as obs_metrics
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime import snapshot as snapfmt
from dllama_tpu.runtime.engine import Engine, SlotDispatch
from dllama_tpu.runtime.faults import FAULTS, injected
from dllama_tpu.runtime.scheduler import SlotScheduler

CFG = tiny_config(seq_len=64)
PAGE = 4
P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]
P3 = [2, 4, 6]
P4 = [9, 8, 7, 6]
PROMPTS = (P1, P2, P3, P4)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def make_engine(batch=1):
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch)


def make_paged_engine(batch=2, page=PAGE):
    pages_per_slot = -(-CFG.seq_len // page)
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch,
                  kv_pages=batch * pages_per_slot + 1,
                  kv_page_size=page)


@pytest.fixture(scope="module")
def solo_refs():
    """Greedy solo completions per prompt — the parity oracle."""
    eng = make_engine()
    refs = {}
    for p in PROMPTS:
        eng.reset()
        toks = [t for t, _ in eng.generate_stream(
            p, len(p) + 30, temperature=0.0, chunk=5)]
        refs[tuple(p)] = toks[len(p):]
    return refs


# -- engine layer: slot_step_async + device-resident feedback --------------

def test_slot_step_async_feed_parity():
    """The async chain fed by ``last_dev`` must be byte-identical to the
    synchronous host-feedback chain, with no host transfer of the fed
    tokens (``last_dev`` stays a device array)."""
    e_sync, e_async = make_engine(2), make_engine(2)
    b = 2
    tokens = np.zeros((b, 4), np.int32)
    tokens[0, :len(P1)] = P1
    tokens[1, :] = P4
    n_valid = np.array([len(P1), 4], np.int32)
    pos = np.zeros((b,), np.int32)
    temps = np.zeros((b,), np.float32)
    topps = np.full((b,), 0.9, np.float32)

    # sync path: host feedback each burst
    out_sync = [e_sync.slot_step(tokens, pos, n_valid, temps_np=temps,
                                 topps_np=topps, steps=1)]
    pos_s = pos + n_valid
    for _ in range(3):
        fed = out_sync[-1][-1][:, None].astype(np.int32)
        out_sync.append(e_sync.slot_step(fed, pos_s, np.ones((b,), np.int32),
                                         temps_np=temps, topps_np=topps,
                                         steps=4))
        pos_s = pos_s + 4

    # async path: device-resident feedback, land only at the end
    handles = [e_async.slot_step_async(tokens, pos, n_valid, temps_np=temps,
                                       topps_np=topps, steps=1)]
    assert isinstance(handles[0], SlotDispatch)
    assert handles[0].fresh  # first executable for this key
    pos_a = pos + n_valid
    for _ in range(3):
        handles.append(e_async.slot_step_async(
            None, pos_a, np.ones((b,), np.int32), temps_np=temps,
            topps_np=topps, steps=4, feed_dev=handles[-1].last_dev))
        pos_a = pos_a + 4
    # the fed token block never visited the host
    assert all(isinstance(h.last_dev, jax.Array) for h in handles)
    out_async = [h.wait() for h in handles]
    # the decode-burst executable was minted once, then reused
    assert handles[1].fresh and not handles[2].fresh and not handles[3].fresh
    for a, s in zip(out_async, out_sync):
        np.testing.assert_array_equal(a, s)


def test_slot_step_async_feed_dev_validation():
    eng = make_engine(2)
    with pytest.raises(ValueError, match="feed_dev"):
        eng.slot_step_async(np.zeros((2, 1), np.int32), np.zeros((2,), np.int32),
                            np.ones((2,), np.int32),
                            temps_np=np.zeros((2,), np.float32),
                            topps_np=np.full((2,), 0.9, np.float32),
                            feed_dev=jax.numpy.zeros((2,), jax.numpy.int32))
    with pytest.raises(ValueError, match="tokens_np or feed_dev"):
        eng.slot_step_async(None, np.zeros((2,), np.int32),
                            np.ones((2,), np.int32),
                            temps_np=np.zeros((2,), np.float32),
                            topps_np=np.full((2,), 0.9, np.float32))


# -- scheduler: overlap on/off byte parity ---------------------------------

def _run_traffic(sched, solo_refs, *, eos_prompt=None, eos_at=3):
    """Staggered ragged greedy traffic; returns {prompt: (tokens, finish)}.
    ``eos_prompt`` additionally runs one request with an EOS id picked
    from its own solo reference (stop-mid-burst coverage)."""
    results = {}

    def run(p, delay, max_new, eos_ids):
        time.sleep(delay)
        t = sched.submit(p, max_new, eos_ids=eos_ids)
        results[tuple(p)] = (list(t.tokens()), t.finish)

    jobs = [(p, d, 12, ()) for p, d in zip(PROMPTS, (0.0, 0.03, 0.2, 0.4))]
    if eos_prompt is not None:
        ref = solo_refs[tuple(eos_prompt)]
        jobs.append((list(eos_prompt) + [13], 0.1, 25, (ref[eos_at],)))
    threads = [threading.Thread(target=run, args=j) for j in jobs]
    for th in threads:
        th.start()
    for th in threads:
        th.join(120)
    return results


def test_overlap_on_off_greedy_byte_parity(solo_refs):
    """Acceptance: greedy output is byte-identical with the pipeline on
    vs off under ragged staggered traffic, and the on-path actually
    overlapped dispatches."""
    outs = {}
    for overlap in (False, True):
        sched = SlotScheduler(make_engine(4), prefill_chunk=4,
                              max_wait_ms=50.0, decode_burst=6,
                              overlap=overlap)
        try:
            outs[overlap] = _run_traffic(sched, solo_refs)
            if overlap:
                assert sched._n_overlapped > 0, \
                    "steady-state decode never entered the pipeline"
                sched.flush()  # the last round may still be landing
                assert sched._inflight_n == 0 and sched._depth == 0
            else:
                assert sched._n_overlapped == 0
        finally:
            sched.close()
    assert outs[True] == outs[False]
    for p in PROMPTS:
        got, finish = outs[True][tuple(p)]
        assert got == solo_refs[tuple(p)][:12], p
        assert finish == "length"


def test_overlap_eos_stop_parity(solo_refs):
    """A row hitting EOS mid-pipeline retires row-wise; its neighbors'
    output and its own truncation point match the synchronous path."""
    outs = {}
    for overlap in (False, True):
        sched = SlotScheduler(make_engine(4), prefill_chunk=4,
                              max_wait_ms=50.0, decode_burst=6,
                              overlap=overlap)
        try:
            outs[overlap] = _run_traffic(sched, solo_refs, eos_prompt=P2)
        finally:
            sched.close()
    assert outs[True] == outs[False]
    eos_key = tuple(list(P2) + [13])
    got, finish = outs[True][eos_key]
    assert finish == "stop"


def test_overlap_cancel_partial_prefix(solo_refs):
    """Cancel mid-decode with the pipeline live: the partial output is a
    prefix of the solo run (no token from a discarded dispatch leaks)."""
    sched = SlotScheduler(make_engine(4), prefill_chunk=4, decode_burst=6,
                          overlap=True)
    try:
        with injected("engine.device_step=delay:0.02x100000"):
            t = sched.submit(P1, 50)
            got = []
            for tok in t.tokens():
                got.append(tok)
                if len(got) >= 3:
                    t.cancel("aborted")
        assert t.finish == "aborted"
        assert got == solo_refs[tuple(P1)][:len(got)]
        assert 0 < len(got) < 50
        assert sched._inflight_n == 0 and sched._depth == 0
    finally:
        sched.close()


# -- flush correctness ------------------------------------------------------

@pytest.fixture(scope="module")
def paged_solo_ref():
    eng = make_engine(1)
    toks = [t for t, _ in eng.generate_stream(
        P1, len(P1) + 30, temperature=0.0, chunk=5)]
    return toks[len(P1):]


def test_handoff_export_flushes_pipeline(paged_solo_ref):
    """Acceptance: a hand-off export fired mid-pipeline observes zero
    in-flight dispatches at every DLREQ01 snapshot, and the exported
    request resumes byte-identically on a peer scheduler."""
    sa = SlotScheduler(make_paged_engine(), prefill_chunk=4,
                       max_wait_ms=20.0, decode_burst=4, overlap=True)
    sb = SlotScheduler(make_paged_engine(), prefill_chunk=4,
                       max_wait_ms=20.0, decode_burst=4, overlap=True)
    inflight_seen = []
    real_export = sa._export_slot_locked

    def spying_export(slot_idx):
        inflight_seen.append(sa._inflight_n)
        return real_export(slot_idx)

    sa._export_slot_locked = spying_export
    try:
        with injected("engine.device_step=delay:0.05x100000"):
            # a second concurrent stream plus a cancel exercise the
            # cancel-flush path while the export flush runs
            t_bg = sa.submit(P3, 40, temperature=0.0)
            t = sa.submit(P1, 30, temperature=0.0)
            it = t.tokens()
            consumed = [next(it) for _ in range(6)]
            t_bg.cancel("aborted")
            records = sa.handoff_export_all()
        list(it)
        assert t.finish == "handoff"
        assert t.rid in records
        assert inflight_seen and all(n == 0 for n in inflight_seen), \
            inflight_seen
        assert sa._inflight_n == 0 and sa._depth == 0

        meta, _ = snapfmt.loads_request(records[t.rid])
        replayed = [int(x) for x in meta["extra"]["completion"]]
        assert replayed[:len(consumed)] == consumed
        t2, _ = sb.import_request(records[t.rid])
        resumed = list(t2.tokens())
        assert t2.finish == "length"
        assert replayed + resumed == paged_solo_ref
    finally:
        sa.close()
        sb.close()


def test_flush_discards_inflight_dispatch():
    """flush() lands-and-discards the pipelined dispatch: the discard
    counter moves, the timeline marks the entry discarded, and greedy
    output is unaffected."""
    sched = SlotScheduler(make_engine(2), prefill_chunk=4, decode_burst=4,
                          overlap=True)
    # warm every executable off the clock (prefill chunk widths + the
    # decode-burst key the pipelined dispatch shares) — CPU compiles
    # take ~1s each and would otherwise stall the timed phase below
    list(sched.submit(P2, 8).tokens())
    obs_flight.TIMELINE.clear()
    before = obs_metrics.SCHED_OVERLAP_DISCARDS.value
    try:
        with injected("engine.device_step=delay:0.05x100000"):
            t = sched.submit(P2, 50)
            time.sleep(0.3)  # steady decode: pipeline nearly always full
            for _ in range(5):
                sched.flush()
                assert sched._inflight_n == 0
                time.sleep(0.1)
            t.cancel("aborted")
            list(t.tokens())
    finally:
        sched.close()
    assert obs_metrics.SCHED_OVERLAP_DISCARDS.value > before, \
        "five flushes against a saturated pipeline never caught a " \
        "pipelined dispatch in flight"
    discarded = [e for e in obs_flight.TIMELINE.snapshot()
                 if e.get("discarded")]
    assert discarded
    for e in discarded:
        assert e["overlapped"] and e["steps"] >= 1
        assert all(s["phase"] == "pad" for s in e["slots"])


# -- honest accounting ------------------------------------------------------

def test_hidden_host_gap_reported_as_hidden(solo_refs):
    """Host gap the pipeline hid behind device compute must show up as
    ``hidden_host_ms`` on overlapped timeline entries and in the hidden
    counter — not vanish, and not pollute the exposed histogram."""
    sched = SlotScheduler(make_engine(2), prefill_chunk=4, decode_burst=4,
                          overlap=True)
    obs_flight.TIMELINE.clear()
    hidden_before = obs_metrics.SCHED_HOST_GAP_HIDDEN_MS.value
    try:
        # device busy 30ms per dispatch, host fanout 5ms per dispatch:
        # the 5ms rides entirely under the in-flight dispatch
        with injected("engine.device_step=delay:0.03x100000,"
                      "sched.host_fanout=delay:0.005x100000"):
            t = sched.submit(P1, 16)
            assert list(t.tokens()) == solo_refs[tuple(P1)][:16]
    finally:
        sched.close()
    entries = obs_flight.TIMELINE.snapshot()
    overlapped = [e for e in entries
                  if e["overlapped"] and not e.get("discarded")]
    assert overlapped, "no dispatch overlapped under steady decode"
    assert any(e["hidden_host_ms"] > 0 for e in overlapped)
    # hidden gap is charged to the hidden counter, and an overlapped
    # entry never double-counts the same ms as exposed host_gap
    assert obs_metrics.SCHED_HOST_GAP_HIDDEN_MS.value > hidden_before
    for e in overlapped:
        if e["hidden_host_ms"] > 0:
            assert e["host_gap_ms"] == 0
    # non-discarded overlapped entries carry live rows, mark the mode
    assert any(s["phase"] == "decode"
               for e in overlapped for s in e["slots"])


def test_overlap_metrics_in_both_formats(solo_refs):
    """Acceptance: pipeline state is exported in the JSON snapshot and
    the Prometheus rendering."""
    sched = SlotScheduler(make_engine(2), prefill_chunk=4, decode_burst=4,
                          overlap=True)
    try:
        t = sched.submit(P3, 12)
        assert list(t.tokens()) == solo_refs[tuple(P3)][:12]
        assert sched._n_overlapped > 0
    finally:
        sched.close()
    js = obs_metrics.snapshot_json()
    for key in ("sched_overlap_ratio", "sched_inflight_depth",
                "sched_host_gap_hidden_ms", "sched_overlap_discards"):
        assert key in js, key
    assert 0 < js["sched_overlap_ratio"] <= 1.0
    assert js["sched_inflight_depth"] == 0  # pipeline drained at close
    prom = obs_metrics.render_prometheus()
    for name in ("dllama_sched_overlap_ratio",
                 "dllama_sched_inflight_depth",
                 "dllama_sched_host_gap_hidden_ms_total",
                 "dllama_sched_overlap_discards_total"):
        assert name in prom, name


# -- EMA compile poisoning (satellite) --------------------------------------

def test_ema_ignores_fresh_compile_wall():
    """A simulated 2s compile wall must not move the burst-size EMA —
    the fresh bit gates the update."""
    sch = SlotScheduler.__new__(SlotScheduler)  # unit: no engine/thread
    sch._step_ms_ema = None
    sch._note_step_time(2000.0, 1, True)       # fresh compile: ignored
    assert sch._step_ms_ema is None
    sch._note_step_time(10.0, 1, False)
    assert sch._step_ms_ema == pytest.approx(10.0)
    sch._note_step_time(2000.0, 4, True)       # warm EMA survives too
    assert sch._step_ms_ema == pytest.approx(10.0)
    sch._note_step_time(20.0, 4, False)        # per-step: 5ms folds in
    assert sch._step_ms_ema == pytest.approx(0.8 * 10.0 + 0.2 * 5.0)


# -- parked wakeups (satellite) ---------------------------------------------

def test_parked_wakeups_bounded_and_deadline_accurate():
    """An idle scheduler must not spin its old fixed-0.1s poll (~12
    wakeups in 1.2s); the deadline-derived timeout caps at 0.5s.  A
    queued deadline still expires promptly while parked."""
    sched = SlotScheduler(make_engine(2), prefill_chunk=4, decode_burst=4)
    try:
        time.sleep(0.1)        # let the loop settle into its parked wait
        sched._park_wakeups = 0
        time.sleep(1.25)
        assert sched._park_wakeups <= 5, sched._park_wakeups
        # deadline accuracy: a queued ticket behind a paused scheduler
        # wakes the parked wait at its own deadline, not 0.5s late
        with sched.exclusive():
            t = sched.submit(P1, 5, deadline=time.monotonic() + 0.3)
            t0 = time.monotonic()
            while t.finish is None and time.monotonic() - t0 < 2.0:
                time.sleep(0.01)
            assert t.finish == "timeout"
            assert time.monotonic() - t0 < 0.6
    finally:
        sched.close()
