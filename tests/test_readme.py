"""Keep README claims from rotting (VERDICT r03 Weak #6 / Next #10).

The README's test count is asserted against the ACTUAL collected session,
so it can never silently drift again: when the suite grows, this test
fails with the exact number to paste.  It only runs when the whole suite
was collected (a -k / single-file run would see a partial count).
"""

from __future__ import annotations

import os
import re

import pytest

from fixtures import REPO


def _full_suite_run(request) -> bool:
    """True when the whole tests/ tree was collected with no selection —
    the only situation where len(session.items) is the real suite size."""
    opt = request.config.option
    if getattr(opt, "keyword", "") or getattr(opt, "markexpr", ""):
        return False
    if getattr(opt, "lf", False) or getattr(opt, "last_failed", False) \
            or getattr(opt, "deselect", None) or getattr(opt, "ignore", None) \
            or getattr(opt, "ignore_glob", None):
        return False
    targets = [a for a in request.config.invocation_params.args
               if not a.startswith("-")]
    return all(os.path.abspath(t).rstrip("/") in (REPO, os.path.join(REPO, "tests"))
               for t in targets)


def test_readme_test_count_matches_suite(request):
    if not _full_suite_run(request):
        pytest.skip("partial run (-k/-m or a subset path): count not judgeable")
    readme = open(os.path.join(REPO, "README.md")).read()
    m = re.search(r"`tests/` \| (\d+) tests", readme)
    assert m, "README no longer states the test count in the layout table"
    stated = int(m.group(1))
    actual = len(request.session.items)
    assert stated == actual, (
        f"README says {stated} tests but the suite collects {actual} — "
        f"update README.md's layout table")
