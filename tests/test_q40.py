"""Packed-Q40 on-device path: format, matmul impls, model + TP equivalence.

Mirrors the reference's kernel test strategy (funcs-test.cpp:18-60:
quantized matmul vs F32 matmul within tolerance on random data) plus the
N-shard ≡ 1-shard invariance pattern (commands-test.cpp:30-69)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu import quants
from dllama_tpu.ops import q40


def _rand(shape, seed=0, scale=0.1):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(np.float32)


class TestFormat:
    def test_quantize_matches_reference_codec(self):
        """q40.quantize must produce the exact same values as the byte
        codec (quants.quantize_q40) — same clamp/floor/offset semantics."""
        w = _rand((64, 48))
        qt = q40.quantize(w)
        via_qt = np.asarray(q40.dequantize(qt))
        # reference codec path: quantize each *input-dim column* — blocks run
        # along axis 0 (input) in the runtime layout, so quantize the
        # transposed row-major view as the converter does per weight row
        via_codec = np.stack([
            quants.dequantize_q40(quants.quantize_q40(w[:, j]), 64)
            for j in range(48)], axis=1)
        np.testing.assert_allclose(via_qt, via_codec, rtol=0, atol=0)

    def test_from_q40_bytes_roundtrip(self):
        """File bytes for a (d_out, n_in) weight → QTensor ≡ dequantized."""
        d_out, n_in = 24, 96
        w = _rand((d_out, n_in), seed=3)
        raw = np.frombuffer(quants.quantize_q40(w), np.uint8)
        qt = q40.from_q40_bytes(raw, d_out, n_in)
        assert qt.shape == (n_in, d_out)
        expect = quants.dequantize_q40(raw, d_out * n_in).reshape(d_out, n_in).T
        np.testing.assert_allclose(np.asarray(q40.dequantize(qt)), expect,
                                   rtol=0, atol=0)

    def test_stacked_leading_dims(self):
        w = _rand((3, 64, 32), seed=1)
        qt = q40.quantize(w)
        assert qt.shape == (3, 64, 32)
        assert qt.qpacked.shape == (3, 32, 32)
        assert qt.scales.shape == (3, 2, 32)
        # per-layer slice == slice-then-quantize
        one = q40.quantize(w[1])
        np.testing.assert_array_equal(np.asarray(qt.qpacked[1]), np.asarray(one.qpacked))


class TestMatmul:
    def _setup(self, t=2, n=128, d=192, seed=0):
        w = _rand((n, d), seed)
        x = _rand((t, n), seed + 1, scale=1.0)
        qt = q40.quantize(w)
        ref = x @ np.asarray(q40.dequantize(qt))
        return x, qt, ref

    def test_xla_impl(self):
        x, qt, ref = self._setup()
        out = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="xla"))
        np.testing.assert_allclose(out, ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    def test_pallas_interpret_matches_xla(self):
        """The fused kernel (interpret mode on CPU) ≡ the XLA emulation."""
        x, qt, ref = self._setup(t=1, n=2048, d=256)
        out_p = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="pallas_interpret"))
        np.testing.assert_allclose(out_p, ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    @pytest.mark.parametrize("variant", ["classic", "fma", "folded", "exact"])
    def test_kernel_variants_match_xla(self, variant):
        """All dequant variants (see _q40_kernel) compute the same
        matmul within their documented rounding bounds, flat and stacked."""
        x, qt, ref = self._setup(t=1, n=1024, d=256)
        tol = 2e-2 * np.abs(ref).max()
        out = np.asarray(q40._pallas_matmul(
            jnp.asarray(x), qt.qpacked, qt.scales, interpret=True, variant=variant))
        np.testing.assert_allclose(out, ref, rtol=0, atol=tol)
        w3 = _rand((2, 1024, 256), seed=6)
        qt3 = q40.quantize(w3)
        x3 = _rand((1, 1024), seed=7, scale=1.0)
        for l in range(2):
            out = np.asarray(q40._pallas_matmul_stacked(
                jnp.asarray(x3), qt3.qpacked, qt3.scales, jnp.int32(l),
                interpret=True, variant=variant))
            ref3 = x3 @ np.asarray(q40.dequantize(qt3))[l]
            np.testing.assert_allclose(out, ref3, rtol=0,
                                       atol=2e-2 * np.abs(ref3).max())

    @pytest.mark.parametrize("variant", ["classic", "fma", "folded", "exact"])
    def test_kernel_multirow_prefill_chunk(self, variant):
        """Prefill-sized inputs (t=8 rows, under PALLAS_MAX_ROWS) through
        every dequant variant — the multi-row path the auto dispatch uses
        for short prefills."""
        x, qt, ref = self._setup(t=8, n=2048, d=256)
        out = np.asarray(q40._pallas_matmul(
            jnp.asarray(x), qt.qpacked, qt.scales, interpret=True, variant=variant))
        np.testing.assert_allclose(out, ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    def test_pallas_interpret_ragged_d(self):
        """Output dim not divisible by the tile: ragged last tile masked."""
        x, qt, ref = self._setup(t=1, n=1024, d=1024 + 384)
        out_p = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="pallas_interpret"))
        assert np.all(np.isfinite(out_p))
        np.testing.assert_allclose(out_p, ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    def test_batched_x(self):
        x, qt, ref = self._setup(t=1)
        x3 = np.broadcast_to(x, (2, 1, 128)).copy()
        out = np.asarray(q40.matmul(jnp.asarray(x3), qt, impl="xla"))
        assert out.shape == (2, 1, 192)
        np.testing.assert_allclose(out[0], ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    def test_mm_dense_passthrough(self):
        x = jnp.asarray(_rand((2, 8)))
        w = jnp.asarray(_rand((8, 4), seed=2))
        np.testing.assert_allclose(np.asarray(q40.mm(x, w)), np.asarray(x @ w),
                                   rtol=1e-6, atol=1e-6)

    def test_split_d_unfuse(self):
        """split_d (the tp>1 unfuse of wqkv/w13) ≡ quantizing the pieces."""
        w = _rand((2, 64, 96), seed=5)
        qt = q40.quantize(w)
        a, b = q40.split_d(qt, [64, 32])
        np.testing.assert_array_equal(
            np.asarray(q40.dequantize(a)), np.asarray(q40.dequantize(qt))[..., :64])
        np.testing.assert_array_equal(
            np.asarray(q40.dequantize(b)), np.asarray(q40.dequantize(qt))[..., 64:])
        assert a.logical_nd == (64, 64) and b.logical_nd == (64, 32)


class TestShardMap:
    """The fused kernel per-shard under shard_map (VERDICT r01 #2): the
    tp>1 production path must be the pallas kernel, not the XLA emulation.
    Interpret mode stands in for Mosaic on the CPU test mesh."""

    def _mesh(self, tp):
        from dllama_tpu.parallel.mesh import make_mesh
        if len(jax.devices()) < tp:
            pytest.skip(f"needs {tp} devices")
        return make_mesh(tp=tp, devices=jax.devices()[:tp])

    def test_row_sharded_matmul(self):
        from dllama_tpu.parallel.mesh import active_mesh
        w = _rand((512, 256), seed=7)
        x = _rand((2, 512), seed=8, scale=1.0)
        qt = q40.quantize(w)
        ref = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="xla"))
        mesh = self._mesh(8)
        with active_mesh(mesh):
            out = np.asarray(q40.matmul(jnp.asarray(x), qt,
                                        impl="pallas_interpret", kind="row"))
        np.testing.assert_allclose(out, ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    def test_col_sharded_matmul_psums_partials(self):
        from dllama_tpu.parallel.mesh import active_mesh
        w = _rand((512, 192), seed=9)
        x = _rand((2, 512), seed=10, scale=1.0)
        qt = q40.quantize(w)
        ref = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="xla"))
        mesh = self._mesh(8)
        with active_mesh(mesh):
            out = np.asarray(q40.matmul(jnp.asarray(x), qt,
                                        impl="pallas_interpret", kind="col"))
        np.testing.assert_allclose(out, ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    def test_unshardable_falls_back_to_xla(self):
        """A weight whose blocks don't divide the mesh must still compute
        correctly (per-tensor XLA fallback, not an error)."""
        from dllama_tpu.parallel.mesh import active_mesh
        w = _rand((64, 48), seed=11)          # 2 blocks: not col-shardable over 8
        x = _rand((1, 64), seed=12, scale=1.0)
        qt = q40.quantize(w)
        ref = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="xla"))
        with active_mesh(self._mesh(8)):
            out = np.asarray(q40.matmul(jnp.asarray(x), qt,
                                        impl="pallas_interpret", kind="col"))
        np.testing.assert_allclose(out, ref, rtol=0, atol=2e-2 * np.abs(ref).max())

    def test_sp_mesh_keeps_fused_pallas_path(self):
        """On an sp>1, tp=1 mesh the fused wqkv/w13 stay fused and run the
        pallas kernel replicated under shard_map (no XLA downgrade)."""
        from dllama_tpu.models.config import tiny_config
        from dllama_tpu.models.params import init_params, quantize_matmuls
        from dllama_tpu.parallel.mesh import make_mesh
        from dllama_tpu.runtime.engine import Engine

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=64,
                          ).with_(quant_impl="pallas_interpret")
        params = quantize_matmuls(init_params(cfg, seed=3), cfg)
        e1 = Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
        esp = Engine(cfg, params, mesh=make_mesh(tp=1, sp=2, devices=jax.devices()[:2]))
        assert "wqkv" in esp.params  # fused layout kept on a tp=1 mesh
        l1, _ = e1.prefill([5, 9, 2])
        lsp, _ = esp.prefill([5, 9, 2])
        np.testing.assert_allclose(l1, lsp, atol=1e-3 + 1e-3 * np.abs(l1).max(), rtol=0)

    def test_tp8_engine_pallas_matches_tp1(self):
        """End-to-end: a tp=8 engine on the pallas(-interpret) path produces
        the same logits and greedy tokens as tp=1 — the VERDICT r01 done-
        criterion for the fused kernel under tensor parallelism."""
        from dllama_tpu.models.config import tiny_config
        from dllama_tpu.models.params import init_params, quantize_matmuls
        from dllama_tpu.parallel.mesh import make_mesh
        from dllama_tpu.runtime.engine import Engine
        from dllama_tpu.sampling import Sampler

        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        # shapes chosen to divide an 8-way mesh at Q40 block granularity
        cfg = tiny_config(dim=256, hidden_dim=256, n_layers=2, n_heads=8,
                          n_kv_heads=8, vocab_size=128, seq_len=64,
                          ).with_(quant_impl="pallas_interpret")
        params = quantize_matmuls(init_params(cfg, seed=4), cfg)
        prompt = [3, 17, 29, 5]

        e1 = Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
        e8 = Engine(cfg, params, mesh=make_mesh(tp=8))
        assert "wq" in e8.params and "wqkv" not in e8.params  # unfused for tp
        l1, _ = e1.prefill(prompt)
        l8, _ = e8.prefill(prompt)
        # under the default classic variant the per-weight rounding is
        # identical across tp configs, so the bound stays tight; a looser
        # bound is only justified if the default becomes folded/exact
        np.testing.assert_allclose(l1, l8, atol=1e-3 + 1e-3 * np.abs(l1).max(), rtol=0)

        def greedy(engine):
            s = Sampler(cfg.vocab_size, 0.0, 0.9, 1)
            return [t for t, _ in engine.generate(prompt, 16, s)]

        t1 = greedy(Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1])))
        t8 = greedy(Engine(cfg, params, mesh=make_mesh(tp=8)))
        assert t1 == t8


class TestTileRules:
    def test_width_aware_override_applies(self, monkeypatch):
        """DLLAMA_Q40_TILES_JSON routes wide-output shapes to bigger td
        (docs/PERF.md lever #1) without touching narrow shapes; illegal
        rules (tn<256 or non-dividing tn) are skipped."""
        monkeypatch.setenv("DLLAMA_Q40_TILES_JSON", "[[8192, 512, 2048]]")
        assert q40._tiles(4096, 22016) == (512, 2048)   # w13: rule hits
        assert q40._tiles(4096, 4096) == (1024, 1024)   # wo: below d_min
        monkeypatch.setenv("DLLAMA_Q40_TILES_JSON", "[[0, 128, 2048]]")
        assert q40._tiles(4096, 22016) == (1024, 1024)  # tn<256 → ignored
        monkeypatch.setenv("DLLAMA_Q40_TILES_JSON", "[[0, 768, 2048]]")
        assert q40._tiles(4096, 22016) == (1024, 1024)  # 4096%768 → ignored
        monkeypatch.setenv("DLLAMA_Q40_TILES_JSON", "[[0, 512, 100]]")
        assert q40._tiles(4096, 22016) == (1024, 1024)  # td%128 → ignored
        monkeypatch.delenv("DLLAMA_Q40_TILES_JSON")
        assert q40._tiles(4096, 22016) == (1024, 1024)  # default unchanged

    def test_kernel_correct_at_rule_tiles(self):
        """Numerics hold at the hypothesis tile class (512, 2048)."""
        rng = np.random.RandomState(0)
        w = (rng.randn(1024, 2048) * 0.1).astype(np.float32)
        qt = q40.quantize(w)
        x = jnp.asarray(rng.randn(1, 1024).astype(np.float32), jnp.bfloat16)
        out = np.asarray(q40._pallas_matmul(x, qt.qpacked, qt.scales,
                                            interpret=True, tiles=(512, 2048)))
        ref = np.asarray(x @ q40.dequantize(qt, jnp.bfloat16))
        np.testing.assert_allclose(out, ref, rtol=0, atol=1e-2 * np.abs(ref).max())


class TestScaleValidation:
    def test_inf_scale_in_file_bytes_rejected(self):
        """A converter-overflowed or corrupt scale (f16 inf/NaN) must fail
        at pack time: the in-kernel f16-bit decode has no exp==0x1F branch
        and would map it to a large finite weight silently (ADVICE r03)."""
        d, n = 2, 64
        nb = n // 32
        raw = np.zeros((d, nb, quants.Q40_BLOCK_BYTES), np.uint8)
        raw[..., :2] = np.frombuffer(np.float16(0.01).tobytes(), np.uint8)
        ok = q40.pack_file_groups([[(raw.reshape(d, -1), d, n)]], stacked=False)
        assert ok.logical_nd == (n, d)
        bad = raw.copy()
        bad[0, 0, :2] = np.frombuffer(np.float16(np.inf).tobytes(), np.uint8)
        with pytest.raises(ValueError, match="inf/NaN"):
            q40.pack_file_groups([[(bad.reshape(d, -1), d, n)]], stacked=False)


class TestProbe:
    def test_probe_failure_degrades_to_xla(self, monkeypatch):
        """A Mosaic failure at a production tile class must downgrade that
        class to the XLA path through the dispatch ledger — labeled
        degrade counter + process degraded flag, not a scrollback print
        (VERDICT r02 Weak #5; obs/dispatch.py)."""
        from dllama_tpu.obs import dispatch as obs_dispatch
        from dllama_tpu.obs import metrics as obs_metrics

        def boom(*a, **k):
            raise RuntimeError("synthetic Mosaic failure")

        monkeypatch.setattr(q40, "_pallas_matmul", boom)
        obs_dispatch.reset()
        try:
            before = obs_metrics.Q40_DEGRADE.get("probe_failed")
            assert q40._pallas_ok(512, 256, 1) is False  # unique key → fresh probe
            assert obs_metrics.Q40_DEGRADE.get("probe_failed") == before + 1
            assert obs_dispatch.degraded() is True
            assert obs_dispatch.reasons().get("q40:probe_failed", 0) >= 1
        finally:
            q40._pallas_ok.cache_clear()  # drop the poisoned verdict
            obs_dispatch.reset()

    def test_probe_catches_nibble_swap(self, monkeypatch):
        """VERDICT r03 Weak #2: the probe fixture is random, so a kernel
        with a nibble-order bug must FAIL the probe (with the previous
        all-ones fixture every block quantized identically and a swapped
        nibble order produced bit-identical results — the probe was blind
        to exactly the class of bug it exists to catch)."""
        def swapped_kernel(x, qp, s, **kw):
            # impostor kernel: correct math, nibble order swapped
            bad = ((qp >> 4) | ((qp & 0xF) << 4)).astype(jnp.uint8)
            n = qp.shape[-2] * 2
            qt = q40.QTensor(bad, s, (n, qp.shape[-1]))
            return x @ q40.dequantize(qt, jnp.bfloat16)

        monkeypatch.setattr(q40, "_pallas_matmul", swapped_kernel)
        try:
            assert q40._pallas_ok(128, 256, 1) is False  # unique key → fresh probe
        finally:
            q40._pallas_ok.cache_clear()

        # sanity: the same harness with the honest emulation passes, so the
        # failure above is the swap being detected, not harness breakage
        honest = lambda x, qp, s, **kw: x @ q40.dequantize(
            q40.QTensor(qp, s, (qp.shape[-2] * 2, qp.shape[-1])), jnp.bfloat16)
        monkeypatch.setattr(q40, "_pallas_matmul", honest)
        try:
            assert q40._pallas_ok(128, 256, 1) is True
        finally:
            q40._pallas_ok.cache_clear()

    def test_probe_passes_at_production_tiles(self):
        """The probe compiles/runs the real 7B tile class (interpret on CPU
        backends is not exercised here — _pallas_ok runs the compiled
        kernel; on CPU jax lowers pallas_call through the interpreter only
        when asked, so restrict to a small class that lowers everywhere)."""
        assert q40._pallas_ok(64, 128, 1) in (True, False)  # must not raise


class TestModel:
    def test_quantized_forward_close_to_dense(self):
        """Tiny llama with quantized matmuls ≡ same model with the
        dequantized weights (not the f32 originals — quantization error is
        the codec's, the matmul must add only matmul-precision error)."""
        from dllama_tpu.models.config import tiny_config
        from dllama_tpu.models.params import init_params, quantize_matmuls
        from dllama_tpu.models.transformer import forward, init_kv_cache

        cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=32)
        params = init_params(cfg, seed=0)
        qparams = quantize_matmuls(params, cfg)
        dparams = {k: (q40.dequantize(v, jnp.float32) if isinstance(v, q40.QTensor) else v)
                   for k, v in qparams.items()}

        tokens = jnp.asarray([[1, 5, 9, 2]], jnp.int32)
        cfg_q = cfg.with_(quant_impl="xla")
        lq, _ = forward(qparams, cfg_q, tokens, init_kv_cache(cfg, 1), jnp.int32(0))
        ld, _ = forward(dparams, cfg, tokens, init_kv_cache(cfg, 1), jnp.int32(0))
        np.testing.assert_allclose(np.asarray(lq), np.asarray(ld),
                                   rtol=0, atol=5e-2 + 2e-2 * np.abs(np.asarray(ld)).max())

    def test_quantized_forward_padded_hidden(self):
        """Hidden dim ≥ TILE_N but not a multiple (TinyLlama's 5632 shape
        class): the w2 input axis gets pack-time padding rows whose zero
        scales must contribute nothing — checked through a full forward,
        both matmul implementations."""
        from dllama_tpu.models.config import tiny_config
        from dllama_tpu.models.params import init_params, quantize_matmuls
        from dllama_tpu.models.transformer import forward, init_kv_cache

        cfg = tiny_config(dim=64, hidden_dim=q40.TILE_N + 384, n_layers=2,
                          n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=32)
        assert q40.padded_n(cfg.hidden_dim) != cfg.hidden_dim  # padding active
        params = init_params(cfg, seed=2)
        qparams = quantize_matmuls(params, cfg)
        dparams = {k: (q40.dequantize(v, jnp.float32) if isinstance(v, q40.QTensor) else v)
                   for k, v in qparams.items()}
        tokens = jnp.asarray([[1, 5, 9, 2]], jnp.int32)
        ld, _ = forward(dparams, cfg, tokens, init_kv_cache(cfg, 1), jnp.int32(0))
        tol = 5e-2 + 2e-2 * np.abs(np.asarray(ld)).max()
        for impl in ("xla", "pallas_interpret"):
            lq, _ = forward(qparams, cfg.with_(quant_impl=impl), tokens,
                            init_kv_cache(cfg, 1), jnp.int32(0))
            np.testing.assert_allclose(np.asarray(lq), np.asarray(ld),
                                       rtol=0, atol=tol)

    def test_tp_sharded_quantized_equivalence(self):
        """N-shard ≡ 1-shard (commands-test.cpp pattern) with packed Q40
        weights: the sharded run uses the partitionable XLA impl."""
        from dllama_tpu.models.config import tiny_config
        from dllama_tpu.models.params import init_params, quantize_matmuls
        from dllama_tpu.models.transformer import forward, init_kv_cache
        from dllama_tpu.parallel import sharding as sh
        from dllama_tpu.parallel.mesh import make_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs multi-device CPU mesh")
        cfg = tiny_config(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=32).with_(quant_impl="xla")
        params = quantize_matmuls(init_params(cfg, seed=0), cfg)
        tokens = jnp.asarray([[3, 7, 11]], jnp.int32)

        ref, _ = forward(params, cfg, tokens, init_kv_cache(cfg, 1), jnp.int32(0))

        mesh = make_mesh(tp=2, devices=jax.devices()[:2])
        placed = sh.place_params(params, cfg, mesh)
        cache = jax.device_put(init_kv_cache(cfg, 1), sh.kv_cache_sharding(mesh))
        out, _ = jax.jit(lambda p, c, t: forward(p, cfg, t, c, jnp.int32(0)))(
            placed, cache, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=0, atol=1e-3 + 1e-3 * np.abs(np.asarray(ref)).max())


class TestEngineIntegration:
    def test_mfile_quantized_load_and_generate(self, tmp_path):
        """End-to-end: Q40 .m file loaded packed, engine generates the same
        tokens as the dequantized load at temperature 0."""
        from tests.fixtures import write_tiny_model
        from dllama_tpu.io import mfile
        from dllama_tpu.models.config import ModelConfig
        from dllama_tpu.models.params import load_params
        from dllama_tpu.runtime.engine import Engine
        from dllama_tpu.sampling import Sampler

        path = tmp_path / "tiny-q40.m"
        write_tiny_model(str(path), ftype=quants.Q40, vocab_size=64, seq_len=64)
        mf = mfile.MFile(str(path))
        cfg = ModelConfig.from_spec(mf.spec, dtype=jnp.float32)

        outs = []
        for keep in (True, False):
            cfg_l, params = load_params(mf, cfg, keep_quantized=keep)
            if keep:
                # a Q40 load keeps packed fused projections, no dense f32
                assert isinstance(params["wqkv"], q40.QTensor)
                assert isinstance(params["w13"], q40.QTensor)
                assert params["wqkv"].logical_nd == (64, 64 + 2 * 32)
            eng = Engine(cfg_l, params)
            toks = [t for t, _ in eng.generate(
                [1, 5, 9], steps=10, sampler=Sampler(cfg.vocab_size, 0.0, 0.9, 0))]
            outs.append(toks)
        # keep=False dequantizes the same Q40 bytes → same values → greedy
        # decode must match exactly
        assert outs[0] == outs[1]


def test_f16_bits_to_f32_exhaustive():
    """The in-kernel integer widening must agree with IEEE f16→f32 for
    every finite bit pattern (the codec never stores inf/nan scales) —
    this is what keeps dequantization bit-identical to the file format
    with uint16-stored scales."""
    bits = np.arange(1 << 16, dtype=np.uint16)
    finite = np.isfinite(bits.view(np.float16))
    got = np.asarray(q40._f16_bits_to_f32(jnp.asarray(bits[finite])))
    exp = bits[finite].view(np.float16).astype(np.float32)
    np.testing.assert_array_equal(got, exp)


def test_extreme_scales_roundtrip_through_kernel():
    """Scales at the f16 extremes — subnormal deltas (tiny weights) and
    near-max deltas (|w| up to ~524k pre-clamp) — must dequantize exactly
    through the uint16 bit path in both the XLA and interpret-kernel
    implementations."""
    rng = np.random.RandomState(0)
    w = rng.randn(64, 128).astype(np.float32)
    w[:32] *= 1e-7          # subnormal f16 deltas (amax/8 < 6.1e-5)
    w[32:] *= 5e4           # deltas near the f16 normal range top
    qt = q40.quantize(w)
    assert qt.scales.dtype == jnp.uint16
    dq = np.asarray(q40.dequantize(qt))
    # independent reconstruction from the stored f16 bits
    sc = np.asarray(qt.scales).view(np.float16).astype(np.float32)
    v = np.asarray(qt.qpacked).astype(np.int32)
    lo = (v & 0xF) - 8
    hi = (v >> 4) - 8
    dense = np.concatenate(
        [lo.reshape(2, 16, 128), hi.reshape(2, 16, 128)], axis=1
    ).reshape(64, 128) * np.repeat(sc, 32, axis=0)
    np.testing.assert_array_equal(dq, dense.astype(np.float32))

    x = _rand((1, 64), seed=1, scale=1.0)
    ref = x @ dq
    out = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="pallas_interpret"))
    np.testing.assert_allclose(out, ref, rtol=0,
                               atol=2e-2 * np.abs(ref).max() + 1e-12)


def test_blocked_layout_probe_matches_stacked():
    """The tile-contiguous layout probe (tools/sweep_q40.py
    blocked_stacked_matmul) computes the SAME matmul as the production
    row-major kernel — pinned in interpret mode so a hardware bandwidth
    win measured by the probe is attributable to layout alone.  Ragged d
    exercises the pad-to-td path (pad scales are zero → pad outputs 0)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "sweep_q40", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "sweep_q40.py"))
    sweep = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sweep)

    tn, td = 512, 128
    L, n, d = 2, 1024, 320  # d ragged: 320 = 2*128 + 64
    w = _rand((L, n, d), seed=11)
    qt = q40.quantize(w)
    x = _rand((1, n), seed=12, scale=1.0)
    qb, sb, dp = sweep.block_pack(np.asarray(qt.qpacked),
                                  np.asarray(qt.scales), tn, td)
    assert dp == 384 and qb.shape == (L, n // tn, dp // td, tn // 2, td)
    for layer in range(L):
        ref = np.asarray(q40._pallas_matmul_stacked(
            jnp.asarray(x), qt.qpacked, qt.scales, jnp.int32(layer),
            interpret=True, variant="classic"))
        out = np.asarray(sweep.blocked_stacked_matmul(
            jnp.asarray(x), jnp.asarray(qb), jnp.asarray(sb),
            jnp.int32(layer), tn, td, dp, interpret=True))
        np.testing.assert_allclose(out[:, :d], ref, rtol=0, atol=1e-5)
        assert np.all(out[:, d:] == 0.0)


def test_blocked_layout_engine_matches_default(monkeypatch):
    """DLLAMA_Q40_LAYOUT=blocked end-to-end: engine decode over blocked
    storage ≡ the row-major default, greedy token for token (CPU mesh
    dispatches through unblock/dequantize; kernel-level parity is pinned
    in interpret mode by test_blocked_layout_probe_matches_stacked)."""
    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params, quantize_matmuls
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine

    cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=128, seq_len=64)
    params = quantize_matmuls(init_params(cfg, seed=3), cfg)
    e1 = Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    s1 = [t for t, _ in e1.generate_stream([5, 9, 2], 12, temperature=0.0)]

    monkeypatch.setenv("DLLAMA_Q40_LAYOUT", "blocked")
    eb = Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    blocked_leaves = {k: v for k, v in eb.params.items()
                      if isinstance(v, q40.BlockedQTensor)}
    assert blocked_leaves, "blocked layout must convert the layer-stacked weights"
    # blocked roundtrip is exact: unblock(to_blocked(qt)) == qt
    for k, v in blocked_leaves.items():
        np.testing.assert_array_equal(
            np.asarray(q40.unblock(v).qpacked),
            np.asarray(e1.params[k].qpacked))
    sb = [t for t, _ in eb.generate_stream([5, 9, 2], 12, temperature=0.0)]
    assert s1 == sb


def test_blocked_layout_interpret_matmul_through_view():
    """QLayerView over a BlockedQTensor dispatches to the blocked kernel
    (interpret) and matches the row-major stacked kernel exactly."""
    w = _rand((3, 1024, 320), seed=21)
    qt = q40.quantize(w)
    bqt = q40.to_blocked(qt, 512, 128)
    x = _rand((1, 1024), seed=22, scale=1.0)
    for layer in range(3):
        ref = np.asarray(q40.matmul(
            jnp.asarray(x), q40.QLayerView(qt, jnp.int32(layer)),
            impl="pallas_interpret"))
        out = np.asarray(q40.matmul(
            jnp.asarray(x), q40.QLayerView(bqt, jnp.int32(layer)),
            impl="pallas_interpret"))
        np.testing.assert_allclose(out, ref, rtol=0, atol=1e-5)


def test_blocked_layout_2d_wcls_roundtrip_and_matmul():
    """2-D weights (wcls — the widest d) block with an implicit L=1 and
    squeeze back out on unblock; the blocked interpret matmul matches the
    row-major kernel on a non-multiple d."""
    w = _rand((1024, 320), seed=31)
    qt = q40.quantize(w)
    assert qt.qpacked.ndim == 2
    bqt = q40.to_blocked(qt, 512, 128)
    assert bqt.lead_2d and bqt.shape == (1024, 320)
    un = q40.unblock(bqt)
    np.testing.assert_array_equal(np.asarray(un.qpacked), np.asarray(qt.qpacked))
    np.testing.assert_array_equal(np.asarray(un.scales), np.asarray(qt.scales))
    x = _rand((2, 1024), seed=32, scale=1.0)
    ref = np.asarray(q40.matmul(jnp.asarray(x), qt, impl="pallas_interpret"))
    out = np.asarray(q40.matmul(jnp.asarray(x), bqt, impl="pallas_interpret"))
    np.testing.assert_allclose(out, ref, rtol=0, atol=1e-5)
    # XLA fallback path (what a CPU mesh or illegal tiles dispatch to)
    outx = np.asarray(q40.matmul(jnp.asarray(x), bqt, impl="xla"))
    np.testing.assert_allclose(outx, ref, rtol=0, atol=2e-2 * np.abs(ref).max())
