"""KV memory-tiering tests: optimistic reservation, host spill, int8 pages.

The tiering contracts (docs/PERF.md "KV memory tiering"), each pinned
here on CPU with the tiny model:

* **host pool semantics** — the pinned host-RAM page pool stores and
  returns spilled page payloads byte-exact, refuses duplicate keys and
  over-capacity puts (a refused spill leaves the victim resident — the
  scheduler depends on that), and a zero-capacity pool is disabled;
* **victim ranking** — idle-longest slots spill first, slot index as
  the deterministic tiebreak;
* **spill / page-in byte parity** — an optimistic scheduler on a pool
  far smaller than the workload's full-reservation demand serves every
  request byte-identical to its uncontended solo run, with the
  overlapped dispatch pipeline both on and off, and ends with the host
  pool empty and every page back on the free list;
* **int8 KV pages** — the per-page-scale quantization round-trips
  within its absmax/127 step; a ``--kv-quant int8`` scheduler's greedy
  decode tracks the dense oracle and the dispatch ledger carries the
  ``kv_int8`` codec label;
* **snapshot codec** — DLREQ01 hand-off records from an int8 pool
  import byte-exact into another int8 replica and are cleanly refused
  by a dense one (the codec is part of the hand-off fingerprint);
* **exhaustion fallback** — with the host pool disabled, page pressure
  degrades to preempt/park (honest queueing), never to wrong bytes.
"""

import threading
import time

import jax
import numpy as np
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.obs import dispatch as obs_dispatch
from dllama_tpu.obs import metrics as obs_metrics
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime import kvtier
from dllama_tpu.runtime import snapshot as snapfmt
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.runtime.faults import FAULTS, injected
from dllama_tpu.runtime.kvtier import HostPagePool, rank_victims
from dllama_tpu.runtime.scheduler import SlotScheduler

pytestmark = pytest.mark.kvtier

CFG = tiny_config(seq_len=64)
PAGE = 4
P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]
P3 = [2, 4, 6]
PROMPTS = (P1, P2, P3)
MAX_NEW = 24


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def make_paged_engine(batch=2, kv_dtype=None, kv_pages=None):
    pages_per_slot = -(-CFG.seq_len // PAGE)
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch,
                  kv_pages=kv_pages or batch * pages_per_slot + 1,
                  kv_page_size=PAGE, kv_dtype=kv_dtype)


@pytest.fixture(scope="module")
def solo_refs():
    """Greedy solo completions per prompt — the parity oracle."""
    eng = Engine(CFG, init_params(CFG, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=1)
    refs = {}
    for p in PROMPTS:
        eng.reset()
        toks = [t for t, _ in eng.generate_stream(
            p, len(p) + MAX_NEW, temperature=0.0, chunk=5)]
        refs[tuple(p)] = toks[len(p):]
    return refs


def run_sched(solo_refs, kv_dtype=None, check_parity=True, **kw):
    """Three greedy requests through a 2-slot scheduler; returns
    (token lists, final occupancy)."""
    eng = make_paged_engine(kv_dtype=kv_dtype,
                            kv_pages=kw.pop("kv_pages", None))
    sched = SlotScheduler(eng, prefill_chunk=8, decode_burst=4, **kw)
    try:
        tickets = [sched.submit(list(p), max_new=MAX_NEW, temperature=0.0)
                   for p in PROMPTS]
        outs = [list(t.tokens()) for t in tickets]
        sched.pool.check()
        occ = sched.occupancy()
    finally:
        sched.close(timeout=60)
    if check_parity:
        for p, o in zip(PROMPTS, outs):
            r = solo_refs[tuple(p)]
            n = min(len(o), len(r))
            assert n >= MAX_NEW - 8 and o[:n] == r[:n], \
                f"scheduler drifted from solo oracle on {p}: {o} vs {r}"
    return outs, occ


# --- unit: host pool + victim ranking -------------------------------------

def test_host_pool_roundtrip_and_refusals():
    arrays = {"pages.k": np.arange(48, dtype=np.float32).reshape(2, 24),
              "pages.v": np.ones((2, 24), np.float32)}
    nbytes = kvtier.arrays_nbytes(arrays)
    pool = HostPagePool(capacity_bytes=2 * nbytes)
    assert pool.would_fit(nbytes)
    assert pool.put(("k1", "r1"), arrays, {"pos": 9})
    assert ("k1", "r1") in pool and len(pool) == 1
    assert pool.bytes_used == nbytes

    got, meta = pool.get(("k1", "r1"))
    assert meta["pos"] == 9
    for name, a in arrays.items():
        np.testing.assert_array_equal(got[name], a)

    # duplicate key refused — a double spill of one slot is a bug, and
    # silently overwriting the first payload would lose bytes
    assert not pool.put(("k1", "r1"), arrays, {})
    # over capacity refused: the caller keeps the victim resident
    assert pool.put(("k2", "r2"), arrays, {})
    assert not pool.would_fit(nbytes)
    assert not pool.put(("k3", "r3"), arrays, {})
    assert len(pool) == 2

    popped, _ = pool.pop(("k1", "r1"))
    np.testing.assert_array_equal(popped["pages.k"], arrays["pages.k"])
    assert ("k1", "r1") not in pool
    assert pool.pop(("k1", "r1")) is None
    pool.drop(("k2", "r2"))
    assert pool.bytes_used == 0 and len(pool) == 0

    # capacity <= 0 disables the pool entirely
    off = HostPagePool(capacity_bytes=0)
    assert not off.would_fit(1)
    assert not off.put(("k", "r"), arrays, {})


def test_host_pool_bytes_gauge_tracks():
    arrays = {"x": np.zeros(128, np.int8)}
    pool = HostPagePool(capacity_bytes=4096)
    pool.put(("a", "r"), arrays, {})
    assert obs_metrics.KV_HOST_POOL_BYTES.value >= 128
    pool.clear()
    assert pool.bytes_used == 0


def test_rank_victims_orders_idle_longest():
    # (slot_idx, active_at): oldest activity first, index breaks ties
    cands = [(3, 50.0), (0, 10.0), (2, 10.0), (1, 99.0)]
    assert rank_victims(cands) == [0, 2, 3, 1]
    assert rank_victims([]) == []


# --- int8 page codec ------------------------------------------------------

def test_int8_quant_roundtrip_tolerance():
    """quantize_kv/dequant_kv round-trip within the absmax/127 step —
    per (…, position) scales, so one hot row cannot blunt its neighbors."""
    from dllama_tpu.ops.attention import dequant_kv, quantize_kv
    rng = np.random.RandomState(0)
    x = (rng.randn(2, 2, 8, 16) * np.array([0.1, 10.0])[None, :, None,
                                            None]).astype(np.float32)
    vals, scale = quantize_kv(x)
    assert vals.dtype == np.int8
    assert scale.shape == x.shape[:3] + (1,)
    back = np.asarray(dequant_kv(vals, scale), np.float32)
    step = np.abs(x).max(axis=-1, keepdims=True) / 127.0
    assert np.all(np.abs(back - x) <= step + 1e-6), \
        "dequantized KV outside one quantization step"


def test_int8_sched_parity_and_ledger(solo_refs):
    """Greedy decode through an int8 paged pool tracks the dense solo
    oracle (tolerance: a long shared prefix — quantization noise may
    legitimately flip a late token) and the dispatch ledger labels the
    paged reads with the kv_int8 codec."""
    outs, occ = run_sched(solo_refs, kv_dtype="q8", check_parity=False)
    for p, o in zip(PROMPTS, outs):
        r = solo_refs[tuple(p)]
        agree = 0
        for a, b in zip(o, r):
            if a != b:
                break
            agree += 1
        assert agree >= 6, \
            f"int8 KV diverged from dense oracle too early on {p}: " \
            f"{o} vs {r}"
    assert occ["kv_pressure"]["codec"] == "int8", occ["kv_pressure"]
    led = obs_dispatch.dispatches()
    assert any("kv_int8" in str(k) for k in led), \
        f"no kv_int8 ledger entry: {list(led)}"


# --- spill / page-in parity -----------------------------------------------

@pytest.mark.parametrize("overlap", [True, False],
                         ids=["overlap", "no-overlap"])
def test_optimistic_spill_parity(solo_refs, overlap):
    """THE tiering acceptance: a 9-usable-page pool against ~22 pages of
    full-reservation demand — requests seat on prompt-sized bindings,
    grow page-by-page, spill idle-longest victims to host RAM and page
    them back in, and every completion is byte-identical to solo."""
    spilled0 = obs_metrics.KV_PAGES_SPILLED.value
    paged0 = obs_metrics.KV_PAGES_PAGED_IN.value
    _, occ = run_sched(solo_refs, kv_reserve="optimistic",
                       spill_headroom=4, host_pool_mb=8, kv_pages=10,
                       overlap=overlap)
    assert obs_metrics.KV_PAGES_SPILLED.value - spilled0 >= 1, \
        "pool at 40% of demand must engage the spill path"
    assert obs_metrics.KV_PAGES_PAGED_IN.value - paged0 >= 1
    kvp = occ["kv_pressure"]
    assert kvp["reserve"] == "optimistic"
    assert kvp["host_pool_bytes"] == 0 and kvp["spilled_slots"] == 0, kvp
    assert occ["kv_pages_free"] == occ["kv_pages_total"], \
        f"page leak after drain: {occ}"


def test_full_reservation_unchanged(solo_refs):
    """Default mode is full reservation: no spill machinery engages even
    with a host pool configured, and parity holds."""
    spilled0 = obs_metrics.KV_PAGES_SPILLED.value
    _, occ = run_sched(solo_refs, host_pool_mb=8)
    assert obs_metrics.KV_PAGES_SPILLED.value == spilled0
    assert occ["kv_pressure"]["reserve"] == "full"


def test_exhaustion_falls_back_to_preempt(solo_refs):
    """Host pool disabled (--kv-host-pool-mb 0): growth on an exhausted
    pool cannot spill, so the grow ladder preempts the slot instead —
    over-commit degrades to honest queueing, and the parked request
    still resumes to a byte-identical finish."""
    pre0 = sum((obs_metrics.snapshot_json().get("sched_preemptions")
                or {}).values())
    _, occ = run_sched(solo_refs, kv_reserve="optimistic",
                       spill_headroom=4, host_pool_mb=0, kv_pages=10)
    pre = sum((obs_metrics.snapshot_json().get("sched_preemptions")
               or {}).values())
    assert pre > pre0, "pressure without a host pool must preempt"
    assert occ["kv_pressure"]["host_pool_bytes"] == 0
    assert occ["kv_pages_free"] == occ["kv_pages_total"], occ


# --- snapshot codec -------------------------------------------------------

def test_handoff_codec_roundtrip_int8(solo_refs):
    """A DLREQ01 record exported mid-decode from an int8 pool imports
    into another int8 replica and resumes to the same tokens an
    uninterrupted int8 run produces."""
    # uninterrupted int8 reference
    (ref_out, *_), _ = run_sched(solo_refs, kv_dtype="q8",
                                 check_parity=False)

    sa = SlotScheduler(make_paged_engine(kv_dtype="q8"), prefill_chunk=8,
                       decode_burst=4)
    sb = SlotScheduler(make_paged_engine(kv_dtype="q8"), prefill_chunk=8,
                       decode_burst=4)
    try:
        assert sa.engine.handoff_fingerprint() == \
            sb.engine.handoff_fingerprint()
        with injected("engine.device_step=delay:0.05"):
            t = sa.submit(list(P1), MAX_NEW, temperature=0.0)
            it = t.tokens()
            for _ in range(4):
                next(it)
            records = sa.handoff_export_all()
        list(it)
        assert t.finish == "handoff"
        meta, _ = snapfmt.loads_request(records[t.rid])
        replayed = [int(x) for x in meta["extra"]["completion"]]
        t2, _ = sb.import_request(records[t.rid])
        resumed = list(t2.tokens())
        assert t2.finish == "length"
        assert replayed + resumed == ref_out, \
            "int8 hand-off resume drifted from the uninterrupted run"
    finally:
        sa.close(timeout=60)
        sb.close(timeout=60)


def test_handoff_codec_mismatch_rejects(solo_refs):
    """An int8-pool record must be refused by a dense-paged importer
    (and vice versa): the codec is part of the hand-off fingerprint, so
    the reject is clean — before any state is written."""
    sa = SlotScheduler(make_paged_engine(kv_dtype="q8"), prefill_chunk=8,
                       decode_burst=4)
    sb = SlotScheduler(make_paged_engine(), prefill_chunk=8,
                       decode_burst=4)
    try:
        assert sa.engine.handoff_fingerprint() != \
            sb.engine.handoff_fingerprint(), \
            "codec must be part of replica hand-off identity"
        with injected("engine.device_step=delay:0.05"):
            t = sa.submit(list(P1), MAX_NEW, temperature=0.0)
            it = t.tokens()
            next(it)
            records = sa.handoff_export_all()
        list(it)
        with pytest.raises(snapfmt.SnapshotMismatch, match="geometry"):
            sb.import_request(records[t.rid])
    finally:
        sa.close(timeout=60)
        sb.close(timeout=60)
