"""Packed-Q40 MoE experts (VERDICT r01 #3).

The reference keeps MoE expert weights Q40 end-to-end
(transformer.cpp:299-317); round 1 dequantized every expert to dense f32 on
host, making Mixtral-8x7B unloadable.  These tests cover the packed expert
path: quantized-vs-dense numerics, the decode expert-select path, `.m`
loading without f32 materialization, and N-shard ≡ 1-shard equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu import quants
from dllama_tpu.io import mfile
from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params, load_params, quantize_matmuls
from dllama_tpu.models.transformer import forward, init_kv_cache
from dllama_tpu.ops import q40
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.sampling import Sampler


MOE_CFG = tiny_config(arch=mfile.ARCH_MIXTRAL, n_experts=4, n_active_experts=2,
                      dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=128, seq_len=64)


def _dequant_all(params):
    return {k: (q40.dequantize(v, jnp.float32) if isinstance(v, q40.QTensor) else v)
            for k, v in params.items()}


def test_quantize_matmuls_packs_experts():
    qparams = quantize_matmuls(init_params(MOE_CFG, seed=0), MOE_CFG)
    for k in ("up", "gate", "down"):
        assert isinstance(qparams[k], q40.QTensor), k
    assert qparams["up"].qpacked.shape == (2, 4, 32, 96)   # (L, E, n/2, F)
    assert qparams["down"].qpacked.shape == (2, 4, 48, 64)  # (L, E, F/2, D)
    assert isinstance(qparams["router"], jnp.ndarray)  # router stays dense


def test_quantized_moe_prefill_matches_dense_dequant():
    """Prefill (masked static expert loop) ≡ the dense einsum dispatch on
    the same dequantized values."""
    qparams = quantize_matmuls(init_params(MOE_CFG, seed=1), MOE_CFG)
    dparams = _dequant_all(qparams)
    tokens = jnp.asarray([[1, 9, 33, 7, 2]], jnp.int32)
    cfg_q = MOE_CFG.with_(quant_impl="xla")
    lq, _ = forward(qparams, cfg_q, tokens, init_kv_cache(MOE_CFG, 1), jnp.int32(0))
    ld, _ = forward(dparams, MOE_CFG, tokens, init_kv_cache(MOE_CFG, 1), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(lq), np.asarray(ld),
                               rtol=0, atol=5e-2 + 2e-2 * np.abs(np.asarray(ld)).max())


def test_quantized_moe_decode_matches_prefill():
    """The decode path (per-token expert select on packed planes) must
    agree with the prefill path (masked loop) — same model, positions fed
    one at a time vs all at once."""
    cfg = MOE_CFG.with_(quant_impl="xla")
    qparams = quantize_matmuls(init_params(cfg, seed=2), cfg)
    prompt = [3, 17, 29, 5]

    e_pre = Engine(cfg, qparams)
    l_pre, _ = e_pre.prefill(prompt)

    e_dec = Engine(cfg, qparams)
    for t in prompt[:-1]:
        e_dec.decode_one(t)
    l_dec, _ = e_dec.decode_one(prompt[-1])
    np.testing.assert_allclose(l_pre, l_dec,
                               rtol=0, atol=1e-3 + 1e-3 * np.abs(l_pre).max())


def test_mixtral_q40_mfile_end_to_end(tmp_path):
    """Q40 Mixtral .m → packed expert load (no dense f32) → generation."""
    from tests.fixtures import write_tiny_model

    path = tmp_path / "tiny-mixtral-q40.m"
    write_tiny_model(str(path), arch=mfile.ARCH_MIXTRAL, ftype=quants.Q40,
                     n_experts=4, vocab_size=64, seq_len=64)
    mf = mfile.MFile(str(path))

    cfg_q, qparams = load_params(mf, keep_quantized=True)
    for k in ("up", "gate", "down"):
        assert isinstance(qparams[k], q40.QTensor), k
    assert qparams["up"].qpacked.dtype == jnp.uint8

    cfg_d, dparams = load_params(mf, keep_quantized=False)
    eq = Engine(cfg_q.with_(quant_impl="xla"), qparams)
    ed = Engine(cfg_d, dparams)
    lq, _ = eq.prefill([1, 5, 9])
    ld, _ = ed.prefill([1, 5, 9])
    np.testing.assert_allclose(lq, ld, rtol=0, atol=5e-2 + 2e-2 * np.abs(ld).max())

    # generation runs on the packed path without error
    toks = [t for t, _ in eq.generate([1, 5, 9], steps=8,
                                      sampler=Sampler(cfg_q.vocab_size, 0.0, 0.9, 0))]
    assert len(toks) == 8


def test_ep_sharded_packed_experts_match_tp1():
    """Expert-PARALLEL packed experts (ep shards the expert axis of the
    (L, E, n/2, d) stacks in HBM — q40._sharded_matmul_ep): ep4×tp2 and
    ep2×tp2 must reproduce the 1-shard logits on both the fused interpret
    path and the XLA fallback, for prefill and decode.  This is the layout
    that lets packed Grok-1-314B fit its 16-chip plan (docs/MEMORY.md)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = tiny_config(arch=mfile.ARCH_MIXTRAL, n_experts=4, n_active_experts=2,
                      dim=256, hidden_dim=256, n_layers=2, n_heads=8,
                      n_kv_heads=8, vocab_size=128, seq_len=32,
                      ).with_(quant_impl="pallas_interpret")
    qparams = quantize_matmuls(init_params(cfg, seed=4), cfg)
    prompt = [1, 2, 3]
    e1 = Engine(cfg, qparams, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    l1, _ = e1.prefill(prompt)
    d1, _ = e1.decode_one(7)
    for impl in ("pallas_interpret", "xla"):
        for ep, tp in ((4, 2), (2, 2)):
            e = Engine(cfg.with_(quant_impl=impl), qparams,
                       mesh=make_mesh(tp=tp, ep=ep))
            le, _ = e.prefill(prompt)
            np.testing.assert_allclose(
                l1, le, rtol=0, atol=1e-3 + 1e-3 * np.abs(l1).max(),
                err_msg=f"prefill impl={impl} ep={ep} tp={tp}")
            de, _ = e.decode_one(7)
            np.testing.assert_allclose(
                d1, de, rtol=0, atol=1e-3 + 1e-3 * np.abs(d1).max(),
                err_msg=f"decode impl={impl} ep={ep} tp={tp}")


def test_moe_prefill_scan_matches_unroll(monkeypatch):
    """Past MOE_PREFILL_UNROLL_MAX experts the quantized prefill switches
    to a lax.scan with a traced expert index (VERDICT r04 Weak #3); it
    must produce the unrolled path's numbers exactly."""
    import dllama_tpu.models.transformer as tr
    cfg = tiny_config(arch=mfile.ARCH_MIXTRAL, n_experts=16,
                      n_active_experts=2, dim=64, hidden_dim=96, n_layers=1,
                      n_heads=4, n_kv_heads=2, vocab_size=128, seq_len=32,
                      ).with_(quant_impl="xla")
    qparams = quantize_matmuls(init_params(cfg, seed=5), cfg)
    tokens = jnp.asarray([[1, 9, 33, 7, 2]], jnp.int32)
    l_scan, _ = forward(qparams, cfg, tokens, init_kv_cache(cfg, 1), jnp.int32(0))
    monkeypatch.setattr(tr, "MOE_PREFILL_UNROLL_MAX", 64)  # force unroll
    l_unroll, _ = forward(qparams, cfg, tokens, init_kv_cache(cfg, 1), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(l_scan), np.asarray(l_unroll),
                               rtol=0, atol=1e-5)


def test_moe_prefill_program_size_flat_in_experts():
    """Compile-scaling guard: the traced program for a 32-expert model must
    not be materially larger than for 16 experts (the scan bounds it; the
    old unroll grew linearly and would double the equation count)."""
    import dllama_tpu.models.transformer as tr

    def n_eqns(e):
        cfg = tiny_config(arch=mfile.ARCH_MIXTRAL, n_experts=e,
                          n_active_experts=2, dim=64, hidden_dim=96,
                          n_layers=1, n_heads=4, n_kv_heads=2, vocab_size=128,
                          seq_len=32).with_(quant_impl="xla")
        qparams = quantize_matmuls(init_params(cfg, seed=5), cfg)
        tokens = jnp.asarray([[1, 9, 33, 7, 2]], jnp.int32)
        jaxpr = jax.make_jaxpr(
            lambda p, t: forward(p, cfg, t, init_kv_cache(cfg, 1),
                                 jnp.int32(0)))(qparams, tokens)
        return sum(1 for _ in jaxpr.jaxpr.eqns)

    assert n_eqns(32) <= n_eqns(16) + 8  # flat, not linear


def test_ep_non_owner_shards_skip_expert_reads():
    """Non-owner shards must perform NO packed-tile reads (VERDICT r04
    Weak #2): every expert EXCEPT the selected one carries NaN scale bits,
    so any shard that still streams its clamped local expert (the old
    masked-input variant: 0·NaN = NaN through the dot) poisons the psum.
    A finite, correct product proves only the owner's lax.cond branch ran
    the kernel."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    rng = np.random.RandomState(0)
    L, E, n, d = 1, 2, 64, 128
    w = (rng.randn(L, E, n, d) * 0.1).astype(np.float32)
    qt = q40.quantize(w)
    nan16 = np.uint16(0x7e00)  # f16 NaN bits
    scales = np.asarray(qt.scales).copy()
    scales[:, 1:] = nan16  # poison every expert but expert 0
    x = jnp.asarray(rng.randn(1, n).astype(np.float32), jnp.bfloat16)
    mesh = make_mesh(tp=1, ep=2, devices=jax.devices()[:2])
    out = q40._sharded_matmul_ep(
        x, jnp.asarray(qt.qpacked), jnp.asarray(scales),
        jnp.int32(0),  # layer 0 · E + expert 0 → owned by ep shard 0
        "row", mesh, interp=True)
    ref = x.astype(jnp.float32) @ q40.dequantize(
        q40.QTensor(qt.qpacked[0, 0], qt.scales[0, 0], qt.logical_nd),
        jnp.float32)
    assert np.isfinite(np.asarray(out)).all(), \
        "NaN product: a non-owner shard read its packed tiles"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=0, atol=1e-2 * float(np.abs(ref).max()))


def test_tp8_quantized_moe_matches_tp1():
    """N-shard ≡ 1-shard with packed experts on the pallas-interpret
    shard_map path (shard-clean shapes)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    cfg = tiny_config(arch=mfile.ARCH_MIXTRAL, n_experts=4, n_active_experts=2,
                      dim=256, hidden_dim=256, n_layers=2, n_heads=8,
                      n_kv_heads=8, vocab_size=128, seq_len=32,
                      ).with_(quant_impl="pallas_interpret")
    qparams = quantize_matmuls(init_params(cfg, seed=3), cfg)
    prompt = [1, 2, 3]
    e1 = Engine(cfg, qparams, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    e8 = Engine(cfg, qparams, mesh=make_mesh(tp=8))
    l1, _ = e1.prefill(prompt)
    l8, _ = e8.prefill(prompt)
    np.testing.assert_allclose(l1, l8, rtol=0, atol=1e-3 + 1e-3 * np.abs(l1).max())
