"""`.m` / `.t` file format roundtrip tests.

The reference has no explicit format test; here the writer (converter side)
and reader (runtime side) are validated against each other, which is the
same contract the reference enforces implicitly between `converter/writer.py`
and `transformer.cpp:loadRoot`."""

import numpy as np
import pytest

from dllama_tpu import quants
from dllama_tpu.io import mfile, tfile


def tiny_spec(arch=mfile.ARCH_LLAMA, ftype=quants.Q40, n_experts=0):
    return mfile.ModelSpec(
        arch=arch, dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
        n_experts=n_experts, n_active_experts=2 if n_experts else 0,
        vocab_size=100, seq_len=32, hidden_act=mfile.ACT_SILU,
        rope_theta=10000.0, weights_ftype=ftype)


def write_random_model(path, spec, seed=0):
    rng = np.random.RandomState(seed)
    tensors = {}
    with mfile.MFileWriter(path, spec) as w:
        for t in w.plan:
            x = rng.randn(*t.shape).astype(np.float32) * 0.05
            tensors[t.name] = x
            w.write_tensor(t.name, x)
    return tensors


@pytest.mark.parametrize("ftype", [quants.F32, quants.Q40, quants.Q80])
def test_mfile_roundtrip_dense(tmp_path, ftype):
    spec = tiny_spec(ftype=ftype)
    path = tmp_path / "model.m"
    tensors = write_random_model(path, spec)

    with mfile.MFile(path) as f:
        assert f.spec.dim == 64
        assert f.spec.arch == mfile.ARCH_LLAMA
        assert f.spec.weights_ftype == ftype
        assert f.spec.kv_dim == 32
        assert f.spec.head_size == 16
        names = [t.name for t in f.plan]
        assert names[0] == "token_embedding"
        assert names[-1] == "wcls"
        assert "layers.0.w2" in names
        tol = {quants.F32: 1e-7, quants.Q40: 0.03, quants.Q80: 0.002}[ftype]
        for name in ("token_embedding", "layers.0.wq", "layers.1.w2", "rms_final", "wcls"):
            got = f.tensor(name)
            assert got.shape == tensors[name].shape
            assert np.abs(got - tensors[name]).max() <= tol


def test_mfile_moe_plan(tmp_path):
    spec = tiny_spec(arch=mfile.ARCH_MIXTRAL, ftype=quants.Q80, n_experts=4)
    path = tmp_path / "moe.m"
    tensors = write_random_model(path, spec)
    with mfile.MFile(path) as f:
        names = [t.name for t in f.plan]
        assert "layers.0.moe_router" in names
        assert "layers.1.experts.3.down" in names
        assert "layers.0.w1" not in names
        got = f.tensor("layers.0.experts.2.gate")
        assert np.abs(got - tensors["layers.0.experts.2.gate"]).max() <= 0.002


def test_mfile_grok_has_extra_norms(tmp_path):
    spec = tiny_spec(arch=mfile.ARCH_GROK1, ftype=quants.F32, n_experts=2)
    spec.hidden_act = mfile.ACT_GELU
    path = tmp_path / "grok.m"
    write_random_model(path, spec)
    with mfile.MFile(path) as f:
        names = [t.name for t in f.plan]
        assert "layers.0.rms_moe" in names and "layers.1.rms_ffn2" in names


def test_mfile_size_mismatch_raises(tmp_path):
    spec = tiny_spec(ftype=quants.F32)
    path = tmp_path / "model.m"
    write_random_model(path, spec)
    with open(path, "ab") as f:
        f.write(b"xx")
    with pytest.raises(ValueError, match="size mismatch"):
        mfile.MFile(path)


def test_write_raw_equals_write_tensor(tmp_path):
    """write_raw with pre-encoded bytes produces a byte-identical file to
    write_tensor quantizing the same values (the synth-bench path)."""
    spec = tiny_spec(ftype=quants.Q40)
    rng = np.random.RandomState(3)
    tensors = {t.name: rng.randn(*t.shape).astype(np.float32) * 0.05
               for t in mfile.tensor_plan(spec)}
    a, b = tmp_path / "a.m", tmp_path / "b.m"
    with mfile.MFileWriter(a, spec) as w:
        for t in w.plan:
            w.write_tensor(t.name, tensors[t.name])
    with mfile.MFileWriter(b, spec) as w:
        for t in w.plan:
            w.write_raw(t.name, quants.quantize_tensor(tensors[t.name], t.ftype))
    assert a.read_bytes() == b.read_bytes()


def test_write_raw_size_checked(tmp_path):
    spec = tiny_spec(ftype=quants.Q40)
    with pytest.raises(ValueError, match="raw payload"):
        with mfile.MFileWriter(tmp_path / "x.m", spec) as w:
            w.write_raw(w.plan[0].name, b"\x00" * 7)


def test_q40_planes_from_file(tmp_path):
    spec = tiny_spec(ftype=quants.Q40)
    path = tmp_path / "model.m"
    write_random_model(path, spec)
    with mfile.MFile(path) as f:
        qvals, scales = f.q40_planes("layers.0.wq")
        assert qvals.shape == (64, 64)
        recon = qvals.astype(np.float32) * np.repeat(scales, 32, axis=1)
        np.testing.assert_allclose(recon, f.tensor("layers.0.wq"), atol=1e-6)


def test_tfile_roundtrip(tmp_path):
    t = tfile.TokenizerData(
        vocab=[b"<unk>", b"<s>", b"</s>"] + [f"<0x{i:02X}>".encode() for i in range(256)] + [b" hello", b"world"],
        scores=[0.0] * 261,
        bos_id=1, eos_id=2, chat_eos_id=2,
        chat_template="{% for m in messages %}<|im_start|>...",
        chat_stop="<|im_end|>")
    path = tmp_path / "tok.t"
    tfile.write_tfile(path, t)
    r = tfile.read_tfile(path)
    assert r.vocab == t.vocab
    assert r.bos_id == 1 and r.eos_id == 2 and r.chat_eos_id == 2
    assert r.chat_template == t.chat_template
    assert r.chat_stop == t.chat_stop
    assert r.max_token_length == max(len(v) for v in t.vocab)


def test_tfile_no_template(tmp_path):
    t = tfile.TokenizerData(vocab=[b"a", b"b"], scores=[0.0, 1.0], bos_id=0, eos_id=1)
    path = tmp_path / "tok.t"
    tfile.write_tfile(path, t)
    r = tfile.read_tfile(path)
    assert r.chat_template is None and r.chat_stop is None
    assert r.scores == [0.0, 1.0]
