"""Pod-supervisor crash-tolerance tests (router/pod.py Supervisor).

The unit tier drives a real :class:`Supervisor` over trivial child
processes — no jax, no model load — and pins the three failure shapes
from docs/ROBUSTNESS.md: death → respawn (same port, counted in
``dllama_pod_respawns_total``), crash loop → quarantine (no respawn
storm), hang (alive but /health silent) → SIGKILL + respawn with
``reason="hung"``.  A raising ``pod.respawn`` fault point counts as
another death, so a supervisor that cannot exec converges to quarantine
instead of spinning.

The slow tier runs tools/chaos_drill.py — the full supervised-pod soak
under live SIGKILL/SIGSTOP chaos with byte-parity, availability, and
KV-leak assertions.
"""

import os
import signal
import sys
import time

import pytest

from fixtures import REPO, free_port
from dllama_tpu.obs import metrics as obs_metrics
from dllama_tpu.router.pod import Supervisor, _Replica
from dllama_tpu.runtime.faults import injected

pytestmark = pytest.mark.chaos

_SLEEPER = [sys.executable, "-c", "import time; time.sleep(600)"]


def _wait(cond, timeout=30.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


def _mk_supervisor(argv, *, idx=0, port=None, **kw):
    rep = _Replica(idx, port if port is not None else free_port(),
                   list(argv), dict(os.environ))
    defaults = dict(respawn_max=5, respawn_window=30.0, hang_probes=2,
                    poll_interval=0.05, probe_timeout=0.5)
    defaults.update(kw)
    return rep, Supervisor([rep], **defaults)


def test_supervisor_respawns_killed_child():
    """SIGKILL a supervised child → a replacement process appears on the
    same port recipe, counted as one reason="exit" respawn."""
    rep, sup = _mk_supervisor(_SLEEPER)
    before = obs_metrics.POD_RESPAWNS.get(str(rep.idx), "exit")
    sup.start()
    try:
        assert rep.proc is not None and rep.proc.poll() is None
        pid0 = rep.proc.pid
        rep.proc.kill()
        _wait(lambda: rep.proc is not None and rep.proc.poll() is None
              and rep.proc.pid != pid0,
              msg="child was never respawned")
        assert not rep.quarantined
        assert sup.replicas_up() == 1
        assert obs_metrics.POD_RESPAWNS.get(str(rep.idx), "exit") \
            >= before + 1
    finally:
        sup.stop()


def test_supervisor_quarantines_crash_loop():
    """A child that exits immediately burns through respawn_max deaths
    inside the window and is quarantined — never respawned forever."""
    rep, sup = _mk_supervisor([sys.executable, "-c", "pass"],
                              respawn_max=2, respawn_window=30.0)
    sup.start()
    try:
        _wait(lambda: rep.quarantined, msg="crash loop never quarantined")
        assert rep.proc is None
        assert len(rep.deaths) > 2
        assert sup.replicas_up() == 0
        # quarantine is terminal for the watch loop: deaths stop growing
        n = len(rep.deaths)
        time.sleep(0.3)
        assert len(rep.deaths) == n
    finally:
        sup.stop()


def test_supervisor_respawn_fault_counts_as_death():
    """An injected pod.respawn failure (exec refused, fork bomb guard…)
    leaves no process; every poll without one counts as another death,
    so the crash-loop window still converges to quarantine."""
    rep, sup = _mk_supervisor(_SLEEPER, respawn_max=3)
    exits_before = obs_metrics.POD_RESPAWNS.get(str(rep.idx), "exit")
    with injected("pod.respawn=raise:RuntimeError"):
        sup.start()
        try:
            _wait(lambda: rep.proc is not None and rep.proc.poll() is None,
                  msg="child never spawned")
            rep.proc.kill()
            _wait(lambda: rep.quarantined,
                  msg="failed respawns never converged to quarantine")
        finally:
            sup.stop()
    # the respawn never succeeded, so the counter must not have moved
    assert obs_metrics.POD_RESPAWNS.get(str(rep.idx), "exit") \
        == exits_before


def test_supervisor_detects_hang():
    """SIGSTOP a child that was answering /health: the process is alive
    but probes stall, so after hang_probes misses the supervisor
    SIGKILLs and respawns it as reason="hung".  Hang detection arms only
    after the first healthy probe — a child still loading is never
    shot."""
    port = free_port()
    script = (
        "import http.server\n"
        "class H(http.server.BaseHTTPRequestHandler):\n"
        "    def do_GET(self):\n"
        "        self.send_response(200)\n"
        "        self.send_header('Content-Length', '2')\n"
        "        self.end_headers()\n"
        "        self.wfile.write(b'ok')\n"
        "    def log_message(self, *a): pass\n"
        f"http.server.HTTPServer(('127.0.0.1', {port}), H).serve_forever()\n")
    rep, sup = _mk_supervisor([sys.executable, "-c", script], port=port,
                              poll_interval=0.1, hang_probes=2)
    hung_before = obs_metrics.POD_RESPAWNS.get(str(rep.idx), "hung")
    sup.start()
    try:
        _wait(lambda: rep.ready, msg="child never answered /health")
        pid0 = rep.proc.pid
        os.kill(pid0, signal.SIGSTOP)  # wedged: alive, silent
        _wait(lambda: obs_metrics.POD_RESPAWNS.get(str(rep.idx), "hung")
              >= hung_before + 1,
              msg="hang was never detected")
        _wait(lambda: rep.proc is not None and rep.proc.poll() is None
              and rep.proc.pid != pid0,
              msg="hung child was never replaced")
        # the replacement serves the same port and goes ready again
        _wait(lambda: rep.ready, msg="replacement never answered /health")
    finally:
        sup.stop()


# -- the full chaos soak (tools/chaos_drill.py) ----------------------------

def _run_chaos(quick: bool) -> None:
    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        from chaos_drill import run_drill
    finally:
        sys.path.remove(tools)
    assert run_drill(quick=quick) == 0


@pytest.mark.slow
def test_chaos_drill_quick():
    """tools/chaos_drill.py --quick: one SIGKILL into a supervised pod
    under live traffic — parity, availability, respawn, no KV leak."""
    _run_chaos(quick=True)


@pytest.mark.slow
def test_chaos_soak_full():
    """The full soak: 4 alternating SIGKILL/SIGSTOP murders under a
    trace-replay mix plus greedy parity streams."""
    _run_chaos(quick=False)
