"""Tensor-parallel correctness: N-shard ≡ 1-shard equivalence.

This is the reference's core TP correctness property (commands-test.cpp:
30-69 slice-invariance) lifted to whole models, as SURVEY §4 prescribes:
the same weights run on a 1-device mesh and an 8-device mesh must produce
the same logits and the same greedy tokens."""

import numpy as np
import pytest
import jax

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.parallel.mesh import make_mesh, parse_workers
from dllama_tpu.parallel.sharding import check_tp_constraint, param_specs
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.sampling import Sampler


CFG = tiny_config(n_heads=8, n_kv_heads=8, dim=64, hidden_dim=128, vocab_size=96,
                  n_layers=2, seq_len=64)


def greedy_run(engine, prompt, steps):
    sampler = Sampler(engine.cfg.vocab_size, 0.0, 0.9, 1)
    out = []
    for tok, _ in engine.generate(prompt, steps, sampler):
        out.append(tok)
    return out


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape["tp"] == 8 and mesh.shape["sp"] == 1 and mesh.shape["dp"] == 1
    mesh2 = make_mesh(tp=4, sp=2)
    assert mesh2.shape["tp"] == 4 and mesh2.shape["sp"] == 2


def test_parse_workers():
    assert parse_workers("tpu:8").shape["tp"] == 8
    assert parse_workers(None).shape["tp"] == 8
    with pytest.raises(ValueError, match="tpu:N"):
        parse_workers("10.0.0.1:9998")


def test_tp_constraint_reference_parity():
    # nSlices > nKvHeads must refuse (transformer.cpp:88-91)
    with pytest.raises(ValueError, match="nKvHeads"):
        check_tp_constraint(tiny_config(n_kv_heads=2), 4)
    check_tp_constraint(tiny_config(n_kv_heads=4, n_heads=4), 4)


def test_param_specs_cover_all_params():
    # specs must cover every param key; "wqkv"/"w13" exist only in the
    # fused quantized layout, so specs is a superset of the dense keys
    for cfg in (CFG, tiny_config(n_experts=4, n_active_experts=2)):
        assert set(param_specs(cfg)) >= set(init_params(cfg, 0))
        assert {"wqkv", "w13"} <= set(param_specs(cfg))


def test_tp8_matches_tp1_logits_and_tokens():
    params = init_params(CFG, seed=21)
    prompt = [3, 14, 15, 92, 6]

    e1 = Engine(CFG, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    e8 = Engine(CFG, params, mesh=make_mesh(tp=8))

    l1, _ = e1.prefill(prompt)
    l8, _ = e8.prefill(prompt)
    np.testing.assert_allclose(l1, l8, atol=1e-4, rtol=1e-3)

    t1 = greedy_run(Engine(CFG, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1])), prompt, 20)
    t8 = greedy_run(Engine(CFG, params, mesh=make_mesh(tp=8)), prompt, 20)
    assert t1 == t8


def test_tp_moe_matches_single_device():
    cfg = tiny_config(arch=0xABCD02, n_experts=4, n_active_experts=2,
                      n_heads=8, n_kv_heads=8, dim=64, hidden_dim=128, seq_len=32)
    params = init_params(cfg, seed=8)
    prompt = [1, 2, 3]
    e1 = Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    e8 = Engine(cfg, params, mesh=make_mesh(tp=8))
    l1, _ = e1.prefill(prompt)
    l8, _ = e8.prefill(prompt)
    np.testing.assert_allclose(l1, l8, atol=1e-4, rtol=1e-3)


def test_ep_moe_matches_single_device():
    """Expert-parallel (ep) sharding of dense expert stacks — a pure
    sharding-spec capability beyond the reference — must be numerically
    invariant, prefill and greedy decode."""
    cfg = tiny_config(arch=0xABCD02, n_experts=4, n_active_experts=2,
                      n_heads=8, n_kv_heads=8, dim=64, hidden_dim=128, seq_len=32)
    params = init_params(cfg, seed=9)
    prompt = [5, 1, 4]
    e1 = Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    eep = Engine(cfg, params, mesh=make_mesh(tp=2, ep=4))
    l1, _ = e1.prefill(prompt)
    lep, _ = eep.prefill(prompt)
    np.testing.assert_allclose(l1, lep, atol=1e-4, rtol=1e-3)
    t1 = greedy_run(Engine(cfg, params, mesh=make_mesh(tp=1, devices=jax.devices()[:1])), prompt, 12)
    tep = greedy_run(Engine(cfg, params, mesh=make_mesh(tp=2, ep=4)), prompt, 12)
    assert t1 == tep


def test_ep_requires_moe_and_divisibility():
    params = init_params(CFG, seed=1)
    with pytest.raises(ValueError, match="MoE"):
        Engine(CFG, params, mesh=make_mesh(tp=1, ep=2, devices=jax.devices()[:2]))
    cfg = tiny_config(arch=0xABCD02, n_experts=4, n_active_experts=2,
                      n_heads=8, n_kv_heads=8, dim=64, hidden_dim=128, seq_len=32)
    with pytest.raises(ValueError, match="divisible"):
        Engine(cfg, init_params(cfg, seed=1),
               mesh=make_mesh(tp=1, ep=3, devices=jax.devices()[:3]))
