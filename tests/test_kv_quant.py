"""Quantized (int8) KV cache — beyond reference (transformer.cpp:280-282
holds f32 caches): int8 values + per-(head, position) f32 scales give ~2×
less cache HBM traffic/residency than bf16, nearly doubling max context
per chip.  Quantize at write (update_cache_at), dequant on read — block-
wise on the long-context decode path so the HBM read stays int8-sized."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.models.transformer import KVCache, init_kv_cache, update_cache_at
from dllama_tpu.ops.attention import (decode_gqa_attention, dequant_kv,
                                      gqa_attention, quantize_kv)
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import Engine

CFG = tiny_config(seq_len=64)


def make_engine(kv=None, tp=1):
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=tp, devices=jax.devices()[:tp]),
                  kv_dtype=kv)


def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 4, 8, 128).astype(np.float32))
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 4, 8, 1)
    back = np.asarray(dequant_kv(q, s), np.float32)
    amax = np.abs(np.asarray(x)).max(-1, keepdims=True)
    # int8 absmax quantization: error ≤ scale/2 = amax/254 per element
    # (+ bf16 output rounding of dequant_kv, ~0.4% of magnitude)
    assert np.all(np.abs(back - np.asarray(x)) <= amax / 254 + 0.004 * amax + 1e-6)


def test_quantize_zero_row_is_exact():
    q, s = quantize_kv(jnp.zeros((1, 1, 2, 16)))
    assert np.all(np.asarray(q) == 0) and np.all(np.asarray(s) == 0)
    assert np.all(np.asarray(dequant_kv(q, s)) == 0)


def test_update_cache_at_quantized_writes_window():
    cfg = tiny_config(seq_len=16)
    cache = init_kv_cache(cfg, batch=1, quant=True)
    assert cache.quantized
    rng = np.random.RandomState(1)
    k_new = jnp.asarray(rng.randn(1, cfg.n_kv_heads, 2, cfg.head_size)
                        .astype(np.float32))
    v_new = jnp.asarray(rng.randn(1, cfg.n_kv_heads, 2, cfg.head_size)
                        .astype(np.float32))
    cache = update_cache_at(cache, k_new, v_new, jnp.int32(1), jnp.int32(3))
    got = dequant_kv(cache.k[1, :, :, 3:5], cache.k_scale[1, :, :, 3:5])
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(k_new, np.float32), atol=0.03)
    # untouched layers/positions stay zero
    assert np.all(np.asarray(cache.k[0]) == 0)
    assert np.all(np.asarray(cache.k[1, :, :, :3]) == 0)


def test_blocked_decode_matches_dequant_oneshot():
    """The long-context decode path (block-wise int8 slicing, ≥4096 cache)
    must match one-shot attention over the fully dequantized cache."""
    rng = np.random.RandomState(2)
    b, hkv, g, s, dh = 1, 2, 2, 4096, 32
    pos = 1234
    kq, ks = quantize_kv(jnp.asarray(rng.randn(b, hkv, s, dh), jnp.float32))
    vq, vs = quantize_kv(jnp.asarray(rng.randn(b, hkv, s, dh), jnp.float32))
    q = jnp.asarray(rng.randn(b, hkv * g, 1, dh), jnp.float32)
    out_blocked = decode_gqa_attention(q, kq, vq, jnp.int32(pos),
                                       scales=(ks, vs))
    out_ref = gqa_attention(q, dequant_kv(kq, ks), dequant_kv(vq, vs),
                            jnp.int32(pos), 1)
    np.testing.assert_allclose(np.asarray(out_blocked), np.asarray(out_ref),
                               rtol=0, atol=2e-2)


def test_blocked_decode_layer_indexed_quantized():
    """The production path slices int8 blocks AND scale columns out of the
    *stacked* (L, …) cache at a traced layer index — the exact read the
    hardware-only llama2-7b-long-q8kv stage runs; pin it on CPU too."""
    rng = np.random.RandomState(3)
    L, b, hkv, g, s, dh = 3, 1, 2, 2, 4096, 32
    pos, layer = 777, 1
    kq, ks = quantize_kv(jnp.asarray(rng.randn(L, b, hkv, s, dh), jnp.float32))
    vq, vs = quantize_kv(jnp.asarray(rng.randn(L, b, hkv, s, dh), jnp.float32))
    q = jnp.asarray(rng.randn(b, hkv * g, 1, dh), jnp.float32)
    out = decode_gqa_attention(q, kq, vq, jnp.int32(pos),
                               layer=jnp.int32(layer), scales=(ks, vs))
    out_ref = gqa_attention(q, dequant_kv(kq[layer], ks[layer]),
                            dequant_kv(vq[layer], vs[layer]),
                            jnp.int32(pos), 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=0, atol=2e-2)


def test_q8_cache_greedy_stream_close_to_dense():
    p = [5, 9, 2, 7]
    dense = [t for t, _ in make_engine().generate_stream(p, 20, temperature=0.0,
                                                         chunk=6)]
    q8 = [t for t, _ in make_engine("q8").generate_stream(p, 20, temperature=0.0,
                                                          chunk=6)]
    # ~0.4% logit perturbation: require a long shared greedy prefix rather
    # than exact equality (near-ties may flip late tokens)
    agree = sum(1 for a, b in zip(dense, q8) if a == b)
    assert agree >= len(p) + 8, (dense, q8)
    l1, _ = make_engine().prefill(p)
    l2, _ = make_engine("q8").prefill(p)
    err = np.max(np.abs(l1 - l2)) / (np.max(np.abs(l1)) + 1e-9)
    assert err < 0.05


def test_q8_cache_tp2_matches_tp1():
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    p = [3, 11, 6]
    l1, _ = make_engine("q8").prefill(p)
    l2, _ = make_engine("q8", tp=2).prefill(p)
    np.testing.assert_allclose(l1, l2, rtol=0,
                               atol=1e-3 + 1e-3 * np.abs(l1).max())


def test_q8_cache_with_ragged_batch():
    e = Engine(CFG, init_params(CFG, seed=4),
               mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
               batch=2, kv_dtype="q8")
    outs = e.generate_batch([[5, 9, 2], [7, 3, 11, 4]], 12, temperature=0.0,
                            chunk=4)
    s1 = [t for t, _ in make_engine("q8").generate_stream([5, 9, 2], 12,
                                                          temperature=0.0,
                                                          chunk=4)]
    assert outs[0] == s1  # same quantized-cache math, batched vs alone


def test_q8_cache_rejects_sp_mesh():
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    with pytest.raises(ValueError, match="sp"):
        Engine(CFG, init_params(CFG, seed=4),
               mesh=make_mesh(tp=1, sp=2, devices=jax.devices()[:2]),
               kv_dtype="q8")


def test_q8_cache_halves_bytes():
    """Exact byte accounting: int8 values (1 B/elem vs bf16's 2) plus one
    f32 scale per (head, position) row — 4/Dh relative overhead, ~3% at
    the production Dh=128 (25% at this fixture's Dh=16, which is why the
    bound is exact, not a ratio)."""
    dense = init_kv_cache(CFG, batch=1, dtype=jnp.bfloat16)
    quant = init_kv_cache(CFG, batch=1, quant=True)
    assert quant.k.dtype == jnp.int8 and quant.v.dtype == jnp.int8
    n_elems = dense.k.size
    assert quant.k.nbytes == n_elems  # 1 B per element
    assert quant.k_scale.nbytes == (n_elems // CFG.head_size) * 4
    quant_bytes = (quant.k.nbytes + quant.v.nbytes
                   + quant.k_scale.nbytes + quant.v_scale.nbytes)
    dense_bytes = dense.k.nbytes + dense.v.nbytes
    assert quant_bytes == dense_bytes // 2 + quant.k_scale.nbytes * 2
