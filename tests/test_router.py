"""Fleet-router tests (router/registry.py scoring + hysteresis, and the
one-command fleet smoke from tools/router_smoke.py wired as a fast-tier
test).

The registry tests run against an in-thread fake replica serving canned
``/health`` JSON — no jax, no subprocesses — and pin:

* **scoring** — dispatch prefers free slots, debits queue depth and
  router-side in-flight, tiebreaks on free KV pages, and buries
  degraded / SLO-violating replicas under a penalty that only loses to
  the same penalty;
* **eligibility** — ejected, draining, and never-probed backends take
  no traffic; hand-off placement additionally requires the replica to
  advertise ``capacity.handoff``;
* **hysteresis** — ``eject_after`` consecutive failures (probe or
  dispatch) eject; ``readmit_after`` consecutive healthy probes
  re-admit; one good probe does not un-eject and one failure does not
  eject.

The smoke test boots 2 real replicas + the router as subprocesses and
asserts zero errors with balanced dispatch — the cheapest end-to-end
proof of the fleet path (probes, least-loaded pick, relay, metrics).
"""

import http.server
import json
import threading

import pytest

from fixtures import REPO, free_port, write_tiny_model, write_tiny_tokenizer
from dllama_tpu.router.registry import Backend, Registry

pytestmark = pytest.mark.router


def _health(free_slots=2, queue_depth=0, free_kv_pages=50, handoff=True,
            degraded=False, slo="ok", status="serving"):
    return {"status": status, "degraded": degraded,
            "slo": {"status": slo},
            "capacity": {"free_slots": free_slots,
                         "queue_depth": queue_depth,
                         "free_kv_pages": free_kv_pages,
                         "handoff": handoff}}


def _backend(health=None, probed=True):
    b = Backend(f"127.0.0.1:{free_port()}")
    if probed:
        b.last_health = health if health is not None else _health()
    return b


# -- scoring and eligibility ----------------------------------------------

def test_score_prefers_idle_capacity():
    idle = _backend(_health(free_slots=3))
    busy = _backend(_health(free_slots=1, queue_depth=2))
    assert Registry._score(idle) > Registry._score(busy)
    # router-side in-flight debits the score before the next probe lands
    idle.in_flight = 5
    assert Registry._score(idle) < Registry._score(busy)


def test_score_kv_pages_tiebreak_only():
    roomy = _backend(_health(free_slots=2, free_kv_pages=60))
    tight = _backend(_health(free_slots=2, free_kv_pages=2))
    assert Registry._score(roomy) > Registry._score(tight)
    # …but a page never outweighs a slot
    assert Registry._score(_backend(_health(free_slots=1, free_kv_pages=0))) \
        > Registry._score(_backend(_health(free_slots=0, free_kv_pages=9e9)))


def test_score_penalizes_degraded_and_slo_violating():
    good = _backend(_health(free_slots=0, queue_depth=5))
    for sick in (_backend(_health(free_slots=8, degraded=True)),
                 _backend(_health(free_slots=8, slo="violating"))):
        assert Registry._score(sick) < Registry._score(good)


def test_pick_eligibility():
    reg = Registry(["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3",
                    "127.0.0.1:4", "127.0.0.1:5"])
    best, drn, eject, unprobed, worse = reg.backends
    best.last_health = _health(free_slots=3)
    drn.last_health = _health(free_slots=9, status="draining")
    eject.last_health = _health(free_slots=9)
    eject.ejected = True
    worse.last_health = _health(free_slots=1, handoff=False)
    assert unprobed.last_health is None
    assert reg.pick() is best
    assert reg.pick(exclude=(best,)) is worse
    assert reg.pick(exclude=(best, worse)) is None
    # hand-off placement additionally requires capacity.handoff
    assert reg.handoff_peers() == [best]
    assert reg.handoff_peers(exclude=(best,)) == []


def test_ejection_and_failure_hysteresis():
    reg = Registry(["127.0.0.1:1", "127.0.0.1:2"], eject_after=3)
    b = reg.backends[0]
    b.last_health = _health()
    reg.record_failure(b)
    reg.record_failure(b)
    assert not b.ejected  # two failures are not three
    reg.record_success(b)  # a served request resets the streak
    reg.record_failure(b)
    reg.record_failure(b)
    assert not b.ejected
    reg.record_failure(b)
    assert b.ejected
    assert reg.pick() is None  # sibling was never probed


# -- probe loop against a fake replica ------------------------------------

class _FakeReplica:
    """In-thread HTTP server returning a settable /health payload (or a
    5xx when told to be sick)."""

    def __init__(self):
        self.payload = _health()
        self.sick = False
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                body = json.dumps(outer.payload).encode()
                self.send_response(503 if outer.sick else 200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_probe_eject_readmit_cycle():
    replica = _FakeReplica()
    try:
        reg = Registry([f"127.0.0.1:{replica.port}"],
                       eject_after=2, readmit_after=2, probe_timeout=2.0)
        b = reg.backends[0]
        assert reg.probe(b)
        assert b.last_health["capacity"]["free_slots"] == 2
        assert b.last_probe_s is not None and reg.pick() is b

        replica.sick = True
        assert not reg.probe(b) and not b.ejected  # 1 failure: hysteresis
        assert not reg.probe(b) and b.ejected      # 2nd ejects
        assert reg.pick() is None

        replica.sick = False
        assert reg.probe(b) and b.ejected          # 1 good probe: still out
        assert reg.probe(b) and not b.ejected      # 2nd re-admits
        assert reg.pick() is b
    finally:
        replica.close()


# -- end-to-end fleet smoke -----------------------------------------------

def test_fleet_smoke(tmp_path):
    """tools/router_smoke.py: router + 2 real replicas, 8 concurrent
    requests, zero errors, every backend served at least one."""
    import os
    import sys

    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        from router_smoke import run_smoke
    finally:
        sys.path.remove(tools)
    model = str(tmp_path / "tiny.model.json")
    tok = str(tmp_path / "tiny.tok.json")
    write_tiny_model(model)
    write_tiny_tokenizer(tok)
    run_smoke(model, tok, n_requests=8, n_replicas=2)


# -- serve-pod: dp × tp replica partitioning (router/pod.py) ---------------

def test_pod_tp_parsing_and_partition():
    from dllama_tpu.router.pod import parse_pod_tp, partition_devices
    assert parse_pod_tp(None, 8, 2) == 4       # default: split evenly
    assert parse_pod_tp("tpu:2", 8, 2) == 2    # explicit degree wins
    with pytest.raises(SystemExit):
        parse_pod_tp("host:port", 8, 2)        # reference-style addr list
    with pytest.raises(SystemExit):
        parse_pod_tp(None, 1, 2)               # more replicas than devices
    devs = list(range(8))
    groups = partition_devices(devs, 2, 3)     # 2 idle devices is legal
    assert groups == [[0, 1, 2], [3, 4, 5]]
    with pytest.raises(SystemExit):
        partition_devices(devs, 3, 3)          # 9 > 8


def test_serve_pod_smoke(tmp_path):
    """dllama serve-pod with dp=2 × tp=2 over 4 forced CPU devices: one
    public port, two in-process tensor-parallel replicas auto-registered
    as router backends, both serving real completions."""
    import subprocess
    import sys
    import time
    import urllib.request

    from fixtures import cpu_env

    model = str(tmp_path / "tiny.m")
    tok = str(tmp_path / "tiny.t")
    write_tiny_model(model)
    write_tiny_tokenizer(tok)
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "serve-pod",
         "--model", model, "--tokenizer", tok,
         "--workers", "tpu:2", "--dp", "2",
         "--port", str(port), "--temperature", "0",
         "--max-seq-len", "64", "--batch-slots", "2",
         "--kv-pages", "64", "--kv-page-size", "4",
         "--probe-interval", "0.5"],
        cwd=REPO, env=cpu_env(4), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    base = f"http://127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"serve-pod died:\n{proc.stdout.read()}")
            try:
                with urllib.request.urlopen(base + "/health", timeout=2) as r:
                    health = json.loads(r.read())
                break
            except OSError:
                time.sleep(0.3)
        else:
            raise AssertionError("serve-pod router never came up")
        assert health["role"] == "router"
        assert len(health["backends"]) == 2, health
        time.sleep(1.2)  # a probe round, so both backends are scored
        for i in range(3):
            body = json.dumps({"prompt": f"hello {i}",
                               "max_tokens": 4}).encode()
            req = urllib.request.Request(
                base + "/v1/completions", body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=240) as r:
                out = json.loads(r.read())
            assert out["choices"][0]["finish_reason"] in ("stop", "length")
    finally:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
    # the end-of-run ledger names the off-TPU collective degrade — a pod
    # bench number can never read as the fused-collective number
    assert "tp_psum" in out, out[-2000:]
