"""Fleet-router tests (router/registry.py scoring + hysteresis, and the
one-command fleet smoke from tools/router_smoke.py wired as a fast-tier
test).

The registry tests run against an in-thread fake replica serving canned
``/health`` JSON — no jax, no subprocesses — and pin:

* **scoring** — dispatch prefers free slots, debits queue depth and
  router-side in-flight, tiebreaks on free KV pages, and buries
  degraded / SLO-violating replicas under a penalty that only loses to
  the same penalty;
* **eligibility** — ejected, draining, and never-probed backends take
  no traffic; hand-off placement additionally requires the replica to
  advertise ``capacity.handoff``;
* **hysteresis** — ``eject_after`` consecutive failures (probe or
  dispatch) eject; ``readmit_after`` consecutive healthy probes
  re-admit; one good probe does not un-eject and one failure does not
  eject.

The smoke test boots 2 real replicas + the router as subprocesses and
asserts zero errors with balanced dispatch — the cheapest end-to-end
proof of the fleet path (probes, least-loaded pick, relay, metrics).
"""

import http.server
import json
import threading
import time

import pytest

from fixtures import REPO, free_port, write_tiny_model, write_tiny_tokenizer
from dllama_tpu.router.registry import Backend, Registry

pytestmark = pytest.mark.router


def _health(free_slots=2, queue_depth=0, free_kv_pages=50, handoff=True,
            degraded=False, slo="ok", status="serving"):
    return {"status": status, "degraded": degraded,
            "slo": {"status": slo},
            "capacity": {"free_slots": free_slots,
                         "queue_depth": queue_depth,
                         "free_kv_pages": free_kv_pages,
                         "handoff": handoff}}


def _backend(health=None, probed=True):
    b = Backend(f"127.0.0.1:{free_port()}")
    if probed:
        b.last_health = health if health is not None else _health()
    return b


# -- scoring and eligibility ----------------------------------------------

def test_score_prefers_idle_capacity():
    idle = _backend(_health(free_slots=3))
    busy = _backend(_health(free_slots=1, queue_depth=2))
    assert Registry._score(idle) > Registry._score(busy)
    # router-side in-flight debits the score before the next probe lands
    idle.in_flight = 5
    assert Registry._score(idle) < Registry._score(busy)


def test_score_kv_pages_tiebreak_only():
    roomy = _backend(_health(free_slots=2, free_kv_pages=60))
    tight = _backend(_health(free_slots=2, free_kv_pages=2))
    assert Registry._score(roomy) > Registry._score(tight)
    # …but a page never outweighs a slot
    assert Registry._score(_backend(_health(free_slots=1, free_kv_pages=0))) \
        > Registry._score(_backend(_health(free_slots=0, free_kv_pages=9e9)))


def test_score_penalizes_degraded_and_slo_violating():
    good = _backend(_health(free_slots=0, queue_depth=5))
    for sick in (_backend(_health(free_slots=8, degraded=True)),
                 _backend(_health(free_slots=8, slo="violating"))):
        assert Registry._score(sick) < Registry._score(good)


def test_pick_eligibility():
    reg = Registry(["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3",
                    "127.0.0.1:4", "127.0.0.1:5"])
    best, drn, eject, unprobed, worse = reg.backends
    best.last_health = _health(free_slots=3)
    drn.last_health = _health(free_slots=9, status="draining")
    eject.last_health = _health(free_slots=9)
    eject.ejected = True
    worse.last_health = _health(free_slots=1, handoff=False)
    assert unprobed.last_health is None
    assert reg.pick() is best
    assert reg.pick(exclude=(best,)) is worse
    assert reg.pick(exclude=(best, worse)) is None
    # hand-off placement additionally requires capacity.handoff
    assert reg.handoff_peers() == [best]
    assert reg.handoff_peers(exclude=(best,)) == []


def test_ejection_and_failure_hysteresis():
    reg = Registry(["127.0.0.1:1", "127.0.0.1:2"], eject_after=3)
    b = reg.backends[0]
    b.last_health = _health()
    reg.record_failure(b)
    reg.record_failure(b)
    assert not b.ejected  # two failures are not three
    reg.record_success(b)  # a served request resets the streak
    reg.record_failure(b)
    reg.record_failure(b)
    assert not b.ejected
    reg.record_failure(b)
    assert b.ejected
    assert reg.pick() is None  # sibling was never probed


# -- probe loop against a fake replica ------------------------------------

class _FakeReplica:
    """In-thread HTTP server returning a settable /health payload (or a
    5xx when told to be sick)."""

    def __init__(self):
        self.payload = _health()
        self.sick = False
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                body = json.dumps(outer.payload).encode()
                self.send_response(503 if outer.sick else 200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_probe_eject_readmit_cycle():
    replica = _FakeReplica()
    try:
        reg = Registry([f"127.0.0.1:{replica.port}"],
                       eject_after=2, readmit_after=2, probe_timeout=2.0)
        b = reg.backends[0]
        assert reg.probe(b)
        assert b.last_health["capacity"]["free_slots"] == 2
        assert b.last_probe_s is not None and reg.pick() is b

        replica.sick = True
        assert not reg.probe(b) and not b.ejected  # 1 failure: hysteresis
        assert not reg.probe(b) and b.ejected      # 2nd ejects
        assert reg.pick() is None

        replica.sick = False
        assert reg.probe(b) and b.ejected          # 1 good probe: still out
        assert reg.probe(b) and not b.ejected      # 2nd re-admits
        assert reg.pick() is b
    finally:
        replica.close()


# -- end-to-end fleet smoke -----------------------------------------------

def test_fleet_smoke(tmp_path):
    """tools/router_smoke.py: router + 2 real replicas, 8 concurrent
    requests, zero errors, every backend served at least one."""
    import os
    import sys

    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        from router_smoke import run_smoke
    finally:
        sys.path.remove(tools)
    model = str(tmp_path / "tiny.model.json")
    tok = str(tmp_path / "tiny.tok.json")
    write_tiny_model(model)
    write_tiny_tokenizer(tok)
    run_smoke(model, tok, n_requests=8, n_replicas=2)


# -- serve-pod: dp × tp replica partitioning (router/pod.py) ---------------

def test_pod_tp_parsing_and_partition():
    from dllama_tpu.router.pod import parse_pod_tp, partition_devices
    assert parse_pod_tp(None, 8, 2) == 4       # default: split evenly
    assert parse_pod_tp("tpu:2", 8, 2) == 2    # explicit degree wins
    with pytest.raises(SystemExit):
        parse_pod_tp("host:port", 8, 2)        # reference-style addr list
    with pytest.raises(SystemExit):
        parse_pod_tp(None, 1, 2)               # more replicas than devices
    devs = list(range(8))
    groups = partition_devices(devs, 2, 3)     # 2 idle devices is legal
    assert groups == [[0, 1, 2], [3, 4, 5]]
    with pytest.raises(SystemExit):
        partition_devices(devs, 3, 3)          # 9 > 8


def test_serve_pod_smoke(tmp_path):
    """dllama serve-pod with dp=2 × tp=2 over 4 forced CPU devices: one
    public port, two in-process tensor-parallel replicas auto-registered
    as router backends, both serving real completions."""
    import subprocess
    import sys
    import time
    import urllib.request

    from fixtures import cpu_env

    model = str(tmp_path / "tiny.m")
    tok = str(tmp_path / "tiny.t")
    write_tiny_model(model)
    write_tiny_tokenizer(tok)
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu", "serve-pod",
         "--model", model, "--tokenizer", tok,
         "--workers", "tpu:2", "--dp", "2",
         "--port", str(port), "--temperature", "0",
         "--max-seq-len", "64", "--batch-slots", "2",
         "--kv-pages", "64", "--kv-page-size", "4",
         "--probe-interval", "0.5"],
        cwd=REPO, env=cpu_env(4), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    base = f"http://127.0.0.1:{port}"
    try:
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise AssertionError(
                    f"serve-pod died:\n{proc.stdout.read()}")
            try:
                with urllib.request.urlopen(base + "/health", timeout=2) as r:
                    health = json.loads(r.read())
                break
            except OSError:
                time.sleep(0.3)
        else:
            raise AssertionError("serve-pod router never came up")
        assert health["role"] == "router"
        assert len(health["backends"]) == 2, health
        time.sleep(1.2)  # a probe round, so both backends are scored
        for i in range(3):
            body = json.dumps({"prompt": f"hello {i}",
                               "max_tokens": 4}).encode()
            req = urllib.request.Request(
                base + "/v1/completions", body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=240) as r:
                out = json.loads(r.read())
            assert out["choices"][0]["finish_reason"] in ("stop", "length")
    finally:
        proc.terminate()
        try:
            out, _ = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
    # the end-of-run ledger names the off-TPU collective degrade — a pod
    # bench number can never read as the fused-collective number
    assert "tp_psum" in out, out[-2000:]


# -- crash tolerance: RTT degradation, stall watchdog, resume --------------

def test_rtt_degradation_buries_score():
    """A 10× probe-RTT excursion past a backend's own floor carries the
    same penalty as degraded/SLO-violating — capacity cannot buy it the
    pick — with the documented clamps (1 ms floor, 50 ms threshold)."""
    reg = Registry(["127.0.0.1:1", "127.0.0.1:2"])
    fast, slow = reg.backends
    fast.last_health = _health(free_slots=1)
    slow.last_health = _health(free_slots=8)
    assert not slow.rtt_degraded()        # no baseline yet: no signal
    slow.rtt_floor = 0.002
    slow.last_probe_s = 0.004             # 2× the floor: normal jitter
    assert not slow.rtt_degraded()
    slow.last_probe_s = 0.3               # 10× past floor AND > 50 ms
    assert slow.rtt_degraded()
    assert slow.summary()["rtt_degraded"] is True
    assert Registry._score(fast) > Registry._score(slow)
    assert reg.pick() is fast             # 8 free slots lose to 1
    # sub-ms loopback floors are clamped: 10× of nothing is not a signal
    slow.rtt_floor = 0.0001
    slow.last_probe_s = 0.004
    assert not slow.rtt_degraded()
    # WAN-ish floors need a real excursion, not just the 10× ratio
    slow.rtt_floor = 0.004
    slow.last_probe_s = 0.045             # >10× but under the 50 ms gate
    assert not slow.rtt_degraded()


def test_force_eject_bypasses_hysteresis_readmit_does_not():
    """force_eject (the stall watchdog's teeth) skips the failure-streak
    wait, but the way back in stays hysteretic: readmit_after healthy
    probes, not one."""
    replica = _FakeReplica()
    try:
        reg = Registry([f"127.0.0.1:{replica.port}"],
                       eject_after=3, readmit_after=2, probe_timeout=2.0)
        b = reg.backends[0]
        assert reg.probe(b) and reg.pick() is b
        reg.force_eject(b, "stream stall (test)")
        assert b.ejected and reg.pick() is None
        assert reg.probe(b) and b.ejected      # 1 good probe: still out
        assert reg.probe(b) and not b.ejected  # 2nd re-admits
        assert reg.pick() is b
    finally:
        replica.close()


def test_record_store_ttl():
    """RecordStore: sweep-on-access expiry with the on_expire hook;
    ttl<=0 keeps records forever (the plain-dict behavior)."""
    from dllama_tpu.runtime.snapshot import RecordStore

    expired: list[str] = []
    rs = RecordStore(ttl=0.15, on_expire=expired.append)
    rs.put("a", b"1")
    rs.put("b", b"2")
    assert rs.get("a") == b"1" and len(rs) == 2 and rs
    time.sleep(0.25)
    rs.put("c", b"3")                     # fresh record, post-expiry
    assert rs.get("a") is None and rs.get("b") is None
    assert sorted(expired) == ["a", "b"]
    assert rs.pop("c") == b"3" and rs.pop("c", b"gone") == b"gone"
    assert not rs
    keep = RecordStore(ttl=0.0)
    keep.put("x", b"y")
    assert keep.get("x") == b"y" and len(keep) == 1
    keep.discard("x")                     # discard never fires on_expire
    assert not keep and keep.sweep() == 0


class _StallingReplica:
    """A replica that answers /health, streams ONE SSE chunk of a
    completion, then goes silent while the socket stays open — the
    wedged-but-connected shape only --stall-timeout can catch."""

    def __init__(self, hold_s: float = 8.0):
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                body = json.dumps(
                    {"status": "serving",
                     "capacity": {"free_slots": 2, "queue_depth": 0,
                                  "free_kv_pages": 50,
                                  "handoff": True}}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                self.rfile.read(
                    int(self.headers.get("Content-Length") or 0))
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()
                evt = {"id": "cmpl-stall", "model": "tiny", "created": 0,
                       "choices": [{"index": 0, "text": "Hello",
                                    "finish_reason": None}]}
                self.wfile.write(b"data: " + json.dumps(evt).encode()
                                 + b"\n\n")
                self.wfile.flush()
                outer.stalled.set()
                time.sleep(hold_s)  # wedged: connected, silent

            def log_message(self, *a):
                pass

        self.stalled = threading.Event()
        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_stall_watchdog_cuts_wedged_stream():
    """One backend wedges mid-stream (bytes sent, then silence): the
    watchdog trips within --stall-timeout, force-ejects the backend,
    the greedy resume ladder finds no peer, and the client gets the
    honest replica_lost finish — never an indefinite hang."""
    import urllib.request

    from dllama_tpu.obs import metrics as obs_metrics
    from dllama_tpu.router.service import RouterState, make_handler

    replica = _StallingReplica()
    state = None
    server = None
    try:
        reg = Registry([f"127.0.0.1:{replica.port}"], probe_timeout=2.0)
        assert reg.probe(reg.backends[0])
        # resume_window=0: with the only backend wedged there is no peer
        # to resume on — don't spend the grace window finding that out
        state = RouterState(reg, retries=1, upstream_timeout=30.0,
                            stall_timeout=1.0, resume_window=0.0)
        server = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), make_handler(state))
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        stalls0 = obs_metrics.ROUTER_STALLS.value
        nopeer0 = obs_metrics.ROUTER_RESUMES.get("no_peer")

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.server_address[1]}/v1/completions",
            json.dumps({"prompt": "hi", "max_tokens": 8, "stream": True,
                        "temperature": 0}).encode(),
            headers={"Content-Type": "application/json"})
        text, finish = "", None
        t0 = time.monotonic()
        with urllib.request.urlopen(req, timeout=30) as r:
            for line in r:
                line = line.strip()
                if not line.startswith(b"data: "):
                    continue
                payload = line[len(b"data: "):]
                if payload == b"[DONE]":
                    break
                c = json.loads(payload)["choices"][0]
                text += c.get("text") or ""
                if c.get("finish_reason"):
                    finish = c["finish_reason"]
        elapsed = time.monotonic() - t0
        assert text == "Hello"
        assert finish == "replica_lost"
        assert elapsed < 6.0, f"watchdog too slow: {elapsed:.1f}s"
        assert obs_metrics.ROUTER_STALLS.value >= stalls0 + 1
        # greedy + auto: the resume ladder ran and honestly reported
        # the empty fleet rather than silently truncating
        assert obs_metrics.ROUTER_RESUMES.get("no_peer") >= nopeer0 + 1
        assert reg.backends[0].ejected  # forced out, not streak-waited
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        replica.close()


def test_resume_policy_validation():
    """resume_policy is a router-level contract: bogus values 400 before
    any backend is touched; valid values are accepted (and the field is
    never forwarded upstream — asserted by the drills' byte parity)."""
    import urllib.error
    import urllib.request

    from dllama_tpu.router.service import RouterState, make_handler

    reg = Registry(["127.0.0.1:1"])  # never probed: no traffic possible
    state = RouterState(reg)
    server = http.server.ThreadingHTTPServer(
        ("127.0.0.1", 0), make_handler(state))
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    try:
        def post(body):
            req = urllib.request.Request(
                base + "/v1/completions", json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            return urllib.request.urlopen(req, timeout=10)

        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"prompt": "x", "resume_policy": "sometimes"})
        assert ei.value.code == 400
        assert b"resume_policy" in ei.value.read()
        # a valid policy passes validation and reaches dispatch, which
        # honestly 503s on the never-probed fleet
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"prompt": "x", "resume_policy": "never"})
        assert ei.value.code == 503
    finally:
        server.shutdown()
        server.server_close()


def test_crash_resume_drill(tmp_path):
    """tools/fault_drill.py crash_resume wired as a test: SIGKILL a
    replica mid-greedy-stream behind a resume-enabled router → the
    client's text is byte-identical to the solo oracle with finish
    stop/length; a sampled (non-greedy) stream killed the same way
    keeps the honest replica_lost."""
    import os
    import sys

    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        from fault_drill import drill_crash_resume
    finally:
        sys.path.remove(tools)
    model = str(tmp_path / "tiny.m")
    tok = str(tmp_path / "tiny.t")
    write_tiny_model(model)
    write_tiny_tokenizer(tok)
    drill_crash_resume(model, tok)
