"""Full-KV-cache determinism smoke — the macbeth.sh analogue.

The reference's `examples/macbeth.sh:1-6` fills the entire KV cache with a
long prompt and checks the continuation is stable.  Here: generate until
the cache is completely full, twice, and across different on-device chunk
sizes — greedy decode must be bit-stable in all cases, and the engine must
stop exactly at seq_len."""

import jax.numpy as jnp

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.runtime.engine import Engine
from tests.fixtures import run_cli, write_tiny_model, write_tiny_tokenizer


CFG = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
                  vocab_size=128, seq_len=48, dtype=jnp.float32)


def _fill_cache(params, chunk):
    eng = Engine(CFG, params)
    toks = [t for t, _ in eng.generate_stream(
        [1, 7, 13, 29], steps=CFG.seq_len, temperature=0.0, seed=5, chunk=chunk)]
    return toks, eng.pos


def test_full_cache_greedy_stable_across_runs_and_chunkings():
    params = init_params(CFG, seed=11)
    t1, pos1 = _fill_cache(params, chunk=16)
    t2, pos2 = _fill_cache(params, chunk=16)
    assert t1 == t2, "same seed + same chunking must reproduce exactly"
    t3, pos3 = _fill_cache(params, chunk=5)
    assert t1 == t3, "greedy decode must not depend on the chunk size"
    assert len(t1) == CFG.seq_len, "generation must run to a completely full cache"
    # last sampled token was never fed (stream accounting); every cache
    # position before it was
    assert pos1 == pos2 == pos3


def test_full_cache_fixed_seed_sampling_stable():
    """temperature>0 with a fixed seed is one PRNG stream per generation
    (fold_in of the seed key) — identical runs must reproduce exactly."""
    params = init_params(CFG, seed=11)

    def run():
        eng = Engine(CFG, params)
        return [t for t, _ in eng.generate_stream(
            [1, 7, 13, 29], steps=CFG.seq_len, temperature=0.8, topp=0.9,
            seed=123, chunk=8)]

    assert run() == run()


def test_cli_full_context_determinism(tmp_path):
    """Operator-surface version (macbeth.sh contract): the CLI generate
    mode with --temperature 0 over a full context window is reproducible."""
    m = str(tmp_path / "t.m")
    t = str(tmp_path / "t.t")
    write_tiny_model(m, vocab_size=64, seq_len=48)
    write_tiny_tokenizer(t, vocab_size=64)
    args = ["generate", "--model", m, "--tokenizer", t, "--prompt", "hello",
            "--steps", "48", "--temperature", "0", "--seed", "3"]
    r1 = run_cli(args)
    r2 = run_cli(args)
    assert r1.returncode == 0, r1.stderr
    assert r1.stdout == r2.stdout
    assert len(r1.stdout) > 0
