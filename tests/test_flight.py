"""Flight recorder, slot timeline, and SLO engine tests (PR 7 tentpole:
obs/flight.py + obs/slo.py + the /debug/requests | /debug/timeline
endpoints, docs/OBSERVABILITY.md).

The acceptance contract pinned here:

* a streamed request served by the slot scheduler yields a COMPLETE
  flight record under its client-supplied ``X-Request-Id`` — queue wait,
  admit slot, every prefill chunk and decode burst, retire reason, and a
  ``ttft_s`` that agrees exactly with the TTFT histogram (both are fed
  the same observed value);
* ``/debug/requests`` lists recent records newest-first and an unknown
  ID is a 404, not an empty 200;
* ``/debug/timeline`` exposes the per-dispatch slot phases and the
  goodput decomposition, and ``tools/trace_dump.py --slots`` renders one
  named Perfetto track per scheduler slot from it;
* the flight ring evicts oldest-first at capacity and the SLO engine's
  burn-rate math, verdict transitions, and violation counter follow the
  documented multiwindow semantics.
"""

import json
import logging
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from fixtures import REPO, cpu_env, free_port, write_tiny_model, \
    write_tiny_tokenizer

from dllama_tpu.obs import flight as obs_flight, metrics as obs_metrics, \
    slo as obs_slo, trace as obs_trace

pytestmark = pytest.mark.obs


# --- FlightRecorder unit tests (no server, no jax) ------------------------

def test_flight_ring_evicts_oldest_first():
    fr = obs_flight.FlightRecorder(capacity=3)
    for i in range(5):
        fr.submit(f"r{i}", n_prompt=i)
    assert len(fr) == 3
    assert fr.get("r0") is None and fr.get("r1") is None
    assert [r["request_id"] for r in fr.recent(10)] == ["r4", "r3", "r2"]


def test_flight_submit_merges_and_first_retire_reason_wins():
    fr = obs_flight.FlightRecorder(capacity=8)
    fr.submit("a", path="/v1/completions")          # server handler first
    fr.submit("a", n_prompt=7, source="scheduler")  # scheduler merges in
    fr.admit("a", slot=2, queued_ms=1.5)
    fr.phase("a", "prefill_chunk", tokens=4, ms=3.0)
    fr.phase("a", "decode_burst", steps=2, tokens=2, wall_ms=1.0)
    fr.first_token("a", 0.25)
    fr.inter_token("a", 0.01)
    fr.inter_token("a", 0.03)
    fr.retire("a", "length", produced=3)
    fr.retire("a", "served")                        # handler fallback loses
    rec = fr.get("a")
    assert rec["path"] == "/v1/completions" and rec["n_prompt"] == 7
    assert rec["slot"] == 2 and rec["queued_ms"] == 1.5
    assert [p["kind"] for p in rec["phases"]] == ["prefill_chunk",
                                                  "decode_burst"]
    assert rec["finish"] == "length" and rec["produced"] == 3
    assert rec["ttft_s"] == 0.25
    assert rec["itl"]["count"] == 2
    assert rec["itl"]["avg_s"] == pytest.approx(0.02)
    assert rec["itl"]["max_s"] == 0.03
    assert "degrade_base" not in rec  # internal baseline never exposed


def test_flight_reused_id_starts_fresh_record():
    fr = obs_flight.FlightRecorder(capacity=8)
    fr.submit("dup", n_prompt=3)
    fr.retire("dup", "stop", produced=5)
    fr.submit("dup", n_prompt=9)  # client recycled the ID after retire
    rec = fr.get("dup")
    assert "finish" not in rec and rec["n_prompt"] == 9
    assert len(fr) == 1


def test_flight_resize_keeps_most_recent():
    fr = obs_flight.FlightRecorder(capacity=8)
    for i in range(6):
        fr.submit(f"k{i}")
    fr.resize(2)
    assert len(fr) == 2 and fr.get("k5") is not None and fr.get("k4") is not None


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_buffer_env_malformed_warns_once(monkeypatch):
    """Satellite: a malformed DLLAMA_FLIGHT_BUFFER/DLLAMA_TRACE_BUFFER
    warns ONCE per distinct spec and falls back to the default, mirroring
    the DLLAMA_Q40_BLOCK_TILES contract."""
    h = _Capture()
    logger = logging.getLogger("dllama.obs.trace")
    logger.addHandler(h)
    try:
        monkeypatch.setattr(obs_trace, "_warned_specs", set())
        monkeypatch.setenv("DLLAMA_FLIGHT_BUFFER", "banana")
        for _ in range(3):
            assert obs_trace.parse_buffer_env(
                "DLLAMA_FLIGHT_BUFFER",
                obs_flight.DEFAULT_FLIGHT_CAPACITY) == \
                obs_flight.DEFAULT_FLIGHT_CAPACITY
        warns = [r for r in h.records if "DLLAMA_FLIGHT_BUFFER" in
                 r.getMessage()]
        assert len(warns) == 1, [r.getMessage() for r in h.records]
        # a negative capacity is just as malformed
        monkeypatch.setenv("DLLAMA_TRACE_BUFFER", "-5")
        assert obs_trace.parse_buffer_env(
            "DLLAMA_TRACE_BUFFER", obs_trace.DEFAULT_CAPACITY) == \
            obs_trace.DEFAULT_CAPACITY
    finally:
        logger.removeHandler(h)


def test_buffer_env_legacy_alias(monkeypatch):
    monkeypatch.delenv("DLLAMA_TRACE_BUFFER", raising=False)
    monkeypatch.setenv("DLLAMA_TRACE_CAPACITY", "123")
    assert obs_trace.parse_buffer_env(
        "DLLAMA_TRACE_BUFFER", obs_trace.DEFAULT_CAPACITY,
        legacy="DLLAMA_TRACE_CAPACITY") == 123
    monkeypatch.setenv("DLLAMA_TRACE_BUFFER", "456")  # new name wins
    assert obs_trace.parse_buffer_env(
        "DLLAMA_TRACE_BUFFER", obs_trace.DEFAULT_CAPACITY,
        legacy="DLLAMA_TRACE_CAPACITY") == 456


# --- SLO engine unit tests (no server, no jax) ----------------------------

@pytest.mark.parametrize("spec", [
    "", "ttft_p95", "nonsense_p95=100ms", "ttft_p95=purple",
    "ttft_p0=100ms", "ttft_p100=100ms", "error_rate=150%",
    "ttft_p95=100ms,ttft_p95=200ms",
])
def test_slo_parse_rejects_bad_specs(spec):
    with pytest.raises(ValueError):
        obs_slo.parse_slo(spec)


def test_slo_parse_grammar():
    objs = obs_slo.parse_slo("ttft_p95=1500ms,itl_p99=0.12s,error_rate=0.5%")
    by_key = {o.key: o for o in objs}
    assert by_key["ttft_p95"].allowed == pytest.approx(0.05)
    assert by_key["ttft_p95"].threshold == pytest.approx(1.5)
    # thresholds resolve to the next bucket boundary at or above target
    assert by_key["ttft_p95"].boundary == 2.5
    assert by_key["itl_p99"].threshold == pytest.approx(0.12)
    assert by_key["itl_p99"].boundary == 0.25
    assert by_key["error_rate"].allowed == pytest.approx(0.005)
    assert obs_slo.parse_windows("1h,5m") == [("5m", 300.0), ("1h", 3600.0)]
    with pytest.raises(ValueError):
        obs_slo.parse_windows("5parsecs")


def test_slo_burn_verdicts_and_violation_transitions():
    """Multiwindow burn math on a private histogram with injected time:
    violating needs ALL windows burning; the violations counter bumps on
    the TRANSITION into violating only; recovery walks back through
    at-risk to ok as the bad observations age out of the windows."""
    h = obs_metrics.Histogram("t_slo_lat", "t_slo_lat", (0.1, 1.0))
    obj = obs_slo.Objective("uttft_p90", kind="latency", allowed=0.1,
                            target_display="500ms", hist=h, threshold=0.5)
    assert obj.boundary == 1.0
    eng = obs_slo.SloEngine([obj], obs_slo.parse_windows("10s,100s"))
    t = 1000.0
    assert eng.evaluate(now=t)["status"] == "ok"  # no traffic yet

    for _ in range(10):
        h.observe(2.0)  # every request blows the 1.0s boundary
    res = eng.evaluate(now=t + 1)
    burns = res["objectives"]["uttft_p90"]["burn"]
    assert burns == {"10s": 10.0, "100s": 10.0}  # (10/10)/0.1
    assert res["status"] == "violating"
    viol = obs_metrics.SLO_VIOLATIONS.json_value().get("uttft_p90", 0)
    assert viol >= 1
    assert eng.evaluate(now=t + 2)["status"] == "violating"
    # still violating: the counter must NOT bump again
    assert obs_metrics.SLO_VIOLATIONS.json_value()["uttft_p90"] == viol
    # gauges carry the per-window burns
    assert obs_metrics.SLO_BURN_RATE.get("uttft_p90", "10s") >= 1.0

    for _ in range(5):
        h.observe(0.05)  # recovery traffic, all good
    res = eng.evaluate(now=t + 15)
    burns = res["objectives"]["uttft_p90"]["burn"]
    # short window sees only the clean tail; long window still burns
    assert burns["10s"] == 0.0 and burns["100s"] >= 1.0
    assert res["status"] == "at_risk"

    for _ in range(95):
        h.observe(0.05)
    res = eng.evaluate(now=t + 16)
    assert res["status"] == "ok"
    assert obs_metrics.SLO_VIOLATIONS.json_value()["uttft_p90"] == viol


def test_slo_summary_line_names_every_objective():
    h = obs_metrics.Histogram("t_slo_sum", "t_slo_sum", (0.1, 1.0))
    obj = obs_slo.Objective("usum_p90", kind="latency", allowed=0.1,
                            target_display="500ms", hist=h, threshold=0.5)
    line = obs_slo.SloEngine(
        [obj], obs_slo.parse_windows("10s,100s")).summary_line()
    assert "slo:" in line and "usum_p90<=500ms" in line
    assert "10s/100s" in line


# --- end-to-end: scheduler-served streamed request over HTTP --------------

@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("flight")
    m, t = str(d / "tiny.m"), str(d / "tiny.t")
    write_tiny_model(m)
    write_tiny_tokenizer(t)
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu.server.api", "--model", m,
         "--tokenizer", t, "--port", str(port), "--temperature", "0",
         "--max-seq-len", "128", "--batch-slots", "2",
         "--slo", "ttft_p95=30s,error_rate=1%"],
        cwd=REPO, env=cpu_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    base = f"http://127.0.0.1:{port}"
    for _ in range(600):
        if proc.poll() is not None:
            raise RuntimeError(f"server died:\n{proc.stdout.read()}")
        try:
            urllib.request.urlopen(base + "/health", timeout=1)
            break
        except OSError:
            time.sleep(0.2)
    else:
        proc.kill()
        raise RuntimeError("server did not come up")
    yield base
    proc.kill()
    proc.wait()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def _post(base, path, body, headers=None, timeout=240):
    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


def test_streamed_scheduler_request_full_flight_record(server):
    """Acceptance: one streamed request through the slot scheduler →
    /debug/requests/<id> holds every lifecycle phase, and the record's
    ttft_s/itl agree with the latency histograms (same observed values
    by construction)."""
    rid = "flight-stream-1"
    before = _get(server, "/metrics")
    with _post(server, "/v1/completions",
               {"prompt": "hello flight", "max_tokens": 8, "stream": True},
               headers={"X-Request-Id": rid}) as r:
        assert r.headers.get("X-Request-Id") == rid
        raw = r.read()
    assert b"[DONE]" in raw
    after = _get(server, "/metrics")

    rec = _get(server, f"/debug/requests/{rid}")
    assert rec["request_id"] == rid
    assert rec["path"] == "/v1/completions"
    assert rec["n_prompt"] >= 1 and rec["max_new"] == 8
    assert rec["source"] == "scheduler"
    assert isinstance(rec["slot"], int) and rec["queued_ms"] >= 0
    kinds = [p["kind"] for p in rec["phases"]]
    assert "prefill_chunk" in kinds and "decode_burst" in kinds
    assert kinds[0] == "prefill_chunk"  # prompt is fed before decode
    pre = [p for p in rec["phases"] if p["kind"] == "prefill_chunk"]
    assert sum(p["tokens"] for p in pre) == rec["n_prompt"]
    for p in rec["phases"]:
        assert (p.get("ms") or p.get("wall_ms")) >= 0
    bursts = [p for p in rec["phases"] if p["kind"] == "decode_burst"]
    emitted = sum(p["emitted"] for p in pre) + \
        sum(p["tokens"] for p in bursts)
    assert emitted == rec["produced"] >= 1
    assert rec["finish"] in ("length", "stop")
    assert "degraded" in rec and isinstance(rec["degrade_events"], dict)
    assert rec["duration_ms"] > 0

    # TTFT / ITL agreement with the histograms: the record stores the
    # exact values the serving layer observed
    d_ttft = after["ttft_seconds"]["sum"] - before["ttft_seconds"]["sum"]
    assert after["ttft_seconds"]["count"] - \
        before["ttft_seconds"]["count"] == 1
    assert rec["ttft_s"] == pytest.approx(d_ttft, abs=5e-6)
    d_itl = after["inter_token_seconds"]["sum"] - \
        before["inter_token_seconds"]["sum"]
    d_itl_n = after["inter_token_seconds"]["count"] - \
        before["inter_token_seconds"]["count"]
    assert rec["itl"]["count"] == d_itl_n >= 1
    assert rec["itl"]["sum_s"] == pytest.approx(d_itl, abs=5e-6)


def test_debug_requests_listing_and_unknown_404(server):
    rid = "flight-list-1"
    with _post(server, "/v1/completions",
               {"prompt": "hi", "max_tokens": 3},
               headers={"X-Request-Id": rid}) as r:
        json.loads(r.read())
    listing = _get(server, "/debug/requests")["requests"]
    assert any(e["request_id"] == rid for e in listing)
    assert listing[0]["request_id"] == rid  # newest first
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/debug/requests/no-such-request")
    assert ei.value.code == 404


def test_timeline_endpoint_phases_and_goodput(server):
    tl = _get(server, "/debug/timeline")
    assert tl["slots"] == 2
    assert tl["steps"], "scheduler traffic must populate the timeline"
    for step in tl["steps"]:
        assert step["wall_ms"] >= 0 and step["steps"] >= 1
        assert len(step["slots"]) == 2
        for s in step["slots"]:
            assert s["phase"] in ("prefill", "decode", "pad")
            if s["phase"] != "pad":
                assert s["request_id"]
    comp = tl["components_ms"]
    assert set(comp) <= {"prefill", "decode", "pad", "host_gap", "idle"}
    assert comp.get("prefill", 0) > 0 and comp.get("decode", 0) > 0
    assert 0 < tl["goodput_ratio"] <= 1


def test_health_slo_verdict_block(server):
    h = _get(server, "/health")
    assert h["slo"] is not None
    assert h["slo"]["status"] in ("ok", "at_risk", "violating")
    assert "ttft_p95" in h["slo"]["objectives"]
    assert "error_rate" in h["slo"]["objectives"]
    assert set(h["slo"]["windows"]) == {"5m", "1h"}


def test_trace_dump_slots_emits_named_track_per_slot(server, tmp_path):
    """Acceptance: the Perfetto export grows one NAMED track per
    scheduler slot, with events named by that slot's per-dispatch
    phase."""
    out = str(tmp_path / "trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_dump.py"),
         server, "-o", out, "--slots"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "goodput" in r.stdout
    with open(out) as f:
        doc = json.load(f)
    names = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e.get("pid") == 2
             and e["name"] == "thread_name"}
    assert names == {"slot 0", "slot 1"}
    phases = {e["name"] for e in doc["traceEvents"]
              if e.get("ph") == "X" and e.get("pid") == 2}
    assert phases & {"prefill", "decode"}
    # request spans (pid 1) and slot tracks (pid 2) share one file
    assert any(e.get("pid") == 1 and e.get("ph") == "X"
               for e in doc["traceEvents"])
