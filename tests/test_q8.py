"""Fused Q80 weight path (ops/q8.py) — reference ftype-dispatch parity.

The reference's matmul dispatches on the weight file type, with Q80 a
first-class production kernel (funcs.cpp:268-285, 414-455).  These tests
cover the packed Q80 twin of the Q40 suite: codec parity with the file
bytes, kernel-vs-XLA equality (plain, stacked view, padded n), loader
integration (Q80 `.m` → packed planes, no dense transit), and model-level
equivalence against the dense-load path.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu import quants
from dllama_tpu.io import mfile
from dllama_tpu.models.params import load_params
from dllama_tpu.ops import q40, q8
from fixtures import write_tiny_model


class TestCodec:
    def test_quantize_matches_file_codec(self):
        """q8.quantize must agree with the byte codec the files use
        (quants.quantize_q80) — same deltas, same int8 values."""
        rng = np.random.RandomState(0)
        w = (rng.randn(64, 8) * 0.3).astype(np.float32)
        qt = q8.quantize(w)
        # file codec quantizes row-major flat; our planes are (n, d) —
        # compare via dequantized values instead of byte order
        file_rt = quants.dequantize_q80(
            np.frombuffer(quants.quantize_tensor(w.T, quants.Q80), np.uint8),
            w.size).reshape(w.T.shape).T
        ours = np.asarray(q8.dequantize(qt, jnp.float32))
        np.testing.assert_allclose(ours, file_rt, rtol=0, atol=1e-6)

    def test_inf_scale_rejected(self):
        w = np.full((32, 4), 1e7, np.float32)  # delta 1e7/127 > f16 max
        with pytest.raises(ValueError, match="overflow"):
            q8.quantize(w)

    def test_file_bytes_roundtrip_through_planes(self):
        """repack_file_bytes_into must place every block where dequantize
        expects it (transpose correctness on random data)."""
        rng = np.random.RandomState(1)
        d, n = 6, 96
        w = (rng.randn(d, n) * 0.2).astype(np.float32)
        raw = np.frombuffer(quants.quantize_tensor(w, quants.Q80), np.uint8)
        np_ = q40.padded_n(n)
        qv = np.zeros((np_, d), np.int8)
        sc = np.zeros((np_ // 32, d), np.float16)
        q8.repack_file_bytes_into(raw, d, n, qv, sc)
        qt = q8.Q8Tensor(jnp.asarray(qv), jnp.asarray(sc.view(np.uint16)), (n, d))
        expect = quants.dequantize_q80(raw, n * d).reshape(d, n).T
        np.testing.assert_allclose(
            np.asarray(q8.dequantize(qt, jnp.float32)), expect, rtol=0, atol=1e-6)


class TestKernel:
    def test_interpret_matches_xla(self):
        rng = np.random.RandomState(2)
        qt = q8.quantize((rng.randn(512, 128) * 0.1).astype(np.float32))
        x = jnp.asarray((rng.randn(3, 512)).astype(np.float32))
        a = np.asarray(q8.matmul(x, qt, impl="pallas_interpret"))
        b = np.asarray(q8.matmul(x, qt, impl="xla"))
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-5 * np.abs(b).max())

    def test_stacked_view_selects_layer(self):
        rng = np.random.RandomState(3)
        ws = (rng.randn(4, 512, 64) * 0.1).astype(np.float32)
        qs = q8.quantize(ws)
        x = jnp.asarray((rng.randn(1, 512)).astype(np.float32))
        for l in (0, 2, 3):
            view = q40.QLayerView(qs, jnp.int32(l))
            got = np.asarray(q8.matmul(x, view, impl="pallas_interpret"))
            ref = np.asarray(q8.matmul(x, q8.quantize(ws[l]), impl="xla"))
            np.testing.assert_allclose(got, ref, rtol=0,
                                       atol=1e-5 * np.abs(ref).max(), err_msg=f"l={l}")

    def test_mm_dispatches_q8(self):
        rng = np.random.RandomState(4)
        qt = q8.quantize((rng.randn(64, 32) * 0.1).astype(np.float32))
        x = jnp.asarray(rng.randn(1, 64).astype(np.float32))
        out = q40.mm(x, qt, impl="xla")
        ref = np.asarray(x) @ np.asarray(q8.dequantize(qt, jnp.float32))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=0,
                                   atol=1e-2 * np.abs(ref).max())


class TestLoader:
    def test_q80_mfile_loads_packed_and_matches_dense(self, tmp_path):
        path = str(tmp_path / "toy-q80.m")
        write_tiny_model(path, ftype=quants.Q80, vocab_size=64, seq_len=32)
        mf = mfile.MFile(path)
        cfg_q, qparams = load_params(mf, keep_quantized=True)
        for k in ("wqkv", "wo", "w13", "w2", "wcls"):
            assert isinstance(qparams[k], q8.Q8Tensor), k
        assert qparams["wqkv"].qpacked.dtype == jnp.int8

        from dllama_tpu.models.transformer import forward, init_kv_cache
        cfg_d, dparams = load_params(mf, keep_quantized=False)
        tokens = jnp.asarray([[1, 9, 33, 7]], jnp.int32)
        lq, _ = forward(qparams, cfg_q.with_(quant_impl="xla"), tokens,
                        init_kv_cache(cfg_q, 1), jnp.int32(0))
        ld, _ = forward(dparams, cfg_d, tokens, init_kv_cache(cfg_d, 1), jnp.int32(0))
        np.testing.assert_allclose(
            np.asarray(lq), np.asarray(ld), rtol=0,
            atol=1e-3 + 1e-3 * np.abs(np.asarray(ld)).max())

    def test_q80_moe_experts_load_packed(self, tmp_path):
        path = str(tmp_path / "toy-q80-moe.m")
        write_tiny_model(path, arch=mfile.ARCH_MIXTRAL, ftype=quants.Q80,
                         n_experts=4, vocab_size=64, seq_len=32)
        cfg_q, qparams = load_params(mfile.MFile(path), keep_quantized=True)
        for k in ("up", "gate", "down"):
            assert isinstance(qparams[k], q8.Q8Tensor), k

        from dllama_tpu.runtime.engine import Engine
        from dllama_tpu.sampling import Sampler
        eng = Engine(cfg_q.with_(quant_impl="xla"), qparams)
        toks = [t for t, _ in eng.generate([1, 5, 9], steps=6,
                                           sampler=Sampler(cfg_q.vocab_size, 0.0, 0.9, 0))]
        assert len(toks) == 6
