"""Kernel-dispatch ledger + compile/memory telemetry + live profiling
(docs/OBSERVABILITY.md; obs/dispatch.py).

The acceptance contract this file pins: NO silent degrade path remains —
every Pallas/blocked/shard fallback in q40/q8 lands in a labeled registry
counter and a structured log record, and an injected degrade is visible
in ``/metrics`` (JSON and Prometheus), ``/health``, and the end-of-run
CLI summary in the SAME test.  Plus: recompiles vs executable-cache hits
are counted per engine step family, and ``POST /debug/profile`` answers
a well-formed per-op report or a clean 503.
"""

import json
import logging
import os
import re
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from fixtures import REPO, cpu_env, free_port, run_cli, write_tiny_model, \
    write_tiny_tokenizer

from dllama_tpu import quants
from dllama_tpu.obs import dispatch as obs_dispatch, metrics as obs_metrics
from dllama_tpu.ops import q40

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_ledger():
    """Each test sees a fresh ledger (the module state is process-global)."""
    obs_dispatch.reset()
    yield
    obs_dispatch.reset()


# --- unit: labeled registry types -----------------------------------------

def test_labeled_counter_json_and_prometheus():
    from dllama_tpu.obs.metrics import Registry
    reg = Registry()
    c = reg.labeled_counter("widget_events", ("kind", "path"), "help")
    c.inc("a", "x")
    c.inc("a", "x", n=2)
    c.inc("b", "y")
    assert c.name == "dllama_widget_events_total"
    assert c.get("a", "x") == 3 and c.get("b", "y") == 1
    assert c.get("never", "seen") == 0
    assert c.total == 4
    assert c.json_value() == {"a/x": 3, "b/y": 1}
    lines = []
    c.render(lines)
    text = "\n".join(lines)
    assert 'dllama_widget_events_total{kind="a",path="x"} 3' in text
    assert 'dllama_widget_events_total{kind="b",path="y"} 1' in text
    with pytest.raises(ValueError):
        c.inc("only-one-label-value")
    c.reset()
    assert c.total == 0 and c.json_value() == {}


def test_labeled_gauge_fn_and_graceful_absence():
    from dllama_tpu.obs.metrics import Registry
    reg = Registry()
    g = reg.labeled_gauge("widget_bytes", "device",
                          fn=lambda: {"0": 5.0, "1": 7.0})
    def rendered():
        lines = []
        g.render(lines)
        return "\n".join(lines)

    assert g.values() == {"0": 5.0, "1": 7.0}
    assert 'dllama_widget_bytes{device="0"} 5' in rendered()
    # a reader that explodes reads as ABSENT (no samples), never as zeros
    g.fn = lambda: 1 / 0
    assert g.values() == {} and g.json_value() == {}
    assert "widget_bytes{" not in rendered()


# --- satellite: DLLAMA_Q40_BLOCK_TILES lazy validated parse ---------------

def test_block_tiles_env_valid_and_default(monkeypatch):
    monkeypatch.delenv("DLLAMA_Q40_BLOCK_TILES", raising=False)
    assert q40.blocked_tiles_env() == q40.DEFAULT_BLOCKED_TILES
    monkeypatch.setenv("DLLAMA_Q40_BLOCK_TILES", "256,1024")
    assert q40.blocked_tiles_env() == (256, 1024)
    assert obs_dispatch.degraded() is False


@pytest.mark.parametrize("bad", ["banana", "512", "0,2048", "512,-1",
                                 "512,2048,64"])
def test_block_tiles_env_malformed_falls_back(monkeypatch, bad):
    monkeypatch.setenv("DLLAMA_Q40_BLOCK_TILES", bad)
    before = obs_metrics.Q40_DEGRADE.get("bad_block_tiles_env")
    assert q40.blocked_tiles_env() == q40.DEFAULT_BLOCKED_TILES
    assert obs_metrics.Q40_DEGRADE.get("bad_block_tiles_env") == before + 1
    assert obs_dispatch.degraded() is True
    assert "q40:bad_block_tiles_env" in obs_dispatch.reasons()


def test_degrade_logs_once_but_counts_every_occurrence(monkeypatch):
    records = []
    h = logging.Handler()
    h.emit = lambda r: records.append(r)
    lg = logging.getLogger("dllama.obs.dispatch")
    lg.addHandler(h)
    old = lg.level
    lg.setLevel(logging.DEBUG)
    try:
        monkeypatch.setenv("DLLAMA_Q40_BLOCK_TILES", "nope")
        for _ in range(3):
            q40.blocked_tiles_env()
    finally:
        lg.removeHandler(h)
        lg.setLevel(old)
    warned = [r for r in records if r.getMessage() == "kernel_degrade"]
    assert len(warned) == 1, "warn-once per (codec, reason, warn_key)"
    assert obs_metrics.Q40_DEGRADE.get("bad_block_tiles_env") == 3


# --- tentpole: forced-pallas blocked guards (real degrades) ---------------

def _blocked_fixture(n, d, seed=0):
    rng = np.random.RandomState(seed)
    qt = q40.quantize((rng.randn(n, d) * 0.05).astype(np.float32))
    return qt, q40.to_blocked(qt)


def test_forced_pallas_illegal_tiles_degrades_correctly():
    """tn clamps below 256 on a tiny shape → Mosaic-illegal; forced pallas
    must degrade through the ledger and still return the right numbers."""
    import jax.numpy as jnp
    qt, bqt = _blocked_fixture(128, 256)
    assert bqt.tiles[0] < 256  # the premise: clamped-down, kernel-illegal
    x = jnp.asarray(np.random.RandomState(1).randn(2, 128), jnp.float32)
    before = obs_metrics.Q40_DEGRADE.get("blocked_tiles_illegal")
    out = q40.matmul(x, bqt, impl="pallas")
    assert obs_metrics.Q40_DEGRADE.get("blocked_tiles_illegal") == before + 1
    assert obs_dispatch.degraded() is True
    ref = x.astype(jnp.bfloat16) @ q40.dequantize(qt, jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_forced_pallas_blocked_rows_over_cap_degrades():
    """Satellite: legal blocked tiles but rows > PALLAS_MAX_ROWS (a
    forced-pallas prefill) must mirror the auto-dispatch rows cap instead
    of a Mosaic lowering failure mid-forward."""
    import jax.numpy as jnp
    qt, bqt = _blocked_fixture(512, 256)
    assert q40._blocked_tiles_ok(bqt)  # the premise: tiles are legal
    rows = q40.PALLAS_MAX_ROWS + 1
    x = jnp.asarray(np.random.RandomState(2).randn(rows, 512), jnp.float32)
    before = obs_metrics.Q40_DEGRADE.get("rows_exceed_pallas_max")
    out = q40.matmul(x, bqt, impl="pallas")
    assert obs_metrics.Q40_DEGRADE.get("rows_exceed_pallas_max") == before + 1
    assert out.shape == (rows, 256)
    ref = x.astype(jnp.bfloat16) @ q40.dequantize(qt, jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_dispatch_paths_recorded():
    """Every resolved dispatch lands in the labeled matmul_dispatch family
    (auto on CPU resolves to xla-dequant)."""
    import jax.numpy as jnp
    qt, _ = _blocked_fixture(128, 256)
    x = jnp.ones((1, 128), jnp.float32)
    before = obs_metrics.MATMUL_DISPATCH.get("q40", "xla-dequant")
    q40.matmul(x, qt)  # impl="auto"; CPU → xla-dequant
    assert obs_metrics.MATMUL_DISPATCH.get("q40", "xla-dequant") == before + 1
    assert obs_dispatch.dispatches().get("q40/xla-dequant", 0) >= 1
    assert obs_dispatch.degraded() is False  # a fallback by policy, not a degrade
    assert "q40/xla-dequant" in obs_dispatch.summary_line()


def test_engine_init_degrades_share_ledger_treatment(monkeypatch):
    """The two engine-construction degrades — blocked layout silently
    kept row-major on a mesh (``blocked_ignored_mesh``), and off-TPU tp
    collectives falling back to plain psum (``tp_psum``) — take the
    identical ledger path: labeled counter + degraded flag + warn-once
    structured record, never scrollback."""
    import jax
    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine

    cfg = tiny_config()
    records = []
    h = logging.Handler()
    h.emit = lambda r: records.append(r)
    lg = logging.getLogger("dllama.obs.dispatch")
    lg.addHandler(h)
    old = lg.level
    lg.setLevel(logging.DEBUG)
    try:
        monkeypatch.setenv("DLLAMA_Q40_LAYOUT", "blocked")
        # one tp=2 engine on CPU trips both: blocked storage is ignored
        # on any mesh, and tp collectives have no RDMA ring off-TPU
        for _ in range(2):
            Engine(cfg, init_params(cfg, seed=4),
                   mesh=make_mesh(tp=2, devices=jax.devices()[:2]))
    finally:
        lg.removeHandler(h)
        lg.setLevel(old)
    assert obs_dispatch.degraded() is True
    for reason in ("blocked_ignored_mesh", "tp_psum"):
        assert obs_metrics.Q40_DEGRADE.get(reason) == 2, reason
        assert obs_dispatch.reasons().get(f"q40:{reason}") == 2, reason
    warned = [r.__dict__["reason"] for r in records
              if r.getMessage() == "kernel_degrade"]
    assert sorted(warned) == ["blocked_ignored_mesh", "tp_psum"], \
        "one structured record per degrade site, not per engine"


# --- tentpole: engine compile telemetry -----------------------------------

@pytest.fixture(scope="module")
def engine():
    import jax
    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    cfg = tiny_config(seq_len=128, vocab_size=300)
    return Engine(cfg, init_params(cfg, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]))


def test_recompile_vs_cache_hit_counting(engine):
    """A fresh step shape is a recompile (observed into the compile-seconds
    histogram); repeating it is a cache hit; the live-executable gauge
    tracks what the engine holds."""
    engine.reset()
    rc0 = obs_metrics.ENGINE_RECOMPILES.value
    ch0 = obs_metrics.ENGINE_CACHE_HITS.value
    hist0 = obs_metrics.ENGINE_COMPILE_S.count
    engine.prefill([1, 2, 3])          # bucket T=16 — may be warm from
    rc1 = obs_metrics.ENGINE_RECOMPILES.value       # earlier module tests
    engine.decode_one(5)               # T=1
    rc2 = obs_metrics.ENGINE_RECOMPILES.value
    ch2 = obs_metrics.ENGINE_CACHE_HITS.value
    engine.decode_one(6)               # T=1 again → pure cache hit
    assert obs_metrics.ENGINE_RECOMPILES.value == rc2
    assert obs_metrics.ENGINE_CACHE_HITS.value == ch2 + 1
    # every recompile observed a first-call wall into the histogram
    assert (obs_metrics.ENGINE_COMPILE_S.count - hist0
            == obs_metrics.ENGINE_RECOMPILES.value - rc0)
    # the gauge equals what this engine holds (step shapes + chunk fns)
    assert obs_metrics.ENGINE_LIVE_EXECUTABLES.value == \
        len(engine._compiled_steps) + len(engine._chunk_fns)
    assert obs_metrics.ENGINE_CACHE_HITS.value > ch0
    assert rc1 >= rc0


def test_chunk_fn_cache_hits(engine):
    engine.reset()
    rc0 = obs_metrics.ENGINE_RECOMPILES.value
    list(engine.generate_stream([1, 2, 3], 8, chunk=4, seed=0))
    rc1 = obs_metrics.ENGINE_RECOMPILES.value
    ch1 = obs_metrics.ENGINE_CACHE_HITS.value
    engine.reset()
    list(engine.generate_stream([1, 2, 3], 8, chunk=4, seed=0))
    # second identical run compiles nothing new and hits the caches
    assert obs_metrics.ENGINE_RECOMPILES.value == rc1
    assert obs_metrics.ENGINE_CACHE_HITS.value > ch1
    assert rc1 > rc0  # the first run did build chunk executables


# --- tentpole: HBM gauges --------------------------------------------------

def test_hbm_gauges_graceful_on_cpu(engine):
    """CPU backends expose no allocator stats: the gauges read as ABSENT
    (empty family, no Prometheus samples), never as fabricated zeros."""
    vals = obs_metrics.HBM_BYTES_IN_USE.values()
    assert isinstance(vals, dict)
    for v in vals.values():     # populated only where memory_stats exists
        assert v >= 0
    if not vals:
        lines = []
        obs_metrics.HBM_BYTES_IN_USE.render(lines)
        assert not any("dllama_hbm_bytes_in_use{" in ln for ln in lines)


# --- acceptance: one injected degrade, visible EVERYWHERE -----------------

@pytest.fixture
def api(engine, tmp_path):
    from dllama_tpu.server.api import ApiState, serve
    from dllama_tpu.tokenizer.bpe import Tokenizer
    tok = Tokenizer(write_tiny_tokenizer(str(tmp_path / "tok.t")))
    state = ApiState(engine, tok, default_temperature=0.0, chunk=2)
    srv = serve(state, host="127.0.0.1", port=free_port(), block=False)
    yield state, f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


def _get(base, path, accept=None):
    req = urllib.request.Request(base + path,
                                 headers={"Accept": accept} if accept else {})
    with urllib.request.urlopen(req, timeout=30) as r:
        return r.status, r.read()


def test_degrade_visible_in_metrics_health_and_summary(api):
    """THE acceptance test: one real injected degrade (forced-pallas on
    Mosaic-illegal blocked tiles) must show up in /metrics JSON, /metrics
    Prometheus, /health, and the end-of-run CLI summary line — in this
    one test."""
    import jax.numpy as jnp
    _, base = api
    _, bqt = _blocked_fixture(128, 256)
    q40.matmul(jnp.ones((1, 128), jnp.float32), bqt, impl="pallas")

    code, raw = _get(base, "/metrics")
    j = json.loads(raw)
    assert code == 200
    assert j["q40_degrade"].get("blocked_tiles_illegal", 0) >= 1
    assert any(k.startswith("q40/") for k in j["matmul_dispatch"])

    code, raw = _get(base, "/metrics?format=prometheus")
    text = raw.decode()
    m = re.search(r'dllama_q40_degrade_total\{reason="blocked_tiles_'
                  r'illegal"\} (\d+)', text)
    assert m and int(m.group(1)) >= 1
    assert "# TYPE dllama_q40_degrade_total counter" in text
    assert re.search(r'dllama_matmul_dispatch_total\{codec="q40",'
                     r'path="[a-z-]+"\} \d+', text)

    code, raw = _get(base, "/health")
    h = json.loads(raw)
    assert code == 200 and h["degraded"] is True
    assert h["degrade_reasons"].get("q40:blocked_tiles_illegal", 0) >= 1

    line = obs_dispatch.summary_line()   # what cmd_inference prints last
    assert "DEGRADED" in line and "q40:blocked_tiles_illegal" in line


def test_clean_run_reads_clean(api):
    import jax.numpy as jnp
    _, base = api
    qt, _ = _blocked_fixture(128, 256)
    q40.matmul(jnp.ones((1, 128), jnp.float32), qt)  # auto → xla, no degrade
    _, raw = _get(base, "/health")
    h = json.loads(raw)
    assert h["degraded"] is False and h["degrade_reasons"] == {}
    assert obs_dispatch.summary_line().startswith("💡 kernel dispatch: clean")


# --- tentpole: POST /debug/profile ----------------------------------------

def test_debug_profile_well_formed_or_clean_503(api):
    _, base = api
    req = urllib.request.Request(base + "/debug/profile?steps=2&top=4",
                                 data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=240) as r:
            code, body = r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        code, body = e.code, json.loads(e.read())
    if code == 503:
        assert "unavailable" in body["error"]
        return
    assert code == 200
    assert body["steps"] == 2 and body["devices"] >= 1
    assert body["compute_ms"] >= 0 and body["collective_ms"] >= 0
    assert 0 <= body["collective_pct"] <= 100
    assert 1 <= len(body["ops"]) <= 4
    for op in body["ops"]:
        assert op["op"] and op["ms"] >= 0
    # ms sorted descending — the top-K contract
    ms = [op["ms"] for op in body["ops"]]
    assert ms == sorted(ms, reverse=True)


def test_debug_profile_rejected_while_draining(api):
    state, base = api
    state.draining = True
    try:
        req = urllib.request.Request(base + "/debug/profile",
                                     data=b"", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 503
    finally:
        state.draining = False


def test_debug_profile_restores_engine_position(api):
    state, base = api
    eng = state.engine
    eng.reset()
    eng.prefill([1, 2, 3])
    pos0 = eng.pos
    req = urllib.request.Request(base + "/debug/profile?steps=1",
                                 data=b"", method="POST")
    try:
        urllib.request.urlopen(req, timeout=240)
    except urllib.error.HTTPError:
        pass  # 503 without xplane tooling — position must STILL be intact
    assert eng.pos == pos0


# --- CLI: end-of-run summary (subprocess, real degrade) -------------------

def test_cli_inference_prints_degraded_summary(tmp_path):
    """`dllama inference` over a Q40 model with a malformed
    DLLAMA_Q40_BLOCK_TILES must run to completion on the fallback tiles
    AND say DEGRADED in its end-of-run dispatch summary."""
    m = str(tmp_path / "m.m")
    t = str(tmp_path / "m.t")
    write_tiny_model(m, ftype=quants.Q40)
    write_tiny_tokenizer(t)
    r = run_cli(["inference", "--model", m, "--tokenizer", t,
                 "--prompt", "hello", "--steps", "4", "--max-seq-len", "64"],
                env={"DLLAMA_Q40_LAYOUT": "blocked",
                     "DLLAMA_Q40_BLOCK_TILES": "banana"})
    assert r.returncode == 0, r.stderr[-2000:]
    assert "kernel dispatch: DEGRADED" in r.stdout
    assert "q40:bad_block_tiles_env" in r.stdout


@pytest.mark.slow
def test_cli_inference_clean_summary(tmp_path):
    m = str(tmp_path / "m.m")
    t = str(tmp_path / "m.t")
    write_tiny_model(m, ftype=quants.Q80)
    write_tiny_tokenizer(t)
    r = run_cli(["inference", "--model", m, "--tokenizer", t,
                 "--prompt", "hello", "--steps", "4", "--max-seq-len", "64"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "kernel dispatch: clean" in r.stdout
    assert "DEGRADED" not in r.stdout


# --- one-dispatch decode: steady pure-decode family count ----------------

def test_steady_decode_dispatch_families(monkeypatch):
    """The one-dispatch-decode contract (docs/PERF.md): the ledger
    records once per compiled call site at trace time, so the distinct
    matmul (``q40/``/``q8/``) + attention (``kv_``) families of one
    steady pure-decode trace ARE the per-step device dispatch count.
    Fused (interpret mode on CPU): ≤ 2 — one matmul family plus
    ``paged-fused``.  Unfused gather arm: ≥ 3.  Sampled rows add
    ``sample/sample-dev`` (on-device, excluded from the count)."""
    import jax
    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine

    cfg = tiny_config(seq_len=64)
    eng = Engine(cfg, init_params(cfg, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                 batch=2, kv_pages=17, kv_page_size=8)
    ptab = np.asarray([[1, 2], [3, 4]], np.int32)

    def trace_families(mode, greedy):
        # each (mode, greedy) pair is a fresh engine compile key, so the
        # slot_step below traces (and records) rather than hitting cache
        monkeypatch.setenv("DLLAMA_FUSED_ATTN", mode)
        obs_dispatch.reset()
        temps = np.zeros(2, np.float32) if greedy \
            else np.full(2, 0.8, np.float32)
        eng.slot_step(np.ones((2, 1), np.int32),
                      np.asarray([9, 9], np.int32), np.ones(2, np.int32),
                      temps_np=temps,
                      topps_np=np.full(2, 0.9, np.float32),
                      page_tables_np=ptab)
        d = obs_dispatch.dispatches()
        return {k for k in d if k.startswith(("q40/", "q8/", "kv_"))}, d

    fused, d = trace_families("interp", greedy=True)
    assert len(fused) <= 2, f"fused steady decode traced {sorted(fused)}"
    attn_fused = {k for k in fused if k.startswith("kv_")}
    assert attn_fused == {"kv_dense/paged-fused"}
    assert "sample/sample-dev" not in d  # greedy consumes no coin

    # the weight-matmul family records inside q40's own dispatch site, so
    # it may already be warm in this process — the attention side is what
    # the fused kernel collapses: 1 family vs the gather arm's 2 (3 for
    # int8 pools, whose dequant rides a third record).  1 matmul + these
    # is the ≤2-vs-≥3 per-step contract docs/PERF.md states; bench stage
    # cpu-tiny-fused4 measures it cold-process.
    unfused, _ = trace_families("off", greedy=True)
    attn_unfused = {k for k in unfused if k.startswith("kv_")}
    assert attn_unfused == {"kv_dense/paged-gather", "kv_dense/attn-score"}

    sampled, d = trace_families("interp", greedy=False)
    assert {k for k in sampled if k.startswith("kv_")} == \
        {"kv_dense/paged-fused"}
    assert len(sampled) <= 2
    assert d.get("sample/sample-dev", 0) >= 1  # sampling stayed on device
    assert obs_dispatch.degraded() is False  # interp is a mode, not a degrade


# --- satellite: fast tier keeps its non-trivial core ----------------------

def test_fast_tier_collects_core_suites():
    """Meta-test: `-m 'not slow'` must keep collecting a non-trivial core —
    the codec tests, the N-shard≡1-shard parity tests, and this ledger
    file.  Guards against a slow-marker sweep quietly emptying tier 1."""
    targets = ["tests/test_quants.py", "tests/test_parallel.py",
               "tests/test_dispatch_ledger.py"]
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-m", "not slow", "-p", "no:cacheprovider", *targets],
        cwd=REPO, env=cpu_env(), capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    for f in targets:
        n = len(re.findall(re.escape(f) + r"::", r.stdout))
        assert n >= 3, f"fast tier collects only {n} tests from {f}"
