"""On-device generation loop tests: chunked decode must reproduce the
per-step host loop, and device sampling must honor the sampler modes."""

import numpy as np
import jax
import jax.numpy as jnp

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.decode_loop import decode_chunk, device_sample
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.sampling import Sampler

CFG = tiny_config(seq_len=64)


def make_engine(seed=4):
    return Engine(CFG, init_params(CFG, seed=seed),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]))


def test_chunked_greedy_equals_host_loop():
    prompt = [5, 9, 2]
    host = [t for t, _ in make_engine().generate(prompt, 24, Sampler(CFG.vocab_size, 0.0, 0.9, 1))]
    dev = [t for t, _ in make_engine().generate_stream(prompt, 24, temperature=0.0, chunk=7)]
    assert dev == host


def test_chunked_eos_rewinds_position():
    e = make_engine()
    ref = [t for t, _ in e.generate_stream([5, 9], 30, temperature=0.0, chunk=8)]
    eos = ref[10]
    e2 = make_engine()
    out = [t for t, _ in e2.generate_stream([5, 9], 30, temperature=0.0, chunk=8, eos_ids=(eos,))]
    assert out[-1] == eos
    # position = tokens actually consumed into the sequence (prompt + generated
    # before EOS); the EOS token itself was never fed (reference chat parity)
    assert e2.pos == len(out) - 1


def test_chunked_sampled_is_reproducible():
    prompt = [5, 9, 2]
    a = [t for t, _ in make_engine().generate_stream(prompt, 20, temperature=0.8, topp=0.9, seed=3)]
    b = [t for t, _ in make_engine().generate_stream(prompt, 20, temperature=0.8, topp=0.9, seed=3)]
    c = [t for t, _ in make_engine().generate_stream(prompt, 20, temperature=0.8, topp=0.9, seed=4)]
    assert a == b
    assert len(c) == len(a)


def test_seed_none_continues_session_stream():
    """Multi-turn chat seeds ONCE per session (app.cpp:33 — one Sampler
    whose state persists across turns): ``seed=None`` must continue the
    engine's RNG stream, not restart it, and the continued stream must be
    reproducible from the session seed alone (VERDICT r04 Weak #6)."""
    def two_turns(second_seed):
        e = make_engine()
        t1 = [t for t, _ in e.generate_stream([5, 9], 10, temperature=0.9,
                                              topp=0.9, seed=3, chunk=4)]
        t2 = [t for t, _ in e.generate_stream([7], 6, temperature=0.9,
                                              topp=0.9, seed=second_seed,
                                              chunk=4)]
        return t1, t2

    a1, a2 = two_turns(None)
    b1, b2 = two_turns(None)
    assert (a1, a2) == (b1, b2)  # session-seed reproducibility
    c1, c2 = two_turns(3)       # re-seeding restarts the stream instead
    assert a1 == c1
    # same cache state, same prompt, same temperature — only the RNG stream
    # position differs, so the continued turn must diverge from the
    # re-seeded turn (if this ever collides, the fold_in counter is broken)
    assert a2 != c2


def test_device_sample_greedy_is_argmax():
    logits = jnp.asarray(np.random.RandomState(0).randn(2, 50).astype(np.float32))
    out = device_sample(logits, jax.random.PRNGKey(0), 0.0, 0.9)
    np.testing.assert_array_equal(np.asarray(out), np.argmax(np.asarray(logits), -1))


def test_device_sample_topp_prunes_tail():
    logits = np.full((1, 32), -10.0, np.float32)
    logits[0, 7] = 10.0
    for seed in range(5):
        out = device_sample(jnp.asarray(logits), jax.random.PRNGKey(seed), 1.0, 0.5)
        assert int(out[0]) == 7


def test_device_sample_plain_multinomial_covers_support():
    logits = jnp.zeros((1, 4))
    seen = {int(device_sample(logits, jax.random.PRNGKey(s), 1.0, 0.0)[0]) for s in range(40)}
    assert len(seen) >= 3  # uniform over 4 tokens; 40 draws hit most of them


def test_decode_chunk_matches_stepwise_forward():
    """The scan-internal cache threading must equal explicit stepping."""
    from dllama_tpu.models.transformer import forward_last, init_kv_cache
    params = init_params(CFG, seed=2)
    cache = init_kv_cache(CFG, batch=1)
    # feed 3 prompt tokens step by step
    for i, t in enumerate([4, 9, 11]):
        logits, cache = forward_last(params, CFG, jnp.asarray([[t]]), cache, jnp.int32(i), jnp.int32(0))
    toks, cache2, last, pos, _ = decode_chunk(
        params, CFG, cache, jnp.asarray([int(np.argmax(np.asarray(logits)))]),
        jnp.int32(3), jax.random.PRNGKey(0), steps=5, temperature=0.0, topp=0.9)
    toks = np.asarray(toks)[:, 0]

    # reference: explicit per-step greedy loop
    cache_b = init_kv_cache(CFG, batch=1)
    for i, t in enumerate([4, 9, 11]):
        logits_b, cache_b = forward_last(params, CFG, jnp.asarray([[t]]), cache_b, jnp.int32(i), jnp.int32(0))
    cur = int(np.argmax(np.asarray(logits_b)))
    expect = []
    for i in range(5):
        logits_b, cache_b = forward_last(params, CFG, jnp.asarray([[cur]]), cache_b, jnp.int32(3 + i), jnp.int32(0))
        cur = int(np.argmax(np.asarray(logits_b)))
        expect.append(cur)
    np.testing.assert_array_equal(toks, expect)
    assert int(pos) == 8


def test_batched_decode_rows_independent():
    """Batched greedy decode (the dp axis use case): each batch row must
    produce exactly the tokens a batch-1 decode of that row produces —
    rows share compiled steps but not state."""
    from dllama_tpu.models.transformer import init_kv_cache

    params = init_params(CFG, seed=7)
    key = jax.random.PRNGKey(0)

    def run(tokens0):
        b = len(tokens0)
        cache = init_kv_cache(CFG, batch=b)
        toks, *_ = decode_chunk(
            params, CFG, cache, jnp.asarray(tokens0, jnp.int32),
            jnp.int32(0), key, steps=12, temperature=0.0, topp=0.9)
        return np.asarray(toks)  # (steps, B)

    batched = run([3, 11])
    solo_a = run([3])
    solo_b = run([11])
    np.testing.assert_array_equal(batched[:, 0], solo_a[:, 0])
    np.testing.assert_array_equal(batched[:, 1], solo_b[:, 0])


def test_pipelined_eos_rolls_back_speculative_rng_tick():
    """The pipelined chunk dispatch (engine.generate_stream) enqueues one
    speculative chunk ahead; a mid-chunk EOS must return that chunk's
    unconsumed RNG tick so the per-session sampler stream is
    schedule-independent — the counter afterwards equals what a serial
    schedule would have consumed (one tick for the first post-prefill
    sample + one per CONSUMED chunk)."""
    e = make_engine()
    ref = [t for t, _ in e.generate_stream([5, 9], 30, temperature=0.0, chunk=8)]
    eos = ref[12]  # interior of chunk 2 (prompt 2 + sample 1 + chunk of 8 = 11)
    e2 = make_engine()
    out = [t for t, _ in e2.generate_stream([5, 9], 30, temperature=0.0,
                                            chunk=8, eos_ids=(eos,))]
    assert out[-1] == eos
    gen_after_first = len(out) - len([5, 9]) - 1  # chunked tokens incl EOS
    consumed_chunks = -(-gen_after_first // 8)
    assert e2._chunk_counter == 1 + consumed_chunks
    # and the rewound position still matches the serial contract
    assert e2.pos == len(out) - 1


def test_steps_prompt_plus_one_returns_cleanly():
    """steps == prompt+1 (API max_tokens=1): the one token comes from the
    prefill-logits sample and NO chunk is dispatched (a k=0 dispatch
    would div-by-zero in the stats and burn a phantom RNG tick)."""
    e = make_engine()
    out = [t for t, _ in e.generate_stream([5, 9], 3, temperature=0.0, chunk=8)]
    assert len(out) == 3
    assert e._chunk_counter == 1  # just the post-prefill sample


def test_abandoned_stream_rolls_back_speculative_tick():
    """A consumer that abandons the generator mid-chunk (the stop-string
    break in drain_generation) must also return the speculative in-flight
    chunk's RNG tick — GeneratorExit runs the same rollback as EOS."""
    e = make_engine()
    gen = e.generate_stream([5, 9], 30, temperature=0.0, chunk=4)
    for _ in range(2 + 1 + 2):  # prompt echo + first sample + 2 chunk tokens
        next(gen)
    gen.close()
    # consumed ticks: post-prefill sample + chunk 1; speculative chunk 2's
    # tick was rolled back on close
    assert e._chunk_counter == 2
