"""Model forward-pass tests: jax vs independent numpy oracle, prefill ≡
decode consistency, and all three arch families.

This is the port of the reference's integration strategy
(llama2-tasks-test.cpp / grok1-tasks-test.cpp): deterministic fixture
weights → run the real execution path → compare against a golden oracle."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from dllama_tpu.io import mfile
from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params, param_shapes
from dllama_tpu.models.transformer import forward, forward_last, init_kv_cache
from reference_impl import np_forward


def np_params(params):
    return {k: np.asarray(v) for k, v in params.items()}


def run_jax_full(cfg, params, tokens):
    cache = init_kv_cache(cfg, batch=1)
    logits, _ = forward(params, cfg, jnp.asarray([tokens]), cache, jnp.int32(0))
    return np.asarray(logits)[0]


CFGS = {
    "llama": tiny_config(),
    "llama_gqa8": tiny_config(n_heads=8, n_kv_heads=8, dim=64),
    "mixtral": tiny_config(arch=mfile.ARCH_MIXTRAL, n_experts=4, n_active_experts=2),
    "grok1": tiny_config(arch=mfile.ARCH_GROK1, n_experts=4, n_active_experts=2,
                         hidden_act=mfile.ACT_GELU),
}


@pytest.mark.parametrize("name", list(CFGS))
def test_forward_matches_numpy_oracle(name):
    cfg = CFGS[name]
    params = init_params(cfg, seed=3)
    tokens = list(np.random.RandomState(0).randint(0, cfg.vocab_size, 7))
    got = run_jax_full(cfg, params, tokens)
    want = np_forward(np_params(params), cfg, tokens)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


@pytest.mark.parametrize("name", ["llama", "mixtral", "grok1"])
def test_decode_matches_prefill(name):
    """Token-at-a-time decode through the KV cache must reproduce the
    full-sequence forward — the autoregression-correctness property."""
    cfg = CFGS[name]
    params = init_params(cfg, seed=11)
    tokens = list(np.random.RandomState(1).randint(0, cfg.vocab_size, 6))

    full = run_jax_full(cfg, params, tokens)

    cache = init_kv_cache(cfg, batch=1)
    step_logits = []
    for i, t in enumerate(tokens):
        logits, cache = forward(params, cfg, jnp.asarray([[t]]), cache, jnp.int32(i))
        step_logits.append(np.asarray(logits)[0, 0])
    np.testing.assert_allclose(np.stack(step_logits), full, atol=2e-4, rtol=1e-3)


def test_prefill_then_decode_continues():
    """Prefill T tokens then decode more — mixed-mode consistency."""
    cfg = CFGS["llama"]
    params = init_params(cfg, seed=5)
    tokens = list(np.random.RandomState(2).randint(0, cfg.vocab_size, 8))

    full = run_jax_full(cfg, params, tokens)

    cache = init_kv_cache(cfg, batch=1)
    _, cache = forward(params, cfg, jnp.asarray([tokens[:5]]), cache, jnp.int32(0))
    outs = []
    for i in range(5, 8):
        logits, cache = forward(params, cfg, jnp.asarray([[tokens[i]]]), cache, jnp.int32(i))
        outs.append(np.asarray(logits)[0, 0])
    np.testing.assert_allclose(np.stack(outs), full[5:8], atol=2e-4, rtol=1e-3)


def test_forward_last_matches_forward():
    cfg = CFGS["llama"]
    params = init_params(cfg, seed=7)
    tokens = np.random.RandomState(3).randint(0, cfg.vocab_size, (1, 6))
    cache = init_kv_cache(cfg, batch=1)
    full, _ = forward(params, cfg, jnp.asarray(tokens), cache, jnp.int32(0))
    cache2 = init_kv_cache(cfg, batch=1)
    last, _ = forward_last(params, cfg, jnp.asarray(tokens), cache2, jnp.int32(0), jnp.int32(3))
    np.testing.assert_allclose(np.asarray(last)[0], np.asarray(full)[0, 3], atol=1e-5)


def test_padded_prefill_ignores_padding():
    """Right-padding must not affect logits at the real last index (the
    engine pads prompts up to a bucket)."""
    cfg = CFGS["llama"]
    params = init_params(cfg, seed=9)
    tokens = [5, 17, 40]
    cache = init_kv_cache(cfg, batch=1)
    exact, _ = forward_last(params, cfg, jnp.asarray([tokens]), cache, jnp.int32(0), jnp.int32(2))
    padded = tokens + [0] * 5
    cache2 = init_kv_cache(cfg, batch=1)
    got, _ = forward_last(params, cfg, jnp.asarray([padded]), cache2, jnp.int32(0), jnp.int32(2))
    np.testing.assert_allclose(np.asarray(got), np.asarray(exact), atol=1e-5)


def test_grok_scales_applied():
    """Grok-1 embedding ×78.38… and logit ×0.577… (grok1-tasks.cpp:13,:272)."""
    cfg = CFGS["grok1"]
    assert cfg.embedding_scale == pytest.approx(78.38367176906169)
    assert cfg.logit_scale == pytest.approx(0.5773502691896257)
    assert not cfg.rope_interleaved  # falcon/neox rope (transformer.cpp:227-231)
    assert CFGS["llama"].rope_interleaved


def test_param_shapes_cover_all_archs():
    for name, cfg in CFGS.items():
        shapes = param_shapes(cfg)
        p = init_params(cfg, seed=0)
        assert set(p) == set(shapes)
        for k, v in p.items():
            assert tuple(v.shape) == shapes[k], k
