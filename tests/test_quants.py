"""Quantization tests — the reference's quants-test.cpp ported in spirit:
roundtrip error bounds swept over sizes (quants-test.cpp:7-52), plus Q40
packing-layout checks against hand-computed blocks."""

import numpy as np
import pytest

from dllama_tpu import quants


def test_batch_bytes():
    # getBatchBytes semantics (quants.cpp:28-51)
    assert quants.batch_bytes(quants.F32, 320, 2) == 320 * 2 * 4
    assert quants.batch_bytes(quants.F16, 320, 2) == 320 * 2 * 2
    assert quants.batch_bytes(quants.Q40, 320, 2) == (320 // 32) * 18 * 2
    assert quants.batch_bytes(quants.Q80, 320, 2) == (320 // 32) * 34 * 2
    with pytest.raises(ValueError):
        quants.batch_bytes(quants.Q40, 33, 1)


@pytest.mark.parametrize("n", [1024, 768, 2752])
def test_q80_roundtrip_error(n):
    # reference bound: max abs error 0.0043 on randomF32(seed)-style data
    # (quants-test.cpp:30-38)
    rng = np.random.RandomState(1234)
    x = rng.rand(n).astype(np.float32)
    raw = quants.quantize_q80(x)
    assert raw.size == quants.batch_bytes(quants.Q80, n)
    y = quants.dequantize_q80(raw, n)
    assert np.abs(x - y).max() <= 0.0043


@pytest.mark.parametrize("n", [1024, 2752])
def test_q40_roundtrip_error(n):
    rng = np.random.RandomState(99)
    x = (rng.rand(n).astype(np.float32) - 0.5) * 2
    raw = quants.quantize_q40(x)
    assert raw.size == quants.batch_bytes(quants.Q40, n)
    y = quants.dequantize_q40(raw, n)
    # 4-bit: max error is half a quantization step = absmax/16 per block
    steps = np.abs(x.reshape(-1, 32)).max(axis=1) / 8.0
    bound = np.repeat(steps, 32) * 1.01 + 1e-6
    assert np.all(np.abs(x - y) <= bound)


def test_q40_block_layout():
    # value i is the low nibble of byte i, value i+16 the high nibble
    # (writer.py:46-52 / BlockQ40 quants.hpp:17-20)
    x = np.zeros(32, dtype=np.float32)
    x[0] = 8.0   # quantizes to nibble 0 (== -8 → value -8*delta)
    x[16] = -8.0
    raw = quants.quantize_q40(x)
    assert raw.size == 18
    d = raw[:2].copy().view(np.float16)[0]
    assert float(d) == -1.0  # delta = min/-8 ... max=8, min=-8 → -min>max false → 8/-8 = -1
    y = quants.dequantize_q40(raw, 32)
    assert y[0] == pytest.approx(8.0, abs=0.6)
    # writer.py clamps the +8.5-offset code at 15 (writer.py:41), so the
    # extreme negative value loses one step: (15-8)*(-1) = -7
    assert y[16] == pytest.approx(-7.0, abs=0.6)


def test_q40_planes_match_dequant():
    rng = np.random.RandomState(7)
    d_out, n_in = 6, 64
    w = rng.randn(d_out, n_in).astype(np.float32)
    raw = quants.quantize_q40(w)
    qvals, scales = quants.q40_planes(raw, (d_out, n_in))
    assert qvals.shape == (d_out, n_in)
    assert scales.shape == (d_out, n_in // 32)
    recon = qvals.astype(np.float32) * np.repeat(scales, 32, axis=1)
    ref = quants.dequantize_q40(raw, d_out * n_in).reshape(d_out, n_in)
    np.testing.assert_allclose(recon, ref, rtol=0, atol=1e-6)


def test_q80_zeros():
    x = np.zeros(64, dtype=np.float32)
    y = quants.dequantize_q80(quants.quantize_q80(x), 64)
    assert np.all(y == 0)


def test_tensor_roundtrip_all_types():
    rng = np.random.RandomState(5)
    x = rng.randn(128).astype(np.float32)
    for ftype, tol in [(quants.F32, 0), (quants.F16, 2e-3), (quants.Q80, 0.03), (quants.Q40, 0.4)]:
        raw = quants.quantize_tensor(x, ftype)
        y = quants.dequantize_tensor(raw, ftype, 128)
        assert np.abs(x - y).max() <= tol + 1e-9, f"ftype={ftype}"
