"""Independent numpy implementation of the model semantics, used as the
golden oracle — the analogue of the reference's hardcoded golden floats
(llama2-tasks-test.cpp:12-525) but computed, not pasted.

Written directly from the reference task handlers' math
(llama2-tasks.cpp / grok1-tasks.cpp), with no JAX: full-sequence causal
attention, no KV cache, loops over layers/heads.  Any agreement bug between
this and dllama_tpu.models.transformer is a real finding in one of them.
"""

from __future__ import annotations

import numpy as np

RMS_EPS = 1e-5


def rmsnorm(x, w):
    ms = np.mean(x.astype(np.float64) ** 2, axis=-1, keepdims=True)
    return (w * (x / np.sqrt(ms + RMS_EPS))).astype(np.float32)


def softmax(x):
    m = x.max(axis=-1, keepdims=True)
    e = np.exp(x - m)
    return e / e.sum(axis=-1, keepdims=True)


def silu(x):
    return x / (1.0 + np.exp(-x))


def gelu_tanh(x):
    return 0.5 * x * (1.0 + np.tanh(0.7978845608028654 * (x + 0.044715 * x ** 3)))


def rope_rotate(x, pos, theta, interleaved):
    """x: (T, H, D). Rotate per the convention (commands.cpp:160-229)."""
    t, h, d = x.shape
    half = d // 2
    j = np.arange(half, dtype=np.float64)
    freqs = theta ** (-2.0 * j / d)
    ang = np.asarray(pos, np.float64)[:, None] * freqs  # (T, half)
    cos, sin = np.cos(ang), np.sin(ang)
    out = np.empty_like(x)
    if interleaved:
        x0, x1 = x[..., 0::2], x[..., 1::2]
        out[..., 0::2] = x0 * cos[:, None] - x1 * sin[:, None]
        out[..., 1::2] = x0 * sin[:, None] + x1 * cos[:, None]
    else:
        x0, x1 = x[..., :half], x[..., half:]
        out[..., :half] = x0 * cos[:, None] - x1 * sin[:, None]
        out[..., half:] = x0 * sin[:, None] + x1 * cos[:, None]
    return out.astype(np.float32)


def moe(xb, router, up, gate, down, n_active, act):
    """xb: (T, D). Reference routing: softmax over all experts, top-k,
    renormalize (grok1-tasks.cpp:60-114)."""
    t, d = xb.shape
    probs = softmax(xb @ router)  # (T, E)
    out = np.zeros_like(xb)
    for i in range(t):
        idx = np.argsort(-probs[i], kind="stable")[:n_active]
        w = probs[i, idx] / probs[i, idx].sum()
        for j, e in enumerate(idx):
            h = act(xb[i] @ gate[e]) * (xb[i] @ up[e])
            out[i] += w[j] * (h @ down[e])
    return out


def np_forward(params, cfg, tokens):
    """Full-sequence forward. params: numpy dict in the runtime layout
    (input-dim-first, layer-stacked). tokens: (T,). Returns (T, V) logits."""
    from dllama_tpu.io import mfile
    act = {0: gelu_tanh, 1: silu}[cfg.hidden_act]
    t = len(tokens)
    hq, hkv, dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_size
    pos = np.arange(t)

    x = params["embedding"][tokens].astype(np.float32) * cfg.embedding_scale

    for li in range(cfg.n_layers):
        lp = {k: np.asarray(v[li]) for k, v in params.items()
              if k not in ("embedding", "rms_final", "wcls")}
        xb = rmsnorm(x, lp["rms_att"])
        q = (xb @ lp["wq"]).reshape(t, hq, dh)
        k = (xb @ lp["wk"]).reshape(t, hkv, dh)
        v = (xb @ lp["wv"]).reshape(t, hkv, dh)
        q = rope_rotate(q, pos, cfg.rope_theta, cfg.rope_interleaved)
        k = rope_rotate(k, pos, cfg.rope_theta, cfg.rope_interleaved)

        # per-head causal attention with GQA grouping (llama2-tasks.cpp:54-94)
        att_out = np.zeros((t, hq, dh), np.float32)
        kv_mul = hq // hkv
        for h in range(hq):
            kh = h // kv_mul
            scores = (q[:, h] @ k[:, kh].T) / np.sqrt(dh)  # (T, T)
            mask = np.tril(np.ones((t, t), bool))
            scores = np.where(mask, scores, -np.inf)
            att_out[:, h] = softmax(scores) @ v[:, kh]
        proj = att_out.reshape(t, hq * dh) @ lp["wo"]
        if cfg.post_block_norms:
            proj = rmsnorm(proj, lp["rms_ffn"])
        x = x + proj

        if cfg.is_moe:
            pre = lp["rms_moe"] if cfg.post_block_norms else lp["rms_ffn"]
            xb = rmsnorm(x, pre)
            ff = moe(xb, lp["router"], lp["up"], lp["gate"], lp["down"],
                     cfg.n_active_experts, act)
            if cfg.post_block_norms:
                ff = rmsnorm(ff, lp["rms_ffn2"])
        else:
            xb = rmsnorm(x, lp["rms_ffn"])
            ff = (act(xb @ lp["w1"]) * (xb @ lp["w3"])) @ lp["w2"]
        x = x + ff

    x = rmsnorm(x, np.asarray(params["rms_final"]))
    logits = (x @ params["wcls"]).astype(np.float32) * cfg.logit_scale
    return logits
