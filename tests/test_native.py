"""Native loader component (csrc/q40pack.cpp + native.py bindings).

The native repack and the numpy fallback must produce byte-identical
runtime planes, and both must agree with the original (slow) reference
pipeline q40_planes → transpose → pack_planes_np."""

import numpy as np
import pytest

from dllama_tpu import native, quants
from dllama_tpu.ops import q40


def _file_bytes(d, n, seed=0):
    w = (np.random.RandomState(seed).randn(d, n) * 0.1).astype(np.float32)
    return np.frombuffer(quants.quantize_q40(w), np.uint8), w


def _repack(raw, d, n, use_native):
    np_ = q40.padded_n(n)
    qp = np.zeros((np_ // 2, d), np.uint8)
    sc = np.zeros((np_ // 32, d), np.float16)
    if use_native:
        native.q40_repack_into(raw, d, n, qp, sc, 0)
    else:
        import unittest.mock as mock
        with mock.patch.object(native, "have_native", return_value=False):
            q40.repack_file_bytes_into(raw, d, n, qp, sc, 0)
    return qp, sc


def test_numpy_repack_matches_reference_pipeline():
    d, n = 48, 96
    raw, _ = _file_bytes(d, n)
    qp, sc = _repack(raw, d, n, use_native=False)
    ref = q40.pack_planes_t(*quants.q40_planes(raw, (d, n)))
    np.testing.assert_array_equal(qp, np.asarray(ref.qpacked))
    np.testing.assert_array_equal(sc.view(np.uint16), np.asarray(ref.scales))


@pytest.mark.skipif(not native.have_native(), reason="libq40pack.so not built")
def test_native_repack_matches_numpy():
    for d, n in [(48, 96), (64, 2048), (129, 32), (1000, 352)]:
        raw, _ = _file_bytes(d, n, seed=d)
        a = _repack(raw, d, n, use_native=True)
        b = _repack(raw, d, n, use_native=False)
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])


@pytest.mark.skipif(not native.have_native(), reason="libq40pack.so not built")
def test_native_repack_column_offset():
    """Fused groups write adjacent column windows of one plane."""
    d1, d2, n = 32, 48, 64
    r1, w1 = _file_bytes(d1, n, seed=1)
    r2, w2 = _file_bytes(d2, n, seed=2)
    np_ = q40.padded_n(n)
    qp = np.zeros((np_ // 2, d1 + d2), np.uint8)
    sc = np.zeros((np_ // 32, d1 + d2), np.float16)
    native.q40_repack_into(r1, d1, n, qp, sc, 0)
    native.q40_repack_into(r2, d2, n, qp, sc, d1)
    qt = q40.QTensor(qp, sc, (n, d1 + d2))
    deq = np.asarray(q40.dequantize(qt))
    exp1 = quants.dequantize_q40(r1, d1 * n).reshape(d1, n).T
    exp2 = quants.dequantize_q40(r2, d2 * n).reshape(d2, n).T
    np.testing.assert_allclose(deq[:, :d1], exp1, atol=0)
    np.testing.assert_allclose(deq[:, d1:], exp2, atol=0)


def test_pack_file_groups_end_to_end(tmp_path):
    """load_params' Q40 path (now through pack_file_groups) dequantizes to
    the same values as MFile.tensor."""
    from tests.fixtures import write_tiny_model
    from dllama_tpu.io import mfile
    from dllama_tpu.models.config import ModelConfig
    from dllama_tpu.models.params import load_params

    path = tmp_path / "m.m"
    write_tiny_model(str(path), ftype=quants.Q40, vocab_size=64, seq_len=32)
    mf = mfile.MFile(str(path))
    cfg = ModelConfig.from_spec(mf.spec)
    _, params = load_params(mf, cfg, keep_quantized=True, fuse=True)
    wqkv = np.asarray(q40.dequantize(params["wqkv"]))
    expect = np.concatenate(
        [mf.tensor("layers.0.wq").T, mf.tensor("layers.0.wk").T,
         mf.tensor("layers.0.wv").T], axis=1)
    np.testing.assert_allclose(wqkv[0], expect, atol=1e-7)


@pytest.mark.skipif(not native.have_native_q80(), reason="q80_repack not built")
def test_native_q80_repack_matches_numpy():
    """csrc q80_repack ≡ the numpy byte transpose, including column-offset
    fused-group writes (Q80 twin of the q40 native-loader tests)."""
    import unittest.mock as mock

    from dllama_tpu.ops import q8

    rng = np.random.RandomState(7)
    for d, n in [(48, 96), (64, 2048), (129, 32), (100, 352)]:
        w = (rng.randn(d, n) * 0.2).astype(np.float32)
        raw = np.frombuffer(quants.quantize_tensor(w, quants.Q80), np.uint8)
        np_ = q40.padded_n(n)
        planes = []
        for use_native in (True, False):
            qv = np.zeros((np_, d), np.int8)
            sc = np.zeros((np_ // 32, d), np.float16)
            if use_native:
                native.q80_repack_into(raw, d, n, qv, sc, 0)
            else:
                # the PRODUCTION numpy branch, not a private copy: force
                # q8.repack_file_bytes_into down its fallback path
                with mock.patch.object(native, "have_native_q80",
                                       return_value=False):
                    q8.repack_file_bytes_into(raw, d, n, qv, sc, 0)
            planes.append((qv, sc))
        np.testing.assert_array_equal(planes[0][0], planes[1][0])
        np.testing.assert_array_equal(planes[0][1], planes[1][1])

    # column-offset fused write + value correctness via dequantize
    d1, d2, n = 32, 48, 64
    w1 = (rng.randn(d1, n) * 0.2).astype(np.float32)
    w2 = (rng.randn(d2, n) * 0.2).astype(np.float32)
    r1 = np.frombuffer(quants.quantize_tensor(w1, quants.Q80), np.uint8)
    r2 = np.frombuffer(quants.quantize_tensor(w2, quants.Q80), np.uint8)
    np_ = q40.padded_n(n)
    qv = np.zeros((np_, d1 + d2), np.int8)
    sc = np.zeros((np_ // 32, d1 + d2), np.float16)
    native.q80_repack_into(r1, d1, n, qv, sc, 0)
    native.q80_repack_into(r2, d2, n, qv, sc, d1)
    import jax.numpy as jnp
    qt = q8.Q8Tensor(jnp.asarray(qv), jnp.asarray(sc.view(np.uint16)), (n, d1 + d2))
    deq = np.asarray(q8.dequantize(qt, jnp.float32))
    np.testing.assert_allclose(
        deq[:, :d1], quants.dequantize_q80(r1, d1 * n).reshape(d1, n).T, atol=1e-6)
    np.testing.assert_allclose(
        deq[:, d1:], quants.dequantize_q80(r2, d2 * n).reshape(d2, n).T, atol=1e-6)
