"""API server tests: NaiveCache unit semantics + live HTTP integration on a
tiny fixture model (the reference has NO api test — SURVEY §4 gap, closed
here)."""

import json
import subprocess
import sys
import time
import urllib.request

import pytest

from fixtures import REPO, cpu_env, free_port, write_tiny_model, write_tiny_tokenizer
from dllama_tpu.server.api import ChatMessage, NaiveCache, parse_request


# --- unit: NaiveCache (dllama-api.cpp:187-232 semantics) ---

def msgs(*pairs):
    return [ChatMessage(r, c) for r, c in pairs]


def test_cache_empty_returns_full_prompt():
    c = NaiveCache()
    start, delta = c.resolve_delta_prompt(msgs(("user", "hi")))
    assert start == 0 and len(delta) == 1


def test_cache_prefix_hit_resumes():
    c = NaiveCache()
    c.push(10, ChatMessage("user", "hi"))
    c.push(20, ChatMessage("assistant", "hello!"))
    start, delta = c.resolve_delta_prompt(
        msgs(("user", "hi"), ("assistant", "hello!"), ("user", "more")))
    assert start == 20
    assert [m.content for m in delta] == ["more"]


def test_cache_mismatch_clears():
    c = NaiveCache()
    c.push(10, ChatMessage("user", "hi"))
    c.push(20, ChatMessage("assistant", "hello!"))
    start, delta = c.resolve_delta_prompt(
        msgs(("user", "DIFFERENT"), ("assistant", "hello!"), ("user", "more")))
    assert start == 0 and len(delta) == 3
    assert c.items == []


def test_cache_equal_length_is_miss():
    # reference requires messages.size() > cacheSize (dllama-api.cpp:214)
    c = NaiveCache()
    c.push(10, ChatMessage("user", "hi"))
    start, delta = c.resolve_delta_prompt(msgs(("user", "hi")))
    assert start == 0 and len(delta) == 1


def test_parse_request_fields():
    p = parse_request({
        "messages": [{"role": "user", "content": "x"}],
        "temperature": 0.1, "top_p": 0.5, "max_tokens": 7,
        "stream": True, "seed": 42, "stop": ["##"],
    }, 0.7, 0.9)
    assert p.temperature == 0.1 and p.top_p == 0.5 and p.max_tokens == 7
    assert p.stream and p.seed == 42 and p.stop == ["##"]
    assert parse_request({"stop": "single"}, 0.7, 0.9).stop == ["single"]


# --- integration: live server on a tiny model ---

@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("api")
    m, t = str(d / "tiny.m"), str(d / "tiny.t")
    write_tiny_model(m)
    write_tiny_tokenizer(t)
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu.server.api", "--model", m,
         "--tokenizer", t, "--port", str(port), "--temperature", "0",
         "--max-seq-len", "128", "--batch-slots", "3"],
        cwd=REPO, env=cpu_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    base = f"http://127.0.0.1:{port}"
    for _ in range(600):
        if proc.poll() is not None:
            raise RuntimeError(f"server died:\n{proc.stdout.read()}")
        try:
            urllib.request.urlopen(base + "/health", timeout=1)
            break
        except OSError:
            time.sleep(0.2)
    else:
        proc.kill()
        raise RuntimeError("server did not come up")
    yield base
    proc.kill()
    proc.wait()


def post(base, path, body, timeout=240):
    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def test_models_endpoint(server):
    with urllib.request.urlopen(server + "/v1/models", timeout=10) as r:
        data = json.loads(r.read())
    assert data["object"] == "list" and data["data"][0]["object"] == "model"


def test_chat_completion_non_stream(server):
    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 8, "temperature": 0, "seed": 1}
    with post(server, "/v1/chat/completions", body) as r:
        data = json.loads(r.read())
    assert data["object"] == "chat.completion"
    assert data["choices"][0]["message"]["role"] == "assistant"
    u = data["usage"]
    assert u["prompt_tokens"] > 0
    assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]


def test_health_perf_block_and_metrics_gauges(server):
    """Performance-economics plane over the real HTTP surface: after a
    served completion, /health carries the roofline summary and both
    /metrics expositions carry the MFU/MBU gauges (obs/cost.py)."""
    # /v1/completions rides the slot scheduler (the attribution seam);
    # an uncontended chat request would take the mutex path instead
    body = {"prompt": "hello", "max_tokens": 6, "temperature": 0}
    with post(server, "/v1/completions", body) as r:
        r.read()
    with urllib.request.urlopen(server + "/health", timeout=10) as r:
        health = json.loads(r.read())
    perf = health["perf"]
    assert perf["flops_total"] > 0 and perf["hbm_bytes_total"] > 0
    assert "mfu" in perf and "mbu" in perf and "peaks" in perf
    assert perf["chip_ms_by_class"]  # the served request bought chip time
    with urllib.request.urlopen(server + "/metrics", timeout=10) as r:
        js = json.loads(r.read())
    assert "mfu" in js and "mbu" in js
    assert js["dispatch_flops"] and js["class_chip_ms"]
    with urllib.request.urlopen(server + "/metrics?format=prometheus",
                                timeout=10) as r:
        txt = r.read().decode()
    assert "dllama_mfu" in txt and "dllama_mbu" in txt
    assert "dllama_dispatch_flops_total" in txt
    assert "dllama_class_chip_ms_total{" in txt


def test_chat_completion_stream_sse(server):
    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 8, "temperature": 0, "stream": True, "seed": 1}
    with post(server, "/v1/chat/completions", body) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [l[6:] for l in raw.splitlines() if l.startswith("data: ")]
    assert events[-1] == "[DONE]"
    parsed = [json.loads(e) for e in events[:-1]]
    assert parsed[-1]["choices"][0]["finish_reason"] == "stop"
    assert all(p["object"] == "chat.completion.chunk" for p in parsed)


def test_followup_uses_naive_cache(server):
    first = {"messages": [{"role": "user", "content": "cache me"}],
             "max_tokens": 6, "temperature": 0, "seed": 1}
    with post(server, "/v1/chat/completions", first) as r:
        d1 = json.loads(r.read())
    reply = d1["choices"][0]["message"]["content"]
    p1 = d1["usage"]["prompt_tokens"]
    follow = {"messages": [
        {"role": "user", "content": "cache me"},
        {"role": "assistant", "content": reply},
        {"role": "user", "content": "again"}],
        "max_tokens": 6, "temperature": 0, "seed": 1}
    with post(server, "/v1/chat/completions", follow) as r:
        data = json.loads(r.read())
    assert data["choices"][0]["message"]["role"] == "assistant"
    # cache hit → only the delta (one user message + generation prompt) is
    # tokenized: about the size of the first one-message prompt, far smaller
    # than re-encoding the whole 3-message history
    assert data["usage"]["prompt_tokens"] <= p1 + 10


def test_bad_json_is_400(server):
    req = urllib.request.Request(
        server + "/v1/chat/completions", b"{not json",
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_missing_messages_is_400(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/v1/chat/completions", {"messages": []})
    assert e.value.code == 400


def test_unknown_route_404(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/v1/other", {})
    assert e.value.code == 404


# --- batched /v1/completions (beyond reference: batch=1, tasks.cpp:199-210) ---

def test_completions_batched_matches_individual(server):
    """A list-valued prompt runs as one lockstep batch; each row's greedy
    text must equal the same prompt served alone."""
    body = {"prompt": ["the sky", "one two three"], "max_tokens": 6,
            "temperature": 0, "seed": 1}
    with post(server, "/v1/completions", body) as r:
        batched = json.loads(r.read())
    assert batched["object"] == "text_completion"
    assert [c["index"] for c in batched["choices"]] == [0, 1]
    u = batched["usage"]
    assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]
    for prompt, choice in zip(body["prompt"], batched["choices"]):
        single = {"prompt": prompt, "max_tokens": 6, "temperature": 0, "seed": 1}
        with post(server, "/v1/completions", single) as r:
            alone = json.loads(r.read())
        assert alone["choices"][0]["text"] == choice["text"]


def test_completions_n_greedy_identical(server):
    with post(server, "/v1/completions",
              {"prompt": "hello", "n": 3, "max_tokens": 5,
               "temperature": 0}) as r:
        data = json.loads(r.read())
    texts = [c["text"] for c in data["choices"]]
    assert len(texts) == 3 and len(set(texts)) == 1  # greedy → identical rows


def test_completions_logprobs(server):
    """OpenAI logprobs: chosen-token log-probs + top-k alternatives from
    one teacher-forced scoring forward.  Greedy decode means every chosen
    token IS the argmax, so its logprob must equal the top-1 logprob."""
    body = {"prompt": ["the sky", "one two"], "max_tokens": 5,
            "temperature": 0, "seed": 1, "logprobs": 2}
    with post(server, "/v1/completions", body) as r:
        data = json.loads(r.read())
    for c in data["choices"]:
        lp = c["logprobs"]
        assert lp is not None
        n = len(lp["tokens"])
        assert n > 0
        assert len(lp["token_logprobs"]) == n == len(lp["top_logprobs"]) \
            == len(lp["text_offset"])
        assert "".join(lp["tokens"]) == c["text"]
        assert all(v <= 0.0 for v in lp["token_logprobs"])
        for chosen, tops in zip(lp["token_logprobs"], lp["top_logprobs"]):
            # distinct token ids may render to one piece string (byte
            # fallback), so ≤ k entries survive the text keying
            assert 1 <= len(tops) <= 2
            assert abs(chosen - max(tops.values())) < 1e-4  # greedy = argmax


def test_completions_logprobs_echo_and_stop_alignment(server):
    """echo=true leads with the prompt's tokens (first logprob null, no
    conditional for position 0); a stop-string truncation drops the
    scored tokens past the cut so the list aligns with the text."""
    body = {"prompt": "the sky", "max_tokens": 6, "temperature": 0,
            "seed": 1, "logprobs": 0, "echo": True}
    with post(server, "/v1/completions", body) as r:
        c = json.loads(r.read())["choices"][0]
    lp = c["logprobs"]
    # the fixture tokenizer adds BOS, so even the first displayed token
    # has a real conditional (the OpenAI null applies only to a truly
    # context-free position 0); prompt tokens lead the list
    assert all(v is not None for v in lp["token_logprobs"])
    assert len(lp["tokens"]) > 6 // 2  # prompt pieces + completion pieces
    assert "".join(lp["tokens"]) == c["text"]
    assert lp["text_offset"] == sorted(lp["text_offset"])

    plain = {"prompt": "the sky", "max_tokens": 8, "temperature": 0, "seed": 1}
    with post(server, "/v1/completions", plain) as r:
        full = json.loads(r.read())["choices"][0]["text"]
    if len(full) < 4:
        pytest.skip("fixture generated too little text to cut")
    stop = full[len(full) // 2:len(full) // 2 + 2]
    with post(server, "/v1/completions",
              {**plain, "stop": [stop], "logprobs": 0}) as r:
        c = json.loads(r.read())["choices"][0]
    joined = "".join(c["logprobs"]["tokens"])
    assert c["text"].startswith(joined)  # a stop can cut mid-piece
    assert stop not in joined
    assert len(c["logprobs"]["token_logprobs"]) == len(c["logprobs"]["tokens"])


def test_completions_over_slots_is_400(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/v1/completions",
             {"prompt": ["a", "b", "c", "d"], "max_tokens": 2})
    assert e.value.code == 400


def test_completions_streaming_matches_non_stream(server):
    """SSE completions stream per-row deltas tagged by choice index from
    the one lockstep batch; reassembled text must equal the
    non-streaming response for the same request."""
    base = {"prompt": ["the sky", "one two three"], "max_tokens": 6,
            "temperature": 0, "seed": 1}
    with post(server, "/v1/completions", base) as r:
        plain = json.loads(r.read())
    with post(server, "/v1/completions", {**base, "stream": True}) as r:
        assert r.headers["Content-Type"].startswith("text/event-stream")
        raw = r.read().decode()
    events = [l[6:] for l in raw.splitlines() if l.startswith("data: ")]
    assert events[-1] == "[DONE]"
    texts, finishes = {0: "", 1: ""}, {}
    for e in events[:-1]:
        c = json.loads(e)["choices"][0]
        texts[c["index"]] += c["text"]
        if c["finish_reason"]:
            finishes[c["index"]] = c["finish_reason"]
    for i, choice in enumerate(plain["choices"]):
        assert texts[i] == choice["text"], (i, texts, plain)
        assert finishes[i] == choice["finish_reason"]


def test_chat_n_choices(server):
    """chat completions with n>1 run the templated prompt as one lockstep
    batch and return n choices (greedy → identical contents)."""
    body = {"messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 6, "temperature": 0, "seed": 1, "n": 2}
    with post(server, "/v1/chat/completions", body) as r:
        data = json.loads(r.read())
    assert [c["index"] for c in data["choices"]] == [0, 1]
    # truncation must be visible per choice (not a hardcoded "stop")
    assert all(c["finish_reason"] in ("stop", "length")
               for c in data["choices"])
    contents = [c["message"]["content"] for c in data["choices"]]
    assert len(set(contents)) == 1  # greedy rows identical
    # and the single-choice reply matches choice 0
    single = {**body, "n": 1}
    with post(server, "/v1/chat/completions", single) as r:
        one = json.loads(r.read())
    assert one["choices"][0]["message"]["content"] == contents[0]


def test_chat_n_stream_rejected(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        post(server, "/v1/chat/completions",
             {"messages": [{"role": "user", "content": "x"}],
              "n": 2, "stream": True})
    assert e.value.code == 400


def test_completions_stop_string_stream_parity(server):
    """A stop string buried inside the generated text must truncate the
    stream exactly where the non-streaming post-hoc find() truncates."""
    base = {"prompt": "the sky", "max_tokens": 10, "temperature": 0, "seed": 1}
    with post(server, "/v1/completions", base) as r:
        full = json.loads(r.read())["choices"][0]["text"]
    if len(full) < 4:
        pytest.skip("fixture generated too little text to cut")
    stop = full[len(full) // 2:len(full) // 2 + 2]
    body = {**base, "stop": [stop]}
    with post(server, "/v1/completions", body) as r:
        plain = json.loads(r.read())["choices"][0]
    with post(server, "/v1/completions", {**body, "stream": True}) as r:
        raw = r.read().decode()
    text, finish = "", None
    for e in [l[6:] for l in raw.splitlines() if l.startswith("data: ")][:-1]:
        c = json.loads(e)["choices"][0]
        text += c["text"]
        finish = c["finish_reason"] or finish
    assert stop not in text
    assert text == plain["text"]
    assert finish == plain["finish_reason"] == "stop"


def test_concurrent_requests_serialize(server):
    """Two clients at once: the accept queue serializes them; both must get
    complete, independent answers (documented queue semantics)."""
    import threading
    results = {}

    def worker(name, content):
        body = {"messages": [{"role": "user", "content": content}],
                "max_tokens": 5, "temperature": 0, "seed": 1}
        with post(server, "/v1/chat/completions", body) as r:
            results[name] = json.loads(r.read())

    threads = [threading.Thread(target=worker, args=(i, f"prompt {i}"))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert sorted(results) == [0, 1]
    for d in results.values():
        assert d["choices"][0]["message"]["role"] == "assistant"
        assert d["usage"]["completion_tokens"] > 0


def test_completions_echo_empty_completion_logprobs(tmp_path, monkeypatch):
    """echo=true with an EOS-first (empty) completion still returns the
    prompt's logprobs (OpenAI echo semantics), and a non-echo empty
    completion gets empty lists — never a silent null."""
    import jax

    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.server.api import ApiState
    from dllama_tpu.tokenizer.bpe import Tokenizer

    tok = Tokenizer(write_tiny_tokenizer(str(tmp_path / "tok.t")))
    cfg = tiny_config(seq_len=64, vocab_size=300)
    eng = Engine(cfg, init_params(cfg, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=2)
    state = ApiState(eng, tok, batch_engine=eng)
    eos = tok.eos_id

    import numpy as np

    def eos_first(id_lists, budget, **kw):  # every row: EOS immediately
        yield np.array([eos] * len(id_lists))

    monkeypatch.setattr(eng, "generate_batch_stream", eos_first)
    kw = dict(temperature=0.0, top_p=1.0, max_tokens=4, seed=1, stop=[])

    choices, _, n_completion = state.complete_batch(
        ["hello", "hi"], echo=True, logprobs=0, **kw)
    assert n_completion == 0
    for c, prompt in zip(choices, ["hello", "hi"]):
        assert c["text"] == prompt and c["finish_reason"] == "stop"
        lp = c["logprobs"]
        assert lp is not None
        assert "".join(lp["tokens"]) == prompt
        # fixture adds BOS, so every displayed prompt token has a real
        # conditional — no leading null
        assert len(lp["token_logprobs"]) == len(lp["tokens"]) > 0
        assert all(v is not None and v <= 0.0 for v in lp["token_logprobs"])

    choices, _, _ = state.complete_batch(["hello", "hi"], logprobs=0, **kw)
    for c in choices:
        assert c["text"] == "" and c["logprobs"] == {
            "tokens": [], "token_logprobs": [], "top_logprobs": None,
            "text_offset": []}
