"""Distinct-stream ragged batching (beyond reference: the reference fixes
batch=1 per cluster, tasks.cpp:199-210).

The contract under test: a batch of B *different* prompts, left-padded to
one bucket, greedy-decodes to exactly the B sequential single-stream
outputs — per-row RoPE offsets and attention key floors make each row see
precisely the angles/keys it would see decoding alone."""

import jax
import numpy as np
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import Engine

CFG = tiny_config(seq_len=64)
MOE_CFG = tiny_config(seq_len=64, n_experts=4, n_active_experts=2)

P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]


def make_engine(batch=1, cfg=CFG, tp=1, dp=1):
    n = tp * dp
    return Engine(cfg, init_params(cfg, seed=4),
                  mesh=make_mesh(tp=tp, dp=dp, devices=jax.devices()[:n]),
                  batch=batch)


def single_stream(prompt, steps, cfg=CFG, **kw):
    e = make_engine(cfg=cfg)
    return [t for t, _ in e.generate_stream(prompt, steps, **kw)]


def test_ragged_batch_matches_single_stream_greedy():
    s1 = single_stream(P1, 16, temperature=0.0, chunk=5)
    s2 = single_stream(P2, 16, temperature=0.0, chunk=5)
    outs = make_engine(2).generate_batch([P1, P2], 16, temperature=0.0, chunk=5)
    assert outs[0] == s1
    assert outs[1] == s2


def test_ragged_batch_moe_matches_single_stream():
    """The MoE router must route each ragged row independently (moe_ffn
    flattens (B, T) row-major; offsets only affect RoPE/masks)."""
    e = Engine(MOE_CFG, init_params(MOE_CFG, seed=4),
               mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=2)
    s1 = single_stream(P1, 12, cfg=MOE_CFG, temperature=0.0, chunk=4)
    s2 = single_stream(P2, 12, cfg=MOE_CFG, temperature=0.0, chunk=4)
    outs = e.generate_batch([P1, P2], 12, temperature=0.0, chunk=4)
    assert outs == [s1, s2]


def test_ragged_batch_grok_matches_single_stream():
    """Grok-1's structural extras (embedding scale, post-sub-block norms,
    GELU MoE, logit scale) must compose with per-row offsets exactly like
    the plain arch."""
    from dllama_tpu.io import mfile
    cfg = tiny_config(arch=mfile.ARCH_GROK1, n_experts=4, n_active_experts=2,
                      seq_len=64)
    e = Engine(cfg, init_params(cfg, seed=4),
               mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=2)
    s1 = single_stream(P1, 12, cfg=cfg, temperature=0.0, chunk=4)
    s2 = single_stream(P2, 12, cfg=cfg, temperature=0.0, chunk=4)
    assert e.generate_batch([P1, P2], 12, temperature=0.0, chunk=4) == [s1, s2]


def test_ragged_batch_per_row_eos():
    """EOS must stop ONLY its own row; other rows keep decoding, and the
    finished row's sequence ends exactly at its EOS token."""
    ref = make_engine(2).generate_batch([P1, P2], 20, temperature=0.0, chunk=6)
    eos = ref[0][len(P1) + 2]  # third generated token of row 0
    outs = make_engine(2).generate_batch([P1, P2], 20, temperature=0.0,
                                         chunk=6, eos_ids=(eos,))
    assert outs[0] == ref[0][:len(P1) + 3]  # truncated at its EOS
    # row 1 unaffected unless it happens to sample the same token
    expect1 = ref[1]
    if eos in ref[1][len(P2):]:
        expect1 = ref[1][:ref[1].index(eos, len(P2)) + 1]
    assert outs[1] == expect1


def test_ragged_batch_sampled_reproducible():
    a = make_engine(2).generate_batch([P1, P2], 14, temperature=0.8,
                                      topp=0.9, seed=3, chunk=4)
    b = make_engine(2).generate_batch([P1, P2], 14, temperature=0.8,
                                      topp=0.9, seed=3, chunk=4)
    c = make_engine(2).generate_batch([P1, P2], 14, temperature=0.8,
                                      topp=0.9, seed=4, chunk=4)
    assert a == b
    assert len(c) == 2  # different seed still produces full rows


def test_ragged_batch_on_dp_mesh():
    """The batch axis shards over dp: distinct rows live on distinct
    devices and must still match the single-stream outputs."""
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    s1 = single_stream(P1, 12, temperature=0.0, chunk=4)
    s2 = single_stream(P2, 12, temperature=0.0, chunk=4)
    outs = make_engine(2, dp=2).generate_batch([P1, P2], 12,
                                               temperature=0.0, chunk=4)
    assert outs == [s1, s2]


def test_single_prompt_batch_full_budget_matches_single_stream():
    """pos must advance only to the longest prompt (not the compile
    bucket), so a batch-of-one gets the identical full context budget as
    the single-stream run — all the way to seq_len."""
    steps = CFG.seq_len  # exhaust the window
    s1 = single_stream(P1, steps, temperature=0.0, chunk=8)
    outs = make_engine(1).generate_batch([P1], steps, temperature=0.0, chunk=8)
    assert outs[0] == s1
    assert len(outs[0]) == CFG.seq_len


def test_prefill_ragged_validation():
    e = make_engine(2)
    with pytest.raises(ValueError, match="1 prompts for batch=2"):
        e.prefill_ragged([P1])
    with pytest.raises(ValueError, match="empty"):
        e.prefill_ragged([P1, []])
    e.prefill_ragged([P1, P2])
    with pytest.raises(ValueError, match="fresh"):
        e.prefill_ragged([P1, P2])  # pos != 0 without reset
    e.reset()
    e.prefill_ragged([P1, P2])  # reset clears the guard


def test_generate_batch_then_single_stream_reset():
    """A ragged batch must not leak its offsets into a later single-stream
    run on the same engine (reset clears them)."""
    e = make_engine(1)
    ref = [t for t, _ in e.generate_stream(P1, 12, temperature=0.0, chunk=4)]
    e.reset()
    e.generate_batch([P2], 10, temperature=0.0, chunk=4)
    e.reset()
    again = [t for t, _ in e.generate_stream(P1, 12, temperature=0.0, chunk=4)]
    assert again == ref
