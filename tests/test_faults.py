"""Fault-injection harness + serving fault-tolerance tests.

The reference's degraded modes (stalled sockets, dead clients, a
coordinator that is not up yet) are only ever exercised by production
incidents — socket.cpp has no test for any of them.  Here every one is a
deterministic test: the fault registry (runtime/faults.py) arms named
fault points in the real serving stack and the assertions run against a
live in-process server (plus one real-SIGTERM subprocess drill).

Covers the acceptance contract: disconnect mid-SSE rewinds ``engine.pos``
and the server keeps serving; a deadline expiry returns a well-formed
truncated completion with ``finish_reason="timeout"``; a full admission
queue answers 429 + Retry-After; SIGTERM drains in-flight work; and
``init_distributed`` retries through injected coordinator failures.
"""

import json
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from fixtures import REPO, cpu_env, free_port, write_tiny_model, write_tiny_tokenizer
from dllama_tpu.runtime.faults import (
    FAULTS, Fault, FaultInjected, FaultRegistry, injected, parse_spec)

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_registry():
    """No fault leaks between tests (the registry is process-global)."""
    FAULTS.clear()
    yield
    FAULTS.clear()


# --- unit: spec grammar ---

def test_parse_spec_full_entry():
    (f,) = parse_spec("engine.device_step=delay:0.5@2x3")
    assert (f.point, f.action, f.arg) == ("engine.device_step", "delay", "0.5")
    assert (f.skip, f.times) == (2, 3)


def test_parse_spec_multiple_and_defaults():
    a, b = parse_spec("server.emit_delta=disconnect, p.q=raise:ConnectionError@1x2")
    assert (a.action, a.arg, a.skip, a.times) == ("disconnect", None, 0, None)
    assert (b.action, b.arg, b.skip, b.times) == ("raise", "ConnectionError", 1, 2)


def test_parse_spec_rejects_malformed():
    for bad in ("nope", "p=explode", "p=raise:NoSuchError", "p=delay@x"):
        with pytest.raises(ValueError, match="bad fault entry"):
            parse_spec(bad)


# --- unit: registry windows + actions ---

def test_firing_window_skip_and_times():
    reg = FaultRegistry()
    reg.install("p=delay:0@1x2")  # dormant hit 1, fires hits 2-3, dormant after
    for _ in range(5):
        reg.fire("p")
    (f,) = reg.snapshot()
    assert f.hits == 5 and f.fired == 2


def test_raise_action_and_injected_scope():
    with injected("p=raise:ConnectionError:boom"):
        with pytest.raises(ConnectionError, match="boom"):
            FAULTS.fire("p")
    FAULTS.fire("p")  # disarmed on exit: no-op
    assert not FAULTS.active()


def test_default_raise_is_fault_injected():
    with injected("p=raise"):
        with pytest.raises(FaultInjected):
            FAULTS.fire("p")


def test_nan_action_returned_to_call_site():
    reg = FaultRegistry()
    reg.install(Fault("p", "nan"))
    assert reg.fire("p") == ["nan"]
    assert reg.fire("other") == []


def test_install_env():
    reg = FaultRegistry()
    assert not reg.install_env({"NOT_THE_VAR": "p=nan"})
    assert reg.install_env({"DLLAMA_FAULTS": "p=nan"})
    assert reg.fire("p") == ["nan"]


# --- unit: init_distributed retry/backoff ---

def _fake_distributed(monkeypatch, proc_id=0):
    import jax

    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    monkeypatch.setattr(jax, "process_index", lambda: proc_id)
    return calls


def test_init_distributed_retries_through_injected_failures(monkeypatch):
    from dllama_tpu.parallel.distributed import init_distributed

    calls = _fake_distributed(monkeypatch, proc_id=1)
    with injected("distributed.initialize=raise:ConnectionErrorx2"):
        t0 = time.monotonic()
        assert init_distributed("127.0.0.1:1234", 2, 1,
                                max_retries=5, backoff=0.01) == 1
        (f,) = FAULTS.snapshot()
    assert f.fired == 2          # two coordinator failures before success
    assert len(calls) == 1       # real init reached exactly once
    assert time.monotonic() - t0 >= 0.01 + 0.02  # exponential backoff slept


def test_init_distributed_gives_up_after_max_retries(monkeypatch):
    from dllama_tpu.parallel.distributed import init_distributed

    calls = _fake_distributed(monkeypatch)
    with injected("distributed.initialize=raise:ConnectionError"):
        with pytest.raises(ConnectionError):
            init_distributed("127.0.0.1:1234", 2, 0,
                             max_retries=1, backoff=0.01)
    assert calls == []  # every attempt failed at the (injected) connect


def test_init_distributed_bad_args_never_retry():
    from dllama_tpu.parallel.distributed import init_distributed

    t0 = time.monotonic()
    with pytest.raises(ValueError, match="--proc-id"):
        init_distributed("127.0.0.1:1234", 2, None, backoff=5.0)
    assert time.monotonic() - t0 < 1.0  # fail-fast, no backoff sleep


# --- engine: watchdog + nan at the sync seam ---

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    import jax

    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.tokenizer.bpe import Tokenizer

    d = tmp_path_factory.mktemp("faults")
    tok = Tokenizer(write_tiny_tokenizer(str(d / "tok.t")))
    cfg = tiny_config(seq_len=128, vocab_size=300)
    eng = Engine(cfg, init_params(cfg, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    return eng, tok


def test_step_timeout_watchdog(stack):
    import numpy as np

    from dllama_tpu.runtime.engine import StepTimeout

    eng, _ = stack
    eng.step_timeout = 0.2
    try:
        with injected("engine.device_step=delay:3"):
            with pytest.raises(StepTimeout, match="pos="):
                eng._sync(np.zeros(2), "probe step")
    finally:
        eng.step_timeout = None


def test_sync_reports_nan_action(stack):
    import numpy as np

    eng, _ = stack
    with injected("engine.device_step=nan"):
        assert eng._sync(np.zeros(2), "probe step") == ["nan"]
    assert eng._sync(np.zeros(2), "probe step") == []


# --- live in-process server ---

@pytest.fixture
def api(stack):
    from dllama_tpu.server.api import ApiState, serve

    servers = []

    def make(**kw):
        eng, tok = stack
        state = ApiState(eng, tok, default_temperature=0.0, chunk=2, **kw)
        srv = serve(state, host="127.0.0.1", port=free_port(), block=False)
        servers.append(srv)
        return state, f"http://127.0.0.1:{srv.server_address[1]}"

    yield make
    for s in servers:
        s.shutdown()
        s.server_close()


def post(base, path, body, timeout=240):
    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


CHAT = "/v1/chat/completions"
BODY = {"messages": [{"role": "user", "content": "hello"}], "seed": 3}


def _wait_active(state, n=1, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if state.queue_depths()[0] >= n:
            return
        time.sleep(0.01)
    pytest.fail("request never became active")


def _wait_idle(state, timeout=10.0):
    """The admission slot frees a beat AFTER the client has its response
    (the handler thread still has to run its accounting) — wait it out
    before a test that needs the next request to own the queue."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if state.queue_depths() == (0, 0):
            return
        time.sleep(0.005)
    pytest.fail("server never went idle")


def test_health_is_enriched_and_metrics_export(api):
    state, base = api(max_pending=5)
    h = get(base, "/health")
    assert h["status"] == "ok" and h["ready"] is True
    assert h["backend"] == "cpu" and h["mesh"].get("tp") == 1
    assert (h["in_flight"], h["queued"], h["max_pending"]) == (0, 0, 5)
    assert h["seq_len"] == 128 and h["uptime_s"] >= 0
    m = get(base, "/metrics")
    for k in ("requests_served", "requests_rejected_429", "deadline_timeouts",
              "client_disconnects", "read_timeouts_408", "avg_request_s"):
        assert k in m


def test_disconnect_mid_stream_rewinds_pos_and_server_survives(api):
    state, base = api()
    eng = state.engine
    body = dict(BODY, max_tokens=24, stream=True)
    with injected("server.emit_delta=disconnect"):
        with post(base, CHAT, body) as r:
            raw = r.read()  # server aborts the stream; no terminator
        assert b"[DONE]" not in raw
        (f,) = FAULTS.snapshot()
        assert f.fired >= 1, "the injected disconnect must actually fire"
    # THE invariant: the cache's last entry records exactly the position
    # the KV cache was rewound to (runtime/stream.py pos-rewind contract)
    assert state.naive_cache.items
    assert eng.pos == state.naive_cache.items[-1].end_pos
    assert get(base, "/metrics")["client_disconnects"] >= 1
    # and the server still serves: a fresh conversation works end to end
    with post(base, CHAT, {"messages": [{"role": "user", "content": "again"}],
                           "max_tokens": 4, "seed": 1}) as r:
        data = json.loads(r.read())
    assert data["choices"][0]["message"]["content"] is not None
    assert data["choices"][0]["finish_reason"] == "stop"


def test_deadline_expiry_returns_truncated_timeout_completion(api):
    state, base = api()
    with post(base, CHAT, dict(BODY, max_tokens=4)) as r:
        json.loads(r.read())  # warm the compile caches off the clock
    t0 = time.monotonic()
    with injected("engine.device_step=delay:0.4"):
        with post(base, CHAT, dict(BODY, max_tokens=32, timeout=0.6)) as r:
            data = json.loads(r.read())
    elapsed = time.monotonic() - t0
    assert data["object"] == "chat.completion"  # well-formed OpenAI shape
    assert data["choices"][0]["finish_reason"] == "timeout"
    assert data["usage"]["completion_tokens"] >= 1  # truncated, not empty
    # bounded: deadline + one in-flight chunk (+ slack for a slow box)
    assert elapsed < 6.0
    assert get(base, "/metrics")["deadline_timeouts"] >= 1


def test_full_queue_answers_429_with_retry_after(api):
    state, base = api(max_pending=1)
    with post(base, CHAT, dict(BODY, max_tokens=2)) as r:
        r.read()  # warm
    _wait_idle(state)
    results = {}
    with injected("engine.device_step=delay:0.2"):
        def slow():
            with post(base, CHAT, dict(BODY, max_tokens=16)) as r:
                results["slow"] = json.loads(r.read())
        t = threading.Thread(target=slow)
        t.start()
        _wait_active(state)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(base, CHAT, dict(BODY, max_tokens=2))
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        t.join(120)
    # the rejected request never disturbed the admitted one
    assert results["slow"]["choices"][0]["message"]["content"] is not None
    assert get(base, "/metrics")["requests_rejected_429"] >= 1


def test_stalled_body_read_answers_408(api):
    state, base = api()
    with injected("server.read_body=raise:TimeoutError"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(base, CHAT, dict(BODY, max_tokens=2))
    assert ei.value.code == 408
    assert state.metrics.read_timeouts_408 == 1


def test_drain_rejects_new_work_and_finishes_inflight(api):
    state, base = api(drain_grace=60.0)
    with post(base, CHAT, dict(BODY, max_tokens=2)) as r:
        r.read()  # warm
    _wait_idle(state)
    results = {}
    with injected("engine.device_step=delay:0.2"):
        def slow():
            with post(base, CHAT, dict(BODY, max_tokens=16)) as r:
                results["slow"] = json.loads(r.read())
        t = threading.Thread(target=slow)
        t.start()
        _wait_active(state)
        state.begin_drain()
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(base, CHAT, dict(BODY, max_tokens=2))
        assert ei.value.code == 503
        assert "Retry-After" in ei.value.headers
        t.join(120)
    # generous grace: the in-flight request ran to its natural finish
    assert results["slow"]["choices"][0]["finish_reason"] == "stop"
    assert get(base, "/health")["status"] == "draining"
    assert state.metrics.requests_rejected_503 >= 1


def test_sigterm_drains_inflight_then_exits_cleanly(tmp_path):
    """Real-process drill: SIGTERM mid-request → the in-flight request
    completes, new connections stop being served, exit code 0."""
    m, t = str(tmp_path / "tiny.m"), str(tmp_path / "tiny.t")
    write_tiny_model(m)
    write_tiny_tokenizer(t)
    port = free_port()
    env = cpu_env()
    # slow decode so the request is reliably in flight when SIGTERM lands
    env["DLLAMA_FAULTS"] = "engine.device_step=delay:0.15"
    proc = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu.server.api", "--model", m,
         "--tokenizer", t, "--port", str(port), "--temperature", "0",
         "--max-seq-len", "64", "--drain-grace", "60", "--io-timeout", "5"],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    base = f"http://127.0.0.1:{port}"
    try:
        for _ in range(600):
            if proc.poll() is not None:
                raise RuntimeError(f"server died:\n{proc.stdout.read()}")
            try:
                urllib.request.urlopen(base + "/health", timeout=1)
                break
            except OSError:
                time.sleep(0.2)
        else:
            raise RuntimeError("server did not come up")
        results = {}

        def slow():
            with post(base, CHAT, dict(BODY, max_tokens=48)) as r:
                results["slow"] = json.loads(r.read())

        t_req = threading.Thread(target=slow)
        t_req.start()
        for _ in range(600):  # wait until the request is actually decoding
            if get(base, "/health")["in_flight"] >= 1:
                break
            time.sleep(0.05)
        else:
            pytest.fail("request never became active")
        proc.send_signal(signal.SIGTERM)
        t_req.join(180)
        assert not t_req.is_alive()
        data = results["slow"]  # in-flight request finished, well-formed
        assert data["choices"][0]["message"]["content"] is not None
        assert data["choices"][0]["finish_reason"] in ("stop", "timeout")
        assert proc.wait(timeout=120) == 0  # drained and exited cleanly
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
