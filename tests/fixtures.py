"""Shared fixture builders: tiny `.m`/`.t` files usable end-to-end
(CLI/API subprocess tests) — the analogue of the reference's generated
xorshift weight fixtures (llama2-tasks-test.cpp:556-562)."""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np

from dllama_tpu import quants
from dllama_tpu.io import mfile, tfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHATML_JINJA = "{% for message in messages %}<|im_start|>...jinja...{% endfor %}"


def write_tiny_model(path, *, arch=mfile.ARCH_LLAMA, ftype=quants.Q80,
                     vocab_size=300, n_experts=0, seq_len=128, seed=0) -> mfile.ModelSpec:
    spec = mfile.ModelSpec(
        arch=arch, dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
        n_experts=n_experts, n_active_experts=2 if n_experts else 0,
        vocab_size=vocab_size, seq_len=seq_len, hidden_act=mfile.ACT_SILU,
        rope_theta=10000.0, weights_ftype=ftype)
    rng = np.random.RandomState(seed)
    with mfile.MFileWriter(path, spec) as w:
        for t in w.plan:
            w.write_tensor(t.name, (rng.randn(*t.shape) * 0.05).astype(np.float32))
    return spec


def write_tiny_tokenizer(path, vocab_size=300) -> tfile.TokenizerData:
    """Vocab: 3 specials (+ 256 byte tokens when it fits) + a few words;
    chatml template.  Small vocab sizes skip the byte-fallback pieces."""
    vocab = [b"<unk>", b"<s>", b"</s>"]
    words = [b" ", b"a", b"b", b"e", b"h", b"i", b"l", b"o", b"he", b"ll",
             b"hell", b"hello", b"hi", b" hi", b" hello",
             b"<|im_end|>", b"<|im_start|>"]
    if vocab_size >= 3 + 256 + len(words):
        vocab += [f"<0x{i:02X}>".encode() for i in range(256)]
    vocab += words
    if len(vocab) > vocab_size:
        raise ValueError(f"vocab_size {vocab_size} too small for fixture")
    while len(vocab) < vocab_size:
        vocab.append(f"<extra_{len(vocab)}>".encode())
    scores = [float(len(v)) if v in words else 0.0 for v in vocab]
    t = tfile.TokenizerData(
        vocab=vocab, scores=scores, bos_id=1, eos_id=2,
        chat_eos_id=vocab.index(b"<|im_end|>"),
        chat_template=CHATML_JINJA, chat_stop=None)
    tfile.write_tfile(path, t)
    return t


def free_port() -> int:
    """An OS-assigned free TCP port (shared by every server-spawning test)."""
    import socket
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def cpu_env(n_devices: int = 1) -> dict:
    """Subprocess env that actually selects the CPU backend (shared recipe,
    see dllama_tpu/hostenv.py)."""
    from dllama_tpu.hostenv import forced_cpu_env

    env = forced_cpu_env(n_devices)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def run_cli(args: list[str], *, input_text: str | None = None, n_devices: int = 1,
            timeout: int = 240, env: dict | None = None) -> subprocess.CompletedProcess:
    """``env`` overlays extra variables (e.g. DLLAMA_Q40_LAYOUT) on the
    forced-CPU base environment."""
    full_env = cpu_env(n_devices)
    if env:
        full_env.update(env)
    return subprocess.run(
        [sys.executable, "-m", "dllama_tpu", *args], cwd=REPO, env=full_env,
        input=input_text, capture_output=True, text=True, timeout=timeout)
