"""Cross-parity against the ACTUAL reference binary (VERDICT r03 Next #4).

Every other numerics test compares the runtime to `tests/reference_impl.py`,
an independent numpy rewrite — but both were written by the same author.
This suite removes that blind spot: it builds the reference's C++ `dllama`
from `/root/reference` (Makefile:11-41 recipe, compiled out-of-tree because
the reference checkout is read-only), synthesizes a tiny model + tokenizer
through OUR writers (`io/mfile.MFileWriter`, `io/tfile.write_tfile`), runs
BOTH engines' `generate` mode at temperature 0, and asserts the token
streams are identical — the spirit of the reference's own golden-output
test (llama2-tasks-test.cpp:556-605), but with the real binary as oracle.

What identical streams certify end-to-end:
  * `.m`/`.t` byte compatibility (the reference binary parses our files);
  * tokenizer encode parity (the forced prompt pieces match);
  * forward-pass numerics parity (24 greedy argmax steps agree — through
    rmsnorm, RoPE, GQA attention, SiLU FFN, and the Q40 codec for the
    quantized case);
  * sampler greedy semantics (tokenizer.cpp:387-389).

Print-alignment note: the reference prints transition pieces t0→t1 …
t_{S-1}→t_S (dllama.cpp:45-93), ours prints bos→t0 … t_{S-2}→t_{S-1}
(cli.py cmd_generate) — so ours equals "<s>" + (reference text minus its
final piece).  The assertions below encode exactly that relation.

Skipped when g++ or the reference checkout is unavailable.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

import numpy as np
import pytest

from dllama_tpu import quants
from dllama_tpu.io import mfile

from fixtures import REPO, run_cli, write_tiny_tokenizer

REF = os.environ.get("DLLAMA_REF", "/root/reference")
BUILD = os.path.join(REPO, "build", "ref")
# translation units from the reference Makefile's `dllama` rule
_TUS = ["utils", "quants", "funcs", "commands", "socket", "transformer",
        "tasks", "llama2-tasks", "grok1-tasks", "mixtral-tasks", "tokenizer",
        "app"]

pytestmark = [
    pytest.mark.slow,  # first run compiles 13 C++ TUs
    pytest.mark.skipif(
        shutil.which("g++") is None or not os.path.isfile(
            os.path.join(REF, "src", "apps", "dllama", "dllama.cpp")),
        reason="needs g++ and the reference checkout"),
]


# plain -O2, NOT the reference Makefile's -march=native: with native
# vectorization the reference's Q80-weights forward reads uninitialized
# memory and nondeterministically produces all-NaN logits (reproduced on an
# all-zero Q80 file; its own CI never runs a Q80-weights model end-to-end,
# and funcs-test only covers the bare kernel).  At -O2 the same binary is
# deterministic and matches us token-for-token.
_CC_FLAGS = ["-std=c++11", "-O2"]


def _ref_binary() -> str:
    """Build (once) and return the reference dllama.  The cache is keyed on
    the compile flags (stamp file): a binary built with different flags —
    e.g. the pre-fix -march=native one — must never be served."""
    exe = os.path.join(BUILD, "dllama")
    stamp = os.path.join(BUILD, "flags.txt")
    want = " ".join(_CC_FLAGS)
    if os.path.isfile(exe) and os.path.isfile(stamp) \
            and open(stamp).read() == want:
        return exe
    shutil.rmtree(BUILD, ignore_errors=True)  # drop stale objects too
    os.makedirs(BUILD, exist_ok=True)
    cc = ["g++"] + _CC_FLAGS
    objs = []
    for tu in _TUS:
        obj = os.path.join(BUILD, tu + ".o")
        subprocess.run(cc + ["-c", os.path.join(REF, "src", tu + ".cpp"),
                             "-o", obj], check=True, timeout=180)
        objs.append(obj)
    # link to a temp name then rename: an interrupted link must not leave a
    # truncated binary that the isfile() cache check would trust forever
    subprocess.run(cc + [os.path.join(REF, "src", "apps", "dllama", "dllama.cpp"),
                         "-o", exe + ".part"] + objs + ["-lpthread"],
                   check=True, timeout=180)
    os.replace(exe + ".part", exe)
    with open(stamp, "w") as f:
        f.write(want)
    return exe


def _write_model(path: str, ftype: int, arch: int = mfile.ARCH_LLAMA,
                 n_experts: int = 0, seq_len: int = 64) -> None:
    # dims are reference-legal for every weights ftype: its Q40 microkernel
    # asserts n % 256 == 0 on each matmul's input dim (funcs.cpp:213-217)
    spec = mfile.ModelSpec(
        arch=arch, dim=256, hidden_dim=512, n_layers=2, n_heads=4,
        n_kv_heads=2, n_experts=n_experts,
        n_active_experts=2 if n_experts else 0, vocab_size=128,
        seq_len=seq_len,
        hidden_act=mfile.ACT_GELU if arch == mfile.ARCH_GROK1 else mfile.ACT_SILU,
        rope_theta=10000.0, weights_ftype=ftype)
    # seed 0 chosen by a margin sweep: the worst top-2 greedy logit margin
    # across every parity config is ≥0.09% of the logit scale (1.5% for
    # the 24-step generate cases) — ~100× above plausible cross-build
    # accumulation noise, so the exact-stream assertions cannot flake on a
    # different XLA/BLAS than the one that authored them (seed 3's worst
    # margin was 0.03%, with single steps at 0.08% of scale)
    rng = np.random.RandomState(0)
    with mfile.MFileWriter(path, spec) as w:
        for t in w.plan:
            w.write_tensor(t.name, (rng.randn(*t.shape) * 0.05).astype(np.float32))


def _ref_generate(exe: str, mpath: str, tpath: str, prompt: str, steps: int) -> str:
    r = subprocess.run(
        [exe, "generate", "--model", mpath, "--tokenizer", tpath,
         "--prompt", prompt, "--steps", str(steps), "--temperature", "0",
         "--seed", "1", "--nthreads", "2", "--buffer-float-type", "f32"],
        capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stdout + r.stderr
    lines = r.stdout.splitlines()
    # the stream is the single line between the loader's "Loaded" line and
    # the "Generated tokens:" stats block
    idx = next(i for i, l in enumerate(lines) if l.startswith("Generated tokens:"))
    return lines[idx - 1]


def _our_generate(mpath: str, tpath: str, prompt: str, steps: int) -> str:
    r = run_cli(["generate", "--model", mpath, "--tokenizer", tpath,
                 "--prompt", prompt, "--steps", str(steps), "--temperature", "0",
                 "--seed", "1", "--buffer-float-type", "f32", "--chunk", "8"])
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout.splitlines()[-1]


@pytest.mark.parametrize("ftype", [quants.F32, quants.F16, quants.Q40,
                                   quants.Q80],
                         ids=["f32-weights", "f16-weights", "q40-weights",
                              "q80-weights"])
def test_generate_stream_matches_reference_binary(tmp_path, ftype):
    exe = _ref_binary()
    mpath, tpath = str(tmp_path / "toy.m"), str(tmp_path / "toy.t")
    _write_model(mpath, ftype)
    write_tiny_tokenizer(tpath, vocab_size=128)
    steps = 24

    ref_text = _ref_generate(exe, mpath, tpath, "hello hi", steps)
    our_text = _our_generate(mpath, tpath, "hello hi", steps)

    assert our_text.startswith("<s>hello hi"), our_text  # prompt echo + encode parity
    gen = our_text[len("<s>"):]
    # ours == reference minus its final transition piece (see module docstring);
    # require the full 23 shared transitions to match exactly
    assert ref_text.startswith(gen), f"ref={ref_text!r}\nours={gen!r}"
    # and the match must extend well past the prompt into sampled territory
    assert len(gen) > len("hello hi") + 20, gen


def _ref_api_binary() -> str:
    """Link the reference dllama-api against the cached objects."""
    exe = os.path.join(BUILD, "dllama-api")
    _ref_binary()  # ensures objects exist with the right flags
    if not os.path.isfile(exe):
        objs = [os.path.join(BUILD, tu + ".o") for tu in _TUS]
        subprocess.run(
            ["g++"] + _CC_FLAGS +
            [os.path.join(REF, "src", "apps", "dllama-api", "dllama-api.cpp"),
             "-o", exe + ".part"] + objs + ["-lpthread"],
            check=True, timeout=180)
        os.replace(exe + ".part", exe)
    return exe


def _post_chat(port: int, body: dict, timeout: float = 180) -> dict:
    """POST /v1/chat/completions as ONE TCP segment (single sendall).

    The reference api's reader parses whatever its first read() returns —
    a request whose headers and body arrive in separate segments (as
    urllib sends them) gets its body truncated when the server isn't busy
    enough for the kernel to coalesce the segments (observed: empty
    messages, max_tokens lost; an upstream short-read bug).  One write
    sidesteps it deterministically for both servers."""
    import socket
    payload = json.dumps(body).encode()
    req = (f"POST /v1/chat/completions HTTP/1.1\r\nHost: 127.0.0.1\r\n"
           f"Content-Type: application/json\r\n"
           f"Content-Length: {len(payload)}\r\n"
           f"Connection: close\r\n\r\n").encode() + payload
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(req)
        raw = b""
        while True:
            head, sep, rest = raw.partition(b"\r\n\r\n")
            if sep:
                m = [l.split(b":", 1)[1].strip() for l in head.split(b"\r\n")
                     if l.lower().startswith(b"content-length:")]
                if m and len(rest) >= int(m[0]):
                    break  # complete body — don't wait for a close
            data = s.recv(65536)
            if not data:
                break
            raw += data
    head, _, rest = raw.partition(b"\r\n\r\n")
    parts = head.split(b" ", 2)
    if len(parts) < 2 or not rest:
        # closed without a (complete) response — retryable, not a crash
        raise ConnectionError(f"empty/truncated response: {raw[:200]!r}")
    assert int(parts[1]) == 200, raw[:400]
    try:
        return json.loads(rest)
    except json.JSONDecodeError as e:
        raise ConnectionError(f"truncated body: {e}") from e


def _post_chat_retry(port: int, body: dict, proc, deadline_s: float = 150) -> dict:
    """Readiness via the real request succeeding (a bare empty port probe
    also desyncs the reference's reader).  Fails fast with the server's
    output if ``proc`` died; each attempt's socket timeout is bounded by
    the remaining deadline."""
    t0 = time.time()
    while True:
        if proc.poll() is not None:
            out = b"".join(f.read() for f in (proc.stdout, proc.stderr) if f)
            raise RuntimeError(
                f"server exited rc={proc.returncode}: {out[-800:]!r}")
        remaining = deadline_s - (time.time() - t0)
        try:
            return _post_chat(port, body, timeout=max(remaining, 5.0))
        except (ConnectionError, OSError):
            if remaining <= 0:
                raise
            time.sleep(1.0)


def test_api_server_matches_reference_api_binary(tmp_path):
    """API-layer cross-parity (dllama-api.cpp): the same POST
    /v1/chat/completions at temperature 0 must yield the same completion
    content and IDENTICAL usage counts from both servers — externally
    validating the template render, prompt accounting, max_tokens budget,
    and usage fields (:284, :336-345).  The reference appends one extra
    transition piece to its content (same print alignment as generate
    mode), so ours must be a strict prefix with equal token counts."""
    api = _ref_api_binary()
    mpath, tpath = str(tmp_path / "toy.m"), str(tmp_path / "toy.t")
    _write_model(mpath, quants.F32, seq_len=256)
    write_tiny_tokenizer(tpath, vocab_size=128)
    body = {"messages": [{"role": "user", "content": "hello hi"}],
            "temperature": 0, "seed": 1, "max_tokens": 24}

    from fixtures import cpu_env, free_port

    ref_port = free_port()
    ref = subprocess.Popen(
        [api, "--model", mpath, "--tokenizer", tpath, "--temperature", "0",
         "--seed", "1", "--nthreads", "1", "--buffer-float-type", "f32",
         "--port", str(ref_port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        ref_out = _post_chat_retry(ref_port, body, ref, 60)
    finally:
        ref.kill()

    our_port = free_port()
    ours = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu.server.api", "--model", mpath,
         "--tokenizer", tpath, "--temperature", "0", "--seed", "1",
         "--buffer-float-type", "f32", "--chunk", "8", "--port", str(our_port)],
        cwd=REPO, env=cpu_env(1), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    try:
        our_out = _post_chat_retry(our_port, body, ours)
    finally:
        ours.kill()

    ref_msg = ref_out["choices"][0]["message"]
    our_msg = our_out["choices"][0]["message"]
    assert our_msg["role"] == ref_msg["role"] == "assistant"
    assert len(our_msg["content"]) > 40
    assert ref_msg["content"].startswith(our_msg["content"]), (
        f"ref={ref_msg['content']!r}\nours={our_msg['content']!r}")
    assert our_out["usage"] == ref_out["usage"]


def test_api_multiturn_conversation_matches_reference(tmp_path):
    """Multi-turn conversation parity: a 3-message conversation (user →
    assistant → user) rendered, prefilled and completed identically by
    both servers.  The assistant content is plain encodable text so both
    engines re-prefill the same token ids — generated synthetic pieces
    would NOT round-trip decode→encode (a BPE property, not a bug: with
    the toy vocab the reference re-encoded a turn-1 reply to 365 tokens,
    overflowing its context into an empty reply with negative usage —
    its api has no overflow refusal, dllama-api.cpp:284).  Our server's
    cache-resume ≡ recompute invariant is covered by tests/test_api.py;
    this test pins the cross-engine conversation rendering."""
    api = _ref_api_binary()
    mpath, tpath = str(tmp_path / "toy.m"), str(tmp_path / "toy.t")
    _write_model(mpath, quants.F32, seq_len=256)
    write_tiny_tokenizer(tpath, vocab_size=128)
    convo = {"messages": [{"role": "user", "content": "hello hi"},
                          {"role": "assistant", "content": " hello hello hi"},
                          {"role": "user", "content": "hi hello"}],
             "temperature": 0, "seed": 1, "max_tokens": 16}

    from fixtures import cpu_env, free_port

    ref_port = free_port()
    ref = subprocess.Popen(
        [api, "--model", mpath, "--tokenizer", tpath, "--temperature", "0",
         "--seed", "1", "--nthreads", "1", "--buffer-float-type", "f32",
         "--port", str(ref_port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        ref_out = _post_chat_retry(ref_port, convo, ref, 60)
    finally:
        ref.kill()

    our_port = free_port()
    ours = subprocess.Popen(
        [sys.executable, "-m", "dllama_tpu.server.api", "--model", mpath,
         "--tokenizer", tpath, "--temperature", "0", "--seed", "1",
         "--buffer-float-type", "f32", "--chunk", "8", "--port", str(our_port)],
        cwd=REPO, env=cpu_env(1), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    try:
        our_out = _post_chat_retry(our_port, convo, ours)
    finally:
        ours.kill()

    our_c = our_out["choices"][0]["message"]["content"]
    ref_c = ref_out["choices"][0]["message"]["content"]
    assert len(our_c) > 20, our_c
    assert ref_c.startswith(our_c), f"ref={ref_c!r}\nours={our_c!r}"
    assert our_out["usage"] == ref_out["usage"]


def test_chat_turn_matches_reference_binary(tmp_path):
    """Chat-mode parity: chatml template rendering (tokenizer.cpp:447-465),
    prompt prefill across the template, streaming EOS holdback, and the
    context-end stop all reproduce the reference's first assistant turn
    byte-for-byte at temperature 0 (dllama.cpp:111-203)."""
    exe = _ref_binary()
    mpath, tpath = str(tmp_path / "toy.m"), str(tmp_path / "toy.t")
    _write_model(mpath, quants.F32, seq_len=256)
    write_tiny_tokenizer(tpath, vocab_size=128)
    stdin = "sys prompt here\nhello hi\n"

    def turn(out: str) -> str:
        assert "🤖 Assistant" in out, out
        body = out.split("🤖 Assistant", 1)[1]
        for stop in ("(end of context)", "👱 User"):
            body = body.split(stop, 1)[0]
        return body.strip()

    # the reference's chat REPL busy-loops on stdin EOF, but a turn that
    # fills the context makes it exit on its own (dllama.cpp:189-191), so
    # communicate() terminates once generation hits seq_len
    r = subprocess.run(
        [exe, "chat", "--model", mpath, "--tokenizer", tpath,
         "--temperature", "0", "--seed", "1", "--nthreads", "1",
         "--buffer-float-type", "f32"],
        input=stdin, capture_output=True, text=True, timeout=300)
    ref_turn = turn(r.stdout)

    from fixtures import run_cli
    ours = run_cli(["chat", "--model", mpath, "--tokenizer", tpath,
                    "--temperature", "0", "--seed", "1",
                    "--buffer-float-type", "f32", "--chunk", "8"],
                   input_text=stdin)
    assert ours.returncode == 0, ours.stdout + ours.stderr
    our_turn = turn(ours.stdout)

    assert len(our_turn) > 200, our_turn  # a real multi-hundred-token turn
    if "(end of context)" in r.stdout:
        # turn ended by exhausting seq_len (no EOS): the engines disagree
        # by at most ONE trailing piece at that boundary (the reference's
        # loop stops at seqLen-1 positions while ours flushes the final
        # budgeted token) — everything before it must match byte-for-byte
        longer, shorter = ((our_turn, ref_turn) if len(our_turn) >= len(ref_turn)
                           else (ref_turn, our_turn))
        assert longer.startswith(shorter), f"ref={ref_turn!r}\nours={our_turn!r}"
        assert len(longer) - len(shorter) <= 12, (  # ≤ one piece
            f"tail diff too large: {len(longer) - len(shorter)}")
    else:
        # EOS-terminated turns must match exactly (the holdback contract)
        assert our_turn == ref_turn


@pytest.mark.parametrize("arch", [mfile.ARCH_MIXTRAL, mfile.ARCH_GROK1],
                         ids=["mixtral", "grok1"])
def test_moe_archs_match_reference_binary(tmp_path, arch):
    """MoE task-graph parity against the real binary: router softmax/top-k/
    renormalize semantics (grok1-tasks.cpp:60-114), rotate-half RoPE
    (FalconRopeCommand), Grok's embedding/logit scales, post-block norms,
    GELU experts, and the no-BOS Grok prompt rule (dllama.cpp:27)."""
    exe = _ref_binary()
    mpath, tpath = str(tmp_path / "toy.m"), str(tmp_path / "toy.t")
    _write_model(mpath, quants.Q40, arch=arch, n_experts=4)
    write_tiny_tokenizer(tpath, vocab_size=128)
    steps = 20

    ref_text = _ref_generate(exe, mpath, tpath, "hello hi", steps)
    our_text = _our_generate(mpath, tpath, "hello hi", steps)

    if arch == mfile.ARCH_MIXTRAL:
        # BOS prepended: same alignment as the llama cases
        assert our_text.startswith("<s>hello hi"), our_text
        gen = our_text[len("<s>"):]
    else:
        # Grok-1: no BOS (dllama.cpp:27) — the reference's printed stream
        # starts at the transition out of the FIRST prompt token, so its
        # text is ours minus our leading bos→"hello" piece
        assert our_text.startswith("hello hi"), our_text
        gen = our_text[len("hello"):]
    assert ref_text.startswith(gen), f"ref={ref_text!r}\nours={gen!r}"
    assert len(gen) > 12 + 20, gen  # well past the prompt, MoE experts live
