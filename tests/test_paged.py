"""Paged KV pool + radix prefix cache tests (runtime/pagepool.py, the
paged primitives in ops/attention.py, and the scheduler's page plumbing).

The tentpole contracts, each pinned here on CPU with a tiny model:

* **byte parity** — a greedy request served through the paged pool is
  token-identical to the same request on the contiguous solo engine,
  alone and with ragged staggered neighbors (pages are an addressing
  change, never a numerics change);
* **recycling** — pages freed by retirement are rebound to later
  requests with no stale-KV leak: the recycled occupant still decodes
  byte-identically (write-before-visible holds per page);
* **refcounts** — after arbitrary churn the pool's refcount/free-list
  invariants hold exactly (``PagePool.check``);
* **prefix sharing** — a repeated prompt prefix matches the radix tree,
  binds shared pages copy-free (``prefix_tokens_reused_total`` counts
  it), decodes byte-identically, and does strictly less prefill work
  than the same traffic with reuse disabled (PR-7 flight phases);
* **memory win** — a pool holding fewer tokens than slots × seq_len
  still serves every slot concurrently: per-request reservation replaces
  the contiguous layout's worst-case per-slot allocation;
* **exhaustion** — an admission that cannot get pages defers (queued,
  ``kv_pool_exhausted_total``) and completes once retirements free
  pages; it never surfaces as a dispatch error.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.obs import flight as obs_flight, metrics as obs_metrics
from dllama_tpu.ops.attention import (_rows_ceiling_attention,
                                      paged_decode_attention,
                                      paged_gather_layer)
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import ContextOverflow, Engine
from dllama_tpu.runtime.pagepool import (PagePool, PagePoolExhausted,
                                         RadixTree)
from dllama_tpu.runtime.scheduler import SlotScheduler

CFG = tiny_config(seq_len=64)
PAGE = 8
P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]
P3 = [2, 4, 6]
P4 = [9, 8, 7, 6]
PROMPTS = (P1, P2, P3, P4)


# -- host-side allocator ---------------------------------------------------

def test_pool_alloc_refcount_exhaustion():
    pool = PagePool(5, 4)  # pages 1..4 usable
    assert pool.capacity == 4 and pool.available == 4
    a = pool.alloc(3)
    assert sorted(a) == [1, 2, 3] and pool.in_use == 3
    with pytest.raises(PagePoolExhausted):
        pool.alloc(2)
    assert pool.available == 1  # a failed alloc must not leak pages
    pool.incref(a[:1])
    pool.decref(a)  # drops to refs: [2]=0 [3]=0, [1]=1
    assert pool.available == 3
    pool.decref(a[:1])
    assert pool.available == 4
    with pytest.raises(RuntimeError):
        pool.decref(a[:1])  # double free
    with pytest.raises(RuntimeError):
        pool.decref([0])  # scratch is pinned
    pool.check()


def test_pool_claim_and_check():
    pool = PagePool(4, 2)
    pool.claim(2)
    assert pool.in_use == 1
    with pytest.raises(RuntimeError):
        pool.claim(2)  # already live
    with pytest.raises(RuntimeError):
        pool.claim(0)
    pool.check()
    pool.decref([2])
    pool.check()


def test_radix_match_insert_evict():
    pool = PagePool(8, 2)
    tree = RadixTree(pool)
    toks = [1, 2, 3, 4, 5]  # two full blocks + a partial
    pages = pool.alloc(2)
    assert tree.insert(toks, pages) == 2
    assert len(tree) == 2
    # insert took its own refs: the "slot" frees, the tree retains
    pool.decref(pages)
    assert pool.in_use == 2
    matched, got = tree.match([1, 2, 3, 4, 9, 9])
    assert matched == 4 and got == pages
    assert tree.match([9, 9, 9, 9])[0] == 0
    assert tree.match([1, 2])[0] == 2  # one full block
    # a second request re-inserting the same blocks adds nothing
    assert tree.insert(toks, pages) == 0
    # eviction frees tree-only pages, deepest-leaf first
    assert tree.evict(2) == 2
    assert pool.available == pool.capacity and len(tree) == 0
    pool.check()


def test_radix_evict_spares_referenced_pages():
    pool = PagePool(8, 2)
    tree = RadixTree(pool)
    pages = pool.alloc(2)
    tree.insert([1, 2, 3, 4], pages)
    # a live slot still holds the pages (refs 2): nothing is evictable
    assert tree.evict(2) == 0
    pool.decref(pages[1:])  # leaf page now tree-only
    assert tree.evict(2) == 1
    assert len(tree) == 1
    pool.decref(pages[:1])
    pool.check()


def test_radix_export_restore_roundtrip():
    pool = PagePool(8, 2)
    tree = RadixTree(pool)
    pages = pool.alloc(3)
    tree.insert([1, 2, 3, 4], pages[:2])
    # a branching second prompt: same first block (existing node wins, no
    # new reference), fresh second block
    tree.insert([1, 2, 9, 9], [pages[0], pages[2]])
    pool.decref(pages)
    data = tree.export()
    pool2 = PagePool(8, 2)
    tree2 = RadixTree(pool2)
    tree2.restore(data)
    assert len(tree2) == 3 and pool2.in_use == 3
    assert tree2.match([1, 2, 9, 9]) == (4, [pages[0], pages[2]])
    pool2.check()
    with pytest.raises(RuntimeError):
        tree2.restore(data)  # only into an empty tree


# -- device-side paged attention ------------------------------------------

def test_paged_decode_matches_gather_attention():
    """The page-walking decode fold must equal the one-shot gather-view
    attention on the same pool — they are the same logical computation, so
    any divergence is a fold-masking bug.  Geometry chosen to clear the
    blocked-decode dispatch threshold (s >= 4096)."""
    rng = np.random.RandomState(3)
    L, n_pages, hkv, ps, dh, b, hq = 1, 40, 2, 128, 8, 3, 4
    maxp = 32  # s = 4096
    pool_k = jnp.asarray(rng.randn(L, n_pages, hkv, ps, dh), jnp.float32)
    pool_v = jnp.asarray(rng.randn(L, n_pages, hkv, ps, dh), jnp.float32)
    # arbitrary (even repeating) physical pages: the logical view is
    # whatever the table says it is
    table = jnp.asarray(rng.randint(0, n_pages, (b, maxp)), jnp.int32)
    q = jnp.asarray(rng.randn(b, hq, 1, dh), jnp.float32)
    pos_rows = jnp.asarray([130, 4095, 700], jnp.int32)
    layer = jnp.int32(0)
    got = paged_decode_attention(q, pool_k, pool_v, layer, table, pos_rows)
    k_l = paged_gather_layer(pool_k, layer, table)
    v_l = paged_gather_layer(pool_v, layer, table)
    want = _rows_ceiling_attention(q, k_l, v_l, pos_rows)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# -- scheduler over the paged engine --------------------------------------

def make_contiguous_engine(batch=1):
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch)


def make_paged_engine(batch=4, kv_pages=None, page=PAGE):
    # default pool: every slot can hold a full seq_len (parity testing);
    # the memory-win test passes a smaller pool explicitly
    pages_per_slot = -(-CFG.seq_len // page)
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch,
                  kv_pages=kv_pages or batch * pages_per_slot + 1,
                  kv_page_size=page)


@pytest.fixture(scope="module")
def solo_refs():
    """Greedy solo completions per prompt on the CONTIGUOUS engine — the
    cross-layout parity oracle."""
    eng = make_contiguous_engine()
    refs = {}
    for p in PROMPTS:
        eng.reset()
        toks = [t for t, _ in eng.generate_stream(
            p, len(p) + 30, temperature=0.0, chunk=5)]
        refs[tuple(p)] = toks[len(p):]
    return refs


@pytest.fixture(scope="module")
def paged_stack():
    """One paged batch=4 engine + scheduler shared across tests — page
    recycling across tests IS part of the contract under test."""
    eng = make_paged_engine(4)
    sched = SlotScheduler(eng, prefill_chunk=4, max_wait_ms=20.0,
                          decode_burst=4)
    yield eng, sched
    sched.close()


def _collect(sched, prompt, max_new=30, delay=0.0):
    time.sleep(delay)
    t = sched.submit(prompt, max_new, temperature=0.0)
    return t, list(t.tokens())


def test_paged_greedy_parity_ragged_traffic(solo_refs, paged_stack):
    """4 staggered greedy requests with ragged prompt lengths through the
    paged pool: every stream byte-identical to its solo contiguous run."""
    _, sched = paged_stack
    outs = {}

    def run(p, delay):
        _, toks = _collect(sched, p, max_new=30, delay=delay)
        outs[tuple(p)] = toks

    ths = [threading.Thread(target=run, args=(p, 0.02 * i))
           for i, p in enumerate(PROMPTS)]
    for t in ths:
        t.start()
    for t in ths:
        t.join(120)
    for p in PROMPTS:
        want = solo_refs[tuple(p)][:len(outs[tuple(p)])]
        assert outs[tuple(p)] == want, f"prompt {p} diverged"
        assert len(outs[tuple(p)]) > 0


def test_page_recycling_no_stale_kv(solo_refs, paged_stack):
    """Churn: two waves of more requests than slots force every page
    through free→bound→free→bound; recycled pages must never leak a
    previous occupant's KV into a new stream."""
    _, sched = paged_stack
    for _ in range(2):
        outs = {}

        def run(p):
            _, toks = _collect(sched, p, max_new=10)
            outs[tuple(p)] = toks

        ths = [threading.Thread(target=run, args=(p,)) for p in PROMPTS]
        for t in ths:
            t.start()
        for t in ths:
            t.join(120)
        for p in PROMPTS:
            assert outs[tuple(p)] == solo_refs[tuple(p)][:10], \
                f"stale KV: prompt {p} diverged after recycling"


def test_refcount_invariant_after_churn(paged_stack):
    _, sched = paged_stack
    with sched._cond:
        sched.pool.check()
        held = sum(len(s.pages) for s in sched.slots)
        # every in-use page is owned by a slot and/or the radix tree
        assert sched.pool.in_use >= held


def test_prefix_reuse_byte_identical_and_cheaper(solo_refs):
    """The tentpole acceptance: a shared system prompt makes later
    requests bind cached pages (prefix_tokens_reused_total > 0), decode
    byte-identically, and do strictly less prefill work than the same
    traffic with reuse disabled (PR-7 flight phases carry the receipts)."""
    rng = np.random.RandomState(11)
    system = [int(x) for x in rng.randint(1, CFG.vocab_size, 4 * PAGE)]
    prompt = system + [3, 1]

    def serve(prefix_reuse):
        eng = make_paged_engine(2)
        sched = SlotScheduler(eng, prefill_chunk=4,
                              prefix_reuse=prefix_reuse)
        try:
            t1, o1 = _collect(sched, prompt, max_new=8)
            t2, o2 = _collect(sched, prompt, max_new=8)
        finally:
            sched.close()
        return (t1, o1), (t2, o2)

    reused0 = obs_metrics.PREFIX_TOKENS_REUSED.value
    hits0 = obs_metrics.PREFIX_HITS.value
    (t1, o1), (t2, o2) = serve(True)
    assert o1 == o2, "prefix-reused decode diverged from the cold run"
    assert obs_metrics.PREFIX_HITS.value > hits0
    # the whole 4-page system prompt came from the tree
    assert obs_metrics.PREFIX_TOKENS_REUSED.value - reused0 == 4 * PAGE

    def prefill_tokens(t):
        rec = obs_flight.get(t.rid)
        assert rec is not None
        return sum(ph.get("tokens", 0) for ph in rec.get("phases", [])
                   if ph.get("kind") == "prefill_chunk")

    # receipts: the hit request prefilled only the suffix, and its record
    # carries the prefix_reuse span
    rec2 = obs_flight.get(t2.rid)
    kinds = [ph.get("kind") for ph in rec2.get("phases", [])]
    assert "prefix_reuse" in kinds, kinds
    assert prefill_tokens(t2) < prefill_tokens(t1)
    assert prefill_tokens(t2) == len(prompt) - 4 * PAGE

    # A/B: same traffic, reuse disabled — full prefill both times, and
    # strictly more prefill work than the reusing run did
    (t1n, o1n), (t2n, o2n) = serve(False)
    assert o1n == o1 and o2n == o1, "reuse changed the tokens"
    assert prefill_tokens(t2n) == len(prompt)
    assert prefill_tokens(t2) < prefill_tokens(t2n)


def test_pool_smaller_than_slots_times_seqlen_serves_all(solo_refs):
    """The memory win: 4 slots × seq_len 64 = 256 cache positions under
    the contiguous layout; a pool of 17 usable pages × 8 = 136 tokens
    serves the same 4 concurrent requests, because each reserves only
    min(len + max_new, seq_len) worth of pages."""
    eng = make_paged_engine(4, kv_pages=18)
    assert eng.kv_pages * PAGE < 4 * CFG.seq_len
    sched = SlotScheduler(eng, prefill_chunk=4)
    try:
        outs = {}

        def run(p, delay):
            _, toks = _collect(sched, p, max_new=10, delay=delay)
            outs[tuple(p)] = toks

        ths = [threading.Thread(target=run, args=(p, 0.02 * i))
               for i, p in enumerate(PROMPTS)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(120)
        # all four were concurrently resident and correct
        for p in PROMPTS:
            assert outs[tuple(p)] == solo_refs[tuple(p)][:10]
        sched.pool.check()
    finally:
        sched.close()


def test_exhaustion_defers_then_recovers():
    """A request that cannot get pages waits in the queue (counted by
    kv_pool_exhausted_total) and completes once a retirement frees pages;
    a request that could NEVER fit fails fast at submit."""
    # 6 usable pages × 8 = 48 tokens; each request reserves
    # min(3 + 40, 64) = 43 tokens → 6 pages, so only one can be resident
    eng = make_paged_engine(2, kv_pages=7)
    sched = SlotScheduler(eng, prefill_chunk=4, prefix_reuse=False)
    try:
        with pytest.raises(ContextOverflow):
            # needs ceil(64/8) = 8 pages > the 6-page capacity: this can
            # never be admitted, so it must fail fast, not queue forever
            sched.submit(list(range(1, 60)), 40)
        exhausted0 = obs_metrics.KV_POOL_EXHAUSTED.value
        outs = []

        def run():
            t = sched.submit(P1, 40, temperature=0.0)
            outs.append((list(t.tokens()), t.finish))

        ths = [threading.Thread(target=run) for _ in range(2)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(120)
        assert len(outs) == 2
        for toks, finish in outs:
            assert finish == "length" and len(toks) > 0
        assert outs[0][0] == outs[1][0]
        assert obs_metrics.KV_POOL_EXHAUSTED.value > exhausted0
        with sched._cond:
            assert sched.pool.available == sched.pool.capacity
            sched.pool.check()
    finally:
        sched.close()
