"""Performance-economics plane tests (obs/cost.py + perf sentinel).

Three layers of evidence, tier-1 on CPU:

* **hand counts** — the roofline model's FLOPs/bytes for the tiny
  config are recomputed here from first principles as literal
  arithmetic (one prefill chunk, one decode step, one paged-int8
  decode, one tp=2 ring hop, one decode burst) and must match
  ``CostModel`` EXACTLY — the model is only trustworthy because it is
  small enough to check token by token;
* **attribution e2e** — a real staggered scheduler run on the tiny
  engine: ledger counters carry exactly what the tracker carried, every
  flight record gains a cost block, and per-request ``chip_ms`` sums to
  the scheduler's busy (prefill + decode) goodput component within 5%;
* **sentinel** — ``tools/perf_sentinel.py`` exits nonzero on a canned
  20% tok/s regression, zero on an equal pair, loads all three snapshot
  schemas, and its ``--self-check`` passes (the tier-1 CI hook).
"""

import importlib.util
import json
import os
import sys
import threading

import pytest

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from dllama_tpu.obs import cost as obs_cost  # noqa: E402
from dllama_tpu.obs import dispatch as obs_dispatch  # noqa: E402
from dllama_tpu.obs import flight as obs_flight  # noqa: E402
from dllama_tpu.obs import metrics as obs_metrics  # noqa: E402

# tiny_config geometry the hand counts below are written against:
# dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2, vocab=128
# -> head_size=16, kv_dim=32.
#
# per-layer matmul params: wq+wo (2*64*64=8192) + wk+wv (2*64*32=4096)
#                          + w1+w2+w3 (3*64*96=18432) = 30720
# params_per_token = 2 layers * 30720 = 61440;  logits head = 64*128=8192
PARAMS_PER_TOKEN = 61440
HEAD_PARAMS = 8192
# Q40 wire bytes: 18 B per 32 weights
W_READ_Q40 = 61440 // 32 * 18 + 8192 // 32 * 18  # 34560 + 4608 = 39168
KV_POS_F32 = 2 * 32 * 4    # (k+v) * kv_dim * 4 B = 256 B/position/layer
KV_POS_INT8 = 2 * (32 + 4 * 2)  # values + f32 scale planes = 80 B


def tiny_cost_model(**over):
    kw = dict(dim=64, hidden_dim=96, n_layers=2, n_heads=4, n_kv_heads=2,
              vocab_size=128, weight_codec="q40", kv_codec="kv_f32",
              kv_el_bytes=4)
    kw.update(over)
    return obs_cost.CostModel(**kw)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- hand-counted unit costs ----------------------------------------------

def test_prefill_chunk_hand_count():
    """One 8-token prefill chunk from position 0, single row."""
    cm = tiny_cost_model()
    out = cm.dispatch_cost([("prefill", 0, 8)])
    mm = out["entries"][("q40", "matmul", "prefill")]
    at = out["entries"][("kv_f32", "attention", "prefill")]
    # matmuls: 2*8*61440; logits: prefill samples ONE position: 2*1*64*128
    assert mm["flops"] == 2 * 8 * PARAMS_PER_TOKEN + 2 * 1 * 64 * 128
    assert mm["flops"] == 999424
    # weights stream once, one occupied row takes the whole read
    assert mm["bytes"] == W_READ_Q40 == 39168
    # attention: 4*dim FLOPs per (query, ctx) pair per layer; ctx lengths
    # 1..8 sum to 36
    assert at["flops"] == 4 * 64 * 2 * 36 == 18432
    # KV: write 8 positions + one block read of the final 8-token context
    assert at["bytes"] == 8 * 2 * KV_POS_F32 + 8 * 2 * KV_POS_F32 == 8192
    assert out["flops"] == 999424 + 18432
    assert out["hbm_bytes"] == 39168 + 8192


def test_decode_step_hand_count():
    """One single-token decode step at cache position 10."""
    cm = tiny_cost_model()
    out = cm.dispatch_cost([("decode", 10, 1)])
    mm = out["entries"][("q40", "matmul", "decode")]
    at = out["entries"][("kv_f32", "attention", "decode")]
    assert mm["flops"] == 2 * 1 * PARAMS_PER_TOKEN + 2 * 1 * 64 * 128
    assert mm["flops"] == 139264
    assert mm["bytes"] == W_READ_Q40
    # the new token attends over 11 positions (10 cached + itself)
    assert at["flops"] == 4 * 64 * 2 * 11 == 5632
    assert at["bytes"] == 1 * 2 * KV_POS_F32 + 11 * 2 * KV_POS_F32 == 6144


def test_paged_int8_decode_hand_count():
    """Decode over an int8 paged pool: reads round up to whole pages and
    pay the per-(head, position) scale planes."""
    cm = tiny_cost_model(kv_codec="kv_int8", kv_el_bytes=1,
                         paged=True, page_size=16)
    out = cm.dispatch_cost([("decode", 10, 1)])
    at = out["entries"][("kv_int8", "paged-decode", "decode")]
    # context 11 rounds up to one whole 16-position page
    assert at["bytes"] == 1 * 2 * KV_POS_INT8 + 16 * 2 * KV_POS_INT8
    assert at["bytes"] == 160 + 2560
    # attention FLOPs stay at the TRUE context, not the page granularity
    assert at["flops"] == 4 * 64 * 2 * 11


def test_tp2_ring_hop_hand_count():
    """tp=2: two f32 all-reduces of dim per layer per token, 2*(tp-1)
    ring hop copies each — tracked on its own path, excluded from HBM."""
    cm = tiny_cost_model(tp=2)
    out = cm.dispatch_cost([("decode", 0, 1)])
    ring = out["entries"][("q40", "tp-ring", "decode")]
    assert ring["bytes"] == 1 * 2 * 2 * (2 * 1) * 64 * 4 == 2048
    assert ring["flops"] == 0
    assert out["hbm_bytes"] == W_READ_Q40 + (
        out["entries"][("kv_f32", "attention", "decode")]["bytes"])
    cm1 = tiny_cost_model(tp=1)
    assert ("q40", "tp-ring", "decode") not in \
        cm1.dispatch_cost([("decode", 0, 1)])["entries"]


def test_decode_burst_rereads_weights_and_context():
    """A 4-step burst is 4 sequential passes: 4 weight streams, each new
    token re-reading its whole (growing) context."""
    cm = tiny_cost_model()
    out = cm.dispatch_cost([("decode", 4, 4)], steps=4)
    mm = out["entries"][("q40", "matmul", "decode")]
    at = out["entries"][("kv_f32", "attention", "decode")]
    assert mm["bytes"] == 4 * W_READ_Q40
    # contexts 5,6,7,8: read 26 positions total, write 4
    assert at["bytes"] == 4 * 2 * KV_POS_F32 + 26 * 2 * KV_POS_F32
    assert at["flops"] == 4 * 64 * 2 * 26
    # every decoded position pays the logits head
    assert mm["flops"] == 2 * 4 * PARAMS_PER_TOKEN + 2 * 4 * 64 * 128


def test_mixed_dispatch_splits_weight_read_across_rows():
    cm = tiny_cost_model()
    out = cm.dispatch_cost([("prefill", 0, 8), ("decode", 10, 1)])
    mm_p = out["entries"][("q40", "matmul", "prefill")]
    mm_d = out["entries"][("q40", "matmul", "decode")]
    assert mm_p["bytes"] == mm_d["bytes"] == W_READ_Q40 / 2
    assert out["per_row"][0]["hbm_bytes"] == W_READ_Q40 / 2 + 8192
    # row totals and entry totals agree
    assert sum(r["flops"] for r in out["per_row"]) == out["flops"]


def test_q8_and_dense_codec_bytes():
    q8 = tiny_cost_model(weight_codec="q8")
    assert q8.weight_read_bytes() == (61440 // 32 + 8192 // 32) * 34
    dense = tiny_cost_model(weight_codec="dense", weight_el_bytes=2)
    assert dense.weight_read_bytes() == (61440 + 8192) * 2


# --- peaks and tracker ----------------------------------------------------

def test_peaks_env_override_and_tpu_table(monkeypatch):
    monkeypatch.setenv("DLLAMA_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("DLLAMA_PEAK_BYTES_S", "1e11")
    obs_cost.reset()
    p = obs_cost.peaks()
    assert p["source"] == "env" and p["flops"] == 1e12
    assert p["bytes_per_s"] == 1e11
    monkeypatch.delenv("DLLAMA_PEAK_FLOPS")
    monkeypatch.delenv("DLLAMA_PEAK_BYTES_S")
    obs_cost.set_backend("TPU v5 lite", "tpu")
    p = obs_cost.peaks()
    assert p["source"] == "table"
    assert p["flops"] == 197e12 and p["bytes_per_s"] == 819e9
    obs_cost.set_backend(None, None)
    obs_cost.reset()


def test_tracker_mfu_mbu_ratio(monkeypatch):
    monkeypatch.setenv("DLLAMA_PEAK_FLOPS", "1e9")
    monkeypatch.setenv("DLLAMA_PEAK_BYTES_S", "1e9")
    obs_cost.reset()
    tr = obs_cost.PerfTracker()
    # 5e8 FLOPs + 2.5e8 bytes over 1000 ms against 1e9/s peaks
    tr.note(5e8, 2.5e8, 1000.0)
    assert tr.mfu() == pytest.approx(0.5)
    assert tr.mbu() == pytest.approx(0.25)
    snap = tr.snapshot()
    assert snap["flops_total"] == 5e8 and snap["chip_wall_ms"] == 1000.0
    obs_cost.reset()


# --- scheduler attribution e2e --------------------------------------------

@pytest.fixture
def clean_obs(monkeypatch):
    # deterministic peaks: MFU/MBU must be computable without the CPU
    # microbenchmark's noise
    monkeypatch.setenv("DLLAMA_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("DLLAMA_PEAK_BYTES_S", "1e11")
    obs_dispatch.reset()
    obs_flight.clear()
    obs_metrics.SCHED_STEP_TIME_MS.reset()
    obs_cost.reset()
    yield
    obs_dispatch.reset()
    obs_flight.clear()
    obs_metrics.SCHED_STEP_TIME_MS.reset()
    obs_cost.reset()


def _run_staggered(slots=4, max_new=24):
    import jax

    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.scheduler import SlotScheduler
    import time as _time

    from dllama_tpu.obs.log import request_id_var

    cfg = tiny_config(seq_len=64)
    eng = Engine(cfg, init_params(cfg, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                 batch=slots)
    sched = SlotScheduler(eng, prefill_chunk=4, max_wait_ms=20.0,
                          decode_burst=4)
    prompts = [[5, 9, 2], [7, 3, 11, 4, 6], [2, 4, 6], [9, 8, 7, 6]]
    rids = [f"cost-e2e-{i}" for i in range(slots)]

    def run(i, delay):
        _time.sleep(delay)
        # the submitting thread's request id rides the ticket into the
        # flight record (same seam the HTTP handler uses)
        request_id_var.set(rids[i])
        t = sched.submit(prompts[i], max_new)
        for _ in t.tokens():
            pass

    ths = [threading.Thread(target=run, args=(i, 0.03 * i))
           for i in range(slots)]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    sched.close()
    return rids


def test_scheduler_attribution_e2e(clean_obs):
    rids = _run_staggered()

    # every request's flight record gained a full cost block
    costs = {}
    for rid in rids:
        rec = obs_flight.get(rid)
        assert rec is not None and "cost" in rec, rid
        for k in ("chip_ms", "flops", "hbm_bytes", "kv_page_ms"):
            assert k in rec["cost"]
        assert rec["cost"]["flops"] > 0 and rec["cost"]["chip_ms"] > 0
        costs[rid] = rec["cost"]

    # ledger counters hold exactly what the tracker accumulated
    # (json keys are "codec/path/phase")
    snap = obs_cost.TRACKER.snapshot()
    flops_by_key = obs_metrics.DISPATCH_FLOPS.json_value()
    bytes_by_key = obs_metrics.DISPATCH_BYTES.json_value()
    ledger_flops = sum(flops_by_key.values())
    assert ledger_flops == pytest.approx(snap["flops_total"], rel=1e-9)
    ledger_hbm = sum(v for k, v in bytes_by_key.items()
                     if k.split("/")[1] != "tp-ring")
    assert ledger_hbm == pytest.approx(snap["hbm_bytes_total"], rel=1e-9)
    # tp=1: no ring entries at all
    assert not any(k.split("/")[1] == "tp-ring" for k in bytes_by_key)
    # phases seen: both prefill and decode attributed
    phases = {k.split("/")[2] for k in flops_by_key}
    assert {"prefill", "decode"} <= phases

    # per-request chip_ms telescopes to the busy goodput component
    comp = obs_metrics.SCHED_STEP_TIME_MS.json_value()
    busy = comp.get("prefill", 0.0) + comp.get("decode", 0.0)
    attributed = sum(c["chip_ms"] for c in costs.values())
    assert busy > 0
    assert attributed == pytest.approx(busy, rel=0.05)

    # per-class chip time saw the same milliseconds (default class)
    by_class = obs_metrics.CLASS_CHIP_MS.json_value()
    assert sum(by_class.values()) == pytest.approx(attributed, rel=0.05)
    assert "standard" in by_class

    # MFU/MBU gauges set and present in BOTH expositions
    assert obs_metrics.MFU.value > 0 and obs_metrics.MBU.value > 0
    js = obs_metrics.snapshot_json()
    assert js["mfu"] > 0 and js["mbu"] > 0
    txt = obs_metrics.render_prometheus()
    assert "dllama_mfu" in txt and "dllama_mbu" in txt
    assert "dllama_dispatch_flops_total" in txt
    assert "dllama_class_chip_ms_total" in txt

    # /health perf block carries the same summary
    perf = obs_cost.summary()
    assert perf["flops_total"] == snap["flops_total"]
    assert perf["mfu"] is not None and perf["peaks"]["source"] == "env"
    assert perf["chip_ms_by_class"]


def test_model_from_engine_sniffs_codecs(clean_obs):
    import jax

    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine

    cfg = tiny_config(seq_len=64)
    eng = Engine(cfg, init_params(cfg, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=2)
    cm = obs_cost.model_from_engine(eng)
    assert cm is not None
    assert cm.params_per_token == PARAMS_PER_TOKEN
    assert cm.tp == 1 and not cm.paged
    # an unmodelable engine degrades to None, never raises
    assert obs_cost.model_from_engine(object()) is None


# --- perf sentinel --------------------------------------------------------

def _result(value, extras=None):
    return {"metric": "tiny decode tok/s", "value": value, "unit": "tok/s",
            "vs_baseline": None, **({"extras": extras} if extras else {})}


def test_sentinel_regression_and_clean_pair(tmp_path, capsys):
    ps = _load_tool("perf_sentinel")
    base = tmp_path / "base.json"
    slow = tmp_path / "slow.json"
    same = tmp_path / "same.json"
    base.write_text(json.dumps(_result(100.0)))
    slow.write_text(json.dumps(_result(80.0)))   # 20% tok/s drop
    same.write_text(json.dumps(_result(100.0)))
    assert ps.main([str(base), str(slow)]) == 1
    assert "regression" in capsys.readouterr().out.lower()
    assert ps.main([str(base), str(same), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["verdict"] == "ok" and rep["regressions"] == []


def test_sentinel_loads_driver_wrapper_and_jsonl(tmp_path):
    ps = _load_tool("perf_sentinel")
    # driver wrapper (BENCH_r*.json shape): result rides in "parsed"
    wrapper = tmp_path / "BENCH_r98.json"
    wrapper.write_text(json.dumps(
        {"n": 98, "cmd": "bench", "rc": 0, "tail": "noise",
         "parsed": _result(50.0, {"cpu_sched4_agg_toks": 40.0})}))
    flat = ps.load_any(str(wrapper))
    assert flat == {"value": 50.0, "cpu_sched4_agg_toks": 40.0}
    # stage-snapshot JSONL: keys are stage:metric, histograms -> _avg
    jl = tmp_path / "BENCH_metrics.jsonl"
    jl.write_text(json.dumps(
        {"stage": "cpu-tiny-sched4", "ts": 1.0, "schema_version": 2,
         "metrics": {"schema_version": 2, "sched_goodput_ratio": 0.9,
                     "mfu": 0.25,
                     "ttft_seconds": {"count": 2, "sum": 0.4, "avg": 0.2,
                                      "buckets": {}}}}) + "\n")
    flat = ps.load_any(str(jl))
    assert flat["cpu-tiny-sched4:sched_goodput_ratio"] == 0.9
    assert flat["cpu-tiny-sched4:mfu"] == 0.25
    assert flat["cpu-tiny-sched4:ttft_seconds_avg"] == 0.2
    # direction map: latency is lower-better, throughput higher-better
    assert ps.direction_of("x:ttft_seconds_avg") == "lower"
    assert ps.direction_of("cpu_sched4_agg_toks") == "higher"
    assert ps.direction_of("mfu") == "higher"


def test_sentinel_self_check_fast():
    """The tier-1 CI hook: --self-check must pass without touching the
    filesystem or network."""
    ps = _load_tool("perf_sentinel")
    assert ps.self_check() == 0
    assert ps.main(["--self-check"]) == 0


def test_bench_stamps_metrics_bank(tmp_path, monkeypatch):
    """Satellite: every banked stage row carries schema_version, the
    bench run id, and the git SHA."""
    bank = tmp_path / "bank.jsonl"
    monkeypatch.setenv("BENCH_METRICS_BANK", str(bank))
    monkeypatch.setenv("BENCH_RUN_ID", "testrun-1")
    monkeypatch.setenv("BENCH_GIT_SHA", "abc1234")
    sys.path.insert(0, REPO)
    import bench
    bench._bank_stage_metrics("unit-stage")
    row = json.loads(bank.read_text().strip())
    assert row["stage"] == "unit-stage"
    assert row["schema_version"] == row["metrics"]["schema_version"]
    assert row["bench_run_id"] == "testrun-1"
    assert row["git_sha"] == "abc1234"


def test_bench_vs_baseline_helper():
    sys.path.insert(0, REPO)
    import bench
    assert bench._vs_baseline(19.64, 9.82) == 2.0
    assert bench._vs_baseline(19.64, None) is None
    assert bench._vs_baseline(None, 9.82) is None
    assert bench._vs_baseline(5.0, 0) is None
