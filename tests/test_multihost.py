"""Multi-host (multi-process) smoke test — VERDICT r03 Next #5.

The reference runs its worker topology as separate OS processes joined
over TCP (dllama.cpp:205-219, examples/n-workers.sh).  Our equivalent is a
JAX process group (`parallel/distributed.py`): every process runs the SAME
CLI command plus its coordinates, `jax.distributed.initialize` wires them
into one runtime, and the tp mesh spans both processes (collectives ride
Gloo on CPU here, ICI/DCN on real pods).

This test actually spawns nproc=2 forced-CPU processes (1 local device
each → a global tp=2 mesh), runs a greedy generate end-to-end, and checks
(a) both exit cleanly, (b) only process 0 prints, and (c) the token stream
equals a single-process tp=2 run of the same command — the distributed
mesh must be numerically invisible.
"""

from __future__ import annotations

import subprocess
import sys

import pytest

from fixtures import cpu_env, free_port, REPO, write_tiny_model, write_tiny_tokenizer
from dllama_tpu import quants


def _cmd(mode: str, mpath: str, tpath: str, extra: list[str],
         prompt_args: list[str] | None = None,
         steps: str = "20") -> list[str]:
    return [sys.executable, "-m", "dllama_tpu", mode,
            "--model", mpath, "--tokenizer", tpath,
            *(prompt_args or ["--prompt", "hello hi"]),
            "--steps", steps, "--temperature", "0", "--seed", "1",
            "--buffer-float-type", "f32", "--chunk", "8",
            "--workers", "tpu:2"] + extra


@pytest.mark.slow
def test_nproc2_generate_matches_single_process(tmp_path):
    mpath, tpath = str(tmp_path / "toy.m"), str(tmp_path / "toy.t")
    write_tiny_model(mpath, ftype=quants.F32, vocab_size=128, seq_len=64)
    write_tiny_tokenizer(tpath, vocab_size=128)

    # single-process tp=2 golden (2 virtual devices in one process)
    ref = subprocess.run(_cmd("generate", mpath, tpath, []),
                         cwd=REPO, env=cpu_env(2), capture_output=True,
                         text=True, timeout=300)
    assert ref.returncode == 0, ref.stdout + ref.stderr
    golden = ref.stdout.splitlines()[-1]
    assert golden.startswith("<s>hello hi"), golden

    # nproc=2: same command on both processes + coordinates; proc 1 runs
    # `worker --program generate` (the reference's worker role)
    port = free_port()
    coords = ["--coordinator", f"localhost:{port}", "--nproc", "2"]
    p1 = subprocess.Popen(
        _cmd("worker", mpath, tpath,
             ["--program", "generate"] + coords + ["--proc-id", "1"]),
        cwd=REPO, env=cpu_env(1), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        p0 = subprocess.run(
            _cmd("generate", mpath, tpath, coords + ["--proc-id", "0"]),
            cwd=REPO, env=cpu_env(1), capture_output=True, text=True,
            timeout=300)
        out1, err1 = p1.communicate(timeout=120)
    finally:
        if p1.poll() is None:
            p1.kill()
    assert p0.returncode == 0, p0.stdout + p0.stderr
    assert p1.returncode == 0, out1 + err1

    # only process 0 owns the stream (Gloo's C++ banner on fd 1 is not ours)
    assert "<s>" not in out1 and "extra_" not in out1, out1
    assert p0.stdout.splitlines()[-1] == golden


@pytest.mark.slow
def test_nproc2_ragged_batch_matches_single_process(tmp_path):
    """Distinct-stream ragged batching over a REAL 2-process tp=2 mesh:
    the distributed mesh must be invisible — identical stream texts and
    only process 0 printing (worker mirrors `--program batch`)."""
    mpath, tpath = str(tmp_path / "toy.m"), str(tmp_path / "toy.t")
    write_tiny_model(mpath, ftype=quants.F32, vocab_size=128, seq_len=64)
    write_tiny_tokenizer(tpath, vocab_size=128)
    pf = str(tmp_path / "prompts.txt")
    with open(pf, "w") as f:
        f.write("hello hi\nonce upon\n")

    def cmd(mode, extra):
        return _cmd(mode, mpath, tpath, extra,
                    prompt_args=["--prompts-file", pf], steps="16")

    ref = subprocess.run(cmd("batch", []), cwd=REPO, env=cpu_env(2),
                         capture_output=True, text=True, timeout=300)
    assert ref.returncode == 0, ref.stdout + ref.stderr
    golden = [l for l in ref.stdout.splitlines()
              if not l.startswith(("💡", "Batched", "Generated"))]
    assert "▶ stream 1" in ref.stdout

    port = free_port()
    coords = ["--coordinator", f"localhost:{port}", "--nproc", "2"]
    p1 = subprocess.Popen(
        cmd("worker", ["--program", "batch"] + coords + ["--proc-id", "1"]),
        cwd=REPO, env=cpu_env(1), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        p0 = subprocess.run(cmd("batch", coords + ["--proc-id", "0"]),
                            cwd=REPO, env=cpu_env(1), capture_output=True,
                            text=True, timeout=300)
        out1, err1 = p1.communicate(timeout=120)
    finally:
        if p1.poll() is None:
            p1.kill()
    assert p0.returncode == 0, p0.stdout + p0.stderr
    assert p1.returncode == 0, out1 + err1
    assert "▶ stream" not in out1, out1  # only process 0 prints
    got = [l for l in p0.stdout.splitlines()
           if not l.startswith(("💡", "Batched", "Generated", "[Gloo]"))]
    assert got == golden
