"""Elastic-pod tests (router/elastic.py + runtime registry membership).

The unit tier is jax-free and subprocess-light: the policy is a pure
function of synthetic signal windows (hysteresis, cooldown, the three
decision directions), the device pool is plain accounting, the registry
add/remove/retire surface mutates a real :class:`Registry` without its
probe thread, and the controller runs against in-memory fakes so every
scale/reshape path executes deterministically in milliseconds.  The
port-hold fence and the supervisor's runtime add/remove/retiring
behavior use real sockets and trivial child processes.

The slow tier runs ``tools/chaos_drill.py --reshape --quick`` — a real
supervised elastic pod doing a live 2×tp=1 → 2×tp=2 reshape with a
SIGKILL landing mid-migration, asserting convergence, greedy byte
parity through the migration, bounded unavailability, and zero KV
leaks.
"""

import os
import socket
import sys
import time
from types import SimpleNamespace

import pytest

from fixtures import REPO, free_port
from dllama_tpu.router.elastic import (DevicePool, ElasticController,
                                       ElasticPolicy)
from dllama_tpu.router.pod import Supervisor, _Replica, _hold_port
from dllama_tpu.router.registry import Registry

pytestmark = pytest.mark.elastic

_SLEEPER = [sys.executable, "-c", "import time; time.sleep(600)"]


def _wait(cond, timeout=30.0, msg="condition never held"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(msg)


# -- device pool ----------------------------------------------------------

def test_device_pool_contiguous_then_fragmented():
    """Allocation prefers a contiguous ordinal run; once scale churn
    fragments the free set, the lowest free ordinals serve."""
    pool = DevicePool(8)
    a = pool.allocate(2)
    b = pool.allocate(2)
    c = pool.allocate(2)
    assert a == [0, 1] and b == [2, 3] and c == [4, 5]
    pool.release(b)                      # hole at 2,3
    d = pool.allocate(4)                 # no contiguous 4-run left
    assert d == [2, 3, 6, 7]
    assert pool.free == 0


def test_device_pool_exhaustion_and_double_release():
    pool = DevicePool(2)
    got = pool.allocate(2)
    with pytest.raises(ValueError):
        pool.allocate(1)                 # exhausted
    pool.release(got)
    with pytest.raises(ValueError):
        pool.release([0])                # double release
    with pytest.raises(ValueError):
        pool.release([99])               # out of range
    with pytest.raises(ValueError):
        DevicePool(0)


# -- policy (pure signal-window decisions) --------------------------------

def _hot(util=0.95, q=5.0, kv=0.5):
    return {"util": util, "queue_per_replica": q, "kv_free_frac": kv}


def _cold(util=0.05, q=0.0, kv=0.9):
    return {"util": util, "queue_per_replica": q, "kv_free_frac": kv}


def _policy(**kw):
    defaults = dict(window=3, cooldown=10.0, min_replicas=1,
                    max_replicas=4)
    defaults.update(kw)
    return ElasticPolicy(**defaults)


def test_policy_needs_full_window():
    """No verdict until the window fills: two hot samples out of three
    decide nothing."""
    p = _policy()
    p.observe(_hot())
    p.observe(_hot())
    assert p.decide(0.0, n_replicas=1, tp=1, free_devices=3) is None
    p.observe(_hot())
    d = p.decide(0.0, n_replicas=1, tp=1, free_devices=3)
    assert d is not None and d.direction == "up" and d.reason == "load"


def test_policy_hysteresis_one_cool_sample_blocks():
    """A single non-hot sample inside the window vetoes scale-up — the
    sustained-signal rule that keeps a spiky load from flapping."""
    p = _policy()
    p.observe(_hot())
    p.observe({"util": 0.5, "queue_per_replica": 0.0,
               "kv_free_frac": 0.5})
    p.observe(_hot())
    assert p.decide(0.0, n_replicas=1, tp=1, free_devices=3) is None


def test_policy_cooldown_blocks_and_clears_window():
    p = _policy()
    for _ in range(3):
        p.observe(_hot())
    assert p.decide(100.0, n_replicas=1, tp=1, free_devices=3) is not None
    p.note_action(100.0)
    # the cooldown gates even a re-filled window...
    for _ in range(3):
        p.observe(_hot())
    assert p.decide(105.0, n_replicas=1, tp=1, free_devices=3) is None
    # ...and elapses
    assert p.decide(111.0, n_replicas=1, tp=1,
                    free_devices=3) is not None
    # note_action cleared the pre-action samples: a fresh policy clock
    p2 = _policy()
    for _ in range(3):
        p2.observe(_hot())
    p2.note_action(0.0)
    assert p2.decide(50.0, n_replicas=1, tp=1, free_devices=3) is None


def test_policy_scale_down_and_min_floor():
    p = _policy(min_replicas=2)
    for _ in range(3):
        p.observe(_cold())
    d = p.decide(0.0, n_replicas=3, tp=1, free_devices=1)
    assert d is not None and d.direction == "down" and d.reason == "idle"
    for _ in range(3):
        p.observe(_cold())
    assert p.decide(0.0, n_replicas=2, tp=1, free_devices=2) is None


def test_policy_up_capped_at_max():
    p = _policy(max_replicas=2)
    for _ in range(3):
        p.observe(_hot())
    assert p.decide(0.0, n_replicas=2, tp=1, free_devices=2) is None


def test_policy_reshape_narrower_when_devices_exhausted():
    """Hot fleet, zero free devices, tp>1: the answer is trading tp for
    dp — reshape to half the degree instead of giving up."""
    p = _policy()
    for _ in range(3):
        p.observe(_hot())
    d = p.decide(0.0, n_replicas=2, tp=2, free_devices=0)
    assert d is not None and d.direction == "reshape" and d.tp == 1
    # at tp=1 there is nothing to trade: no decision
    for _ in range(3):
        p.observe(_hot())
    assert p.decide(0.0, n_replicas=4, tp=1, free_devices=0) is None


def test_policy_reshape_wider_on_kv_starvation():
    p = _policy()
    for _ in range(3):
        p.observe(_hot(kv=0.01))
    d = p.decide(0.0, n_replicas=4, tp=1, free_devices=0)
    assert d is not None and d.direction == "reshape" \
        and d.reason == "kv_pressure" and d.tp == 2
    # blocked when doubling tp cannot seat min_replicas
    p2 = _policy(min_replicas=2)
    for _ in range(3):
        p2.observe(_hot(kv=0.01))
    assert p2.decide(0.0, n_replicas=2, tp=1, free_devices=0) is None


# -- registry runtime membership ------------------------------------------

def _registry(n=2):
    reg = Registry([f"127.0.0.1:{10000 + i}" for i in range(n)],
                   probe_interval=999.0)
    for b in reg.backends:
        b.last_health = {"status": "ok", "capacity": {"free_slots": 2}}
    return reg


def test_registry_runtime_add_gated_until_first_probe():
    reg = _registry()
    b = reg.add("127.0.0.1:10099")
    assert reg.get("127.0.0.1:10099") is b
    # no health yet: invisible to dispatch, invisible to `available`
    assert b not in [reg.pick() for _ in range(4)]
    assert reg.snapshot()["available"] == 2
    b.last_health = {"status": "ok", "capacity": {"free_slots": 99}}
    assert reg.pick() is b
    assert reg.snapshot()["available"] == 3
    with pytest.raises(ValueError):
        reg.add("127.0.0.1:10099")       # duplicate


def test_registry_retire_fences_dispatch_not_export():
    reg = _registry()
    victim = reg.backends[0]
    reg.retire(victim.addr)
    # never picked, not a hand-off import target, not "available"...
    assert all(reg.pick() is not victim for _ in range(4))
    assert victim not in reg.handoff_peers()
    snap = reg.snapshot()
    assert snap["available"] == 1
    # ...but NOT ejected: still a live row (the drain's export source)
    row = [r for r in snap["backends"] if r["addr"] == victim.addr][0]
    assert row["retiring"] and not row["ejected"]


def test_registry_remove_runtime():
    reg = _registry()
    gone = reg.backends[0].addr
    assert reg.remove(gone) is not None
    assert reg.get(gone) is None
    assert reg.snapshot()["total"] == 1
    assert reg.remove("127.0.0.1:59999") is None   # unknown: no-op


# -- controller over fakes ------------------------------------------------

class FakeRegistry:
    """Registry seam the controller needs: membership + admission."""

    def __init__(self, ports=()):
        self.rows = {}
        for p in ports:
            self.add(f"127.0.0.1:{p}")

    def add(self, addr):
        if addr in self.rows:
            raise ValueError(addr)
        self.rows[addr] = SimpleNamespace(
            addr=addr, last_health={"status": "ok"}, ejected=False,
            retiring=False)

    def remove(self, addr):
        return self.rows.pop(addr, None)

    def retire(self, addr):
        if addr in self.rows:
            self.rows[addr].retiring = True

    def get(self, addr):
        return self.rows.get(addr)

    def score(self, b):
        return 0.0

    def eligible_backends(self):
        return []


class FakeOps:
    """Replica mechanics without processes."""

    def __init__(self, *, tp=1, n=2):
        self.reps = [self._mk(i, tp, [i]) for i in range(n)]
        self._next = n
        self.retired = []

    @staticmethod
    def _mk(idx, tp, ordinals):
        return SimpleNamespace(idx=idx, port=9000 + idx, tp=tp,
                               ordinals=list(ordinals), retiring=False,
                               quarantined=False)

    def spawn(self, tp, ordinals):
        rep = self._mk(self._next, tp, ordinals)
        self._next += 1
        self.reps.append(rep)
        return rep

    def retire(self, rep, *, grace):
        rep.retiring = True
        self.reps.remove(rep)
        self.retired.append(rep)

    def live_replicas(self):
        return [r for r in self.reps if not r.quarantined]

    def reap_quarantined(self):
        out = [r for r in self.reps if r.quarantined]
        for r in out:
            self.reps.remove(r)
        return out


def _controller(*, tp=1, n=2, pool_total=4, min_replicas=1,
                max_replicas=4):
    ops = FakeOps(tp=tp, n=n)
    reg = FakeRegistry(r.port for r in ops.reps)
    pool = DevicePool(pool_total)
    for r in ops.reps:                   # seat the boot shape
        r.ordinals = pool.allocate(tp)
    policy = ElasticPolicy(window=3, cooldown=0.0,
                           min_replicas=min_replicas,
                           max_replicas=max_replicas)
    ctl = ElasticController(ops, reg, pool, policy, tp=tp,
                            interval=0.01, drain_grace=0.1,
                            boot_timeout=2.0)
    return ctl, ops, reg, pool


def test_controller_manual_scale_up_and_down():
    ctl, ops, reg, pool = _controller(n=2, pool_total=4)
    ctl.request_scale(4)
    ctl._tick()                          # controller thread's step
    assert len(ops.live_replicas()) == 4
    assert pool.free == 0
    assert len(reg.rows) == 4            # registered at runtime
    ctl.request_scale(2)
    ctl._tick()
    assert len(ops.live_replicas()) == 2
    assert pool.free == 2 and len(reg.rows) == 2
    assert len(ops.retired) == 2         # drained, not dropped


def test_controller_scale_clamps_to_bounds():
    ctl, ops, _, _ = _controller(n=2, pool_total=4, min_replicas=2,
                                 max_replicas=3)
    ctl.request_scale(99)
    ctl._tick()
    assert len(ops.live_replicas()) == 3
    ctl.request_scale(0)
    ctl._tick()
    assert len(ops.live_replicas()) == 2


def test_controller_scale_up_blocked_without_devices():
    ctl, ops, _, _ = _controller(n=2, pool_total=2)
    ctl.request_scale(4)
    ctl._tick()                          # pool empty: no spawn, no crash
    assert len(ops.live_replicas()) == 2


def test_controller_reshape_narrow_to_wide_and_back():
    """4×tp=1 → 2×tp=2 over a full 4-device pool (must retire before it
    can spawn), then back — the live-reshape interleave."""
    ctl, ops, reg, pool = _controller(tp=1, n=4, pool_total=4)
    ctl.request_reshape(2)
    ctl._tick()
    live = ops.live_replicas()
    assert ctl.tp == 2
    assert [r.tp for r in live] == [2, 2]
    assert pool.free == 0 and len(reg.rows) == 2
    ctl.request_reshape(1)
    ctl._tick()
    live = ops.live_replicas()
    assert ctl.tp == 1 and len(live) == 4
    assert all(r.tp == 1 for r in live)
    assert pool.free == 0 and len(reg.rows) == 4


def test_controller_reshape_rejects_oversized_tp():
    ctl, _, _, _ = _controller(tp=1, n=2, pool_total=4)
    with pytest.raises(ValueError):
        ctl.request_reshape(8)           # exceeds the device budget
    with pytest.raises(ValueError):
        ctl.request_reshape(0)


def test_controller_reaps_quarantined_replica():
    ctl, ops, reg, pool = _controller(n=3, pool_total=4)
    victim = ops.reps[1]
    victim.quarantined = True
    ctl._tick()
    assert victim not in ops.reps
    assert f"127.0.0.1:{victim.port}" not in reg.rows
    assert pool.free == 2                # 1 spare + the reclaimed seat


def test_controller_never_retires_last_replica():
    ctl, ops, _, _ = _controller(n=1, pool_total=2)
    assert ctl._retire_one("test") is False
    assert len(ops.live_replicas()) == 1


def test_controller_fleet_status_shape():
    ctl, _, _, _ = _controller(tp=1, n=2, pool_total=4)
    fs = ctl.fleet_status()
    assert fs["elastic"] is True and fs["tp"] == 1
    assert fs["n_replicas"] == 2 and fs["busy"] is None
    assert fs["device_pool"] == {"total": 4, "free": 2}
    assert [r["tp"] for r in fs["replicas"]] == [1, 1]


# -- port-hold fence + supervisor runtime membership ----------------------

def test_hold_port_fences_the_bind_race():
    """While the allocation socket is held, nobody can steal the port;
    Supervisor.spawn releases it in the instant before the child
    starts."""
    port, held = _hold_port()
    thief = socket.socket()
    try:
        with pytest.raises(OSError):
            thief.bind(("127.0.0.1", port))
    finally:
        thief.close()
    rep = _Replica(0, port, list(_SLEEPER), dict(os.environ), sock=held)
    sup = Supervisor([rep], poll_interval=0.05, probe_timeout=0.5)
    sup.spawn(rep)
    try:
        assert rep.sock is None          # fence released at spawn
        assert held.fileno() == -1       # and actually closed
        reclaim = socket.socket()
        try:
            reclaim.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            reclaim.bind(("127.0.0.1", port))
        finally:
            reclaim.close()
    finally:
        rep.proc.kill()
        rep.proc.wait(timeout=10)


def test_supervisor_runtime_add_remove():
    rep0 = _Replica(0, free_port(), list(_SLEEPER), dict(os.environ))
    sup = Supervisor([rep0], poll_interval=0.05, probe_timeout=0.5)
    sup.start()
    try:
        rep1 = _Replica(1, free_port(), list(_SLEEPER), dict(os.environ))
        sup.add(rep1)
        assert rep1.proc is not None and rep1.proc.poll() is None
        assert sup.replicas_up() == 2
        rep1.retiring = True
        rep1.proc.kill()
        rep1.proc.wait(timeout=10)
        sup.remove(rep1)
        assert sup.replicas_up() == 1
        assert len(sup.snapshot()) == 1
    finally:
        sup.stop()


def test_supervisor_skips_retiring_replica_death():
    """A retiring replica's exit is drain completion, not a death: no
    respawn, no crash-loop accounting."""
    rep = _Replica(0, free_port(), list(_SLEEPER), dict(os.environ))
    sup = Supervisor([rep], poll_interval=0.05, probe_timeout=0.5)
    sup.start()
    try:
        _wait(lambda: rep.proc is not None and rep.proc.poll() is None)
        rep.retiring = True
        pid = rep.proc.pid
        rep.proc.kill()
        rep.proc.wait(timeout=10)
        time.sleep(0.3)                  # several watch-loop passes
        assert rep.proc.pid == pid       # same dead process: no respawn
        assert len(rep.deaths) == 0
        assert not rep.quarantined
    finally:
        sup.stop()


# -- the reshape chaos soak (tools/chaos_drill.py --reshape) --------------

@pytest.mark.slow
def test_reshape_chaos_drill_quick():
    """Live 2×tp=1 → 2×tp=2 reshape on a real supervised elastic pod
    with a SIGKILL mid-migration: convergence, greedy byte parity
    through the hand-off/resume ladder, bounded unavailability, zero
    KV-page leaks."""
    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        from chaos_drill import run_reshape_drill
    finally:
        sys.path.remove(tools)
    assert run_reshape_drill(quick=True) == 0
