"""Prompt-lookup speculative decoding (beyond reference — the reference
has no speculation).  The whole contract is EXACTNESS: every emitted token
is a true-greedy argmax, so `generate_pld` must reproduce the vanilla
greedy stream token for token no matter how many proposals get accepted
or rejected."""

import jax
import numpy as np
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import Engine

CFG = tiny_config(seq_len=96)


def make_engine(batch=1):
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=batch)


PROMPTS = [
    [5, 9, 2],
    [7, 3, 11, 4, 6, 1, 8],
    [2, 3, 4, 2, 3, 4, 2, 3, 4, 2, 3],  # repetitive → real acceptances
]


@pytest.mark.parametrize("k,ngram", [(5, 2), (3, 3), (7, 1)])
def test_pld_exactly_matches_vanilla_greedy(k, ngram):
    for prompt in PROMPTS:
        ref = [t for t, _ in make_engine().generate_stream(
            prompt, 40, temperature=0.0, chunk=8)]
        pld = make_engine().generate_pld(prompt, 40, ngram=ngram, k=k)
        assert pld == ref, (prompt, k, ngram)


def test_pld_echoes_whole_prompt_when_steps_small():
    """generate_stream echoes the full prompt before the steps check; so
    must generate_pld."""
    prompt = [5, 9, 2, 7, 1]
    ref = [t for t, _ in make_engine().generate_stream(prompt, 3,
                                                       temperature=0.0)]
    assert make_engine().generate_pld(prompt, 3) == ref == prompt


def test_pld_eos_truncates_like_vanilla():
    ref = [t for t, _ in make_engine().generate_stream(
        [5, 9, 2], 40, temperature=0.0, chunk=8)]
    eos = ref[10]
    want = [t for t, _ in make_engine().generate_stream(
        [5, 9, 2], 40, temperature=0.0, chunk=8, eos_ids=(eos,))]
    got = make_engine().generate_pld([5, 9, 2], 40, ngram=2, k=5,
                                     eos_ids=(eos,))
    assert got == want
    assert got[-1] == eos


def test_pld_continues_usable_after_run():
    """The dead cache rows a rejected window wrote must never poison a
    later decode: pos-accounting keeps them beyond the live prefix."""
    e = make_engine()
    first = e.generate_pld([5, 9, 2], 24, ngram=2, k=5)
    # same engine, fresh conversation
    e.reset()
    again = e.generate_pld([5, 9, 2], 24, ngram=2, k=5)
    assert first == again


def test_pld_rejects_batch_and_sp():
    with pytest.raises(ValueError, match="single-stream"):
        make_engine(batch=2).generate_pld([1, 2], 8)
    if len(jax.devices()) >= 2:
        cfg = tiny_config(seq_len=64)
        sp_engine = Engine(cfg, init_params(cfg, seed=4),
                           mesh=make_mesh(tp=1, sp=2,
                                          devices=jax.devices()[:2]))
        with pytest.raises(ValueError, match="sp"):
            sp_engine.generate_pld([1, 2], 8)
