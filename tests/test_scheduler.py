"""Continuous-batching slot scheduler tests (runtime/scheduler.py).

The tentpole contracts, each pinned here on CPU with a tiny model:

* **greedy parity** — a temperature-0 request produces byte-identical
  tokens whichever slot it lands in and whatever its neighbors are doing,
  including a request admitted *mid-decode* of another stream (the
  write-before-visible invariant in ops/attention.py slot primitives);
* **slot lifecycle** — cancel/deadline retire a request at the next step
  boundary with its partial output, and the freed slot serves a new
  request without any cache scrub (per-slot reset = position 0);
* **drain** — begin_drain refuses new submissions while in-flight slots
  run to completion;
* **fault drill** — a failed dispatch retires the victims with the error
  on their tickets and the loop keeps serving (slot churn under
  injected device faults);
* **regression** — one-shot ``generate_batch`` ragged offsets survive
  interleaved slot traffic on the same engine (``exclusive()``);
* **throughput acceptance** — 4 concurrent requests through the
  scheduler beat the same 4 served serially on the mutex-style batch=1
  path by ≥2× aggregate decode throughput, with an injected per-dispatch
  device delay standing in for the TPU's weight-read cost (host compute
  on CPU is noise; the dispatch count is what the scheduler amortizes).
"""

import logging
import threading
import time

import jax
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.obs import flight as obs_flight, trace as obs_trace
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.runtime.faults import FAULTS, injected
from dllama_tpu.runtime.scheduler import (SchedulerClosed,
                                          SchedulerSaturated, SlotScheduler)

CFG = tiny_config(seq_len=64)
P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]
P3 = [2, 4, 6]
P4 = [9, 8, 7, 6]
PROMPTS = (P1, P2, P3, P4)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def make_engine(batch=1):
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch)


@pytest.fixture(scope="module")
def solo_refs():
    """Greedy solo completions per prompt — the parity oracle."""
    eng = make_engine()
    refs = {}
    for p in PROMPTS:
        eng.reset()
        toks = [t for t, _ in eng.generate_stream(
            p, len(p) + 30, temperature=0.0, chunk=5)]
        refs[tuple(p)] = toks[len(p):]
    return refs


@pytest.fixture(scope="module")
def sched_stack():
    """One batch=4 engine + scheduler shared across tests — slot reuse
    across tests IS the per-slot-reset contract under test."""
    eng = make_engine(4)
    sched = SlotScheduler(eng, prefill_chunk=4, max_wait_ms=50.0,
                          decode_burst=6)
    yield eng, sched
    sched.close()


def test_staggered_joins_greedy_parity(solo_refs, sched_stack):
    _, sched = sched_stack
    results = {}

    def run(p, delay):
        time.sleep(delay)
        t = sched.submit(p, 10)
        results[tuple(p)] = (list(t.tokens()), t.finish)

    threads = [threading.Thread(target=run, args=(p, d))
               for p, d in zip(PROMPTS, (0.0, 0.05, 0.3, 0.6))]
    for th in threads:
        th.start()
    for th in threads:
        th.join(120)
    for p in PROMPTS:
        got, finish = results[tuple(p)]
        assert got == solo_refs[tuple(p)][:10], p
        assert finish == "length"


def test_join_mid_decode_matches_solo(solo_refs, sched_stack):
    """THE acceptance criterion: a greedy request admitted while another
    stream is mid-decode is byte-identical to the same request solo."""
    _, sched = sched_stack
    t_long = sched.submit(P2, 25)
    time.sleep(0.4)  # t_long is decoding by now (tiny model, warm)
    t_short = sched.submit(P1, 10)
    long_out = list(t_long.tokens())
    short_out = list(t_short.tokens())
    assert short_out == solo_refs[tuple(P1)][:10]
    assert long_out == solo_refs[tuple(P2)][:25]


def test_cancel_frees_slot_for_reuse(solo_refs, sched_stack):
    _, sched = sched_stack
    t1 = sched.submit(P1, 50)
    got = []
    for tok in t1.tokens():
        got.append(tok)
        t1.cancel("aborted")  # disconnect analog: cancel after first token
    assert t1.finish == "aborted"
    assert got == solo_refs[tuple(P1)][:len(got)]  # partial, not garbage
    deadline = time.monotonic() + 10
    while sched.occupancy()["active"] and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sched.occupancy()["active"] == 0
    t2 = sched.submit(P3, 6)
    assert list(t2.tokens()) == solo_refs[tuple(P3)][:6]


def test_deadline_retires_with_partial_output(solo_refs, sched_stack):
    _, sched = sched_stack
    FAULTS.install("engine.device_step=delay:0.05x1000")
    try:
        t = sched.submit(P2, 50, deadline=time.monotonic() + 0.4)
        out = list(t.tokens())
    finally:
        FAULTS.clear()
    assert t.finish == "timeout"
    assert 0 < len(out) < 50  # truncated by the deadline, not the budget
    ref = solo_refs[tuple(P2)]  # oracle only covers the first 30 tokens
    n = min(len(out), len(ref))
    assert out[:n] == ref[:n]


def test_drain_refuses_new_and_finishes_inflight(solo_refs):
    eng = make_engine(2)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4)
    try:
        t = sched.submit(P2, 20)
        sched.begin_drain(time.monotonic() + 60)
        with pytest.raises(SchedulerClosed):
            sched.submit(P1, 4)
        out = list(t.tokens())
        # generous grace: the in-flight request ran to its natural finish
        assert t.finish == "length"
        assert out == solo_refs[tuple(P2)][:20]
    finally:
        sched.close()


def test_slot_churn_under_device_faults(solo_refs, sched_stack):
    """Fault drill: a dispatch failure retires every active slot with the
    error on its ticket; the loop survives and the next wave of requests
    (slot churn over the same rows) decodes correctly."""
    _, sched = sched_stack
    with injected("engine.device_step=raise:RuntimeError:churnx1"):
        t = sched.submit(P1, 8)
        with pytest.raises(RuntimeError, match="churn"):
            list(t.tokens())
        assert t.finish == "error"
    # churn: more requests than slots, several waves over reused rows
    for _ in range(2):
        tickets = [sched.submit(p, 6) for p in PROMPTS]
        for p, t in zip(PROMPTS, tickets):
            assert list(t.tokens()) == solo_refs[tuple(p)][:6]
            assert t.finish == "length"


def test_saturation_raises():
    small = SlotScheduler(make_engine(2), max_queue=1)
    tickets = []
    try:
        FAULTS.install("engine.device_step=delay:0.05x1000")
        tickets = [small.submit(P1, 30) for _ in range(2)]
        deadline = time.monotonic() + 30
        while small.occupancy()["active"] < 2:  # both slots taken
            assert time.monotonic() < deadline
            time.sleep(0.01)
        tickets.append(small.submit(P1, 30))  # fills the wait queue
        with pytest.raises(SchedulerSaturated):
            small.submit(P2, 4)
    finally:
        FAULTS.clear()
        for t in tickets:
            t.cancel()
        small.close()


def test_exclusive_parks_slots_for_oneshot_batch(solo_refs, sched_stack):
    """The lockstep one-shot paths (list prompts, n>1, logprobs) reset
    the shared cache — exclusive() must wait out live slots, run the
    one-shot, and hand the engine back."""
    eng, sched = sched_stack
    t = sched.submit(P1, 8)
    with sched.exclusive():
        assert sched.occupancy()["active"] == 0
        eng.reset()
        # the budget is a TOTAL row length; P2 (7 tokens) needs headroom
        outs = eng.generate_batch(list(PROMPTS), 12, temperature=0.0,
                                  chunk=3)
        ref = solo_refs[tuple(P2)]
        comp = outs[1][len(P2):]
        assert comp == ref[:len(comp)] and comp
    # the parked request was already complete (retired before the pause)
    assert list(t.tokens()) == solo_refs[tuple(P1)][:8]


def test_generate_batch_ragged_offsets_survive_slot_reset(solo_refs,
                                                          sched_stack):
    """Regression: interleaved slot traffic (per-row pos vectors) must not
    disturb the one-shot batch path's ragged offset bookkeeping."""
    eng, sched = sched_stack
    for p in (P3, P4):
        list(sched.submit(p, 5).tokens())  # slot traffic
    with sched.exclusive():
        eng.reset()
        outs = eng.generate_batch(list(PROMPTS), 8, temperature=0.0, chunk=4)
    for p, row in zip(PROMPTS, outs):
        ref = solo_refs[tuple(p)]
        comp = row[len(p):]
        assert comp == ref[:len(comp)] and comp, p


def test_aggregate_throughput_beats_serialized_2x(sched_stack):
    """Acceptance: 4 concurrent requests through the scheduler ≥ 2× the
    serialized batch=1 aggregate decode throughput.  An injected
    per-dispatch device delay models the TPU weight-read cost both paths
    pay per dispatch — the scheduler amortizes it over 4 rows."""
    eng4, sched = sched_stack
    e1 = make_engine(1)
    max_new = 16

    def run_serial():
        for p in PROMPTS:
            e1.reset()
            toks = [t for t, _ in e1.generate_stream(
                p, len(p) + max_new, temperature=0.0, chunk=5)]
            assert len(toks) >= len(p) + max_new - 1

    def run_sched():
        tickets = [sched.submit(p, max_new) for p in PROMPTS]
        for t in tickets:
            assert len(list(t.tokens())) == max_new

    run_serial()   # warm both paths' executables off the clock
    run_sched()
    FAULTS.install("engine.device_step=delay:0.02x100000")
    try:
        t0 = time.monotonic()
        run_serial()
        serial_s = time.monotonic() - t0
        t0 = time.monotonic()
        run_sched()
        sched_s = time.monotonic() - t0
    finally:
        FAULTS.clear()
    # equal token totals, so the tok/s ratio is the inverse duration ratio
    assert serial_s >= 2.0 * sched_s, (serial_s, sched_s)


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_request_id_stamped_in_spans_and_logs(solo_refs, sched_stack):
    """PR-7 satellite: the scheduler thread serves many requests, so the
    ticket's request ID must be stamped explicitly — sched_admit and
    sched_retire spans carry ``rid``, sched_step carries the ``rids`` of
    every row it drove, and the join/retire log records carry
    ``request_id`` via the contextvar the record factory reads."""
    _, sched = sched_stack
    h = _Capture()
    logger = logging.getLogger("dllama.runtime.scheduler")
    old_level = logger.level
    logger.addHandler(h)
    logger.setLevel(logging.INFO)
    try:
        t = sched.submit(P1, 6)
        rid = t.rid
        assert list(t.tokens()) == solo_refs[tuple(P1)][:6]
    finally:
        logger.removeHandler(h)
        logger.setLevel(old_level)
    spans = obs_trace.TRACER.snapshot()
    admits = [s for s in spans if s["name"] == "sched_admit"
              and s["rid"] == rid]
    retires = [s for s in spans if s["name"] == "sched_retire"
               and s["rid"] == rid]
    steps = [s for s in spans if s["name"] == "sched_step"
             and rid in s["args"].get("rids", ())]
    assert len(admits) == 1 and admits[0]["args"]["queued_ms"] >= 0
    assert len(retires) == 1 and retires[0]["args"]["reason"] == "length"
    assert steps, "every dispatch span must name the rows it drove"
    tagged = [r for r in h.records
              if getattr(r, "request_id", None) == rid]
    msgs = {r.getMessage() for r in tagged}
    assert any("join" in m for m in msgs), msgs
    assert any("retire" in m for m in msgs), msgs


def test_goodput_components_sum_to_wall_window(solo_refs, sched_stack):
    """Acceptance: the goodput decomposition telescopes — prefill +
    decode + pad + host_gap + idle account for the whole first-dispatch →
    last-dispatch wall, within 5%."""
    _, sched = sched_stack
    tickets = [sched.submit(p, 8) for p in PROMPTS]
    for p, t in zip(PROMPTS, tickets):
        assert list(t.tokens()) == solo_refs[tuple(p)][:8]
    window = sched.wall_window()
    assert window is not None
    wall_ms = (window[1] - window[0]) * 1e3
    comp_ms = sum(sched._comp.values())
    assert comp_ms == pytest.approx(wall_ms, rel=0.05), \
        (dict(sched._comp), wall_ms)
    busy = sched._comp["prefill"] + sched._comp["decode"]
    assert 0 < busy <= comp_ms


def test_timeline_entries_name_slot_phases(solo_refs, sched_stack):
    _, sched = sched_stack
    obs_flight.TIMELINE.clear()
    t = sched.submit(P2, 6)
    assert list(t.tokens()) == solo_refs[tuple(P2)][:6]
    steps = obs_flight.TIMELINE.snapshot()
    assert steps, "dispatches must land in the timeline"
    rid = t.rid
    phases_seen = set()
    for e in steps:
        assert len(e["slots"]) == 4  # one entry per slot, every step
        assert e["wall_ms"] >= 0 and e["host_gap_ms"] >= 0
        for s in e["slots"]:
            if s.get("request_id") == rid:
                phases_seen.add(s["phase"])
    assert "prefill" in phases_seen and "decode" in phases_seen
