"""The bench orchestrator's output contract (bench.py).

The driver records bench.py's LAST stdout line as the round's JSON; every
failure branch was manually validated against dead/half-up/killed relay
states — these tests pin the pieces that must never regress: the
single-line emit contract, the extras merge, the relay TCP gate, and the
SIGTERM last-resort line.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

from fixtures import REPO, free_port

sys.path.insert(0, REPO)
import bench  # noqa: E402


def test_emit_contract(capfd):
    """One parseable line; backend stripped; extras riding along."""
    bench._emit({"metric": "m", "value": 1.5, "unit": "tok/s",
                 "vs_baseline": None, "backend": "tpu"},
                {"llama3-8b_toks": 88.0})
    out = capfd.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 1
    obj = json.loads(lines[0])
    assert obj["value"] == 1.5 and "backend" not in obj
    assert obj["extras"] == {"llama3-8b_toks": 88.0}


def test_relay_listening_gate(monkeypatch):
    port = free_port()
    monkeypatch.setattr(bench, "RELAY_PORT", port)
    monkeypatch.setattr(bench, "RELAY_HOST", "127.0.0.1")
    assert bench._relay_listening(1.0) is False  # nothing bound
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(1)
    try:
        assert bench._relay_listening(1.0) is True
    finally:
        srv.close()


def test_sigterm_emits_last_resort_line():
    """A killed bench must still leave one parseable JSON line (the r03
    failure mode: a dead round with nothing for BENCH_r{N}.json)."""
    env = dict(os.environ)
    env["BENCH_BUDGET_S"] = "3000"
    env["BENCH_RELAY_PORT"] = str(free_port())  # guaranteed-dead relay
    p = subprocess.Popen([sys.executable, os.path.join(REPO, "bench.py")],
                         stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                         env=env, cwd=REPO)
    time.sleep(3)  # inside the poll loop, nothing emitted yet
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=30)
    assert p.returncode == 1
    lines = [l for l in out.decode().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    obj = json.loads(lines[0])
    assert obj["unit"] == "tok/s" and "interrupted" in obj["metric"]
