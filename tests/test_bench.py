"""The bench orchestrator's output contract (bench.py).

The driver records bench.py's LAST stdout line as the round's JSON; every
failure branch was manually validated against dead/half-up/killed relay
states — these tests pin the pieces that must never regress: the
single-line emit contract, the extras merge, the relay TCP gate, and the
SIGTERM last-resort line.
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import subprocess
import sys
import time

import pytest

from fixtures import REPO, free_port

sys.path.insert(0, REPO)
import bench  # noqa: E402


def test_emit_contract(capfd):
    """One parseable line; backend stripped; extras riding along (plus
    the perf-sentinel verdict when a previous banked round exists next
    to bench.py — evidence, never a gate)."""
    bench._emit({"metric": "m", "value": 1.5, "unit": "tok/s",
                 "vs_baseline": None, "backend": "tpu"},
                {"llama3-8b_toks": 88.0})
    out = capfd.readouterr().out
    lines = [l for l in out.splitlines() if l.strip()]
    assert len(lines) == 1
    obj = json.loads(lines[0])
    assert obj["value"] == 1.5 and "backend" not in obj
    extras = obj["extras"]
    assert extras["llama3-8b_toks"] == 88.0
    sentinel = extras.pop("perf_sentinel", None)
    assert extras == {"llama3-8b_toks": 88.0}
    if sentinel is not None:  # this checkout has banked rounds
        assert sentinel["verdict"] in ("ok", "regression")
        assert sentinel["vs"].startswith("BENCH_r")


def test_relay_listening_gate(monkeypatch):
    port = free_port()
    monkeypatch.setattr(bench, "RELAY_PORT", port)
    monkeypatch.setattr(bench, "RELAY_HOST", "127.0.0.1")
    assert bench._relay_listening(1.0) is False  # nothing bound
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", port))
    srv.listen(1)
    try:
        assert bench._relay_listening(1.0) is True
    finally:
        srv.close()


def test_sigterm_emits_last_resort_line():
    """A killed bench must still leave one parseable JSON line (the r03
    failure mode: a dead round with nothing for BENCH_r{N}.json)."""
    env = dict(os.environ)
    env["BENCH_BUDGET_S"] = "3000"
    env["BENCH_RELAY_PORT"] = str(free_port())  # guaranteed-dead relay
    p = subprocess.Popen([sys.executable, os.path.join(REPO, "bench.py")],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         env=env, cwd=REPO)
    # Wait for the poll-loop stderr marker before killing: it prints after
    # the term handler is installed, so the SIGTERM provably races nothing.
    # (A fixed sleep flaked when a parallel TPU bench starved this child's
    # interpreter startup past the margin.)  select() bounds the wait even
    # if the child goes silent before the marker.
    deadline = time.time() + 120
    buf = b""
    while b"polling for tunnel" not in buf and time.time() < deadline:
        r, _, _ = select.select([p.stderr], [], [],
                                max(0.0, deadline - time.time()))
        if not r:
            break
        chunk = os.read(p.stderr.fileno(), 4096)
        if not chunk:
            break
        buf += chunk
    p.stderr.close()
    p.send_signal(signal.SIGTERM)
    out, _ = p.communicate(timeout=30)
    assert p.returncode == 1
    lines = [l for l in out.decode().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    obj = json.loads(lines[0])
    assert obj["unit"] == "tok/s" and "interrupted" in obj["metric"]


def test_dead_relay_emits_insession_capture():
    """With the relay dead but a committed in-session TPU capture present,
    the round-end bench must surface that hardware evidence (provenance-
    tagged) as its one line — not only a degraded CPU number (r05: the
    relay was alive mid-session and dead at round end in 3 of 4 rounds)."""
    art_path = os.path.join(REPO, "BENCH_insession.json")
    if not os.path.exists(art_path):
        pytest.skip("no in-session artifact in this checkout")
    art = json.loads(open(art_path).read().strip())
    if not art.get("value") or "DEGRADED" in art.get("metric", ""):
        pytest.skip("in-session artifact is not hardware evidence")
    # mirror bench's freshness gate exactly: round stamp first, 14 h
    # timestamp fallback — same parser bench uses
    cur_round = bench.current_round()
    if art.get("round") is not None and cur_round is not None:
        fresh = int(art["round"]) == cur_round
    else:
        fresh = time.time() - float(art.get("captured_unix") or 0) < 14 * 3600
    if not fresh:
        pytest.skip("in-session artifact is stale; bench correctly "
                    "prefers the degraded path")
    env = dict(os.environ)
    env["BENCH_BUDGET_S"] = "200"
    env["BENCH_RELAY_PORT"] = str(free_port())  # guaranteed-dead relay
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                       env=env, cwd=REPO, timeout=600)
    lines = [l for l in r.stdout.decode().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    obj = json.loads(lines[0])
    assert "in-session capture" in obj["metric"]
    assert obj["value"] == art["value"]


def test_maybe_blocked_applies_to_q40_only(monkeypatch):
    """The blocked-layout lever converts Q40 params and refuses to claim
    the layout for q80 runs (blocked_params is a no-op on Q8 planes; the
    banner would mislabel the measurement)."""
    import numpy as np
    import jax.numpy as jnp
    from dllama_tpu.ops import q40
    from dllama_tpu.ops.q8 import Q8Tensor

    monkeypatch.setenv("DLLAMA_Q40_LAYOUT", "blocked")
    qt = q40.quantize(
        (np.random.RandomState(0).randn(2, 64, 32) * 0.1).astype(np.float32))
    out = bench.maybe_blocked({"a": qt})
    assert isinstance(out["a"], q40.BlockedQTensor)
    q8t = Q8Tensor(jnp.zeros((2, 64, 32), jnp.int8),
                   jnp.zeros((2, 2, 32), jnp.uint16), (64, 32))
    out2 = bench.maybe_blocked({"b": q8t}, codec="q80")
    assert out2["b"] is q8t
    monkeypatch.delenv("DLLAMA_Q40_LAYOUT")
    out3 = bench.maybe_blocked({"a": qt})
    assert out3["a"] is qt  # lever off → untouched


def test_bench_decode_pipelined_schedule_runs():
    """_bench_decode's depth-1 pipelined loop (dispatch chunk i+1 on the
    device-carried token before fetching chunk i) must keep the position
    arithmetic sound end to end — a schedule regression shows up as a
    cache-bounds crash or a nonsense rate."""
    cfg = bench._model_cfg("cpu-tiny").with_(quant_impl="xla")
    ms = bench._bench_decode(cfg, chunk=8, n_chunks=3)
    assert 0 < ms < 10_000


def test_memory_plan_models_blocked_padding(monkeypatch):
    """The planner's blocked-layout estimate pads the output axis with
    to_blocked's exact clamp (narrow planes pad to 128 multiples, not the
    full tile)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "memory_plan", os.path.join(REPO, "tools", "memory_plan.py"))
    mp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mp)
    cfg = mp._cfg("llama2-7b")
    base = mp.plan(cfg)["weights_sharded"]
    monkeypatch.setenv("DLLAMA_Q40_LAYOUT", "blocked")
    blocked = mp.plan(cfg)["weights_sharded"]
    assert base < blocked < base * 1.12  # padding exists but is bounded
