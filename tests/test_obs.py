"""Observability tests: metric registry, dual exposition, structured
logs with request IDs, and span tracing (docs/OBSERVABILITY.md).

The acceptance contract: one registry feeds both a Prometheus text 0.0.4
scrape and the backward-compatible ``/metrics`` JSON (a superset of every
pre-PR key); every HTTP response carries ``X-Request-Id`` and grepping
captured log records for that ID reconstructs the request's lifecycle
(accept → queue → prefill → decode → finish) including engine-side
records; ``/debug/trace`` (+ tools/trace_dump.py) emits Chrome
trace_event JSON with distinct queue-wait/prefill/decode-chunk spans.
"""

import importlib.util
import io
import json
import logging
import math
import os
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from fixtures import free_port, write_tiny_tokenizer

from dllama_tpu.obs import log as obs_log, metrics as obs_metrics, trace as obs_trace
from dllama_tpu.obs.metrics import Counter, Gauge, Histogram, Registry
from dllama_tpu.runtime.faults import FAULTS, injected

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: every key the pre-registry /metrics JSON exported — the JSON path must
#: remain a superset of these forever (dashboards parse them)
PRE_PR_KEYS = {
    "uptime_s", "requests_served", "requests_rejected_429",
    "requests_rejected_503", "read_timeouts_408", "deadline_timeouts",
    "client_disconnects", "server_errors", "avg_request_s",
    "checksum_verified", "checksum_failures", "numeric_faults",
    "snapshot_restores",
}


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


# --- unit: registry -------------------------------------------------------

def test_counter_gauge_histogram_basics():
    reg = Registry()
    c = reg.counter("hits", "help text")
    g = reg.gauge("depth")
    h = reg.histogram("lat_seconds", (0.1, 1.0, 10.0))
    c.inc()
    c.inc(4)
    g.set(2.5)
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    assert c.value == 5 and c.name == "dllama_hits_total"
    assert g.value == 2.5 and g.name == "dllama_depth"
    hv = h.json_value()
    assert hv["count"] == 4 and hv["sum"] == pytest.approx(55.55)
    assert hv["buckets"] == {"0.1": 1, "1": 2, "10": 3, "+Inf": 4}
    reg.reset()
    assert c.value == 0 and g.value == 0.0 and h.count == 0


def test_registry_get_or_create_and_kind_collision():
    reg = Registry()
    a = reg.counter("x")
    assert reg.counter("x") is a          # same key → same object
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x")                    # key exists as another kind
    j = reg.snapshot_json()
    assert j["schema_version"] == obs_metrics.SCHEMA_VERSION
    assert j["x"] == 0 and "uptime_s" in j


def test_boundary_values_land_in_le_buckets():
    """Prometheus ``le`` is less-or-EQUAL: an observation exactly on a
    bucket upper bound belongs in that bucket."""
    h = Histogram("dllama_b", "b", (1.0, 2.0))
    h.observe(1.0)
    h.observe(2.0)
    assert h.json_value()["buckets"] == {"1": 1, "2": 2, "+Inf": 2}


def _parse_prom(text):
    """Minimal Prometheus text-format parser: returns ({name: type},
    {name: [(labels, value)]}) and fails on any unparseable line."""
    types, samples = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, t = line.split(" ", 3)
            types[name] = t.strip()
        elif line.startswith("# HELP "):
            assert len(line.split(" ", 3)) == 4, f"bare HELP line: {line!r}"
        elif line.startswith("#"):
            pytest.fail(f"unknown comment line: {line!r}")
        else:
            m = re.match(r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})? "
                         r"(-?(?:[0-9.eE+-]+|\+Inf))$", line)
            assert m, f"unparseable sample line: {line!r}"
            samples.setdefault(m.group(1), []).append(
                (m.group(2) or "", float(m.group(3).replace("+Inf", "inf"))))
    return types, samples


def _check_histogram_invariants(name, types, samples):
    base = name[: -len("_bucket")] if name.endswith("_bucket") else name
    buckets = samples[f"{base}_bucket"]
    les = [float(lbl[len('{le="'):-2].replace("+Inf", "inf"))
           for lbl, _ in buckets]
    counts = [v for _, v in buckets]
    assert les == sorted(les) and les[-1] == math.inf
    assert counts == sorted(counts), f"{base} buckets must be cumulative"
    (_, total_count), = samples[f"{base}_count"]
    assert counts[-1] == total_count, f"{base} +Inf bucket != count"
    assert f"{base}_sum" in samples


def test_prometheus_text_parses_with_invariants():
    obs_metrics.TTFT.observe(0.3)
    obs_metrics.REQUESTS_SERVED.inc(0)  # present even at zero
    text = obs_metrics.render_prometheus()
    types, samples = _parse_prom(text)
    # counters end _total, gauges/histograms don't; HELP+TYPE present
    assert types["dllama_requests_served_total"] == "counter"
    assert types["dllama_uptime_seconds"] == "gauge"
    assert types["dllama_ttft_seconds"] == "histogram"
    for name, t in types.items():
        if t == "histogram":
            _check_histogram_invariants(name, types, samples)
            continue
        rows = samples.get(name, [])
        if rows and rows[0][0] == "":
            # plain scalar family: exactly one unlabeled sample
            assert len(rows) == 1, name
        else:
            # labeled family (matmul_dispatch, q40_degrade, hbm gauges):
            # zero samples until first touch, then one per distinct label
            # set — duplicates would make scrapers sum silently
            labels = [lbl for lbl, _ in rows]
            assert all(labels), f"{name} mixes labeled and unlabeled samples"
            assert len(set(labels)) == len(labels), f"{name} duplicate labels"


def test_module_json_is_superset_of_pre_pr_keys():
    j = obs_metrics.snapshot_json()
    missing = (PRE_PR_KEYS - {"avg_request_s", "uptime_s"}) - set(j)
    assert not missing, f"registry JSON lost pre-PR keys: {missing}"
    assert "schema_version" in j and "ttft_seconds" in j


def test_concurrent_bump_vs_snapshot():
    """Counters and histograms stay exact and internally consistent while
    scrapes run concurrently with bumps from several threads."""
    reg = Registry()
    c = reg.counter("n")
    h = reg.histogram("lat", (1, 2, 4))
    N, T = 5000, 4

    def bump():
        for i in range(N):
            c.inc()
            h.observe(i % 6)

    threads = [threading.Thread(target=bump) for _ in range(T)]
    for t in threads:
        t.start()
    for _ in range(200):  # scrape while the writers run
        s = reg.snapshot_json()
        hv = s["lat"]
        assert hv["buckets"]["+Inf"] == hv["count"]
        cum = list(hv["buckets"].values())
        assert cum == sorted(cum)
        reg.render_prometheus()
    for t in threads:
        t.join()
    assert c.value == N * T and h.count == N * T


def test_integrity_counters_ride_the_registry():
    """io/integrity.py's counter API is now a view over the registry: a
    bump is visible in BOTH exposition paths and reset still zeroes."""
    from dllama_tpu.io import integrity
    integrity.reset_counters()
    integrity.bump_counter("checksum_failures", 3)
    assert integrity.counters()["checksum_failures"] == 3
    assert obs_metrics.snapshot_json()["checksum_failures"] == 3
    assert "dllama_checksum_failures_total 3" in obs_metrics.render_prometheus()
    integrity.reset_counters()
    assert all(v == 0 for v in integrity.counters().values())


# --- unit: structured logging --------------------------------------------

def test_json_log_line_shape():
    buf = io.StringIO()
    obs_log.configure("json", "debug", stream=buf, force=True)
    lg = obs_log.get_logger("test.shape")
    obs_log.set_request_id("rid-json-1")
    try:
        lg.info("hello", extra={"k": 1, "path": "/x"})
    finally:
        obs_log.set_request_id(None)
    rec = json.loads(buf.getvalue().strip())
    assert rec["event"] == "hello" and rec["level"] == "INFO"
    assert rec["logger"] == "dllama.test.shape"
    assert rec["request_id"] == "rid-json-1"
    assert rec["k"] == 1 and rec["path"] == "/x" and "ts" in rec


def test_human_format_and_no_request_id():
    buf = io.StringIO()
    obs_log.configure("human", "info", stream=buf, force=True)
    obs_log.get_logger("test.h").warning("boom", extra={"n": 2})
    line = buf.getvalue().strip()
    assert "WARNING" in line and "dllama.test.h" in line
    assert "boom" in line and "n=2" in line
    assert "[" not in line.split("boom")[0].split("dllama.test.h")[1], \
        "no [rid] bracket when no request id is set"


def test_env_spec_parsing():
    assert obs_log._parse_env("json:debug") == ("json", "debug")
    assert obs_log._parse_env("debug,json") == ("json", "debug")
    assert obs_log._parse_env("human") == ("human", None)
    assert obs_log._parse_env("") == (None, None)
    assert obs_log._parse_env("bogus:nope") == (None, None)


# --- unit: tracer ---------------------------------------------------------

def test_tracer_ring_capacity_and_span():
    tr = obs_trace.Tracer(capacity=4)
    for i in range(10):
        tr.record("s", float(i), float(i) + 0.5, i=i)
    spans = tr.snapshot()
    assert len(spans) == 4
    assert [s["args"]["i"] for s in spans] == [6, 7, 8, 9]
    with tr.span("timed", x=1):
        time.sleep(0.01)
    last = tr.snapshot()[-1]
    assert last["name"] == "timed" and last["dur"] >= 0.009


def test_trace_events_chrome_format_and_rid_filter():
    tr = obs_trace.Tracer(capacity=64)
    for rid in ("r1", "r2", "r3"):
        obs_log.set_request_id(rid)
        tr.record("request", 1.0, 2.0)
    obs_log.set_request_id(None)
    doc = tr.trace_json(last_requests=2)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {e["args"]["request_id"] for e in xs} == {"r2", "r3"}
    assert metas and metas[0]["name"] == "thread_name"
    e = xs[0]
    assert e["ts"] == pytest.approx(1.0 * 1e6)
    assert e["dur"] == pytest.approx(1.0 * 1e6)
    assert e["cat"] == "dllama" and isinstance(e["tid"], int)


# --- satellite: RunStats running sums ------------------------------------

def test_runstats_running_sums_match_numpy():
    import numpy as np
    from dllama_tpu.runtime.engine import RunStats, StepStats

    rng = np.random.RandomState(7)
    stats = [StepStats(*(rng.rand(5) * 10)) for _ in range(200)]
    rs = RunStats()
    for s in stats:
        rs.add(s)
    assert rs.avg_generation_ms == pytest.approx(
        np.mean([s.generation_ms for s in stats]))
    assert rs.avg_inference_ms == pytest.approx(
        np.mean([s.inference_ms for s in stats]))
    assert rs.avg_transfer_ms == pytest.approx(
        np.mean([s.transfer_ms for s in stats]))
    assert rs.avg_sent_bytes == pytest.approx(
        np.mean([s.sent_bytes for s in stats]))
    assert rs.avg_recv_bytes == pytest.approx(
        np.mean([s.recv_bytes for s in stats]))
    assert rs.tokens_per_second == pytest.approx(
        1000.0 / rs.avg_generation_ms)
    empty = RunStats()
    assert empty.avg_generation_ms == 0.0 and empty.tokens_per_second == 0.0


# --- live in-process server ----------------------------------------------

@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    import jax

    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.tokenizer.bpe import Tokenizer

    d = tmp_path_factory.mktemp("obs")
    tok = Tokenizer(write_tiny_tokenizer(str(d / "tok.t")))
    cfg = tiny_config(seq_len=128, vocab_size=300)
    eng = Engine(cfg, init_params(cfg, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]))
    return eng, tok


@pytest.fixture
def api(stack):
    from dllama_tpu.server.api import ApiState, serve

    servers = []

    def make(**kw):
        eng, tok = stack
        state = ApiState(eng, tok, default_temperature=0.0, chunk=2, **kw)
        srv = serve(state, host="127.0.0.1", port=free_port(), block=False)
        servers.append(srv)
        return state, f"http://127.0.0.1:{srv.server_address[1]}"

    yield make
    for s in servers:
        s.shutdown()
        s.server_close()


CHAT = "/v1/chat/completions"
BODY = {"messages": [{"role": "user", "content": "hello"}], "seed": 3}


def post(base, path, body, headers=None, timeout=240):
    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=timeout)


def get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    return urllib.request.urlopen(req, timeout=30)


def test_metrics_dual_exposition_live(api):
    state, base = api()
    with post(base, CHAT, dict(BODY, stream=True)) as r:
        assert r.headers["X-Request-Id"]
        assert b"[DONE]" in r.read()

    # default JSON stays a superset of every pre-PR key, plus the new
    # schema_version and histogram objects
    with get(base, "/metrics") as r:
        assert "application/json" in r.headers["Content-Type"]
        j = json.loads(r.read())
    missing = PRE_PR_KEYS - set(j)
    assert not missing, f"/metrics JSON lost pre-PR keys: {missing}"
    assert j["schema_version"] == obs_metrics.SCHEMA_VERSION
    assert j["requests_served"] == 1            # per-instance view
    assert j["ttft_seconds"]["count"] >= 1      # populated by the request
    assert j["inter_token_seconds"]["count"] >= 1

    # Accept negotiation → Prometheus text 0.0.4 with populated latency
    # histograms from the live request
    with get(base, "/metrics", headers={"Accept": "text/plain"}) as r:
        assert "version=0.0.4" in r.headers["Content-Type"]
        text = r.read().decode()
    types, samples = _parse_prom(text)
    assert types["dllama_ttft_seconds"] == "histogram"
    assert types["dllama_inter_token_seconds"] == "histogram"
    for name, t in types.items():
        if t == "histogram":
            _check_histogram_invariants(name, types, samples)
    (_, ttft_count), = samples["dllama_ttft_seconds_count"]
    assert ttft_count >= 1
    (_, it_count), = samples["dllama_inter_token_seconds_count"]
    assert it_count >= 1
    # engine-side step histograms populated too
    (_, g_count), = samples["dllama_engine_generation_ms_count"]
    assert g_count >= 1

    # ?format=prometheus works without the Accept header
    with get(base, "/metrics?format=prometheus") as r:
        assert "version=0.0.4" in r.headers["Content-Type"]


def test_request_id_lifecycle_in_logs(api):
    obs_log.configure("json", "debug", stream=io.StringIO(), force=True)
    records = []

    class Cap(logging.Handler):
        def emit(self, record):
            records.append(record)

    cap = Cap(level=logging.DEBUG)
    root = logging.getLogger("dllama")
    root.addHandler(cap)
    try:
        state, base = api()
        rid = "lifecycle.test-123"
        with post(base, CHAT, BODY, headers={"X-Request-Id": rid}) as r:
            assert r.headers["X-Request-Id"] == rid  # echoed, not regenerated
            json.loads(r.read())
        # "finish" is logged on the server thread AFTER the last response
        # byte, so the client can observe the full body a hair before the
        # record lands — wait for it rather than racing it
        want = {"accept", "queue", "prefill", "decode", "finish"}
        deadline = time.monotonic() + 5.0
        while True:
            mine = [r for r in records
                    if getattr(r, "request_id", None) == rid]
            events = {r.getMessage() for r in mine}
            if want <= events or time.monotonic() > deadline:
                break
            time.sleep(0.02)
        # full lifecycle under ONE grep key: server accept/queue/finish
        # AND engine-side prefill/decode records
        assert want <= events, events
        assert any(r.name.startswith("dllama.runtime") for r in mine)
        assert any(r.name.startswith("dllama.server") for r in mine)
    finally:
        root.removeHandler(cap)


def test_client_request_id_sanitized(api):
    state, base = api()
    dirty = "abc<script>!{}$#123"
    with post(base, CHAT, BODY, headers={"X-Request-Id": dirty}) as r:
        assert r.headers["X-Request-Id"] == "abcscript123"
        json.loads(r.read())


def test_request_id_on_429(api):
    state, base = api(max_pending=0)
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(base, CHAT, BODY)
    assert ei.value.code == 429
    assert ei.value.headers["X-Request-Id"]
    assert state.metrics.requests_rejected_429 == 1


def test_request_id_on_500(api):
    state, base = api()
    with injected("engine.device_step=raise:RuntimeError:kaboomx1"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(base, CHAT, BODY)
    assert ei.value.code == 500
    assert ei.value.headers["X-Request-Id"]
    assert state.metrics.server_errors == 1
    state.engine.reset()          # don't leak a mid-prefill position
    state.naive_cache.clear()


def test_debug_trace_endpoint(api):
    state, base = api()
    obs_trace.clear()
    with post(base, CHAT, BODY) as r:
        json.loads(r.read())
    with get(base, "/debug/trace?last=5") as r:
        doc = json.loads(r.read())
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in xs}
    assert {"request", "queue_wait", "prefill"} <= names, names
    assert "decode_chunk" in names or "decode_step" in names, names
    for e in xs:  # chrome trace_event essentials
        assert e["ph"] == "X" and "ts" in e and "dur" in e
        assert e["pid"] == 1 and isinstance(e["tid"], int)


def test_trace_dump_cli(api, tmp_path, capsys):
    spec = importlib.util.spec_from_file_location(
        "trace_dump", os.path.join(REPO, "tools", "trace_dump.py"))
    tool = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tool)

    state, base = api()
    with post(base, CHAT, BODY) as r:
        json.loads(r.read())
    out = tmp_path / "trace.json"
    assert tool.main([base, "-o", str(out), "-n", "5"]) == 0
    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"request", "queue_wait"} <= names
    printed = capsys.readouterr().out
    assert "spans across" in printed
    # unreachable server → clean failure, not a traceback
    assert tool.main(["http://127.0.0.1:1", "-o", str(out)]) == 1


def test_metric_catalog_matches_docs():
    """Doc-drift guard (PR-7 satellite): every module-level metric family
    in obs/metrics.py has a row in the docs/OBSERVABILITY.md catalog, and
    every catalog row names a real family — both directions.  Ad-hoc
    metrics registered by tests don't count (module attributes only);
    dllama_uptime_seconds is rendered inline by the registry."""
    from dllama_tpu.obs.metrics import LabeledCounter, LabeledGauge
    code = {"dllama_uptime_seconds"}
    for obj in vars(obs_metrics).values():
        if isinstance(obj, (Counter, Gauge, Histogram,
                            LabeledCounter, LabeledGauge)):
            code.add(obj.name)
    with open(os.path.join(REPO, "docs", "OBSERVABILITY.md"),
              encoding="utf-8") as f:
        text = f.read()
    documented = set(re.findall(r"^\| `(dllama_[a-z0-9_]+)", text, re.M))
    assert code <= documented, \
        f"metric families missing a catalog row: {sorted(code - documented)}"
    assert documented <= code, \
        f"catalog rows naming no metric family: {sorted(documented - code)}"
