"""Test harness config.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding path
(tensor/sequence parallel over a `jax.sharding.Mesh`) compiles and executes
without TPU hardware — the same trick the driver uses for
``__graft_entry__.dryrun_multichip``.  Must run before the first jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
