"""Test harness config.

Tests run on CPU with 8 virtual XLA devices so the multi-chip sharding path
(tensor/sequence parallel over a `jax.sharding.Mesh`) compiles and executes
without TPU hardware — the same trick the driver uses for
``__graft_entry__.dryrun_multichip``.

The session environment pins JAX to the TPU tunnel (JAX_PLATFORMS=axon set
by sitecustomize *and* baked into jax.config at interpreter start), so a
plain env-var override is ignored; the config update below is what actually
forces CPU.  It must happen before the first backend query.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
