"""Artifact-integrity tests: validated loaders + checksum manifests.

The acceptance contract (docs/ROBUSTNESS.md): any single-byte corruption
of a ``.m``/``.t`` header — or of any checksummed tensor region when the
sidecar manifest is present — is rejected with an
:class:`~dllama_tpu.io.integrity.ArtifactError` naming the file, the
field, and the byte offset.  Never a bare ``struct.error``, never a
silently-garbage tensor.  The fuzz tests here flip/truncate real bytes
in real files, the way storage actually fails.
"""

import importlib.util
import os
import shutil

import numpy as np
import pytest

from fixtures import write_tiny_model, write_tiny_tokenizer

from dllama_tpu.io import integrity, mfile, tfile
from dllama_tpu.io.integrity import ArtifactError

pytestmark = pytest.mark.integrity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def artifacts(tmp_path_factory):
    """One tiny model + tokenizer with manifests; tests that corrupt bytes
    take their own copies."""
    d = tmp_path_factory.mktemp("integrity")
    m, t = str(d / "tiny.m"), str(d / "tiny.t")
    write_tiny_model(m)
    write_tiny_tokenizer(t)
    integrity.write_manifest(m)
    integrity.write_manifest(t)
    return m, t


def flipped_copy(src: str, dst: str, offset: int, xor: int = 0x01) -> str:
    shutil.copy(src, dst)
    man_src = integrity.manifest_path_for(src)
    if os.path.exists(man_src):
        shutil.copy(man_src, integrity.manifest_path_for(dst))
    with open(dst, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ xor]))
    return dst


def test_artifact_error_carries_structured_context():
    e = ArtifactError("x.m", "dim", "value out of range",
                      offset=16, expected="1..1048576", got=-3)
    assert isinstance(e, ValueError)  # pre-integrity callers catch ValueError
    assert (e.path, e.field, e.offset) == ("x.m", "dim", 16)
    msg = str(e)
    assert "x.m" in msg and "dim" in msg and "byte 16" in msg
    assert "'1..1048576'" in msg and "-3" in msg


def test_unknown_tensor_name_lists_known(artifacts):
    model, _ = artifacts
    with mfile.MFile(model) as f:
        with pytest.raises(ArtifactError, match="unknown tensor name") as ei:
            f.info("layers.0.bogus")
        assert "layers.0.bogus" in str(ei.value)
        assert "layers.0.w1" in str(ei.value)  # lists what the file has
        # and the old KeyError contract is gone for every read path
        with pytest.raises(ArtifactError):
            f.tensor("nope")


def test_mfile_header_fuzz_never_struct_error(artifacts, tmp_path):
    """Every single-byte flip in the .m header (no manifest) parses to a
    spec or raises ArtifactError — never struct.error or a giant alloc."""
    import struct
    model, _ = artifacts
    data = bytearray(open(model, "rb").read())
    header_size = mfile.MFile(model).spec.header_size
    victim = str(tmp_path / "flip.m")
    for off in range(header_size):
        flipped = bytearray(data)
        flipped[off] ^= 0xFF
        with open(victim, "wb") as f:
            f.write(flipped)
        try:
            mfile.read_spec(victim)
        except ArtifactError:
            pass
        except struct.error as e:  # the pre-validation failure mode
            pytest.fail(f"struct.error leaked at header byte {off}: {e}")


def test_manifest_catches_every_header_flip(artifacts, tmp_path):
    """With the sidecar present the header digest is always-on: ANY
    header byte flip fails the open with ArtifactError."""
    model, _ = artifacts
    header_size = mfile.MFile(model).spec.header_size
    victim = str(tmp_path / "flip.m")
    for off in range(header_size):
        flipped_copy(model, victim, off, xor=0xFF)
        with pytest.raises(ArtifactError):
            mfile.MFile(victim)


def test_manifest_catches_tensor_flips_lazily(artifacts, tmp_path):
    """--verify-weights: a flipped byte anywhere in a tensor region fails
    that tensor's first read, naming the region's byte offset; untouched
    tensors still read clean from the same corrupt file."""
    model, _ = artifacts
    man = integrity.load_manifest(integrity.manifest_path_for(model))
    rng = np.random.RandomState(11)
    names = sorted(man["tensors"])
    clean = "token_embedding"
    for name in rng.choice([n for n in names if n != clean], size=8,
                           replace=False):
        ent = man["tensors"][name]
        off = ent["offset"] + int(rng.randint(ent["nbytes"]))
        victim = flipped_copy(model, str(tmp_path / "flip.m"), off)
        with mfile.MFile(victim, verify=True) as f:
            with pytest.raises(ArtifactError) as ei:
                f.tensor(name)
            assert ei.value.offset == ent["offset"]
            assert name in str(ei.value)
            f.tensor(clean)  # untouched region verifies and decodes


@pytest.mark.parametrize("cut", ["mid_header", "mid_tensor", "one_byte"])
def test_truncation_rejected(artifacts, tmp_path, cut):
    model, _ = artifacts
    size = os.path.getsize(model)
    keep = {"mid_header": 6, "mid_tensor": size - 100, "one_byte": size - 1}[cut]
    victim = str(tmp_path / "trunc.m")
    shutil.copy(model, victim)
    with open(victim, "r+b") as f:
        f.truncate(keep)
    with pytest.raises(ArtifactError):
        mfile.MFile(victim)


def test_verify_requires_manifest(tmp_path):
    model = str(tmp_path / "bare.m")
    write_tiny_model(model)
    with pytest.raises(ArtifactError, match="checksum_model"):
        mfile.MFile(model, verify=True)
    mfile.MFile(model)  # without verify a bare file still loads


def test_corrupt_manifest_is_itself_an_error(artifacts, tmp_path):
    """A manifest that cannot be parsed must not silently disable
    verification — it is treated as corruption."""
    model, _ = artifacts
    victim = str(tmp_path / "m.m")
    shutil.copy(model, victim)
    with open(integrity.manifest_path_for(victim), "w") as f:
        f.write('{"format": "dllama-manifest", "version": 1')  # truncated
    with pytest.raises(ArtifactError, match="manifest"):
        mfile.MFile(victim)


def test_stale_manifest_detected(artifacts, tmp_path):
    """A manifest whose byte-ranges disagree with the file's tensor plan
    (regenerated model, stale sidecar) is rejected, not trusted."""
    import json
    model, _ = artifacts
    victim = str(tmp_path / "m.m")
    shutil.copy(model, victim)
    man = integrity.load_manifest(integrity.manifest_path_for(model))
    man["tensors"]["wcls"]["offset"] += 32
    mp = integrity.manifest_path_for(victim)
    with open(mp, "w") as f:
        json.dump(man, f)
    with mfile.MFile(victim, verify=True) as f:
        with pytest.raises(ArtifactError, match="manifest"):
            f.tensor("wcls")
    del man["tensors"]["wcls"]
    with open(mp, "w") as f:
        json.dump(man, f)
    with mfile.MFile(victim, verify=True) as f:
        with pytest.raises(ArtifactError, match="manifest"):
            f.tensor("wcls")


def test_io_read_tensor_fault_point(artifacts, tmp_path):
    """The io.read_tensor=corrupt fault flips a byte in the read buffer;
    under --verify-weights the checksum catches the injected corruption."""
    from dllama_tpu.runtime.faults import injected
    model, _ = artifacts
    victim = str(tmp_path / "m.m")
    shutil.copy(model, victim)
    shutil.copy(integrity.manifest_path_for(model),
                integrity.manifest_path_for(victim))
    integrity.reset_counters()
    with injected("io.read_tensor=corruptx1"):
        with mfile.MFile(victim, verify=True) as f:
            with pytest.raises(ArtifactError, match="checksum mismatch"):
                f.tensor("token_embedding")
            f.tensor("token_embedding")  # fault disarmed: reads clean
    assert integrity.counters()["checksum_failures"] == 1


def test_lazy_verification_runs_once(artifacts):
    model, _ = artifacts
    integrity.reset_counters()
    with mfile.MFile(model, verify=True) as f:  # header verifies at open
        f.tensor("wcls")
        f.tensor("wcls")  # second read: already-verified region, no re-crc
    assert integrity.counters()["checksum_verified"] == 2  # header + wcls
    assert integrity.counters()["checksum_failures"] == 0


def test_tfile_fuzz_with_manifest(artifacts, tmp_path):
    """The tokenizer manifest is a whole-file digest: a flip ANYWHERE in
    the .t (header, scores, token bytes) fails the load."""
    _, tok = artifacts
    size = os.path.getsize(tok)
    rng = np.random.RandomState(5)
    offsets = {0, 7, size - 1} | {int(o) for o in rng.randint(size, size=20)}
    victim = str(tmp_path / "flip.t")
    for off in sorted(offsets):
        flipped_copy(tok, victim, off)
        with pytest.raises(ArtifactError):
            tfile.read_tfile(victim)


def test_tfile_structural_fuzz_no_manifest(tmp_path):
    """Without a manifest the .t parser is still fully bounds-checked:
    header flips either parse or raise ArtifactError, never struct.error,
    and truncation is always caught."""
    import struct
    tok = str(tmp_path / "tok.t")
    write_tiny_tokenizer(tok)
    data = bytearray(open(tok, "rb").read())
    victim = str(tmp_path / "flip.t")
    for off in range(min(len(data), 96)):
        flipped = bytearray(data)
        flipped[off] ^= 0xFF
        with open(victim, "wb") as f:
            f.write(flipped)
        try:
            tfile.read_tfile(victim)
        except (ArtifactError, ValueError):
            pass
        except struct.error as e:
            pytest.fail(f"struct.error leaked at tokenizer byte {off}: {e}")
    for keep in (3, 17, len(data) - 1):
        with open(victim, "wb") as f:
            f.write(data[:keep])
        with pytest.raises((ArtifactError, ValueError)):
            tfile.read_tfile(victim)


def _load_checksum_tool():
    spec = importlib.util.spec_from_file_location(
        "checksum_model", os.path.join(REPO, "tools", "checksum_model.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_checksum_tool_write_verify_corrupt(tmp_path, capsys):
    tool = _load_checksum_tool()
    model = str(tmp_path / "m.m")
    write_tiny_model(model)
    assert tool.main(["write", model]) == 0
    assert os.path.exists(integrity.manifest_path_for(model))
    assert tool.main(["verify", model]) == 0
    man = integrity.load_manifest(integrity.manifest_path_for(model))
    ent = man["tensors"]["wcls"]
    with open(model, "r+b") as f:
        f.seek(ent["offset"] + 3)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x01]))
    assert tool.main(["verify", model]) == 1
    out = capsys.readouterr().out
    assert "wcls" in out and "checksum mismatch" in out
    assert tool.main(["verify", str(tmp_path / "missing.m")]) == 1


def test_verify_file_counts_regions(artifacts):
    model, tok = artifacts
    man = integrity.load_manifest(integrity.manifest_path_for(model))
    assert integrity.verify_file(model) == 1 + len(man["tensors"])
    assert integrity.verify_file(tok) == 1  # whole-file digest


def test_counters_seeded_from_boot():
    """Every exported counter key exists before any failure — a missing
    metric reads as "missing" to a dashboard, not "zero"."""
    integrity.reset_counters()
    c = integrity.counters()
    assert set(c) >= {"checksum_verified", "checksum_failures",
                      "numeric_faults", "snapshot_restores"}
    assert all(v == 0 for v in c.values())
