"""Speculative decoding tests (runtime/spec.py proposers +
runtime/engine.py ``slot_verify_async`` + the scheduler's ragged verify
bursts, ``--spec``).

The subsystem contracts, each pinned here on CPU with the tiny model:

* **proposer units** — the prompt-lookup index never matches a suffix
  against itself, grows incrementally, rebuilds on slot reuse; the
  draft-model proposer credits exactly the verifier-kept drafts on
  sync-by-replay, and an identical draft engine reproduces the target's
  own greedy continuation;
* **slot verify** — a ragged verify window accepts the leading
  draft match per row, a no-proposal neighbor rides as one plain decode
  step, and the KV the rejected drafts wrote above the accepted ceiling
  is dead: continuing from the ceiling is byte-identical to solo;
* **byte parity** — greedy output under ragged staggered traffic is
  identical with ``--spec off`` / ``pld`` / ``draft``, pipeline on and
  off, including EOS mid-verify and cancels (partial output is a prefix
  of the solo run);
* **acceptance** — an identical draft engine accepts ~every draft;
  prompt lookup on a repetitive continuation clears the ratio floor;
  counters/gauge land in both exposition formats and per-request counts
  in the flight record;
* **flush points** — speculation coexists with preemption park/resume
  (zero pages leaked) and the DLREQ01 hand-off export (pending drafts
  discarded before the snapshot, never exported);
* **reject storm** — the ``spec.propose=corrupt`` fault's adversarial
  drafts collapse the accept ratio while the served bytes stay the
  model's own greedy output.
"""

import threading
import time

import numpy as np
import pytest

import jax

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.obs import flight as obs_flight, metrics as obs_metrics
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime import snapshot as snapfmt
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.runtime.faults import FAULTS, injected
from dllama_tpu.runtime.scheduler import PRIORITY_LEVELS, SlotScheduler
from dllama_tpu.runtime.spec import (DraftModelProposer, PromptLookupProposer,
                                     make_proposer)

pytestmark = pytest.mark.spec

CFG = tiny_config(seq_len=64)
PAGE = 4
P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]
P3 = [2, 4, 6]
P4 = [9, 8, 7, 6]
PROMPTS = (P1, P2, P3, P4)


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def make_engine(batch=1, zero=False):
    params = init_params(CFG, seed=4)
    if zero:
        # zeroed weights give a constant argmax — a fully predictable
        # continuation, the deterministic accept-ratio oracle
        params = jax.tree_util.tree_map(lambda a: a * 0, params)
    return Engine(CFG, params,
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch)


def make_paged_engine(batch=2, page=PAGE):
    pages_per_slot = -(-CFG.seq_len // page)
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch,
                  kv_pages=batch * pages_per_slot + 1,
                  kv_page_size=page)


@pytest.fixture(scope="module")
def solo_refs():
    """Greedy solo completions per prompt — the parity oracle."""
    eng = make_engine()
    refs = {}
    for p in PROMPTS:
        eng.reset()
        toks = [t for t, _ in eng.generate_stream(
            p, len(p) + 30, temperature=0.0, chunk=5)]
        refs[tuple(p)] = toks[len(p):]
    return refs


# -- proposer units ---------------------------------------------------------

def test_pld_index_lookup_and_reset():
    """The n-gram index finds the latest earlier occurrence, never
    self-matches, grows incrementally at sync, rebuilds on a rid change
    and dies at reset."""
    pr = PromptLookupProposer(ngram=2, vocab=64)
    pr.sync(0, "r1", [1, 2, 3, 1, 2], [])
    # suffix (1, 2) occurred earlier at 0..1 → continuation from 2
    assert pr.propose({0: 3}) == {0: [3, 1, 2]}
    # incremental sync: only the new emitted tokens extend the sequence
    pr.sync(0, "r1", [1, 2, 3, 1, 2], [3, 1])
    assert pr.propose({0: 2}) == {0: [2, 3]}
    # no self-match: a suffix with no earlier occurrence proposes nothing
    pr.sync(1, "r2", [7, 8], [])
    assert pr.propose({1: 4}) == {}
    # rid change (slot reuse / import) rebuilds from scratch
    pr.sync(0, "r9", [5, 6], [])
    assert pr.propose({0: 2}) == {}
    # reset is the flush point: state is gone, nothing proposed
    pr.sync(2, "r3", [1, 2, 3, 1, 2], [])
    pr.reset(2)
    assert pr.propose({2: 2}) == {}


def test_pld_want_clamps_and_absent_slot():
    pr = PromptLookupProposer(ngram=2, vocab=64)
    pr.sync(0, "r1", [4, 5, 4, 5, 4, 5], [])
    assert pr.propose({0: 0}) == {}          # k < 1: nothing
    assert pr.propose({3: 4}) == {}          # never-synced slot: nothing
    got = pr.propose({0: 2})[0]
    assert len(got) <= 2                     # never more than wanted


def test_draft_sync_credits_kept_drafts():
    """Sync-by-replay bookkeeping: after a drafting forward fed ``fed``
    tokens and drafted ``drafted``, a sync carrying the verifier's kept
    tokens credits ``fed + min(leading_match, len(drafted) - 1)`` —
    the last draft was sampled but never fed, so its KV does not exist."""
    pr = DraftModelProposer(make_engine(2))
    pr.sync(0, "r1", [1, 2, 3], [])
    st = pr._states[0]
    st.fed, st.drafted = 3, [10, 11, 12, 13]
    # verifier kept 10, 11 then diverged: credit fed + 2
    pr.sync(0, "r1", [1, 2, 3], [10, 11, 9])
    assert st.synced == 5 and st.drafted == []
    # full acceptance still can't credit the never-fed last draft
    st.fed, st.drafted = 6, [20, 21]
    pr.sync(0, "r1", [1, 2, 3], [10, 11, 9, 20, 21])
    assert st.synced == 7


def test_draft_proposer_reproduces_target_greedy(solo_refs):
    """An identical draft engine drafting from the raw prompt must
    reproduce the target's own greedy continuation — the sync/pre-feed/
    draft dispatch chain is exact, not approximate."""
    pr = DraftModelProposer(make_engine(2))
    pr.sync(0, "r1", P1, [])
    got = pr.propose({0: 4})
    assert got[0] == solo_refs[tuple(P1)][:4]


def test_draft_proposer_rejects_unsupported_engines():
    with pytest.raises(ValueError, match="contiguous"):
        DraftModelProposer(make_paged_engine())


# -- engine layer: ragged slot verify ---------------------------------------

def test_slot_verify_masked_kv_and_ride_along(solo_refs):
    """One verify dispatch: row 0 carries 3 drafts (third wrong), row 1
    rides with no proposal.  Row 0 accepts exactly 2 and the KV its
    rejected draft wrote above the ceiling is dead — continuing both
    rows from their ceilings is byte-identical to solo."""
    eng = make_engine(2)
    r1, r3 = solo_refs[tuple(P1)], solo_refs[tuple(P3)]
    temps = np.zeros((2,), np.float32)
    topps = np.full((2,), 0.9, np.float32)
    # prefill both rows in one ragged dispatch
    tokens = np.zeros((2, len(P2)), np.int32)
    tokens[0, :len(P1)] = P1
    tokens[1, :len(P3)] = P3
    nv = np.array([len(P1), len(P3)], np.int32)
    out = eng.slot_step(tokens, np.zeros((2,), np.int32), nv,
                        temps_np=temps, topps_np=topps)
    assert [int(out[-1, 0]), int(out[-1, 1])] == [r1[0], r3[0]]
    # verify window: row 0 feeds its sample + drafts [r1[1], r1[2], X]
    wrong = (r1[3] + 1) % CFG.vocab_size
    vt = np.zeros((2, 4), np.int32)
    vt[0] = [r1[0], r1[1], r1[2], wrong]
    vt[1, 0] = r3[0]
    pos = np.array([len(P1), len(P3)], np.int32)
    preds, accepted = eng.slot_verify_async(
        vt, pos, np.array([4, 1], np.int32),
        temps_np=temps, topps_np=topps).wait()
    assert int(accepted[0]) == 2 and int(accepted[1]) == 0
    assert [int(x) for x in preds[0, :3]] == r1[1:4]  # 2 drafts + bonus
    assert int(preds[1, 0]) == r3[1]                  # plain decode step
    # continue from each row's accepted ceiling: the rejected draft's KV
    # (and row 1's padding columns) must be invisible
    ft = np.zeros((2, 1), np.int32)
    ft[0, 0], ft[1, 0] = r1[3], r3[1]
    cont = eng.slot_step(ft, np.array([len(P1) + 4, len(P3) + 2], np.int32),
                         np.ones((2,), np.int32), temps_np=temps,
                         topps_np=topps, steps=4)
    assert [int(x) for x in cont[:, 0]] == r1[4:8]
    assert [int(x) for x in cont[:, 1]] == r3[2:6]


def test_slot_verify_validation():
    eng = make_engine(2)
    temps = np.zeros((2,), np.float32)
    topps = np.full((2,), 0.9, np.float32)
    with pytest.raises(ValueError, match="T >= 2"):
        eng.slot_verify_async(np.zeros((2, 1), np.int32),
                              np.zeros((2,), np.int32),
                              np.ones((2,), np.int32),
                              temps_np=temps, topps_np=topps)
    with pytest.raises(ValueError, match="n_valid"):
        eng.slot_verify_async(np.zeros((2, 3), np.int32),
                              np.zeros((2,), np.int32),
                              np.array([4, 1], np.int32),
                              temps_np=temps, topps_np=topps)


# -- scheduler: spec on/off byte parity -------------------------------------

def _run_traffic(sched, solo_refs, *, eos_prompt=None, eos_at=3):
    """Staggered ragged greedy traffic; returns {prompt: (tokens, finish)}.
    ``eos_prompt`` additionally runs one request with an EOS id picked
    from its own solo reference (stop-mid-verify coverage)."""
    results = {}

    def run(p, delay, max_new, eos_ids):
        time.sleep(delay)
        t = sched.submit(p, max_new, eos_ids=eos_ids)
        results[tuple(p)] = (list(t.tokens()), t.finish)

    jobs = [(p, d, 12, ()) for p, d in zip(PROMPTS, (0.0, 0.03, 0.2, 0.4))]
    if eos_prompt is not None:
        ref = solo_refs[tuple(eos_prompt)]
        jobs.append((list(eos_prompt) + [13], 0.1, 25, (ref[eos_at],)))
    threads = [threading.Thread(target=run, args=j) for j in jobs]
    for th in threads:
        th.start()
    for th in threads:
        th.join(120)
    return results


def _make_sched(mode, *, overlap=True):
    eng = make_engine(4)
    spec = make_proposer(
        mode, eng,
        draft_engine=make_engine(4) if mode == "draft" else None)
    return SlotScheduler(eng, prefill_chunk=4, max_wait_ms=50.0,
                         decode_burst=6, overlap=overlap,
                         spec=spec, spec_k=4)


@pytest.fixture(scope="module")
def off_results(solo_refs):
    """The --spec off baseline under the same traffic — what every
    speculating run must byte-match."""
    sched = _make_sched("off")
    try:
        return _run_traffic(sched, solo_refs, eos_prompt=P2)
    finally:
        sched.close()


@pytest.mark.parametrize("overlap", [True, False],
                         ids=["overlap", "no-overlap"])
@pytest.mark.parametrize("mode", ["pld", "draft"])
def test_spec_on_off_byte_parity(solo_refs, off_results, mode, overlap):
    """THE acceptance: greedy output under ragged staggered traffic —
    including an EOS that lands mid-verify-window — is byte-identical
    with speculation on (both proposers) and off, pipeline on and off."""
    sched = _make_sched(mode, overlap=overlap)
    try:
        outs = _run_traffic(sched, solo_refs, eos_prompt=P2)
    finally:
        sched.close()
    assert outs == off_results
    for p in PROMPTS:
        got, finish = outs[tuple(p)]
        assert got == solo_refs[tuple(p)][:12], p
        assert finish == "length"
    assert outs[tuple(list(P2) + [13])][1] == "stop"


def test_spec_cancel_partial_prefix(solo_refs):
    """Cancel mid-decode with speculation live: the partial output is a
    prefix of the solo run — no token from a rejected or in-flight
    draft ever leaks into the stream."""
    sched = _make_sched("pld")
    try:
        with injected("engine.device_step=delay:0.02x100000"):
            t = sched.submit(P1, 50)
            got = []
            for tok in t.tokens():
                got.append(tok)
                if len(got) >= 3:
                    t.cancel("aborted")
        assert t.finish == "aborted"
        assert got == solo_refs[tuple(P1)][:len(got)]
        assert 0 < len(got) < 50
        assert sched._proposals == {}
    finally:
        sched.close()


# -- acceptance ratio + exposition ------------------------------------------

def test_identical_draft_engine_accepts_everything(solo_refs):
    """An identical draft engine predicts the target exactly, so ~every
    draft verifies: the per-ticket counts, global counters, gauge, and
    flight record all agree, and the output is still byte-exact."""
    eng = make_engine(2)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          spec=DraftModelProposer(make_engine(2)), spec_k=4)
    base = obs_metrics.snapshot_json()
    try:
        t = sched.submit(P1, 16)
        assert list(t.tokens()) == solo_refs[tuple(P1)][:16]
        assert t.finish == "length"
    finally:
        sched.close()
    assert t.spec_proposed > 0
    assert t.spec_accepted / t.spec_proposed >= 0.9, \
        (t.spec_accepted, t.spec_proposed)
    snap = obs_metrics.snapshot_json()
    d_prop = snap["sched_spec_proposed"] - \
        (base.get("sched_spec_proposed") or 0)
    d_acc = (snap.get("sched_spec_accepted") or {}).get("draft", 0) - \
        ((base.get("sched_spec_accepted") or {}).get("draft", 0))
    assert d_prop >= t.spec_proposed and d_acc >= t.spec_accepted
    assert 0.0 < snap["sched_spec_accept_ratio"] <= 1.0
    prom = obs_metrics.render_prometheus()
    for name in ("dllama_sched_spec_proposed_total",
                 "dllama_sched_spec_accepted_total",
                 'proposer="draft"',
                 "dllama_sched_spec_accept_ratio"):
        assert name in prom, name
    rec = obs_flight.get(t.rid)
    assert rec["spec_proposed"] == t.spec_proposed
    assert rec["spec_accepted"] == t.spec_accepted
    assert any(p["kind"] == "verify_burst" for p in rec["phases"])


def test_pld_accept_ratio_on_repetitive_continuation():
    """Prompt lookup on a repetitive continuation (zero-weight model:
    constant argmax) must clear the accept-ratio floor — the n-gram
    index really does turn repetition into accepted drafts."""
    eng = make_engine(2, zero=True)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          spec=PromptLookupProposer(vocab=CFG.vocab_size),
                          spec_k=4)
    try:
        t = sched.submit([5, 0, 0], 24)
        got = list(t.tokens())
    finally:
        sched.close()
    assert got == [0] * 24  # zero weights: the solo run is constant too
    assert t.spec_proposed > 0
    assert t.spec_accepted / t.spec_proposed >= 0.9, \
        (t.spec_accepted, t.spec_proposed)


# -- flush points: preemption + hand-off ------------------------------------

def test_spec_preempt_park_resume_byte_parity(solo_refs):
    """Speculation coexists with QoS preemption: the victim's pending
    drafts die at park, the resumed request is byte-identical, and the
    page pool ends clean."""
    eng = make_paged_engine(batch=2)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          preempt=True, preempt_age_ms=0.0,
                          prefix_reuse=False,
                          spec=PromptLookupProposer(vocab=CFG.vocab_size),
                          spec_k=4)
    try:
        done: dict = {}

        def run(key, prompt, n, prio):
            t = sched.submit(prompt, n, priority=prio)
            done[key] = (list(t.tokens()), t.finish, t.preempt_count)

        FAULTS.install("engine.device_step=delay:0.05x1000")
        b1 = threading.Thread(target=run, args=(
            "b1", P1, 30, PRIORITY_LEVELS["batch"]))
        b2 = threading.Thread(target=run, args=(
            "b2", P2, 30, PRIORITY_LEVELS["batch"]))
        b1.start()
        b2.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.occupancy()["active"] == 2:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("batch never saturated the slots")
        time.sleep(0.3)
        it = threading.Thread(target=run, args=(
            "it", P3, 6, PRIORITY_LEVELS["interactive"]))
        it.start()
        it.join(120)
        FAULTS.clear()
        b1.join(240)
        b2.join(240)

        assert done["it"][0] == solo_refs[tuple(P3)][:6]
        assert any(done[k][2] >= 1 for k in ("b1", "b2")), \
            f"no ticket recorded a preemption: {done}"
        for k, p in (("b1", P1), ("b2", P2)):
            toks, finish, _ = done[k]
            assert finish == "length", (k, finish)
            assert toks == solo_refs[tuple(p)][:30], \
                f"{k} drifted after resume"
        occ = sched.occupancy()
        assert occ["active"] == 0 and occ["parked"] == 0, occ
        assert occ["kv_pages_free"] == occ["kv_pages_total"], \
            f"page leak: {occ}"
        sched.pool.check()
    finally:
        sched.close()


@pytest.fixture(scope="module")
def paged_solo_ref():
    eng = make_engine(1)
    toks = [t for t, _ in eng.generate_stream(
        P1, len(P1) + 30, temperature=0.0, chunk=5)]
    return toks[len(P1):]


def test_spec_handoff_export_flushes_drafts(paged_solo_ref):
    """A hand-off export fired mid-decode with speculation live: every
    DLREQ01 snapshot is taken with zero pending drafts (a record never
    carries speculative state), and the export resumes byte-identically
    on a peer that speculates too."""
    def spec():
        return PromptLookupProposer(vocab=CFG.vocab_size)

    sa = SlotScheduler(make_paged_engine(), prefill_chunk=4,
                       max_wait_ms=20.0, decode_burst=4,
                       spec=spec(), spec_k=4)
    sb = SlotScheduler(make_paged_engine(), prefill_chunk=4,
                       max_wait_ms=20.0, decode_burst=4,
                       spec=spec(), spec_k=4)
    drafts_seen = []
    real_export = sa._export_slot_locked

    def spying_export(slot_idx):
        drafts_seen.append(dict(sa._proposals))
        return real_export(slot_idx)

    sa._export_slot_locked = spying_export
    try:
        with injected("engine.device_step=delay:0.05x100000"):
            t = sa.submit(P1, 30, temperature=0.0)
            it = t.tokens()
            consumed = [next(it) for _ in range(6)]
            records = sa.handoff_export_all()
        list(it)
        assert t.finish == "handoff"
        assert t.rid in records
        assert drafts_seen and all(p == {} for p in drafts_seen), \
            "an export snapshot saw pending drafts"
        meta, _ = snapfmt.loads_request(records[t.rid])
        replayed = [int(x) for x in meta["extra"]["completion"]]
        assert replayed[:len(consumed)] == consumed
        t2, _ = sb.import_request(records[t.rid])
        resumed = list(t2.tokens())
        assert t2.finish == "length"
        assert replayed + resumed == paged_solo_ref
    finally:
        sa.close()
        sb.close()


# -- reject storm ------------------------------------------------------------

def test_reject_storm_parity_and_graceful_ratio(solo_refs, off_results):
    """The spec.propose=corrupt fault forces adversarial drafts for
    every slot: proposals happen, near-none verify, and the served
    bytes are still the model's own greedy output."""
    sched = _make_sched("pld")
    base = obs_metrics.snapshot_json().get("sched_spec_proposed") or 0
    try:
        with injected("spec.propose=corrupt"):
            outs = _run_traffic(sched, solo_refs, eos_prompt=P2)
    finally:
        sched.close()
    assert outs == off_results
    proposed = (obs_metrics.snapshot_json().get("sched_spec_proposed")
                or 0) - base
    assert proposed > 0, "the storm never forced a proposal"
