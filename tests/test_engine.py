"""Engine tests: generation loop semantics, prefill modes, stats, limits."""

import numpy as np
import pytest
import jax

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import Engine, _next_bucket
from dllama_tpu.sampling import Sampler


CFG = tiny_config(seq_len=32)


def make_engine(cfg=CFG, seed=4):
    return Engine(cfg, init_params(cfg, seed=seed),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]))


def test_timing_mode_attribution_source():
    """Pins the I/T attribution source (VERDICT r04 Weak #1): on a remote
    tunnel the device-ready marker fires at dispatch, so "host-fetch" mode
    must put the whole step in I with T=0 (the only trustworthy clock edge
    is the host fetch); the local default keeps the ready/fetch split."""
    eng = Engine(CFG, init_params(CFG, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                 timing_mode="host-fetch")
    assert eng.timing_mode == "host-fetch"
    _, st = eng.prefill([5, 9, 2])
    assert st.transfer_ms == 0.0
    assert st.inference_ms == st.generation_ms
    toks_stats = [s for _, s in eng.generate_stream([7], 10, chunk=4)]
    chunk_stats = [s for s in toks_stats if s.generation_ms > 0]
    assert chunk_stats and all(s.transfer_ms == 0.0 for s in chunk_stats)

    local = make_engine()
    assert local.timing_mode == "device-ready"  # CPU backend default
    _, st2 = local.prefill([5])
    assert abs(st2.inference_ms + st2.transfer_ms - st2.generation_ms) < 1e-6

    with pytest.raises(ValueError, match="timing_mode"):
        Engine(CFG, init_params(CFG, seed=4),
               mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
               timing_mode="bogus")


def test_next_bucket():
    assert _next_bucket(1) == 16
    assert _next_bucket(16) == 16
    assert _next_bucket(17) == 32
    assert _next_bucket(100) == 128


def test_generate_greedy_deterministic():
    prompt = [5, 9, 2]
    a = [t for t, _ in make_engine().generate(prompt, 12, Sampler(CFG.vocab_size, 0.0, 0.9, 7))]
    b = [t for t, _ in make_engine().generate(prompt, 12, Sampler(CFG.vocab_size, 0.0, 0.9, 99))]
    assert a == b  # greedy ignores seed
    assert a[:3] == prompt
    assert len(a) == 12


def test_batched_prefill_equals_single_token_prefill():
    """True prefill must produce the same continuation as the reference's
    token-at-a-time prompt feed (dllama.cpp:53-58)."""
    prompt = [5, 9, 2, 17, 30]
    s = lambda: Sampler(CFG.vocab_size, 0.0, 0.9, 1)
    fast = [t for t, _ in make_engine().generate(prompt, 15, s())]
    slow = [t for t, _ in make_engine().generate(prompt, 15, s(), prefill_single_token=True)]
    assert fast == slow


def test_eos_stops_generation():
    e = make_engine()
    toks = [t for t, _ in e.generate([5, 9], 30, Sampler(CFG.vocab_size, 0.0, 0.9, 1))]
    eos = toks[4]  # pretend the 5th token is EOS; regenerate with it as a stop
    e2 = make_engine()
    toks2 = [t for t, _ in e2.generate([5, 9], 30, Sampler(CFG.vocab_size, 0.0, 0.9, 1), eos_ids=(eos,))]
    assert toks2[-1] == eos
    assert len(toks2) <= len(toks)


def test_steps_clamped_to_seq_len():
    e = make_engine()
    toks = [t for t, _ in e.generate([1, 2], 10_000, Sampler(CFG.vocab_size, 0.0, 0.9, 1))]
    assert len(toks) == CFG.seq_len  # clamp (app.cpp:118-120 parity)
    assert e.pos <= CFG.seq_len


def test_decode_beyond_seq_len_raises():
    e = make_engine()
    e.pos = e.seq_len
    with pytest.raises(ValueError, match="seq_len"):
        e.decode_one(1)


def test_stats_populated():
    e = make_engine()
    logits, st = e.prefill([1, 2, 3])
    assert logits.shape == (1, CFG.vocab_size)
    assert st.generation_ms > 0
    assert st.inference_ms > 0
    assert st.generation_ms + 1e-6 >= st.inference_ms


def test_reset_restarts_sequence():
    e = make_engine()
    l1, _ = e.prefill([4, 7, 1])
    e.reset()
    assert e.pos == 0
    l2, _ = e.prefill([4, 7, 1])
    np.testing.assert_allclose(l1, l2, atol=1e-5)


def test_prefill_bucket_never_overflows_cache():
    """Regression: a padded prefill bucket near the end of context must not
    exceed the cache — dynamic_update_slice clamps out-of-range starts
    backwards, silently overwriting valid KV history."""
    e = make_engine()
    e.prefill(list(range(1, 21)))  # pos=20 of seq_len=32
    l_cont, _ = e.prefill([21, 22, 23, 24, 25])  # bucket must cap at 12, not 16
    e2 = make_engine()
    l_full, _ = e2.prefill(list(range(1, 26)))
    np.testing.assert_allclose(l_cont, l_full, atol=1e-4, rtol=1e-3)


def test_multi_turn_kv_continuity():
    """Chat-style incremental prefill: a second prefill continues the same
    KV sequence (dllama.cpp:111-203 chat mode keeps pos across turns)."""
    e = make_engine()
    e.prefill([4, 7, 1])
    l_cont, _ = e.prefill([9, 3])
    e2 = make_engine()
    l_full, _ = e2.prefill([4, 7, 1, 9, 3])
    np.testing.assert_allclose(l_cont, l_full, atol=1e-4, rtol=1e-3)
