"""Per-request KV hand-off tests (runtime/snapshot.py DLREQ01 records,
scheduler export/import — the substrate under the fleet router's
drain-aware rolling restart).

The tentpole contracts, each pinned here on CPU with a tiny model:

* **record integrity** — DLREQ01 dumps/loads round-trips meta + arrays
  exactly; any flipped byte or truncation is an :class:`ArtifactError`,
  never silent corruption; the request-record and snapshot-file magics
  refuse each other's payloads;
* **byte parity** — a greedy request exported mid-decode from one paged
  scheduler and imported into a second (same geometry, same weights)
  resumes decode byte-identically: replayed + resumed tokens equal the
  undisturbed solo run, with no re-prefill;
* **geometry gate** — a record from an incompatible replica (different
  fingerprint, or page payload inconsistent with the record position)
  is rejected with :class:`SnapshotMismatch` before any state is
  touched;
* **queued tickets** — a drain-time export retires never-admitted
  tickets with finish ``handoff`` and no record (the router re-submits
  those from scratch; nothing was streamed, so that is idempotent).
"""

import numpy as np
import pytest

import jax

from dllama_tpu.io.integrity import ArtifactError
from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime import snapshot as snapfmt
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.runtime.faults import injected
from dllama_tpu.runtime.scheduler import SlotScheduler
from dllama_tpu.runtime.snapshot import SnapshotMismatch

pytestmark = pytest.mark.router

CFG = tiny_config(seq_len=64)
PAGE = 4
P = [5, 9, 2]


def make_paged_engine(batch=2, page=PAGE):
    pages_per_slot = -(-CFG.seq_len // page)
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch,
                  kv_pages=batch * pages_per_slot + 1,
                  kv_page_size=page)


@pytest.fixture(scope="module")
def solo_ref():
    """Greedy solo completion on the contiguous engine — the hand-off
    parity oracle (pages and hand-off are addressing changes, never
    numerics changes)."""
    eng = Engine(CFG, init_params(CFG, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=1)
    toks = [t for t, _ in eng.generate_stream(
        P, len(P) + 30, temperature=0.0, chunk=5)]
    return toks[len(P):]


@pytest.fixture(scope="module")
def stack():
    """Two independent paged schedulers with identical geometry and
    weights — exporter and importer of a fleet hand-off."""
    scheds = []
    for _ in range(2):
        eng = make_paged_engine()
        scheds.append(SlotScheduler(eng, prefill_chunk=4,
                                    max_wait_ms=20.0, decode_burst=4))
    yield scheds[0], scheds[1]
    for s in scheds:
        s.close()


# -- DLREQ01 record format -------------------------------------------------

def _mk_record():
    arrays = {
        "pages.k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        "pages.v": np.arange(24, 48, dtype=np.float32).reshape(2, 3, 4),
        "rng_key": np.array([7, 11], dtype=np.uint32),
    }
    extra = {"rid": "req-abc", "prompt": [1, 2, 3], "completion": [9, 8],
             "max_new": 16, "temperature": 0.0, "stop": ["END"]}
    blob = snapfmt.dumps_request(fingerprint="fp-1", pos=7, chunk_counter=3,
                                 arrays=arrays, extra=extra)
    return blob, arrays, extra


def test_dlreq01_roundtrip():
    blob, arrays, extra = _mk_record()
    meta, got = snapfmt.loads_request(blob)
    assert meta["fingerprint"] == "fp-1"
    assert meta["pos"] == 7 and meta["chunk_counter"] == 3
    assert meta["extra"] == extra
    assert set(got) == set(arrays)
    for name, arr in arrays.items():
        assert got[name].dtype == arr.dtype
        np.testing.assert_array_equal(got[name], arr)


def test_dlreq01_detects_corruption():
    blob, _, _ = _mk_record()
    # a flipped byte anywhere past the header fails the crc — probe one
    # offset in the json meta and one in the array payload
    for off in (20, len(blob) - 5):
        bad = bytearray(blob)
        bad[off] ^= 0xFF
        with pytest.raises(ArtifactError):
            snapfmt.loads_request(bytes(bad))
    with pytest.raises(ArtifactError):
        snapfmt.loads_request(blob[:len(blob) // 2])  # truncated
    with pytest.raises(ArtifactError):
        snapfmt.loads_request(b"")


def test_magics_are_mutually_exclusive(tmp_path):
    blob, _, _ = _mk_record()
    # a DLSNAP02 snapshot header on a hand-off payload must be refused…
    with pytest.raises(ArtifactError, match="hand-off"):
        snapfmt.loads_request(snapfmt.MAGIC + blob[len(snapfmt.REQ_MAGIC):])
    # …and the snapshot-file loader must refuse a DLREQ01 record on disk
    p = tmp_path / "req.dlsnap"
    p.write_bytes(blob)
    with pytest.raises(ArtifactError):
        snapfmt.load(p)


# -- scheduler export/import ----------------------------------------------

def test_handoff_resume_byte_parity(stack, solo_ref):
    """Export a greedy request mid-decode from scheduler A, import into
    scheduler B, drain it there: replayed + resumed tokens must equal
    the undisturbed solo run — the fleet e2e invariant, in-process."""
    sa, sb = stack
    with injected("engine.device_step=delay:0.05"):
        t = sa.submit(P, 30, temperature=0.0)
        it = t.tokens()
        consumed = [next(it) for _ in range(6)]
        records = sa.handoff_export_all()
    list(it)  # drain the severed stream
    assert t.finish == "handoff"
    assert set(records) == {t.rid}

    meta, _ = snapfmt.loads_request(records[t.rid])
    replayed = [int(x) for x in meta["extra"]["completion"]]
    # the exporter ships everything produced, which is at least what the
    # consumer saw (the dispatch burst may have run ahead of the reader)
    assert replayed[:len(consumed)] == consumed

    t2, extra = sb.import_request(records[t.rid])
    assert t2.rid == t.rid
    assert extra["completion"] == replayed
    resumed = list(t2.tokens())
    assert t2.finish == "length"
    assert replayed + resumed == solo_ref
    # resumption decodes only the remaining budget — no silent re-prefill
    assert len(resumed) == 30 - len(replayed)


def test_import_rejects_incompatible_geometry(stack):
    sa, _ = stack
    blob = snapfmt.dumps_request(
        fingerprint="some-other-fleet", pos=4, chunk_counter=0,
        arrays={"pages.k": np.zeros((2, 1, 2, PAGE, 4), np.float32),
                "pages.v": np.zeros((2, 1, 2, PAGE, 4), np.float32)},
        extra={"rid": "alien", "prompt": [1, 2], "max_new": 4})
    with pytest.raises(SnapshotMismatch, match="geometry"):
        sa.import_request(blob)


def test_import_rejects_inconsistent_pages(stack):
    """Right fingerprint, but the page payload disagrees with the record
    position (a torn or doctored export) — refused before any state is
    written."""
    sa, _ = stack
    fp = sa.engine.handoff_fingerprint()
    kvshape = sa.engine.cache.k.shape
    wrong = (kvshape[0], 1) + tuple(kvshape[2:])  # pos=9 needs 3 pages
    blob = snapfmt.dumps_request(
        fingerprint=fp, pos=9, chunk_counter=0,
        arrays={"pages.k": np.zeros(wrong, np.float32),
                "pages.v": np.zeros(wrong, np.float32)},
        extra={"rid": "torn", "prompt": [1, 2], "max_new": 4,
               "fed": 2, "produced": 0})
    with pytest.raises(SnapshotMismatch, match="position"):
        sa.import_request(blob)


def test_export_fails_queued_tickets_without_records(stack):
    """batch=2 scheduler with 3 requests: the two admitted ones export
    records, the queued one retires ``handoff`` with no record."""
    sa, _ = stack
    with injected("engine.device_step=delay:0.05"):
        tickets = [sa.submit([3 + i, 4, 6], 30, temperature=0.0)
                   for i in range(3)]
        its = [t.tokens() for t in tickets]
        next(its[0])  # both slots admitted and decoding
        records = sa.handoff_export_all()
    for it in its:
        list(it)
    assert all(t.finish == "handoff" for t in tickets)
    admitted = {t.rid for t in tickets if t.slot is not None}
    queued = {t.rid for t in tickets} - admitted
    assert len(queued) == 1
    assert set(records) == admitted


def test_tp4_export_tp1_import_byte_parity():
    """Cross-geometry hand-off: a record exported from a tp=4 sharded
    paged scheduler imports into a tp=1 replica and resumes
    byte-identically.  The hand-off fingerprint digests *global* cache
    geometry (page size, heads, head dim), never the mesh shape — a
    pod-slice replica draining into a single-chip spare is exactly the
    rolling-restart path the fleet router exercises."""
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    cfg = tiny_config(hidden_dim=128, n_kv_heads=4, seq_len=64)

    def paged(tp):
        pages_per_slot = -(-cfg.seq_len // PAGE)
        return Engine(cfg, init_params(cfg, seed=4),
                      mesh=make_mesh(tp=tp, devices=jax.devices()[:tp]),
                      batch=2, kv_pages=2 * pages_per_slot + 1,
                      kv_page_size=PAGE)

    solo = Engine(cfg, init_params(cfg, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=1)
    toks = [t for t, _ in solo.generate_stream(
        P, len(P) + 30, temperature=0.0, chunk=5)]
    solo_ref = toks[len(P):]

    sa = SlotScheduler(paged(4), prefill_chunk=4, max_wait_ms=20.0,
                       decode_burst=4)
    sb = SlotScheduler(paged(1), prefill_chunk=4, max_wait_ms=20.0,
                       decode_burst=4)
    try:
        assert sa.engine.handoff_fingerprint() == \
            sb.engine.handoff_fingerprint(), \
            "mesh shape must not be part of replica identity"
        with injected("engine.device_step=delay:0.05"):
            t = sa.submit(P, 30, temperature=0.0)
            it = t.tokens()
            for _ in range(6):
                next(it)
            records = sa.handoff_export_all()
        list(it)
        assert t.finish == "handoff"
        meta, _ = snapfmt.loads_request(records[t.rid])
        replayed = [int(x) for x in meta["extra"]["completion"]]
        t2, _ = sb.import_request(records[t.rid])
        resumed = list(t2.tokens())
        assert t2.finish == "length"
        assert replayed + resumed == solo_ref, \
            "tp=4 export → tp=1 import drifted"
    finally:
        sa.close()
        sb.close()
