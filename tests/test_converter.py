"""Converter tests.

The strongest check in the suite: a real HuggingFace ``LlamaForCausalLM``
is saved to safetensors, converted to `.m` by converter/convert_hf.py, and
the resulting model's logits are compared against the torch forward pass —
cross-implementation parity covering the q/k RoPE permutation, tensor
order, and every transform in between."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "converter"))

from dllama_tpu import quants
from dllama_tpu.io import mfile, tfile
from dllama_tpu.models.params import load_params


@pytest.fixture(scope="module")
def hf_model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM
    torch.manual_seed(0)
    config = LlamaConfig(
        hidden_size=64, intermediate_size=96, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, vocab_size=128,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False,
        # the .m format carries no norm-eps field: the reference runtime
        # hardcodes 1e-5 (funcs.cpp:120), so converted HF models always run
        # with 1e-5 regardless of config.json — align the fixture
        rms_norm_eps=1e-5)
    model = LlamaForCausalLM(config).eval()
    d = tmp_path_factory.mktemp("hf_llama")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def test_convert_hf_logits_match_torch(hf_model_dir, tmp_path):
    import torch
    import jax.numpy as jnp
    folder, torch_model = hf_model_dir
    out = str(tmp_path / "conv.m")

    import convert_hf
    convert_hf.convert(folder, quants.F32, out)

    mf = mfile.MFile(out)
    assert mf.spec.arch == mfile.ARCH_LLAMA
    assert mf.spec.n_kv_heads == 2
    cfg, params = load_params(mf)
    cfg = cfg.with_(dtype=jnp.float32)

    tokens = [[3, 17, 42, 99, 7]]
    with torch.no_grad():
        want = torch_model(torch.tensor(tokens)).logits.numpy()[0]

    from dllama_tpu.models.transformer import forward, init_kv_cache
    logits, _ = forward(params, cfg, jnp.asarray(tokens),
                        init_kv_cache(cfg, 1), jnp.int32(0))
    got = np.asarray(logits)[0]
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-3)


def test_convert_hf_q40_close_to_f32(hf_model_dir, tmp_path):
    import convert_hf
    folder, _ = hf_model_dir
    f32_path = str(tmp_path / "f32.m")
    q40_path = str(tmp_path / "q40.m")
    convert_hf.convert(folder, quants.F32, f32_path)
    convert_hf.convert(folder, quants.Q40, q40_path)
    a = mfile.MFile(f32_path).tensor("layers.0.wq")
    b = mfile.MFile(q40_path).tensor("layers.0.wq")
    assert np.abs(a - b).max() < np.abs(a).max() / 7  # 4-bit step bound


def test_convert_hf_q80_loads_packed(hf_model_dir, tmp_path):
    """HF → Q80 `.m` → the packed Q8 loader path (reference ftype-dispatch
    parity end-to-end through the converter)."""
    import convert_hf
    import jax.numpy as jnp

    from dllama_tpu.ops import q8

    folder, _ = hf_model_dir
    q80_path = str(tmp_path / "q80.m")
    convert_hf.convert(folder, quants.Q80, q80_path)
    mf = mfile.MFile(q80_path)
    assert mf.spec.weights_ftype == quants.Q80
    # 8-bit codec: much tighter than the Q40 bound
    a = mf.tensor("layers.0.wq")
    cfg, params = load_params(mf, keep_quantized=True)
    assert isinstance(params["wqkv"], q8.Q8Tensor)
    # layer-stacked fused (L, n, q|k|v): layer 0's q slice must equal the
    # file tensor's dequant exactly (same codec, pure byte transpose)
    w = np.asarray(q8.dequantize(params["wqkv"], jnp.float32))[0]
    np.testing.assert_allclose(w[:, :cfg.dim], a.reshape(cfg.dim, cfg.dim).T,
                               rtol=0, atol=1e-6)


def test_convert_llama_meta_checkpoint(tmp_path):
    import torch
    import convert_llama
    dim, n_layers, n_heads, vocab = 64, 2, 4, 96
    # Meta sizing rule: hidden = multiple_of * ceil((2*4*dim/3)/multiple_of)
    folder = tmp_path / "meta"
    folder.mkdir()
    (folder / "params.json").write_text(json.dumps({
        "dim": dim, "n_layers": n_layers, "n_heads": n_heads,
        "multiple_of": 32, "norm_eps": 1e-5, "vocab_size": vocab}))
    rng = np.random.RandomState(0)
    hidden_dim = 32 * ((int(2 * 4 * dim / 3) + 31) // 32)

    def t(*shape):
        return torch.tensor(rng.randn(*shape).astype(np.float32) * 0.05)

    # two shards, split like Meta does (attention/ffn on axis 0/1)
    sd0, sd1 = {}, {}
    def split(key, full, axis):
        halves = np.split(full.numpy(), 2, axis=axis)
        sd0[key] = torch.tensor(halves[0])
        sd1[key] = torch.tensor(halves[1])

    emb = t(vocab, dim); split("tok_embeddings.weight", emb, 1)
    for l in range(n_layers):
        for k, ax in [("attention.wq.weight", 0), ("attention.wk.weight", 0),
                      ("attention.wv.weight", 0), ("attention.wo.weight", 1)]:
            split(f"layers.{l}.{k}", t(dim, dim), ax)
        split(f"layers.{l}.feed_forward.w1.weight", t(hidden_dim, dim), 0)
        split(f"layers.{l}.feed_forward.w2.weight", t(dim, hidden_dim), 1)
        split(f"layers.{l}.feed_forward.w3.weight", t(hidden_dim, dim), 0)
        sd0[f"layers.{l}.attention_norm.weight"] = torch.ones(dim)
        sd1[f"layers.{l}.attention_norm.weight"] = torch.ones(dim)
        sd0[f"layers.{l}.ffn_norm.weight"] = torch.ones(dim)
        sd1[f"layers.{l}.ffn_norm.weight"] = torch.ones(dim)
    sd0["norm.weight"] = torch.ones(dim); sd1["norm.weight"] = torch.ones(dim)
    split("output.weight", t(vocab, dim), 0)
    torch.save(sd0, folder / "consolidated.00.pth")
    torch.save(sd1, folder / "consolidated.01.pth")

    out = str(tmp_path / "meta.m")
    convert_llama.convert(str(folder), quants.F32, out)
    mf = mfile.MFile(out)
    assert mf.spec.hidden_dim == hidden_dim
    # wq reconstructed = concat of both shards on axis 0
    wq = mf.tensor("layers.0.wq")
    assert wq.shape == (dim, dim)
    np.testing.assert_allclose(wq[:dim // 2], sd0["layers.0.attention.wq.weight"].numpy())


def test_convert_tokenizer_hf_fast(tmp_path):
    import convert_tokenizer_hf
    d = tmp_path / "tok"
    d.mkdir()
    vocab = {"a": 0, "b": 1, "ab": 2}
    (d / "tokenizer.json").write_text(json.dumps({
        "model": {"type": "BPE", "vocab": vocab, "merges": ["a b"]},
        "added_tokens": [
            {"id": 3, "content": "<s>"}, {"id": 4, "content": "</s>"}],
    }))
    (d / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "bos_token": "<s>", "eos_token": "</s>",
        "chat_template": "{% for m in messages %}<|im_start|>...{% endfor %}"}))
    out = convert_tokenizer_hf.convert(str(d), "test", "<|stop|>",
                                       out_path=str(tmp_path / "t.t"))
    r = tfile.read_tfile(out)
    assert r.vocab == [b"a", b"b", b"ab", b"<s>", b"</s>"]
    assert r.bos_id == 3 and r.eos_id == 4 and r.chat_eos_id == 4
    assert "<|im_start|>" in r.chat_template
    assert r.chat_stop == "<|stop|>"


def test_convert_tokenizer_llama3(tmp_path):
    import base64
    import convert_tokenizer_llama3 as c3
    lines = [f"{base64.b64encode(bytes([65 + i])).decode()} {i}" for i in range(10)]
    src = tmp_path / "tokenizer.model"
    src.write_text("\n".join(lines) + "\n")
    out = c3.convert(str(src), out_path=str(tmp_path / "l3.t"))
    r = tfile.read_tfile(out)
    assert r.vocab[0] == b"A"
    assert len(r.vocab) == 10 + 256
    assert r.vocab[10 + 9] == b"<|eot_id|>"
    assert r.bos_id == 128000 and r.chat_eos_id == 128009
    assert "<|start_header_id|>" in r.chat_template


def test_launch_lists_reference_zoo():
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import launch
    assert set(launch.MODELS) == {"tinyllama_1_1b_3t_q40", "llama3_8b_q40",
                                  "llama3_8b_instruct_q40"}
