"""Tensor-parallel serving tests (the PR-12 tentpole, on 8 forced CPU
host devices).

The slot scheduler has always been proven on tp=1; these tests run the
same serving contracts over a tp=4 mesh and hold them to the house
invariant — greedy output byte-identical to the tp=1 solo run in every
mode:

* **staggered continuous batching** — four greedy requests joining a
  tp=4 scheduler at different times, each byte-identical to its tp=1
  solo decode (the overlapped dispatch pipeline is on by default, so
  device-resident feed rows ride the sharded mesh with no host
  round-trip);
* **radix prefix sharing on a sharded pool** — a repeated system prompt
  binds cached pages on the tp-sharded paged pool, bumps the prefix-hit
  counters, and decodes byte-identically;
* **overlap off** — the non-pipelined dispatch path holds the same
  parity on tp=4;
* **preemption** — an interactive burst preempts a decoding batch slot
  (DLREQ01 park, pages freed), the victim resumes byte-identically, no
  page leaks (``pool.check()``);
* **ledger hygiene** — building a tp>1 engine on a non-TPU backend
  records the ``tp_psum`` degrade (the fused collective-matmul ring is
  TPU-only), same treatment as ``blocked_ignored_mesh``;
* **collective probe** — ``Engine.probe_collective`` lands a sample in
  the ``engine_collective_ms`` histogram on tp>1 and stays silent on
  tp=1.

Config note: the suite's usual ``tiny_config`` only shards to tp=2
(n_kv_heads=2); this file widens it to n_kv_heads=4 / hidden_dim=128 so
tp=4 divides every sharded axis (see ``valid_tp_degrees``).
"""

import threading
import time

import jax
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.obs import dispatch as obs_dispatch, metrics as obs_metrics
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.parallel.sharding import valid_tp_degrees
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.runtime.faults import FAULTS
from dllama_tpu.runtime.scheduler import PRIORITY_LEVELS, SlotScheduler

pytestmark = pytest.mark.tp

CFG = tiny_config(hidden_dim=128, n_kv_heads=4, seq_len=64)
PAGE = 4
P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]
P3 = [2, 4, 6]
P4 = [9, 8, 7, 6]
PROMPTS = (P1, P2, P3, P4)
TP = 4


@pytest.fixture(autouse=True)
def _clean():
    FAULTS.clear()
    yield
    FAULTS.clear()


def make_engine(tp, batch=1, **kw):
    if tp > len(jax.devices()):
        pytest.skip(f"needs {tp} devices")
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=tp, devices=jax.devices()[:tp]),
                  batch=batch, **kw)


def make_paged_engine(tp, batch=2, page=PAGE):
    pages_per_slot = -(-CFG.seq_len // page)
    return make_engine(tp, batch=batch,
                       kv_pages=batch * pages_per_slot + 2,
                       kv_page_size=page)


@pytest.fixture(scope="module")
def solo_refs():
    """Greedy tp=1 solo completions — the parity oracle every tp=4 mode
    must reproduce byte-for-byte."""
    eng = Engine(CFG, init_params(CFG, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=1)
    refs = {}
    for p in PROMPTS:
        eng.reset()
        toks = [t for t, _ in eng.generate_stream(
            p, len(p) + 30, temperature=0.0, chunk=5)]
        refs[tuple(p)] = toks[len(p):]
    return refs


def test_config_actually_allows_tp4():
    assert TP in valid_tp_degrees(CFG)


def _staggered(sched, n=10, delays=(0.0, 0.05, 0.2, 0.35)):
    results = {}

    def run(p, delay):
        time.sleep(delay)
        t = sched.submit(p, n)
        results[tuple(p)] = (list(t.tokens()), t.finish)

    threads = [threading.Thread(target=run, args=(p, d))
               for p, d in zip(PROMPTS, delays)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(180)
    return results


def test_tp4_staggered_sched_parity(solo_refs):
    """Continuous batching on a tp=4 mesh, overlap pipeline on (the
    default): staggered greedy joins match tp=1 solo byte-for-byte."""
    eng = make_engine(TP, batch=4)
    sched = SlotScheduler(eng, prefill_chunk=4, max_wait_ms=30.0)
    try:
        results = _staggered(sched)
        for p in PROMPTS:
            got, finish = results[tuple(p)]
            assert got == solo_refs[tuple(p)][:10], p
            assert finish == "length"
    finally:
        sched.close()


def test_tp4_no_overlap_parity(solo_refs):
    eng = make_engine(TP, batch=4)
    sched = SlotScheduler(eng, prefill_chunk=4, max_wait_ms=30.0,
                          overlap=False)
    try:
        results = _staggered(sched)
        for p in PROMPTS:
            assert results[tuple(p)][0] == solo_refs[tuple(p)][:10], p
    finally:
        sched.close()


def test_tp4_prefix_radix_reuse_on_sharded_pool(solo_refs):
    """A repeated system prompt on the tp=4 paged pool must take the
    radix fast path (prefix counters bump) and stay byte-identical —
    page gather/scatter on a sharded cache is an addressing change,
    never a numerics change."""
    import numpy as np
    rng = np.random.RandomState(11)
    system = [int(x) for x in rng.randint(1, CFG.vocab_size, 4 * PAGE)]
    prompt = system + [3, 1]

    eng = make_paged_engine(TP, batch=2)
    sched = SlotScheduler(eng, prefill_chunk=4, prefix_reuse=True)
    hits0 = obs_metrics.PREFIX_HITS.value
    reused0 = obs_metrics.PREFIX_TOKENS_REUSED.value
    try:
        t1 = sched.submit(prompt, 8)
        o1 = list(t1.tokens())
        t2 = sched.submit(prompt, 8)
        o2 = list(t2.tokens())
    finally:
        sched.close()
    assert o1 == o2, "prefix-reused decode diverged from the cold run"
    assert obs_metrics.PREFIX_HITS.value > hits0
    assert obs_metrics.PREFIX_TOKENS_REUSED.value - reused0 == 4 * PAGE


def test_tp4_preempt_park_resume_parity(solo_refs):
    """Interactive burst preempts a tp=4 batch slot mid-decode; the
    victim parks (pages freed to the sharded pool), resumes, and
    finishes byte-identical to tp=1 solo; no pages leak."""
    eng = make_paged_engine(TP, batch=2)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          preempt=True, preempt_age_ms=0.0,
                          prefix_reuse=False)
    try:
        done: dict = {}

        def run(key, prompt, n, prio):
            t = sched.submit(prompt, n, priority=prio)
            done[key] = (list(t.tokens()), t.finish, t.preempt_count)

        FAULTS.install("engine.device_step=delay:0.05x1000")
        b1 = threading.Thread(target=run, args=(
            "b1", P1, 30, PRIORITY_LEVELS["batch"]))
        b2 = threading.Thread(target=run, args=(
            "b2", P2, 30, PRIORITY_LEVELS["batch"]))
        b1.start()
        b2.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.occupancy()["active"] == 2:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("batch never saturated the slots")
        time.sleep(0.3)
        it = threading.Thread(target=run, args=(
            "it", P3, 6, PRIORITY_LEVELS["interactive"]))
        it.start()
        it.join(120)
        FAULTS.clear()
        b1.join(240)
        b2.join(240)

        assert done["it"][0] == solo_refs[tuple(P3)][:6]
        assert [k for k in ("b1", "b2") if done[k][2] >= 1], \
            f"no ticket recorded a preemption: {done}"
        for k, p in (("b1", P1), ("b2", P2)):
            toks, finish, _ = done[k]
            assert finish == "length", (k, finish)
            assert toks == solo_refs[tuple(p)][:30], \
                f"{k} drifted after park/resume on tp={TP}"
        occ = sched.occupancy()
        assert occ["kv_pages_free"] == occ["kv_pages_total"], occ
        sched.pool.check()
    finally:
        FAULTS.clear()
        sched.close()


def test_tp_engine_on_cpu_records_psum_degrade():
    """Satellite contract: a tp>1 engine off TPU records the
    ``tp_psum`` degrade exactly like ``blocked_ignored_mesh`` — counter
    + degraded flag + warn-once — so a CPU/GPU run can never pass off a
    plain-psum decode as the fused collective number."""
    obs_dispatch.reset()
    try:
        make_engine(2)
        assert obs_dispatch.degraded() is True
        assert obs_dispatch.reasons().get("q40:tp_psum", 0) >= 1
        # tp=1 engines stay clean — no collective, no degrade
        obs_dispatch.reset()
        make_engine(1)
        assert obs_dispatch.reasons().get("q40:tp_psum", 0) == 0
    finally:
        obs_dispatch.reset()


def test_probe_collective_feeds_histogram():
    eng = make_engine(2)
    before = obs_metrics.ENGINE_COLLECTIVE_MS.count
    ms = eng.probe_collective()
    assert ms is not None and ms >= 0.0
    assert obs_metrics.ENGINE_COLLECTIVE_MS.count == before + 1
    # rate limit: an immediate second probe declines
    assert eng.probe_collective() is None
    # tp=1: nothing to measure
    e1 = make_engine(1)
    assert e1.probe_collective() is None
    assert obs_metrics.ENGINE_COLLECTIVE_MS.count == before + 1


def test_constraint_error_names_valid_degrees():
    """Satellite: every tp rejection tells the operator which degrees
    WOULD work for this model, instead of a bare modulus complaint."""
    from dllama_tpu.parallel.sharding import check_tp_constraint
    bad = 3  # heads 4, kv 4, hidden 128 — 3 divides none of them
    with pytest.raises(ValueError, match=r"valid tp degrees.*\[1, 2, 4\]"):
        check_tp_constraint(CFG, bad)
